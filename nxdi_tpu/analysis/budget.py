"""Expected collective budget per compiled submodel program.

The budget is derived from what ``parallel/policy.py`` SHOULD produce for the
config — deliberately NOT from the ``ShardingPolicy`` object the wrapper
actually compiled with. If a policy regression sneaks sharding into a program
(the decode stream suddenly S-sharded, an extra replicated axis forcing
all-gathers), the budget stays put and the observed counts blow past it;
deriving the budget from the buggy policy itself would silently raise the
ceiling along with the bug.

Counts are *textual* upper bounds over the optimized HLO. The decoder layer
stack runs under ``lax.scan`` (one ``while`` body in HLO), so the per-layer
collectives appear once in text — budgets are therefore small constants per
feature, not multiples of ``num_layers``. Unscanned (unrolled) model families
can scale the body terms via ``layers_unrolled``.

Every contribution is recorded as an ``explain`` string so a budget failure
tells the reader what WAS allowed, not just that a number was exceeded.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

from nxdi_tpu.analysis.hlo import COLLECTIVE_OPS


def _add(budget: Dict[str, int], explain: List[str], op: str, n: int, why: str) -> None:
    if n <= 0:
        return
    budget[op] += n
    explain.append(f"+{n} {op}: {why}")


def expected_collective_budget(
    tc, arch, wrapper
) -> Tuple[Dict[str, int], List[str]]:
    """Upper-bound collective counts for one submodel program.

    ``tc``: TpuConfig — the source of truth for which policy the submodel is
    *supposed* to run. ``arch``: the wrapper's DecoderArch (layer count, MoE).
    ``wrapper``: the ModelWrapper (decode-vs-prefill kind, speculation).
    """
    budget = {op: 0 for op in COLLECTIVE_OPS}
    explain: List[str] = []

    world = tc.tp_degree * getattr(tc, "pp_degree", 1)
    if world <= 1:
        explain.append("single-device mesh: every collective is unexplained")
        return budget, explain

    decode_like = wrapper.attend_to_cache and not wrapper.prefill_to_cache
    # which collective-inducing features the EXPECTED policy engages — owned
    # by parallel/policy.py so policy changes and budgets evolve together
    from nxdi_tpu.parallel.policy import expected_policy_features

    feats = expected_policy_features(tc, decode_like)
    # fused speculation runs TWO decoder stacks (draft + target) per program
    stacks = 2 if getattr(wrapper, "draft_arch", None) is not None else 1
    # unrolled families pay the body terms per layer; scanned (default) once
    body_scale = stacks * (
        arch.num_layers if getattr(wrapper, "layers_unrolled", False) else 1
    )

    if tc.tp_degree > 1:
        _add(budget, explain, "all-reduce", 2 * body_scale,
             "row-parallel attn-out + mlp-down psum (scanned layer body)")
        _add(budget, explain, "all-reduce", 2 * stacks,
             "final-norm / lm_head epilogue reduction")
        if tc.on_device_sampling_config is not None:
            _add(budget, explain, "all-gather", 3 * stacks,
                 "on-device sampling cross-shard top-k gather (values+indices)")
        if tc.output_logits:
            _add(budget, explain, "all-gather", 1,
                 "full-logits output gather (vocab-parallel lm_head)")

    if feats["sp"]:
        _add(budget, explain, "all-gather", 5 * body_scale,
             "SP: S-sharded stream gathered at QKV/MLP boundaries")
        _add(budget, explain, "reduce-scatter", 3 * body_scale,
             "SP: row-parallel psums become reduce-scatters")
        _add(budget, explain, "all-to-all", 2 * body_scale,
             "SP: partitioner resharding between S- and H-sharded views")
        _add(budget, explain, "all-reduce", 2 * body_scale,
             "SP: residual-stream reductions the partitioner keeps as psum")
    if feats["cp"]:
        _add(budget, explain, "all-gather", 4 * body_scale,
             "CP: KV all-gathered within the cp group per attention")
        _add(budget, explain, "reduce-scatter", 2 * body_scale,
             "CP: S-sharded stream scatter at block exits")
        _add(budget, explain, "all-to-all", 2 * body_scale,
             "CP: head<->sequence resharding around attention")
    if feats["mlp_cp"]:
        _add(budget, explain, "all-gather", 3 * body_scale,
             "MLP-CP: MLP stream gathered back to the replicated residual")
        _add(budget, explain, "reduce-scatter", 1 * body_scale,
             "MLP-CP: scatter into the S-sharded MLP stream")
        _add(budget, explain, "all-to-all", 1 * body_scale,
             "MLP-CP: partitioner resharding at the MLP boundary")

    if feats["flash_decoding"]:
        _add(budget, explain, "all-reduce", 2 * body_scale,
             "flash decoding: distributed softmax over KV-S shards")
        _add(budget, explain, "all-gather", 2 * body_scale,
             "flash decoding: per-shard partial attention assembly")
    if feats["attention_dp"]:
        _add(budget, explain, "all-gather", 3 * body_scale,
             "attention-DP: batch-sharded decode regrouped at block exits")
        _add(budget, explain, "all-to-all", 2 * body_scale,
             "attention-DP: batch<->head resharding around attention")
        _add(budget, explain, "collective-permute", 2 * body_scale,
             "attention-DP: dp-group rotation")
        _add(budget, explain, "all-reduce", 1 * body_scale,
             "attention-DP: cross-group reduction")

    if getattr(arch, "moe", None) is not None:
        _moe_budget(budget, explain, tc, arch.moe, decode_like, body_scale,
                    world)

    if tc.quantized:
        _add(budget, explain, "all-reduce", 1 * body_scale,
             "quantized matmul: scale/accumulator reduction")

    if getattr(tc, "pp_degree", 1) > 1:
        _add(budget, explain, "collective-permute", 4,
             "pipeline parallel: stage-boundary activation shifts")
        _add(budget, explain, "all-gather", 2,
             "pipeline parallel: final-stage output broadcast")

    return budget, explain


def _moe_budget(
    budget: Dict[str, int],
    explain: List[str],
    tc,
    moe,
    decode_like: bool,
    body_scale: int,
    world: int,
) -> None:
    """MoE dispatch/combine collective budget.

    **TPxEP meshes** (an explicit ``moe_ep_degree`` or a
    ``hybrid_sharding_config``) get EXACT derived counts instead of the old
    generous flat budget: the sparse MoE path (ops/moe.py ``_sparse_moe``)
    dispatches tokens by a LOCAL gather inside ``shard_map`` (every shard
    holds the replicated token stream) and combines with **one psum over
    the (ep[, epx], tp) world** per layer body — so the budget is one
    all-reduce per body (plus one for the always-on shared expert), and
    **zero** all-to-all / all-gather. The degrees come from the CONFIG
    (``moe_ep_degree`` / ``hybrid_sharding_config.moe_{cte,tkg}_ep_degree``
    with the per-phase regime picked by the submodel kind), never from the
    compiled arch — a regime typo must blow past the budget, not raise it.

    Regimes WITHOUT declared degrees (full-world EP from the family
    builder's ``ep_policy``, expert-internal TP, dense dispatch) keep the
    flat allowance: GSPMD owns their lowering and its collective pattern is
    not pinned by this repo's code.
    """
    hsc = getattr(tc, "hybrid_sharding_config", None)
    ep_degree = None
    if hsc is not None:
        ep_degree = (
            hsc.moe_tkg_ep_degree if decode_like else hsc.moe_cte_ep_degree
        )
        regime = (
            f"per-phase hybrid TPxEP ({'tkg' if decode_like else 'cte'} "
            f"regime: moe_{'tkg' if decode_like else 'cte'}_ep_degree="
            f"{ep_degree})"
        )
    elif getattr(tc, "moe_ep_degree", None) and tc.moe_ep_degree > 1:
        ep_degree = tc.moe_ep_degree
        regime = f"hybrid TPxEP (moe_ep_degree={ep_degree})"

    sparse = getattr(tc, "moe_dispatch", "sparse") == "sparse"
    if ep_degree is not None and sparse:
        tp_inner = max(world // ep_degree, 1)
        n_ar = 1
        why = (
            f"MoE {regime} x tp={tp_inner}: sparse dispatch is a local "
            "gather; combine is ONE psum over the (ep, tp) world"
        )
        if getattr(moe, "shared_expert_intermediate_size", None):
            n_ar += 1
            why += "; +1 shared-expert row-parallel psum"
        _add(budget, explain, "all-reduce", n_ar * body_scale, why)
        explain.append(
            "+0 all-to-all, +0 all-gather: TPxEP dispatch/combine counts "
            "derived from moe_*_degree (no flat allowance)"
        )
        return

    _add(budget, explain, "all-to-all", 4 * body_scale,
         "MoE: token dispatch/combine over the expert axis")
    _add(budget, explain, "all-gather", 4 * body_scale,
         "MoE: router logits / expert outputs regrouped")
    _add(budget, explain, "all-reduce", 2 * body_scale,
         "MoE: expert-parallel partial-sum reduction")


def over_budget(
    observed: Dict[str, int], budget: Dict[str, int]
) -> Dict[str, Tuple[int, int]]:
    """``{op: (observed, budget)}`` for every op type exceeding its budget."""
    return {
        op: (observed.get(op, 0), budget.get(op, 0))
        for op in COLLECTIVE_OPS
        if observed.get(op, 0) > budget.get(op, 0)
    }
