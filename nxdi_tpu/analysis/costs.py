"""Per-program cost observatory: FLOP/HBM model + roofline for every program.

NxDI serves from a small fixed set of AOT-compiled ``(submodel, bucket[,
steps])`` programs, so each one's cost is a *static, per-program* quantity —
computable before a single request is served and joinable against the
measured dispatch latencies the telemetry registry already records. This
module is that account:

- :func:`cost_sheets` — one :class:`CostSheet` per compiled program:
  XLA's own counters (``compiled.cost_analysis()`` FLOPs / bytes accessed,
  ``compiled.memory_analysis()`` argument/output/temp HBM) cross-checked
  against an **analytic model** derived from the config/arch (weight bytes
  by dtype, KV bytes per bucket window, matmul + attention FLOPs —
  scan-aware like the collective-budget checker: counts follow the math,
  not the HLO text). When a backend returns ``None``/partial analyses
  (CPU, older jaxlib, pallas custom calls) the sheet degrades to the
  analytic numbers and is tagged ``source="analytic"`` — never an error.
- Roofline classification per declared :class:`ChipSpec` (default v5e):
  ``t_compute = flops/peak_flops``, ``t_hbm = bytes/peak_bw``; the floor is
  their max and ``bound`` says which ceiling the program sits under.
- An HBM-fit account (weights + max-live KV + XLA temp vs per-chip HBM)
  shared with the auditor's ``hbm_fit`` checker (analysis/checkers.py).
- :func:`attach_cost_gauges` — the runtime join: at every telemetry export
  the measured mean dispatch latency per (submodel, bucket, steps) is
  divided by the program's CostSheet to publish
  ``nxdi_program_mfu_pct`` / ``nxdi_program_hbm_bw_pct`` /
  ``nxdi_roofline_gap_ratio`` gauges, and the whole sheet table rides the
  JSON snapshot as ``_cost_sheets``.

Canonical-number policy: the roofline/MFU math reads the ANALYTIC flops and
bytes. XLA's counters are recorded alongside (``xla_flops``/``xla_bytes``)
and cross-checked (>2x divergence sets ``mismatch`` and logs a warning),
but they are not the trajectory quantity: XLA reports the partitioned
module's textual totals, which miss pallas custom-call FLOPs entirely and
count causally-masked attention at full density — so they move when the
lowering strategy moves. The analytic model is what ``bench.py``'s
``cte_mfu_pct``/``mfu_pct``/``hbm_roofline_pct`` trajectory has always
meant, and using it for the serving gauges too means BENCH_*.json and the
Prometheus export can never disagree.

Analytic numbers are GLOBAL then divided by the mesh world (tp*pp) for the
per-chip roofline; XLA numbers come from the partitioned per-device module
and are per-chip already. CLI: ``python -m nxdi_tpu.cli.costs``.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

logger = logging.getLogger("nxdi_tpu")


# ---------------------------------------------------------------------------
# chip specs
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class ChipSpec:
    """Declared per-chip peaks the roofline is computed against (datasheet
    numbers; the bf16 peak — the serving dtype — not the int8 TOPS line)."""

    name: str
    bf16_tflops: float  # peak dense bf16 TFLOP/s
    hbm_gbs: float      # peak HBM bandwidth, GB/s (1e9)
    hbm_gib: float      # HBM capacity per chip, GiB (2**30)

    @property
    def flops_per_s(self) -> float:
        return self.bf16_tflops * 1e12

    @property
    def bytes_per_s(self) -> float:
        return self.hbm_gbs * 1e9

    @property
    def hbm_bytes(self) -> float:
        return self.hbm_gib * 2.0 ** 30

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "bf16_tflops": self.bf16_tflops,
            "hbm_gbs": self.hbm_gbs,
            "hbm_gib": self.hbm_gib,
        }


#: datasheet peaks per supported chip generation
CHIP_SPECS: Dict[str, ChipSpec] = {
    "v4": ChipSpec("v4", bf16_tflops=275.0, hbm_gbs=1228.0, hbm_gib=32.0),
    "v5e": ChipSpec("v5e", bf16_tflops=197.0, hbm_gbs=819.0, hbm_gib=16.0),
    "v5p": ChipSpec("v5p", bf16_tflops=459.0, hbm_gbs=2765.0, hbm_gib=95.0),
    "v6e": ChipSpec("v6e", bf16_tflops=918.0, hbm_gbs=1640.0, hbm_gib=32.0),
}

DEFAULT_CHIP = "v5e"


def resolve_chip(tpu_config=None, override=None) -> ChipSpec:
    """ChipSpec from ``TpuConfig(chip=...)`` (a name or a dict of overrides
    on top of v5e) or an explicit ``override`` of the same forms."""
    spec = override if override is not None else getattr(tpu_config, "chip", None)
    if spec is None:
        return CHIP_SPECS[DEFAULT_CHIP]
    if isinstance(spec, ChipSpec):
        return spec
    if isinstance(spec, str):
        if spec not in CHIP_SPECS:
            raise ValueError(
                f"unknown chip {spec!r}; known: {sorted(CHIP_SPECS)} "
                "(or pass a dict of ChipSpec fields)"
            )
        return CHIP_SPECS[spec]
    if isinstance(spec, dict):
        base_name = spec.get("base", DEFAULT_CHIP)
        if base_name not in CHIP_SPECS:
            raise ValueError(
                f"unknown chip base {base_name!r}; known: {sorted(CHIP_SPECS)}"
            )
        base = CHIP_SPECS[base_name].to_dict()
        base["name"] = "custom"
        base.update({k: v for k, v in spec.items() if k != "base"})
        try:
            return ChipSpec(**base)
        except TypeError as e:
            raise ValueError(f"bad chip spec fields {sorted(spec)}: {e}")
    raise TypeError(f"chip must be a name, dict, or ChipSpec; got {type(spec)}")


# ---------------------------------------------------------------------------
# pytree byte accounting (works on ShapeDtypeStructs and concrete arrays)
# ---------------------------------------------------------------------------

def tree_bytes(tree) -> int:
    """Total bytes of every leaf (shape x dtype — exact for quantized
    pytrees too, since int8 leaves carry their own dtype)."""
    import jax.tree_util as jtu

    total = 0
    for leaf in jtu.tree_leaves(tree):
        total += int(np.prod(leaf.shape)) * int(np.dtype(leaf.dtype).itemsize)
    return total


def tree_param_count(tree) -> int:
    import jax.tree_util as jtu

    return sum(int(np.prod(leaf.shape)) for leaf in jtu.tree_leaves(tree))


def _cache_itemsize(cache_struct) -> int:
    import jax.tree_util as jtu

    leaves = jtu.tree_leaves(cache_struct)
    if not leaves:
        return 2
    return int(np.dtype(leaves[0].dtype).itemsize)


# ---------------------------------------------------------------------------
# the analytic model (scan-aware: derived from arch/config, not HLO text)
# ---------------------------------------------------------------------------

def analytic_program_costs(
    wrapper, bucket: int, steps: int, param_count: int, param_bytes: int,
    kv_itemsize: int = 2,
) -> Dict[str, float]:
    """GLOBAL per-dispatch FLOPs and HBM bytes for one compiled program.

    The model mirrors what ``bench.py`` has always reported so the
    BENCH_*.json trajectory stays comparable:

    - matmul FLOPs: ``2 * param_count`` per token (the weight-streaming
      account; the embedding gather is counted like the reference did), with
      the lm_head paid once per *sampled* row in gather-last prefill;
    - attention FLOPs: ``QK^T + A.V`` over the attended window, halved for
      the causal prefill triangle;
    - HBM bytes: one full weight read per step plus the KV window
      read (decode) or KV write (prefill) at the cache store dtype.

    Multi-step programs (``steps`` > 1) pay everything per retired step —
    the lax.scan body re-streams weights and re-reads the window each
    iteration. Fused-speculation wrappers run a second (draft) stack; its
    weights already live in ``param_count``/``param_bytes`` (the app's
    struct covers both), so the weight-streaming terms are correct and only
    the attention/window terms are approximate for that program.
    """
    arch = wrapper.arch
    B = wrapper.batch_size
    decode_like = wrapper.attend_to_cache and not wrapper.prefill_to_cache
    L = arch.num_layers
    H = arch.num_attention_heads
    KV = arch.num_kv_heads
    D = arch.head_dim
    Dv = getattr(arch, "v_head_dim", None) or D
    lm_head = arch.vocab_size * arch.hidden_size

    if decode_like:
        active = max(1, wrapper.n_active_tokens)  # speculation windows: >1
        per_step_flops = (
            2.0 * param_count * B * active
            + 2.0 * L * H * (D + Dv) * bucket * B * active
        )
        per_step_kv_read = float(L * KV * (D + Dv) * bucket * B * kv_itemsize)
        flops = steps * per_step_flops
        hbm = steps * (float(param_bytes) + per_step_kv_read)
        kv_bytes = steps * per_step_kv_read
    else:
        tokens = B * bucket
        flops = (
            2.0 * (param_count - lm_head) * tokens
            + 2.0 * lm_head * B  # gather-last: lm_head on one row per batch
            + 1.0 * L * H * (D + Dv) * bucket * bucket * B  # causal triangle
        )
        kv_bytes = float(L * KV * (D + Dv) * bucket * B * kv_itemsize)
        hbm = float(param_bytes) + kv_bytes  # one weight read + the KV fill
    return {
        "flops": float(flops),
        "hbm_bytes": float(hbm),
        "weight_bytes": float(param_bytes),
        "kv_bytes": float(kv_bytes),
    }


# ---------------------------------------------------------------------------
# XLA's own counters (per-device module; None-tolerant on every backend)
# ---------------------------------------------------------------------------

def xla_cost_analysis(compiled) -> Optional[Dict[str, float]]:
    """``{"flops": ..., "bytes_accessed": ...}`` from
    ``compiled.cost_analysis()`` across its jax-version shapes (dict,
    list-of-dict, None), or None when unavailable/partial."""
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict) or "flops" not in ca:
        return None
    out = {"flops": float(ca["flops"])}
    if "bytes accessed" in ca:
        out["bytes_accessed"] = float(ca["bytes accessed"])
    return out


def xla_memory_analysis(compiled) -> Optional[Dict[str, int]]:
    """argument/output/alias/temp byte sizes from
    ``compiled.memory_analysis()``, or None when the backend has none."""
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return None
    if ma is None:
        return None
    out = {}
    for key, attr in (
        ("argument_bytes", "argument_size_in_bytes"),
        ("output_bytes", "output_size_in_bytes"),
        ("alias_bytes", "alias_size_in_bytes"),
        ("temp_bytes", "temp_size_in_bytes"),
        ("generated_code_bytes", "generated_code_size_in_bytes"),
    ):
        v = getattr(ma, attr, None)
        if v is None:
            return None
        out[key] = int(v)
    return out


# ---------------------------------------------------------------------------
# HBM-fit account (shared with the auditor's hbm_fit checker)
# ---------------------------------------------------------------------------

def hbm_residency(
    param_bytes: int, cache_bytes: int, world: int, chip: ChipSpec,
    memory: Optional[Dict[str, int]] = None,
) -> Dict[str, float]:
    """Per-chip HBM residency of one program while serving: sharded weights
    + the full allocated KV cache (= max-live KV across every bucket) +
    XLA's temp/scratch and non-aliased outputs when the backend reports
    them. Returns the breakdown plus ``fits``."""
    world = max(1, int(world))
    weights = param_bytes / world
    kv = cache_bytes / world
    temp = out_extra = 0.0
    if memory is not None:
        temp = float(memory.get("temp_bytes", 0))
        # donated caches alias outputs; only the non-aliased remainder is new
        out_extra = max(
            0.0, float(memory.get("output_bytes", 0)) - float(memory.get("alias_bytes", 0))
        )
    resident = weights + kv + temp + out_extra
    return {
        "weight_bytes_per_chip": weights,
        "kv_bytes_per_chip": kv,
        "temp_bytes": temp,
        "output_extra_bytes": out_extra,
        "resident_bytes": resident,
        "hbm_capacity_bytes": chip.hbm_bytes,
        "fits": resident <= chip.hbm_bytes,
    }


# ---------------------------------------------------------------------------
# CostSheet
# ---------------------------------------------------------------------------

#: XLA-vs-analytic FLOPs divergence beyond this ratio flags a mismatch
MISMATCH_RATIO = 2.0


@dataclass
class CostSheet:
    """The per-program cost account: canonical (analytic) FLOPs/bytes, the
    XLA cross-check, roofline classification, and the HBM-fit breakdown."""

    tag: str
    key: Any
    label: str
    bucket: int
    steps: int
    batch: int
    chip: ChipSpec
    world: int
    source: str  # "xla" (XLA analyses available) | "analytic" (fallback)
    flops: float  # canonical, PER CHIP per dispatch
    hbm_bytes: float  # canonical, PER CHIP per dispatch
    weight_bytes: float  # per chip
    kv_bytes: float  # per chip
    xla_flops: Optional[float] = None
    xla_bytes: Optional[float] = None
    memory: Optional[Dict[str, int]] = None
    fit: Dict[str, float] = field(default_factory=dict)
    mismatch: Optional[str] = None

    # -- roofline ----------------------------------------------------------
    @property
    def t_compute_s(self) -> float:
        return self.flops / self.chip.flops_per_s

    @property
    def t_hbm_s(self) -> float:
        return self.hbm_bytes / self.chip.bytes_per_s

    @property
    def floor_s(self) -> float:
        """Theoretical minimum dispatch latency on the declared chip."""
        return max(self.t_compute_s, self.t_hbm_s)

    @property
    def bound(self) -> str:
        return "compute" if self.t_compute_s >= self.t_hbm_s else "hbm"

    # -- the measured joins (bench.py AND the serving gauges use these, so
    # the BENCH trajectory and the Prometheus export share one formula) ----
    def mfu_pct(self, measured_s: float) -> float:
        if measured_s <= 0:
            return 0.0
        return 100.0 * self.flops / (measured_s * self.chip.flops_per_s)

    def hbm_bw_pct(self, measured_s: float) -> float:
        if measured_s <= 0:
            return 0.0
        return 100.0 * self.hbm_bytes / (measured_s * self.chip.bytes_per_s)

    def gap_ratio(self, measured_s: float) -> float:
        floor = self.floor_s
        return measured_s / floor if floor > 0 else 0.0

    def to_dict(self) -> dict:
        d = {
            "submodel": self.tag,
            "program": self.label,
            "bucket": self.bucket,
            "steps": self.steps,
            "batch": self.batch,
            "chip": self.chip.to_dict(),
            "world": self.world,
            "source": self.source,
            "flops": self.flops,
            "hbm_bytes": self.hbm_bytes,
            "weight_bytes": self.weight_bytes,
            "kv_bytes": self.kv_bytes,
            "t_compute_s": self.t_compute_s,
            "t_hbm_s": self.t_hbm_s,
            "floor_s": self.floor_s,
            "bound": self.bound,
            "fit": self.fit,
        }
        if self.xla_flops is not None:
            d["xla_flops"] = self.xla_flops
        if self.xla_bytes is not None:
            d["xla_bytes"] = self.xla_bytes
        if self.memory is not None:
            d["memory"] = self.memory
        if self.mismatch:
            d["mismatch"] = self.mismatch
        return d


def program_cost_sheet(
    wrapper, key, prog=None, *, param_count: int, param_bytes: int,
    cache_bytes: int, kv_itemsize: int = 2, chip: Optional[ChipSpec] = None,
    compiled=None,
) -> CostSheet:
    """One CostSheet for one compiled-program slot. ``compiled`` (or
    ``prog._compiled``) supplies the XLA analyses when present; everything
    degrades to the analytic model — this function never raises on a
    backend that cannot answer."""
    from nxdi_tpu.runtime.model_wrapper import normalize_program_key

    tc = wrapper.config.tpu_config
    chip = chip or resolve_chip(tc)
    world = max(1, tc.tp_degree * getattr(tc, "pp_degree", 1))
    bucket, steps = normalize_program_key(key)
    label = getattr(prog, "label", f"{wrapper.tag}[{key}]") if prog is not None \
        else f"{wrapper.tag}[{key}]"

    ana = analytic_program_costs(
        wrapper, bucket, steps, param_count, param_bytes, kv_itemsize
    )
    if compiled is None and prog is not None:
        compiled = getattr(prog, "_compiled", None)
    xla = xla_cost_analysis(compiled) if compiled is not None else None
    memory = xla_memory_analysis(compiled) if compiled is not None else None

    sheet = CostSheet(
        tag=wrapper.tag,
        key=key,
        label=label,
        bucket=bucket,
        steps=steps,
        batch=wrapper.batch_size,
        chip=chip,
        world=world,
        source="xla" if xla is not None else "analytic",
        flops=ana["flops"] / world,
        hbm_bytes=ana["hbm_bytes"] / world,
        weight_bytes=ana["weight_bytes"] / world,
        kv_bytes=ana["kv_bytes"] / world,
        xla_flops=None if xla is None else xla["flops"],
        xla_bytes=None if xla is None else xla.get("bytes_accessed"),
        memory=memory,
    )
    sheet.fit = hbm_residency(param_bytes, cache_bytes, world, chip, memory)
    if sheet.xla_flops and sheet.flops > 0:
        # XLA's counter sees a lax.scan layer body ONCE (the stack is a
        # while loop in HLO), so on an L-layer scanned model its total is
        # legitimately up to ~L lower than the scan-aware analytic count —
        # widen the undercount bound by L before calling it a mismatch
        scan_layers = 1 if getattr(wrapper, "layers_unrolled", False) else max(
            1, getattr(wrapper.arch, "num_layers", 1)
        )
        # a stepped program (K-step scan window / device-loop cap rung)
        # repeats the WHOLE decode body in a while loop the counter also
        # sees once — the analytic side legitimately counts `steps` times
        # more, so the undercount bound widens by steps as well
        scan_layers *= max(1, steps or 1)
        ratio = sheet.xla_flops / sheet.flops
        if ratio > MISMATCH_RATIO or ratio < 1.0 / (MISMATCH_RATIO * scan_layers):
            sheet.mismatch = (
                f"XLA reports {sheet.xla_flops:.3g} FLOPs/chip vs analytic "
                f"{sheet.flops:.3g} ({ratio:.2f}x, scan-undercount allowance "
                f"{scan_layers}x) for {label} — one of the two models is not "
                "seeing this program's real work (pallas custom calls are "
                "invisible to XLA's counter; a changed lowering can also "
                "double-count masked attention)"
            )
            logger.warning("cost model mismatch: %s", sheet.mismatch)
    return sheet


# ---------------------------------------------------------------------------
# app-level sheets
# ---------------------------------------------------------------------------

def _app_struct_account(app) -> Tuple[int, int, int, int]:
    """(param_count, param_bytes, cache_bytes, kv_itemsize) from the app's
    abstract structs — no weights touched, identical for loaded apps."""
    params_struct = app.build_params_struct()
    cache_struct = app._cache_struct()
    return (
        tree_param_count(params_struct),
        tree_bytes(params_struct),
        tree_bytes(cache_struct),
        _cache_itemsize(cache_struct),
    )


def cost_sheets(
    app, *, chip=None, compile_missing: bool = False,
) -> List[CostSheet]:
    """A CostSheet for every (submodel, bucket[, steps]) program of an app.

    Programs already compiled (a loaded app's executables) are read in
    place — zero retracing, safe next to the hot path, like
    ``collective_summary``. With ``compile_missing`` (the CLI's mode on an
    unloaded app) uncompiled slots are lowered+compiled from abstract
    structs exactly like ``aot_compile``; a slot whose compile fails still
    gets its analytic sheet.
    """
    import jax
    import jax.tree_util as jtu

    app._build_wrappers()
    chip = resolve_chip(app.tpu_config, override=chip)
    params_struct = app.build_params_struct()
    cache_struct = app._cache_struct()
    param_count = tree_param_count(params_struct)
    param_bytes = tree_bytes(params_struct)
    cache_bytes = tree_bytes(cache_struct)
    kv_itemsize = _cache_itemsize(cache_struct)

    sheets: List[CostSheet] = []
    for tag, wrapper in app.models.items():
        ps = cs = None
        for bucket, steps, key, prog in wrapper.iter_programs():
            compiled = getattr(prog, "_compiled", None)
            if compiled is None and compile_missing:
                try:
                    if ps is None:
                        attach = lambda s, sh: jax.ShapeDtypeStruct(  # noqa: E731
                            s.shape, s.dtype, sharding=sh
                        )
                        ps = jtu.tree_map(attach, params_struct, wrapper._param_shardings)
                        cs = jtu.tree_map(attach, cache_struct, wrapper._cache_shardings)
                    with jax.set_mesh(wrapper._mesh):
                        compiled = prog.jitted.lower(
                            ps, cs, wrapper._example_for_key(key)
                        ).compile()
                except Exception as e:
                    logger.warning(
                        "cost sheet: could not compile %s (%s: %s); using the "
                        "analytic model", getattr(prog, "label", key),
                        type(e).__name__, e,
                    )
                    compiled = None
            sheets.append(program_cost_sheet(
                wrapper, key, prog,
                param_count=param_count, param_bytes=param_bytes,
                cache_bytes=cache_bytes, kv_itemsize=kv_itemsize,
                chip=chip, compiled=compiled,
            ))
    return sheets


def cost_summary(app) -> Dict[str, dict]:
    """Compact {program label: cost line} from a LOADED app's executables
    (no retracing) — what the bench probes print next to their latencies."""
    def sig(x: float) -> float:  # significant digits, not fixed decimals —
        return float(f"{x:.4g}")  # tiny test programs round to 0 otherwise

    out: Dict[str, dict] = {}
    for s in cost_sheets(app, compile_missing=False):
        out[s.label] = {
            "source": s.source,
            "gflops": sig(s.flops / 1e9),
            "hbm_mb": sig(s.hbm_bytes / 1e6),
            "bound": s.bound,
            "floor_ms": sig(s.floor_s * 1e3),
            "chip": s.chip.name,
        }
    return out


# ---------------------------------------------------------------------------
# the runtime join: registry attachment publishing the roofline gauges
# ---------------------------------------------------------------------------

def attach_cost_gauges(app) -> None:
    """Join the CostSheets to the live registry: on every telemetry export
    (snapshot / Prometheus scrape) the measured MEAN dispatch latency of
    each (submodel, bucket, steps) series — ``sum/count`` of the
    ``nxdi_dispatch_seconds`` histogram, which is exact, unlike a
    bucket-interpolated percentile — is divided through the program's
    CostSheet to set ``nxdi_program_mfu_pct`` / ``nxdi_program_hbm_bw_pct``
    / ``nxdi_roofline_gap_ratio``, and the sheet table rides the JSON
    snapshot as ``_cost_sheets``.

    The gauges measure *achieved vs declared-chip-peak*; they are truthful
    step utilization at ``telemetry="full"`` (synced host dispatch) or for
    device-resident chains timed externally, and an upper bound on host
    cost otherwise. Attach errors never propagate into serving: the update
    recomputes lazily and any failure leaves the gauges unset.

    The hooks hold the app through a WEAK reference: ``app.telemetry`` owns
    the hook closures, so a strong capture would cycle app <-> telemetry
    and defeat the ``del app`` HBM-release idiom bench.py and the probes
    rely on between app builds — once the app is collected, the hooks
    quietly become no-ops.
    """
    import weakref

    tel = getattr(app, "telemetry", None)
    if tel is None or not tel.enabled:
        return
    if getattr(app, "_cost_gauges_attached", False):
        return
    app._cost_gauges_attached = True

    app_ref = weakref.ref(app)
    state: Dict[str, Any] = {"account": None, "memo": {}}

    def _sheets() -> List[CostSheet]:
        app = app_ref()
        if app is None:  # the app was freed; nothing to report
            return []
        if state["account"] is None:
            state["account"] = _app_struct_account(app)
        param_count, param_bytes, cache_bytes, kv_itemsize = state["account"]
        chip = resolve_chip(app.tpu_config)
        out = []
        for tag, wrapper in app.models.items():
            for bucket, steps, key, prog in wrapper.iter_programs():
                mk = (tag, str(key))
                cached = state["memo"].get(mk)
                compiled = getattr(prog, "_compiled", None)
                # refresh an analytic sheet once its program has compiled
                if cached is None or (
                    cached.source == "analytic" and compiled is not None
                ):
                    cached = program_cost_sheet(
                        wrapper, key, prog,
                        param_count=param_count, param_bytes=param_bytes,
                        cache_bytes=cache_bytes, kv_itemsize=kv_itemsize,
                        chip=chip, compiled=compiled,
                    )
                    state["memo"][mk] = cached
                out.append(cached)
        return out

    def _update() -> None:
        for sheet in _sheets():
            labels = dict(
                submodel=sheet.tag, bucket=str(sheet.bucket), steps=str(sheet.steps)
            )
            series = tel.dispatch_seconds.snapshot_series(**labels)
            if series is None or series.count == 0:
                continue
            mean_s = series.sum / series.count
            if mean_s <= 0:
                continue
            tel.program_mfu_pct.set(sheet.mfu_pct(mean_s), **labels)
            tel.program_hbm_bw_pct.set(sheet.hbm_bw_pct(mean_s), **labels)
            tel.roofline_gap_ratio.set(sheet.gap_ratio(mean_s), **labels)

    tel.attach(_update)
    tel.add_snapshot_extra(
        "_cost_sheets", lambda: [s.to_dict() for s in _sheets()]
    )
