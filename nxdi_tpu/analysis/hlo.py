"""Text-level views of a lowered/compiled program.

The auditor never interprets HLO semantically — it counts and maps things
that XLA spells out in the program text:

- **StableHLO** (``lowered.as_text()``): the ``@main`` signature carries a
  ``tf.aliasing_output`` / ``jax.buffer_donor`` attribute on every argument
  whose donation RESOLVED to an output alias. A donated-but-unaliased cache
  input is exactly the "two copies of the KV cache in HBM" failure mode.
- **optimized HLO** (``compiled.as_text()``): collectives exist only after
  the SPMD partitioner ran, so ``all-gather``/``all-reduce``/... are counted
  here. The layer stack is a ``lax.scan`` (a ``while`` loop in HLO), so the
  textual count is per *program*, not per layer — a policy regression that
  adds one collective to the loop body shows up as +1, regardless of depth.
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Set, Tuple

#: collective op mnemonics as they appear in optimized HLO text.
COLLECTIVE_OPS = (
    "all-reduce",
    "all-gather",
    "reduce-scatter",
    "collective-permute",
    "all-to-all",
)

# an op DEFINITION: the opcode token directly before its operand paren —
# `... f32[...] all-reduce(...)` and the async halves `... (f32[...],
# f32[...]) all-reduce-start(...)` (tuple result types contain spaces, so the
# opcode may follow a `)` + space, not a single type token). `-done` ops take
# the start's tuple without a fresh operand list and are NOT counted again;
# operand references (`%all-reduce.5`) are excluded by the preceding-char
# class (never `%`/`.`).
_COLLECTIVE_DEF_RE = re.compile(
    r"(?:^|[\s)])("
    + "|".join(op.replace("-", "[-]") for op in COLLECTIVE_OPS)
    + r")(?:-start)?\(",
    re.M,
)


def collective_counts(hlo_text: str) -> Dict[str, int]:
    """Per-type counts of collective op *definitions* in optimized HLO."""
    counts = {op: 0 for op in COLLECTIVE_OPS}
    for m in _COLLECTIVE_DEF_RE.finditer(hlo_text):
        counts[m.group(1)] += 1
    return counts


def _main_signature(stablehlo_text: str) -> Optional[str]:
    """The argument list of ``func.func public @main(...)`` with nesting and
    quoted strings (sharding attrs contain braces) handled."""
    anchor = stablehlo_text.find("@main(")
    if anchor < 0:
        return None
    i = anchor + len("@main(")
    depth = 1
    in_quote = False
    out = []
    while i < len(stablehlo_text) and depth > 0:
        c = stablehlo_text[i]
        if in_quote:
            if c == '"' and stablehlo_text[i - 1] != "\\":
                in_quote = False
        elif c == '"':
            in_quote = True
        elif c in "(<[{":
            depth += 1
        elif c in ")>]}":
            depth -= 1
        if depth > 0:
            out.append(c)
        i += 1
    return "".join(out)


_ARG_RE = re.compile(r"%arg(\d+):")


def main_arg_segments(stablehlo_text: str) -> List[Tuple[int, str]]:
    """``[(arg_index, segment_text), ...]`` — one segment per ``@main`` arg,
    covering its type and attribute dictionary."""
    sig = _main_signature(stablehlo_text)
    if sig is None:
        return []
    marks = list(_ARG_RE.finditer(sig))
    segments = []
    for j, m in enumerate(marks):
        end = marks[j + 1].start() if j + 1 < len(marks) else len(sig)
        segments.append((int(m.group(1)), sig[m.start():end]))
    return segments


def aliased_arg_positions(stablehlo_text: str) -> Set[int]:
    """Positions (``%argN`` numbers) whose argument carries a resolved
    input/output alias or donor mark."""
    out = set()
    for idx, seg in main_arg_segments(stablehlo_text):
        if "tf.aliasing_output" in seg or "jax.buffer_donor" in seg:
            out.add(idx)
    return out
