"""The checker suite the program auditor runs over every lowered submodel.

Each checker is a pure function ``(ProgramArtifacts) -> [Finding]`` over the
static views of one compiled program (jaxpr, StableHLO, optimized HLO, the
attention-strategy trace). Registered in :data:`CHECKERS`; the auditor runs
all of them unless told otherwise.

Checkers never raise on a violation — they return findings, so one bad
program cannot mask another's report. The CLI and the pytest wiring decide
what severity fails the run.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from nxdi_tpu.analysis import hlo as hlo_views
from nxdi_tpu.analysis.budget import expected_collective_budget, over_budget

#: captured constants larger than this are "a weight baked into the graph"
DEFAULT_CONST_THRESHOLD_BYTES = 512 * 1024

#: low-precision source dtypes whose upcast to fp32 counts as drift
_LOW_DTYPES = ("bfloat16", "float16", "float8_e4m3fn", "float8_e5m2")

#: function-name fragments (matched against the nxdi_tpu frames of an op's
#: traceback) where fp32 compute is intentional policy
DTYPE_DRIFT_ALLOWLIST = (
    "norm",        # rms_norm / layer_norm: fp32 variance per softmax_dtype
    "softmax",     # attention + sampling softmax
    "rotary",      # rope tables are fp32 by design
    "rope",
    "sample",      # sampling math on logits
    "topk",
    "top_k",
    "logit",       # logits processors / penalties
    "moe_router",  # router softmax precision
    "quantized_linear",  # activation-quantize scale math is fp32 by design;
                         # the actual contraction dtype is policed by the
                         # quantized_dtype checker instead
)


@dataclass
class Finding:
    """One violation (or notable observation) for one compiled program."""

    checker: str
    severity: str  # "error" | "warning"
    submodel: str
    program: str  # e.g. "token_generation_model[64]" / "tkg_multistep[k4,128]"
    message: str

    def to_dict(self) -> Dict[str, str]:
        return {
            "checker": self.checker,
            "severity": self.severity,
            "submodel": self.submodel,
            "program": self.program,
            "message": self.message,
        }

    def __str__(self) -> str:
        return f"[{self.severity}] {self.program} {self.checker}: {self.message}"


@dataclass
class ProgramArtifacts:
    """Everything a checker may look at for one (submodel, bucket) program."""

    wrapper: Any  # ModelWrapper
    tag: str
    key: Any  # bucket int, or (steps, bucket) for multi-step programs
    label: str
    config: Any  # InferenceConfig
    arch: Any  # DecoderArch
    jaxpr: Any = None  # ClosedJaxpr (None if tracing unavailable)
    stablehlo: Optional[str] = None
    hlo: Optional[str] = None
    strategies: Tuple[str, ...] = ()
    n_param_leaves: int = 0
    cache_paths: Tuple[str, ...] = ()
    kept_args: Optional[Tuple[int, ...]] = None  # flat indices kept by lowering
    donated_flags: Optional[Tuple[bool, ...]] = None  # per flat arg
    const_threshold: int = DEFAULT_CONST_THRESHOLD_BYTES
    collectives: Dict[str, int] = field(default_factory=dict)
    compiled: Any = None  # the compiled executable (memory/cost analyses)
    param_bytes: int = 0  # GLOBAL weight bytes (abstract params struct)
    cache_bytes: int = 0  # GLOBAL allocated KV bytes (= max-live KV)
    #: abstract params pytree WITH shardings attached (what aot_compile
    #: lowers against) — lets checkers reason about per-leaf PartitionSpecs
    params_struct: Any = None
    #: one dict per audit run, shared by every program's artifacts — lets a
    #: checker run program-independent passes once instead of re-emitting
    #: identical findings per (submodel, bucket)
    shared: Any = None

    @property
    def tc(self):
        return self.config.tpu_config

    def finding(self, checker: str, message: str, severity: str = "error") -> Finding:
        return Finding(checker, severity, self.tag, self.label, message)


# ---------------------------------------------------------------------------
# 1. donation audit
# ---------------------------------------------------------------------------

def check_donation(art: ProgramArtifacts) -> List[Finding]:
    """Every KV-cache input must alias an output buffer, or decode holds two
    copies of the cache in HBM for the life of the program."""
    if art.stablehlo is None:
        return [art.finding("donation", "no StableHLO available to audit",
                            severity="warning")]
    findings: List[Finding] = []
    aliased = hlo_views.aliased_arg_positions(art.stablehlo)
    n_cache = len(art.cache_paths)

    if art.kept_args is not None:
        kept = sorted(art.kept_args)
        pos_of_flat = {flat: pos for pos, flat in enumerate(kept)}
        for ci, path in enumerate(art.cache_paths):
            flat = art.n_param_leaves + ci
            if art.donated_flags is not None and not art.donated_flags[flat]:
                findings.append(art.finding(
                    "donation",
                    f"cache input '{path}' was compiled WITHOUT donation "
                    "(donate_argnums missing) — the program keeps a second "
                    "copy of this cache buffer",
                ))
                continue
            if flat not in pos_of_flat:
                findings.append(art.finding(
                    "donation",
                    f"cache input '{path}' is unused by the compiled program "
                    "(pruned from the signature) — a decode program that "
                    "never reads its cache is miswired",
                    severity="warning",
                ))
                continue
            if pos_of_flat[flat] not in aliased:
                findings.append(art.finding(
                    "donation",
                    f"cache input '{path}' is donated but did NOT resolve to "
                    "an input/output alias — XLA will materialize a second "
                    f"{path} buffer (check output sharding/layout drift on "
                    "the donated round trip)",
                ))
        return findings

    # fallback when kept_var_idx is unavailable: count aliases vs cache leaves
    if len(aliased) < n_cache:
        findings.append(art.finding(
            "donation",
            f"only {len(aliased)} of {n_cache} cache inputs resolved to an "
            "input/output alias — at least one cache buffer is doubled "
            f"(cache leaves: {', '.join(art.cache_paths)})",
        ))
    return findings


# ---------------------------------------------------------------------------
# 2. collective budget
# ---------------------------------------------------------------------------

def check_collectives(art: ProgramArtifacts) -> List[Finding]:
    """Observed collective counts must stay within the budget derived from
    the config's expected ShardingPolicy (a typo'd policy inserts extras)."""
    if art.hlo is None:
        return [art.finding("collectives", "no optimized HLO available to audit",
                            severity="warning")]
    observed = art.collectives or hlo_views.collective_counts(art.hlo)
    art.collectives = observed
    budget, explain = expected_collective_budget(art.tc, art.arch, art.wrapper)
    findings = []
    for op, (got, allowed) in over_budget(observed, budget).items():
        why = "; ".join(explain) if explain else "no collectives budgeted"
        findings.append(art.finding(
            "collectives",
            f"{got} {op} ops in the compiled program exceed the policy "
            f"budget of {allowed} — an unexplained collective usually means "
            "a sharding-policy regression (budget: " + why + ")",
        ))
    return findings


# ---------------------------------------------------------------------------
# 3. dtype-drift lint
# ---------------------------------------------------------------------------

def _nxdi_frames(eqn) -> List[Tuple[str, str]]:
    """(file, function) pairs of the eqn's traceback inside this package."""
    tb = getattr(eqn.source_info, "traceback", None)
    out = []
    if tb is None:
        return out
    for f in tb.frames:
        if "nxdi_tpu" in f.file_name:
            import os

            out.append((os.path.basename(f.file_name), f.function_name))
    return out


def _walk_jaxprs(jaxpr, visit: Callable[[Any], None]) -> None:
    """Depth-first over a Jaxpr and every nested (closed) jaxpr in eqn params."""
    for eqn in jaxpr.eqns:
        visit(eqn)
        stack = list(eqn.params.values())
        while stack:
            v = stack.pop()
            if hasattr(v, "jaxpr") and hasattr(getattr(v, "jaxpr"), "eqns"):
                _walk_jaxprs(v.jaxpr, visit)  # ClosedJaxpr
            elif hasattr(v, "eqns"):
                _walk_jaxprs(v, visit)  # raw Jaxpr
            elif isinstance(v, (list, tuple)):
                stack.extend(v)


def check_dtype_drift(art: ProgramArtifacts) -> List[Finding]:
    """Flag fp32 intermediates materialized from low-precision values outside
    the allowlisted islands (norms, softmax, rope, sampling logits)."""
    if art.jaxpr is None:
        return [art.finding("dtype_drift", "no jaxpr available to audit",
                            severity="warning")]
    vocab = getattr(art.arch, "vocab_size", -1)
    hits: List[Tuple[Tuple[int, ...], List[Tuple[str, str]]]] = []

    def visit(eqn):
        if eqn.primitive.name != "convert_element_type":
            return
        src = str(eqn.invars[0].aval.dtype)
        dst = str(eqn.outvars[0].aval.dtype)
        if src not in _LOW_DTYPES or dst not in ("float32", "float64"):
            return
        shape = tuple(eqn.outvars[0].aval.shape)
        if shape and shape[-1] == vocab:
            return  # sampling logits: fp32 on purpose
        frames = _nxdi_frames(eqn)
        names = " ".join(fn for _, fn in frames).lower()
        if any(allowed in names for allowed in DTYPE_DRIFT_ALLOWLIST):
            return
        hits.append((shape, frames[:3]))

    _walk_jaxprs(art.jaxpr.jaxpr, visit)
    findings, seen = [], set()
    for shape, frames in hits:
        where = " <- ".join(f"{fn} ({f})" for f, fn in frames) or "<no traceback>"
        msg = (
            f"low-precision value upcast to fp32 at {where} (result shape "
            f"{shape}) outside the allowlisted fp32 islands "
            f"({', '.join(DTYPE_DRIFT_ALLOWLIST[:4])}, ...) — a silent fp32 "
            "path doubles the bytes this intermediate streams"
        )
        if msg not in seen:
            seen.add(msg)
            findings.append(art.finding("dtype_drift", msg))
    return findings


# ---------------------------------------------------------------------------
# 4. baked-constant lint
# ---------------------------------------------------------------------------

def check_baked_constants(art: ProgramArtifacts) -> List[Finding]:
    """Any captured constant above the size threshold is almost certainly a
    weight closed over instead of passed as an argument — it is duplicated
    into every program that closes over it and re-uploaded per executable."""
    if art.jaxpr is None:
        return [art.finding("baked_constants", "no jaxpr available to audit",
                            severity="warning")]
    findings = []

    def scan_consts(consts):
        for c in consts:
            try:
                nbytes = int(np.asarray(c).nbytes)
                shape = tuple(np.asarray(c).shape)
                dtype = str(np.asarray(c).dtype)
            except Exception:
                continue
            if nbytes > art.const_threshold:
                findings.append(art.finding(
                    "baked_constants",
                    f"captured constant {dtype}{list(shape)} ({nbytes} bytes "
                    f"> threshold {art.const_threshold}) is baked into the "
                    "graph — pass it as a program argument so it is stored "
                    "once and shared across programs",
                ))

    scan_consts(art.jaxpr.consts)

    def visit(eqn):
        for v in eqn.params.values():
            if hasattr(v, "consts"):
                scan_consts(v.consts)
            elif isinstance(v, (list, tuple)):
                for x in v:
                    if hasattr(x, "consts"):
                        scan_consts(x.consts)

    _walk_jaxprs(art.jaxpr.jaxpr, visit)
    return findings


# ---------------------------------------------------------------------------
# 5. required kernel strategies (absorbed from _AutoLayoutProgram)
# ---------------------------------------------------------------------------

def missing_required_strategies(
    strategies: Tuple[str, ...], required
) -> List[Tuple[str, Tuple[str, ...]]]:
    """``[(flag, acceptable_names), ...]`` for every enabled kernel flag none
    of whose strategies engaged in the traced program. Shared by the runtime
    lowering check (runtime/model_wrapper.py) and the audit-time checker."""
    missing = []
    for flag, names in required:
        if not any(n in strategies for n in names):
            missing.append((flag, tuple(names)))
    return missing


def required_strategy_error(label: str, flag: str, names) -> str:
    return (
        f"{label}: {flag} is enabled but none of its kernel "
        f"strategies {tuple(names)} engaged in the compiled program — "
        "the flag would be a silent no-op for this model/config; "
        "disable it or use a supported configuration"
    )


def check_required_strategies(art: ProgramArtifacts) -> List[Finding]:
    required = art.wrapper._required_strategies()
    findings = []
    for flag, names in missing_required_strategies(art.strategies, required):
        findings.append(art.finding(
            "required_strategies", required_strategy_error(art.label, flag, names)
        ))
    return findings


# ---------------------------------------------------------------------------
# 6. KV-layout addressing
# ---------------------------------------------------------------------------

def check_kv_layout(art: ProgramArtifacts) -> List[Finding]:
    """Block-KV addressing inputs must be provably LIVE where the layout
    needs them and provably DEAD everywhere else (via ``kept_var_idx``):

    - paged programs: ``slot_mapping`` (the write path) must be live in every
      program, and ``block_table`` (the pool read path) in cache-attending
      programs — a dead one compiles fine today but routes KV writes/reads
      nowhere;
    - non-paged programs: a live ``slot_mapping``/``block_table`` input means
      the program consumes paged addressing no host code maintains — a
      layout-input mixup.
    """
    from nxdi_tpu.kvcache.kv_cache import BlockKVLayout

    paged = isinstance(getattr(art.wrapper, "layout", None), BlockKVLayout)
    try:
        example = art.wrapper._example_for_key(art.key)
    except Exception as e:
        return [art.finding(
            "kv_layout", f"example batch unavailable: {type(e).__name__}: {e}",
            severity="warning",
        )]
    keys = sorted(example)  # jax flattens dicts in sorted-key order
    present = [k for k in ("block_table", "slot_mapping") if k in keys]
    findings: List[Finding] = []
    if paged and "slot_mapping" not in present:
        findings.append(art.finding(
            "kv_layout",
            "paged program has no 'slot_mapping' batch input — the compiled "
            "program cannot address the block pool",
        ))
    if not present:
        return findings
    if art.kept_args is None:
        return findings + [art.finding(
            "kv_layout",
            "kept_var_idx unavailable; cannot prove layout-input liveness",
            severity="warning",
        )]
    kept = set(art.kept_args)
    n_fixed = art.n_param_leaves + len(art.cache_paths)
    # liveness required per input: the write path always, the read path only
    # in programs that attend the cache through the block table
    required_live = {"slot_mapping": True,
                     "block_table": bool(getattr(art.wrapper, "attend_to_cache", False))}
    for k in present:
        live = (n_fixed + keys.index(k)) in kept
        if paged and required_live[k] and not live:
            findings.append(art.finding(
                "kv_layout",
                f"paged program DROPPED its '{k}' input (pruned by "
                "kept_var_idx) — block-KV addressing is provably unused, so "
                "cache writes/reads route nowhere; the forward is not "
                "consuming the paged layout's inputs",
            ))
        elif not paged and live:
            findings.append(art.finding(
                "kv_layout",
                f"non-paged program KEEPS a live '{k}' input — it consumes "
                "paged addressing that no host code maintains for this "
                "layout (layout-input mixup)",
            ))
    return findings


# ---------------------------------------------------------------------------
# 6b. mixed prefill+decode dispatch
# ---------------------------------------------------------------------------

def check_mixed_program(art: ProgramArtifacts) -> List[Finding]:
    """The mixed-dispatch program packs prefill chunks and decode singles of
    R slots into one token stream, so its correctness hangs on three ragged
    row-descriptor inputs reaching the compiled program ALIVE (the kv_layout
    recipe, via ``kept_var_idx``):

    - ``mixed_row_ids``: per-token slot ownership — a pruned one means the
      kernel attends every token to every row's KV (cross-request leakage);
    - ``block_table`` / ``slot_mapping``: the combined R-row pool read and
      per-token write paths;

    and on the KV cache being donated: the packed program both reads and
    commits KV in one launch, so a non-donated cache doubles HBM for the
    largest program in the ladder.
    """
    from nxdi_tpu.runtime.model_wrapper import TAG_MIXED

    if art.tag != TAG_MIXED:
        return []
    try:
        example = art.wrapper._example_for_key(art.key)
    except Exception as e:
        return [art.finding(
            "mixed_program",
            f"example batch unavailable: {type(e).__name__}: {e}",
            severity="warning",
        )]
    keys = sorted(example)  # jax flattens dicts in sorted-key order
    findings: List[Finding] = []
    required = ("mixed_row_ids", "block_table", "slot_mapping")
    missing = [k for k in required if k not in keys]
    if missing:
        return [art.finding(
            "mixed_program",
            f"mixed program is missing batch input(s) {missing} — the packed "
            "token stream cannot be attributed to slots or addressed into "
            "the block pool",
        )]
    n_fixed = art.n_param_leaves + len(art.cache_paths)
    if art.kept_args is None:
        findings.append(art.finding(
            "mixed_program",
            "kept_var_idx unavailable; cannot prove ragged row-descriptor "
            "liveness", severity="warning",
        ))
    else:
        kept = set(art.kept_args)
        for k in required:
            if (n_fixed + keys.index(k)) not in kept:
                findings.append(art.finding(
                    "mixed_program",
                    f"mixed program DROPPED its '{k}' input (pruned by "
                    "kept_var_idx) — the ragged row descriptors are provably "
                    "unused, so packed tokens either attend across requests "
                    "or route KV nowhere",
                ))
    if art.donated_flags is not None:
        for ci, path in enumerate(art.cache_paths):
            if not art.donated_flags[art.n_param_leaves + ci]:
                findings.append(art.finding(
                    "mixed_program",
                    f"mixed program cache input '{path}' compiled WITHOUT "
                    "donation — the single-launch read+commit program would "
                    "hold two cache copies at its largest token bucket",
                ))
    return findings


# ---------------------------------------------------------------------------
# 6c. device-resident decode loop
# ---------------------------------------------------------------------------

def _jaxpr_has_while(jaxpr) -> bool:
    """True iff a ``while`` primitive appears anywhere in ``jaxpr`` —
    including inside nested call/scan/cond sub-jaxprs. The check must run
    on the JAXPR, not the StableHLO: ``lax.scan`` (the layer stack) also
    lowers to ``stablehlo.while``, so the text alone cannot distinguish a
    data-dependent decode loop from a fixed-trip layer scan."""
    seen: list = [jaxpr]
    while seen:
        j = seen.pop()
        for eqn in j.eqns:
            if eqn.primitive.name == "while":
                return True
            for v in eqn.params.values():
                for x in (v if isinstance(v, (list, tuple)) else (v,)):
                    inner = getattr(x, "jaxpr", x)
                    if hasattr(inner, "eqns"):
                        seen.append(inner)
    return False


def check_device_loop(art: ProgramArtifacts) -> List[Finding]:
    """The ``tkg_device_loop`` program amortizes host dispatch over a
    data-dependent number of decode steps, so its correctness hangs on
    three static properties of the lowered program:

    - an actual ``while`` loop in the traced program: a loop that traced
      away (folded/unrolled to a fixed chain) silently reverts to
      fixed-rung semantics and the per-row exit is gone;
    - the per-row halt vectors ``budget_steps`` and ``eos_token_ids``
      surviving lowering ALIVE (the kv_layout recipe, via
      ``kept_var_idx``): a pruned one means rows cannot exit early — every
      lane runs to the cap and the host receives tokens past EOS/budget;
    - the KV-cache carry donated through the loop body: the body reads and
      commits KV every iteration, so a non-donated cache doubles HBM for
      the whole launch.
    """
    from nxdi_tpu.runtime.model_wrapper import TAG_DEVICE_LOOP

    if art.tag != TAG_DEVICE_LOOP:
        return []
    findings: List[Finding] = []
    if art.jaxpr is None:
        findings.append(art.finding(
            "device_loop",
            "traced jaxpr unavailable; cannot prove the decode while-loop "
            "survived tracing", severity="warning",
        ))
    elif not _jaxpr_has_while(art.jaxpr.jaxpr):
        findings.append(art.finding(
            "device_loop",
            "no while primitive in the traced program (stablehlo.while "
            "alone cannot prove it: the layer scan lowers to one too) — "
            "the decode loop traced away, so the launch cannot run a "
            "data-dependent number of steps",
        ))
    try:
        example = art.wrapper._example_for_key(art.key)
    except Exception as e:
        return findings + [art.finding(
            "device_loop",
            f"example batch unavailable: {type(e).__name__}: {e}",
            severity="warning",
        )]
    keys = sorted(example)  # jax flattens dicts in sorted-key order
    required = ("budget_steps", "eos_token_ids")
    missing = [k for k in required if k not in keys]
    if missing:
        return findings + [art.finding(
            "device_loop",
            f"device-loop program is missing batch input(s) {missing} — the "
            "in-graph per-row halt has nothing to compare against",
        )]
    n_fixed = art.n_param_leaves + len(art.cache_paths)
    if art.kept_args is None:
        findings.append(art.finding(
            "device_loop",
            "kept_var_idx unavailable; cannot prove halt-vector liveness",
            severity="warning",
        ))
    else:
        kept = set(art.kept_args)
        for k in required:
            if (n_fixed + keys.index(k)) not in kept:
                findings.append(art.finding(
                    "device_loop",
                    f"device-loop program DROPPED its '{k}' input (pruned "
                    "by kept_var_idx) — the per-row halt is provably "
                    "unused, so every lane runs to the cap and emits past "
                    "its EOS/budget exit",
                ))
    if art.donated_flags is not None:
        for ci, path in enumerate(art.cache_paths):
            if not art.donated_flags[art.n_param_leaves + ci]:
                findings.append(art.finding(
                    "device_loop",
                    f"device-loop cache input '{path}' compiled WITHOUT "
                    "donation — the while-loop body reads and commits KV "
                    "every iteration, so the launch holds two cache copies",
                ))
    return findings


# ---------------------------------------------------------------------------
# 7. LoRA adapter sharding
# ---------------------------------------------------------------------------

def _spec_axes(leaf, dim: int, mesh=None):
    """EFFECTIVE mesh axes a leaf's PartitionSpec assigns to array dim
    ``dim`` (as a tuple; () = unsharded). Specs shorter than the array rank
    leave the trailing dims unsharded (GSPMD trailing rule); size-1 mesh
    axes shard nothing, so they are dropped — ``("ep", "epx", "tp")`` and
    ``("tp",)`` agree on a non-MoE mesh and genuinely differ once ep > 1."""
    sharding = getattr(leaf, "sharding", None)
    spec = getattr(sharding, "spec", None)
    entries = tuple(spec) if spec is not None else ()
    rank = len(getattr(leaf, "shape", ()))
    entries = entries + (None,) * max(0, rank - len(entries))
    e = entries[dim] if dim < len(entries) else None
    if e is None:
        return ()
    axes = tuple(e) if isinstance(e, (tuple, list)) else (e,)
    if mesh is not None:
        sizes = dict(mesh.shape)
        axes = tuple(a for a in axes if sizes.get(a, 1) > 1)
    return axes


def _lora_spec_findings(art: ProgramArtifacts, lc) -> List[Finding]:
    """The program-independent half of the LoRA audit: adapter A/B buffer
    PartitionSpecs vs their base projections (see check_lora_sharding)."""
    ps = art.params_struct
    layers = ps.get("layers") if isinstance(ps, dict) else None
    if not isinstance(layers, dict):
        return [art.finding(
            "lora_sharding", "params struct unavailable; cannot audit LoRA "
            "buffer shardings", severity="warning",
        )]
    from nxdi_tpu.lora.serving import LORA_TARGETABLE_MODULES

    findings: List[Finding] = []
    for name in lc.target_modules:
        group, proj = LORA_TARGETABLE_MODULES[name][0]
        p = layers.get(group, {}).get(proj)
        if not isinstance(p, dict) or "lora_A" not in p:
            continue
        base = p.get("w", p.get("qw"))
        if base is None:
            continue
        mesh = getattr(art.wrapper, "_mesh", None)
        rank_w = len(base.shape)
        in_w = _spec_axes(base, rank_w - 2, mesh)
        out_w = _spec_axes(base, rank_w - 1, mesh)
        in_a = _spec_axes(p["lora_A"], 2, mesh)
        out_b = _spec_axes(p["lora_B"], 3, mesh)
        rank_axes = _spec_axes(p["lora_A"], 3, mesh) + _spec_axes(
            p["lora_B"], 2, mesh
        )
        if in_a != in_w:
            findings.append(art.finding(
                "lora_sharding",
                f"{group}.{proj}: lora_A shards its in-features dim on axes "
                f"{in_a or '()'} but the base weight shards on "
                f"{in_w or '()'} — the adapter delta no longer decomposes "
                "the sharded projection in place, so GSPMD inserts a "
                "per-layer gather/reshard",
            ))
        if out_b != out_w:
            findings.append(art.finding(
                "lora_sharding",
                f"{group}.{proj}: lora_B shards its out-features dim on axes "
                f"{out_b or '()'} but the base weight shards on "
                f"{out_w or '()'} — a replicated adapter next to an "
                "mp-sharded weight silently all-gathers per layer",
            ))
        if rank_axes:
            findings.append(art.finding(
                "lora_sharding",
                f"{group}.{proj}: the LoRA rank dim is sharded on "
                f"{rank_axes} — the low-rank contraction becomes a per-layer "
                "cross-shard reduce; keep the rank dim replicated",
            ))
    return findings


def check_lora_sharding(art: ProgramArtifacts) -> List[Finding]:
    """LoRA adapter buffers must shard on the SAME mesh axes as the base
    projections they rank-decompose (lora/serving.py layout: ``lora_A``
    (L, S, in, r), ``lora_B`` (L, S, r, out) next to a base ``w``/``qw``
    (L, in, out)):

    - column-parallel base (out dim sharded): ``lora_B``'s out dim must
      carry the same axes — a replicated ``lora_B`` next to an mp-sharded
      weight makes GSPMD all-gather the delta (or reshard the activations)
      EVERY layer;
    - row-parallel base (in dim sharded): same for ``lora_A``'s in dim;
    - the rank dim must stay unsharded on both (a sharded contraction dim
      inserts a per-layer reduce);
    - ``adapter_ids`` routing must stay batch-replicated: every row's
      adapter gather happens on every shard, so a sharded id vector would
      route different adapters on different shards.
    """
    lc = getattr(art.tc, "lora_config", None)
    if lc is None:
        return []
    findings: List[Finding] = []
    # the buffer-spec comparison reads only the audit-wide params struct +
    # adapter spec layout — program-independent, so run it ONCE per audit
    # rather than re-emitting identical findings per (submodel, bucket)
    shared = art.shared
    run_specs = shared is None or not shared.get("lora_spec_checked")
    if shared is not None:
        shared["lora_spec_checked"] = True
    if run_specs:
        findings.extend(_lora_spec_findings(art, lc))
    # adapter_ids routing: the batch input must be fully replicated. Scan
    # every positional arg for the entry rather than assuming its position —
    # a reordered aot_compile signature must degrade to "not found", never
    # to auditing the wrong input. compiled_arg_shardings returns None on
    # jax releases without the input_shardings view (spec checks above
    # still ran).
    from nxdi_tpu.jax_compat import compiled_arg_shardings

    args = compiled_arg_shardings(art.compiled)
    for arg in args if isinstance(args, (tuple, list)) else ():
        sh = arg.get("adapter_ids") if isinstance(arg, dict) else None
        if sh is not None and not getattr(sh, "is_fully_replicated", True):
            findings.append(art.finding(
                "lora_sharding",
                "the 'adapter_ids' batch input is not batch-replicated "
                f"(compiled sharding {sh}) — shards would gather DIFFERENT "
                "adapters for the same row",
            ))
    return findings


# ---------------------------------------------------------------------------
# 8. quantized-path dtype rules
# ---------------------------------------------------------------------------

#: elementwise-ish primitives a dequant/quantize chain may pass through
#: between a convert and the dot it feeds
_QDQ_CHAIN_PRIMS = (
    "convert_element_type", "mul", "div", "add", "sub", "max", "min",
    "round", "nearbyint", "clamp", "broadcast_in_dim", "reshape",
    "transpose", "squeeze", "expand_dims", "select_n", "abs", "neg",
    "stop_gradient",
    # jnp.round / jnp.clip lower as small pjit/custom_jvp wrapper eqns —
    # flow through them (their invars) or every quantize chain dead-ends
    # one hop from the dot
    "pjit", "custom_jvp_call", "custom_vjp_call", "closed_call",
)

_INT8_DTYPES = ("int8", "uint8", "float8_e4m3fn", "float8_e5m2")


def _scan_quantized_dots(jaxpr, on_dot) -> None:
    """Depth-first over every (sub)jaxpr; calls ``on_dot(eqn, defs)`` for
    each ``dot_general`` with that jaxpr level's ``{var: producing eqn}``
    map — quantize/dequant chains never cross a scan boundary, so per-level
    dataflow is exact for this audit."""
    defs = {}
    for eqn in jaxpr.eqns:
        for ov in eqn.outvars:
            defs[ov] = eqn
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "dot_general":
            on_dot(eqn, defs)
        stack = list(eqn.params.values())
        while stack:
            v = stack.pop()
            if hasattr(v, "jaxpr") and hasattr(getattr(v, "jaxpr"), "eqns"):
                _scan_quantized_dots(v.jaxpr, on_dot)
            elif hasattr(v, "eqns"):
                _scan_quantized_dots(v, on_dot)
            elif isinstance(v, (list, tuple)):
                stack.extend(v)


def _chain_reaches(var, defs, match, max_depth: int = 16):
    """The first eqn satisfying ``match(eqn)`` reachable BACKWARD from
    ``var`` through elementwise/layout ops (None if the chain dead-ends
    into a real compute op, an argument, or the depth bound). Non-matching
    chain ops — including intermediate converts — are walked THROUGH, so a
    layered ``int8 -> f32 -> bf16`` dequant still attributes to its int8
    origin."""
    seen = set()
    frontier = [(var, 0)]
    while frontier:
        v, depth = frontier.pop()
        if depth > max_depth or id(v) in seen:
            continue
        seen.add(id(v))
        eqn = defs.get(v)
        if eqn is None:
            continue
        name = eqn.primitive.name
        if match(eqn):
            return eqn
        if name in _QDQ_CHAIN_PRIMS or name.startswith("reduce_"):
            for iv in eqn.invars:
                if hasattr(iv, "aval"):
                    frontier.append((iv, depth + 1))
    return None


def check_quantized_dtype(art: ProgramArtifacts) -> List[Finding]:
    """Quantized-path dtype rules for the w8a8 MXU path
    (``quantized=True`` + ``activation_quantization_type``):

    - **un-upcast reach**: at least one ``dot_general`` must contract
      int8 x int8 operands — a program that declared the int8 MXU path but
      upcasts/dequantizes before every dot (an fp32 detour between the
      dequant scale and the dot) silently pays full-precision matmul
      bandwidth while reporting int8 throughput;
    - **static scales are constants**: under
      ``activation_quantization_type="static"`` the calibrated
      ``input_scale`` is a checkpoint constant — an int8 dot whose quantize
      chain contains a per-token ``reduce_max`` means the hot path is
      recomputing the scale the calibration was supposed to eliminate.

    Weight-only quantization (no activation quant) upcasts INTO the matmul
    by design (dequantize-on-read) and is out of scope here.
    """
    tc = art.tc
    aq = getattr(tc, "activation_quantization_type", None)
    if not getattr(tc, "quantized", False) or aq not in ("dynamic", "static"):
        return []
    if art.jaxpr is None:
        return [art.finding("quantized_dtype", "no jaxpr available to audit",
                            severity="warning")]

    int8_dots: List[Tuple[Any, dict]] = []
    detours: List[str] = []

    def on_dot(eqn, defs):
        dts = [str(iv.aval.dtype) for iv in eqn.invars[:2]]
        if all(d in _INT8_DTYPES for d in dts):
            int8_dots.append((eqn, defs))
            return
        # a float dot whose operand chain passes through an int8 upcast is
        # the dequant-before-dot detour (record one attribution per shape)
        def from_int8(e):
            return (
                e.primitive.name == "convert_element_type"
                and str(e.invars[0].aval.dtype) in _INT8_DTYPES
            )

        for iv in eqn.invars[:2]:
            if not str(iv.aval.dtype).startswith("float"):
                continue
            cvt = _chain_reaches(iv, defs, from_int8)
            if cvt is not None:
                frames = _nxdi_frames(cvt)
                where = " <- ".join(f"{fn} ({f})" for f, fn in frames[:3])
                detours.append(
                    f"dot of shape {tuple(eqn.outvars[0].aval.shape)} consumes "
                    f"an int8 weight upcast to {iv.aval.dtype} before the "
                    f"contraction ({where or 'no traceback'})"
                )

    _scan_quantized_dots(art.jaxpr.jaxpr, on_dot)

    findings: List[Finding] = []
    if not int8_dots:
        hint = ("; ".join(detours[:2])) or "no int8 contraction found at all"
        findings.append(art.finding(
            "quantized_dtype",
            f"activation_quantization_type={aq!r} declares the int8 MXU "
            "path, but NO dot_general contracts int8 x int8 operands — the "
            f"dequant happens before the dot (fp32 detour: {hint}); the "
            "program pays full-precision matmul bandwidth while the config "
            "promises w8a8",
        ))
    if aq == "static":
        # the per-token amax reduction lives inside quantized_linear
        # (ops/quantization.py) — attribute by traceback like dtype_drift,
        # which survives the pjit/scan jaxpr nesting the dataflow walk
        # cannot cross. The KV-quant amax (kvcache/) never matches.
        recomputes = []

        def visit(eqn):
            if not eqn.primitive.name.startswith("reduce_max"):
                return
            for fname, fn in _nxdi_frames(eqn):
                if fname == "quantization.py" and "quantized_linear" in fn:
                    recomputes.append(eqn)
                    return

        _walk_jaxprs(art.jaxpr.jaxpr, visit)
        if recomputes:
            findings.append(art.finding(
                "quantized_dtype",
                "static activation quantization declared, but the program "
                f"contains {len(recomputes)} per-token reduce_max amax "
                "reduction(s) inside quantized_linear — the input scale is "
                "being RECOMPUTED on the hot path instead of consumed as "
                "the calibrated input_scale constant",
            ))
    return findings


# ---------------------------------------------------------------------------
# 9. HBM fit
# ---------------------------------------------------------------------------

def check_hbm_fit(art: ProgramArtifacts) -> List[Finding]:
    """Weights + the full allocated KV cache (max-live across every bucket)
    + XLA's temp/scratch must fit the declared chip's per-chip HBM. The
    budget derives from the sharding world like analysis/budget.py derives
    collective budgets — an over-provisioned ``seq_len * kv_cache_batch``
    product fails here at audit time instead of OOMing at load."""
    from nxdi_tpu.analysis.costs import (
        hbm_residency,
        resolve_chip,
        xla_memory_analysis,
    )

    tc = art.tc
    chip = resolve_chip(tc)
    world = max(1, tc.tp_degree * getattr(tc, "pp_degree", 1))
    memory = xla_memory_analysis(art.compiled) if art.compiled is not None else None
    fit = hbm_residency(art.param_bytes, art.cache_bytes, world, chip, memory)
    if fit["fits"]:
        return []

    def gib(x: float) -> str:
        return f"{x / 2.0 ** 30:.3f} GiB"

    return [art.finding(
        "hbm_fit",
        f"per-chip HBM residency {gib(fit['resident_bytes'])} exceeds the "
        f"{chip.name} capacity {gib(fit['hbm_capacity_bytes'])}: weights "
        f"{gib(fit['weight_bytes_per_chip'])} + max-live KV "
        f"{gib(fit['kv_bytes_per_chip'])} + temp {gib(fit['temp_bytes'])} "
        f"+ non-aliased outputs {gib(fit['output_extra_bytes'])} over a "
        f"{world}-chip world — shrink seq_len/kv_cache_batch_size, quantize "
        "weights or KV, or raise the parallel degrees",
    )]


# ---------------------------------------------------------------------------
# 12. serving-role program-set audit
# ---------------------------------------------------------------------------

#: program tags a role-restricted app must NOT ship (dead weight: compiled,
#: loaded into HBM, never dispatched by that role's engine)
_ROLE_FORBIDDEN_TAGS: Dict[str, Tuple[str, ...]] = {
    "decode": (
        "context_encoding_model",   # decode admits KV imports, never prefills
        "prefix_prefill_model",
        "mixed_model",              # mixed packs prefill chunks — same dead CTE
    ),
    "prefill": (
        "tkg_multistep",            # prefill emits ONE token then hands off
        "tkg_device_loop",
        "mixed_model",
    ),
}


def check_program_set(art: ProgramArtifacts) -> List[Finding]:
    """A role-restricted app (``TpuConfig(role="prefill"|"decode")``) must
    ship ONLY its role's program set. Disaggregation's perf story rests on
    the specialization: a decode replica that still compiles the CTE bucket
    ladder pays its compile time, its HBM residency, and its warmup for
    programs the decode engine can never dispatch — and symmetrically for
    multi-step/device-loop TKG programs on a prefill replica. config.py
    refuses the obvious combinations at build time; this checker audits the
    COMPILED reality (what iter_programs actually yields), so a hand-built
    or deserialized app cannot smuggle dead submodels past the role."""
    role = getattr(art.tc, "role", "unified")
    forbidden = _ROLE_FORBIDDEN_TAGS.get(role, ())
    if art.tag not in forbidden:
        return []
    # one finding per (submodel, bucket) program: each is a separately
    # compiled + resident executable, so per-program reporting sizes the
    # waste honestly
    return [art.finding(
        "program_set",
        f"role={role!r} app ships submodel {art.tag!r} — a "
        f"{'decode' if role == 'decode' else 'prefill'}-role engine never "
        f"dispatches it, so the program is dead weight (compile time + HBM "
        f"residency); rebuild with role='unified' or drop the submodel "
        f"flags that compile it",
    )]


#: name -> checker; the auditor runs these in order
CHECKERS: Dict[str, Callable[[ProgramArtifacts], List[Finding]]] = {
    "donation": check_donation,
    "collectives": check_collectives,
    "dtype_drift": check_dtype_drift,
    "baked_constants": check_baked_constants,
    "required_strategies": check_required_strategies,
    "kv_layout": check_kv_layout,
    "mixed_program": check_mixed_program,
    "device_loop": check_device_loop,
    "lora_sharding": check_lora_sharding,
    "quantized_dtype": check_quantized_dtype,
    "hbm_fit": check_hbm_fit,
    "program_set": check_program_set,
}
