"""ViT-family vision encoders (CLIP-style) — the compute for image-to-text
models' vision towers.

Reference: the vision encoders under models/{mllama,llama4,pixtral,qwen2_vl}
and the image-encoding applications (models/encoder_base.py:16,
image_to_text_model_base.py:34). The first tower implemented is the CLIP
layout (llava lineage; contrib llava): conv patch embedding, CLS token,
learned position embeddings, pre-LN transformer with biased qkv/out and
quick-gelu MLP, feature tap at an intermediate layer, optional CLS drop, and
a 2-layer gelu projector into the language model's hidden space.

Everything static lives in :class:`ClipVisionArch` so the encoder jits into a
single fixed-shape program per batch size (the reference compiles the vision
encoder as its own submodel, model_wrapper.py:1616 EncoderModelInstance).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from nxdi_tpu.ops.norms import layer_norm

ACTS = {
    "quick_gelu": lambda x: x * jax.nn.sigmoid(1.702 * x),
    "gelu": jax.nn.gelu,
    "gelu_pytorch_tanh": lambda x: jax.nn.gelu(x, approximate=True),
    "silu": jax.nn.silu,
}


@dataclass(frozen=True)
class ClipVisionArch:
    hidden_size: int
    intermediate_size: int
    num_layers: int
    num_heads: int
    image_size: int
    patch_size: int
    num_channels: int = 3
    hidden_act: str = "quick_gelu"
    layer_norm_eps: float = 1e-5
    # llava: vision_feature_layer=-2 -> hidden state AFTER layer L-2's block
    # (HF indexes the [embeddings, layer0_out, ...] list)
    feature_layer: int = -2
    drop_cls: bool = True  # vision_feature_select_strategy == "default"
    projector_act: str = "gelu"

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2


def _vit_attention(p, x, num_heads: int):
    """Full (bidirectional) ViT self-attention; q/k/v/out biases optional
    (CLIP/SigLIP carry them, ovis2's depend on qkv_bias)."""
    B, S, H = x.shape
    D = H // num_heads

    def lin(name, y):
        out = y @ p[name]["w"]
        return out + p[name]["b"] if "b" in p[name] else out

    q = jnp.swapaxes(lin("q_proj", x).reshape(B, S, num_heads, D), 1, 2)
    k = jnp.swapaxes(lin("k_proj", x).reshape(B, S, num_heads, D), 1, 2)
    v = jnp.swapaxes(lin("v_proj", x).reshape(B, S, num_heads, D), 1, 2)
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * (D ** -0.5)
    weights = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
    ctx = jnp.einsum("bhqk,bhkd->bhqd", weights, v)
    ctx = jnp.swapaxes(ctx, 1, 2).reshape(B, S, H)
    return lin("out_proj", ctx)


def clip_vision_forward(
    arch: ClipVisionArch, params: Dict[str, Any], pixel_values: jax.Array
) -> jax.Array:
    """pixel_values (B, C, H, W) -> patch features (B, N[, +CLS], hidden).

    The feature tap mirrors HF CLIPVisionModel(output_hidden_states=True)
    indexed at ``feature_layer`` so llava goldens match exactly.
    """
    B = pixel_values.shape[0]
    P, C, H = arch.patch_size, arch.num_channels, arch.hidden_size
    g = arch.image_size // P

    # conv with stride=patch == unfold into patches + one matmul (MXU-friendly)
    x = pixel_values.reshape(B, C, g, P, g, P)
    x = jnp.transpose(x, (0, 2, 4, 1, 3, 5)).reshape(B, g * g, C * P * P)
    patches = x @ params["patch_embedding"]  # (B, N, H)

    cls = jnp.broadcast_to(params["class_embedding"], (B, 1, H))
    h = jnp.concatenate([cls, patches], axis=1)
    h = h + params["position_embedding"][None]
    h = layer_norm(h, params["pre_layernorm"]["w"], params["pre_layernorm"]["b"],
                   eps=arch.layer_norm_eps)

    # the feature tap index is static (HF hidden-states list semantics:
    # index 0 = embeddings, i+1 = after layer i), so run ONLY the layers the
    # tap needs — no wasted trailing layers, no stacked per-layer states
    def body(carry, lp):
        res = carry
        y = layer_norm(res, lp["ln1"]["w"], lp["ln1"]["b"], eps=arch.layer_norm_eps)
        res = res + _vit_attention(lp["attn"], y, arch.num_heads)
        y = layer_norm(res, lp["ln2"]["w"], lp["ln2"]["b"], eps=arch.layer_norm_eps)
        y = ACTS[arch.hidden_act](y @ lp["fc1"]["w"] + lp["fc1"]["b"])
        res = res + (y @ lp["fc2"]["w"] + lp["fc2"]["b"])
        return res, None

    idx = arch.feature_layer % (arch.num_layers + 1)
    if idx == 0:
        feat = h
    else:
        used = jax.tree_util.tree_map(lambda a: a[:idx], params["layers"])
        feat, _ = jax.lax.scan(body, h, used)
    if arch.drop_cls:
        feat = feat[:, 1:]
    return feat


def project_image_features(arch: ClipVisionArch, params: Dict[str, Any], feat):
    """2-layer gelu projector into the LM hidden space (llava
    multi_modal_projector)."""
    p = params
    h = feat @ p["linear_1"]["w"] + p["linear_1"]["b"]
    h = ACTS[arch.projector_act](h)
    return h @ p["linear_2"]["w"] + p["linear_2"]["b"]


# ---------------------------------------------------------------------------
# Checkpoint conversion (HF CLIPVisionModel layout)
# ---------------------------------------------------------------------------

def convert_clip_vision(
    state_dict: Dict[str, np.ndarray],
    arch: ClipVisionArch,
    prefix: str = "vision_tower.vision_model.",
    dtype=np.float32,
) -> Dict[str, Any]:
    def get(name):
        for k in (prefix + name, "model." + prefix + name):
            if k in state_dict:
                return np.asarray(state_dict[k], dtype=dtype)
        raise KeyError(prefix + name)

    conv = get("embeddings.patch_embedding.weight")  # (H, C, P, P)
    params: Dict[str, Any] = {
        # match the unfold layout: (C, P, P) flattened -> H
        "patch_embedding": conv.reshape(conv.shape[0], -1).T,
        "class_embedding": get("embeddings.class_embedding"),
        "position_embedding": get("embeddings.position_embedding.weight"),
        "pre_layernorm": {"w": get("pre_layrnorm.weight"), "b": get("pre_layrnorm.bias")},
    }
    layers = []
    for i in range(arch.num_layers):
        pre = f"encoder.layers.{i}."
        lp = {
            "attn": {
                name: {
                    "w": get(pre + f"self_attn.{name}.weight").T,
                    "b": get(pre + f"self_attn.{name}.bias"),
                }
                for name in ("q_proj", "k_proj", "v_proj", "out_proj")
            },
            "ln1": {"w": get(pre + "layer_norm1.weight"), "b": get(pre + "layer_norm1.bias")},
            "ln2": {"w": get(pre + "layer_norm2.weight"), "b": get(pre + "layer_norm2.bias")},
            "fc1": {"w": get(pre + "mlp.fc1.weight").T, "b": get(pre + "mlp.fc1.bias")},
            "fc2": {"w": get(pre + "mlp.fc2.weight").T, "b": get(pre + "mlp.fc2.bias")},
        }
        layers.append(lp)
    import jax.tree_util as jtu

    params["layers"] = jtu.tree_map(lambda *xs: np.stack(xs), *layers)
    return params


def convert_llava_projector(
    state_dict: Dict[str, np.ndarray], dtype=np.float32
) -> Dict[str, Any]:
    def get(name):
        for k in ("multi_modal_projector." + name, "model.multi_modal_projector." + name):
            if k in state_dict:
                return np.asarray(state_dict[k], dtype=dtype)
        raise KeyError(name)

    return {
        "linear_1": {"w": get("linear_1.weight").T, "b": get("linear_1.bias")},
        "linear_2": {"w": get("linear_2.weight").T, "b": get("linear_2.bias")},
    }


# ---------------------------------------------------------------------------
# Pixtral vision tower (mistral-lineage ViT with 2-D rope, no CLS)
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class PixtralVisionArch:
    hidden_size: int
    intermediate_size: int
    num_layers: int
    num_heads: int
    image_size: int
    patch_size: int
    num_channels: int = 3
    rope_theta: float = 10000.0
    rms_norm_eps: float = 1e-5
    feature_layer: int = -1  # pixtral-llava taps the LAST layer, keeps all patches
    hidden_act: str = "gelu"  # HF PixtralVisionConfig default (NOT silu)
    projector_act: str = "gelu"
    # mistral3: the projector RMSNorm uses the TEXT model's rms_norm_eps, not
    # the tower's (HF Mistral3MultiModalProjector); None = use rms_norm_eps
    projector_norm_eps: Optional[float] = None

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @property
    def grid(self) -> int:
        return self.image_size // self.patch_size

    @property
    def num_patches(self) -> int:
        return self.grid ** 2


def pixtral_rope_table(arch: PixtralVisionArch) -> np.ndarray:
    """(grid^2, head_dim) angle table: h rows use even freqs, w columns odd
    freqs, concatenated twice for the rotate-half convention (HF
    PixtralRotaryEmbedding)."""
    dim = arch.head_dim
    freqs = 1.0 / (arch.rope_theta ** (np.arange(0, dim, 2, dtype=np.float64) / dim))
    g = arch.grid
    h = np.arange(g, dtype=np.float64)
    freqs_h = np.outer(h, freqs[::2])  # (g, dim/4)
    freqs_w = np.outer(h, freqs[1::2])
    table = np.concatenate(
        [
            np.repeat(freqs_h[:, None, :], g, axis=1),
            np.repeat(freqs_w[None, :, :], g, axis=0),
        ],
        axis=-1,
    ).reshape(g * g, dim // 2)
    return np.concatenate([table, table], axis=-1).astype(np.float32)


def pixtral_vision_forward(
    arch: PixtralVisionArch, params: Dict[str, Any], pixel_values: jax.Array
) -> jax.Array:
    """(B, C, H, W) -> (B, N, hidden). Each image attends fully within itself
    (HF runs all images as one block-masked sequence; per-image batching is
    the equivalent factorization)."""
    from nxdi_tpu.ops.norms import rms_norm
    from nxdi_tpu.ops.rope import rotate_half

    B = pixel_values.shape[0]
    P, C, H = arch.patch_size, arch.num_channels, arch.hidden_size
    g = arch.grid
    x = pixel_values.reshape(B, C, g, P, g, P)
    x = jnp.transpose(x, (0, 2, 4, 1, 3, 5)).reshape(B, g * g, C * P * P)
    h = x @ params["patch_embedding"]  # (B, N, H)
    h = rms_norm(h, params["ln_pre"], arch.rms_norm_eps)

    # 2-D rope: position of patch (r, c) is r*grid + c; full-resolution images
    # cover the whole table in row-major order
    angles = params["rope_table"]  # (N, head_dim)
    cos = jnp.cos(angles)[None, None]  # (1, 1, N, D)
    sin = jnp.sin(angles)[None, None]

    nH, D = arch.num_heads, arch.head_dim

    def attn(lp, y):
        q = jnp.swapaxes((y @ lp["q_proj"]).reshape(B, -1, nH, D), 1, 2)
        k = jnp.swapaxes((y @ lp["k_proj"]).reshape(B, -1, nH, D), 1, 2)
        v = jnp.swapaxes((y @ lp["v_proj"]).reshape(B, -1, nH, D), 1, 2)
        q = (q * cos + rotate_half(q) * sin).astype(y.dtype)
        k = (k * cos + rotate_half(k) * sin).astype(y.dtype)
        scores = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * (D ** -0.5)
        w = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
        ctx = jnp.einsum("bhqk,bhkd->bhqd", w, v)
        return jnp.swapaxes(ctx, 1, 2).reshape(B, -1, H) @ lp["o_proj"]

    act = ACTS[arch.hidden_act]  # KeyError on unsupported acts, not silent silu

    def body(carry, lp):
        res = carry
        res = res + attn(lp, rms_norm(res, lp["attention_norm"], arch.rms_norm_eps))
        y = rms_norm(res, lp["ffn_norm"], arch.rms_norm_eps)
        y = act(y @ lp["gate_proj"]) * (y @ lp["up_proj"])
        res = res + y @ lp["down_proj"]
        return res, res

    idx = arch.feature_layer % (arch.num_layers + 1)
    if idx == 0:
        return h
    used = jax.tree_util.tree_map(lambda a: a[:idx], params["layers"])
    feat, _ = jax.lax.scan(lambda c, lp: (body(c, lp)[0], None), h, used)
    return feat


def convert_pixtral_vision(
    state_dict: Dict[str, np.ndarray],
    arch: PixtralVisionArch,
    prefix: str = "vision_tower.",
    dtype=np.float32,
) -> Dict[str, Any]:
    def get(name):
        for k in (prefix + name, "model." + prefix + name):
            if k in state_dict:
                return np.asarray(state_dict[k], dtype=dtype)
        raise KeyError(prefix + name)

    conv = get("patch_conv.weight")  # (H, C, P, P)
    layers = []
    for i in range(arch.num_layers):
        pre = f"transformer.layers.{i}."
        layers.append({
            "q_proj": get(pre + "attention.q_proj.weight").T,
            "k_proj": get(pre + "attention.k_proj.weight").T,
            "v_proj": get(pre + "attention.v_proj.weight").T,
            "o_proj": get(pre + "attention.o_proj.weight").T,
            "attention_norm": get(pre + "attention_norm.weight"),
            "ffn_norm": get(pre + "ffn_norm.weight"),
            "gate_proj": get(pre + "feed_forward.gate_proj.weight").T,
            "up_proj": get(pre + "feed_forward.up_proj.weight").T,
            "down_proj": get(pre + "feed_forward.down_proj.weight").T,
        })
    import jax.tree_util as jtu

    return {
        "patch_embedding": conv.reshape(conv.shape[0], -1).T,
        "ln_pre": get("ln_pre.weight"),
        "rope_table": pixtral_rope_table(arch),
        "layers": jtu.tree_map(lambda *xs: np.stack(xs), *layers),
    }


# ---------------------------------------------------------------------------
# SigLIP vision tower (gemma3 lineage: no CLS, valid-conv patch embed,
# pre-LN blocks, post layernorm — reference: contrib/models/gemma3-vision)
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class SiglipVisionArch:
    hidden_size: int
    intermediate_size: int
    num_layers: int
    num_heads: int
    image_size: int
    patch_size: int
    num_channels: int = 3
    hidden_act: str = "gelu_pytorch_tanh"
    layer_norm_eps: float = 1e-6
    # gemma3 projector statics (avg-pool target + soft-emb-norm eps); None
    # when the tower is used without the gemma3 projector
    proj_tokens_per_image: Optional[int] = None
    proj_eps: float = 1e-6

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def grid(self) -> int:
        return self.image_size // self.patch_size


def siglip_vision_forward(
    arch: SiglipVisionArch, params: Dict[str, Any], pixel_values: jax.Array
) -> jax.Array:
    """(B, C, H, W) -> (B, N, hidden) post-layernormed patch features."""
    B = pixel_values.shape[0]
    P, C, Hd = arch.patch_size, arch.num_channels, arch.hidden_size
    g = arch.grid
    x = pixel_values.reshape(B, C, g, P, g, P)
    x = jnp.transpose(x, (0, 2, 4, 1, 3, 5)).reshape(B, g * g, C * P * P)
    h = x @ params["patch_embedding"] + params["patch_bias"]
    h = h + params["position_embedding"][None]

    def body(carry, lp):
        y = layer_norm(carry, lp["ln1"]["w"], lp["ln1"]["b"], arch.layer_norm_eps)
        y = _vit_attention(lp["attn"], y, arch.num_heads)
        res = carry + y
        y = layer_norm(res, lp["ln2"]["w"], lp["ln2"]["b"], arch.layer_norm_eps)
        y = ACTS[arch.hidden_act](y @ lp["fc1"]["w"] + lp["fc1"]["b"])
        y = y @ lp["fc2"]["w"] + lp["fc2"]["b"]
        return res + y, None

    h, _ = jax.lax.scan(body, h, params["layers"])
    return layer_norm(
        h, params["post_layernorm"]["w"], params["post_layernorm"]["b"],
        arch.layer_norm_eps,
    )


def convert_siglip_vision(
    state_dict: Dict[str, np.ndarray],
    arch: SiglipVisionArch,
    prefix: str = "vision_tower.vision_model.",
    dtype=np.float32,
) -> Dict[str, Any]:
    def get(name):
        for k in (prefix + name, "model." + prefix + name):
            if k in state_dict:
                return np.asarray(state_dict[k], dtype=dtype)
        raise KeyError(prefix + name)

    conv = get("embeddings.patch_embedding.weight")  # (H, C, P, P)
    layers = []
    for i in range(arch.num_layers):
        pre = f"encoder.layers.{i}."
        layers.append({
            "attn": {
                name: {
                    "w": get(pre + f"self_attn.{name}.weight").T,
                    "b": get(pre + f"self_attn.{name}.bias"),
                }
                for name in ("q_proj", "k_proj", "v_proj", "out_proj")
            },
            "ln1": {"w": get(pre + "layer_norm1.weight"),
                    "b": get(pre + "layer_norm1.bias")},
            "ln2": {"w": get(pre + "layer_norm2.weight"),
                    "b": get(pre + "layer_norm2.bias")},
            "fc1": {"w": get(pre + "mlp.fc1.weight").T, "b": get(pre + "mlp.fc1.bias")},
            "fc2": {"w": get(pre + "mlp.fc2.weight").T, "b": get(pre + "mlp.fc2.bias")},
        })
    import jax.tree_util as jtu

    return {
        "patch_embedding": conv.reshape(conv.shape[0], -1).T,
        "patch_bias": get("embeddings.patch_embedding.bias"),
        "position_embedding": get("embeddings.position_embedding.weight"),
        "post_layernorm": {"w": get("post_layernorm.weight"),
                           "b": get("post_layernorm.bias")},
        "layers": jtu.tree_map(lambda *xs: np.stack(xs), *layers),
    }


# ---------------------------------------------------------------------------
# Ovis2 vision tower (RMS-norm pre-norm ViT + SwiGLU MLP, hidden-stride 2x2
# merge, visual-token head — reference: contrib/models/Ovis2.5-9B)
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class Ovis2VisionArch:
    hidden_size: int
    intermediate_size: int
    num_layers: int
    num_heads: int
    image_size: int
    patch_size: int
    vocab_size: int  # visual vocab INCLUDING indicator rows
    num_indicator_tokens: int
    hidden_stride: int = 2
    num_channels: int = 3
    hidden_act: str = "silu"
    rms_norm_eps: float = 1e-5
    qkv_bias: bool = False
    mlp_bias: bool = False
    tokenize_function: str = "softmax"

    @property
    def grid(self) -> int:
        return self.image_size // self.patch_size

    @property
    def num_patches(self) -> int:
        return self.grid ** 2

    @property
    def num_tokens(self) -> int:
        # after the hidden_stride x hidden_stride merge
        s = -(-self.grid // self.hidden_stride)
        return s * s


def ovis2_visual_tokens(
    arch: Ovis2VisionArch, params: Dict[str, Any], pixel_values: jax.Array
) -> jax.Array:
    """(B, C, H, W) -> (B, N_merged, visual_vocab - indicators) probabilistic
    visual tokens (softmax over the visual vocabulary)."""
    from nxdi_tpu.ops.norms import rms_norm

    B = pixel_values.shape[0]
    P, C, Hd = arch.patch_size, arch.num_channels, arch.hidden_size
    g = arch.grid
    x = pixel_values.reshape(B, C, g, P, g, P)
    x = jnp.transpose(x, (0, 2, 4, 1, 3, 5)).reshape(B, g * g, C * P * P)
    h = x @ params["patch_embedding"] + params["patch_bias"]
    h = rms_norm(h, params["embed_norm"], arch.rms_norm_eps)
    h = h + params["position_embedding"][None]

    act = ACTS[arch.hidden_act]

    def body(carry, lp):
        y = rms_norm(carry, lp["norm1"], arch.rms_norm_eps)
        res = carry + _vit_attention(lp, y, arch.num_heads)
        y = rms_norm(res, lp["norm2"], arch.rms_norm_eps)

        def mp(p):
            out = y @ p["w"]
            return out + p["b"] if "b" in p else out

        gate = act(mp(lp["gate_proj"])) * mp(lp["up_proj"])
        down = gate @ lp["down_proj"]["w"]
        if "b" in lp["down_proj"]:
            down = down + lp["down_proj"]["b"]
        return res + down, None

    h, _ = jax.lax.scan(body, h, params["layers"])
    h = rms_norm(h, params["final_norm"], arch.rms_norm_eps)

    # hidden_stride x hidden_stride spatial merge (row-major grid)
    m = arch.hidden_stride
    gm = -(-g // m)
    pad = gm * m - g
    h = h.reshape(B, g, g, Hd)
    if pad:
        h = jnp.pad(h, ((0, 0), (0, pad), (0, pad), (0, 0)))
    h = h.reshape(B, gm, m, gm, m, Hd)
    h = jnp.transpose(h, (0, 1, 3, 2, 4, 5)).reshape(B, gm * gm, m * m * Hd)

    logits = h @ params["head_linear"]
    logits = layer_norm(
        logits, params["head_norm"]["w"], params["head_norm"]["b"], 1e-5
    )
    if arch.tokenize_function == "softmax":
        return jax.nn.softmax(logits, axis=-1)
    if arch.tokenize_function == "st_argmax":
        # straight-through argmax == plain argmax one-hot at inference
        return jax.nn.one_hot(jnp.argmax(logits, -1), logits.shape[-1],
                              dtype=logits.dtype)
    raise NotImplementedError(
        f"ovis2 tokenize_function {arch.tokenize_function!r} (gumbel sampling "
        "is a training-time stochastic path)"
    )


def convert_ovis2_vision(
    state_dict: Dict[str, np.ndarray],
    arch: Ovis2VisionArch,
    prefix: str = "vision_tower.",
    dtype=np.float32,
) -> Dict[str, Any]:
    def get(name, optional=False):
        for k in (prefix + name, "model." + prefix + name):
            if k in state_dict:
                return np.asarray(state_dict[k], dtype=dtype)
        if optional:
            return None
        raise KeyError(prefix + name)

    def lin(name, transpose=True):
        out = {"w": get(name + ".weight").T if transpose else get(name + ".weight")}
        b = get(name + ".bias", optional=True)
        if b is not None:
            out["b"] = b
        return out

    conv = get("transformer.embeddings.patch_embedding.weight")
    layers = []
    for i in range(arch.num_layers):
        pre = f"transformer.encoder.layers.{i}."
        layers.append({
            "norm1": get(pre + "rms_norm1.weight"),
            "norm2": get(pre + "rms_norm2.weight"),
            "q_proj": lin(pre + "attention.q_proj"),
            "k_proj": lin(pre + "attention.k_proj"),
            "v_proj": lin(pre + "attention.v_proj"),
            "out_proj": lin(pre + "attention.out_proj"),
            "gate_proj": lin(pre + "ffn.gate_proj"),
            "up_proj": lin(pre + "ffn.up_proj"),
            "down_proj": lin(pre + "ffn.down_proj"),
        })
    import jax.tree_util as jtu

    return {
        "patch_embedding": conv.reshape(conv.shape[0], -1).T,
        "patch_bias": get("transformer.embeddings.patch_embedding.bias"),
        "embed_norm": get("transformer.embeddings.rms_norm.weight"),
        "position_embedding": get("transformer.embeddings.position_embedding.weight"),
        "final_norm": get("transformer.rms_norm.weight"),
        "head_linear": get("head_linear.weight").T,
        "head_norm": {"w": get("head_norm.weight"), "b": get("head_norm.bias")},
        "layers": jtu.tree_map(lambda *xs: np.stack(xs), *layers),
    }
