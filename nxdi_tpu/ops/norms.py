"""Normalization ops.

The reference routes RMSNorm through a Neuron custom call ``AwsNeuronRmsNorm``
(modules/custom_calls.py:36-61). On TPU, XLA fuses the reduction+rsqrt+scale
pattern natively, so the idiomatic implementation is plain jnp with fp32
accumulation; a Pallas fused rmsnorm(+quant) kernel slots in later behind
``mlp_kernel_enabled``-style flags.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def rms_norm(x, weight, eps: float = 1e-6, gemma_style: bool = False):
    """RMSNorm with float32 accumulation, output in x.dtype (matches HF llama).

    ``gemma_style``: gemma-lineage checkpoints store weights as an OFFSET from
    one and multiply in float32 before the downcast — ``(norm(x) * (1 + w))``
    (reference: NeuronGemma3RMSNorm, models/gemma3/modeling_gemma3.py:44)."""
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    y = xf * jax.lax.rsqrt(var + eps)
    w = weight.astype(jnp.float32)
    if gemma_style:
        w = 1.0 + w
    return (y * w).astype(dtype)


def layer_norm(x, weight, bias=None, eps: float = 1e-5):
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    y = (xf - mean) * jax.lax.rsqrt(var + eps)
    y = y * weight.astype(jnp.float32)
    if bias is not None:
        y = y + bias.astype(jnp.float32)
    return y.astype(dtype)
