"""On-device sampling: greedy and top-k -> top-p -> temperature multinomial.

Reference: modules/generation/sampling.py — ``Sampler`` (:243) with per-batch
sampling-params tensor ``[top_k, top_p, temperature]`` (:185
``prepare_sampling_params``), staged sharded top-k (:287), inverse-CDF
multinomial (:364, torch.multinomial is untraceable there; here we use the same
inverse-CDF trick because it is deterministic given the uniform draw), and
padded-logit masking (:24 ``mask_padded_logits``).

TPU-native notes:
  - Logits arrive vocab-sharded (lm_head is column-parallel). ``lax.top_k`` on
    the sharded axis is handled by GSPMD as shard-local top-k + gather + final
    top-k — the same two-stage reduction the reference hand-writes.
  - ``global_topk`` bounds the candidate set (default 256) so the expensive
    full-vocab sort never happens.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

NEG_INF = -30000.0


def prepare_sampling_params(
    batch_size: int,
    top_k: Sequence[int] = (1,),
    top_p: Sequence[float] = (1.0,),
    temperature: Sequence[float] = (1.0,),
) -> np.ndarray:
    """(B, 3) float32 tensor [top_k, top_p, temperature] per batch line
    (reference: sampling.py:185-208)."""

    def bcast(x, name):
        arr = np.asarray(x, dtype=np.float32).reshape(-1)
        if arr.size == 1:
            arr = np.full((batch_size,), arr[0], dtype=np.float32)
        if arr.size != batch_size:
            raise ValueError(f"{name} must have 1 or batch_size entries, got {arr.size}")
        return arr

    return np.stack(
        [bcast(top_k, "top_k"), bcast(top_p, "top_p"), bcast(temperature, "temperature")],
        axis=1,
    )


def normalize_eos_ids(eos_token_id) -> List[int]:
    """int | list | array | None -> list of int eos ids (shared by the HF
    adapter and the serving engine so both accept the same spellings)."""
    if eos_token_id is None:
        return []
    return [int(e) for e in np.atleast_1d(eos_token_id).astype(np.int64)]


#: QoS priority classes (nxdi_tpu/control/qos.py), most latency-critical
#: first. Defined HERE because SamplingParams is the wire format the class
#: rides on (a leaf module the router, engine, and control plane all
#: import); the control plane re-exports it.
PRIORITY_CLASSES = ("interactive", "batch", "best_effort")


@dataclass
class SamplingParams:
    """Per-request sampling knobs. ``do_sample=False`` coerces the row to
    greedy (top_k=1) exactly like the HF adapter's generate path; actual
    stochastic sampling additionally needs the app compiled with
    ``OnDeviceSamplingConfig(do_sample=True)``. THE one sampling-row builder:
    the static generation adapter and the serving engine both encode their
    ``(top_k, top_p, temperature)`` rows through this class, so greedy
    coercion can never diverge between the two paths."""

    max_new_tokens: int = 64
    eos_token_ids: Tuple[int, ...] = ()
    do_sample: bool = False
    top_k: int = 1
    top_p: float = 1.0
    temperature: float = 1.0
    #: continuations to generate from ONE prompt (best-of-n). The serving
    #: engine expands n > 1 into sibling requests that fork the parent's
    #: prompt KV blocks copy-on-write instead of re-prefilling n times
    #: (paged layout; elsewhere siblings simply prefill). Host-side only —
    #: never part of the per-row sampling tensor.
    n: int = 1
    #: QoS identity (nxdi_tpu/control/qos.py): the tenant a token-bucket
    #: quota charges and the priority class deadline-aware scheduling
    #: orders by. Host-side only, like ``n`` — never part of the sampling
    #: tensor row, so QoS can never change what a request generates, only
    #: when it runs. None = the QosConfig defaults (or no QoS at all).
    tenant_id: Optional[str] = None
    priority: Optional[str] = None

    def __post_init__(self):
        self.eos_token_ids = tuple(normalize_eos_ids(self.eos_token_ids))
        if self.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if self.n < 1:
            raise ValueError("n must be >= 1")
        if self.tenant_id is not None:
            self.tenant_id = str(self.tenant_id)
        if self.priority is not None and self.priority not in PRIORITY_CLASSES:
            raise ValueError(
                f"priority must be one of {PRIORITY_CLASSES}, "
                f"got {self.priority!r}"
            )

    def row(self) -> Tuple[float, float, float]:
        """One (top_k, top_p, temperature) sampling row; greedy unless
        ``do_sample``."""
        return (
            float(self.top_k if self.do_sample else 1),
            float(self.top_p),
            float(self.temperature),
        )

    def tensor(self, batch_size: int) -> np.ndarray:
        """(B, 3) float32 sampling-params tensor with this row broadcast —
        what the static adapter dispatches for a whole-batch generate."""
        k, p, t = self.row()
        return prepare_sampling_params(
            batch_size, top_k=[k], top_p=[p], temperature=[t]
        )

    @staticmethod
    def rows_tensor(params: Sequence["SamplingParams"]) -> np.ndarray:
        """(B, 3) tensor with one row per request — the serving engine's
        batched decode dispatch."""
        rows = [p.row() for p in params]
        return prepare_sampling_params(
            len(rows),
            top_k=[r[0] for r in rows],
            top_p=[r[1] for r in rows],
            temperature=[r[2] for r in rows],
        )


class StepRngSchedule:
    """Host-side per-dispatch rng key data: fresh ``(seed, counter)`` threefry
    key every step — distinct draws each dispatch, reproducible under a fixed
    seed. THE one schedule shared by the static generation adapter and the
    serving engine, so fixed-seed sampled decode cannot diverge between them."""

    def __init__(self, seed: int = 0):
        self.seed = int(seed)
        self.counter = 0

    def next(self) -> np.ndarray:
        self.counter += 1
        return np.array([self.seed, self.counter], dtype=np.uint32)

    def advance(self, steps: int) -> None:
        """Skip ``steps`` counter values without drawing them. The device
        loop (models/base.py device_loop_token_gen) burns one counter per
        loop iteration IN-GRAPH (iteration t samples with key
        ``(seed, counter + t)``), so after a launch that ran N iterations
        the host schedule must land where N chained 1-step dispatches
        would have — that alignment is the sampled ON/OFF parity
        contract."""
        self.counter += max(int(steps), 0)


def extract_next_tokens(outputs) -> np.ndarray:
    """(B,) next tokens of a forward's outputs: on-device sampled ``tokens``
    when compiled with on-device sampling, host-side greedy argmax from
    ``logits`` otherwise (the reference keeps both paths too). THE one
    extraction rule shared by the static adapter and the serving engine."""
    if "tokens" in outputs:
        return np.asarray(jax.device_get(outputs["tokens"]))[:, 0]
    logits = np.asarray(jax.device_get(outputs["logits"]))
    return logits[:, -1, :].argmax(axis=-1).astype(np.int64)


#: column order of the (B, 5) array :func:`logit_health_stats` emits — the
#: numerics sentinel (telemetry/sentinel.py) indexes by this tuple, never by
#: magic numbers
LOGIT_STAT_FIELDS = ("nan", "inf", "max_abs", "entropy", "margin")


def logit_health_stats(logits) -> jax.Array:
    """(B, 5) per-row health stats over the sampled-position logit row block:
    ``[nan_count, inf_count, max|logit|, entropy_nats, top1-top2 margin]``
    (column order :data:`LOGIT_STAT_FIELDS`).

    One small in-graph reduction over logits the program already
    materialized — compiled into the forward when
    ``TpuConfig(sentinel=...)`` asks for logit health, so the stats ride
    the dispatch as a tiny extra output instead of shipping the full-vocab
    fp32 row across the program boundary. max|logit|, entropy, and margin
    are computed over the FINITE entries (a NaN burst must not turn every
    other column into NaN too — the counts carry the alarm)."""
    x = logits.astype(jnp.float32)
    if x.ndim == 3:
        x = x[:, -1, :]  # the sampled position's row block
    nan = jnp.sum(jnp.isnan(x), axis=-1).astype(jnp.float32)
    inf = jnp.sum(jnp.isinf(x), axis=-1).astype(jnp.float32)
    finite = jnp.where(jnp.isfinite(x), x, NEG_INF)
    # vocab-padding entries arrive as mask_padded_logits' NEG_INF (finite!)
    # — they are not model output and must not peg max|logit| at 30000
    valid = jnp.isfinite(x) & (x > NEG_INF)
    max_abs = jnp.max(jnp.where(valid, jnp.abs(x), 0.0), axis=-1)
    logp = jax.nn.log_softmax(finite, axis=-1)
    entropy = -jnp.sum(jnp.exp(logp) * logp, axis=-1)
    top2 = jax.lax.top_k(finite, 2)[0]
    margin = top2[:, 0] - top2[:, 1]
    return jnp.stack([nan, inf, max_abs, entropy, margin], axis=-1)


def next_step_rng(rng: jax.Array) -> jax.Array:
    """The per-step PRNG key schedule for device-resident decode chains: each
    step's key is split off the previous step's. SINGLE source of truth —
    the 1-step next_inputs path (models/base.py), the K-step decode scan
    (multi_step_token_gen), and the fused-speculation window chain all fold
    keys through this function, which is what makes a K-step scan emit
    token-for-token the same sampled stream as K chained 1-step dispatches."""
    return jax.random.split(rng, 1)[0]


def mask_padded_logits(logits, pad_size: int):
    """Mask the vocab-padding tail added so vocab divides tp
    (reference: sampling.py:24-40)."""
    if pad_size == 0:
        return logits
    vocab = logits.shape[-1]
    idx = jnp.arange(vocab)
    return jnp.where(idx >= vocab - pad_size, NEG_INF, logits)


def greedy_sample(logits):
    """(..., V) -> (...) argmax token ids."""
    return jnp.argmax(logits, axis=-1).astype(jnp.int32)


def topk_topp_temperature_sample(
    logits,  # (B, V) fp32/bf16
    sampling_params,  # (B, 3) [top_k, top_p, temperature]
    rng: jax.Array,  # PRNG key
    global_topk: int = 256,
    deterministic: bool = False,
):
    """Per-batch dynamic top-k/top-p/temperature sampling, fixed-shape.

    All batch lines run the same fixed-shape program; per-line parameters are
    applied as masks (the reference's approach on Neuron, same reason: traced
    graphs need static shapes).
    """
    B, V = logits.shape
    k = min(global_topk, V)
    logits = logits.astype(jnp.float32)
    top_vals, top_idx = jax.lax.top_k(logits, k)  # (B, k), sorted desc

    top_k_param = sampling_params[:, 0]
    top_p_param = sampling_params[:, 1]
    temperature = jnp.maximum(sampling_params[:, 2], 1e-6)

    rank = jnp.arange(k)[None, :].astype(jnp.float32)
    # top-k mask: keep rank < top_k (top_k <= 0 means disabled -> keep all)
    k_mask = jnp.where(top_k_param[:, None] > 0, rank < top_k_param[:, None], True)
    vals = jnp.where(k_mask, top_vals, NEG_INF)

    # temperature before top-p (HF order: temperature -> top-k -> top-p)
    vals = vals / temperature[:, None]

    # top-p over the candidate set: keep smallest prefix with cumprob >= top_p,
    # always keeping the best token (reference: sampling.py:338-363)
    probs = jax.nn.softmax(vals, axis=-1)
    cumprobs = jnp.cumsum(probs, axis=-1)
    p_mask = (cumprobs - probs) < top_p_param[:, None]  # exclusive cumsum < p
    p_mask = p_mask.at[:, 0].set(True)  # rank 0 always survives (top_p -> 0 == greedy)
    vals = jnp.where(p_mask, vals, NEG_INF)

    probs = jax.nn.softmax(vals, axis=-1)
    cdf = jnp.cumsum(probs, axis=-1)
    if deterministic:
        u = jnp.full((B, 1), 0.5, dtype=jnp.float32)
    else:
        u = jax.random.uniform(rng, (B, 1), dtype=jnp.float32)
    # inverse CDF: first index where cdf >= u  (reference: sampling.py:364-436)
    choice = jnp.sum((cdf < u).astype(jnp.int32), axis=-1)
    choice = jnp.clip(choice, 0, k - 1)
    return jnp.take_along_axis(top_idx, choice[:, None], axis=1)[:, 0].astype(jnp.int32)


def sample(
    logits,  # (B, V)
    sampling_params,  # (B, 3)
    rng: Optional[jax.Array] = None,
    do_sample: bool = False,
    global_topk: int = 256,
    deterministic: bool = False,
):
    """Top-level sampler (reference: sampling.py:437-467 ``Sampler.forward``).

    With ``do_sample=False`` this is pure argmax. With ``do_sample=True``,
    batch lines with top_k==1 still reduce to greedy exactly (their mask keeps
    only rank 0).
    """
    if not do_sample:
        return greedy_sample(logits)
    if rng is None:
        rng = jax.random.PRNGKey(0)
    greedy = greedy_sample(logits)
    sampled = topk_topp_temperature_sample(
        logits, sampling_params, rng, global_topk=global_topk, deterministic=deterministic
    )
    is_greedy = sampling_params[:, 0] == 1
    return jnp.where(is_greedy, greedy, sampled)
