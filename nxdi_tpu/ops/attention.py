"""Attention ops — XLA-native reference path.

This is the always-available fallback the Pallas kernels (ops/kernels/) swap in
for, mirroring the reference's strategy switch in
modules/attention/attention_base.py:1330 ``get_flash_attention_strategy``:
kernels are an optimization, never a semantic change.

TPU-first details:
  - GQA is computed grouped — Q reshaped to (B, KV, G, S, D) and einsummed
    against un-repeated K/V — instead of materializing ``repeat_kv`` like the
    reference's torch path (attention_base.py ``repeat_kv``). Saves HBM
    bandwidth, and XLA maps the grouped einsum onto the MXU directly.
  - Softmax accumulates in fp32 (configurable via softmax_dtype).
  - Masks are computed from position ids, not passed as materialized (S, S)
    bool inputs, so the same jitted program serves any right-padded batch.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -30000.0  # large-negative in bf16 range; matches reference mask fill style


def causal_mask_from_positions(q_pos, kv_pos, valid_kv=None):
    """Boolean mask (B, Sq, Skv): query at q_pos may attend key at kv_pos iff
    kv_pos <= q_pos (exact-position KV write semantics; see kvcache/kv_cache.py).

    reference: models/model_base.py:226-434 mask builders.
    """
    mask = kv_pos[:, None, :] <= q_pos[:, :, None]
    if valid_kv is not None:
        mask = mask & valid_kv[:, None, :]
    return mask


def sliding_window_mask_from_positions(q_pos, kv_pos, window: int, valid_kv=None):
    """Causal AND kv_pos > q_pos - window (reference: attention_base.py:3080 windowed)."""
    mask = causal_mask_from_positions(q_pos, kv_pos, valid_kv)
    return mask & (kv_pos[:, None, :] > q_pos[:, :, None] - window)


def chunked_attention_mask_from_positions(q_pos, kv_pos, chunk_size: int, valid_kv=None):
    """Llama4-style chunked attention: attend only within the same chunk
    (reference: attention_base.py:2559-2648)."""
    mask = causal_mask_from_positions(q_pos, kv_pos, valid_kv)
    same_chunk = (kv_pos[:, None, :] // chunk_size) == (q_pos[:, :, None] // chunk_size)
    return mask & same_chunk


def grouped_attention(
    q,  # (B, H, Sq, D)
    k,  # (B, KV, Skv, D)
    v,  # (B, KV, Skv, D)
    mask,  # (B, Sq, Skv) bool
    scale: Optional[float] = None,
    softmax_dtype=jnp.float32,
    sink: Optional[jax.Array] = None,  # (H,) learned attention-sink logits
    logit_softcap: Optional[float] = None,  # gemma2: cap*tanh(s/cap)
):
    """Grouped-head scaled dot-product attention. Returns (B, H, Sq, D)."""
    B, H, Sq, D = q.shape
    KV = k.shape[1]
    G = H // KV
    if scale is None:
        scale = D ** -0.5
    qg = q.reshape(B, KV, G, Sq, D)
    scores = jnp.einsum("bkgqd,bksd->bkgqs", qg, k, preferred_element_type=softmax_dtype)
    scores = scores.astype(softmax_dtype) * scale
    if logit_softcap is not None:
        scores = logit_softcap * jnp.tanh(scores / logit_softcap)
    scores = jnp.where(mask[:, None, None, :, :], scores, NEG_INF)
    if sink is not None:
        # gpt-oss style: concat a learned per-head sink logit before softmax and
        # drop its probability mass (reference: modules/attention/sink.py).
        sink_col = jnp.broadcast_to(
            sink.reshape(1, KV, G, 1, 1).astype(softmax_dtype), (B, KV, G, Sq, 1)
        )
        full = jnp.concatenate([scores, sink_col], axis=-1)
        weights = jax.nn.softmax(full, axis=-1)[..., :-1]
    else:
        weights = jax.nn.softmax(scores, axis=-1)
    weights = weights.astype(v.dtype)
    out = jnp.einsum("bkgqs,bksd->bkgqd", weights, v)
    # v's head dim may differ from q's (MLA: qk_head_dim vs v_head_dim)
    return out.reshape(B, H, Sq, v.shape[-1])


def attention_with_positions(
    q, k, v, q_pos, kv_pos, *,
    scale=None, softmax_dtype=jnp.float32,
    sliding_window: Optional[int] = None,
    chunk_size: Optional[int] = None,
    sink=None,
    sliding_window_enabled=None,
    chunk_enabled=None,
    logit_softcap=None,
    extra_or_mask=None,
):
    """Attention with the mask derived from positions (prefill and decode both).

    ``sliding_window_enabled`` (traced scalar bool) gates the window per LAYER
    for interleaved-SWA models (gemma3 every-6th-global, gpt-oss alternating —
    reference: get_updated_configs gemma3/modeling_gemma3.py:68, gpt-oss
    interleaved kv manager): the flag rides the layer scan, selecting between
    the windowed and plain causal mask inside one compiled body.

    ``extra_or_mask`` (B, Sq, Skv) bool is OR-ed into the final mask — the
    gemma3-vision bidirectional image-span pass (HF's or_mask_function applied
    to both the full and sliding masks).
    """
    mask = _mask_from_positions(
        q_pos, kv_pos, sliding_window, chunk_size, sliding_window_enabled,
        chunk_enabled, extra_or_mask,
    )
    return grouped_attention(
        q, k, v, mask, scale=scale, softmax_dtype=softmax_dtype, sink=sink,
        logit_softcap=logit_softcap,
    )


def _mask_from_positions(
    q_pos, kv_pos, sliding_window, chunk_size, sliding_window_enabled,
    chunk_enabled, extra_or_mask=None,
):
    if sliding_window is not None:
        mask = sliding_window_mask_from_positions(q_pos, kv_pos, sliding_window)
        if sliding_window_enabled is not None:
            mask = jnp.where(
                sliding_window_enabled, mask, causal_mask_from_positions(q_pos, kv_pos)
            )
    elif chunk_size is not None:
        mask = chunked_attention_mask_from_positions(q_pos, kv_pos, chunk_size)
        if chunk_enabled is not None:
            mask = jnp.where(
                chunk_enabled, mask, causal_mask_from_positions(q_pos, kv_pos)
            )
    else:
        mask = causal_mask_from_positions(q_pos, kv_pos)
    if extra_or_mask is not None:
        mask = mask | extra_or_mask
    return mask


def attention_two_part(
    q,  # (B, H, Sq, D)
    kk, vv,  # cache segment (B, KV, W, D/Dv)
    k2, v2,  # fresh segment (B, KV, S2, D/Dv)
    q_pos, kv_pos, kv_pos2, *,
    scale=None, softmax_dtype=jnp.float32,
    sliding_window=None, chunk_size=None, sink=None,
    sliding_window_enabled=None, chunk_enabled=None, logit_softcap=None,
):
    """Attention over [cache, fresh] WITHOUT concatenating K/V: only the
    SCORES (tiny vs the cache) are concatenated for one softmax, then the two
    weighted sums add. This is the deferred-cache-write decode path
    (models/base.py): concatenating the K/V would re-materialize the whole
    cache window per layer, which costs more than the attention itself."""
    B, H, Sq, D = q.shape
    KV = kk.shape[1]
    G = H // KV
    W, S2 = kk.shape[2], k2.shape[2]
    if scale is None:
        scale = D ** -0.5
    qg = q.reshape(B, KV, G, Sq, D)
    s1 = jnp.einsum("bkgqd,bksd->bkgqs", qg, kk, preferred_element_type=softmax_dtype)
    s2 = jnp.einsum("bkgqd,bksd->bkgqs", qg, k2, preferred_element_type=softmax_dtype)
    s = jnp.concatenate([s1, s2], axis=-1).astype(softmax_dtype) * scale
    if logit_softcap is not None:
        s = logit_softcap * jnp.tanh(s / logit_softcap)
    m1 = _mask_from_positions(
        q_pos, kv_pos, sliding_window, chunk_size, sliding_window_enabled, chunk_enabled
    )
    m2 = _mask_from_positions(
        q_pos, kv_pos2, sliding_window, chunk_size, sliding_window_enabled, chunk_enabled
    )
    mask = jnp.concatenate([m1, m2], axis=-1)
    s = jnp.where(mask[:, None, None, :, :], s, NEG_INF)
    if sink is not None:
        sink_col = jnp.broadcast_to(
            sink.reshape(1, KV, G, 1, 1).astype(softmax_dtype), (B, KV, G, Sq, 1)
        )
        full = jnp.concatenate([s, sink_col], axis=-1)
        weights = jax.nn.softmax(full, axis=-1)[..., :-1]
    else:
        weights = jax.nn.softmax(s, axis=-1)
    w1 = weights[..., :W].astype(vv.dtype)
    w2 = weights[..., W:].astype(v2.dtype)
    out = jnp.einsum("bkgqs,bksd->bkgqd", w1, vv) + jnp.einsum(
        "bkgqs,bksd->bkgqd", w2, v2
    )
    return out.reshape(B, H, Sq, vv.shape[-1])
