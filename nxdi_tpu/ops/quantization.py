"""Quantization — weight int8/fp8, dynamic activation int8, pytree transforms.

The reference quantizes offline via NxD's ``quantize`` (per-tensor / per-channel
symmetric) and swaps modules to quantized parallel layers at load
(application_base.py:744-797 ``save_quantized_state_dict``/``quantize()``,
inference_demo.py:170-199 CLI flags, config.py:217-241 + :434-517 activation
quantization). TPU-native, a "quantized linear" is just a low-bit weight array
plus a scale array with matching PartitionSpecs; XLA fuses the dequantizing
upcast-and-multiply into the matmul's operand read, so HBM traffic is the
int8/fp8 bytes — which is the entire win on a bandwidth-bound chip.

Conventions
-----------
- Weights live in ``(in, out)`` layout (parallel/layers.py); per-channel scales
  reduce over the ``in`` axis with **keepdims**, so dequantization is always the
  broadcast ``qw.astype(dt) * scale`` regardless of rank (works unchanged for
  layer-stacked ``(L, in, out)`` leaves and MoE expert ``(E, in, out)`` /
  ``(L, E, in, out)`` leaves).
- A "linear param dict" is any sub-dict containing key ``"w"``. Quantization
  replaces it with ``{"qw", "scale", **rest}``. ``models/base._linear`` and the
  MoE einsums consume either form via :func:`materialize_weight` /
  :func:`quantized_linear`.
- Per-tensor scales keep full rank with all-singleton dims, so the same
  broadcast rule applies.

Activation quantization: ``dynamic`` computes a per-token symmetric scale on
the activations, runs the matmul in int8 on the MXU
(``preferred_element_type=int32``), and rescales — the analog of the
reference's dynamic ``ActivationQuantizationType`` (config.py:434-517).
"""

from __future__ import annotations

from contextlib import contextmanager
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import ml_dtypes
import numpy as np
from jax.sharding import PartitionSpec as P

# quant dtype name -> (numpy dtype, qmax)
QUANT_DTYPES = {
    "int8": (np.int8, 127.0),
    "f8e4m3": (ml_dtypes.float8_e4m3fn, 448.0),
    "f8e5m2": (ml_dtypes.float8_e5m2, 57344.0),
}

PER_TENSOR = "per_tensor_symmetric"
PER_CHANNEL = "per_channel_symmetric"
MXFP4 = "mxfp4"  # OCP microscaling fp4: E2M1 values, power-of-2 block scales

MXFP4_BLOCK = 32
# E2M1 representable magnitudes; stored as value*2 in int8 so the grid is
# integer-exact ({0,1,2,3,4,6,8,12} with signs)
_E2M1_GRID = np.array([0.0, 0.5, 1.0, 1.5, 2.0, 3.0, 4.0, 6.0], dtype=np.float32)

# Never quantized regardless of user config: routing stays full precision (the
# reference keeps router/gating fp32 too — moe_v2.py RouterTopK), and these are
# consumed via p["w"] directly in ops/moe.py.
DEFAULT_MODULES_TO_NOT_CONVERT = (
    "router",
    "shared_expert_gate",
    # biased norms are {"w","b"} dicts too (gpt2/whisper/vision lineages) —
    # they must never be mistaken for linear layers by the {"w"}-dict walk
    "input_layernorm",
    "post_attention_layernorm",
    "pre_feedforward_layernorm",
    "post_feedforward_layernorm",
    "norm",
    "layer_norm",
    "pre_layernorm",
    "ln1",
    "ln2",
    "self_attn_layer_norm",
    "encoder_attn_layer_norm",
    "final_layer_norm",
)


def quantize_mxfp4(w: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """OCP MXFP4 (reference: gpt-oss MXFP4 weights, models/gpt_oss/
    mx_layout_transform.py): 32-element blocks along the ``in`` axis share a
    power-of-two scale; elements quantize to the E2M1 grid.

    Returns ``(qw4, scale)``: qw4 int8 of shape (..., in/32, 32, out) holding
    2x the fp4 value (integer-exact), scale float32 (..., in/32, 1, out) with
    the 0.5 folded in — so ``qw4 * scale`` dequantizes by broadcast.
    """
    w32 = np.asarray(w, dtype=np.float32)
    fin = w32.shape[-2]
    if fin % MXFP4_BLOCK:
        raise ValueError(
            f"mxfp4 needs the in dim ({fin}) divisible by {MXFP4_BLOCK}"
        )
    nb = fin // MXFP4_BLOCK
    blocks = w32.reshape(*w32.shape[:-2], nb, MXFP4_BLOCK, w32.shape[-1])
    amax = np.max(np.abs(blocks), axis=-2, keepdims=True)
    # power-of-two scale: smallest 2^e with amax/2^e <= 6 (the E2M1 max)
    e = np.ceil(np.log2(np.maximum(amax, 1e-30) / _E2M1_GRID[-1]))
    scale = np.exp2(e).astype(np.float32)
    t = blocks / scale  # |t| <= 6
    mids = (_E2M1_GRID[:-1] + _E2M1_GRID[1:]) / 2  # nearest-grid thresholds
    idx = np.searchsorted(mids, np.abs(t), side="right")
    q = np.sign(t) * _E2M1_GRID[idx] * 2.0  # store value*2
    return q.astype(np.int8), (scale * 0.5).astype(np.float32)


def quantize_array(
    w: np.ndarray, quant_dtype: str = "int8", scheme: str = PER_CHANNEL
) -> Tuple[np.ndarray, np.ndarray]:
    """Symmetric quantization of an (in, out)-layout weight (any rank >= 2).

    Returns ``(qw, scale)`` with ``scale`` float32, keepdims over the reduced
    axes so that ``qw * scale`` dequantizes by broadcast.
    """
    if quant_dtype == MXFP4 or scheme == MXFP4:
        return quantize_mxfp4(w)
    np_dt, qmax = QUANT_DTYPES[quant_dtype]
    w32 = np.asarray(w, dtype=np.float32)
    if scheme == PER_TENSOR:
        # leading stack dims (layer, expert) were separate tensors in the
        # reference — keep one scale per stacked (in, out) matrix
        amax = np.max(np.abs(w32), axis=(-2, -1), keepdims=True)
    elif scheme == PER_CHANNEL:
        # per-output-channel: reduce over the `in` axis (-2)
        amax = np.max(np.abs(w32), axis=-2, keepdims=True)
    else:
        raise ValueError(f"unknown quantization scheme {scheme!r}")
    scale = np.maximum(amax, 1e-12) / qmax
    q = w32 / scale
    if quant_dtype == "int8":
        qw = np.clip(np.rint(q), -127, 127).astype(np_dt)
    else:
        qw = np.clip(q, -qmax, qmax).astype(np_dt)
    return qw, scale.astype(np.float32)


def dequantize_array(qw: np.ndarray, scale: np.ndarray, dtype=np.float32) -> np.ndarray:
    return (np.asarray(qw, dtype=np.float32) * scale).astype(dtype)


def is_quantized(p: Dict[str, Any]) -> bool:
    return isinstance(p, dict) and ("qw" in p or "qw4" in p)


def materialize_weight(p: Dict[str, Any], dtype) -> jax.Array:
    """Return the (dequantized) weight for einsum-style consumers (MoE experts).
    XLA fuses the convert+scale into the downstream contraction's operand read."""
    if "qw4" in p:  # mxfp4 block layout -> flatten blocks back to (in, out)
        w = p["qw4"].astype(dtype) * p["scale"].astype(dtype)
        return w.reshape(*w.shape[:-3], w.shape[-3] * w.shape[-2], w.shape[-1])
    if "qw" in p:
        return p["qw"].astype(dtype) * p["scale"].astype(dtype)
    return p["w"].astype(dtype)


def quantized_linear(
    x: jax.Array,
    p: Dict[str, Any],
    act_quant: Optional[str] = None,
    clamp_bound: Optional[float] = None,
) -> jax.Array:
    """``x @ W`` over a quantized param dict ``{"qw", "scale"[, "b"]}``.

    Weight-only path: upcast-in-matmul, rescale after (scale broadcasts over the
    out axis since it kept a singleton `in` dim). ``act_quant="dynamic"`` with an
    int8 weight additionally quantizes activations per-token and runs the
    contraction on the MXU in int8 (reference: config.py:434-517).
    """
    if "qw4" in p:  # mxfp4: dequantize-on-read, weight-only
        y = x @ materialize_weight(p, x.dtype)
        if "b" in p:
            y = y + p["b"]
        return y
    qw, scale = p["qw"], p["scale"]
    if act_quant == "dynamic" and qw.dtype == jnp.int8:
        if clamp_bound is not None:
            x = jnp.clip(x, -clamp_bound, clamp_bound)
        x_amax = jnp.max(jnp.abs(x), axis=-1, keepdims=True)
        if _CALIB is not None:
            # calibration pass (under jax.disable_jit): record the largest
            # activation magnitude this linear has seen. The key is a CONTENT
            # fingerprint of the weight (shape + a 4x..x4 corner) because the
            # layer scan hands the body fresh SLICES of the stacked weights —
            # attach_input_scales recomputes the same fingerprints per layer
            key = _weight_fingerprint(qw)
            _CALIB[key] = max(_CALIB.get(key, 0.0), float(jnp.max(x_amax)))
        x_scale = jnp.maximum(x_amax.astype(jnp.float32), 1e-12) / 127.0
        qx = jnp.clip(
            jnp.round(x.astype(jnp.float32) / x_scale), -127, 127
        ).astype(jnp.int8)
        y = jax.lax.dot_general(
            qx, qw, (((qx.ndim - 1,), (qw.ndim - 2,)), ((), ())),
            preferred_element_type=jnp.int32,
        )
        # scale: (..., 1, out) -> broadcast over y's out axis; x_scale per token
        y = y.astype(jnp.float32) * x_scale * jnp.squeeze(scale, axis=-2)
        y = y.astype(x.dtype)
    elif act_quant == "static" and qw.dtype == jnp.int8:
        # static activation quantization (reference: config.py:434-517
        # "STATIC"): the per-tensor input scale is CALIBRATED OFFLINE
        # (calibrate_input_scales) and carried in the quantized checkpoint —
        # no per-token amax reduction on the hot path
        if clamp_bound is not None:
            x = jnp.clip(x, -clamp_bound, clamp_bound)
        in_s = p["input_scale"].astype(jnp.float32)
        qx = jnp.clip(
            jnp.round(x.astype(jnp.float32) / in_s), -127, 127
        ).astype(jnp.int8)
        y = jax.lax.dot_general(
            qx, qw, (((qx.ndim - 1,), (qw.ndim - 2,)), ((), ())),
            preferred_element_type=jnp.int32,
        )
        y = y.astype(jnp.float32) * in_s * jnp.squeeze(scale, axis=-2)
        y = y.astype(x.dtype)
    else:
        y = x @ qw.astype(x.dtype)
        y = (y * jnp.squeeze(scale, axis=-2).astype(x.dtype))
    if "b" in p:
        y = y + p["b"]
    return y


# ---------------------------------------------------------------------------
# Pytree transforms: params / PartitionSpecs / ShapeDtypeStructs
# ---------------------------------------------------------------------------

def _should_quantize(path: Tuple[str, ...], skip: Optional[list]) -> bool:
    """Module-name filter (reference: ``modules_to_not_convert``,
    inference_demo.py:170-199). ``skip`` entries match the last path component
    ("o_proj") or a dotted path suffix ("attn.o_proj"). The defaults in
    :data:`DEFAULT_MODULES_TO_NOT_CONVERT` always apply."""
    skip = list(DEFAULT_MODULES_TO_NOT_CONVERT) + list(skip or [])
    dotted = ".".join(str(s) for s in path)
    for name in skip:
        if path and str(path[-1]) == name:
            return False
        # dotted-suffix match on component boundaries only: "attn.q_proj"
        # must not match "self_attn.q_proj"
        if dotted == name or dotted.endswith("." + name):
            return False
    return True


def _walk(tree: Any, path: Tuple[str, ...], fn):
    if isinstance(tree, dict):
        if "w" in tree:
            out = fn(tree, path)
            if out is not None:
                return out
        return {k: _walk(v, path + (k,), fn) for k, v in tree.items()}
    return tree


def quantize_params(
    params: Dict[str, Any],
    quant_dtype: str = "int8",
    scheme: str = PER_CHANNEL,
    modules_to_not_convert: Optional[list] = None,
    static_input_scales: bool = False,
) -> Dict[str, Any]:
    """Quantize every linear param dict (``{"w": ...}``) in a host params
    pytree. Biases and norms pass through untouched. This is the online analog
    of the reference's offline ``generate_quantized_state_dict``.

    ``static_input_scales`` additionally seeds an ``input_scale=1.0`` entry
    per quantized linear (the static-activation-quant layout). 1.0 is an
    IDENTITY placeholder — run :func:`calibrate_input_scales` (or load a
    calibrated artifact) before serving, or activations simply round to the
    nearest integer."""

    def fn(d, path):
        if not _should_quantize(path, modules_to_not_convert):
            return None
        qw, scale = quantize_array(np.asarray(d["w"]), quant_dtype, scheme)
        out = {k: v for k, v in d.items() if k != "w"}
        if quant_dtype == MXFP4 or scheme == MXFP4:
            out.update(qw4=qw, scale=scale)
        else:
            out.update(qw=qw, scale=scale)
            if static_input_scales:
                # identity placeholder, one per stacked layer (the leading
                # dims before (in, out)) so it rides the layer scan's slicing
                out["input_scale"] = np.ones(d["w"].shape[:-2], np.float32)
        return out

    return _walk(params, (), fn)


# ---------------------------------------------------------------------------
# Static activation calibration (reference: the offline quantization tooling
# producing per-linear input scales consumed by config.py:434-517 "STATIC")
# ---------------------------------------------------------------------------

_CALIB: Optional[Dict[Any, float]] = None


def _weight_fingerprint(qw) -> Tuple:
    """Shape + FULL-content hash identifying a quantized weight (or a
    per-layer slice of a stacked one) across the eager scan's re-slicing.

    Hashing the whole tensor (not a corner sample) matters: two linears with
    identical shape and corner — tied projections, zero-heavy weights — must
    not silently merge into one amax calibration bucket and share a max-based
    input_scale (ADVICE r5). Calibration runs eagerly and rarely, so the full
    SHA-1 pass over each weight's bytes is off every hot path."""
    import hashlib

    qb = np.ascontiguousarray(np.asarray(qw))
    return (tuple(qb.shape), str(qb.dtype), hashlib.sha1(qb.tobytes()).digest())


@contextmanager
def activation_calibration():
    """Collect per-linear activation amax during DYNAMIC-quant forwards run
    under ``jax.disable_jit()`` (eager mode makes the amax concrete). Yields
    the collector dict keyed by weight fingerprint."""
    global _CALIB
    prev, _CALIB = _CALIB, {}
    try:
        yield _CALIB
    finally:
        _CALIB = prev


def attach_input_scales(
    params: Dict[str, Any], amax_by_fp: Dict[Any, float]
) -> Dict[str, Any]:
    """Write calibrated ``input_scale = amax / 127`` into every quantized
    linear the calibration traffic touched. Layer-stacked weights (leading
    scan axis) get a PER-LAYER (L,) scale vector — it rides the layer scan's
    slicing exactly like the weights do. Untouched linears keep their current
    (placeholder) scale."""

    def scale_of(amax: float) -> np.float32:
        return np.float32(max(amax, 1e-12) / 127.0)

    def walk(tree):
        if not isinstance(tree, dict):
            return tree
        if "qw" in tree:
            qw = np.asarray(tree["qw"])
            whole = _weight_fingerprint(qw)
            if whole in amax_by_fp:  # unstacked linear, called as-is
                return {**tree, "input_scale": scale_of(amax_by_fp[whole])}
            if qw.ndim >= 3:
                keys = [_weight_fingerprint(qw[i]) for i in range(qw.shape[0])]
                if any(k in amax_by_fp for k in keys):
                    cur = np.broadcast_to(
                        np.asarray(tree.get("input_scale", np.float32(1.0))),
                        (qw.shape[0],),
                    )
                    scales = np.asarray(
                        [
                            scale_of(amax_by_fp[k]) if k in amax_by_fp else cur[i]
                            for i, k in enumerate(keys)
                        ],
                        np.float32,
                    )
                    return {**tree, "input_scale": scales}
            # untouched by the calibration traffic (MoE experts consumed via
            # ragged-dot, linears the sample prompts never reached): keep any
            # existing scale, else seed the identity placeholder — the static
            # specs/struct expect input_scale on EVERY quantized linear, so a
            # missing key would break shard_pytree with a tree mismatch
            if "input_scale" not in tree:
                return {
                    **tree,
                    "input_scale": np.ones(qw.shape[:-2], np.float32),
                }
            return tree
        return {k: walk(v) for k, v in tree.items()}

    return walk(params)


def calibrate_input_scales(forward_fn, params, sample_batches):
    """Offline static-activation calibration: run ``forward_fn(params, batch)``
    for each sample batch in eager mode with the collector active, then
    return params with calibrated ``input_scale`` entries attached.

    ``forward_fn`` must route its linears through :func:`quantized_linear`
    with ``act_quant="dynamic"`` (the dynamic path records the amax)."""
    with jax.disable_jit(), activation_calibration() as rec:
        for batch in sample_batches:
            forward_fn(params, batch)
    return attach_input_scales(params, rec)


def calibrate_app_input_scales(app, sample_prompts):
    """Application-level static-activation calibration (the analog of the
    reference's offline quantization tooling emitting input scales): run CTE
    prefills of the sample prompts EAGERLY on an app built with
    ``activation_quantization_type="dynamic"``, record each linear's input
    amax, and return the app's params with calibrated ``input_scale`` entries.

    The compiled bucket programs are bypassed for the calibration traffic
    (eager execution is what makes the amax concrete); tp=1 is the intended
    calibration topology. Typical flow::

        app = ...  # quantized=True, activation_quantization_type="dynamic"
        app.load()
        params = calibrate_app_input_scales(app, [prompt_ids, ...])
        # serve statically: save params / rebuild the app with
        # activation_quantization_type="static"
    """
    from nxdi_tpu.runtime.model_wrapper import TAG_CONTEXT_ENCODING

    w = app.models[TAG_CONTEXT_ENCODING]

    class _EagerPrograms(dict):
        def __missing__(self, bucket):
            fn = w.make_forward(bucket)
            self[bucket] = fn
            return fn

    saved = w._programs
    w._programs = _EagerPrograms()
    try:
        with jax.disable_jit(), activation_calibration() as rec, \
                jax.set_mesh(app.mesh):
            for ids in sample_prompts:
                ids = np.asarray(ids)
                pos = np.tile(
                    np.arange(ids.shape[1], dtype=np.int32), (ids.shape[0], 1)
                )
                app.forward(ids, pos)
    finally:
        w._programs = saved
    return attach_input_scales(app.params, rec)


def quantize_param_specs(
    specs: Dict[str, Any],
    scheme: str = PER_CHANNEL,
    modules_to_not_convert: Optional[list] = None,
    quant_dtype: str = "int8",
    static_input_scales: bool = False,
) -> Dict[str, Any]:
    """Mirror :func:`quantize_params` on a PartitionSpec pytree. The scale
    inherits the weight's spec with the ``in`` axis (index -2) un-sharded —
    per-output-channel scales shard exactly like the out dim."""

    def fn(d, path):
        if not _should_quantize(path, modules_to_not_convert):
            return None
        spec_w = d["w"]
        if scheme == MXFP4 or quant_dtype == MXFP4:
            # block layout (..., nb, 32, out): the in-axis sharding moves to
            # the block axis (sharding nb over tp == sharding in over tp),
            # the 32-wide block axis stays unsharded
            entries = tuple(spec_w)
            out_entry = entries[-1] if len(entries) >= 1 else None
            in_entry = entries[-2] if len(entries) >= 2 else None
            blocked = P(*(entries[:-2] + (in_entry, None, out_entry)))
            out = {k: v for k, v in d.items() if k != "w"}
            out.update(qw4=blocked, scale=blocked)
            return out
        entries = tuple(spec_w)
        if len(entries) < 2:
            # replicated / short spec (GSPMD pads trailing dims): scale replicated
            scale_spec = P()
        else:
            out_entry = entries[-1] if scheme == PER_CHANNEL else None
            scale_spec = P(*(entries[:-2] + (None, out_entry)))
        out = {k: v for k, v in d.items() if k != "w"}
        out.update(qw=spec_w, scale=scale_spec)
        if static_input_scales:
            out["input_scale"] = P()
        return out

    return _walk(specs, (), fn)


def quantize_shape_struct(
    struct: Dict[str, Any],
    quant_dtype: str = "int8",
    scheme: str = PER_CHANNEL,
    modules_to_not_convert: Optional[list] = None,
    static_input_scales: bool = False,
) -> Dict[str, Any]:
    """Mirror :func:`quantize_params` on a ShapeDtypeStruct pytree (AOT compile
    path, application.py params_shape_struct)."""
    np_dt = None if quant_dtype == MXFP4 else QUANT_DTYPES[quant_dtype][0]

    def fn(d, path):
        if not _should_quantize(path, modules_to_not_convert):
            return None
        s = d["w"]
        if quant_dtype == MXFP4 or scheme == MXFP4:
            fin, fout = s.shape[-2], s.shape[-1]
            if fin % MXFP4_BLOCK:
                raise ValueError(
                    f"{'.'.join(map(str, path))}: mxfp4 needs the in dim "
                    f"({fin}) divisible by {MXFP4_BLOCK}"
                )
            nb = fin // MXFP4_BLOCK
            out = {k: v for k, v in d.items() if k != "w"}
            out.update(
                qw4=jax.ShapeDtypeStruct(
                    s.shape[:-2] + (nb, MXFP4_BLOCK, fout), jnp.int8
                ),
                scale=jax.ShapeDtypeStruct(s.shape[:-2] + (nb, 1, fout), jnp.float32),
            )
            return out
        if scheme == PER_TENSOR:
            scale_shape = s.shape[:-2] + (1, 1)
        else:
            scale_shape = s.shape[:-2] + (1, s.shape[-1])
        out = {k: v for k, v in d.items() if k != "w"}
        out.update(
            qw=jax.ShapeDtypeStruct(s.shape, jnp.dtype(np_dt)),
            scale=jax.ShapeDtypeStruct(scale_shape, jnp.float32),
        )
        if static_input_scales:
            out["input_scale"] = jax.ShapeDtypeStruct(s.shape[:-2], jnp.float32)
        return out

    return _walk(struct, (), fn)


def validate_quantized_params(params: Dict[str, Any], tpu_config) -> None:
    """Check a loaded pre-quantized artifact against the configured scheme:
    qw dtype must match ``quantization_dtype`` and scale shapes must match
    ``quantization_type`` (an artifact saved per-channel loaded under a
    per-tensor config would otherwise fail deep inside AOT compile)."""
    want_mx = tpu_config.quantization_dtype == MXFP4
    np_dt = None if want_mx else QUANT_DTYPES[tpu_config.quantization_dtype][0]
    scheme = tpu_config.quantization_type
    problems = []

    def visit(tree, path):
        if not isinstance(tree, dict):
            return
        if "qw4" in tree:
            name = ".".join(path)
            if not want_mx:
                problems.append(
                    f"{name}: artifact holds mxfp4 (qw4) but configured "
                    f"quantization_dtype={tpu_config.quantization_dtype}"
                )
                return
            q4 = tree["qw4"]
            if np.dtype(q4.dtype) != np.int8:
                problems.append(f"{name}: qw4 dtype {q4.dtype} != int8")
            if q4.ndim < 3 or q4.shape[-2] != MXFP4_BLOCK:
                problems.append(
                    f"{name}: qw4 shape {tuple(q4.shape)} is not the "
                    f"(..., nb, {MXFP4_BLOCK}, out) block layout"
                )
            elif "scale" not in tree:
                problems.append(f"{name}: missing mxfp4 scale")
            elif tuple(tree["scale"].shape) != q4.shape[:-2] + (1, q4.shape[-1]):
                problems.append(
                    f"{name}: scale shape {tuple(tree['scale'].shape)} != "
                    f"{q4.shape[:-2] + (1, q4.shape[-1])}"
                )
            return
        if "qw" in tree:
            if want_mx:
                problems.append(
                    ".".join(path) + ": artifact holds qw but configured "
                    "quantization_dtype=mxfp4 expects qw4 block layout"
                )
                return
            name = ".".join(path)
            if np.dtype(tree["qw"].dtype) != np.dtype(np_dt):
                problems.append(
                    f"{name}: qw dtype {tree['qw'].dtype} != configured "
                    f"quantization_dtype={tpu_config.quantization_dtype}"
                )
            want = (
                tree["qw"].shape[:-2] + (1, 1)
                if scheme == PER_TENSOR
                else tree["qw"].shape[:-2] + (1, tree["qw"].shape[-1])
            )
            if tuple(tree["scale"].shape) != want:
                problems.append(
                    f"{name}: scale shape {tuple(tree['scale'].shape)} != {want} "
                    f"expected for quantization_type={scheme}"
                )
            return
        for k, v in tree.items():
            visit(v, path + (k,))

    visit(params, ())
    if problems:
        raise ValueError(
            "quantized_checkpoints_path artifact does not match the configured "
            "quantization scheme:\n  " + "\n  ".join(problems[:8])
        )


def flatten_params(params: Dict[str, Any], prefix: str = "") -> Dict[str, np.ndarray]:
    """Dotted-key flat dict for safetensors round-trip of quantized checkpoints
    (reference saves quantized state dicts to ``quantized_checkpoints_path``,
    application_base.py:744)."""
    flat: Dict[str, np.ndarray] = {}
    for k, v in params.items():
        key = f"{prefix}{k}"
        if isinstance(v, dict):
            flat.update(flatten_params(v, key + "."))
        else:
            flat[key] = np.asarray(v)
    return flat


def unflatten_params(flat: Dict[str, np.ndarray]) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for key, v in flat.items():
        parts = key.split(".")
        d = out
        for p in parts[:-1]:
            d = d.setdefault(p, {})
        d[parts[-1]] = v
    return out
