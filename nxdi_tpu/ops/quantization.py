"""Quantization — weight int8/fp8, dynamic activation int8, pytree transforms.

The reference quantizes offline via NxD's ``quantize`` (per-tensor / per-channel
symmetric) and swaps modules to quantized parallel layers at load
(application_base.py:744-797 ``save_quantized_state_dict``/``quantize()``,
inference_demo.py:170-199 CLI flags, config.py:217-241 + :434-517 activation
quantization). TPU-native, a "quantized linear" is just a low-bit weight array
plus a scale array with matching PartitionSpecs; XLA fuses the dequantizing
upcast-and-multiply into the matmul's operand read, so HBM traffic is the
int8/fp8 bytes — which is the entire win on a bandwidth-bound chip.

Conventions
-----------
- Weights live in ``(in, out)`` layout (parallel/layers.py); per-channel scales
  reduce over the ``in`` axis with **keepdims**, so dequantization is always the
  broadcast ``qw.astype(dt) * scale`` regardless of rank (works unchanged for
  layer-stacked ``(L, in, out)`` leaves and MoE expert ``(E, in, out)`` /
  ``(L, E, in, out)`` leaves).
- A "linear param dict" is any sub-dict containing key ``"w"``. Quantization
  replaces it with ``{"qw", "scale", **rest}``. ``models/base._linear`` and the
  MoE einsums consume either form via :func:`materialize_weight` /
  :func:`quantized_linear`.
- Per-tensor scales keep full rank with all-singleton dims, so the same
  broadcast rule applies.

Activation quantization: ``dynamic`` computes a per-token symmetric scale on
the activations, runs the matmul in int8 on the MXU
(``preferred_element_type=int32``), and rescales — the analog of the
reference's dynamic ``ActivationQuantizationType`` (config.py:434-517).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import ml_dtypes
import numpy as np
from jax.sharding import PartitionSpec as P

# quant dtype name -> (numpy dtype, qmax)
QUANT_DTYPES = {
    "int8": (np.int8, 127.0),
    "f8e4m3": (ml_dtypes.float8_e4m3fn, 448.0),
    "f8e5m2": (ml_dtypes.float8_e5m2, 57344.0),
}

PER_TENSOR = "per_tensor_symmetric"
PER_CHANNEL = "per_channel_symmetric"

# Never quantized regardless of user config: routing stays full precision (the
# reference keeps router/gating fp32 too — moe_v2.py RouterTopK), and these are
# consumed via p["w"] directly in ops/moe.py.
DEFAULT_MODULES_TO_NOT_CONVERT = ("router", "shared_expert_gate")


def quantize_array(
    w: np.ndarray, quant_dtype: str = "int8", scheme: str = PER_CHANNEL
) -> Tuple[np.ndarray, np.ndarray]:
    """Symmetric quantization of an (in, out)-layout weight (any rank >= 2).

    Returns ``(qw, scale)`` with ``scale`` float32, keepdims over the reduced
    axes so that ``qw * scale`` dequantizes by broadcast.
    """
    np_dt, qmax = QUANT_DTYPES[quant_dtype]
    w32 = np.asarray(w, dtype=np.float32)
    if scheme == PER_TENSOR:
        # leading stack dims (layer, expert) were separate tensors in the
        # reference — keep one scale per stacked (in, out) matrix
        amax = np.max(np.abs(w32), axis=(-2, -1), keepdims=True)
    elif scheme == PER_CHANNEL:
        # per-output-channel: reduce over the `in` axis (-2)
        amax = np.max(np.abs(w32), axis=-2, keepdims=True)
    else:
        raise ValueError(f"unknown quantization scheme {scheme!r}")
    scale = np.maximum(amax, 1e-12) / qmax
    q = w32 / scale
    if quant_dtype == "int8":
        qw = np.clip(np.rint(q), -127, 127).astype(np_dt)
    else:
        qw = np.clip(q, -qmax, qmax).astype(np_dt)
    return qw, scale.astype(np.float32)


def dequantize_array(qw: np.ndarray, scale: np.ndarray, dtype=np.float32) -> np.ndarray:
    return (np.asarray(qw, dtype=np.float32) * scale).astype(dtype)


def is_quantized(p: Dict[str, Any]) -> bool:
    return isinstance(p, dict) and "qw" in p


def materialize_weight(p: Dict[str, Any], dtype) -> jax.Array:
    """Return the (dequantized) weight for einsum-style consumers (MoE experts).
    XLA fuses the convert+scale into the downstream contraction's operand read."""
    if is_quantized(p):
        return p["qw"].astype(dtype) * p["scale"].astype(dtype)
    return p["w"].astype(dtype)


def quantized_linear(
    x: jax.Array,
    p: Dict[str, Any],
    act_quant: Optional[str] = None,
    clamp_bound: Optional[float] = None,
) -> jax.Array:
    """``x @ W`` over a quantized param dict ``{"qw", "scale"[, "b"]}``.

    Weight-only path: upcast-in-matmul, rescale after (scale broadcasts over the
    out axis since it kept a singleton `in` dim). ``act_quant="dynamic"`` with an
    int8 weight additionally quantizes activations per-token and runs the
    contraction on the MXU in int8 (reference: config.py:434-517).
    """
    qw, scale = p["qw"], p["scale"]
    if act_quant == "dynamic" and qw.dtype == jnp.int8:
        if clamp_bound is not None:
            x = jnp.clip(x, -clamp_bound, clamp_bound)
        x_amax = jnp.max(jnp.abs(x), axis=-1, keepdims=True)
        x_scale = jnp.maximum(x_amax.astype(jnp.float32), 1e-12) / 127.0
        qx = jnp.clip(
            jnp.round(x.astype(jnp.float32) / x_scale), -127, 127
        ).astype(jnp.int8)
        y = jax.lax.dot_general(
            qx, qw, (((qx.ndim - 1,), (qw.ndim - 2,)), ((), ())),
            preferred_element_type=jnp.int32,
        )
        # scale: (..., 1, out) -> broadcast over y's out axis; x_scale per token
        y = y.astype(jnp.float32) * x_scale * jnp.squeeze(scale, axis=-2)
        y = y.astype(x.dtype)
    else:
        y = x @ qw.astype(x.dtype)
        y = (y * jnp.squeeze(scale, axis=-2).astype(x.dtype))
    if "b" in p:
        y = y + p["b"]
    return y


# ---------------------------------------------------------------------------
# Pytree transforms: params / PartitionSpecs / ShapeDtypeStructs
# ---------------------------------------------------------------------------

def _should_quantize(path: Tuple[str, ...], skip: Optional[list]) -> bool:
    """Module-name filter (reference: ``modules_to_not_convert``,
    inference_demo.py:170-199). ``skip`` entries match the last path component
    ("o_proj") or a dotted path suffix ("attn.o_proj"). The defaults in
    :data:`DEFAULT_MODULES_TO_NOT_CONVERT` always apply."""
    skip = list(DEFAULT_MODULES_TO_NOT_CONVERT) + list(skip or [])
    dotted = ".".join(str(s) for s in path)
    for name in skip:
        if path and str(path[-1]) == name:
            return False
        # dotted-suffix match on component boundaries only: "attn.q_proj"
        # must not match "self_attn.q_proj"
        if dotted == name or dotted.endswith("." + name):
            return False
    return True


def _walk(tree: Any, path: Tuple[str, ...], fn):
    if isinstance(tree, dict):
        if "w" in tree:
            out = fn(tree, path)
            if out is not None:
                return out
        return {k: _walk(v, path + (k,), fn) for k, v in tree.items()}
    return tree


def quantize_params(
    params: Dict[str, Any],
    quant_dtype: str = "int8",
    scheme: str = PER_CHANNEL,
    modules_to_not_convert: Optional[list] = None,
) -> Dict[str, Any]:
    """Quantize every linear param dict (``{"w": ...}``) in a host params
    pytree. Biases and norms pass through untouched. This is the online analog
    of the reference's offline ``generate_quantized_state_dict``."""

    def fn(d, path):
        if not _should_quantize(path, modules_to_not_convert):
            return None
        qw, scale = quantize_array(np.asarray(d["w"]), quant_dtype, scheme)
        out = {k: v for k, v in d.items() if k != "w"}
        out.update(qw=qw, scale=scale)
        return out

    return _walk(params, (), fn)


def quantize_param_specs(
    specs: Dict[str, Any],
    scheme: str = PER_CHANNEL,
    modules_to_not_convert: Optional[list] = None,
) -> Dict[str, Any]:
    """Mirror :func:`quantize_params` on a PartitionSpec pytree. The scale
    inherits the weight's spec with the ``in`` axis (index -2) un-sharded —
    per-output-channel scales shard exactly like the out dim."""

    def fn(d, path):
        if not _should_quantize(path, modules_to_not_convert):
            return None
        spec_w = d["w"]
        entries = tuple(spec_w)
        if len(entries) < 2:
            # replicated / short spec (GSPMD pads trailing dims): scale replicated
            scale_spec = P()
        else:
            out_entry = entries[-1] if scheme == PER_CHANNEL else None
            scale_spec = P(*(entries[:-2] + (None, out_entry)))
        out = {k: v for k, v in d.items() if k != "w"}
        out.update(qw=spec_w, scale=scale_spec)
        return out

    return _walk(specs, (), fn)


def quantize_shape_struct(
    struct: Dict[str, Any],
    quant_dtype: str = "int8",
    scheme: str = PER_CHANNEL,
    modules_to_not_convert: Optional[list] = None,
) -> Dict[str, Any]:
    """Mirror :func:`quantize_params` on a ShapeDtypeStruct pytree (AOT compile
    path, application.py params_shape_struct)."""
    np_dt, _ = QUANT_DTYPES[quant_dtype]

    def fn(d, path):
        if not _should_quantize(path, modules_to_not_convert):
            return None
        s = d["w"]
        if scheme == PER_TENSOR:
            scale_shape = s.shape[:-2] + (1, 1)
        else:
            scale_shape = s.shape[:-2] + (1, s.shape[-1])
        out = {k: v for k, v in d.items() if k != "w"}
        out.update(
            qw=jax.ShapeDtypeStruct(s.shape, jnp.dtype(np_dt)),
            scale=jax.ShapeDtypeStruct(scale_shape, jnp.float32),
        )
        return out

    return _walk(struct, (), fn)


def validate_quantized_params(params: Dict[str, Any], tpu_config) -> None:
    """Check a loaded pre-quantized artifact against the configured scheme:
    qw dtype must match ``quantization_dtype`` and scale shapes must match
    ``quantization_type`` (an artifact saved per-channel loaded under a
    per-tensor config would otherwise fail deep inside AOT compile)."""
    np_dt, _ = QUANT_DTYPES[tpu_config.quantization_dtype]
    scheme = tpu_config.quantization_type
    problems = []

    def visit(tree, path):
        if not isinstance(tree, dict):
            return
        if "qw" in tree:
            name = ".".join(path)
            if np.dtype(tree["qw"].dtype) != np.dtype(np_dt):
                problems.append(
                    f"{name}: qw dtype {tree['qw'].dtype} != configured "
                    f"quantization_dtype={tpu_config.quantization_dtype}"
                )
            want = (
                tree["qw"].shape[:-2] + (1, 1)
                if scheme == PER_TENSOR
                else tree["qw"].shape[:-2] + (1, tree["qw"].shape[-1])
            )
            if tuple(tree["scale"].shape) != want:
                problems.append(
                    f"{name}: scale shape {tuple(tree['scale'].shape)} != {want} "
                    f"expected for quantization_type={scheme}"
                )
            return
        for k, v in tree.items():
            visit(v, path + (k,))

    visit(params, ())
    if problems:
        raise ValueError(
            "quantized_checkpoints_path artifact does not match the configured "
            "quantization scheme:\n  " + "\n  ".join(problems[:8])
        )


def flatten_params(params: Dict[str, Any], prefix: str = "") -> Dict[str, np.ndarray]:
    """Dotted-key flat dict for safetensors round-trip of quantized checkpoints
    (reference saves quantized state dicts to ``quantized_checkpoints_path``,
    application_base.py:744)."""
    flat: Dict[str, np.ndarray] = {}
    for k, v in params.items():
        key = f"{prefix}{k}"
        if isinstance(v, dict):
            flat.update(flatten_params(v, key + "."))
        else:
            flat[key] = np.asarray(v)
    return flat


def unflatten_params(flat: Dict[str, np.ndarray]) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for key, v in flat.items():
        parts = key.split(".")
        d = out
        for p in parts[:-1]:
            d = d.setdefault(p, {})
        d[parts[-1]] = v
    return out
