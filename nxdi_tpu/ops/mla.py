"""Multi-head Latent Attention (MLA) — deepseek lineage.

Reference: models/deepseek/modeling_deepseek.py:79 ``DeepseekV3Attention``
(q LoRA path :172-186, compressed kv :188-199, yarn rope rope_util.py) —
re-designed around a LATENT KV cache instead of the reference's expanded
per-head cache:

  - the cache's ``k`` stores the ROTATED shared rope key (B, 1, S, qk_rope),
    its ``v`` the rms-normed compressed kv latent (B, 1, S, kv_lora) —
    per-position cache cost is ``kv_lora + qk_rope`` (e.g. 576 for V3) instead
    of ``heads * (qk_nope + qk_rope + v_dim)``, the whole point of MLA;
  - at attention time the latent is expanded through ``kv_b`` to per-head
    k_nope/value (the non-absorbed formulation — mathematically identical to
    HF eager; the absorbed-matmul decode optimization is a later kernel).

Head sharding: MLA has no GQA — q/kv_b/o shard over heads, which must divide
tp (the reference asserts the same, modeling_deepseek.py:137).

``rope_interleave`` checkpoints (deepseek stores rope channels interleaved)
are handled at CONVERSION time by permuting the rope-dim output columns of
q(_b) and kv_a, so the runtime always uses the standard rotate-half.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from nxdi_tpu.ops import attention as attn_ops
from nxdi_tpu.ops.norms import rms_norm
from nxdi_tpu.ops.rope import apply_rotary_pos_emb
from nxdi_tpu.parallel.mesh import AXIS_MP


@dataclass(frozen=True)
class MLAArch:
    num_heads: int
    q_lora_rank: Optional[int]
    kv_lora_rank: int
    qk_nope_head_dim: int
    qk_rope_head_dim: int
    v_head_dim: int
    softmax_scale: float

    @property
    def qk_head_dim(self) -> int:
        return self.qk_nope_head_dim + self.qk_rope_head_dim


def mla_attention_block(
    arch,  # DecoderArch with .mla set
    p_attn: Dict[str, Any],
    hidden: jax.Array,  # (B, S, hidden)
    cos: jax.Array,
    sin: jax.Array,
    k_cache_l: jax.Array,  # (B, 1, S_max, qk_rope) rotated rope keys
    v_cache_l: jax.Array,  # (B, 1, S_max, kv_lora) normed latents
    position_ids: jax.Array,
    cache_spec,
    attend_to_cache: bool,
    policy,
    layout,
    cache_inputs: Optional[Dict[str, jax.Array]] = None,
    adapter_ids: Optional[jax.Array] = None,
    window_enabled=None,
    use_rope=None,
) -> Tuple[jax.Array, Tuple[jax.Array, jax.Array]]:
    from nxdi_tpu.models.base import _linear

    mla: MLAArch = arch.mla
    B, S, _ = hidden.shape
    H = mla.num_heads
    nope, rope_d, r = mla.qk_nope_head_dim, mla.qk_rope_head_dim, mla.kv_lora_rank
    aq, ac = arch.act_quant, arch.act_clamp

    # -- queries
    if mla.q_lora_rank is None:
        q = _linear(hidden, p_attn["q_proj"], aq, ac)
    else:
        qa = _linear(hidden, p_attn["q_a"], aq, ac)
        qa = rms_norm(qa, p_attn["q_a_norm"], arch.rms_norm_eps)
        q = _linear(qa, p_attn["q_b"], aq, ac)
    q = q.reshape(B, S, H, mla.qk_head_dim)
    q_nope, q_rot = q[..., :nope], q[..., nope:]

    # -- compressed kv + shared rope key
    ckv = _linear(hidden, p_attn["kv_a"], aq, ac)  # (B, S, r + rope_d)
    c, k_rot = ckv[..., :r], ckv[..., r:]
    c = rms_norm(c, p_attn["kv_a_norm"], arch.rms_norm_eps)  # normed BEFORE caching

    q_rot = jnp.swapaxes(q_rot, 1, 2)  # (B, H, S, rope_d)
    k_rot = k_rot[:, None]  # (B, 1, S, rope_d)
    q_rot, k_rot = apply_rotary_pos_emb(q_rot, k_rot, cos, sin)

    # -- latent cache update (k <- rotated rope key, v <- normed latent)
    # layouts expect (B, KV, S, D): rope key (B, 1, S, rope_d), latent (B, 1, S, r)
    ci = dict(cache_inputs or {})
    ci["position_ids"] = position_ids
    new_k, new_v = layout.update(k_cache_l, v_cache_l, k_rot, c[:, None], ci, cache_spec)

    if attend_to_cache:
        k_rot_all, c_all, kv_pos = layout.read(new_k, new_v, ci, cache_spec)
    else:
        k_rot_all, c_all = k_rot, c[:, None]
        kv_pos = position_ids

    # -- expand latent to per-head k_nope / value through kv_b
    W = c_all.shape[2]
    kb = _linear(c_all[:, 0], p_attn["kv_b"], aq, ac)  # (B, W, H*(nope+v))
    kb = kb.reshape(B, W, H, nope + mla.v_head_dim)
    k_nope = jnp.swapaxes(kb[..., :nope], 1, 2)  # (B, H, W, nope)
    v = jnp.swapaxes(kb[..., nope:], 1, 2)  # (B, H, W, v_dim)

    qq = jnp.concatenate([jnp.swapaxes(q_nope, 1, 2), q_rot], axis=-1)  # (B,H,S,qk)
    kk = jnp.concatenate(
        [k_nope, jnp.broadcast_to(k_rot_all, (B, H, W, rope_d))], axis=-1
    )

    mask = attn_ops.causal_mask_from_positions(position_ids, kv_pos)
    ctx = attn_ops.grouped_attention(
        qq, kk, v, mask, scale=mla.softmax_scale, softmax_dtype=jnp.float32
    )  # (B, H, S, v_dim)

    ctx = jnp.swapaxes(ctx, 1, 2).reshape(B, S, H * mla.v_head_dim)
    out = _linear(ctx, p_attn["o_proj"], aq, ac)
    return out, (new_k, new_v)


# ---------------------------------------------------------------------------
# Param layout / conversion helpers (used by the deepseek family module)
# ---------------------------------------------------------------------------

def mla_param_specs(mla: MLAArch) -> Dict[str, Any]:
    specs: Dict[str, Any] = {
        "kv_a": {"w": P()},  # small (hidden -> r + rope): replicated
        "kv_a_norm": P(),
        "kv_b": {"w": P(None, AXIS_MP)},  # heads on out dim
        "o_proj": {"w": P(AXIS_MP, None)},
    }
    if mla.q_lora_rank is None:
        specs["q_proj"] = {"w": P(None, AXIS_MP)}
    else:
        specs["q_a"] = {"w": P()}
        specs["q_a_norm"] = P()
        specs["q_b"] = {"w": P(None, AXIS_MP)}
    return specs


def mla_shape_struct(mla: MLAArch, hidden_size: int, num_layers: int, dtype) -> Dict[str, Any]:
    def s(*shape):
        return jax.ShapeDtypeStruct((num_layers,) + shape, dtype)

    H, hs = mla.num_heads, hidden_size
    struct: Dict[str, Any] = {
        "kv_a": {"w": s(hs, mla.kv_lora_rank + mla.qk_rope_head_dim)},
        "kv_a_norm": s(mla.kv_lora_rank),
        "kv_b": {"w": s(mla.kv_lora_rank, H * (mla.qk_nope_head_dim + mla.v_head_dim))},
        "o_proj": {"w": s(H * mla.v_head_dim, hs)},
    }
    if mla.q_lora_rank is None:
        struct["q_proj"] = {"w": s(hs, H * mla.qk_head_dim)}
    else:
        struct["q_a"] = {"w": s(hs, mla.q_lora_rank)}
        struct["q_a_norm"] = s(mla.q_lora_rank)
        struct["q_b"] = {"w": s(mla.q_lora_rank, H * mla.qk_head_dim)}
    return struct


def deinterleave_rope_columns(w_t: np.ndarray, head_dim: int, nope: int, rope_d: int) -> np.ndarray:
    """Permute the rope-dim output columns of a per-head projection weight
    (already transposed to (in, H*head_dim)) from interleaved [r0,i0,r1,i1,...]
    to rotate-half [r0,r1,...,i0,i1,...] layout (HF rope_interleave handling,
    done once at conversion instead of per step)."""
    fin, out = w_t.shape
    H = out // head_dim
    w = w_t.reshape(fin, H, head_dim)
    rope_part = w[..., nope:]
    perm = np.concatenate([np.arange(0, rope_d, 2), np.arange(1, rope_d, 2)])
    w = np.concatenate([w[..., :nope], rope_part[..., perm]], axis=-1)
    return w.reshape(fin, out)
