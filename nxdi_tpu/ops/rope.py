"""Rotary position embeddings.

Matches HF's llama rotation convention (rotate_half) which the reference also
uses (modules/attention/utils.py ``apply_rotary_pos_emb``). Supports plain RoPE
(rope_theta), llama3-style frequency scaling, and (later) M-RoPE for Qwen-VL.

Frequencies are computed on the fly from position ids — no precomputed
sin/cos cache parameter, which keeps the jitted graph shape-polymorphic only
over the bucketed dims and lets XLA fuse the trig into the surrounding ops.
"""

from __future__ import annotations

import math

import jax.numpy as jnp
import numpy as np


def default_inv_freq(head_dim: int, rope_theta: float) -> np.ndarray:
    return 1.0 / (rope_theta ** (np.arange(0, head_dim, 2, dtype=np.float64) / head_dim))


def llama3_scaled_inv_freq(
    head_dim: int,
    rope_theta: float,
    factor: float = 8.0,
    low_freq_factor: float = 1.0,
    high_freq_factor: float = 4.0,
    original_max_position: int = 8192,
) -> np.ndarray:
    """Llama-3.1 rope scaling (matches HF ``_compute_llama3_parameters``)."""
    inv_freq = default_inv_freq(head_dim, rope_theta)
    low_freq_wavelen = original_max_position / low_freq_factor
    high_freq_wavelen = original_max_position / high_freq_factor
    wavelen = 2 * np.pi / inv_freq
    scaled = np.where(wavelen > low_freq_wavelen, inv_freq / factor, inv_freq)
    smooth = (original_max_position / wavelen - low_freq_factor) / (
        high_freq_factor - low_freq_factor
    )
    smoothed = (1 - smooth) * scaled / factor + smooth * scaled
    is_medium = (wavelen >= high_freq_wavelen) & (wavelen <= low_freq_wavelen)
    return np.where(is_medium, smoothed, scaled)


def yarn_inv_freq(
    head_dim: int,
    rope_theta: float,
    rope_scaling: dict,
    max_position_embeddings: int = 4096,
):
    """YaRN frequency interpolation (matches HF _compute_yarn_parameters).
    Returns (inv_freq, attention_factor) — the factor scales cos/sin
    (models consume it via DecoderArch.rope_mscale)."""
    import math

    factor = rope_scaling.get("factor", 1.0)
    dim = head_dim
    orig = rope_scaling.get("original_max_position_embeddings") or max_position_embeddings
    mscale = rope_scaling.get("mscale")
    mscale_all_dim = rope_scaling.get("mscale_all_dim")

    def get_mscale(scale, m=1):
        if scale <= 1:
            return 1.0
        return 0.1 * m * math.log(scale) + 1.0

    attention_factor = rope_scaling.get("attention_factor")
    if attention_factor is None:
        if mscale and mscale_all_dim:
            attention_factor = float(get_mscale(factor, mscale) / get_mscale(factor, mscale_all_dim))
        else:
            attention_factor = get_mscale(factor)

    beta_fast = rope_scaling.get("beta_fast") or 32
    beta_slow = rope_scaling.get("beta_slow") or 1

    def correction_dim(num_rotations):
        return (dim * math.log(orig / (num_rotations * 2 * math.pi))) / (2 * math.log(rope_theta))

    low = correction_dim(beta_fast)
    high = correction_dim(beta_slow)
    if rope_scaling.get("truncate", True):
        low = math.floor(low)
        high = math.ceil(high)
    low, high = max(low, 0), min(high, dim - 1)
    if low == high:
        high += 0.001

    pos_freqs = rope_theta ** (np.arange(0, dim, 2, dtype=np.float64) / dim)
    extrap = 1.0 / pos_freqs
    interp = 1.0 / (factor * pos_freqs)
    ramp = np.clip((np.arange(dim // 2, dtype=np.float64) - low) / (high - low), 0, 1)
    extrap_factor = 1 - ramp
    inv_freq = interp * (1 - extrap_factor) + extrap * extrap_factor
    return inv_freq.astype(np.float32), float(attention_factor)


def inv_freq_from_hf_config(
    head_dim: int, rope_theta: float, rope_scaling=None, max_position_embeddings: int = 4096
) -> np.ndarray:
    if rope_scaling is None:
        return default_inv_freq(head_dim, rope_theta)
    rope_type = rope_scaling.get("rope_type", rope_scaling.get("type", "default"))
    if rope_type == "yarn":
        return yarn_inv_freq(head_dim, rope_theta, rope_scaling, max_position_embeddings)[0]
    if rope_type == "llama3":
        return llama3_scaled_inv_freq(
            head_dim,
            rope_theta,
            factor=rope_scaling.get("factor", 8.0),
            low_freq_factor=rope_scaling.get("low_freq_factor", 1.0),
            high_freq_factor=rope_scaling.get("high_freq_factor", 4.0),
            original_max_position=rope_scaling.get("original_max_position_embeddings", 8192),
        )
    if rope_type in ("linear",):
        return default_inv_freq(head_dim, rope_theta) / rope_scaling.get("factor", 1.0)
    if rope_type == "default":
        return default_inv_freq(head_dim, rope_theta)
    if rope_type == "dynamic":
        # dynamic NTK equals default frequencies within the original context
        # window; beyond it the runtime would need to rescale — warn loudly.
        import warnings

        warnings.warn(
            "rope_type 'dynamic' treated as default frequencies; positions "
            "beyond original_max_position_embeddings will rotate incorrectly"
        )
        return default_inv_freq(head_dim, rope_theta)
    # yarn etc.: failing loudly beats silently wrong long-context rotations
    raise ValueError(f"Unsupported rope scaling type: {rope_type}")


def longrope_inv_freq(
    head_dim: int,
    rope_theta: float,
    rope_scaling: dict,
    max_position_embeddings: int,
    original_max_position_embeddings: int,
):
    """LongRoPE (phi3 128k lineage) frequencies + attention factor.

    Matches HF ``_compute_longrope_parameters``: per-channel rescale factors
    (``short_factor`` within the pretrained window, ``long_factor`` beyond it)
    and a cos/sin scale ``sqrt(1 + ln(factor)/ln(orig_max))`` where factor =
    max_position/original_max. Returns a STACKED (2, D/2) array
    [short, long]; the regime is selected in-graph per forward from
    ``max(position_ids)+1 > original_max`` (models/base.py), mirroring HF's
    dynamic frequency update."""
    short = np.asarray(rope_scaling["short_factor"], np.float32)
    long = np.asarray(rope_scaling["long_factor"], np.float32)
    exponents = np.arange(0, head_dim, 2, dtype=np.float32) / head_dim
    base = rope_theta ** exponents
    factor = rope_scaling.get("factor")
    if original_max_position_embeddings:
        factor = max_position_embeddings / original_max_position_embeddings
    attention_factor = rope_scaling.get("attention_factor")
    if attention_factor is None:
        if factor is None or factor <= 1.0:
            attention_factor = 1.0
        else:
            attention_factor = math.sqrt(
                1 + math.log(factor) / math.log(original_max_position_embeddings)
            )
    return (
        np.stack([1.0 / (short * base), 1.0 / (long * base)]),
        float(attention_factor),
    )


def rope_cos_sin(position_ids, inv_freq, dtype=jnp.float32):
    """(B, S) int positions -> cos/sin of shape (B, S, head_dim)."""
    inv_freq = jnp.asarray(inv_freq, dtype=jnp.float32)
    freqs = position_ids.astype(jnp.float32)[..., None] * inv_freq[None, None, :]
    emb = jnp.concatenate([freqs, freqs], axis=-1)
    return jnp.cos(emb).astype(dtype), jnp.sin(emb).astype(dtype)


def rotate_half(x):
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate([-x2, x1], axis=-1)


def apply_rotary_pos_emb(q, k, cos, sin):
    """q/k: (B, heads, S, head_dim); cos/sin: (B, S, head_dim).

    Computed in fp32 and cast back — bf16 rotation loses position precision at
    long context (same reason the reference keeps rope in fp32).
    """
    cos = cos[:, None, :, :].astype(jnp.float32)
    sin = sin[:, None, :, :].astype(jnp.float32)
    qf, kf = q.astype(jnp.float32), k.astype(jnp.float32)
    q_out = qf * cos + rotate_half(qf) * sin
    k_out = kf * cos + rotate_half(kf) * sin
    return q_out.astype(q.dtype), k_out.astype(k.dtype)


def apply_rotary_pos_emb_interleaved(q, k, cos, sin):
    """GPT-J/llama4-style rope: channels form ADJACENT (real, imag) pairs
    (HF llama4 apply_rotary_emb via complex view) instead of rotate-half.
    q/k: (B, heads, S, head_dim); cos/sin: (B, S, head_dim) — only the first
    head_dim/2 entries (one per pair) are read."""
    D = q.shape[-1]
    cos = cos[:, None, :, : D // 2].astype(jnp.float32)
    sin = sin[:, None, :, : D // 2].astype(jnp.float32)

    def rot(x):
        xf = x.astype(jnp.float32)
        x1, x2 = xf[..., ::2], xf[..., 1::2]
        o1 = x1 * cos - x2 * sin
        o2 = x2 * cos + x1 * sin
        return jnp.stack([o1, o2], axis=-1).reshape(x.shape).astype(x.dtype)

    return rot(q), rot(k)


def l2_norm(x, eps: float = 1e-6):
    """Unweighted RMS/L2 normalization (llama4 qk norm, Llama4TextL2Norm)."""
    import jax

    xf = x.astype(jnp.float32)
    y = xf * jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    return y.astype(x.dtype)


def mrope_cos_sin(
    mrope_position_ids, inv_freq, mrope_section, dtype=jnp.float32,
    interleaved: bool = False,
):
    """Qwen2-VL multimodal rope: (B, 3, S) [temporal, height, width] position
    streams -> cos/sin (B, S, head_dim), the head_dim/2 frequency channels
    partitioned into ``mrope_section`` chunks that each read their stream
    (HF apply_multimodal_rotary_pos_emb; reference: models/qwen2_vl/ M-RoPE).
    Text tokens carry identical positions in all three streams, which reduces
    exactly to standard 1-D rope."""
    inv_freq = jnp.asarray(inv_freq, dtype=jnp.float32)  # (D/2,)
    pos = mrope_position_ids.astype(jnp.float32)  # (B, 3, S)
    freqs = pos[..., None] * inv_freq[None, None, None, :]  # (B, 3, S, D/2)
    if interleaved:
        # qwen3-vl interleaved layout [T H W T H W ... T T]: start from the
        # temporal stream and overwrite every 3rd channel with H / W
        # (HF Qwen3VLTextRotaryEmbedding.apply_interleaved_mrope)
        half_dim = freqs.shape[-1]
        ch = jnp.arange(half_dim)
        sel_h = (ch % 3 == 1) & (ch < 3 * mrope_section[1])
        sel_w = (ch % 3 == 2) & (ch < 3 * mrope_section[2])
        half = jnp.where(sel_h, freqs[:, 1], jnp.where(sel_w, freqs[:, 2], freqs[:, 0]))
    else:
        parts = []
        off = 0
        for i, sec in enumerate(mrope_section):
            parts.append(freqs[:, i % 3, :, off:off + sec])
            off += sec
        half = jnp.concatenate(parts, axis=-1)  # (B, S, D/2)
    emb = jnp.concatenate([half, half], axis=-1)
    return jnp.cos(emb).astype(dtype), jnp.sin(emb).astype(dtype)
