"""Rotary position embeddings.

Matches HF's llama rotation convention (rotate_half) which the reference also
uses (modules/attention/utils.py ``apply_rotary_pos_emb``). Supports plain RoPE
(rope_theta), llama3-style frequency scaling, and (later) M-RoPE for Qwen-VL.

Frequencies are computed on the fly from position ids — no precomputed
sin/cos cache parameter, which keeps the jitted graph shape-polymorphic only
over the bucketed dims and lets XLA fuse the trig into the surrounding ops.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np


def default_inv_freq(head_dim: int, rope_theta: float) -> np.ndarray:
    return 1.0 / (rope_theta ** (np.arange(0, head_dim, 2, dtype=np.float64) / head_dim))


def llama3_scaled_inv_freq(
    head_dim: int,
    rope_theta: float,
    factor: float = 8.0,
    low_freq_factor: float = 1.0,
    high_freq_factor: float = 4.0,
    original_max_position: int = 8192,
) -> np.ndarray:
    """Llama-3.1 rope scaling (matches HF ``_compute_llama3_parameters``)."""
    inv_freq = default_inv_freq(head_dim, rope_theta)
    low_freq_wavelen = original_max_position / low_freq_factor
    high_freq_wavelen = original_max_position / high_freq_factor
    wavelen = 2 * np.pi / inv_freq
    scaled = np.where(wavelen > low_freq_wavelen, inv_freq / factor, inv_freq)
    smooth = (original_max_position / wavelen - low_freq_factor) / (
        high_freq_factor - low_freq_factor
    )
    smoothed = (1 - smooth) * scaled / factor + smooth * scaled
    is_medium = (wavelen >= high_freq_wavelen) & (wavelen <= low_freq_wavelen)
    return np.where(is_medium, smoothed, scaled)


def inv_freq_from_hf_config(head_dim: int, rope_theta: float, rope_scaling=None) -> np.ndarray:
    if rope_scaling is None:
        return default_inv_freq(head_dim, rope_theta)
    rope_type = rope_scaling.get("rope_type", rope_scaling.get("type", "default"))
    if rope_type == "llama3":
        return llama3_scaled_inv_freq(
            head_dim,
            rope_theta,
            factor=rope_scaling.get("factor", 8.0),
            low_freq_factor=rope_scaling.get("low_freq_factor", 1.0),
            high_freq_factor=rope_scaling.get("high_freq_factor", 4.0),
            original_max_position=rope_scaling.get("original_max_position_embeddings", 8192),
        )
    if rope_type in ("linear",):
        return default_inv_freq(head_dim, rope_theta) / rope_scaling.get("factor", 1.0)
    if rope_type == "default":
        return default_inv_freq(head_dim, rope_theta)
    if rope_type == "dynamic":
        # dynamic NTK equals default frequencies within the original context
        # window; beyond it the runtime would need to rescale — warn loudly.
        import warnings

        warnings.warn(
            "rope_type 'dynamic' treated as default frequencies; positions "
            "beyond original_max_position_embeddings will rotate incorrectly"
        )
        return default_inv_freq(head_dim, rope_theta)
    # yarn etc.: failing loudly beats silently wrong long-context rotations
    raise ValueError(f"Unsupported rope scaling type: {rope_type}")


def rope_cos_sin(position_ids, inv_freq, dtype=jnp.float32):
    """(B, S) int positions -> cos/sin of shape (B, S, head_dim)."""
    inv_freq = jnp.asarray(inv_freq, dtype=jnp.float32)
    freqs = position_ids.astype(jnp.float32)[..., None] * inv_freq[None, None, :]
    emb = jnp.concatenate([freqs, freqs], axis=-1)
    return jnp.cos(emb).astype(dtype), jnp.sin(emb).astype(dtype)


def rotate_half(x):
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate([-x2, x1], axis=-1)


def apply_rotary_pos_emb(q, k, cos, sin):
    """q/k: (B, heads, S, head_dim); cos/sin: (B, S, head_dim).

    Computed in fp32 and cast back — bf16 rotation loses position precision at
    long context (same reason the reference keeps rope in fp32).
    """
    cos = cos[:, None, :, :].astype(jnp.float32)
    sin = sin[:, None, :, :].astype(jnp.float32)
    qf, kf = q.astype(jnp.float32), k.astype(jnp.float32)
    q_out = qf * cos + rotate_half(qf) * sin
    k_out = kf * cos + rotate_half(kf) * sin
    return q_out.astype(q.dtype), k_out.astype(k.dtype)
