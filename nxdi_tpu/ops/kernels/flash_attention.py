"""Pallas flash-attention kernels (TPU).

The TPU-native replacements for the reference's NKI attention kernels
(SURVEY §2.9: external ``attention_isa_kernel`` CTE flash,
``attention_tkg_fwd_isa_kernel`` decode, in-repo sliding-window flash
``modules/sliding_window/attention.py:234``). Same role as there: an
*optimization* behind a flag (``attn_kernel_enabled``), never a semantic
change — ops/attention.py stays the always-available XLA fallback with
identical mask semantics.

Design notes (vs the reference's 128-partition NKI tiling):
  - grid = (batch*q_heads, S_q/block_q, S_kv/block_k); the kv dim is the
    innermost (sequential) axis so the online-softmax running state (m, l,
    acc) lives in VMEM scratch across kv steps — the classic flash recipe
    tiled for the 128x128 MXU.
  - positions are AFFINE per row (start + arange) everywhere this framework
    calls attention — prefill arange, decode scalar, speculation windows,
    chunk prefill — so the kernels take per-row scalar STARTS via scalar
    prefetch (SMEM) and rebuild position tiles with 2-D iota in-kernel.
    Mosaic gets no awkward 1-row vector loads, and causal / sliding-window /
    chunked masks still match the XLA path bit-for-bit.
  - causal block skip: a kv block entirely in the future contributes nothing
    and is skipped under ``pl.when`` (the reference's strided-CP kernel
    solves the same wasted-work problem differently).
  - GQA without repeat_kv: q head h reads kv head h // (H/KV) via the
    BlockSpec index map — no materialized head replication in HBM.

On non-TPU backends the kernels run in interpreter mode (tests compare them
against the XLA path on CPU); on TPU they compile with Mosaic.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -30000.0


def _interpret() -> bool:
    return jax.devices()[0].platform != "tpu"


# single source of truth for the prefill block defaults (cte_probe and the
# A/B harness report these; keep env names in sync)
DEFAULT_PREFILL_BLOCK_Q = 512
DEFAULT_PREFILL_BLOCK_K = 1024


def _pick_block(s: int, target: int) -> int:
    b = min(target, s)
    while s % b:
        b //= 2
    return max(b, 1)


def prefill_kernel_supported(q_shape, k_shape) -> bool:
    B, H, Sq, D = q_shape
    KV, Sk = k_shape[1], k_shape[2]
    if H % KV:
        return False
    if _interpret():
        return True
    # Mosaic pads the lane (head_dim) axis internally — D=64/96 (llama 1B/3B,
    # qwen2, phi) verified bit-compatible on v5e hardware; only the sequence
    # blocks must divide the sublane/lane tiling.
    return D % 8 == 0 and Sq % 8 == 0 and Sk % 128 == 0


def decode_kernel_supported(q_shape, k_shape) -> bool:
    B, H, Sq, D = q_shape
    KV, Sk = k_shape[1], k_shape[2]
    if H % KV or Sq != 1:
        return False
    if _interpret():
        return True
    return D % 8 == 0 and Sk % 128 == 0


# ---------------------------------------------------------------------------
# Shared mask math (2-D position tiles from scalar starts)
# ---------------------------------------------------------------------------


def _mask_tile(q_start, kv_start, qi, ki, bq, bk, sliding_window, chunk_size):
    """(bq, bk) bool mask; q row r is position q_start + qi*bq + r, kv col c
    is position kv_start + ki*bk + c."""
    q_pos = q_start + qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    kv_pos = kv_start + ki * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    m = kv_pos <= q_pos
    if sliding_window is not None:
        m &= kv_pos > q_pos - sliding_window
    if chunk_size is not None:
        m &= (kv_pos // chunk_size) == (q_pos // chunk_size)
    return m


def _online_softmax_step(s, mask, m_ref, l_ref, acc_ref, v, sl=slice(None)):
    """One flash block update of the (m, l, acc) running state; ``sl`` selects
    the scratch rows (the paged kernels keep per-kv-head slices in one
    scratch buffer)."""
    s = jnp.where(mask, s, NEG_INF)
    m_prev = m_ref[sl, 0]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
    corr = jnp.exp(m_prev - m_new)
    p = jnp.where(mask, jnp.exp(s - m_new[:, None]), 0.0)
    l_ref[sl, 0] = l_ref[sl, 0] * corr + jnp.sum(p, axis=-1)
    m_ref[sl, 0] = m_new
    # probabilities ride the MXU in the inputs' dtype; accumulate in f32
    acc_ref[sl, :] = acc_ref[sl, :] * corr[:, None] + jnp.dot(
        p.astype(v.dtype), v, preferred_element_type=jnp.float32
    )


# ---------------------------------------------------------------------------
# Prefill (context encoding) kernel
# ---------------------------------------------------------------------------


def _prefill_kernel(
    qs_ref, ks_ref, q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref,
    *, scale, sliding_window, chunk_size, n_kv_blocks, H, block_q, block_k,
):
    qi, ki = pl.program_id(1), pl.program_id(2)
    b = pl.program_id(0) // H
    q_start = qs_ref[b]
    kv_start = ks_ref[b]

    @pl.when(ki == 0)
    def _():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # causal skip: kv block entirely in the future of the q block
    @pl.when(kv_start + ki * block_k <= q_start + qi * block_q + block_q - 1)
    def _():
        q = q_ref[0]  # (block_q, D) — native dtype feeds the MXU
        k = k_ref[0]
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        mask = _mask_tile(
            q_start, kv_start, qi, ki, block_q, block_k, sliding_window, chunk_size
        )
        _online_softmax_step(s, mask, m_ref, l_ref, acc_ref, v)

    @pl.when(ki == n_kv_blocks - 1)
    def _():
        l = jnp.maximum(l_ref[:, 0], 1e-20)
        o_ref[0] = (acc_ref[:] / l[:, None]).astype(o_ref.dtype)


def flash_attention_prefill(
    q,  # (B, H, Sq, D)
    k,  # (B, KV, Sk, D)
    v,  # (B, KV, Sk, D)
    q_pos,  # (B, Sq) int32 — affine per row (start + arange)
    kv_pos,  # (B, Sk) int32 — affine per row
    *,
    scale: Optional[float] = None,
    sliding_window: Optional[int] = None,
    chunk_size: Optional[int] = None,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
):
    """512x1024 default blocks: at 128x128 the (B*H, Sq/bq, Sk/bk) grid hits
    ~65k steps/layer at prefill shapes and per-step overhead dominated the
    kernel (xprof: 30 ms/layer vs ~11 ms of FLOPs; 512x512 measured ~3x
    faster end to end on v5e). The round-5 sweep (scripts/kernel_ab.py --cte,
    KERNEL_AB.json) widened K: 512x1024 measured 683 vs 770 ms at the bench
    prefill (bs32 x 1024) — fewer KV-stream restarts per Q block; 256x256
    and 1024x512 both lose. NXDI_TPU_PREFILL_BLOCK_Q/_K override for
    on-chip retuning."""
    import os

    if block_q is None:
        block_q = int(
            os.environ.get("NXDI_TPU_PREFILL_BLOCK_Q", DEFAULT_PREFILL_BLOCK_Q)
        )
    if block_k is None:
        block_k = int(
            os.environ.get("NXDI_TPU_PREFILL_BLOCK_K", DEFAULT_PREFILL_BLOCK_K)
        )
    B, H, Sq, D = q.shape
    KV, Sk = k.shape[1], k.shape[2]
    G = H // KV
    scale = D ** -0.5 if scale is None else scale
    block_q = _pick_block(Sq, block_q)
    block_k = _pick_block(Sk, block_k)
    n_kv_blocks = Sk // block_k

    qf = q.reshape(B * H, Sq, D)
    kf = k.reshape(B * KV, Sk, D)
    vf = v.reshape(B * KV, Sk, D)
    q_start = q_pos[:, 0].astype(jnp.int32)
    kv_start = kv_pos[:, 0].astype(jnp.int32)

    kernel = functools.partial(
        _prefill_kernel,
        scale=scale,
        sliding_window=sliding_window,
        chunk_size=chunk_size,
        n_kv_blocks=n_kv_blocks,
        H=H,
        block_q=block_q,
        block_k=block_k,
    )

    def kv_index(bh, qi, ki, *prefetch):
        return (bh // H) * KV + (bh % H) // G, ki, 0

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B * H, Sq // block_q, n_kv_blocks),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda bh, qi, ki, *_: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, D), kv_index),
            pl.BlockSpec((1, block_k, D), kv_index),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda bh, qi, ki, *_: (bh, qi, 0)),
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),  # running max
            pltpu.VMEM((block_q, 1), jnp.float32),  # running denom
            pltpu.VMEM((block_q, D), jnp.float32),  # weighted-V accumulator
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B * H, Sq, D), q.dtype),
        interpret=_interpret(),
    )(q_start, kv_start, qf, kf, vf)
    return out.reshape(B, H, Sq, D)


# ---------------------------------------------------------------------------
# Decode (token generation) kernel — q_len == 1, KV long
# ---------------------------------------------------------------------------


def _decode_kernel(
    qs_ref, ks_ref, q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref,
    *, scale, sliding_window, chunk_size, n_kv_blocks, KV, block_k,
):
    ki = pl.program_id(1)
    b = pl.program_id(0) // KV
    q_start = qs_ref[b]  # the single decode position (same for all G rows)
    kv_start = ks_ref[b]

    @pl.when(ki == 0)
    def _():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    @pl.when(kv_start + ki * block_k <= q_start)
    def _():
        q = q_ref[0]  # (G, D) — native dtype feeds the MXU
        k = k_ref[0]
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # (G, block_k)
        G = s.shape[0]
        mask = _mask_tile(
            q_start, kv_start, 0, ki, 1, block_k, sliding_window, chunk_size
        )  # (1, block_k): all G rows decode the same position
        mask = jnp.broadcast_to(mask, (G, block_k))
        _online_softmax_step(s, mask, m_ref, l_ref, acc_ref, v)

    @pl.when(ki == n_kv_blocks - 1)
    def _():
        l = jnp.maximum(l_ref[:, 0], 1e-20)
        o_ref[0] = (acc_ref[:] / l[:, None]).astype(o_ref.dtype)


def flash_attention_decode(
    q,  # (B, H, 1, D)
    k,  # (B, KV, Sk, D)
    v,  # (B, KV, Sk, D)
    q_pos,  # (B, 1) int32
    kv_pos,  # (B, Sk) int32 — affine per row
    *,
    scale: Optional[float] = None,
    sliding_window: Optional[int] = None,
    chunk_size: Optional[int] = None,
    block_k: int = 512,
):
    """Single-position decode: grid over (batch x kv-head) with the G grouped
    query rows as the matmul M dim — one (G, D) x (D, block_k) MXU pass per
    cache block (the reference's TKG kernel role, attention_base.py:1419)."""
    B, H, Sq, D = q.shape
    assert Sq == 1, "decode kernel is single-position"
    KV, Sk = k.shape[1], k.shape[2]
    G = H // KV
    scale = D ** -0.5 if scale is None else scale
    block_k = _pick_block(Sk, block_k)
    n_kv_blocks = Sk // block_k

    qf = q.reshape(B, KV, G, D).reshape(B * KV, G, D)
    kf = k.reshape(B * KV, Sk, D)
    vf = v.reshape(B * KV, Sk, D)
    q_start = q_pos[:, 0].astype(jnp.int32)
    kv_start = kv_pos[:, 0].astype(jnp.int32)

    kernel = functools.partial(
        _decode_kernel,
        scale=scale,
        sliding_window=sliding_window,
        chunk_size=chunk_size,
        n_kv_blocks=n_kv_blocks,
        KV=KV,
        block_k=block_k,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B * KV, n_kv_blocks),
        in_specs=[
            pl.BlockSpec((1, G, D), lambda bk, ki, *_: (bk, 0, 0)),
            pl.BlockSpec((1, block_k, D), lambda bk, ki, *_: (bk, ki, 0)),
            pl.BlockSpec((1, block_k, D), lambda bk, ki, *_: (bk, ki, 0)),
        ],
        out_specs=pl.BlockSpec((1, G, D), lambda bk, ki, *_: (bk, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((G, 1), jnp.float32),
            pltpu.VMEM((G, 1), jnp.float32),
            pltpu.VMEM((G, D), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B * KV, G, D), q.dtype),
        interpret=_interpret(),
    )(q_start, kv_start, qf, kf, vf)
    return out.reshape(B, KV, G, D).reshape(B, H, 1, D).astype(q.dtype)


# ---------------------------------------------------------------------------
# Fused decode kernel — deferred-write composition (cache + fresh row)
# ---------------------------------------------------------------------------


def fused_decode_kernel_supported(q_shape, k_cache_shape) -> bool:
    """Same envelope as the plain decode kernel; the fresh row adds nothing."""
    return decode_kernel_supported(q_shape, k_cache_shape)


def _fused_decode_kernel(
    qs_ref, ks_ref, q_ref, k_ref, v_ref, kn_ref, vn_ref, o_ref, m_ref, l_ref, acc_ref,
    *, scale, sliding_window, chunk_size, n_kv_blocks, KV, block_k, stacked=False,
):
    ki = pl.program_id(1)
    b = pl.program_id(0) // KV
    q_start = qs_ref[b]  # the single decode position == this step's write slot
    kv_start = ks_ref[b]

    @pl.when(ki == 0)
    def _():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    @pl.when(kv_start + ki * block_k <= q_start)
    def _():
        q = q_ref[0]  # (G, D)
        # S-minor transposed cache view (D, block_k); the stacked variant's
        # blocks carry a leading (1,) layer dim picked by scalar prefetch
        kT = k_ref[0, 0] if stacked else k_ref[0]
        vT = v_ref[0, 0] if stacked else v_ref[0]
        # VPU broadcast-multiply-reduce: with M = G (typically 4-8) an MXU
        # matmul wastes ~97% of the systolic array; the elementwise form
        # matches XLA's own near-roofline decode lowering
        s = jnp.sum(
            q.astype(jnp.float32)[:, :, None] * kT.astype(jnp.float32)[None, :, :],
            axis=1,
        ) * scale  # (G, block_k)
        G = s.shape[0]
        # STRICT causal mask over the cache: the slot AT q_start holds last
        # step's (stale) row — the fresh row below replaces it (deferred-write
        # semantics, attention_two_part's poisoned-slot mask with T == 1)
        kv_pos = kv_start + ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_k), 1
        )
        mask = kv_pos < q_start
        if sliding_window is not None:
            mask &= kv_pos > q_start - sliding_window
        if chunk_size is not None:
            mask &= (kv_pos // chunk_size) == (q_start // chunk_size)
        mask = jnp.broadcast_to(mask, (G, block_k))
        s = jnp.where(mask, s, NEG_INF)
        m_prev = m_ref[:, 0]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
        corr = jnp.exp(m_prev - m_new)
        p = jnp.where(mask, jnp.exp(s - m_new[:, None]), 0.0)
        l_ref[:, 0] = l_ref[:, 0] * corr + jnp.sum(p, axis=-1)
        m_ref[:, 0] = m_new
        acc_ref[:] = acc_ref[:] * corr[:, None] + jnp.sum(
            p[:, None, :] * vT.astype(jnp.float32)[None, :, :], axis=2
        )

    @pl.when(ki == n_kv_blocks - 1)
    def _():
        # fold in the fresh row (position q_start; always attended — its own
        # position satisfies every causal/window/chunk mask). The (G, 1) dot
        # is a VPU reduction — Mosaic rejects an MXU matmul with N == 1.
        q = q_ref[0]
        kn = kn_ref[0]  # (1, D)
        vn = vn_ref[0]
        s2 = jnp.sum(
            q.astype(jnp.float32) * kn.astype(jnp.float32), axis=-1
        ) * scale  # (G,)
        m_prev = m_ref[:, 0]
        m_new = jnp.maximum(m_prev, s2)
        corr = jnp.exp(m_prev - m_new)
        p2 = jnp.exp(s2 - m_new)
        l = l_ref[:, 0] * corr + p2
        acc = acc_ref[:] * corr[:, None] + p2[:, None] * vn.astype(jnp.float32)
        l = jnp.maximum(l, 1e-20)
        o_ref[0] = (acc / l[:, None]).astype(o_ref.dtype)


def _fused_decode_stacked_kernel(li_ref, qs_ref, ks_ref, *rest, **kw):
    del li_ref  # consumed by the cache index maps
    _fused_decode_kernel(qs_ref, ks_ref, *rest, stacked=True, **kw)


def flash_attention_decode_fused_stacked(
    q,  # (B, H, 1, D)
    k_cache_s,  # (L, B, KV, Sk, D) — FULL stacked OLD cache
    v_cache_s,
    k_new,  # (B, KV, 1, D) — this step's fresh row
    v_new,
    q_pos,  # (B, 1)
    layer_idx,  # scalar/1-elt int32 — the in-scan layer index
    *,
    scale: Optional[float] = None,
    sliding_window: Optional[int] = None,
    chunk_size: Optional[int] = None,
    block_k: int = 512,
    kv_len: Optional[int] = None,
):
    """The STACKED form of :func:`flash_attention_decode_fused`: the cache
    operand is the whole (L, B, KV, S, D) stack and the active layer is
    selected by a scalar-prefetched index — inside the decoder ``lax.scan`` a
    pallas operand on the per-layer cache slice materializes a full-cache
    copy per layer (the round-3 finding that made the per-layer kernel LOSE
    to XLA two-part, bench.py notes); indexing the stack in the BlockSpec
    reads only the touched blocks, like ops/kernels/kv_commit.py.

    Same contract as the per-layer kernel otherwise (strict-causal old-cache
    mask + fresh-row fold; contiguous layout kv positions = 0..Sk-1)."""
    B, H, Sq, D = q.shape
    assert Sq == 1, "fused decode kernel is single-position"
    L, KV, Sk = k_cache_s.shape[0], k_cache_s.shape[2], k_cache_s.shape[3]
    G = H // KV
    scale = D ** -0.5 if scale is None else scale
    attended = Sk if kv_len is None else min(kv_len, Sk)
    block_k = _pick_block(attended, block_k)
    n_kv_blocks = attended // block_k

    qf = q.reshape(B, KV, G, D).reshape(B * KV, G, D)
    # S-minor bitcast view of the stacked cache (L, B*KV, D, Sk)
    kf = jnp.swapaxes(k_cache_s, 3, 4).reshape(L, B * KV, D, Sk)
    vf = jnp.swapaxes(v_cache_s, 3, 4).reshape(L, B * KV, D, Sk)
    knf = k_new.reshape(B * KV, 1, D)
    vnf = v_new.reshape(B * KV, 1, D)
    q_start = q_pos[:, 0].astype(jnp.int32)
    kv_start = jnp.zeros((B,), jnp.int32)  # contiguous layout positions
    li = jnp.asarray(layer_idx, jnp.int32).reshape(1)

    kernel = functools.partial(
        _fused_decode_stacked_kernel,
        scale=scale,
        sliding_window=sliding_window,
        chunk_size=chunk_size,
        n_kv_blocks=n_kv_blocks,
        KV=KV,
        block_k=block_k,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(B * KV, n_kv_blocks),
        in_specs=[
            pl.BlockSpec((1, G, D), lambda bk, ki, *_: (bk, 0, 0)),
            pl.BlockSpec(
                (1, 1, D, block_k), lambda bk, ki, li_ref, *_: (li_ref[0], bk, 0, ki)
            ),
            pl.BlockSpec(
                (1, 1, D, block_k), lambda bk, ki, li_ref, *_: (li_ref[0], bk, 0, ki)
            ),
            pl.BlockSpec((1, 1, D), lambda bk, ki, *_: (bk, 0, 0)),
            pl.BlockSpec((1, 1, D), lambda bk, ki, *_: (bk, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, G, D), lambda bk, ki, *_: (bk, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((G, 1), jnp.float32),
            pltpu.VMEM((G, 1), jnp.float32),
            pltpu.VMEM((G, D), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B * KV, G, D), q.dtype),
        interpret=_interpret(),
    )(li, q_start, kv_start, qf, kf, vf, knf, vnf)
    return out.reshape(B, KV, G, D).reshape(B, H, 1, D).astype(q.dtype)


def sharded_fused_decode_stacked_call(
    policy, q, k_cache_s, v_cache_s, k_new, v_new, q_pos, layer_idx,
    *, scale=None, sliding_window=None, chunk_size=None, kv_len=None,
):
    """Stacked fused decode under GSPMD. Returns None when the KV sequence
    dim is sharded (flash decoding) — callers fall back."""
    from jax.sharding import PartitionSpec as P

    fn = functools.partial(
        flash_attention_decode_fused_stacked,
        scale=scale,
        sliding_window=sliding_window,
        chunk_size=chunk_size,
        kv_len=kv_len,
    )
    mesh = jax.sharding.get_abstract_mesh()
    if mesh is None or mesh.empty:
        return fn(q, k_cache_s, v_cache_s, k_new, v_new, q_pos, layer_idx)
    kv_spec = policy.cache_kv
    if kv_spec[2] is not None:
        return None  # KV sequence sharded (flash decoding) -> XLA path
    q_spec = P(*policy.q)
    fresh_spec = P(*policy.kv)
    cache_spec = P(None, *kv_spec)
    qp_spec = P(policy.q[0], policy.q[2])
    shard_fn = jax.shard_map(
        fn,
        mesh=mesh,
        in_specs=(q_spec, cache_spec, cache_spec, fresh_spec, fresh_spec,
                  qp_spec, P()),
        out_specs=q_spec,
        check_vma=False,
    )
    return shard_fn(q, k_cache_s, v_cache_s, k_new, v_new, q_pos, layer_idx)


def flash_attention_decode_fused(
    q,  # (B, H, 1, D)
    k_cache,  # (B, KV, Sk, D) — OLD cache (this step's slot stale)
    v_cache,  # (B, KV, Sk, D)
    k_new,  # (B, KV, 1, D) — this step's fresh row
    v_new,  # (B, KV, 1, D)
    q_pos,  # (B, 1) int32 decode position == write slot
    kv_pos,  # (B, Sk) int32 — affine per row
    *,
    scale: Optional[float] = None,
    sliding_window: Optional[int] = None,
    chunk_size: Optional[int] = None,
    block_k: int = 512,
    kv_len: Optional[int] = None,
):
    """Deferred-write decode attention in ONE kernel: online-softmax over the
    old cache with a STRICT causal mask (this step's slot excluded) merged
    with the fresh K/V row — the kernel form of ops/attention.py
    ``attention_two_part`` for T == 1 (reference: the fused TKG kernels,
    attention_base.py:1419-1994). Composes with the Pallas commit kernel
    (kv_commit.py): the step never materializes an updated cache view.

    ``kv_len`` statically bounds how many cache positions are attended (the
    bucket's KV window) WITHOUT slicing the cache — the grid just stops
    early, so no windowed copy of the cache is materialized for the kernel.

    The cache operands ride the S-minor TRANSPOSED view (B*KV, D, Sk): the
    decode program's preferred cache layout is sequence-minor, so the
    swapaxes below is a layout-preserving bitcast — feeding the cache to the
    kernel untransposed costs a full relayout copy per layer (measured: the
    kernel was 3x SLOWER than the XLA path until the view matched).
    """
    B, H, Sq, D = q.shape
    assert Sq == 1, "fused decode kernel is single-position"
    KV, Sk = k_cache.shape[1], k_cache.shape[2]
    G = H // KV
    scale = D ** -0.5 if scale is None else scale
    attended = Sk if kv_len is None else min(kv_len, Sk)
    block_k = _pick_block(attended, block_k)
    n_kv_blocks = attended // block_k

    qf = q.reshape(B, KV, G, D).reshape(B * KV, G, D)
    kf = jnp.swapaxes(k_cache, 2, 3).reshape(B * KV, D, Sk)  # bitcast view
    vf = jnp.swapaxes(v_cache, 2, 3).reshape(B * KV, D, Sk)
    knf = k_new.reshape(B * KV, 1, D)
    vnf = v_new.reshape(B * KV, 1, D)
    q_start = q_pos[:, 0].astype(jnp.int32)
    kv_start = kv_pos[:, 0].astype(jnp.int32)

    kernel = functools.partial(
        _fused_decode_kernel,
        scale=scale,
        sliding_window=sliding_window,
        chunk_size=chunk_size,
        n_kv_blocks=n_kv_blocks,
        KV=KV,
        block_k=block_k,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B * KV, n_kv_blocks),
        in_specs=[
            pl.BlockSpec((1, G, D), lambda bk, ki, *_: (bk, 0, 0)),
            pl.BlockSpec((1, D, block_k), lambda bk, ki, *_: (bk, 0, ki)),
            pl.BlockSpec((1, D, block_k), lambda bk, ki, *_: (bk, 0, ki)),
            pl.BlockSpec((1, 1, D), lambda bk, ki, *_: (bk, 0, 0)),
            pl.BlockSpec((1, 1, D), lambda bk, ki, *_: (bk, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, G, D), lambda bk, ki, *_: (bk, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((G, 1), jnp.float32),
            pltpu.VMEM((G, 1), jnp.float32),
            pltpu.VMEM((G, D), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B * KV, G, D), q.dtype),
        interpret=_interpret(),
    )(q_start, kv_start, qf, kf, vf, knf, vnf)
    return out.reshape(B, KV, G, D).reshape(B, H, 1, D).astype(q.dtype)


def sharded_fused_decode_call(
    policy, q, k_cache, v_cache, k_new, v_new, q_pos, kv_pos,
    *, scale=None, sliding_window=None, chunk_size=None, kv_len=None,
):
    """Fused deferred-write decode under GSPMD (see sharded_kernel_call).
    Returns None when the KV sequence dim is sharded (flash decoding)."""
    from jax.sharding import PartitionSpec as P

    fn = functools.partial(
        flash_attention_decode_fused,
        scale=scale,
        sliding_window=sliding_window,
        chunk_size=chunk_size,
        kv_len=kv_len,
    )
    mesh = jax.sharding.get_abstract_mesh()
    if mesh is None or mesh.empty:
        return fn(q, k_cache, v_cache, k_new, v_new, q_pos, kv_pos)
    kv_spec = policy.cache_kv
    if kv_spec[2] is not None:
        return None  # KV sequence sharded (flash decoding) -> XLA path
    q_spec = P(*policy.q)
    fresh_spec = P(*policy.kv)
    qp_spec = P(policy.q[0], policy.q[2])
    kp_spec = P(kv_spec[0], None)
    shard_fn = jax.shard_map(
        fn,
        mesh=mesh,
        in_specs=(q_spec, P(*kv_spec), P(*kv_spec), fresh_spec, fresh_spec,
                  qp_spec, kp_spec),
        out_specs=q_spec,
        check_vma=False,
    )
    return shard_fn(q, k_cache, v_cache, k_new, v_new, q_pos, kv_pos)


# ---------------------------------------------------------------------------
# Paged (block-table) decode kernel
# ---------------------------------------------------------------------------


def paged_decode_kernel_supported(q_shape, cache_shape, block_size) -> bool:
    B, H, Sq, D = q_shape
    total_slots, KV = cache_shape[0], cache_shape[1]
    if H % KV or Sq != 1 or total_slots % block_size:
        return False
    if _interpret():
        return True
    # the cache block is (block_size, KV, D): Mosaic needs the last two dims
    # (KV, D) full (they are) and the head count small enough that the
    # per-head python loop stays reasonable
    return D % 8 == 0 and block_size % 8 == 0 and KV <= 16


def _paged_decode_kernel(
    bt_ref, qp_ref, q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref,
    *, scale, v_scale, n_blocks, KV, G, block_size, compute_dtype,
):
    bi = pl.program_id(1)
    b = pl.program_id(0)
    q_pos = qp_ref[b]
    bt = bt_ref[b, bi]

    @pl.when(bi == 0)
    def _():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # skip unallocated blocks and blocks entirely past the decode position
    @pl.when((bt >= 0) & (bi * block_size <= q_pos))
    def _():
        kv_pos = bi * block_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_size), 1
        )
        base_mask = kv_pos <= q_pos
        # one cache-block read serves every kv head (the block's last two
        # dims are the FULL (KV, D) tail — Mosaic-valid for any KV)
        for kv in range(KV):
            q = q_ref[0, kv]  # (G, D)
            k = k_ref[:, kv, :].astype(compute_dtype)  # (block_size, D)
            v = v_ref[:, kv, :].astype(compute_dtype)
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
            ) * scale  # (G, block_size)
            mask = jnp.broadcast_to(base_mask, (G, block_size))
            _online_softmax_step(
                s, mask, m_ref, l_ref, acc_ref, v, sl=slice(kv * G, (kv + 1) * G)
            )

    @pl.when(bi == n_blocks - 1)
    def _():
        l = jnp.maximum(l_ref[:, 0], 1e-20)
        o_ref[0] = (
            (acc_ref[:] * v_scale / l[:, None])
            .reshape(KV, G, acc_ref.shape[-1])
            .astype(o_ref.dtype)
        )


def paged_attention_decode(
    q,  # (B, H, 1, D)
    k_cache,  # (total_slots, KV, D) — one layer's slice of the paged pool
    v_cache,  # (total_slots, KV, D)
    block_table,  # (B, NB) int32 block ids in logical token order; <0 = hole
    q_pos,  # (B, 1) int32 decode positions
    *,
    block_size: int,
    scale: Optional[float] = None,
    k_scale: float = 1.0,
    v_scale: float = 1.0,
):
    """Decode attention reading K/V **through the block table** — no
    materialized (B, KV, W, D) gather in HBM (the round-1 XLA path's
    O(table-width) traffic; reference analog: NKI block-TKG kernel,
    attention_base.py:50-162). The table rides scalar prefetch (SMEM) and the
    BlockSpec index maps address cache blocks directly; each grid step reads
    a (block_size, KV, D) block ONCE for all kv heads (full-tail blocks keep
    Mosaic's tiling constraints satisfied for any per-shard KV count).
    Prefix-cached blocks are just table entries — nothing special. fp8 scaled
    caches fold ``k_scale`` into the softmax scale and ``v_scale`` into the
    output normalization (exact, since both are per-tensor)."""
    B, H, Sq, D = q.shape
    assert Sq == 1, "paged decode kernel is single-position"
    KV = k_cache.shape[1]
    G = H // KV
    NB = block_table.shape[1]
    scale = (D ** -0.5 if scale is None else scale) * k_scale
    compute_dtype = q.dtype

    qf = q.reshape(B, KV, G, D)
    bt = block_table.astype(jnp.int32)
    qp = q_pos[:, 0].astype(jnp.int32)

    kernel = functools.partial(
        _paged_decode_kernel,
        scale=scale,
        v_scale=v_scale,
        n_blocks=NB,
        KV=KV,
        G=G,
        block_size=block_size,
        compute_dtype=compute_dtype,
    )

    def cache_index(b, bi, bt_ref, qp_ref):
        # unallocated/future blocks clamp to block 0 — the kernel masks them out
        return jnp.maximum(bt_ref[b, bi], 0), 0, 0

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, NB),
        in_specs=[
            pl.BlockSpec((1, KV, G, D), lambda b, bi, *_: (b, 0, 0, 0)),
            pl.BlockSpec((block_size, KV, D), cache_index),
            pl.BlockSpec((block_size, KV, D), cache_index),
        ],
        out_specs=pl.BlockSpec((1, KV, G, D), lambda b, bi, *_: (b, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((KV * G, 1), jnp.float32),
            pltpu.VMEM((KV * G, 1), jnp.float32),
            pltpu.VMEM((KV * G, D), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, KV, G, D), q.dtype),
        interpret=_interpret(),
    )(bt, qp, qf, k_cache, v_cache)
    return out.reshape(B, H, 1, D)


def paged_prefill_kernel_supported(q_shape, cache_shape, block_size) -> bool:
    B, H, Sq, D = q_shape
    total_slots, KV = cache_shape[0], cache_shape[1]
    G = H // KV if H % KV == 0 else 0
    if not G or total_slots % block_size:
        return False
    if _interpret():
        return True
    return D % 8 == 0 and block_size % 128 == 0 and Sq % 8 == 0 and KV <= 16


def _paged_prefill_kernel(
    bt_ref, qs_ref, q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref,
    *, scale, v_scale, n_blocks, KV, G, block_q, block_size, compute_dtype,
):
    qi, bi = pl.program_id(1), pl.program_id(2)
    b = pl.program_id(0)
    q_start = qs_ref[b]
    bt = bt_ref[b, bi]

    @pl.when(bi == 0)
    def _():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # skip unallocated blocks and blocks entirely past this q tile
    @pl.when((bt >= 0) & (bi * block_size <= q_start + qi * block_q + block_q - 1))
    def _():
        # row r is query position q_start + qi*block_q + r; kv col c is
        # LOGICAL position bi*block_size + c (table order); one cache block
        # read serves every kv head (full (KV, D) block tail)
        q_pos = (
            q_start
            + qi * block_q
            + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_size), 0)
        )
        kv_pos = bi * block_size + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_size), 1
        )
        base_mask = kv_pos <= q_pos
        for kv in range(KV):
            q = q_ref[0, kv].reshape(G * block_q, q_ref.shape[-1])
            k = k_ref[:, kv, :].astype(compute_dtype)  # (block_size, D)
            v = v_ref[:, kv, :].astype(compute_dtype)
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
            ) * scale  # (G*bq, block_size)
            mask = jnp.broadcast_to(
                base_mask[None], (G, block_q, block_size)
            ).reshape(G * block_q, block_size)
            _online_softmax_step(
                s, mask, m_ref, l_ref, acc_ref, v,
                sl=slice(kv * G * block_q, (kv + 1) * G * block_q),
            )

    @pl.when(bi == n_blocks - 1)
    def _():
        l = jnp.maximum(l_ref[:, 0], 1e-20)
        o_ref[0] = (
            (acc_ref[:] * v_scale / l[:, None])
            .reshape(KV, G, block_q, acc_ref.shape[-1])
            .astype(o_ref.dtype)
        )


def paged_attention_prefill(
    q,  # (B, H, Sq, D) — the active chunk/suffix queries
    k_cache,  # (total_slots, KV, D) — paged pool, chunk already written
    v_cache,  # (total_slots, KV, D)
    block_table,  # (B, NB) int32 block ids in logical token order; <0 = hole
    q_pos,  # (B, Sq) int32 — affine per row (chunk start + arange)
    *,
    block_size: int,
    scale: Optional[float] = None,
    k_scale: float = 1.0,
    v_scale: float = 1.0,
    block_q: int = 256,
):
    """Prefix-cache / chunked-prefill CTE attention reading K/V **through the
    block table** — the multi-token-q extension of ``paged_attention_decode``
    (reference: the NKI block-CTE kernels, attention_base.py:50-162,909,1083).
    HBM traffic is one pass over the LIVE blocks per kv head instead of the
    XLA path's materialized (B, KV, NB*block_size, D) gather; prefix-cached
    blocks are just table entries. The chunk's own K/V must already be
    scattered into the pool (BlockKVLayout.update runs first), so new tokens
    attend earlier tokens of the same chunk through the table like the
    reference's contexted prefill."""
    B, H, Sq, D = q.shape
    KV = k_cache.shape[1]
    G = H // KV
    NB = block_table.shape[1]
    scale = (D ** -0.5 if scale is None else scale) * k_scale
    compute_dtype = q.dtype
    # bound the softmax state (KV*G*bq rows of f32 scratch) against VMEM
    block_q = _pick_block(Sq, max(8, min(block_q, 4096 // max(H, 1))))

    qf = q.reshape(B, KV, G, Sq, D)
    bt = block_table.astype(jnp.int32)
    qs = q_pos[:, 0].astype(jnp.int32)

    kernel = functools.partial(
        _paged_prefill_kernel,
        scale=scale,
        v_scale=v_scale,
        n_blocks=NB,
        KV=KV,
        G=G,
        block_q=block_q,
        block_size=block_size,
        compute_dtype=compute_dtype,
    )

    def cache_index(b, qi, bi, bt_ref, qs_ref):
        return jnp.maximum(bt_ref[b, bi], 0), 0, 0

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, Sq // block_q, NB),
        in_specs=[
            pl.BlockSpec(
                (1, KV, G, block_q, D), lambda b, qi, bi, *_: (b, 0, 0, qi, 0)
            ),
            pl.BlockSpec((block_size, KV, D), cache_index),
            pl.BlockSpec((block_size, KV, D), cache_index),
        ],
        out_specs=pl.BlockSpec(
            (1, KV, G, block_q, D), lambda b, qi, bi, *_: (b, 0, 0, qi, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((KV * G * block_q, 1), jnp.float32),
            pltpu.VMEM((KV * G * block_q, 1), jnp.float32),
            pltpu.VMEM((KV * G * block_q, D), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, KV, G, Sq, D), q.dtype),
        interpret=_interpret(),
    )(bt, qs, qf, k_cache, v_cache)
    return out.reshape(B, H, Sq, D)


def sharded_paged_prefill_call(
    policy, q, k_cache, v_cache, block_table, q_pos,
    *, block_size, scale=None, k_scale=1.0, v_scale=1.0,
):
    """Paged prefill under GSPMD (see sharded_paged_decode_call): cache and q
    shard over kv heads on tp; table and positions are replicated."""
    from jax.sharding import PartitionSpec as P

    fn = functools.partial(
        paged_attention_prefill,
        block_size=block_size,
        scale=scale,
        k_scale=k_scale,
        v_scale=v_scale,
    )
    mesh = jax.sharding.get_abstract_mesh()
    if mesh is None or mesh.empty:
        return fn(q, k_cache, v_cache, block_table, q_pos)
    if policy.q[0] is not None or policy.q[2] is not None:
        return None  # batch/seq-sharded prefill (DP/CP) -> XLA path
    shard_fn = jax.shard_map(
        fn,
        mesh=mesh,
        in_specs=(
            P(*policy.q),
            P(None, policy.q[1], None),
            P(None, policy.q[1], None),
            P(None, None),
            P(None, None),
        ),
        out_specs=P(*policy.q),
        check_vma=False,
    )
    return shard_fn(q, k_cache, v_cache, block_table, q_pos)


def sharded_paged_decode_call(
    policy, q, k_cache, v_cache, block_table, q_pos,
    *, block_size, scale=None, k_scale=1.0, v_scale=1.0,
):
    """Paged decode under GSPMD: cache + q shard over kv-heads on tp, the
    block table and positions are replicated host metadata. Returns None when
    the mesh layout shards anything the kernel can't see locally."""
    from jax.sharding import PartitionSpec as P

    fn = functools.partial(
        paged_attention_decode,
        block_size=block_size,
        scale=scale,
        k_scale=k_scale,
        v_scale=v_scale,
    )
    mesh = jax.sharding.get_abstract_mesh()
    if mesh is None or mesh.empty:
        return fn(q, k_cache, v_cache, block_table, q_pos)
    # block pool layer slice is (slots, KV, D) sharded on heads only
    if policy.q[0] is not None or policy.q[2] is not None:
        return None  # batch/seq-sharded decode (DP/flash-decode) -> XLA path
    shard_fn = jax.shard_map(
        fn,
        mesh=mesh,
        in_specs=(
            P(*policy.q),
            P(None, policy.q[1], None),
            P(None, policy.q[1], None),
            P(None, None),
            P(None, None),
        ),
        out_specs=P(*policy.q),
        check_vma=False,
    )
    return shard_fn(q, k_cache, v_cache, block_table, q_pos)


# ---------------------------------------------------------------------------
# Sharded dispatch — kernels under GSPMD
# ---------------------------------------------------------------------------


def sharded_kernel_call(
    policy,
    q, k, v, q_pos, kv_pos,
    *,
    decode: bool,
    scale=None,
    sliding_window=None,
    chunk_size=None,
):
    """Run the flash kernel per mesh shard via ``shard_map`` (GSPMD cannot
    partition a pallas_call by itself). Head/batch shardings follow the
    submodel's :class:`ShardingPolicy`; attention is head-local so no in-shard
    collectives are needed. CP's q-sequence sharding is fine — GSPMD shards
    are contiguous slices, so per-shard positions stay affine and each shard's
    start is its own ``row[0]``. Returns None only when the policy shards the
    KV sequence dim (flash decoding needs a cross-shard softmax) — the caller
    falls back to ops/attention.py."""
    from jax.sharding import PartitionSpec as P

    fn = functools.partial(
        flash_attention_decode if decode else flash_attention_prefill,
        scale=scale,
        sliding_window=sliding_window,
        chunk_size=chunk_size,
    )
    mesh = jax.sharding.get_abstract_mesh()
    if mesh is None or mesh.empty:
        return fn(q, k, v, q_pos, kv_pos)

    kv_spec = policy.cache_kv if decode else policy.kv
    if kv_spec[2] is not None:
        return None  # KV sequence sharded (flash decoding) -> XLA path
    q_spec = P(*policy.q)
    qp_spec = P(policy.q[0], policy.q[2])  # (B, Sq) follows q's batch/seq axes
    kp_spec = P(kv_spec[0], None)
    shard_fn = jax.shard_map(
        fn,
        mesh=mesh,
        in_specs=(q_spec, P(*kv_spec), P(*kv_spec), qp_spec, kp_spec),
        out_specs=q_spec,
        check_vma=False,
    )
    return shard_fn(q, k, v, q_pos, kv_pos)
