from nxdi_tpu.ops.kernels.flash_attention import (
    decode_kernel_supported,
    flash_attention_decode,
    flash_attention_prefill,
    prefill_kernel_supported,
    sharded_kernel_call,
)

__all__ = [
    "decode_kernel_supported",
    "flash_attention_decode",
    "flash_attention_prefill",
    "prefill_kernel_supported",
    "sharded_kernel_call",
]
