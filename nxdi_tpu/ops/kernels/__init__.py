from nxdi_tpu.ops.kernels.flash_attention import (
    decode_kernel_supported,
    flash_attention_decode,
    flash_attention_prefill,
    paged_attention_decode,
    paged_decode_kernel_supported,
    prefill_kernel_supported,
    sharded_kernel_call,
    sharded_paged_decode_call,
)

__all__ = [
    "decode_kernel_supported",
    "flash_attention_decode",
    "flash_attention_prefill",
    "paged_attention_decode",
    "paged_decode_kernel_supported",
    "prefill_kernel_supported",
    "sharded_kernel_call",
    "sharded_paged_decode_call",
]
