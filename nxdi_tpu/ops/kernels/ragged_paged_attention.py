"""Ragged paged-attention Pallas kernel (TPU).

ONE attention launch for a MIXED batch: prefill chunks (query_len = chunk)
and decode steps (query_len = 1) packed into a single flat token stream,
each token tagged with its (row, position) and every row reading K/V
through its slice of the paged block table — the "Ragged Paged Attention"
kernel shape (PAPERS.md) that lets the serving engine issue one dispatch
per step instead of separate CTE + TKG programs.

Relationship to the per-row kernels (flash_attention.py):
  - same cache addressing: the block table rides scalar prefetch and the
    BlockSpec index maps pull (block_size, KV, D) pool blocks directly —
    no materialized (R, KV, W, D) gather in HBM.
  - same softmax state machine: `_online_softmax_step` is shared, and a
    fully-masked block update is an exact no-op on the running (m, l, acc)
    state (s == NEG_INF everywhere -> m_new == m_prev, corr == 1, p == 0).
    A packed token therefore sees EXACTLY the per-row kernel's update
    sequence — its own row's blocks in ascending order with identical
    operands — so the ragged output is bit-for-bit the per-row paged
    prefill/decode output for every real token (tests/unit/
    test_ragged_paged_attention.py pins this).
  - grid = (T/block_q, R*NB) with the row-x-block axis innermost
    (sequential) so the (m, l, acc) scratch persists across the whole
    row sweep for each q tile; a (row, block) step that cannot touch the
    tile (row outside the tile's [min, max] row range, or an unallocated
    table hole) is skipped under `pl.when`.

Padding tokens carry row_id == -1: no (row, block) step matches them, so
they finalize as zeros (l clamps to 1e-20) and the model-side gather never
reads them.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from nxdi_tpu.ops.kernels.flash_attention import (
    NEG_INF,
    _interpret,
    _online_softmax_step,
    _pick_block,
)


def ragged_paged_kernel_supported(q_shape, cache_shape, block_size) -> bool:
    """Same Mosaic envelope as the per-row paged prefill kernel, plus the
    packed layout's B == 1 (the batch dim is folded into the token stream)."""
    B, H, T, D = q_shape
    total_slots, KV = cache_shape[0], cache_shape[1]
    if B != 1 or H % KV or total_slots % block_size:
        return False
    if _interpret():
        return True
    return D % 8 == 0 and block_size % 128 == 0 and T % 8 == 0 and KV <= 16


def _ragged_kernel(
    bt_ref, tmin_ref, tmax_ref, rid_ref, qp_ref, q_ref, k_ref, v_ref, o_ref,
    m_ref, l_ref, acc_ref,
    *, scale, v_scale, n_rows, n_blocks, KV, G, block_q, block_size,
    compute_dtype,
):
    qi, j = pl.program_id(0), pl.program_id(1)
    rj = j // n_blocks  # the row this step serves
    bj = j % n_blocks  # the row's logical cache block
    bt = bt_ref[rj, bj]

    @pl.when(j == 0)
    def _():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # skip table holes and rows entirely outside this q tile
    @pl.when((bt >= 0) & (rj >= tmin_ref[qi]) & (rj <= tmax_ref[qi]))
    def _():
        # packed token t belongs to row rid[t] at position qp[t]; kv col c
        # is LOGICAL position bj*block_size + c of row rj — a token attends
        # the (rj, c) pair iff it lives in that row and the position is
        # causal for it
        row_tile = rid_ref[:, 0]  # (block_q,)
        pos_tile = qp_ref[:, 0]
        kv_pos = bj * block_size + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_size), 1
        )
        base_mask = (row_tile[:, None] == rj) & (kv_pos <= pos_tile[:, None])
        for kv in range(KV):
            q = q_ref[0, kv].reshape(G * block_q, q_ref.shape[-1])
            k = k_ref[:, kv, :].astype(compute_dtype)  # (block_size, D)
            v = v_ref[:, kv, :].astype(compute_dtype)
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
            ) * scale  # (G*bq, block_size)
            mask = jnp.broadcast_to(
                base_mask[None], (G, block_q, block_size)
            ).reshape(G * block_q, block_size)
            _online_softmax_step(
                s, mask, m_ref, l_ref, acc_ref, v,
                sl=slice(kv * G * block_q, (kv + 1) * G * block_q),
            )

    @pl.when(j == n_rows * n_blocks - 1)
    def _():
        l = jnp.maximum(l_ref[:, 0], 1e-20)
        o_ref[0] = (
            (acc_ref[:] * v_scale / l[:, None])
            .reshape(KV, G, block_q, acc_ref.shape[-1])
            .astype(o_ref.dtype)
        )


def ragged_paged_attention(
    q,  # (1, H, T, D) — the packed mixed-batch queries
    k_cache,  # (total_slots, KV, D) — paged pool, this step's rows written
    v_cache,  # (total_slots, KV, D)
    block_tables,  # (R, NB) int32 block ids per row in logical order; <0 = hole
    row_ids,  # (T,) int32 — owning row per packed token; -1 = padding
    q_pos,  # (T,) int32 — position within the row per packed token
    *,
    block_size: int,
    scale: Optional[float] = None,
    k_scale: float = 1.0,
    v_scale: float = 1.0,
    block_q: int = 256,
):
    """Causal attention for a ragged mixed batch in one launch: the grid
    sweeps every (row, cache-block) pair for each packed-q tile, and the
    per-token (row, position) tags mask each tile down to exactly the
    per-row causal window — prefill chunks and single-token decode rows
    coexist in the same token stream. Per-tile row bounds (precomputed
    host-side-in-graph from ``row_ids``) skip the rows a tile cannot touch,
    so a tile over one row's chunk pays that row's blocks only."""
    B, H, T, D = q.shape
    assert B == 1, "ragged kernel takes the packed (1, H, T, D) layout"
    KV = k_cache.shape[1]
    G = H // KV
    R, NB = block_tables.shape
    scale = (D ** -0.5 if scale is None else scale) * k_scale
    compute_dtype = q.dtype
    # same VMEM bound as the per-row paged prefill kernel
    block_q = _pick_block(T, max(8, min(block_q, 4096 // max(H, 1))))
    nq = T // block_q

    qf = q.reshape(1, KV, G, T, D)
    bt = block_tables.astype(jnp.int32)
    rid = row_ids.astype(jnp.int32)
    qp = q_pos.astype(jnp.int32)
    # per-tile live row range for the block skip; an all-padding tile gets
    # an empty range (min > max) and touches no blocks at all
    rid2 = rid.reshape(nq, block_q)
    live = rid2 >= 0
    tile_min = jnp.min(jnp.where(live, rid2, jnp.int32(2 ** 30)), axis=1)
    tile_max = jnp.max(jnp.where(live, rid2, jnp.int32(-1)), axis=1)

    kernel = functools.partial(
        _ragged_kernel,
        scale=scale,
        v_scale=v_scale,
        n_rows=R,
        n_blocks=NB,
        KV=KV,
        G=G,
        block_q=block_q,
        block_size=block_size,
        compute_dtype=compute_dtype,
    )

    def cache_index(qi, j, bt_ref, tmin_ref, tmax_ref):
        # holes/skipped steps clamp to block 0 — the kernel masks them out
        return jnp.maximum(bt_ref[j // NB, j % NB], 0), 0, 0

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(nq, R * NB),
        in_specs=[
            pl.BlockSpec((block_q, 1), lambda qi, j, *_: (qi, 0)),
            pl.BlockSpec((block_q, 1), lambda qi, j, *_: (qi, 0)),
            pl.BlockSpec(
                (1, KV, G, block_q, D), lambda qi, j, *_: (0, 0, 0, qi, 0)
            ),
            pl.BlockSpec((block_size, KV, D), cache_index),
            pl.BlockSpec((block_size, KV, D), cache_index),
        ],
        out_specs=pl.BlockSpec(
            (1, KV, G, block_q, D), lambda qi, j, *_: (0, 0, 0, qi, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((KV * G * block_q, 1), jnp.float32),
            pltpu.VMEM((KV * G * block_q, 1), jnp.float32),
            pltpu.VMEM((KV * G * block_q, D), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((1, KV, G, T, D), q.dtype),
        interpret=_interpret(),
    )(bt, tile_min, tile_max, rid[:, None], qp[:, None], qf, k_cache, v_cache)
    return out.reshape(1, H, T, D)


def sharded_ragged_paged_call(
    policy, q, k_cache, v_cache, block_tables, row_ids, q_pos,
    *, block_size, scale=None, k_scale=1.0, v_scale=1.0,
):
    """Ragged paged attention under GSPMD (see sharded_paged_prefill_call):
    cache and q shard over kv heads on tp; tables and token tags are
    replicated host metadata."""
    from jax.sharding import PartitionSpec as P

    fn = functools.partial(
        ragged_paged_attention,
        block_size=block_size,
        scale=scale,
        k_scale=k_scale,
        v_scale=v_scale,
    )
    mesh = jax.sharding.get_abstract_mesh()
    if mesh is None or mesh.empty:
        return fn(q, k_cache, v_cache, block_tables, row_ids, q_pos)
    if policy.q[0] is not None or policy.q[2] is not None:
        return None  # batch/seq-sharded packed stream (DP/CP) -> XLA path
    shard_fn = jax.shard_map(
        fn,
        mesh=mesh,
        in_specs=(
            P(*policy.q),
            P(None, policy.q[1], None),
            P(None, policy.q[1], None),
            P(None, None),
            P(None),
            P(None),
        ),
        out_specs=P(*policy.q),
        check_vma=False,
    )
    return shard_fn(q, k_cache, v_cache, block_tables, row_ids, q_pos)
