"""Pallas KV-cache commit kernel — in-place decode-row writes without XLA scatter.

Why this exists: the deferred-write decode path (models/base.py
``defer_write`` + kvcache/kv_cache.py ``commit_rows``) ends the step with one
scatter of the fresh K/V rows into the layer-stacked cache. XLA's TPU scatter
lowering is catastrophically slow for this shape: profiled 8-14 ms to land
512 KB of rows in a 1 GB cache (copy.39/copy.40 in the decode trace — full
cache copies inserted around the scatter), ~55% of the whole decode step. The
reference never meets this problem because its caches are torch Parameters
mutated in place by the runtime (kv_cache_manager.py:374 ``update_cache``);
this kernel is the TPU-native equivalent of that in-place write:
``input_output_aliases`` pins the outputs to the cache buffers and the grid
touches ONLY the 128-slot window holding each written row.

Layout detail (the part that makes it actually in-place): XLA's preferred
cache layout for the decode program is S-minor ({3,4,2,1,0} — sequence
contiguous, the "transposed-K" storage the reference also favors for TKG,
kv_cache_manager.py transposed option). A Pallas operand is always row-major,
so the kernel takes the cache through a logical (L, B, KV, D, S) TRANSPOSED
view: inside a program whose cache value already sits in the S-minor layout,
``jnp.swapaxes(cache, 3, 4)`` is a layout-preserving bitcast — no copy — and
the kernel's row-major view is byte-identical to the surrounding program's
preferred layout. Committing through the untransposed view instead costs 4
full-cache relayout copies (~21 ms, measured).

Semantics (matches ContiguousKVLayout.commit_rows jnp path bit-for-bit for
T == 1 under the contract below):
  - slot ``slots[b, 0]`` of cache line ``line(b)`` receives ``rows[:, b, :, 0]``
  - ``line(b) = seq_ids[b]`` under continuous batching else ``b``
  - out-of-range slots or seq_ids drop the row (best-effort; see contract)
  - duplicate (line, slot) pairs across batch rows only come from SPMD
    padding lanes repeating row 0 with identical values, so any write order
    yields the same bytes.

CONTRACT: each grid step read-modify-writes the whole 128-slot window around
its row, so two steps whose (line, window) collide with DIFFERENT contents
race (a dropped lane's passthrough write-back can clobber a valid write that
landed in the same window between its read and its write). The engaged paths
keep collisions value-identical or impossible:
  - routed (continuous batching): seq_ids are validated in-range host-side
    (model_wrapper._layout_inputs raises) and distinct except for padding
    lanes that repeat row 0's write verbatim;
  - non-routed: each lane only touches its own cache line, so a dropped
    (negative-slot) lane's write-back cannot overlap another lane's window.

T > 1 (speculation windows) stays on the jnp scatter path: adjacent
positions share an aligned window within one line, exactly the racing
pattern above.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_WIN = 128  # lane-aligned slot window per write (S is the minor dim)


def _interpret() -> bool:
    return jax.devices()[0].platform != "tpu"


def commit_rows_supported(k_cache_shape, v_cache_shape, k_rows_shape, v_rows_shape) -> bool:
    """caches (L, B_cache, KV, S, D/Dv); rows (L, B, KV, T, D/Dv). T must be 1."""
    if any(
        len(s) != 5
        for s in (k_cache_shape, v_cache_shape, k_rows_shape, v_rows_shape)
    ):
        return False
    L, B_cache, KV, S, D = k_cache_shape
    Dv = v_cache_shape[4]
    if v_cache_shape != (L, B_cache, KV, S, Dv):
        return False
    if k_rows_shape[0] != L or k_rows_shape[2] != KV or k_rows_shape[4] != D:
        return False
    if v_rows_shape != k_rows_shape[:4] + (Dv,):
        return False
    if k_rows_shape[3] != 1:
        return False
    if _interpret():
        return True
    return S % _WIN == 0 and D % 8 == 0 and Dv % 8 == 0


def _commit_kernel(
    slots_ref, lines_ref, k_rows, v_rows, k_in, v_in, k_out, v_out, *, S, B_cache
):
    b = pl.program_id(0)
    slot = slots_ref[b, 0]
    line = lines_ref[b]
    # out-of-range seq_ids DROP the write (matching the jnp scatter's
    # mode='drop') — the index map clips them onto line 0 for addressing only
    valid = (slot >= 0) & (slot < S) & (line >= 0) & (line < B_cache)
    lane = slot % _WIN

    def put(out_ref, rows_ref, in_ref):
        # window-slot index along the minor S axis of the transposed view
        win = jax.lax.broadcasted_iota(jnp.int32, in_ref.shape, 4)
        out_ref[:] = jnp.where((win == lane) & valid, rows_ref[:], in_ref[:])

    put(k_out, k_rows, k_in)
    put(v_out, v_rows, v_in)


def kv_commit_rows(
    k_cache,  # (L, B_cache, KV, S, D) store dtype
    v_cache,  # (L, B_cache, KV, S, Dv)
    k_rows,  # (L, B, KV, 1, D) store dtype (caller scales/casts)
    v_rows,  # (L, B, KV, 1, Dv)
    slots,  # (B, 1) int32 target slots; <0 or >=S drops the write
    seq_ids: Optional[jax.Array] = None,  # (B,) cache-line routing
):
    """In-place commit of one fresh K/V row per batch line into the
    layer-stacked cache. Grid (B,); each step read-modify-writes the
    (L, KV, D, 128) window holding the target slot through aliased outputs,
    on the S-minor transposed view (see module docstring)."""
    L, B_cache, KV, S, D = k_cache.shape
    Dv = v_cache.shape[4]
    B = slots.shape[0]
    slots = slots.astype(jnp.int32)
    if seq_ids is None:
        lines = jnp.arange(B, dtype=jnp.int32)
    else:
        lines = seq_ids.astype(jnp.int32)  # raw: kernel drops out-of-range

    # bitcast-transpose to the S-minor view (free inside a program whose
    # cache already carries the S-minor layout)
    k_t = jnp.swapaxes(k_cache, 3, 4)  # (L, B_cache, KV, D, S)
    v_t = jnp.swapaxes(v_cache, 3, 4)
    kr_t = jnp.swapaxes(k_rows, 3, 4)  # (L, B, KV, D, 1)
    vr_t = jnp.swapaxes(v_rows, 3, 4)

    # tile the layer dim so in/out + double-buffered blocks fit scoped VMEM
    # (~16 MB): k+v, in+out, 2x pipelining = 8 copies of the block in flight
    block_bytes = KV * max(D, Dv) * _WIN * jnp.dtype(k_cache.dtype).itemsize
    budget = 8 * 1024 * 1024
    l_blk = 1
    for cand in range(L, 0, -1):
        if L % cand == 0 and 8 * cand * block_bytes <= budget:
            l_blk = cand
            break

    def cache_index(b, lt, slots_ref, lines_ref):
        slot = jnp.clip(slots_ref[b, 0], 0, S - 1)
        line = jnp.clip(lines_ref[b], 0, B_cache - 1)
        return lt, line, 0, 0, slot // _WIN

    def rows_index(b, lt, slots_ref, lines_ref):
        return lt, b, 0, 0, 0

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, L // l_blk),
        in_specs=[
            pl.BlockSpec((l_blk, 1, KV, D, 1), rows_index),
            pl.BlockSpec((l_blk, 1, KV, Dv, 1), rows_index),
            pl.BlockSpec((l_blk, 1, KV, D, _WIN), cache_index),
            pl.BlockSpec((l_blk, 1, KV, Dv, _WIN), cache_index),
        ],
        out_specs=[
            pl.BlockSpec((l_blk, 1, KV, D, _WIN), cache_index),
            pl.BlockSpec((l_blk, 1, KV, Dv, _WIN), cache_index),
        ],
    )
    out_k, out_v = pl.pallas_call(
        functools.partial(_commit_kernel, S=S, B_cache=B_cache),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct(k_t.shape, k_t.dtype),
            jax.ShapeDtypeStruct(v_t.shape, v_t.dtype),
        ],
        # inputs are (slots, lines, k_rows, v_rows, k_cache, v_cache)
        input_output_aliases={4: 0, 5: 1},
        interpret=_interpret(),
    )(slots, lines, kr_t, vr_t, k_t, v_t)
    return jnp.swapaxes(out_k, 3, 4), jnp.swapaxes(out_v, 3, 4)


def sharded_commit_call(
    cache_pspec,  # PartitionSpec of the stacked cache (L, B, KV, S, D)
    k_cache, v_cache, k_rows, v_rows, slots, seq_ids=None,
):
    """Commit under GSPMD: shard_map mirroring the cache sharding (kv heads on
    tp, optionally batch on dp). Returns None when the cache's sequence dim is
    sharded (flash-decoding KV-S layout) — slots are global positions the
    local shard can't address — and the caller falls back to the jnp scatter.
    """
    from jax.sharding import PartitionSpec as P

    mesh = jax.sharding.get_abstract_mesh()
    if mesh is None or mesh.empty:
        return kv_commit_rows(k_cache, v_cache, k_rows, v_rows, slots, seq_ids)
    axes = tuple(cache_pspec) + (None,) * (5 - len(tuple(cache_pspec)))
    if axes[3] is not None:
        return None  # sequence-sharded cache: global slots, local shards
    if axes[1] is not None and seq_ids is not None:
        return None  # batch-sharded + seq-id routing would cross shards
    rows_spec = P(axes[0], axes[1], axes[2], None, None)
    shard_fn = jax.shard_map(
        kv_commit_rows,
        mesh=mesh,
        in_specs=(
            P(*axes),
            P(*axes),
            rows_spec,
            rows_spec,
            P(axes[1], None),
            None if seq_ids is None else P(axes[1]),
        ),
        out_specs=(P(*axes), P(*axes)),
        check_vma=False,
    )
    return shard_fn(k_cache, v_cache, k_rows, v_rows, slots, seq_ids)
