"""Pallas fused MLP and fused-QKV projection kernels.

The TPU-native answer to the reference's fused weight-streaming kernels
(reference: the NKI MLP kernel path, models/llama/modeling_llama.py:502-943
``mlp_kernel_enabled`` / ``quantized_mlp_kernel_enabled``, and the QKV kernel
gated on ``fused_qkv``, modules/attention/gqa.py:669).

Fused MLP: ``down( act(x @ gate) * (x @ up) )`` in ONE pass over the weights.
The grid walks intermediate-dim tiles; each step streams a (H, bi) slab of
gate+up and a (bi, H) slab of down exactly once, keeps the activations in
VMEM, and accumulates the down partial products in an f32 scratch — no
intermediate (M, I) tensor ever touches HBM. At decode shapes the op is
weight-bandwidth-bound, so the kernel's job is to match the HBM roofline
while removing XLA's three separate kernel launches + intermediate
round-trips.

Fused QKV: one (H_in, Tq+Tk+Tv) matmul over the load-time-interleaved fused
weight (see dense.fuse_qkv_weights) — a plain tiled matmul kernel; the win is
one weight stream + one launch for three projections.

Under tensor parallelism both wrap in ``shard_map``: gate/up column-sharded,
down row-sharded with an in-kernel-local matmul + psum (MLP); the fused QKV
weight column-sharded with the per-rank head-block interleave making each
shard self-contained (no collective).

Engagement is LOUD: config flags either run these kernels or the caller
raises — there is no silent fallback (round-3 verdict weak #4).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from nxdi_tpu.parallel.mesh import AXIS_MP


def _interpret() -> bool:
    return jax.devices()[0].platform != "tpu"


def _pick_block(s: int, target: int) -> int:
    b = min(target, s)
    while s % b:
        b //= 2
    return max(b, 1)


def _mlp_block_i(i_dim: int, h: int, target: int) -> int:
    """Intermediate-dim tile clamped to the VMEM budget: each grid step
    streams gate+up (H, bi) and down (bi, H) double-buffered — 12*H*bi bytes
    in flight (bf16). Keep that under ~10 MB of the ~16 MB scoped VMEM."""
    bi = _pick_block(i_dim, target)
    while bi > 128 and 12 * h * bi > 10 * 1024 * 1024:
        bi //= 2
    return bi


_KERNEL_ACTS = ("silu", "gelu", "gelu_pytorch_tanh", "gelu_new", "relu")


def _act(x, name: str):
    if name == "silu":
        return jax.nn.silu(x)
    if name in ("gelu_pytorch_tanh", "gelu_new"):
        return jax.nn.gelu(x, approximate=True)
    if name == "gelu":
        return jax.nn.gelu(x, approximate=False)
    if name == "relu":
        return jax.nn.relu(x)
    raise NotImplementedError(f"fused MLP kernel: unsupported activation {name!r}")


# ---------------------------------------------------------------------------
# Fused gate/up/down MLP
# ---------------------------------------------------------------------------


def fused_mlp_supported(m: int, h: int, i_local: int, act: str) -> bool:
    """Static eligibility for the LOCAL (per-rank) problem shape."""
    if act not in _KERNEL_ACTS:
        return False
    if _interpret():
        return True
    # Mosaic wants lane-aligned minor dims; H rides VMEM whole per block
    return h % 128 == 0 and i_local % 128 == 0


def _fused_mlp_kernel(x_ref, g_ref, u_ref, d_ref, o_ref, acc_ref, *, act, n_i):
    i = pl.program_id(1)

    @pl.when(i == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    x = x_ref[...]
    g = jnp.dot(x, g_ref[...], preferred_element_type=jnp.float32)
    u = jnp.dot(x, u_ref[...], preferred_element_type=jnp.float32)
    a = (_act(g, act) * u).astype(x.dtype)
    acc_ref[...] += jnp.dot(a, d_ref[...], preferred_element_type=jnp.float32)

    @pl.when(i == n_i - 1)
    def _done():
        o_ref[...] = acc_ref[...].astype(o_ref.dtype)


def fused_mlp(
    x: jax.Array,  # (M, H)
    gate_w: jax.Array,  # (H, I)
    up_w: jax.Array,  # (H, I)
    down_w: jax.Array,  # (I, H)
    *,
    act: str = "silu",
    block_m: int = 256,
    block_i: int = 512,
) -> jax.Array:
    M, H = x.shape
    I = gate_w.shape[1]
    bm = _pick_block(M, block_m)
    bi = _mlp_block_i(I, H, block_i)
    n_m, n_i = M // bm, I // bi
    kernel = functools.partial(_fused_mlp_kernel, act=act, n_i=n_i)
    return pl.pallas_call(
        kernel,
        grid=(n_m, n_i),
        in_specs=[
            pl.BlockSpec((bm, H), lambda m, i: (m, 0)),
            pl.BlockSpec((H, bi), lambda m, i: (0, i)),
            pl.BlockSpec((H, bi), lambda m, i: (0, i)),
            pl.BlockSpec((bi, H), lambda m, i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((bm, H), lambda m, i: (m, 0)),
        out_shape=jax.ShapeDtypeStruct((M, H), x.dtype),
        scratch_shapes=[pltpu.VMEM((bm, H), jnp.float32)],
        interpret=_interpret(),
    )(x, gate_w, up_w, down_w)


def sharded_fused_mlp_call(
    x: jax.Array,  # (B, S, H)
    gate_w: jax.Array,  # (H, I) — column-sharded over AXIS_MP when tp > 1
    up_w: jax.Array,
    down_w: jax.Array,  # (I, H) — row-sharded
    *,
    act: str = "silu",
) -> Optional[jax.Array]:
    """Fused MLP under GSPMD; returns None when the local shape is ineligible
    (callers raise — the flag never silently no-ops)."""
    from jax.sharding import PartitionSpec as P

    B, S, H = x.shape
    I = gate_w.shape[1]
    mesh = jax.sharding.get_abstract_mesh()
    tp = 1
    if mesh is not None and not mesh.empty and AXIS_MP in mesh.shape:
        tp = mesh.shape[AXIS_MP]
    if I % tp or not fused_mlp_supported(B * S, H, I // tp, act):
        return None

    def local(x2, g, u, d):
        y = fused_mlp(x2, g, u, d, act=act)
        if tp > 1:
            y = jax.lax.psum(y, AXIS_MP)
        return y

    x2 = x.reshape(B * S, H)
    if tp == 1:
        out = local(x2, gate_w, up_w, down_w)
    else:
        out = jax.shard_map(
            local,
            mesh=mesh,
            in_specs=(P(), P(None, AXIS_MP), P(None, AXIS_MP), P(AXIS_MP, None)),
            out_specs=P(),
            check_vma=False,
        )(x2, gate_w, up_w, down_w)
    return out.reshape(B, S, H)


# ---------------------------------------------------------------------------
# Stacked variants — weights read from the LAYER-STACKED arrays via scalar-
# prefetched layer index. Inside the decoder lax.scan a pallas operand on a
# per-layer xs slice materializes a full weight copy per layer (the same
# slice-copy tax that made the fused TKG attention kernel lose, see the
# round-3 notes in bench.py); indexing the stacked array inside the kernel's
# BlockSpec avoids the slice entirely, like ops/kernels/kv_commit.py does for
# the KV cache.
# ---------------------------------------------------------------------------


def _fused_mlp_stacked_kernel(
    l_ref, x_ref, g_ref, u_ref, d_ref, o_ref, acc_ref, *, act, n_i
):
    del l_ref  # consumed by the index maps
    i = pl.program_id(1)

    @pl.when(i == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    x = x_ref[...]
    g = jnp.dot(x, g_ref[0], preferred_element_type=jnp.float32)
    u = jnp.dot(x, u_ref[0], preferred_element_type=jnp.float32)
    a = (_act(g, act) * u).astype(x.dtype)
    acc_ref[...] += jnp.dot(a, d_ref[0], preferred_element_type=jnp.float32)

    @pl.when(i == n_i - 1)
    def _done():
        o_ref[...] = acc_ref[...].astype(o_ref.dtype)


def fused_mlp_stacked(
    x: jax.Array,  # (M, H)
    gate_s: jax.Array,  # (L, H, I)
    up_s: jax.Array,  # (L, H, I)
    down_s: jax.Array,  # (L, I, H)
    layer_idx: jax.Array,  # (1,) int32
    *,
    act: str = "silu",
    block_m: int = 256,
    block_i: int = 512,
) -> jax.Array:
    M, H = x.shape
    I = gate_s.shape[2]
    bm = _pick_block(M, block_m)
    bi = _mlp_block_i(I, H, block_i)
    n_m, n_i = M // bm, I // bi
    kernel = functools.partial(_fused_mlp_stacked_kernel, act=act, n_i=n_i)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_m, n_i),
        in_specs=[
            pl.BlockSpec((bm, H), lambda m, i, l_ref: (m, 0)),
            pl.BlockSpec((1, H, bi), lambda m, i, l_ref: (l_ref[0], 0, i)),
            pl.BlockSpec((1, H, bi), lambda m, i, l_ref: (l_ref[0], 0, i)),
            pl.BlockSpec((1, bi, H), lambda m, i, l_ref: (l_ref[0], i, 0)),
        ],
        out_specs=pl.BlockSpec((bm, H), lambda m, i, l_ref: (m, 0)),
        scratch_shapes=[pltpu.VMEM((bm, H), jnp.float32)],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((M, H), x.dtype),
        interpret=_interpret(),
    )(layer_idx.astype(jnp.int32), x, gate_s, up_s, down_s)


def sharded_fused_mlp_stacked_call(
    x: jax.Array,  # (B, S, H)
    gate_s: jax.Array,  # (L, H, I) — I sharded over AXIS_MP when tp > 1
    up_s: jax.Array,
    down_s: jax.Array,  # (L, I, H)
    layer_idx: jax.Array,  # scalar/1-elt int32
    *,
    act: str = "silu",
) -> Optional[jax.Array]:
    from jax.sharding import PartitionSpec as P

    B, S, H = x.shape
    I = gate_s.shape[2]
    mesh = jax.sharding.get_abstract_mesh()
    tp = 1
    if mesh is not None and not mesh.empty and AXIS_MP in mesh.shape:
        tp = mesh.shape[AXIS_MP]
    if I % tp or not fused_mlp_supported(B * S, H, I // tp, act):
        return None

    li = layer_idx.reshape(1)

    def local(x2, g, u, d, li_):
        y = fused_mlp_stacked(x2, g, u, d, li_, act=act)
        if tp > 1:
            y = jax.lax.psum(y, AXIS_MP)
        return y

    x2 = x.reshape(B * S, H)
    if tp == 1:
        out = local(x2, gate_s, up_s, down_s, li)
    else:
        out = jax.shard_map(
            local,
            mesh=mesh,
            in_specs=(P(), P(None, None, AXIS_MP), P(None, None, AXIS_MP),
                      P(None, AXIS_MP, None), P()),
            out_specs=P(),
            check_vma=False,
        )(x2, gate_s, up_s, down_s, li)
    return out.reshape(B, S, H)


def _qkv_stacked_kernel(l_ref, x_ref, w_ref, o_ref):
    del l_ref
    y = jnp.dot(x_ref[...], w_ref[0], preferred_element_type=jnp.float32)
    o_ref[...] = y.astype(o_ref.dtype)


def qkv_matmul_stacked(
    x: jax.Array,  # (M, H_in)
    w_s: jax.Array,  # (L, H_in, T)
    layer_idx: jax.Array,  # (1,) int32
    b_s: Optional[jax.Array] = None,  # (L, T) — added OUTSIDE the kernel
    *,
    block_m: int = 256,
    block_n: int = 512,
) -> jax.Array:
    # bias stays out of the pallas operands: Mosaic rejects packed bf16
    # bias layouts, and XLA fuses the add into the kernel's output for free
    M, H = x.shape
    T = w_s.shape[2]
    bm = _pick_block(M, block_m)
    bn = _pick_block(T, block_n)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(M // bm, T // bn),
        in_specs=[
            pl.BlockSpec((bm, H), lambda m, n, l_ref: (m, 0)),
            pl.BlockSpec((1, H, bn), lambda m, n, l_ref: (l_ref[0], 0, n)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda m, n, l_ref: (m, n)),
    )
    out = pl.pallas_call(
        _qkv_stacked_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((M, T), x.dtype),
        interpret=_interpret(),
    )(layer_idx.astype(jnp.int32), x, w_s)
    if b_s is not None:
        out = out + jnp.take(
            b_s, layer_idx.reshape(()).astype(jnp.int32), axis=0, mode="clip"
        ).astype(out.dtype)
    return out


def sharded_qkv_stacked_call(
    x: jax.Array,  # (B, S, H_in)
    w_s: jax.Array,  # (L, H_in, T) — T sharded (interleaved head blocks)
    layer_idx: jax.Array,
    b_s: Optional[jax.Array] = None,
) -> Optional[jax.Array]:
    from jax.sharding import PartitionSpec as P

    B, S, H = x.shape
    T = w_s.shape[2]
    mesh = jax.sharding.get_abstract_mesh()
    tp = 1
    if mesh is not None and not mesh.empty and AXIS_MP in mesh.shape:
        tp = mesh.shape[AXIS_MP]
    if T % tp or not qkv_matmul_supported(B * S, H, T // tp):
        return None

    li = layer_idx.reshape(1)
    x2 = x.reshape(B * S, H)
    if tp == 1:
        out = qkv_matmul_stacked(x2, w_s, li, b_s)
    else:
        in_specs = [P(), P(None, None, AXIS_MP), P()] + (
            [P(None, AXIS_MP)] if b_s is not None else []
        )
        out = jax.shard_map(
            qkv_matmul_stacked,
            mesh=mesh,
            in_specs=tuple(in_specs),
            out_specs=P(None, AXIS_MP),
            check_vma=False,
        )(*([x2, w_s, li] + ([b_s] if b_s is not None else [])))
    return out.reshape(B, S, T)


# ---------------------------------------------------------------------------
# Fused QKV projection (plain tiled matmul over the interleaved fused weight)
# ---------------------------------------------------------------------------


def qkv_matmul_supported(m: int, h_in: int, t_local: int) -> bool:
    if _interpret():
        return True
    return h_in % 128 == 0 and t_local % 128 == 0


def _matmul_kernel(x_ref, w_ref, o_ref):
    y = jnp.dot(x_ref[...], w_ref[...], preferred_element_type=jnp.float32)
    o_ref[...] = y.astype(o_ref.dtype)


def qkv_matmul(
    x: jax.Array,  # (M, H_in)
    w: jax.Array,  # (H_in, T)
    b: Optional[jax.Array] = None,  # (T,) — added OUTSIDE the kernel
    *,
    block_m: int = 256,
    block_n: int = 512,
) -> jax.Array:
    # bias stays out of the pallas operands: Mosaic rejects packed bf16
    # bias layouts, and XLA fuses the add into the kernel's output for free
    M, H = x.shape
    T = w.shape[1]
    bm = _pick_block(M, block_m)
    bn = _pick_block(T, block_n)
    out = pl.pallas_call(
        _matmul_kernel,
        grid=(M // bm, T // bn),
        in_specs=[
            pl.BlockSpec((bm, H), lambda m, n: (m, 0)),
            pl.BlockSpec((H, bn), lambda m, n: (0, n)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda m, n: (m, n)),
        out_shape=jax.ShapeDtypeStruct((M, T), x.dtype),
        interpret=_interpret(),
    )(x, w)
    if b is not None:
        out = out + b.astype(out.dtype)
    return out


def sharded_qkv_call(
    x: jax.Array,  # (B, S, H_in)
    w: jax.Array,  # (H_in, T) — column-sharded (interleaved head blocks)
    b: Optional[jax.Array] = None,
) -> Optional[jax.Array]:
    from jax.sharding import PartitionSpec as P

    B, S, H = x.shape
    T = w.shape[1]
    mesh = jax.sharding.get_abstract_mesh()
    tp = 1
    if mesh is not None and not mesh.empty and AXIS_MP in mesh.shape:
        tp = mesh.shape[AXIS_MP]
    if T % tp or not qkv_matmul_supported(B * S, H, T // tp):
        return None

    x2 = x.reshape(B * S, H)
    if tp == 1:
        out = qkv_matmul(x2, w, b)
    else:
        in_specs = [P(), P(None, AXIS_MP)] + ([P(AXIS_MP)] if b is not None else [])
        out = jax.shard_map(
            functools.partial(qkv_matmul),
            mesh=mesh,
            in_specs=tuple(in_specs),
            out_specs=P(None, AXIS_MP),
            check_vma=False,
        )(*([x2, w] + ([b] if b is not None else [])))
    return out.reshape(B, S, T)
