"""Mixture-of-Experts ops — router + expert MLPs, expert-parallel over the mesh.

Reference: modules/moe_v2.py:23-132 assembles RouterTopK + ExpertMLPsV2 +
SharedExperts into an MoE wrapper, with TPxEP process groups (:135-161) and
NKI blockwise-matmul kernels. TPU-native the same structure is:

  - **Router**: one replicated linear -> scoring (softmax / sigmoid /
    grouped-top-k for deepseek-V3) -> top-k -> (optional) renormalize, exactly
    HF's semantics so logits match the CPU golden.
  - **Experts, sparse dispatch (default)**: tokens are sorted by their routed
    expert and run through ``jax.lax.ragged_dot`` — XLA's grouped matmul, the
    MXU-native equivalent of the reference's blockwise NKI expert kernels
    (ExpertMLPsV2 block dispatch). FLOPs scale with ``T x top_k``, not with
    ``T x num_experts``; at 128-expert/top-8 scale that is 16x fewer expert
    FLOPs than dense dispatch. Static shapes throughout: the sort, the group
    sizes, and the combine are all fixed-(T*K) arrays, so the path jits/scans
    cleanly.
  - **Experts, dense dispatch (fallback)**: every expert runs on every token
    with a zero combine weight for unselected experts. No sort, no
    gather/scatter; kept for A/B testing via ``moe_dispatch="dense"``.
  - **Parallelism**: three regimes over the (ep, tp) mesh axes (parallel/mesh
    AXIS_MP = the full model-parallel world):
      * full-EP (``ep=True``, default when the world divides the expert
        count): the expert dim is sharded over the whole (ep, tp) world.
      * expert-internal TP (``ep=False``): the expert intermediate dim is
        sharded over the world (the reference's moe_tp_degree).
      * hybrid TPxEP (``hybrid_ep=True``, from ``moe_ep_degree`` x
        ``moe_tp_degree``): experts shard over the dedicated ``ep`` mesh axis
        while each expert's intermediate shards over ``tp`` — the reference's
        moe_v2.py:135-161 TPxEP process-group factorization. Attention and
        dense layers keep sharding over the full world via AXIS_MP.
    The sparse path runs under ``shard_map`` (GSPMD cannot partition a
    ragged_dot over its group dim); each shard computes its local experts /
    intermediate slice and one psum over (ep, tp) produces the combined
    output — the reference's EP dispatch AR/RS collectives
    (attention_base.py:179 EPDispatchOption).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from nxdi_tpu.parallel.mesh import AXIS_EP, AXIS_EPX, AXIS_MP, AXIS_TP


@dataclass(frozen=True)
class MoEArch:
    """Static MoE architecture description (hashable; part of DecoderArch)."""

    num_experts: int
    top_k: int
    intermediate_size: int  # per-expert intermediate
    hidden_act: str = "silu"
    norm_topk_prob: bool = True  # renormalize top-k weights (mixtral: always)
    # expert-parallel over the full (ep, tp) world (family builder sets this
    # when the world divides E); False -> expert-internal TP on the
    # intermediate dim; hybrid_ep -> experts over the ep axis, intermediate
    # over tp (reference: moe_ep_degree x moe_tp_degree, config.py:603)
    ep: bool = False
    hybrid_ep: bool = False
    # per-phase hybrid TPxEP (reference: HybridShardingConfig config.py:1060 +
    # moe_v2.py:135-161 per-phase process groups): prefill programs compile
    # TP-heavy (experts over ep, intermediate over epx x tp), decode programs
    # EP-heavy (experts over ep x epx, intermediate over tp). ``phase`` is a
    # per-SUBMODEL arch override (the TKG/speculation wrappers flip it to
    # "decode"); expert weights are duplicated per regime ("experts_tkg"),
    # mirroring the reference's preshard-hook duplication.
    per_phase_hybrid: bool = False
    phase: str = "prefill"
    # "sparse" (ragged_dot grouped matmul) or "dense" (all experts, all tokens)
    dispatch: str = "sparse"
    # shared (always-on) experts, qwen2-moe/llama4 style
    shared_expert_intermediate_size: Optional[int] = None
    shared_expert_gated: bool = False  # sigmoid(gate(x)) scaling on shared out
    # gpt-oss variants (reference: models/gpt_oss/modeling_gpt_oss.py): router
    # takes top-k of LOGITS then softmaxes the selected values; experts carry
    # biases and use the clamped glu  (up+1) * gate*sigmoid(alpha*gate)
    topk_softmax: bool = False
    # llama4 (reference: models/llama4/): top-k logits -> sigmoid scores that
    # scale the expert INPUT (not output); shared expert always added
    llama4_router: bool = False
    router_bias: bool = False
    expert_bias: bool = False
    gptoss_glu: bool = False
    glu_limit: Optional[float] = None
    glu_alpha: float = 1.702
    # deepseek-V3 routing (reference contrib DeepSeek-V3; HF DeepseekV3TopkRouter):
    # sigmoid scores (+ optional learned correction bias used ONLY for
    # selection), grouped top-k over n_group groups keeping topk_group, final
    # weights from the UNCORRECTED sigmoid scores, scaled by routed_scaling
    sigmoid_routing: bool = False
    # phimoe (Phi-3.5-MoE) sparsemixer routing (HF sparsemixer, eval path):
    # expert k's weight comes from a softmax over scores THRESHOLD-masked at
    # (max - s)/clamp(|s|, min=max) > 2*jitter_eps, with the top-1 expert
    # masked out before selecting the second
    sparsemixer: bool = False
    router_jitter: float = 0.01
    n_group: Optional[int] = None
    topk_group: Optional[int] = None
    routed_scaling: float = 1.0
    correction_bias: bool = False


def ep_policy(tp_degree: int, num_experts: int) -> bool:
    """Shared EP-vs-TP decision for family builders: expert parallelism when
    the tp world divides the expert count."""
    return tp_degree > 1 and num_experts % tp_degree == 0


def moe_parallel_fields(tc, num_experts: int) -> Dict[str, Any]:
    """MoEArch constructor kwargs for the parallel/dispatch knobs, derived from
    the :class:`TpuConfig` — shared by every MoE family builder."""
    hsc = getattr(tc, "hybrid_sharding_config", None)
    if hsc is not None:
        if num_experts % hsc.moe_tkg_ep_degree:
            raise ValueError(
                f"moe_tkg_ep_degree ({hsc.moe_tkg_ep_degree}) must divide the "
                f"expert count ({num_experts})"
            )
        return {
            "ep": False,
            "hybrid_ep": True,
            "per_phase_hybrid": True,
            "dispatch": getattr(tc, "moe_dispatch", "sparse"),
        }
    hybrid = bool(getattr(tc, "moe_ep_degree", None) and tc.moe_ep_degree > 1)
    if hybrid and num_experts % tc.moe_ep_degree != 0:
        raise ValueError(
            f"moe_ep_degree ({tc.moe_ep_degree}) must divide the expert count "
            f"({num_experts})"
        )
    return {
        "ep": (not hybrid) and ep_policy(tc.tp_degree, num_experts),
        "hybrid_ep": hybrid,
        "dispatch": getattr(tc, "moe_dispatch", "sparse"),
    }


def convert_hf_experts(get, cast, num_experts: int, router_key: str, expert_fmt) -> Dict[str, Any]:
    """Stack per-expert HF weights into the (E, in, out) layout ops/moe.py
    consumes. ``expert_fmt(j, proj)`` yields the HF key for expert j's
    gate/up/down projection."""
    import numpy as np

    gate = np.stack([get(expert_fmt(j, "gate")).T for j in range(num_experts)])
    up = np.stack([get(expert_fmt(j, "up")).T for j in range(num_experts)])
    down = np.stack([get(expert_fmt(j, "down")).T for j in range(num_experts)])
    return {
        "router": {"w": cast(get(router_key).T)},
        "experts": {
            "gate_proj": {"w": cast(gate)},
            "up_proj": {"w": cast(up)},
            "down_proj": {"w": cast(down)},
        },
    }


def _expert_dim_axes(moe: MoEArch, phase: Optional[str] = None) -> Tuple[str, ...]:
    """Mesh axes sharding the expert dim (for specs and shard_map offsets).
    ``phase`` overrides ``moe.phase`` (spec builders emit both regimes)."""
    if moe.hybrid_ep:
        if moe.per_phase_hybrid and (phase or moe.phase) == "decode":
            return (AXIS_EP, AXIS_EPX)
        return (AXIS_EP,)
    if moe.ep:
        return AXIS_MP
    return ()


def _inter_dim_axes(moe: MoEArch, phase: Optional[str] = None) -> Tuple[str, ...]:
    """Mesh axes sharding the expert intermediate dim."""
    if moe.hybrid_ep:
        if moe.per_phase_hybrid and (phase or moe.phase) == "decode":
            return (AXIS_TP,)
        return (AXIS_EPX, AXIS_TP)
    if moe.ep:
        return ()
    return AXIS_MP


def _axes_entry(axes: Tuple[str, ...]):
    """PartitionSpec entry for an axes tuple ('' -> None)."""
    if not axes:
        return None
    return axes if len(axes) > 1 else axes[0]


def expert_parallel_specs(moe: MoEArch) -> Dict[str, Any]:
    """PartitionSpecs for one layer's MoE params (pre-layer-stacking).

    Expert dim over :func:`_expert_dim_axes`, intermediate dim over
    :func:`_inter_dim_axes` (reference: moe_ep_degree vs moe_tp_degree,
    config.py:603). In hybrid mode weights are 2-D sharded (experts x
    intermediate)."""
    def expert_spec_for(phase):
        e = _axes_entry(_expert_dim_axes(moe, phase))
        i = _axes_entry(_inter_dim_axes(moe, phase))
        spec = {
            "gate_proj": {"w": P(e, None, i)},
            "up_proj": {"w": P(e, None, i)},
            "down_proj": {"w": P(e, i, None)},
        }
        if moe.expert_bias:
            spec["gate_proj"]["b"] = P(e, i)
            spec["up_proj"]["b"] = P(e, i)
            spec["down_proj"]["b"] = P(e, None)
        return spec

    specs: Dict[str, Any] = {
        "router": {"w": P()},
        "experts": expert_spec_for("prefill"),
    }
    if moe.per_phase_hybrid:
        # duplicated decode-regime copy (reference: mlp_op_tkg duplication in
        # the hybrid preshard hook)
        specs["experts_tkg"] = expert_spec_for("decode")
    if moe.router_bias:
        specs["router"]["b"] = P()
    if moe.correction_bias:
        specs["router"]["e_bias"] = P()
    if moe.shared_expert_intermediate_size:
        specs["shared_expert"] = {
            "gate_proj": {"w": P(None, AXIS_MP)},
            "up_proj": {"w": P(None, AXIS_MP)},
            "down_proj": {"w": P(AXIS_MP, None)},
        }
        if moe.shared_expert_gated:
            specs["shared_expert_gate"] = {"w": P()}
    return specs


# ---------------------------------------------------------------------------
# Routing
# ---------------------------------------------------------------------------


def route_topk(
    router_logits: jax.Array, moe: MoEArch, p_router: Optional[Dict[str, Any]] = None
) -> Tuple[jax.Array, jax.Array]:
    """Router logits (T, E) -> (weights (T, K) f32, expert ids (T, K) i32).

    Covers the HF routing family zoo: full-softmax top-k (mixtral/qwen3moe,
    reference RouterTopK moe_v2.py:23), top-k-then-softmax (gpt-oss), sigmoid
    top-k on the INPUT scale (llama4), and deepseek-V3 sigmoid grouped top-k
    with selection-only correction bias."""
    logits = router_logits.astype(jnp.float32)
    if moe.sparsemixer:
        # HF phimoe sparsemixer, inference path (top-2 only)
        assert moe.top_k == 2, "sparsemixer routing is top-2"

        def pick(scores):
            mx = jnp.max(scores, axis=-1, keepdims=True)
            idx = jnp.argmax(scores, axis=-1, keepdims=True)
            factor = jnp.maximum(jnp.abs(scores), mx)
            drop = (mx - scores) / factor > 2.0 * moe.router_jitter
            gates = jax.nn.softmax(jnp.where(drop, -jnp.inf, scores), axis=-1)
            w = jnp.take_along_axis(gates, idx, axis=-1)
            return w, idx

        w1, i1 = pick(logits)
        masked = jnp.where(
            jax.nn.one_hot(i1[:, 0], logits.shape[-1], dtype=bool), -jnp.inf, logits
        )
        # the second threshold mask uses the ORIGINAL |scores| clamp floor
        mx2 = jnp.max(masked, axis=-1, keepdims=True)
        i2 = jnp.argmax(masked, axis=-1, keepdims=True)
        factor2 = jnp.maximum(jnp.abs(logits), mx2)
        drop2 = (mx2 - logits) / factor2 > 2.0 * moe.router_jitter
        gates2 = jax.nn.softmax(jnp.where(drop2, -jnp.inf, masked), axis=-1)
        w2 = jnp.take_along_axis(gates2, i2, axis=-1)
        return (
            jnp.concatenate([w1, w2], axis=-1),
            jnp.concatenate([i1, i2], axis=-1).astype(jnp.int32),
        )
    if moe.sigmoid_routing or moe.routed_scaling != 1.0 or (moe.n_group or 0) > 1:
        # deepseek lineage. V3 (sigmoid_routing): sigmoid scores, selection
        # over bias-corrected scores, group metric = sum of top-2 members.
        # V2 (softmax scoring): softmax scores, no correction bias, group
        # metric = max member (HF DeepseekV2 MoEGate). Both: weights from the
        # raw scores, optional renorm, * routed_scaling_factor.
        if moe.sigmoid_routing:
            scores = jax.nn.sigmoid(logits)
        else:
            scores = jax.nn.softmax(logits, axis=-1)
        select = scores
        if moe.correction_bias:
            select = scores + p_router["e_bias"].astype(jnp.float32)
        if moe.n_group and moe.n_group > 1:
            T = logits.shape[0]
            E, G = moe.num_experts, moe.n_group
            grouped = select.reshape(T, G, E // G)
            if moe.sigmoid_routing:
                top2, _ = jax.lax.top_k(grouped, min(2, E // G))
                group_scores = jnp.sum(top2, axis=-1)
            else:
                group_scores = jnp.max(grouped, axis=-1)
            _, group_idx = jax.lax.top_k(group_scores, moe.topk_group)
            group_mask = jnp.sum(
                jax.nn.one_hot(group_idx, G, dtype=jnp.float32), axis=-2
            )  # (T, G)
            member_mask = jnp.repeat(group_mask, E // G, axis=-1)
            select = jnp.where(member_mask > 0, select, -jnp.inf)
        _, top_idx = jax.lax.top_k(select, moe.top_k)
        # weights come from the UNCORRECTED scores
        top_vals = jnp.take_along_axis(scores, top_idx, axis=-1)
        if moe.norm_topk_prob:
            top_vals = top_vals / (jnp.sum(top_vals, axis=-1, keepdims=True) + 1e-20)
        top_vals = top_vals * moe.routed_scaling
        return top_vals, top_idx
    if moe.llama4_router:
        top_vals, top_idx = jax.lax.top_k(logits, moe.top_k)
        return jax.nn.sigmoid(top_vals), top_idx
    if moe.topk_softmax:
        # gpt-oss: top-k on raw logits, softmax over the k selected values
        top_vals, top_idx = jax.lax.top_k(logits, moe.top_k)
        return jax.nn.softmax(top_vals, axis=-1), top_idx
    probs = jax.nn.softmax(logits, axis=-1)
    top_vals, top_idx = jax.lax.top_k(probs, moe.top_k)  # (T, K)
    if moe.norm_topk_prob:
        top_vals = top_vals / jnp.sum(top_vals, axis=-1, keepdims=True)
    return top_vals, top_idx


def route(router_logits: jax.Array, moe: MoEArch, p_router=None) -> jax.Array:
    """Router logits (T, E) -> dense combine weights (T, E), zero for
    unselected experts (used by the dense-dispatch path)."""
    top_vals, top_idx = route_topk(router_logits, moe, p_router)
    return jnp.sum(
        jax.nn.one_hot(top_idx, moe.num_experts, dtype=top_vals.dtype)
        * top_vals[..., None],
        axis=-2,
    )  # (T, E)


# ---------------------------------------------------------------------------
# Expert compute — sparse (ragged_dot) and dense dispatch
# ---------------------------------------------------------------------------


def _expert_act(moe: MoEArch, gate: jax.Array, up: jax.Array) -> jax.Array:
    from nxdi_tpu.models.base import ACT_FNS

    if moe.gptoss_glu:
        if moe.glu_limit is not None:
            gate = jnp.minimum(gate, moe.glu_limit)
            up = jnp.clip(up, -moe.glu_limit, moe.glu_limit)
        return (up + 1.0) * (gate * jax.nn.sigmoid(gate * moe.glu_alpha))
    return ACT_FNS[moe.hidden_act](gate) * up


def _sparse_expert_ffn(
    moe: MoEArch,
    ew: Dict[str, Any],
    xt: jax.Array,  # (T, H) local tokens
    weights: jax.Array,  # (T, K) f32 combine weights
    idx: jax.Array,  # (T, K) i32 expert ids
    e_lo,  # scalar: first expert id held locally
    e_count: int,  # number of experts held locally
    down_bias_on=1.0,  # 0/1 gate so replicated down biases aren't double-psummed
) -> jax.Array:
    """Grouped-matmul expert FFN over the locally-held expert/intermediate
    shard. Returns the PARTIAL combined output (T, H) — callers psum over the
    (ep, tp) axes when sharded.

    The ragged_dot grouped matmul wants rows sorted by group; rows routed to
    non-local experts sort to a tail past ``sum(group_sizes)`` whose output is
    unspecified-but-finite — their combine weight is zeroed so they never
    contribute."""
    T, H = xt.shape
    K = moe.top_k
    N = T * K
    hp = jax.lax.Precision.HIGHEST

    flat_e = idx.reshape(N)
    flat_t = jnp.repeat(jnp.arange(T, dtype=jnp.int32), K)
    local_e = flat_e - e_lo
    in_range = (local_e >= 0) & (local_e < e_count)
    sort_key = jnp.where(in_range, local_e, e_count).astype(jnp.int32)
    order = jnp.argsort(sort_key, stable=True)
    se = sort_key[order]  # sorted local expert ids (tail = e_count)
    st = flat_t[order]  # token row per sorted slot
    comb = jnp.where(in_range, weights.reshape(N), 0.0)[order]  # f32

    xs = jnp.take(xt, st, axis=0)  # (N, H)
    if moe.llama4_router:
        # llama4 scales the expert INPUT by the sigmoid score; combine weight 1
        xs = xs * comb[:, None].astype(xs.dtype)
        comb = jnp.where(comb > 0, 1.0, 0.0)
    group_sizes = jnp.bincount(se, length=e_count).astype(jnp.int32)

    se_c = jnp.minimum(se, e_count - 1)  # clipped for bias gathers
    gate = jax.lax.ragged_dot(xs, ew["gate_proj"]["w"], group_sizes, precision=hp)
    up = jax.lax.ragged_dot(xs, ew["up_proj"]["w"], group_sizes, precision=hp)
    if moe.expert_bias:
        gate = gate + ew["gate_proj"]["b"][se_c]
        up = up + ew["up_proj"]["b"][se_c]
    inner = _expert_act(moe, gate, up)
    rows = jax.lax.ragged_dot(inner, ew["down_proj"]["w"], group_sizes, precision=hp)
    if moe.expert_bias:
        rows = rows + (ew["down_proj"]["b"][se_c] * down_bias_on).astype(rows.dtype)

    rows = rows * comb[:, None].astype(rows.dtype)
    # un-sort back to (T, K) slots, then reduce over K — deterministic combine
    unsorted = jnp.zeros((N, H), rows.dtype).at[order].set(rows)
    return jnp.sum(unsorted.reshape(T, K, H), axis=1)


def _strip_mp_axes(spec: P) -> P:
    """Drop ep/tp axes from an activation spec (tokens replicate over the
    model-parallel world inside the sparse shard_map; dp/cp stay sharded)."""
    out = []
    for entry in spec:
        if entry is None:
            out.append(None)
            continue
        axes = tuple(a for a in (entry if isinstance(entry, (tuple, list)) else (entry,))
                     if a not in (AXIS_EP, AXIS_EPX, AXIS_TP))
        out.append(_axes_entry(axes))
    return P(*out)


def _sparse_moe(
    moe: MoEArch,
    experts: Dict[str, Any],  # dequantized expert weights (global view)
    x: jax.Array,  # (B, S, H)
    weights: jax.Array,  # (B, S, K) f32
    idx: jax.Array,  # (B, S, K) i32
    hidden_spec: P,
) -> jax.Array:
    """Dispatch the sparse expert FFN, sharded over the mesh when one is in
    scope. Token (dp/cp) axes stay data-parallel; expert/intermediate shards
    each compute a partial combined output and one psum over (ep, tp) merges
    them — the EP dispatch collective of the reference (moe_v2.py:135-161)."""
    e_axes = _expert_dim_axes(moe)
    i_axes = _inter_dim_axes(moe)
    mesh = jax.sharding.get_abstract_mesh()

    def local(ex, xb, wb, ib):
        B, S, H = xb.shape
        if e_axes:
            e_count = ex["gate_proj"]["w"].shape[0]
            e_lo = jax.lax.axis_index(e_axes) * e_count
        else:
            e_count = moe.num_experts
            e_lo = 0
        if i_axes:
            down_on = (jax.lax.axis_index(i_axes) == 0).astype(jnp.float32)
        else:
            down_on = 1.0
        out = _sparse_expert_ffn(
            moe, ex, xb.reshape(B * S, H), wb.reshape(B * S, -1),
            ib.reshape(B * S, -1), e_lo, e_count, down_on,
        )
        out = jax.lax.psum(out, AXIS_MP)
        return out.reshape(B, S, H)

    if mesh is None or mesh.empty or not set(AXIS_MP).issubset(mesh.axis_names):
        return _sparse_expert_ffn(
            moe,
            experts,
            x.reshape(-1, x.shape[-1]),
            weights.reshape(-1, moe.top_k),
            idx.reshape(-1, moe.top_k),
            0,
            moe.num_experts,
        ).reshape(x.shape)

    tok_spec = _strip_mp_axes(hidden_spec)
    tok2 = P(tok_spec[0] if len(tok_spec) > 0 else None,
             tok_spec[1] if len(tok_spec) > 1 else None, None)
    e = _axes_entry(e_axes)
    i = _axes_entry(i_axes)
    w_specs = {
        "gate_proj": {"w": P(e, None, i)},
        "up_proj": {"w": P(e, None, i)},
        "down_proj": {"w": P(e, i, None)},
    }
    if moe.expert_bias:
        w_specs["gate_proj"]["b"] = P(e, i)
        w_specs["up_proj"]["b"] = P(e, i)
        w_specs["down_proj"]["b"] = P(e, None)
    fn = jax.shard_map(
        local,
        mesh=mesh,
        in_specs=(w_specs, tok2, tok2, tok2),
        out_specs=tok2,
        check_vma=False,
    )
    return fn(experts, x, weights, idx)


def moe_block(
    arch, moe: MoEArch, p: Dict[str, Any], x: jax.Array, hidden_spec: Optional[P] = None
) -> jax.Array:
    """MoE feed-forward: (B, S, H) -> (B, S, H).

    Param leaves: router.w (H, E); experts.{gate,up}_proj.w (E, H, I),
    experts.down_proj.w (E, I, H); optional shared_expert mlp.
    """
    from nxdi_tpu.ops.quantization import materialize_weight as mat_w

    B, S, H = x.shape
    xt = x.reshape(B * S, H)

    router_logits = xt.astype(jnp.float32) @ p["router"]["w"].astype(jnp.float32)
    if moe.router_bias:
        router_logits = router_logits + p["router"]["b"].astype(jnp.float32)

    # per-phase hybrid: decode programs read the EP-heavy duplicated copy
    p_experts = p["experts"]
    if moe.per_phase_hybrid and moe.phase == "decode" and "experts_tkg" in p:
        p_experts = p["experts_tkg"]

    if moe.dispatch == "sparse":
        top_vals, top_idx = route_topk(router_logits, moe, p["router"])
        experts = {
            "gate_proj": {"w": mat_w(p_experts["gate_proj"], x.dtype)},
            "up_proj": {"w": mat_w(p_experts["up_proj"], x.dtype)},
            "down_proj": {"w": mat_w(p_experts["down_proj"], x.dtype)},
        }
        if moe.expert_bias:
            for k in experts:
                experts[k]["b"] = p_experts[k]["b"]
        out = _sparse_moe(
            moe,
            experts,
            x,
            top_vals.reshape(B, S, moe.top_k),
            top_idx.reshape(B, S, moe.top_k),
            hidden_spec if hidden_spec is not None else P(),
        ).reshape(B * S, H)
    else:
        weights = route(router_logits, moe, p["router"]).astype(x.dtype)  # (T, E)
        # dense dispatch: all experts on all tokens, combine contracted over E.
        # mat_w dequantizes low-bit expert weights in the einsum's operand read.
        gate = jnp.einsum("th,ehi->eti", xt, mat_w(p_experts["gate_proj"], x.dtype))
        up = jnp.einsum("th,ehi->eti", xt, mat_w(p_experts["up_proj"], x.dtype))
        if moe.llama4_router:
            # llama4 scales the expert INPUT by the sigmoid score. gate/up are
            # linear and bias-free on this path, so scaling their OUTPUTS before
            # the activation is identical (act(s*g(x)) where s*g(x) = g(s*x)) —
            # avoids materializing an (E, T, H) scaled-input tensor
            se = jnp.swapaxes(weights, 0, 1)[:, :, None].astype(gate.dtype)  # (E, T, 1)
            gate = gate * se
            up = up * se
        if moe.expert_bias:
            gate = gate + p_experts["gate_proj"]["b"][:, None, :]
            up = up + p_experts["up_proj"]["b"][:, None, :]
        inner = _expert_act(moe, gate, up)  # (E, T, I)
        expert_out = jnp.einsum("eti,eih->eth", inner, mat_w(p_experts["down_proj"], x.dtype))
        if moe.expert_bias:
            expert_out = expert_out + p_experts["down_proj"]["b"][:, None, :]
        if moe.llama4_router:
            out = jnp.sum(expert_out, axis=0)  # input already carries the score
        else:
            out = jnp.einsum("te,eth->th", weights, expert_out)  # psum over E under EP

    if moe.shared_expert_intermediate_size:
        from nxdi_tpu.models.base import ACT_FNS

        act = ACT_FNS[moe.hidden_act]
        sp = p["shared_expert"]
        shared = (
            act(xt @ mat_w(sp["gate_proj"], x.dtype)) * (xt @ mat_w(sp["up_proj"], x.dtype))
        ) @ mat_w(sp["down_proj"], x.dtype)
        if moe.shared_expert_gated:
            shared = jax.nn.sigmoid(
                xt.astype(jnp.float32) @ p["shared_expert_gate"]["w"].astype(jnp.float32)
            ).astype(shared.dtype) * shared
        out = out + shared

    return out.reshape(B, S, H)


def duplicate_per_phase_experts(obj):
    """Mirror every MoE ``experts`` subtree as ``experts_tkg`` in a HOST param
    pytree (reference: ``duplicate_and_replace_prefixes`` in the hybrid
    preshard hook — the decode regime gets its own sharded copy). Host arrays
    are shared; ``device_put`` lays each copy out under its own spec."""
    if isinstance(obj, dict):
        out = {k: duplicate_per_phase_experts(v) for k, v in obj.items()}
        if "router" in out and "experts" in out and "experts_tkg" not in out:
            out["experts_tkg"] = out["experts"]
        return out
    if isinstance(obj, (list, tuple)):
        return type(obj)(duplicate_per_phase_experts(v) for v in obj)
    return obj


def moe_shape_struct(moe: MoEArch, hidden_size: int, num_layers: int, dtype) -> Dict[str, Any]:
    """ShapeDtypeStruct pytree for layer-stacked MoE params."""

    def s(*shape):
        return jax.ShapeDtypeStruct((num_layers,) + shape, dtype)

    E, H, I = moe.num_experts, hidden_size, moe.intermediate_size
    struct: Dict[str, Any] = {
        "router": {"w": s(H, E)},
        "experts": {
            "gate_proj": {"w": s(E, H, I)},
            "up_proj": {"w": s(E, H, I)},
            "down_proj": {"w": s(E, I, H)},
        },
    }
    if moe.router_bias:
        struct["router"]["b"] = s(E)
    if moe.correction_bias:
        # f32 regardless of model dtype (selection-precision critical)
        struct["router"]["e_bias"] = jax.ShapeDtypeStruct((num_layers, E), jnp.float32)
    if moe.expert_bias:
        struct["experts"]["gate_proj"]["b"] = s(E, I)
        struct["experts"]["up_proj"]["b"] = s(E, I)
        struct["experts"]["down_proj"]["b"] = s(E, H)
    if moe.per_phase_hybrid:
        import copy

        struct["experts_tkg"] = copy.deepcopy(struct["experts"])
    if moe.shared_expert_intermediate_size:
        SI = moe.shared_expert_intermediate_size
        struct["shared_expert"] = {
            "gate_proj": {"w": s(H, SI)},
            "up_proj": {"w": s(H, SI)},
            "down_proj": {"w": s(SI, H)},
        }
        if moe.shared_expert_gated:
            struct["shared_expert_gate"] = {"w": s(H, 1)}
    return struct
