"""Mixture-of-Experts ops — router + expert MLPs, expert-parallel over the mesh.

Reference: modules/moe_v2.py:23-132 assembles RouterTopK + ExpertMLPsV2 +
SharedExperts into an MoE wrapper, with TPxEP process groups (:135-161) and
NKI blockwise-matmul kernels. TPU-native the same structure is:

  - **Router**: one replicated linear -> full softmax -> top-k -> (optional)
    renormalize, exactly HF's semantics so logits match the CPU golden.
  - **Experts**: dense dispatch. Every expert runs on every token; the per-token
    combine weight is zero for unselected experts. No gather/scatter, no
    capacity limits, no dynamic shapes — the einsum over the expert dim maps
    straight onto the MXU, and the combine contraction is exact.
  - **Parallelism**: the expert dim is sharded over the ``tp`` mesh axis when it
    divides (expert parallelism: each device holds E/tp full experts; the
    combine einsum contracts over experts so GSPMD inserts one psum — the
    reference's EP dispatch AR/RS collectives, attention_base.py:179).
    Otherwise the intermediate dim is sharded (expert-internal TP, the
    reference's moe_tp_degree).

Dense dispatch costs E/topk x the active-expert FLOPs. That is the right first
trade on TPU: decode is HBM-bound on expert *weights*, which any-expert routing
must stream anyway; a ragged/sorted dispatch kernel is a later optimization
(PAPERS.md megablocks lineage) that slots in behind this same interface.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from nxdi_tpu.parallel.mesh import AXIS_TP


@dataclass(frozen=True)
class MoEArch:
    """Static MoE architecture description (hashable; part of DecoderArch)."""

    num_experts: int
    top_k: int
    intermediate_size: int  # per-expert intermediate
    hidden_act: str = "silu"
    norm_topk_prob: bool = True  # renormalize top-k weights (mixtral: always)
    # expert-parallel over tp axis (family builder sets this when tp | E);
    # False -> expert-internal TP on the intermediate dim
    ep: bool = False
    # shared (always-on) experts, qwen2-moe/llama4 style
    shared_expert_intermediate_size: Optional[int] = None
    shared_expert_gated: bool = False  # sigmoid(gate(x)) scaling on shared out
    # gpt-oss variants (reference: models/gpt_oss/modeling_gpt_oss.py): router
    # takes top-k of LOGITS then softmaxes the selected values; experts carry
    # biases and use the clamped glu  (up+1) * gate*sigmoid(alpha*gate)
    topk_softmax: bool = False
    # llama4 (reference: models/llama4/): top-k logits -> sigmoid scores that
    # scale the expert INPUT (not output); shared expert always added
    llama4_router: bool = False
    router_bias: bool = False
    expert_bias: bool = False
    gptoss_glu: bool = False
    glu_limit: Optional[float] = None
    glu_alpha: float = 1.702


def ep_policy(tp_degree: int, num_experts: int) -> bool:
    """Shared EP-vs-TP decision for family builders: expert parallelism when
    the tp world divides the expert count."""
    return tp_degree > 1 and num_experts % tp_degree == 0


def convert_hf_experts(get, cast, num_experts: int, router_key: str, expert_fmt) -> Dict[str, Any]:
    """Stack per-expert HF weights into the (E, in, out) layout ops/moe.py
    consumes. ``expert_fmt(j, proj)`` yields the HF key for expert j's
    gate/up/down projection."""
    import numpy as np

    gate = np.stack([get(expert_fmt(j, "gate")).T for j in range(num_experts)])
    up = np.stack([get(expert_fmt(j, "up")).T for j in range(num_experts)])
    down = np.stack([get(expert_fmt(j, "down")).T for j in range(num_experts)])
    return {
        "router": {"w": cast(get(router_key).T)},
        "experts": {
            "gate_proj": {"w": cast(gate)},
            "up_proj": {"w": cast(up)},
            "down_proj": {"w": cast(down)},
        },
    }


def expert_parallel_specs(moe: MoEArch) -> Dict[str, Any]:
    """PartitionSpecs for one layer's MoE params (pre-layer-stacking).

    EP when ``moe.ep`` (family builder sets it when tp divides the expert
    count), else TP on the expert intermediate (reference: moe_ep_degree vs
    moe_tp_degree, config.py:603).
    """
    if moe.ep:
        expert_spec = {
            "gate_proj": {"w": P(AXIS_TP, None, None)},
            "up_proj": {"w": P(AXIS_TP, None, None)},
            "down_proj": {"w": P(AXIS_TP, None, None)},
        }
        if moe.expert_bias:
            for k in expert_spec:
                expert_spec[k]["b"] = P(AXIS_TP, None)
    else:
        expert_spec = {
            "gate_proj": {"w": P(None, None, AXIS_TP)},
            "up_proj": {"w": P(None, None, AXIS_TP)},
            "down_proj": {"w": P(None, AXIS_TP, None)},
        }
        if moe.expert_bias:
            expert_spec["gate_proj"]["b"] = P(None, AXIS_TP)
            expert_spec["up_proj"]["b"] = P(None, AXIS_TP)
            expert_spec["down_proj"]["b"] = P()
    specs: Dict[str, Any] = {
        "router": {"w": P()},
        "experts": expert_spec,
    }
    if moe.router_bias:
        specs["router"]["b"] = P()
    if moe.shared_expert_intermediate_size:
        specs["shared_expert"] = {
            "gate_proj": {"w": P(None, AXIS_TP)},
            "up_proj": {"w": P(None, AXIS_TP)},
            "down_proj": {"w": P(AXIS_TP, None)},
        }
        if moe.shared_expert_gated:
            specs["shared_expert_gate"] = {"w": P()}
    return specs


def route(router_logits: jax.Array, moe: MoEArch) -> jax.Array:
    """Router logits (T, E) -> dense combine weights (T, E), zero for
    unselected experts (HF Mixtral/Qwen3Moe semantics: full softmax -> top-k ->
    optional renormalize; reference: RouterTopK in moe_v2.py:23)."""
    if moe.llama4_router:
        top_vals, top_idx = jax.lax.top_k(router_logits.astype(jnp.float32), moe.top_k)
        scores = jax.nn.sigmoid(top_vals)
        dense = jnp.sum(
            jax.nn.one_hot(top_idx, moe.num_experts, dtype=scores.dtype)
            * scores[..., None],
            axis=-2,
        )
        return dense
    if moe.topk_softmax:
        # gpt-oss: top-k on raw logits, softmax over the k selected values
        top_vals, top_idx = jax.lax.top_k(router_logits.astype(jnp.float32), moe.top_k)
        top_vals = jax.nn.softmax(top_vals, axis=-1)
    else:
        probs = jax.nn.softmax(router_logits.astype(jnp.float32), axis=-1)
        top_vals, top_idx = jax.lax.top_k(probs, moe.top_k)  # (T, K)
        if moe.norm_topk_prob:
            top_vals = top_vals / jnp.sum(top_vals, axis=-1, keepdims=True)
    dense = jnp.sum(
        jax.nn.one_hot(top_idx, moe.num_experts, dtype=top_vals.dtype)
        * top_vals[..., None],
        axis=-2,
    )  # (T, E)
    return dense


def moe_block(arch, moe: MoEArch, p: Dict[str, Any], x: jax.Array) -> jax.Array:
    """MoE feed-forward: (B, S, H) -> (B, S, H).

    Param leaves: router.w (H, E); experts.{gate,up}_proj.w (E, H, I),
    experts.down_proj.w (E, I, H); optional shared_expert mlp.
    """
    from nxdi_tpu.models.base import ACT_FNS

    act = ACT_FNS[moe.hidden_act]
    B, S, H = x.shape
    xt = x.reshape(B * S, H)

    from nxdi_tpu.ops.quantization import materialize_weight as mat_w

    router_logits = xt.astype(jnp.float32) @ p["router"]["w"].astype(jnp.float32)
    if moe.router_bias:
        router_logits = router_logits + p["router"]["b"].astype(jnp.float32)
    weights = route(router_logits, moe).astype(x.dtype)  # (T, E)

    # dense dispatch: all experts on all tokens, combine contracted over E.
    # mat_w dequantizes low-bit expert weights in the einsum's operand read.
    gate = jnp.einsum("th,ehi->eti", xt, mat_w(p["experts"]["gate_proj"], x.dtype))
    up = jnp.einsum("th,ehi->eti", xt, mat_w(p["experts"]["up_proj"], x.dtype))
    if moe.llama4_router:
        # llama4 scales the expert INPUT by the sigmoid score. gate/up are
        # linear and bias-free on this path, so scaling their OUTPUTS before
        # the activation is identical (act(s*g(x)) where s*g(x) = g(s*x)) —
        # avoids materializing an (E, T, H) scaled-input tensor
        se = jnp.swapaxes(weights, 0, 1)[:, :, None].astype(gate.dtype)  # (E, T, 1)
        gate = gate * se
        up = up * se
    if moe.expert_bias:
        gate = gate + p["experts"]["gate_proj"]["b"][:, None, :]
        up = up + p["experts"]["up_proj"]["b"][:, None, :]
    if moe.gptoss_glu:
        if moe.glu_limit is not None:
            gate = jnp.minimum(gate, moe.glu_limit)
            up = jnp.clip(up, -moe.glu_limit, moe.glu_limit)
        inner = (up + 1.0) * (gate * jax.nn.sigmoid(gate * moe.glu_alpha))
    else:
        inner = act(gate) * up  # (E, T, I)
    expert_out = jnp.einsum("eti,eih->eth", inner, mat_w(p["experts"]["down_proj"], x.dtype))
    if moe.expert_bias:
        expert_out = expert_out + p["experts"]["down_proj"]["b"][:, None, :]
    if moe.llama4_router:
        out = jnp.sum(expert_out, axis=0)  # input already carries the score
    else:
        out = jnp.einsum("te,eth->th", weights, expert_out)  # psum over E under EP

    if moe.shared_expert_intermediate_size:
        sp = p["shared_expert"]
        shared = (
            act(xt @ mat_w(sp["gate_proj"], x.dtype)) * (xt @ mat_w(sp["up_proj"], x.dtype))
        ) @ mat_w(sp["down_proj"], x.dtype)
        if moe.shared_expert_gated:
            shared = jax.nn.sigmoid(
                xt.astype(jnp.float32) @ p["shared_expert_gate"]["w"].astype(jnp.float32)
            ).astype(shared.dtype) * shared
        out = out + shared

    return out.reshape(B, S, H)


def moe_shape_struct(moe: MoEArch, hidden_size: int, num_layers: int, dtype) -> Dict[str, Any]:
    """ShapeDtypeStruct pytree for layer-stacked MoE params."""

    def s(*shape):
        return jax.ShapeDtypeStruct((num_layers,) + shape, dtype)

    E, H, I = moe.num_experts, hidden_size, moe.intermediate_size
    struct: Dict[str, Any] = {
        "router": {"w": s(H, E)},
        "experts": {
            "gate_proj": {"w": s(E, H, I)},
            "up_proj": {"w": s(E, H, I)},
            "down_proj": {"w": s(E, I, H)},
        },
    }
    if moe.router_bias:
        struct["router"]["b"] = s(E)
    if moe.expert_bias:
        struct["experts"]["gate_proj"]["b"] = s(E, I)
        struct["experts"]["up_proj"]["b"] = s(E, I)
        struct["experts"]["down_proj"]["b"] = s(E, H)
    if moe.shared_expert_intermediate_size:
        SI = moe.shared_expert_intermediate_size
        struct["shared_expert"] = {
            "gate_proj": {"w": s(H, SI)},
            "up_proj": {"w": s(H, SI)},
            "down_proj": {"w": s(SI, H)},
        }
        if moe.shared_expert_gated:
            struct["shared_expert_gate"] = {"w": s(H, 1)}
    return struct
