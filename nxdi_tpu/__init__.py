"""nxdi_tpu — a TPU-native LLM inference framework.

Brand-new JAX/XLA/Pallas implementation of the capability surface of
``neuronx-distributed-inference`` (AWS NxD Inference): bucketed AOT-compiled
submodels (context encoding / token generation / speculation), device-resident
KV cache, tensor/context/expert parallelism over an ICI mesh, on-device
sampling, speculative decoding, quantization, LoRA serving, and a
HuggingFace-compatible generation API. See SURVEY.md at the repo root.
"""

__version__ = "0.1.0"

from nxdi_tpu import jax_compat as _jax_compat

_jax_compat.ensure()

from nxdi_tpu.config import (  # noqa: F401
    InferenceConfig,
    OnDeviceSamplingConfig,
    TpuConfig,
)
