"""HuggingFace-compatible generation driver.

The analog of the reference's ``HuggingFaceGenerationAdapter``
(utils/hf_adapter.py:115): a CPU-side loop that makes a compiled TPU
application behave like ``model.generate(...)`` — right-padding aware, KV-cache
aware, on-device sampling aware. One CTE dispatch for the prompt, then one TKG
dispatch per generated token (reference ``_sample`` :150).

``load_pretrained_config`` adapts a HF ``config.json`` into the kwargs an
:class:`InferenceConfig` expects (reference: hf_adapter.py:36).
"""

from __future__ import annotations

import json
import logging
import os
from dataclasses import dataclass
from typing import List, Optional

import jax
import numpy as np

from nxdi_tpu.ops.sampling import (
    SamplingParams,
    StepRngSchedule,
    extract_next_tokens,
    normalize_eos_ids,
)

logger = logging.getLogger("nxdi_tpu")


def load_pretrained_config(model_path: str):
    """Returns a callable giving the HF config dict (reference: hf_adapter.py:36)."""

    def load():
        cfg_path = os.path.join(model_path, "config.json")
        with open(cfg_path) as f:
            cfg = json.load(f)
        # flatten nested text_config style entries are model-family concerns;
        # here we pass the dict through.
        return cfg

    return load


@dataclass
class GenerationConfigLite:
    max_new_tokens: Optional[int] = None
    max_length: Optional[int] = None
    do_sample: bool = False
    top_k: int = 1
    top_p: float = 1.0
    temperature: float = 1.0
    eos_token_id: Optional[object] = None  # int or list
    pad_token_id: int = 0
    seed: int = 0


class HuggingFaceGenerationAdapter:
    def __init__(self, app):
        self.app = app
        self.config = app.config
        self.tpu_config = app.tpu_config

    def generate(self, *args, **kwargs) -> np.ndarray:
        """Greedy/sampling generation. Returns (B, S + new_tokens) ids, with each
        row's generated tokens appended after its true prompt (right-padding in
        the prompt region is preserved, like the reference's right-pad support).
        See :meth:`_generate` for the parameters.

        Telemetry: one request span (``app.telemetry``) covers this batched
        call — phases pad -> prefill -> decode, TTFT at the first token fetch,
        TPOT per generated token (window loops attribute their per-token
        mean), tokens in/out counters. ``tokens_out`` counts emitted decode
        positions, including a row's post-EOS padding inside the batch.
        """
        import time as _time

        tel = getattr(self.app, "telemetry", None)
        if tel is not None and tel.enabled:
            span, clock = tel.start_request(), tel.clock
        else:
            from nxdi_tpu.telemetry.spans import NULL_SPAN

            span, clock = NULL_SPAN, _time.perf_counter
        try:
            return self._generate(span, clock, *args, **kwargs)
        finally:
            # idempotent (success paths already finished): this closes the
            # span when generate RAISES (prompt too long, dispatch error), so
            # failed requests still count and render in the Perfetto trace
            span.finish()

    def _generate(
        self,
        span,
        clock,
        input_ids: np.ndarray,  # (B, S) right-padded
        attention_mask: Optional[np.ndarray] = None,
        max_new_tokens: Optional[int] = None,
        max_length: Optional[int] = None,
        do_sample: bool = False,
        top_k: int = 1,
        top_p: float = 1.0,
        temperature: float = 1.0,
        eos_token_id=None,
        pad_token_id: int = 0,
        seed: int = 0,
        adapter_ids: Optional[np.ndarray] = None,
        pixel_values: Optional[np.ndarray] = None,
        image_attention_mask: Optional[np.ndarray] = None,
        logits_processor=None,
        generation_config=None,
        **unused,
    ) -> np.ndarray:
        span.phase("pad")
        # HF GenerationConfig passthrough (reference: hf_adapter.py generation
        # config plumbing): config values act as defaults for unset args
        if generation_config is not None:
            gc = generation_config
            if max_new_tokens is None:
                max_new_tokens = getattr(gc, "max_new_tokens", None)
            # HF GenerationConfig carries a DEFAULT max_length=20; only honor
            # it when max_new_tokens is genuinely unset
            if max_length is None and max_new_tokens is None:
                max_length = getattr(gc, "max_length", None)
            if not do_sample:
                do_sample = bool(getattr(gc, "do_sample", False))
            if top_k == 1 and getattr(gc, "top_k", None):
                top_k = gc.top_k
            if top_p == 1.0 and getattr(gc, "top_p", None):
                top_p = gc.top_p
            if temperature == 1.0 and getattr(gc, "temperature", None):
                temperature = gc.temperature
            if eos_token_id is None:
                eos_token_id = getattr(gc, "eos_token_id", None)
            if pad_token_id == 0 and getattr(gc, "pad_token_id", None) is not None:
                pad_token_id = gc.pad_token_id
        if logits_processor:
            # host-side logits interception (reference: LogitsProcessorList
            # support in the HF adapter): tokens are selected on host from the
            # compiled model's full logits, so the program must emit them
            if not self.tpu_config.output_logits:
                raise ValueError(
                    "logits_processor needs host-visible logits: compile with "
                    "TpuConfig(output_logits=True)"
                )
            if getattr(self.app, "is_fused_spec", False):
                raise ValueError(
                    "logits_processor is incompatible with fused speculation "
                    "(tokens are selected inside the compiled window)"
                )
        input_ids = np.asarray(input_ids)
        B, S = input_ids.shape
        if attention_mask is None:
            attention_mask = (input_ids != pad_token_id).astype(np.int32)
            # all-pad rows would break length math; treat fully-pad as len 1
        lengths = attention_mask.sum(axis=1).astype(np.int32)
        lengths = np.maximum(lengths, 1)

        if max_length is None:
            max_length = (
                int(lengths.max()) + max_new_tokens
                if max_new_tokens is not None
                else self.tpu_config.seq_len
            )
        if int(lengths.max()) > self.tpu_config.max_context_length:
            raise ValueError(
                f"prompt length {int(lengths.max())} exceeds max_context_length "
                f"{self.tpu_config.max_context_length} (largest context-encoding "
                "bucket); recompile with a larger max_context_length"
            )
        span.add_tokens_in(int(lengths.sum()))
        max_length = min(max_length, self.tpu_config.seq_len)
        n_new = max_length - int(lengths.max())
        if n_new <= 0:
            span.finish()
            return input_ids

        eos_ids = normalize_eos_ids(eos_token_id)

        odsc = self.tpu_config.on_device_sampling_config
        compiled_do_sample = bool(odsc and odsc.do_sample)
        if do_sample and not compiled_do_sample and not logits_processor:
            # (with logits_processor, sampling runs on HOST from the emitted
            # logits, so the compiled sampler mode is irrelevant)
            logger.warning(
                "generate(do_sample=True) requested but the model was compiled "
                "without on-device sampling (OnDeviceSamplingConfig(do_sample="
                "True)); falling back to greedy."
            )
        self._rng = StepRngSchedule(seed)

        # ONE sampling-row rule with the serving engine (serving/request.py):
        # both paths build their (top_k, top_p, temperature) rows through
        # SamplingParams, so greedy coercion can never diverge between the
        # static batch adapter and the continuous-batching engine
        sampling_params = SamplingParams(
            max_new_tokens=n_new,
            do_sample=do_sample,
            top_k=top_k,
            top_p=top_p,
            temperature=temperature,
        ).tensor(B)

        lora_kwargs = {}
        if adapter_ids is not None:
            lora_kwargs["adapter_ids"] = np.asarray(adapter_ids, dtype=np.int32)

        # ---- context encoding (multimodal prefill carries pixel_values) ----
        cte_kwargs = dict(lora_kwargs)
        if pixel_values is not None:
            cte_kwargs["pixel_values"] = pixel_values
        if image_attention_mask is not None:
            # idefics: (B, S, num_images) per-prompt image visibility; decode
            # steps reuse the last prompt row inside the application
            cte_kwargs["image_attention_mask"] = image_attention_mask
        position_ids = np.tile(np.arange(S, dtype=np.int32), (B, 1))
        span.phase("prefill")
        outputs = self.app.forward(
            input_ids.astype(np.int32),
            position_ids,
            last_token_index=lengths - 1,
            sampling_params=sampling_params,
            rng=self._next_rng(),
            **cte_kwargs,
        )
        running = input_ids.copy() if logits_processor else None
        if logits_processor:
            next_tokens = self._host_select(
                outputs, running, logits_processor, do_sample, top_k, top_p,
                temperature, lengths=lengths, prompt_width=S,
                pad_token_id=pad_token_id,
            )
            running = np.concatenate([running, next_tokens[:, None]], axis=1)
        else:
            next_tokens = self._next_tokens(outputs)
        span.first_token()
        span.tokens(B)
        span.phase("decode")
        _td0 = clock()

        generated: List[np.ndarray] = [next_tokens]
        finished = np.zeros((B,), dtype=bool)
        for e in eos_ids:
            finished |= next_tokens == e

        if getattr(self.app, "is_fused_spec", False) and do_sample:
            logger.warning(
                "fused speculation decodes greedily (draft proposal + target "
                "verification are argmax); do_sample=True request falls back "
                "to greedy."
            )
        if getattr(self.app, "is_fused_spec", False) and not finished.all():
            gen = self._fused_spec_decode(
                next_tokens, lengths, n_new, eos_ids, pad_token_id, sampling_params, B,
                lora_kwargs=lora_kwargs,
            )
            span.tokens(gen.size - B, clock() - _td0)
            span.finish()
            return self._assemble(input_ids, gen, lengths, pad_token_id)

        # multi-step decode: the tkg_multistep submodel retires K tokens per
        # dispatch (in-graph sample/advance/commit scan, models/base.py
        # multi_step_token_gen); windows chain device-resident with the same
        # lag-1 fetch pipeline as the 1-step async loop. Host-side logits
        # interception and per-request adapters cannot ride the scan.
        from nxdi_tpu.runtime.model_wrapper import MULTISTEP_EOS_SLOTS

        if (
            getattr(self.app, "multistep_supported", False)
            and not finished.all()
            and not lora_kwargs
            and not logits_processor
            and len(eos_ids) <= MULTISTEP_EOS_SLOTS
        ):
            gen = self._multistep_decode_loop(
                next_tokens, lengths, n_new, eos_ids, pad_token_id,
                sampling_params, B,
                cte_next_inputs=outputs.get("next_inputs"),
            )
            span.tokens(gen.size - B, clock() - _td0)
            span.finish()
            return self._assemble(input_ids, gen, lengths, pad_token_id)

        # per-request adapters are host-side state the device decode loop
        # cannot carry; fall back to the sync loop when they are in play
        if (
            self.app.async_supported
            and "next_inputs" in outputs
            and not finished.all()
            and not lora_kwargs
            and not logits_processor
        ):
            gen = self._device_decode_loop(
                outputs["next_inputs"], next_tokens, lengths, n_new, eos_ids, pad_token_id, B
            )
            span.tokens(gen.size - B, clock() - _td0)
            span.finish()
            return self._assemble(input_ids, gen, lengths, pad_token_id)

        # ---- token generation loop ----
        cur_pos = lengths.copy()  # position of the next token to write
        _tstep = clock()
        for _ in range(n_new - 1):
            if finished.all():
                break
            step_inputs = next_tokens[:, None].astype(np.int32)
            outputs = self.app.forward(
                step_inputs,
                cur_pos[:, None].astype(np.int32),
                last_token_index=np.zeros((B,), dtype=np.int32),
                sampling_params=sampling_params,
                rng=self._next_rng(),
                **lora_kwargs,
            )
            if logits_processor:
                next_tokens = self._host_select(
                    outputs, running, logits_processor, do_sample, top_k, top_p,
                    temperature, lengths=lengths, prompt_width=S,
                    pad_token_id=pad_token_id,
                )
                running = np.concatenate([running, next_tokens[:, None]], axis=1)
            else:
                next_tokens = self._next_tokens(outputs)
            next_tokens = np.where(finished, pad_token_id, next_tokens)
            generated.append(next_tokens)
            _now = clock()
            span.tokens(B, _now - _tstep)
            _tstep = _now
            for e in eos_ids:
                finished |= next_tokens == e
            cur_pos = cur_pos + 1

        gen = np.stack(generated, axis=1)  # (B, T)
        span.finish()
        return self._assemble(input_ids, gen, lengths, pad_token_id)

    def _host_select(
        self, outputs, running, processors, do_sample, top_k, top_p, temperature,
        lengths=None, prompt_width=None, pad_token_id=0,
    ) -> np.ndarray:
        """Apply host logits processors, then pick tokens on host (reference:
        the HF adapter's LogitsProcessorList flow).

        ``running`` is the right-padded prompt with generated tokens appended
        past ``prompt_width``. Ids-dependent processors (repetition penalty,
        no-repeat-ngram) must not see pad tokens as context, so each row is
        rebuilt LEFT-padded from its true length — the layout HF's own
        generate feeds processors."""
        import torch

        logits = np.asarray(outputs["logits"])[:, -1, :].astype(np.float32)
        scores = torch.tensor(logits)
        running = np.asarray(running)
        if lengths is not None and prompt_width is not None:
            B, W = running.shape
            ids_np = np.full_like(running, pad_token_id)
            for b in range(B):
                true = np.concatenate(
                    [running[b, : lengths[b]], running[b, prompt_width:]]
                )
                ids_np[b, W - true.shape[0]:] = true
        else:
            ids_np = running
        ids = torch.tensor(ids_np, dtype=torch.long)
        for proc in processors:
            scores = proc(ids, scores)
        scores = scores.numpy()
        if not do_sample:
            return scores.argmax(-1).astype(np.int64)
        # ONE sampling semantics: route the processed logits through the same
        # sampler the compiled programs use (ops/sampling.py)
        from nxdi_tpu.ops import sampling as sampling_ops
        from nxdi_tpu.ops.sampling import prepare_sampling_params

        B = scores.shape[0]
        sp = prepare_sampling_params(
            B, top_k=[top_k], top_p=[top_p], temperature=[temperature]
        )
        toks = sampling_ops.sample(
            scores, sp, rng=self._next_rng(), do_sample=True
        )
        return np.asarray(toks).astype(np.int64)

    def _assemble(self, input_ids, gen, lengths, pad_token_id) -> np.ndarray:
        """Place generated tokens immediately after each row's true length."""
        B, S = input_ids.shape
        T = gen.shape[1]
        out = np.full((B, S + T), pad_token_id, dtype=input_ids.dtype)
        out[:, :S] = input_ids
        for b in range(B):
            out[b, lengths[b] : lengths[b] + T] = gen[b]
        return out

    def _device_decode_loop(
        self, next_inputs, first_tokens, lengths, n_new, eos_ids, pad_token_id, B
    ) -> np.ndarray:
        """Device-resident decode: each step's outputs feed the next step with
        no host round trip; EOS is checked with a one-step lag so the fetch of
        step N-1 overlaps step N's execution (the reference's 2-deep async
        pipeline, async_execution.py:190)."""
        token_stream = [first_tokens]  # step 0 already on host
        device_stream = []
        finished = np.zeros((B,), dtype=bool)
        for e in eos_ids:
            finished |= first_tokens == e
        max_len0 = int(lengths.max())

        for step in range(1, n_new):
            # query position this step = lengths + step - 1 -> window = max+1
            outputs = self.app.token_gen_device(next_inputs, max_len0 + step)
            next_inputs = outputs["next_inputs"]
            device_stream.append(outputs["tokens"])
            # lag-1 EOS: fetch the PREVIOUS step's tokens (ready or nearly so)
            if len(device_stream) >= 2:
                prev = np.asarray(jax.device_get(device_stream[-2]))[:B, 0]
                token_stream.append(prev)
                for e in eos_ids:
                    finished |= prev == e
                if finished.all():
                    device_stream = device_stream[-1:]
                    break
        for dev in device_stream[-1:] if device_stream else []:
            tok = np.asarray(jax.device_get(dev))[:B, 0]
            token_stream.append(tok)

        gen = np.stack(token_stream[:n_new], axis=1)
        return self._mask_and_trim_eos(gen, eos_ids, pad_token_id)

    @staticmethod
    def _mask_and_trim_eos(gen, eos_ids, pad_token_id) -> np.ndarray:
        """Pad-mask tokens sampled after each row's EOS, then trim the
        device pipelines' overshoot past the all-finished point so the output
        length matches the sync loop exactly (shared by the 1-step async and
        multi-step window loops)."""
        if not eos_ids:
            return gen
        B = gen.shape[0]
        first_eos = []
        for b in range(B):
            hits = [i for i, t in enumerate(gen[b]) if t in eos_ids]
            if hits:
                gen[b, hits[0] + 1 :] = pad_token_id
            first_eos.append(hits[0] if hits else gen.shape[1] - 1)
        return gen[:, : max(first_eos) + 1]

    def _multistep_decode_loop(
        self, first_tokens, lengths, n_new, eos_ids, pad_token_id,
        sampling_params, B, cte_next_inputs=None,
    ) -> np.ndarray:
        """Decode striding by K tokens per dispatch (tkg_multistep submodel).

        Window j+1 is dispatched device-resident (its inputs are window j's
        on-device next_inputs) BEFORE window j's tokens are fetched — the same
        one-window-lag pipeline as :meth:`_device_decode_loop`, so the host
        fetch overlaps the next window's execution. The step ladder picks the
        smallest compiled rung covering the remaining budget, so tail windows
        don't burn a full-K scan; any overshoot tokens are trimmed here
        exactly like the 1-step loops trim post-EOS samples.
        """
        from nxdi_tpu.runtime.model_wrapper import (
            MULTISTEP_EOS_SLOTS,
            TAG_TOKEN_GENERATION_MULTISTEP,
            decode_window_limit,
        )

        w = self.app.models[TAG_TOKEN_GENERATION_MULTISTEP]
        window_limit = decode_window_limit(self.tpu_config, self.app.models)
        remaining = n_new - 1
        token_stream = [first_tokens]  # (B,) columns; step 0 from the CTE
        finished = np.zeros((B,), dtype=bool)
        for e in eos_ids:
            finished |= first_tokens == e
        if remaining <= 0 or finished.all():
            return np.stack(token_stream, axis=1)

        steps = w.select_steps(remaining)
        max_len0 = int(lengths.max())
        # window 0 starts device-resident straight off the CTE's next_inputs —
        # zero host round trips, and the split-chained rng schedule is exactly
        # the 1-step async chain's. The CTE always emits next_inputs for
        # multistep apps (runtime/application.py enable_models; config
        # validation forces on-device sampling), so this is never absent.
        assert cte_next_inputs is not None, (
            "multistep decode needs the CTE's device-resident next_inputs"
        )
        import jax.numpy as jnp

        Bc = w.batch_size
        eos_arr = np.full((Bc, MULTISTEP_EOS_SLOTS), -1, dtype=np.int32)
        for j, e in enumerate(eos_ids):
            eos_arr[:B, j] = e
        dev_batch = dict(cte_next_inputs)
        dev_batch["eos_token_ids"] = jnp.asarray(eos_arr)
        dev_batch["pad_token_id"] = jnp.full((Bc,), pad_token_id, jnp.int32)
        total_len = min(max_len0 + 1 + steps, window_limit)
        outputs = self.app.token_gen_multistep_device(
            dev_batch, total_len, steps=steps
        )
        device_stream = [outputs["tokens"]]  # (B, K_j) device arrays
        nxt = outputs["next_inputs"]
        produced = steps

        while produced < remaining and not finished.all():
            s = w.select_steps(remaining - produced)
            total_len = min(max_len0 + 1 + produced + s, window_limit)
            outputs = self.app.token_gen_multistep_device(nxt, total_len, steps=s)
            nxt = outputs["next_inputs"]
            device_stream.append(outputs["tokens"])
            produced += s
            # lag-1: fetch the PREVIOUS window while this one executes
            prev = np.asarray(jax.device_get(device_stream[-2]))[:B]
            token_stream.extend(prev.T)
            for e in eos_ids:
                finished |= (prev == e).any(axis=1)
            if finished.all():
                break
        last = np.asarray(jax.device_get(device_stream[-1]))[:B]
        token_stream.extend(last.T)

        gen = np.stack(token_stream, axis=1)[:, :n_new]
        return self._mask_and_trim_eos(gen, eos_ids, pad_token_id)

    def _fused_spec_decode(
        self, first_tokens, lengths, n_new, eos_ids, pad_token_id, sampling_params, B,
        lora_kwargs=None,
    ) -> np.ndarray:
        """Multi-token decode via fused speculation (reference:
        hf_adapter.py:515 ``_fused_assisted_decoding``): each dispatch retires
        counts[b] tokens per row; rows advance at different rates, so per-row
        positions are tracked host-side. Returns (B, T<=n_new) including the
        context-encoding token, padded after each row's EOS."""
        eos_set = set(int(e) for e in eos_ids)
        rows = [[int(first_tokens[b])] for b in range(B)]
        finished = np.array(
            [rows[b][0] in eos_set or n_new <= 1 for b in range(B)], dtype=bool
        )
        cur_tok = np.array(first_tokens, dtype=np.int32)
        cur_pos = lengths.astype(np.int32).copy()  # position of cur_tok
        from nxdi_tpu.runtime.model_wrapper import decode_window_limit

        window_limit = decode_window_limit(self.tpu_config, self.app.models)

        tel = getattr(self.app, "telemetry", None)
        if tel is not None and not tel.enabled:
            tel = None
        while not finished.all():
            outputs = self.app.forward(
                cur_tok[:, None],
                cur_pos[:, None],
                last_token_index=np.zeros((B,), dtype=np.int32),
                sampling_params=sampling_params,
                **(lora_kwargs or {}),
            )
            toks = np.asarray(jax.device_get(outputs["tokens"]))  # (B, k+1)
            cnts = np.asarray(jax.device_get(outputs["counts"]))  # (B,)
            if tel is not None:
                tel.record_spec_window(
                    (int(c) for c, f in zip(cnts, finished) if not f),
                    path=getattr(self.app, "spec_telemetry_path", "fused"),
                )
            for b in range(B):
                if finished[b]:
                    continue
                # token j sits at position cur_pos+1+j; tokens at positions
                # >= the compiled window were computed against dropped KV
                # writes — discard them (a row can still fill to the last slot)
                c = min(int(cnts[b]), window_limit - 1 - int(cur_pos[b]))
                if c <= 0:
                    finished[b] = True
                    continue
                for j in range(c):
                    t = int(toks[b, j])
                    rows[b].append(t)
                    if t in eos_set or len(rows[b]) >= n_new:
                        finished[b] = True
                        break
                cur_tok[b] = toks[b, c - 1]
                cur_pos[b] += c

        T = min(n_new, max(len(r) for r in rows))
        gen = np.full((B, T), pad_token_id, dtype=np.int64)
        for b in range(B):
            r = rows[b][:T]
            gen[b, : len(r)] = r
        return gen

    def _next_rng(self) -> np.ndarray:
        return self._rng.next()

    def _next_tokens(self, outputs) -> np.ndarray:
        # shared with the serving engine (ops/sampling.py): ONE extraction
        # rule, ONE rng schedule — fixed-seed decode cannot diverge
        return extract_next_tokens(outputs)
