"""HF checkpoint ingestion and saving.

Loads a HuggingFace-style checkpoint directory (safetensors, sharded safetensors
with an index, or pytorch ``.bin``) into a flat ``{name: np.ndarray}`` dict, and
saves state dicts back out as safetensors.

Reference behavior being reproduced (not ported line-by-line):
  - modules/checkpoint.py:24 ``load_state_dict`` — dir containing
    ``model.safetensors`` | ``model.safetensors.index.json`` | ``pytorch_model.bin``(+index)
  - modules/checkpoint.py:171 ``save_state_dict_safetensors`` with sharding by size
  - modules/checkpoint.py:202 ``create_n_layer_checkpoint`` for tiny test models

All tensors come back as numpy (host) arrays; device placement and sharding are
the runtime's job (parallel/mesh.py), keeping IO independent of jax state.
"""

from __future__ import annotations

import json
import os
import re
from typing import Dict, Iterable

import numpy as np

SAFETENSORS_MODEL = "model.safetensors"
SAFETENSORS_INDEX = "model.safetensors.index.json"
PYTORCH_MODEL = "pytorch_model.bin"
PYTORCH_INDEX = "pytorch_model.bin.index.json"

# torch is CPU-only in this image and used strictly for .bin deserialization and
# bf16<->numpy conversion (numpy has no native bfloat16).
try:
    import torch  # noqa: F401

    _HAS_TORCH = True
except ImportError:  # pragma: no cover
    _HAS_TORCH = False

import ml_dtypes


def _torch_to_numpy(t) -> np.ndarray:
    import torch

    t = t.detach().contiguous().cpu()
    if t.dtype == torch.bfloat16:
        return t.view(torch.uint16).numpy().view(ml_dtypes.bfloat16)
    if t.dtype == torch.float8_e4m3fn:
        return t.view(torch.uint8).numpy().view(ml_dtypes.float8_e4m3fn)
    return t.numpy()


def _load_safetensors_file(path: str) -> Dict[str, np.ndarray]:
    # framework="pt" handles every dtype (the numpy framework rejects bf16/fp8)
    # and is preferred when torch is present; otherwise fall back to numpy,
    # which suffices for fp32/fp16/int checkpoints.
    from safetensors import safe_open

    out = {}
    if _HAS_TORCH:
        with safe_open(path, framework="pt") as f:
            for k in f.keys():
                out[k] = _torch_to_numpy(f.get_tensor(k))
        return out
    try:
        with safe_open(path, framework="np") as f:
            for k in f.keys():
                out[k] = f.get_tensor(k)
    except (TypeError, ValueError) as e:
        raise RuntimeError(
            f"Loading {path} requires torch (bf16/fp8 tensors cannot be read "
            f"via the numpy framework): {e}"
        ) from e
    return out


def load_state_dict(model_path: str) -> Dict[str, np.ndarray]:
    """Load a full (unsharded view of a possibly sharded) checkpoint directory.

    Mirrors reference modules/checkpoint.py:24-170 dispatch order: safetensors
    file, safetensors index, pytorch bin, pytorch bin index.
    """
    model_path = str(model_path)
    if os.path.isfile(model_path):
        return _load_checkpoint_file(model_path)
    if not os.path.isdir(model_path):
        raise FileNotFoundError(f"Checkpoint path not found: {model_path}")

    st = os.path.join(model_path, SAFETENSORS_MODEL)
    st_index = os.path.join(model_path, SAFETENSORS_INDEX)
    pt = os.path.join(model_path, PYTORCH_MODEL)
    pt_index = os.path.join(model_path, PYTORCH_INDEX)

    if os.path.exists(st):
        return _load_safetensors_file(st)
    if os.path.exists(st_index):
        return _load_from_index(model_path, st_index)
    if os.path.exists(pt):
        return _load_checkpoint_file(pt)
    if os.path.exists(pt_index):
        return _load_from_index(model_path, pt_index)
    # last resort: any *.safetensors files in dir
    files = sorted(f for f in os.listdir(model_path) if f.endswith(".safetensors"))
    if files:
        out = {}
        for f in files:
            out.update(_load_safetensors_file(os.path.join(model_path, f)))
        return out
    raise FileNotFoundError(f"No checkpoint files found under {model_path}")


def _load_from_index(model_path: str, index_path: str) -> Dict[str, np.ndarray]:
    with open(index_path) as f:
        index = json.load(f)
    shard_files = sorted(set(index["weight_map"].values()))
    out: Dict[str, np.ndarray] = {}
    for shard in shard_files:
        out.update(_load_checkpoint_file(os.path.join(model_path, shard)))
    return out


def _load_checkpoint_file(path: str) -> Dict[str, np.ndarray]:
    if path.endswith(".safetensors"):
        return _load_safetensors_file(path)
    if not _HAS_TORCH:
        raise RuntimeError("Loading .bin checkpoints requires torch")
    import torch

    sd = torch.load(path, map_location="cpu", weights_only=True)
    return {k: _torch_to_numpy(v) for k, v in sd.items() if v is not None}


def save_state_dict_safetensors(
    state_dict: Dict[str, np.ndarray],
    save_dir: str,
    max_shard_size_bytes: int = 10 * 1024**3,
) -> None:
    """Save as (possibly sharded) safetensors with an index file
    (reference: modules/checkpoint.py:171-199)."""
    os.makedirs(save_dir, exist_ok=True)
    items = [(k, v) for k, v in state_dict.items() if v is not None]
    shards, cur, cur_bytes = [], {}, 0
    for k, v in items:
        nbytes = int(np.asarray(v).nbytes)
        if cur and cur_bytes + nbytes > max_shard_size_bytes:
            shards.append(cur)
            cur, cur_bytes = {}, 0
        # safetensors serializes the raw buffer — transposed/strided views
        # (e.g. converted (in, out)-layout weights) must be made contiguous
        cur[k] = np.ascontiguousarray(v)
        cur_bytes += nbytes
    if cur:
        shards.append(cur)

    from safetensors.numpy import save_file

    if len(shards) == 1:
        save_file(shards[0], os.path.join(save_dir, SAFETENSORS_MODEL))
        return
    weight_map = {}
    for i, shard in enumerate(shards):
        name = f"model-{i + 1:05d}-of-{len(shards):05d}.safetensors"
        save_file(shard, os.path.join(save_dir, name))
        for k in shard:
            weight_map[k] = name
    with open(os.path.join(save_dir, SAFETENSORS_INDEX), "w") as f:
        json.dump({"weight_map": weight_map}, f)


_LAYER_RE = re.compile(r"(^|\.)layers\.(\d+)\.")


def prune_state_dict(state_dict: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    """Drop None entries (reference: modules/checkpoint.py ``prune_state_dict``)."""
    return {k: v for k, v in state_dict.items() if v is not None}


def create_n_layer_checkpoint(
    state_dict: Dict[str, np.ndarray], num_layers: int
) -> Dict[str, np.ndarray]:
    """Keep only the first ``num_layers`` decoder layers — used to synthesize tiny
    test checkpoints from full models (reference: modules/checkpoint.py:202)."""
    out = {}
    for k, v in state_dict.items():
        m = _LAYER_RE.search(k)
        if m and int(m.group(2)) >= num_layers:
            continue
        out[k] = v
    return out


def rename_keys(
    state_dict: Dict[str, np.ndarray], renames: Iterable[tuple]
) -> Dict[str, np.ndarray]:
    """Apply (pattern, replacement) regex renames, e.g. stripping a ``model.`` prefix
    (reference: application_base.py:691-737 prefix handling)."""
    out = {}
    for k, v in state_dict.items():
        nk = k
        for pat, rep in renames:
            nk = re.sub(pat, rep, nk)
        out[nk] = v
    return out


def strip_language_model_prefix(
    state_dict: Dict[str, np.ndarray],
) -> Dict[str, np.ndarray]:
    """Select the text-decoder subtree of a composite (vision+text) HF state
    dict: drop the ``[model.]language_model.`` prefixes and keep the top-level
    ``lm_head.weight`` — the common ingestion step for every image-to-text
    family (llava, pixtral/mistral3, gemma3-vision, ovis2, janus, ...)."""
    out = {}
    for k, v in state_dict.items():
        for prefix in ("model.language_model.", "language_model.model.", "language_model."):
            if k.startswith(prefix):
                out[k[len(prefix):]] = v
                break
        else:
            if k in ("lm_head.weight", "language_model.lm_head.weight"):
                out["lm_head.weight"] = v
    return out
