"""Configuration system for the TPU-native inference framework.

Two-level design mirroring the reference framework's contract
(reference: models/config.py:84 ``NeuronConfig``, :813 ``InferenceConfig``):

- :class:`TpuConfig` — runtime/feature flags (parallel degrees, bucketing,
  sampling, speculation, quantization, ...). Everything the compiler/runtime
  needs that is NOT a model hyperparameter.
- :class:`InferenceConfig` — model hyperparameters, typically adapted from a
  HuggingFace ``config.json``, plus a ``tpu_config`` attribute. Serialized to
  JSON next to compiled artifacts so compile-time and run-time agree
  (reference: models/config.py:891-1002).

The JSON artifact is intentionally shaped like the reference's
``neuron_config.json`` so tooling that reads it keeps working.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List

import jax.numpy as jnp
import numpy as np

CONFIG_FILE = "tpu_config.json"

_DTYPES = {
    "bfloat16": jnp.bfloat16,
    "float32": jnp.float32,
    "float16": jnp.float16,
    "float8_e4m3": jnp.float8_e4m3fn,
    "float8_e5m2": jnp.float8_e5m2,
    "int8": jnp.int8,
}


def to_jax_dtype(dtype) -> Any:
    """Map a string (or jnp dtype) to a jnp dtype (reference: utils/distributed.py analog)."""
    if isinstance(dtype, str):
        key = dtype.replace("torch.", "")
        if key not in _DTYPES:
            raise ValueError(f"Unsupported dtype {dtype!r}; options: {sorted(_DTYPES)}")
        return _DTYPES[key]
    return dtype


def dtype_name(dtype) -> str:
    for name, dt in _DTYPES.items():
        if dt == dtype:
            return name
    return str(dtype)


class OnDeviceSamplingConfig:
    """Sampling-on-device flags (reference: models/config.py:1028)."""

    def __init__(self, **kwargs):
        self.do_sample = kwargs.pop("do_sample", False)
        self.top_k = kwargs.pop("top_k", 1)
        self.top_p = kwargs.pop("top_p", 1.0)
        self.temperature = kwargs.pop("temperature", 1.0)
        self.dynamic = kwargs.pop("dynamic", True)  # per-request sampling params tensor
        self.global_topk = kwargs.pop("global_topk", 256)  # stage-1 shard top-k width
        self.deterministic = kwargs.pop("deterministic", False)
        self.on_device_sampling_seed = kwargs.pop("on_device_sampling_seed", 0)
        # batch-sharded sampling over the tp world (reference:
        # DataParallelSampler, modules/generation/sampling.py:469-569): each
        # shard runs the top-k stages on its batch rows, GSPMD gathers tokens
        self.dp_sampling = kwargs.pop("dp_sampling", False)
        if kwargs:
            raise ValueError(f"Unknown OnDeviceSamplingConfig args: {sorted(kwargs)}")

    def to_dict(self):
        return dict(self.__dict__)


class KVQuantizationConfig:
    """KV-cache quantization (reference: models/config.py:300-306, kv_cache_manager.py:642)."""

    def __init__(self, **kwargs):
        self.dtype = kwargs.pop("dtype", "float8_e4m3")
        # direct_cast | per_tensor | per_key | per_channel
        # (reference: QuantizationType PER_TENSOR/PER_KEY/PER_CHANNEL
        # _SYMMETRIC scale buffers, kv_cache_manager.py:642-692)
        self.scale_mode = kwargs.pop("scale_mode", "direct_cast")
        # per_tensor: values are stored as value/scale in fp8 and rescaled on
        # read. Static scales, typically from offline amax calibration.
        self.k_scale = float(kwargs.pop("k_scale", 1.0))
        self.v_scale = float(kwargs.pop("v_scale", 1.0))
        # per_key: per-layer, per-kv-head scales, shape (L, KV).
        # per_channel: per-layer, per-head-dim-channel scales, shape (L, D).
        # Accepted as nested lists/arrays, or loaded from ``scales_path`` (an
        # .npz with k_scales/v_scales produced by
        # kvcache.calibration.calibrate_kv_scales).
        self.scales_path = kwargs.pop("scales_path", None)
        k_scales = kwargs.pop("k_scales", None)
        v_scales = kwargs.pop("v_scales", None)
        if self.scales_path is not None and k_scales is None:
            with np.load(self.scales_path) as z:
                k_scales = z["k_scales"]
                v_scales = z["v_scales"]
        if k_scales is not None:
            k_scales = np.asarray(k_scales, dtype=np.float32)
            v_scales = np.asarray(v_scales, dtype=np.float32)
        self.k_scales = k_scales
        self.v_scales = v_scales
        if k_scales is not None and self.scale_mode == "per_tensor":
            # calibration's per_tensor mode returns (L,) per-layer arrays;
            # the per-tensor layout takes one static scalar — collapse to
            # the max so the documented calibrate->config flow works
            self.k_scale = float(np.max(k_scales))
            self.v_scale = float(np.max(v_scales))
            self.k_scales = self.v_scales = None
        elif k_scales is not None and self.scale_mode not in ("per_key", "per_channel"):
            raise ValueError(
                "k_scales/v_scales arrays are only consumed by "
                "scale_mode='per_tensor'|'per_key'|'per_channel'; got "
                f"scale_mode={self.scale_mode!r}"
            )
        if self.scale_mode not in ("direct_cast", "per_tensor", "per_key", "per_channel"):
            raise ValueError(
                "kv quant scale_mode must be direct_cast|per_tensor|per_key|"
                f"per_channel, got {self.scale_mode!r}"
            )
        if self.scale_mode == "direct_cast" and (self.k_scale != 1.0 or self.v_scale != 1.0):
            raise ValueError("k_scale/v_scale require scale_mode='per_tensor'")
        if self.scale_mode in ("per_key", "per_channel") and self.k_scales is None:
            raise ValueError(
                f"scale_mode={self.scale_mode!r} needs k_scales/v_scales arrays "
                "(or scales_path) from calibration "
                "(nxdi_tpu.kvcache.calibration.calibrate_kv_scales)"
            )
        if kwargs:
            raise ValueError(f"Unknown KVQuantizationConfig args: {sorted(kwargs)}")

    def to_dict(self):
        d = dict(self.__dict__)
        for key in ("k_scales", "v_scales"):
            if d.get(key) is not None:
                d[key] = np.asarray(d[key]).tolist()
        return d


class ChunkedPrefillConfig:
    """Chunked prefill over block KV (reference: models/config.py:1042)."""

    def __init__(self, **kwargs):
        self.max_num_seqs = kwargs.pop("max_num_seqs", 8)
        self.chunk_size = kwargs.pop("chunk_size", 512)
        self.kernel_q_tile_size = kwargs.pop("kernel_q_tile_size", 128)
        self.kernel_kv_tile_size = kwargs.pop("kernel_kv_tile_size", 512)
        if kwargs:
            raise ValueError(f"Unknown ChunkedPrefillConfig args: {sorted(kwargs)}")

    def to_dict(self):
        return dict(self.__dict__)


class TelemetryConfig:
    """Serving telemetry (nxdi_tpu/telemetry): always-on metrics registry +
    per-request lifecycle spans owned by the application (``app.telemetry``).

    ``detail``:
      - ``"off"``   — nothing records.
      - ``"basic"`` (default) — all metrics/spans record; dispatch latency is
        the host cost only (never forces a device sync).
      - ``"full"``  — host-path dispatches additionally block until outputs
        are ready before recording, so latency histograms measure true step
        time (``SubmodelProfiler`` flips this on while attached).

    ``max_spans`` bounds the request-span ring buffer (Perfetto export).

    Flight recorder (nxdi_tpu/telemetry/flight.py; serving engine only):

    ``flight`` enables the per-step engine flight recorder;
    ``flight_records`` bounds its StepRecord ring buffer;
    ``postmortem_dir`` — directory where trigger-fired postmortem bundles
    (SLO breach, preemption storm, retrace-guard trip) are written as JSON;
    ``None`` keeps the recorder in-memory only (manual dumps still work).
    ``storm_window`` / ``storm_preemptions`` — a preemption storm fires the
    postmortem trigger when the last ``storm_window`` engine steps carried
    >= ``storm_preemptions`` recompute preemptions.

    ``replica_id`` — stable replica identity for the fleet observatory
    (telemetry/fleet.py): rides every JSON snapshot as
    ``_process.replica_id`` and becomes the ``replica`` label on federated
    series. None = ``"<hostname>:<pid>"``, derived once per process.

    Distributed tracing (nxdi_tpu/telemetry/tracing.py):

    ``trace`` enables per-hop trace recording (ingest queueing, prefill,
    handoff export/import, first decode token) into a bounded per-replica
    buffer served at ``/traces``; a no-op at ``detail="off"`` like every
    other surface. ``trace_buffer`` bounds retained hop spans (overflow
    counts ``nxdi_traces_dropped_total``); ``trace_sample_rate`` is the
    deterministic credit-accumulator rate applied when THIS process mints
    a fresh context (requests arriving with a valid ``traceparent`` keep
    the sender's sampling decision).
    """

    def __init__(self, **kwargs):
        self.enabled = bool(kwargs.pop("enabled", True))
        self.detail = kwargs.pop("detail", "basic")
        self.max_spans = int(kwargs.pop("max_spans", 256))
        self.trace = bool(kwargs.pop("trace", True))
        self.trace_buffer = int(kwargs.pop("trace_buffer", 256))
        self.trace_sample_rate = float(kwargs.pop("trace_sample_rate", 1.0))
        # stable replica identity (fleet observatory, telemetry/fleet.py):
        # the label every federated series carries for this process. None =
        # derived once per Telemetry as "<hostname>:<pid>" — stable for the
        # process lifetime; pin it here for stable labels across restarts.
        rid = kwargs.pop("replica_id", None)
        self.replica_id = None if rid is None else str(rid)
        self.flight = bool(kwargs.pop("flight", True))
        self.flight_records = int(kwargs.pop("flight_records", 512))
        self.postmortem_dir = kwargs.pop("postmortem_dir", None)
        self.storm_window = int(kwargs.pop("storm_window", 32))
        self.storm_preemptions = int(kwargs.pop("storm_preemptions", 8))
        if self.detail not in ("off", "basic", "full"):
            raise ValueError(
                f"telemetry detail must be 'off'|'basic'|'full', got {self.detail!r}"
            )
        if self.max_spans < 1:
            raise ValueError("telemetry max_spans must be >= 1")
        if self.trace_buffer < 1:
            raise ValueError("telemetry trace_buffer must be >= 1")
        if not 0.0 <= self.trace_sample_rate <= 1.0:
            raise ValueError(
                "telemetry trace_sample_rate must be within [0, 1]"
            )
        if self.flight_records < 1:
            raise ValueError("telemetry flight_records must be >= 1")
        if self.storm_window < 1 or self.storm_preemptions < 1:
            raise ValueError(
                "telemetry storm_window and storm_preemptions must be >= 1"
            )
        if kwargs:
            raise ValueError(f"Unknown TelemetryConfig args: {sorted(kwargs)}")

    def to_dict(self):
        return dict(self.__dict__)


class SloConfig:
    """Declared serving SLOs (nxdi_tpu/telemetry/slo.py): latency targets the
    SLO tracker measures per-request attainment against.

    ``ttft_s`` — time-to-first-token target in seconds (None = not declared);
    ``tpot_s`` — mean inter-token (time-per-output-token) target in seconds.
    A request ATTAINS its SLO when every declared target holds with
    ``value <= target`` (exactly at the target is attained; the breach is
    strict ``>``). ``window`` bounds the rolling population behind the
    ``nxdi_slo_attainment_pct`` / ``nxdi_slo_goodput_tok_s`` gauges.
    """

    def __init__(self, **kwargs):
        ttft = kwargs.pop("ttft_s", None)
        tpot = kwargs.pop("tpot_s", None)
        self.ttft_s = None if ttft is None else float(ttft)
        self.tpot_s = None if tpot is None else float(tpot)
        self.window = int(kwargs.pop("window", 256))
        if kwargs:
            raise ValueError(f"Unknown SloConfig args: {sorted(kwargs)}")
        if self.ttft_s is None and self.tpot_s is None:
            raise ValueError("SloConfig needs at least one of ttft_s / tpot_s")
        if (self.ttft_s is not None and self.ttft_s <= 0) or (
            self.tpot_s is not None and self.tpot_s <= 0
        ):
            raise ValueError("SLO targets must be positive seconds")
        if self.window < 1:
            raise ValueError("SLO window must be >= 1")

    def to_dict(self):
        return dict(self.__dict__)


class QosConfig:
    """QoS control plane, engine tier (nxdi_tpu/control/qos.py): multi-tenant
    token-bucket quotas + deadline-aware admission/preemption over the
    priority classes ``interactive`` | ``batch`` | ``best_effort``.

    ``default_class`` — priority class of requests that declare none;
    ``class_slos`` — per-class latency targets (class name -> SloConfig /
    its kwargs dict / None = no deadline for that class). Classes absent
    from the map fall back to the built-in defaults; an explicit None
    entry disables the class's deadline. Slack against these targets is
    what deadline-aware admission orders the waiting queue by
    (``deadline = arrival + ttft_s + tpot_s * |generated|``);
    ``quotas`` — per-tenant token buckets (tenant -> {"refill_per_s",
    "burst"}); a submission is charged ``prompt + max_new_tokens`` at
    admission and rejected with a deterministic 429-style error finish
    when its tenant's bucket cannot cover it;
    ``default_quota`` — bucket for tenants not in ``quotas`` (None =
    unbounded — the greedy-parity default);
    ``default_tenant`` — tenant identity of requests that declare none;
    ``deadline_admission`` / ``deadline_preemption`` — enable the two
    scheduler hooks independently;
    ``slack_guard_s`` — a RUNNING request whose slack is below this is
    never chosen as a preemption victim (it is about to breach; evicting
    it guarantees the breach) unless every candidate is below the guard;
    ``window`` — rolling per-class attainment population behind the
    ``nxdi_qos_slo_attainment_pct{class}`` gauges.
    """

    #: built-in per-class deadline targets (seconds); best_effort has none
    DEFAULT_CLASS_SLOS = {
        "interactive": {"ttft_s": 0.5, "tpot_s": 0.1},
        "batch": {"ttft_s": 5.0, "tpot_s": 0.5},
        "best_effort": None,
    }

    def __init__(self, **kwargs):
        from nxdi_tpu.ops.sampling import PRIORITY_CLASSES

        self.default_class = str(kwargs.pop("default_class", "batch"))
        if self.default_class not in PRIORITY_CLASSES:
            raise ValueError(
                f"qos default_class must be one of {PRIORITY_CLASSES}, "
                f"got {self.default_class!r}"
            )
        slos = dict(kwargs.pop("class_slos", None) or {})
        unknown = sorted(set(slos) - set(PRIORITY_CLASSES))
        if unknown:
            raise ValueError(f"qos class_slos has unknown classes: {unknown}")
        self.class_slos = {}
        for cls in PRIORITY_CLASSES:
            slo = slos.get(cls, self.DEFAULT_CLASS_SLOS[cls])
            if isinstance(slo, dict):
                slo = SloConfig(**slo)
            if slo is not None and not isinstance(slo, SloConfig):
                raise ValueError(
                    f"qos class_slos[{cls!r}] must be an SloConfig, a dict "
                    f"of its kwargs, or None — got {type(slo)}"
                )
            self.class_slos[cls] = slo
        self.default_tenant = str(kwargs.pop("default_tenant", "default"))
        self.quotas = {
            str(t): self._quota(t, q)
            for t, q in dict(kwargs.pop("quotas", None) or {}).items()
        }
        dq = kwargs.pop("default_quota", None)
        self.default_quota = None if dq is None else self._quota("*", dq)
        self.deadline_admission = bool(kwargs.pop("deadline_admission", True))
        self.deadline_preemption = bool(kwargs.pop("deadline_preemption", True))
        self.slack_guard_s = float(kwargs.pop("slack_guard_s", 0.05))
        self.window = int(kwargs.pop("window", 256))
        if kwargs:
            raise ValueError(f"Unknown QosConfig args: {sorted(kwargs)}")
        if self.slack_guard_s < 0:
            raise ValueError("qos slack_guard_s must be >= 0")
        if self.window < 1:
            raise ValueError("qos window must be >= 1")

    @staticmethod
    def _quota(tenant, q) -> dict:
        q = dict(q)
        try:
            refill = float(q.pop("refill_per_s"))
            burst = float(q.pop("burst"))
        except KeyError as e:
            raise ValueError(
                f"qos quota for tenant {tenant!r} needs refill_per_s and "
                f"burst, missing {e}"
            )
        if q:
            raise ValueError(
                f"Unknown qos quota keys for tenant {tenant!r}: {sorted(q)}"
            )
        if refill < 0 or burst <= 0:
            raise ValueError(
                f"qos quota for tenant {tenant!r} needs refill_per_s >= 0 "
                "and burst > 0"
            )
        return {"refill_per_s": refill, "burst": burst}

    def to_dict(self):
        d = dict(self.__dict__)
        d["class_slos"] = {
            c: None if s is None else s.to_dict()
            for c, s in self.class_slos.items()
        }
        return d


class AutoscaleConfig:
    """QoS control plane, fleet tier (nxdi_tpu/control/autoscaler.py): the
    policy loop that closes FleetMonitor load signals back into replica
    lifecycle.

    ``interval_s`` — loop pace of the background autoscaler thread;
    ``ewma_alpha`` — smoothing weight of the fleet-mean load-score trend
    (``trend = alpha * mean + (1 - alpha) * trend``; 1.0 = unsmoothed);
    ``scale_up_score`` / ``scale_down_score`` — hysteresis band on the
    smoothed trend: above the high watermark the fleet grows, below the
    low one it shrinks, in between it holds (the band is what stops
    flapping on a noisy signal);
    ``min_replicas`` / ``max_replicas`` — hard bounds on ACTIVE (non-
    draining) replicas;
    ``cooldown_s`` — minimum seconds between two scaling actions (retire
    of an already-drained replica is exempt — it frees resources and
    cannot flap);
    ``rebalance_ratio`` — prefill:decode mean-score ratio beyond which the
    role mix rebalances one replica toward the pressured role (applies
    symmetrically as ratio and 1/ratio; 0 disables role rebalance);
    ``decision_ring`` — bound on the journaled decision trace behind the
    ``/autoscale`` endpoint and ``cli.fleet --autoscale-log``.
    """

    def __init__(self, **kwargs):
        self.interval_s = float(kwargs.pop("interval_s", 1.0))
        self.ewma_alpha = float(kwargs.pop("ewma_alpha", 0.5))
        self.scale_up_score = float(kwargs.pop("scale_up_score", 6.0))
        self.scale_down_score = float(kwargs.pop("scale_down_score", 1.5))
        self.min_replicas = int(kwargs.pop("min_replicas", 1))
        self.max_replicas = int(kwargs.pop("max_replicas", 8))
        self.cooldown_s = float(kwargs.pop("cooldown_s", 10.0))
        self.rebalance_ratio = float(kwargs.pop("rebalance_ratio", 0.0))
        self.decision_ring = int(kwargs.pop("decision_ring", 256))
        if kwargs:
            raise ValueError(f"Unknown AutoscaleConfig args: {sorted(kwargs)}")
        if self.interval_s <= 0:
            raise ValueError("autoscale interval_s must be > 0")
        if not 0.0 < self.ewma_alpha <= 1.0:
            raise ValueError("autoscale ewma_alpha must be in (0, 1]")
        if self.scale_down_score >= self.scale_up_score:
            raise ValueError(
                "autoscale needs scale_down_score < scale_up_score "
                "(the hysteresis band)"
            )
        if self.min_replicas < 1 or self.max_replicas < self.min_replicas:
            raise ValueError(
                "autoscale needs 1 <= min_replicas <= max_replicas"
            )
        if self.cooldown_s < 0:
            raise ValueError("autoscale cooldown_s must be >= 0")
        if self.rebalance_ratio < 0:
            raise ValueError("autoscale rebalance_ratio must be >= 0 (0 off)")
        if self.decision_ring < 1:
            raise ValueError("autoscale decision_ring must be >= 1")

    def to_dict(self):
        return dict(self.__dict__)


class SentinelConfig:
    """Numerics sentinel (nxdi_tpu/telemetry/sentinel.py): online correctness
    observability for the serving path — in-graph logit-health stats,
    sampled shadow-replay verification, and the preemption-replay invariant.

    ``logit_health`` — compile a small in-graph reduction over each
    dispatch's sampled-position logit row block (NaN/Inf counts, max|logit|,
    mean entropy, top1-top2 margin) exported as ``nxdi_numerics_*`` series
    per (submodel, bucket); a nonzero NaN/Inf count fires the ``numerics``
    postmortem trigger through the flight recorder.
    ``replay_rate`` — fraction of RETIRED greedy requests teacher-force
    replayed through the static all-position logit probe
    (utils/accuracy.py) and token-matched against what the engine actually
    streamed (0.0 = off, 1.0 = every request; deterministic credit
    accumulator, not a random draw, so tests and fleets are reproducible).
    ``preemption_check`` — on every recompute-resume, verify the replayed
    ``prompt + generated`` prefix reproduces the pre-preemption tokens
    exactly (greedy rows) — a mismatch counts
    ``nxdi_sentinel_replay_mismatch_total{kind="preemption"}`` and fires a
    ``numerics`` bundle instead of silently serving a forked continuation.
    ``divergence_tol`` / ``tol_map`` — tolerance (and per-index overrides,
    accuracy.py tol-map convention) on the replay's logit-margin report;
    token equality is always strict.
    ``bundle_cooldown`` — minimum dispatches between two ``numerics``
    bundles of the same kind (a persistent NaN must not write a bundle per
    step).
    """

    def __init__(self, **kwargs):
        self.logit_health = bool(kwargs.pop("logit_health", True))
        self.replay_rate = float(kwargs.pop("replay_rate", 0.0))
        self.preemption_check = bool(kwargs.pop("preemption_check", True))
        self.divergence_tol = float(kwargs.pop("divergence_tol", 0.001))
        tol_map = kwargs.pop("tol_map", None)
        # JSON round trips stringify int keys; accept both spellings
        self.tol_map = (
            None if tol_map is None
            else {int(k): float(v) for k, v in dict(tol_map).items()}
        )
        self.bundle_cooldown = int(kwargs.pop("bundle_cooldown", 64))
        if kwargs:
            raise ValueError(f"Unknown SentinelConfig args: {sorted(kwargs)}")
        if not 0.0 <= self.replay_rate <= 1.0:
            raise ValueError("sentinel replay_rate must be in [0, 1]")
        if self.divergence_tol < 0:
            raise ValueError("sentinel divergence_tol must be >= 0")
        if self.bundle_cooldown < 1:
            raise ValueError("sentinel bundle_cooldown must be >= 1")

    def to_dict(self):
        return dict(self.__dict__)


class FleetConfig:
    """Fleet observatory (nxdi_tpu/telemetry/fleet.py): how a
    :class:`~nxdi_tpu.telemetry.fleet.FleetMonitor` polls N replica
    ``/snapshot`` endpoints and classifies their health.

    ``poll_interval_s`` — seconds between poll rounds (``cli.fleet --watch``
    and the ``--serve`` federation endpoint pace on this);
    ``timeout_s`` — per-replica HTTP timeout (a poll can never hang the
    monitor longer than this per replica);
    ``staleness_s`` — a snapshot whose embedded ``_process.snapshot_unix_s``
    is older than this counts as a FAILED poll even when transport
    succeeded (a wedged replica keeps answering with frozen metrics — the
    age-out is what catches it);
    ``unreachable_failures`` — consecutive failed polls before a replica
    transitions DEGRADED -> UNREACHABLE (the first failure is DEGRADED
    unless this is 1); UNREACHABLE replicas leave the fleet aggregates;
    ``backoff_base_s`` / ``backoff_max_s`` — per-replica exponential
    backoff between polls of a FAILING replica
    (``min(base * 2**(failures-1), max)``); healthy replicas poll every
    round.
    """

    def __init__(self, **kwargs):
        self.poll_interval_s = float(kwargs.pop("poll_interval_s", 1.0))
        self.timeout_s = float(kwargs.pop("timeout_s", 2.0))
        self.staleness_s = float(kwargs.pop("staleness_s", 10.0))
        self.unreachable_failures = int(kwargs.pop("unreachable_failures", 3))
        self.backoff_base_s = float(kwargs.pop("backoff_base_s", 0.5))
        self.backoff_max_s = float(kwargs.pop("backoff_max_s", 30.0))
        if kwargs:
            raise ValueError(f"Unknown FleetConfig args: {sorted(kwargs)}")
        if self.poll_interval_s <= 0 or self.timeout_s <= 0:
            raise ValueError("fleet poll_interval_s and timeout_s must be > 0")
        if self.staleness_s <= 0:
            raise ValueError("fleet staleness_s must be > 0")
        if self.unreachable_failures < 1:
            raise ValueError("fleet unreachable_failures must be >= 1")
        if self.backoff_base_s <= 0 or self.backoff_max_s < self.backoff_base_s:
            raise ValueError(
                "fleet backoff needs 0 < backoff_base_s <= backoff_max_s"
            )

    def to_dict(self):
        return dict(self.__dict__)


class RouterConfig:
    """Replica router tier (nxdi_tpu/router): dispatch/failover/shedding
    knobs over the fleet observatory's load signals.

    ``degraded_penalty`` — score added to a DEGRADED replica when ranking a
    NEW dispatch (it stays dispatchable — its data is recent by the fleet
    age-out — but healthy peers win ties decisively); existing session pins
    survive DEGRADED so multi-turn traffic keeps its warm KV;
    ``inflight_weight`` — per-request weight of the router's OWN live
    assignment count in the ranking (least-outstanding-requests: polled
    load signals lag a poll interval, the local term keeps a burst between
    polls from landing wholesale on one replica; 0 ranks on the pinned
    fleet score alone);
    ``shed_queue_depth`` — router-level load-shedding watermark: a submit
    is rejected with explicit backpressure (HTTP 429, counted in
    ``nxdi_router_sheds_total``) when EVERY dispatchable replica's
    queue-depth gauge exceeds this;
    ``shed_class_factors`` — class-aware shedding (QoS control plane):
    per-priority-class multipliers on the shed watermark, so under
    pressure ``best_effort`` sheds first (factor < 1) while
    ``interactive`` keeps landing until the fleet is far deeper
    underwater (factor > 1). Requests without a priority class shed at
    the base watermark (factor 1.0);
    ``max_failovers`` — bounded retry: how many times one request may be
    re-dispatched after its replica fails (None = replica count - 1, i.e.
    every other replica gets one chance);
    ``stream_failures`` — consecutive transport failures polling one
    request's upstream stream before the router forces a health poll and
    takes the failover decision (1 = fail over on the first error);
    ``ingest_timeout_s`` — per-call HTTP timeout against replica ingest
    endpoints (/submit, /stream, /drain);
    ``poll_interval_s`` — background health/load poll cadence of the
    router's embedded FleetMonitor (``Router.start()``);
    ``max_sessions`` — LRU bound on the session-affinity pin table;
    ``max_requests`` — bound on retained finished-request records (live
    requests are never evicted);
    ``trace_sample_rate`` — deterministic credit-accumulator sampling rate
    for distributed traces minted at submit (telemetry/tracing.py): every
    submission carries a trace id regardless, but only sampled requests
    record hop spans (0 disables recording entirely);
    ``trace_buffer`` — bound on the router's retained hop spans (overflow
    counts the router registry's ``nxdi_traces_dropped_total``).
    """

    def __init__(self, **kwargs):
        self.degraded_penalty = float(kwargs.pop("degraded_penalty", 4.0))
        self.inflight_weight = float(kwargs.pop("inflight_weight", 1.0))
        self.shed_queue_depth = float(kwargs.pop("shed_queue_depth", 16.0))
        scf = kwargs.pop("shed_class_factors", None)
        if scf is None:
            scf = {"interactive": 2.0, "batch": 1.0, "best_effort": 0.5}
        self.shed_class_factors = {str(k): float(v) for k, v in dict(scf).items()}
        mf = kwargs.pop("max_failovers", None)
        self.max_failovers = None if mf is None else int(mf)
        self.stream_failures = int(kwargs.pop("stream_failures", 2))
        self.ingest_timeout_s = float(kwargs.pop("ingest_timeout_s", 5.0))
        self.poll_interval_s = float(kwargs.pop("poll_interval_s", 0.5))
        self.max_sessions = int(kwargs.pop("max_sessions", 4096))
        self.max_requests = int(kwargs.pop("max_requests", 4096))
        self.trace_sample_rate = float(kwargs.pop("trace_sample_rate", 1.0))
        self.trace_buffer = int(kwargs.pop("trace_buffer", 512))
        if kwargs:
            raise ValueError(f"Unknown RouterConfig args: {sorted(kwargs)}")
        if self.degraded_penalty < 0:
            raise ValueError("router degraded_penalty must be >= 0")
        if self.inflight_weight < 0:
            raise ValueError("router inflight_weight must be >= 0")
        if self.shed_queue_depth < 0:
            raise ValueError("router shed_queue_depth must be >= 0")
        if any(v <= 0 for v in self.shed_class_factors.values()):
            raise ValueError("router shed_class_factors must all be > 0")
        if self.max_failovers is not None and self.max_failovers < 0:
            raise ValueError("router max_failovers must be >= 0 (or None)")
        if self.stream_failures < 1:
            raise ValueError("router stream_failures must be >= 1")
        if self.ingest_timeout_s <= 0 or self.poll_interval_s <= 0:
            raise ValueError(
                "router ingest_timeout_s and poll_interval_s must be > 0"
            )
        if self.max_sessions < 1 or self.max_requests < 1:
            raise ValueError("router max_sessions/max_requests must be >= 1")
        if not 0.0 <= self.trace_sample_rate <= 1.0:
            raise ValueError("router trace_sample_rate must be within [0, 1]")
        if self.trace_buffer < 1:
            raise ValueError("router trace_buffer must be >= 1")

    def to_dict(self):
        return dict(self.__dict__)


class FaultConfig:
    """Fault tolerance and recovery (nxdi_tpu/runtime/faults.py): the
    dispatch watchdog and the engine's step-fault recovery budget.

    ``watchdog`` — run every model dispatch on a watchdog worker thread
    with a per-program timeout of ``CostSheet floor × watchdog_multiplier``
    (clamped below by ``watchdog_min_timeout_s``); a timed-out launch trips
    the watchdog, counts as transient, and retries. Off by default — the
    worker-thread hop costs a context switch per dispatch.
    ``watchdog_multiplier`` / ``watchdog_min_timeout_s`` — the timeout
    formula's two knobs (floors come from the cost observatory; tags
    without a sheet use the bare minimum).
    ``max_retries`` — in-place transient-dispatch retries before the fault
    escapes to the engine step (each preceded by the deterministic backoff
    ``min(backoff_base_s * 2**attempt, backoff_max_s)``).
    ``max_recoveries`` — times one request may be requeued through the
    recompute-preemption path after a transient step fault before it
    error-finishes (the router then fails it over).
    """

    def __init__(self, **kwargs):
        self.watchdog = bool(kwargs.pop("watchdog", False))
        self.watchdog_multiplier = float(kwargs.pop("watchdog_multiplier", 20.0))
        self.watchdog_min_timeout_s = float(
            kwargs.pop("watchdog_min_timeout_s", 0.5)
        )
        self.max_retries = int(kwargs.pop("max_retries", 2))
        self.backoff_base_s = float(kwargs.pop("backoff_base_s", 0.05))
        self.backoff_max_s = float(kwargs.pop("backoff_max_s", 2.0))
        self.max_recoveries = int(kwargs.pop("max_recoveries", 3))
        if kwargs:
            raise ValueError(f"Unknown FaultConfig args: {sorted(kwargs)}")
        if self.watchdog_multiplier <= 0:
            raise ValueError("fault watchdog_multiplier must be > 0")
        if self.watchdog_min_timeout_s <= 0:
            raise ValueError("fault watchdog_min_timeout_s must be > 0")
        if self.max_retries < 0:
            raise ValueError("fault max_retries must be >= 0")
        if self.backoff_base_s <= 0 or self.backoff_max_s < self.backoff_base_s:
            raise ValueError(
                "fault backoff needs 0 < backoff_base_s <= backoff_max_s"
            )
        if self.max_recoveries < 0:
            raise ValueError("fault max_recoveries must be >= 0")

    def to_dict(self):
        return dict(self.__dict__)


class HybridShardingConfig:
    """Per-phase hybrid MoE TPxEP regimes (reference: models/config.py:1060
    ``HybridShardingConfig``). ``moe_cte_ep_degree`` experts-axis width for
    prefill (TP-heavy), ``moe_tkg_ep_degree`` for decode (EP-heavy); the
    per-phase moe-tp widths are the world divided by these. The tkg degree
    must be a multiple of the cte degree (the mesh refines ep into ep x epx)."""

    def __init__(self, **kwargs):
        self.moe_cte_ep_degree = int(kwargs.pop("moe_cte_ep_degree", 1))
        self.moe_tkg_ep_degree = int(kwargs.pop("moe_tkg_ep_degree", 1))
        if kwargs:
            raise ValueError(f"Unknown HybridShardingConfig args: {sorted(kwargs)}")
        if self.moe_cte_ep_degree < 1 or self.moe_tkg_ep_degree < 1:
            raise ValueError("hybrid sharding degrees must be >= 1")
        if self.moe_tkg_ep_degree % self.moe_cte_ep_degree:
            raise ValueError(
                f"moe_tkg_ep_degree ({self.moe_tkg_ep_degree}) must be a "
                f"multiple of moe_cte_ep_degree ({self.moe_cte_ep_degree}) — "
                "the mesh refines the cte ep axis into (ep, epx)"
            )

    def to_dict(self):
        return dict(self.__dict__)


class SpeculationConfig:
    """Speculative decoding knobs (reference: models/config.py:244-266)."""

    def __init__(self, **kwargs):
        self.speculation_length = kwargs.pop("speculation_length", 0)
        self.enable_fused_speculation = kwargs.pop("enable_fused_speculation", False)
        self.enable_eagle_speculation = kwargs.pop("enable_eagle_speculation", False)
        self.is_eagle3 = kwargs.pop("is_eagle3", False)
        self.is_eagle_draft = kwargs.pop("is_eagle_draft", False)
        self.token_tree_config = kwargs.pop("token_tree_config", None)
        if kwargs:
            raise ValueError(f"Unknown SpeculationConfig args: {sorted(kwargs)}")

    def to_dict(self):
        d = dict(self.__dict__)
        if self.token_tree_config is not None and hasattr(self.token_tree_config, "to_dict"):
            d["token_tree_config"] = self.token_tree_config.to_dict()
        return d


def promote_text_config(config) -> None:
    """Composite HF configs (llava, llama4, ...) nest the LM hyperparams under
    ``text_config``; promote them to the top level as the source of truth —
    the wrapper level carries PretrainedConfig defaults (e.g.
    tie_word_embeddings) that must NOT shadow the text values."""
    tc = getattr(config, "text_config", None)
    if tc is None:
        return
    if not isinstance(tc, dict):
        tc = tc.to_dict()
    for k, v in tc.items():
        setattr(config, k, v)


class TensorCaptureConfig:
    """Named intermediate tensors compiled into extra model outputs
    (reference: TensorCaptureConfig config.py:1085, model_base.py:1091-1198).

    ``capture_points``: any of "embeds" (post-embedding stream),
    "layer_hiddens" (every decoder layer's output, stacked (L, B, S, H)),
    "hidden" (pre-final-norm stream), "logits" (full-vocab logits)."""

    VALID = ("embeds", "layer_hiddens", "hidden", "logits")

    def __init__(self, **kwargs):
        pts = tuple(kwargs.pop("capture_points", ("hidden",)))
        for p in pts:
            if p not in self.VALID:
                raise ValueError(
                    f"unknown capture point {p!r}; valid: {self.VALID}"
                )
        self.capture_points = pts
        if kwargs:
            raise ValueError(f"Unknown TensorCaptureConfig args: {sorted(kwargs)}")

    def to_dict(self):
        return {"capture_points": list(self.capture_points)}


class TensorReplacementConfig:
    """Inject host-captured tensors INTO the device graph — tensor capture's
    plumbing in reverse (reference: utils/tensor_replacement/registry.py:1-50,
    config.py:1136-1166, model_wrapper.py:331-348: replay CPU-captured module
    outputs inside the compiled graph to bisect numeric divergence).

    TPU-native: each replacement point becomes an extra fixed-shape jitted
    input (zeros + a zero mask when unused, so one compiled program serves
    both plain and replaced runs). ``replace_points`` any of:
      - "embeds": replace the post-embedding stream with ``tr_embeds`` (B,S,H)
      - "layers": replace individual layers' output streams — inside the layer
        scan, ``where(tr_layer_mask[l], tr_layer_values[l], hidden)`` with
        ``tr_layer_values`` (B,L,S,H) and ``tr_layer_mask`` (L,) per row
      - "hidden": replace the pre-final-norm stream with ``tr_hidden`` (B,S,H)
    ("logits" is deliberately not a point: nothing downstream consumes it —
    capture the logits instead.)"""

    VALID = ("embeds", "layers", "hidden")

    def __init__(self, **kwargs):
        pts = tuple(kwargs.pop("replace_points", ("layers",)))
        for p in pts:
            if p not in self.VALID:
                raise ValueError(
                    f"unknown replacement point {p!r}; valid: {self.VALID}"
                )
        self.replace_points = pts
        if kwargs:
            raise ValueError(f"Unknown TensorReplacementConfig args: {sorted(kwargs)}")

    def to_dict(self):
        return {"replace_points": list(self.replace_points)}


class LoraServingConfig:
    """Multi-adapter LoRA serving (reference: modules/lora_serving/config.py)."""

    def __init__(self, **kwargs):
        self.max_loras = kwargs.pop("max_loras", 1)
        self.max_lora_rank = kwargs.pop("max_lora_rank", 16)
        self.lora_ckpt_paths = kwargs.pop("lora_ckpt_paths", None)  # {adapter_id: path}
        self.target_modules = kwargs.pop(
            "target_modules", ["q_proj", "k_proj", "v_proj", "o_proj"]
        )
        self.lora_dtype = kwargs.pop("lora_dtype", "bfloat16")
        self.lora_alpha = kwargs.pop("lora_alpha", 16.0)
        if kwargs:
            raise ValueError(f"Unknown LoraServingConfig args: {sorted(kwargs)}")

    def to_dict(self):
        return dict(self.__dict__)


class TpuConfig:
    """Runtime/feature configuration — the analog of the reference's NeuronConfig
    (reference: models/config.py:84-609). Field names are kept compatible where the
    concept transfers so users of the reference find what they expect.
    """

    def __init__(self, **kwargs) -> None:
        # --- basic shapes (reference: config.py:94-101) ---
        self.batch_size = kwargs.pop("batch_size", 1)
        self.padding_side = kwargs.pop("padding_side", "right")
        self.seq_len = kwargs.pop("seq_len", 128)
        self.n_active_tokens = kwargs.pop("n_active_tokens", self.seq_len)
        self.max_context_length = kwargs.pop("max_context_length", self.seq_len)
        self.max_new_tokens = kwargs.pop("max_new_tokens", None)
        self.max_length = kwargs.pop("max_length", self.seq_len)
        self.on_cpu = kwargs.pop("on_cpu", False)
        self.output_logits = kwargs.pop("output_logits", False)

        # --- dtypes ---
        self.dtype = to_jax_dtype(kwargs.pop("dtype", kwargs.pop("torch_dtype", "bfloat16")))
        self.attention_dtype = kwargs.pop("attention_dtype", None)
        if self.attention_dtype is not None:
            self.attention_dtype = to_jax_dtype(self.attention_dtype)
        self.rpl_reduce_dtype = kwargs.pop("rpl_reduce_dtype", None)  # row-parallel reduce dtype
        if self.rpl_reduce_dtype is not None:
            self.rpl_reduce_dtype = to_jax_dtype(self.rpl_reduce_dtype)
        self.cast_type = kwargs.pop("cast_type", "config")
        self.softmax_dtype = to_jax_dtype(kwargs.pop("softmax_dtype", "float32"))

        # --- batching (reference: config.py:162-171) ---
        self.ctx_batch_size = kwargs.pop("ctx_batch_size", self.batch_size)
        self.tkg_batch_size = kwargs.pop("tkg_batch_size", self.batch_size)
        self.max_batch_size = kwargs.pop("max_batch_size", self.batch_size)
        self.is_continuous_batching = kwargs.pop("is_continuous_batching", False)
        self.kv_cache_batch_size = kwargs.pop("kv_cache_batch_size", self.batch_size)
        self.kv_cache_padding_size = kwargs.pop("kv_cache_padding_size", 0)

        # --- sampling (reference: config.py:174-181) ---
        odsc = kwargs.pop("on_device_sampling_config", None)
        if isinstance(odsc, dict):
            odsc = OnDeviceSamplingConfig(**odsc)
        self.on_device_sampling_config = odsc

        # --- async (reference: config.py:184) — JAX dispatch is async by default; this
        # flag controls explicit double-buffered dispatch in the generation loop.
        self.async_mode = kwargs.pop("async_mode", False)

        # --- multi-step decode dispatch: ONE compiled program runs K token-
        # generation steps (sample -> embed -> layer stack -> KV commit chained
        # via lax.scan) per host dispatch, so the per-dispatch weight stream
        # amortizes over K tokens ("Kernel Looping" / ClusterFusion-style
        # collapse of per-step dispatch boundaries; see models/base.py
        # multi_step_token_gen). 1 = classic one-dispatch-per-token decode.
        self.decode_steps_per_dispatch = int(
            kwargs.pop("decode_steps_per_dispatch", 1)
        )

        # --- device-resident decode loop: compile the `tkg_device_loop`
        # submodel — a lax.while_loop running one full decode step per
        # iteration with per-row EOS + token-budget exit applied IN-GRAPH
        # (models/base.py device_loop_token_gen). The serving engine then
        # retires a batch's whole heterogeneous remaining budget in ONE
        # dispatch instead of a ladder of fixed-K scan windows.
        self.device_loop = bool(kwargs.pop("device_loop", False))
        # per-iteration device->host token out-feed (io_callback ring).
        # None = auto: ON for real accelerator backends, OFF on CPU/interpret
        # where the buffered whole-result path is the exact tier-1 surface.
        self.device_loop_outfeed = kwargs.pop("device_loop_outfeed", None)
        # upper bound on tokens per loop launch (0 = unlimited). A fence
        # forces the loop back to the host every N iterations so admission /
        # retirement / preemption get a scheduling point under load — the
        # "preemption fence" between resident-loop launches.
        self.device_loop_fence = int(kwargs.pop("device_loop_fence", 0))

        # --- bucketing (reference: config.py:187-208) ---
        self.enable_bucketing = kwargs.pop("enable_bucketing", False)
        self.buckets = kwargs.pop("buckets", None)
        self.bucket_n_active_tokens = kwargs.pop("bucket_n_active_tokens", False)
        self.context_encoding_buckets = kwargs.pop("context_encoding_buckets", None)
        self.token_generation_buckets = kwargs.pop("token_generation_buckets", None)
        self.prefix_buckets = kwargs.pop("prefix_buckets", None)

        # --- quantization (reference: config.py:217-241) ---
        self.quantized = kwargs.pop("quantized", False)
        self.quantized_checkpoints_path = kwargs.pop("quantized_checkpoints_path", None)
        self.quantization_dtype = kwargs.pop("quantization_dtype", "int8")
        self.quantization_type = kwargs.pop("quantization_type", "per_tensor_symmetric")
        self.modules_to_not_convert = kwargs.pop("modules_to_not_convert", None)
        kvq = kwargs.pop("kv_quant_config", None)
        if isinstance(kvq, dict):
            kvq = KVQuantizationConfig(**kvq)
        self.kv_quant_config = kvq
        self.kv_cache_quant = kwargs.pop("kv_cache_quant", False)
        if self.kv_cache_quant and self.kv_quant_config is None:
            self.kv_quant_config = KVQuantizationConfig()
        # activation quantization (reference: config.py:434-517): "dynamic"
        # computes per-token scales on the hot path; "static" reads calibrated
        # per-tensor input scales from the quantized checkpoint
        # (ops/quantization.calibrate_input_scales)
        self.activation_quantization_type = kwargs.pop("activation_quantization_type", None)
        if isinstance(self.activation_quantization_type, str):
            self.activation_quantization_type = self.activation_quantization_type.lower()
        self.quantize_clamp_bound = kwargs.pop("quantize_clamp_bound", None)
        if self.activation_quantization_type is not None:
            if self.activation_quantization_type not in ("dynamic", "static"):
                raise ValueError(
                    "activation_quantization_type: 'dynamic' or 'static' "
                    f"(got {self.activation_quantization_type!r})"
                )
            if not self.quantized or self.quantization_dtype != "int8":
                raise ValueError(
                    f"activation_quantization_type={self.activation_quantization_type!r} "
                    "requires quantized=True with quantization_dtype='int8' "
                    "(the int8 MXU path)"
                )

        # --- speculation (reference: config.py:244-272) ---
        spec = kwargs.pop("speculation_config", None)
        if isinstance(spec, dict):
            spec = SpeculationConfig(**spec)
        self.speculation_config = spec
        self.speculation_length = kwargs.pop(
            "speculation_length", spec.speculation_length if spec else 0
        )
        self.enable_fused_speculation = kwargs.pop(
            "enable_fused_speculation", spec.enable_fused_speculation if spec else False
        )
        self.enable_eagle_speculation = kwargs.pop(
            "enable_eagle_speculation", spec.enable_eagle_speculation if spec else False
        )
        if self.enable_eagle_speculation:
            self.enable_fused_speculation = True
        self.is_eagle3 = kwargs.pop("is_eagle3", spec.is_eagle3 if spec else False)
        self.is_eagle_draft = kwargs.pop("is_eagle_draft", False)
        # EAGLE token-tree speculation: medusa-style path list (reference:
        # modules/eagle/token_tree.py:8 TokenTree config)
        self.token_tree_config = kwargs.pop(
            "token_tree_config", spec.token_tree_config if spec else None
        )
        self.is_medusa = kwargs.pop("is_medusa", False)
        self.medusa_speculation_length = kwargs.pop("medusa_speculation_length", 0)
        self.num_medusa_heads = kwargs.pop("num_medusa_heads", 0)
        self.medusa_tree = kwargs.pop("medusa_tree", None)

        # --- paged / block KV (reference: config.py:278-283) ---
        self.is_block_kv_layout = kwargs.pop("is_block_kv_layout", False)
        self.pa_num_blocks = kwargs.pop("pa_num_blocks", None)
        self.pa_block_size = kwargs.pop("pa_block_size", 128)
        self.is_prefix_caching = kwargs.pop("is_prefix_caching", False)
        cpc = kwargs.pop("chunked_prefill_config", None)
        if isinstance(cpc, dict):
            cpc = ChunkedPrefillConfig(**cpc)
        self.chunked_prefill_config = cpc
        self.is_chunked_prefill = cpc is not None
        # unified mixed prefill+decode dispatch: compile the `mixed` packed
        # submodel (token-count bucket ladder) and let the serving engine
        # issue ONE program per step for a batch holding prefill chunks AND
        # decode rows together (ragged paged-attention kernel / XLA mask)
        self.mixed_dispatch = kwargs.pop("mixed_dispatch", False)
        # prefill/decode disaggregation (serving/handoff.py): which half of
        # the serving topology this process compiles.
        #   "unified" — every submodel the other flags ask for (default);
        #   "prefill" — CTE/prefix-prefill + the plain 1-token TKG only: the
        #     engine prefills, samples the first token, then parks the KV
        #     block chain for export to a decode replica;
        #   "decode"  — TKG/multistep/device-loop only (no CTE bucket
        #     ladder — a smaller HBM program footprint): requests enter via
        #     an imported KV chain, never a local prefill.
        self.role = kwargs.pop("role", "unified")

        # --- LoRA (reference: config.py:357-359) ---
        lora = kwargs.pop("lora_config", None)
        if isinstance(lora, dict):
            lora = LoraServingConfig(**lora)
        self.lora_config = lora

        # --- parallelism (reference: config.py:362-390) ---
        self.tp_degree = kwargs.pop("tp_degree", 1)
        self.cp_degree = kwargs.pop("cp_degree", 1)
        self.attention_dp_degree = kwargs.pop("attention_dp_degree", 1)
        self.pp_degree = kwargs.pop("pp_degree", 1)
        # microbatches per pipelined forward (GPipe rotation over the batch
        # dim; reference: pp_degree plumbed via NxD ModelBuilder,
        # application_base.py:158-163). 0 = use pp_degree.
        self.pp_microbatches = kwargs.pop("pp_microbatches", 0)
        self.ep_degree = kwargs.pop("ep_degree", 1)
        self.moe_tp_degree = kwargs.pop("moe_tp_degree", None)
        self.moe_ep_degree = kwargs.pop("moe_ep_degree", None)
        # per-phase hybrid MoE sharding (reference: HybridShardingConfig,
        # models/config.py:1060): prefill compiles TP-heavy (experts over a
        # small cte-ep axis), decode EP-heavy (experts over cte-ep x epx).
        # Expert weights are DUPLICATED per regime like the reference's
        # preshard hook (mlp_op_tkg duplication) — relayout-free at phase
        # transitions at the cost of one extra per-rank expert shard copy.
        hsc = kwargs.pop("hybrid_sharding_config", None)
        if isinstance(hsc, dict):
            hsc = HybridShardingConfig(**hsc)
        self.hybrid_sharding_config = hsc
        # "sparse" = ragged_dot grouped matmul over routed tokens (default);
        # "dense" = all experts compute all tokens (reference ExpertMLPs
        # non-blockwise mode; kept as an A/B and debugging fallback)
        self.moe_dispatch = kwargs.pop("moe_dispatch", "sparse")
        if self.moe_dispatch not in ("sparse", "dense"):
            raise ValueError(
                f"moe_dispatch must be 'sparse' or 'dense', got {self.moe_dispatch!r}"
            )
        self.world_size = kwargs.pop("world_size", None)
        if self.world_size is None:
            self.world_size = self.tp_degree * self.pp_degree
        self.start_rank_id = kwargs.pop("start_rank_id", 0)
        self.sequence_parallel_enabled = kwargs.pop("sequence_parallel_enabled", False)
        # MLP-CP (reference: mlp_cp_degree config.py:364,374-375): without SP
        # this shards JUST the MLP block's stream on S (the mlp_hidden policy,
        # parallel/policy.py); with SP the whole inter-layer stream is already
        # S-sharded and the knob is subsumed.
        self.mlp_cp_degree = kwargs.pop("mlp_cp_degree", 1)
        self.flash_decoding_enabled = kwargs.pop("flash_decoding_enabled", False)
        self.num_cores_per_group = kwargs.pop("num_cores_per_group", 1)
        self.vocab_parallel = kwargs.pop("vocab_parallel", True)

        # --- kernels (reference: config.py:418-533). On TPU these gate Pallas kernels;
        # the XLA path is always available as fallback.
        self.attn_kernel_enabled = kwargs.pop("attn_kernel_enabled", None)
        self.attn_tkg_kernel_enabled = kwargs.pop("attn_tkg_kernel_enabled", False)
        self.attn_block_tkg_kernel_enabled = kwargs.pop("attn_block_tkg_kernel_enabled", False)
        self.fused_qkv = kwargs.pop("fused_qkv", False)
        self.qkv_kernel_enabled = kwargs.pop("qkv_kernel_enabled", False)
        self.mlp_kernel_enabled = kwargs.pop("mlp_kernel_enabled", False)
        self.k_cache_transposed = kwargs.pop("k_cache_transposed", False)

        # --- misc/debug ---
        self.qk_layernorm = kwargs.pop("qk_layernorm", False)
        self.sliding_window = kwargs.pop("sliding_window", None)
        # window-sized ring KV cache for uniformly sliding-window models
        # (reference: window-sized cache shapes kv_cache_manager.py:195-210):
        # cache S dim = sliding_window slots instead of seq_len
        self.window_sized_kv = kwargs.pop("window_sized_kv", False)
        # long-context mode (reference: enable_long_context_mode, derived at
        # >=32k — models/config.py:578-587 sets Neuron runtime/compiler modes;
        # the TPU analog coarsens the bucket ladders so 128k-class configs
        # don't compile a dozen huge CTE programs). Auto-on at 32k; override
        # explicitly to force either way.
        _lcm = kwargs.pop("long_context_mode", None)
        self.long_context_mode = (
            bool(_lcm) if _lcm is not None else self.seq_len >= 32 * 1024
        )
        self.windowed_context_encoding_size = kwargs.pop("windowed_context_encoding_size", None)
        self.logical_nc_config = kwargs.pop("logical_nc_config", 1)
        self.skip_warmup = kwargs.pop("skip_warmup", False)
        self.save_sharded_checkpoint = kwargs.pop("save_sharded_checkpoint", False)
        self.compilation_cache_dir = kwargs.pop("compilation_cache_dir", None)
        tcc = kwargs.pop("tensor_capture_config", None)
        if isinstance(tcc, dict):
            tcc = TensorCaptureConfig(**tcc)
        self.tensor_capture_config = tcc
        trc = kwargs.pop("tensor_replacement_config", None)
        if isinstance(trc, dict):
            trc = TensorReplacementConfig(**trc)
        self.tensor_replacement_config = trc
        # serving telemetry (nxdi_tpu/telemetry): always-on metrics registry
        # + request spans; accepts a TelemetryConfig, a dict of its kwargs, or
        # a detail-level string ("off" | "basic" | "full")
        tel = kwargs.pop("telemetry", None)
        if isinstance(tel, str):
            tel = TelemetryConfig(detail=tel)
        elif isinstance(tel, dict):
            tel = TelemetryConfig(**tel)
        elif tel is None:
            tel = TelemetryConfig()
        self.telemetry = tel
        # declared serving SLOs (nxdi_tpu/telemetry/slo.py): TTFT/TPOT
        # latency targets the SLO tracker measures attainment against and
        # the flight recorder's breach trigger fires on. An SloConfig, a
        # dict of its kwargs, or None (no SLO declared — nothing tracked).
        slo = kwargs.pop("slo", None)
        if isinstance(slo, dict):
            slo = SloConfig(**slo)
        self.slo = slo
        # QoS control plane, engine tier (nxdi_tpu/control/qos.py):
        # multi-tenant token-bucket quotas + deadline-aware admission and
        # preemption over priority classes. A QosConfig, a dict of its
        # kwargs, True (defaults), or None (off — admission stays FCFS/
        # cache-aware and output is byte-identical to previous rounds).
        qos = kwargs.pop("qos", None)
        if qos is True:
            qos = QosConfig()
        elif isinstance(qos, dict):
            qos = QosConfig(**qos)
        self.qos = qos
        # numerics sentinel (nxdi_tpu/telemetry/sentinel.py): in-graph
        # logit-health stats + sampled shadow-replay verification + the
        # preemption-replay invariant. A SentinelConfig, a dict of its
        # kwargs, True (defaults), or None (off — no stats compiled in,
        # serving output byte-identical to previous rounds).
        sentinel = kwargs.pop("sentinel", None)
        if sentinel is True:
            sentinel = SentinelConfig()
        elif isinstance(sentinel, dict):
            sentinel = SentinelConfig(**sentinel)
        self.sentinel = sentinel
        # fault tolerance (nxdi_tpu/runtime/faults.py): dispatch watchdog +
        # step-fault recovery budgets. A FaultConfig, a dict of its kwargs,
        # True (defaults), or None (defaults too — recovery is always on;
        # the config only tunes budgets and opts into the watchdog).
        faults = kwargs.pop("faults", None)
        if faults is True or faults is None:
            faults = FaultConfig()
        elif isinstance(faults, dict):
            faults = FaultConfig(**faults)
        self.faults = faults
        # declared chip generation for the cost observatory's roofline math
        # and the hbm_fit auditor checker (analysis/costs.py): a name from
        # CHIP_SPECS ("v4"|"v5e"|"v5p"|"v6e"), or a dict of ChipSpec field
        # overrides (optionally with "base": name). None = v5e.
        self.chip = kwargs.pop("chip", None)
        # serve-time retrace guard (analysis/retrace.py): "warn" logs and
        # "error" raises when any submodel program lowers AFTER warmup sealed
        # the program set (a mid-serving retrace blocks requests on multi-
        # second compilation); "off" disables recording enforcement.
        self.retrace_guard = kwargs.pop("retrace_guard", "warn")
        self.allow_unknown = kwargs.pop("allow_unknown", False)

        self.is_prefill_stage = None  # set by enable_context_encoding/token_generation

        if kwargs and not self.allow_unknown:
            raise ValueError(f"Unknown TpuConfig arguments: {sorted(kwargs)}")
        self.validate()

    # -- validation (reference: config.py:611-687 does similar cross-checks) --
    def validate(self) -> None:
        if self.padding_side not in ("right", "left"):
            raise ValueError("padding_side must be 'right' or 'left'")
        if self.retrace_guard not in ("off", "warn", "error"):
            raise ValueError(
                f"retrace_guard must be 'off'|'warn'|'error', got {self.retrace_guard!r}"
            )
        if self.chip is not None:
            if not isinstance(self.chip, (str, dict)):
                raise ValueError(
                    "chip must be a chip name or a dict of ChipSpec overrides "
                    f"(analysis/costs.py CHIP_SPECS), got {type(self.chip)}"
                )
            # resolve eagerly so a typo'd name/field fails HERE, not inside a
            # swallowed export attachment or an auditor checker at serve time
            from nxdi_tpu.analysis.costs import resolve_chip

            try:
                resolve_chip(override=self.chip)
            except (TypeError, ValueError) as e:
                raise ValueError(f"invalid TpuConfig chip={self.chip!r}: {e}")
        if self.max_context_length > self.seq_len:
            raise ValueError(
                f"max_context_length ({self.max_context_length}) cannot exceed seq_len ({self.seq_len})"
            )
        if self.cp_degree > 1 and self.tp_degree % self.cp_degree != 0:
            raise ValueError("cp_degree must divide tp_degree (CP splits the TP world)")
        if self.attention_dp_degree > 1:
            if self.tp_degree % (self.attention_dp_degree * self.cp_degree) != 0:
                raise ValueError(
                    "attention_dp_degree * cp_degree must divide tp_degree "
                    "(both carve sub-axes out of the TP world)"
                )
            if self.tkg_batch_size % self.attention_dp_degree != 0:
                raise ValueError("tkg_batch_size must be divisible by attention_dp_degree")
        if self.flash_decoding_enabled:
            if self.attention_dp_degree > 1:
                raise ValueError(
                    "flash_decoding_enabled and attention_dp_degree > 1 are "
                    "mutually exclusive: both claim the decode KV cache layout"
                )
            if self.cp_degree <= 1:
                raise ValueError(
                    "flash_decoding_enabled shards the KV cache sequence dim over "
                    "the cp mesh axis; set cp_degree > 1"
                )
            if self.enable_bucketing or self.token_generation_buckets:
                raise ValueError(
                    "flash decoding requires a single token-generation bucket: "
                    "the cache sequence dim is sharded and cannot be re-windowed "
                    "per bucket"
                )
        if self.pp_degree > 1:
            n_micro = self.pp_microbatches or self.pp_degree
            if self.is_block_kv_layout:
                raise ValueError(
                    "pipeline parallel composes with the contiguous KV layout "
                    "only (the paged pool is not batch-addressable per stage)"
                )
            if self.flash_decoding_enabled or self.attention_dp_degree > 1 or self.cp_degree > 1:
                raise ValueError(
                    "pipeline parallel currently composes with tp/sp only "
                    "(cp / attention-dp / flash-decoding also reshard the "
                    "batch or cache dims the pipeline microbatches over)"
                )
            for name, bs in (("batch_size", self.batch_size),
                             ("ctx_batch_size", self.ctx_batch_size),
                             ("tkg_batch_size", self.tkg_batch_size)):
                if bs and bs % n_micro != 0:
                    raise ValueError(
                        f"{name} ({bs}) must be divisible by pp_microbatches ({n_micro})"
                    )
        if self.hybrid_sharding_config is not None:
            hsc = self.hybrid_sharding_config
            if self.moe_ep_degree and self.moe_ep_degree > 1:
                raise ValueError(
                    "hybrid_sharding_config replaces moe_ep_degree (the mesh "
                    "ep/epx axes come from the per-phase degrees)"
                )
            if self.tp_degree % hsc.moe_tkg_ep_degree:
                raise ValueError(
                    f"moe_tkg_ep_degree ({hsc.moe_tkg_ep_degree}) must divide "
                    f"tp_degree ({self.tp_degree})"
                )
        kvq = self.kv_quant_config
        if kvq is not None and kvq.scale_mode in ("per_key", "per_channel"):
            if self.is_block_kv_layout or self.window_sized_kv:
                raise ValueError(
                    f"kv quant scale_mode={kvq.scale_mode!r} composes with the "
                    "contiguous KV layout only (paged/ring layouts take "
                    "per-tensor scales)"
                )
            if self.pp_degree > 1:
                raise ValueError(
                    f"kv quant scale_mode={kvq.scale_mode!r} is not supported "
                    "under pipeline parallel yet (per-layer scale indexing "
                    "needs the in-scan layer index)"
                )
        # fused projection kernels (reference: fused_qkv gqa.py:557, "QKV
        # kernel only supported when fused_qkv is TRUE" gqa.py:669) — these
        # flags either engage their kernels or raise; never a silent no-op
        if self.qkv_kernel_enabled and not self.fused_qkv:
            raise ValueError(
                "qkv_kernel_enabled requires fused_qkv=True (the kernel runs "
                "over the fused interleaved QKV weight)"
            )
        if self.fused_qkv and self.lora_config is not None:
            raise ValueError(
                "fused_qkv does not compose with LoRA serving (adapters "
                "target the separate q/k/v projections)"
            )
        if self.mlp_kernel_enabled and self.lora_config is not None:
            raise ValueError(
                "mlp_kernel_enabled does not compose with LoRA serving"
            )
        if self.mlp_kernel_enabled and self.quantized:
            raise ValueError(
                "mlp_kernel_enabled composes with full-precision weights only "
                "for now (quantized fused MLP is not implemented)"
            )
        if (self.mlp_kernel_enabled or self.qkv_kernel_enabled) and (
            self.window_sized_kv or self.pp_degree > 1
        ):
            # those paths scan without the stacked-weight extraction, so the
            # kernels would silently pay a per-layer weight slice copy
            raise ValueError(
                "mlp_kernel_enabled/qkv_kernel_enabled are not supported with "
                "window_sized_kv or pipeline parallel yet"
            )
        if self.window_sized_kv:
            if not self.sliding_window:
                raise ValueError(
                    "window_sized_kv needs tpu_config.sliding_window (the ring "
                    "slot count) — set it to the model's sliding window"
                )
            if self.sliding_window > self.seq_len:
                raise ValueError(
                    f"window_sized_kv ring ({self.sliding_window} slots) cannot "
                    f"exceed seq_len ({self.seq_len}) — the ring layout would "
                    "address slots the cache does not have"
                )
            if (
                self.is_block_kv_layout
                or self.is_medusa
                or self.is_prefix_caching
                or self.is_chunked_prefill
                or self.flash_decoding_enabled
            ):
                raise ValueError(
                    "window_sized_kv composes with contiguous decode (and "
                    "linear speculation) only: paged/medusa/prefix modes "
                    "assume position-addressed cache slots, which the ring "
                    "layout does not provide"
                )
            if self.speculation_length > 0 or self.enable_fused_speculation:
                # linear speculation over a ring: the ring is over-provisioned
                # by spec_len+1 slots so rejected-draft writes can never
                # clobber a slot still inside any query's attention window,
                # and stale rejected rows always resolve to out-of-window
                # positions (see kvcache WindowKVLayout docstring)
                if self.window_ring_slots > self.seq_len:
                    raise ValueError(
                        f"window_sized_kv + speculation needs sliding_window +"
                        f" speculation_length + 1 = {self.window_ring_slots} "
                        f"ring slots, which exceeds seq_len ({self.seq_len})"
                    )
        if self.mlp_cp_degree and self.mlp_cp_degree > 1:
            # without SP this engages the dedicated MLP-CP policy (only the
            # MLP stream shards on S — parallel/policy.py mlp_hidden); with
            # SP the whole inter-layer stream is already S-sharded and the
            # knob is subsumed. GSPMD shards S over the FULL model-parallel
            # axis — partial subgroup S-sharding has no mesh sub-axis to
            # land on, so intermediate degrees are rejected loudly rather
            # than silently promoted.
            if self.mlp_cp_degree != self.tp_degree:
                raise ValueError(
                    f"mlp_cp_degree ({self.mlp_cp_degree}) must equal "
                    f"tp_degree ({self.tp_degree}) (or 1): the MLP-CP policy "
                    "shards the MLP stream's S dim over the whole tp axis"
                )
        if self.is_medusa and self.num_medusa_heads <= 0:
            raise ValueError("is_medusa requires num_medusa_heads > 0")
        if self.lora_config is not None and self.async_mode:
            raise ValueError(
                "LoRA serving is incompatible with async_mode: the device-"
                "resident decode loop cannot carry per-request adapter_ids"
            )
        if self.lora_config is not None and (
            self.enable_fused_speculation or self.is_medusa or self.speculation_length > 0
        ):
            raise ValueError(
                "LoRA serving is not supported with speculative decoding yet: "
                "the speculation graphs do not thread adapter_ids"
            )
        if self.speculation_length < 0:
            raise ValueError("speculation_length must be >= 0")
        if self.decode_steps_per_dispatch < 1:
            raise ValueError("decode_steps_per_dispatch must be >= 1")
        if self.decode_steps_per_dispatch > 1:
            # the K-step scan samples, advances positions, and commits KV
            # in-graph — host-side sampling / speculative strides / per-step
            # host inputs cannot ride inside it
            if self.on_device_sampling_config is None:
                raise ValueError(
                    "decode_steps_per_dispatch > 1 requires on-device sampling "
                    "(the K-step scan samples each token in-graph)"
                )
            if (
                self.enable_fused_speculation
                or self.is_medusa
                or self.speculation_length > 0
            ):
                raise ValueError(
                    "decode_steps_per_dispatch > 1 and speculative decoding "
                    "both own the token-generation stride; enable one"
                )
            if self.is_block_kv_layout:
                raise ValueError(
                    "decode_steps_per_dispatch > 1 needs in-graph KV "
                    "addressing by position; the block layout's slot mappings "
                    "are host-computed per step"
                )
            if self.lora_config is not None:
                raise ValueError(
                    "decode_steps_per_dispatch > 1 does not thread per-request "
                    "adapter_ids through the in-graph decode scan yet"
                )
            if (
                self.tensor_capture_config is not None
                or self.tensor_replacement_config is not None
            ):
                raise ValueError(
                    "decode_steps_per_dispatch > 1 does not compose with "
                    "tensor capture/replacement (per-step host tensors cannot "
                    "ride the in-graph scan)"
                )
            if self.ctx_batch_size != self.tkg_batch_size:
                # windows chain device-resident off the CTE's next_inputs
                # (already padded to the CTE batch), so both programs must
                # share one compiled batch — the same invariant the async
                # 1-step chain enforces (application.async_supported)
                raise ValueError(
                    "decode_steps_per_dispatch > 1 requires ctx_batch_size == "
                    "tkg_batch_size (the K-step windows chain device-resident "
                    "from the context-encoding outputs)"
                )
        if self.device_loop_fence < 0:
            raise ValueError("device_loop_fence must be >= 0 (0 = unlimited)")
        if self.device_loop:
            # the while-loop body samples, advances positions, and commits KV
            # in-graph — the same closed-world contract as the K-step scan,
            # plus a data-dependent trip count no host input can ride inside
            if self.on_device_sampling_config is None:
                raise ValueError(
                    "device_loop requires on-device sampling (the loop body "
                    "samples each token in-graph)"
                )
            if (
                self.enable_fused_speculation
                or self.is_medusa
                or self.speculation_length > 0
            ):
                raise ValueError(
                    "device_loop and speculative decoding both own the "
                    "token-generation stride; enable one"
                )
            if self.is_block_kv_layout:
                raise ValueError(
                    "device_loop needs in-graph KV addressing by position; "
                    "the block layout's slot mappings are host-computed per "
                    "step"
                )
            if self.lora_config is not None:
                raise ValueError(
                    "device_loop does not thread per-request adapter_ids "
                    "through the in-graph decode loop yet"
                )
            if (
                self.tensor_capture_config is not None
                or self.tensor_replacement_config is not None
            ):
                raise ValueError(
                    "device_loop does not compose with tensor capture/"
                    "replacement (per-step host tensors cannot ride the "
                    "in-graph loop)"
                )
            if self.ctx_batch_size != self.tkg_batch_size:
                raise ValueError(
                    "device_loop requires ctx_batch_size == tkg_batch_size "
                    "(loop launches share the decode batch the CTE filled)"
                )
            if self.mixed_dispatch:
                raise ValueError(
                    "device_loop and mixed_dispatch are different serving "
                    "step shapes (resident decode loop vs one packed "
                    "prefill+decode program); enable one"
                )
        if self.is_block_kv_layout and self.pa_num_blocks is None:
            self.pa_num_blocks = max(
                1, (self.seq_len * self.max_batch_size) // self.pa_block_size
            )
        if self.is_prefix_caching and not self.is_block_kv_layout:
            raise ValueError("is_prefix_caching requires is_block_kv_layout")
        if self.is_chunked_prefill and not self.is_block_kv_layout:
            raise ValueError("chunked prefill requires is_block_kv_layout")
        if self.mixed_dispatch and not self.is_block_kv_layout:
            raise ValueError(
                "mixed_dispatch requires is_block_kv_layout (the packed rows "
                "read KV through the paged block tables)"
            )
        if self.role not in ("unified", "prefill", "decode"):
            raise ValueError(
                f"role must be 'unified', 'prefill' or 'decode', got {self.role!r}"
            )
        if self.role != "unified":
            if not self.is_block_kv_layout:
                raise ValueError(
                    f"role={self.role!r} requires is_block_kv_layout (the KV "
                    "handoff plane exports/imports paged block chains)"
                )
            if self.mixed_dispatch:
                raise ValueError(
                    "mixed_dispatch is inherently a unified prefill+decode "
                    f"program; it cannot compose with role={self.role!r}"
                )
            if self.role == "prefill" and (
                self.decode_steps_per_dispatch > 1 or self.device_loop
            ):
                raise ValueError(
                    "role='prefill' ships only CTE/prefix-prefill + a 1-token "
                    "TKG; decode_steps_per_dispatch > 1 and device_loop are "
                    "decode-role program shapes"
                )

    # -- (de)serialization (reference: config.py:891-1002) --
    _SUBCONFIGS = {
        "on_device_sampling_config": OnDeviceSamplingConfig,
        "kv_quant_config": KVQuantizationConfig,
        "chunked_prefill_config": ChunkedPrefillConfig,
        "tensor_capture_config": TensorCaptureConfig,
        "tensor_replacement_config": TensorReplacementConfig,
        "speculation_config": SpeculationConfig,
        "lora_config": LoraServingConfig,
        "hybrid_sharding_config": HybridShardingConfig,
        "telemetry": TelemetryConfig,
        "slo": SloConfig,
        "sentinel": SentinelConfig,
        "faults": FaultConfig,
    }

    @property
    def window_ring_slots(self) -> int:
        """Slot count of the window-sized ring stacks. Plain decode rings
        hold exactly ``sliding_window`` slots; under linear speculation the
        ring is over-provisioned by the spec window (spec_len + 1) so
        rejected-draft writes land in slots whose previous occupants are
        already outside every query's attention window."""
        lookahead = (
            self.speculation_length + 1 if self.speculation_length > 0 else 0
        )
        return int(self.sliding_window or 0) + lookahead

    def to_dict(self) -> Dict[str, Any]:
        out = {}
        derived = ("is_prefill_stage", "allow_unknown", "is_chunked_prefill")
        for k, v in self.__dict__.items():
            if k in derived:
                continue
            if k in self._SUBCONFIGS:
                out[k] = v.to_dict() if v is not None else None
            elif k in ("dtype", "attention_dtype", "rpl_reduce_dtype", "softmax_dtype"):
                out[k] = dtype_name(v) if v is not None else None
            else:
                out[k] = v
        return out

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "TpuConfig":
        return cls(**{k: v for k, v in dict(d).items() if v is not None or k.endswith("_config")})

    def copy(self, **overrides) -> "TpuConfig":
        d = self.to_dict()
        d.update(overrides)
        return TpuConfig.from_dict(d)


class InferenceConfig:
    """Model hyperparameters + a :class:`TpuConfig` (reference: models/config.py:813).

    ``load_config`` is a callable returning a dict of hyperparameters — typically
    :func:`nxdi_tpu.generation.hf_adapter.load_pretrained_config` wrapping a HF
    ``config.json`` (reference: utils/hf_adapter.py:36).
    """

    # attributes that must exist after construction (reference: config.py:841-858)
    REQUIRED = ["hidden_size", "num_attention_heads", "num_hidden_layers", "vocab_size"]

    def __init__(self, tpu_config: TpuConfig, load_config=None, metadata=None, **kwargs):
        self.tpu_config = tpu_config
        self.metadata = metadata or {}
        if load_config is not None:
            for k, v in load_config().items():
                setattr(self, k, v)
        for k, v in kwargs.items():
            setattr(self, k, v)
        self.add_derived_config()
        self.validate_config()

    # subclasses override (reference: config.py:860-888)
    def add_derived_config(self) -> None:
        if not hasattr(self, "num_key_value_heads") and hasattr(self, "num_attention_heads"):
            self.num_key_value_heads = self.num_attention_heads
        if not hasattr(self, "head_dim") and hasattr(self, "hidden_size"):
            self.head_dim = self.hidden_size // self.num_attention_heads

    def get_required_attributes(self) -> List[str]:
        return list(self.REQUIRED)

    def validate_config(self) -> None:
        missing = [a for a in self.get_required_attributes() if not hasattr(self, a)]
        if missing:
            raise ValueError(f"InferenceConfig missing required attributes: {missing}")

    # -- JSON round trip (reference: config.py:891-1002) --
    def to_dict(self) -> Dict[str, Any]:
        out = {}
        for k, v in self.__dict__.items():
            if k == "tpu_config":
                out[k] = v.to_dict()
            elif k == "fused_spec_config" and v is not None:
                out[k] = v.to_dict() if hasattr(v, "to_dict") else v
            else:
                try:
                    json.dumps(v)
                    out[k] = v
                except TypeError:
                    continue  # non-serializable helper attrs are reconstructable
        return out

    def save(self, model_path: str) -> str:
        os.makedirs(model_path, exist_ok=True)
        path = os.path.join(model_path, CONFIG_FILE)
        with open(path, "w") as f:
            json.dump(self.to_dict(), f, indent=2, sort_keys=True)
        return path

    @classmethod
    def load(cls, model_path: str, **kwargs) -> "InferenceConfig":
        with open(os.path.join(model_path, CONFIG_FILE)) as f:
            d = json.load(f)
        tpu_config = TpuConfig.from_dict(d.pop("tpu_config"))
        obj = cls.__new__(cls)
        obj.tpu_config = tpu_config
        obj.metadata = {}
        for k, v in d.items():
            setattr(obj, k, v)
        for k, v in kwargs.items():
            setattr(obj, k, v)
        obj.add_derived_config()
        obj.validate_config()
        return obj


class FusedSpecConfig:
    """Pairs a draft model config with the target for fused speculation
    (reference: models/config.py:1009 ``FusedSpecNeuronConfig``)."""

    def __init__(self, worker_cls_name: str, draft_config: InferenceConfig, draft_model_path: str):
        self.worker_cls_name = worker_cls_name
        self.draft_config = draft_config
        self.draft_model_path = draft_model_path

    def to_dict(self):
        return {
            "worker_cls_name": self.worker_cls_name,
            "draft_config": self.draft_config.to_dict(),
            "draft_model_path": self.draft_model_path,
        }
