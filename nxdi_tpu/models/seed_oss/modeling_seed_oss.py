"""Seed-OSS family — llama with q/k/v biases, a separate o-bias switch, and
an explicit ``head_dim``.

Reference: contrib/models/Seed-OSS-36B-Instruct. HF SeedOssForCausalLM
(modeling_seed_oss.py:158-231): q/k/v carry ``attention_bias``, o_proj
carries ``attention_out_bias``; rope and norms are the llama standard."""

from __future__ import annotations

from nxdi_tpu.config import InferenceConfig
from nxdi_tpu.models import dense
from nxdi_tpu.models.base import DecoderArch

build_inv_freq = dense.build_inv_freq


class SeedOssInferenceConfig(dense.DenseInferenceConfig):
    def add_derived_config(self):
        super().add_derived_config()
        for k, v in (("attention_bias", True), ("attention_out_bias", False),
                     ("mlp_bias", False)):
            if not hasattr(self, k) or getattr(self, k) is None:
                setattr(self, k, v)


def build_arch(config: InferenceConfig, **overrides) -> DecoderArch:
    kwargs = dict(
        attention_bias=bool(getattr(config, "attention_bias", True)),
        attention_o_bias=bool(getattr(config, "attention_out_bias", False)),
        mlp_bias=bool(getattr(config, "mlp_bias", False)),
    )
    kwargs.update(overrides)
    return dense.build_arch(config, **kwargs)


def convert_hf_state_dict(state_dict, config: InferenceConfig):
    return dense.convert_hf_state_dict(state_dict, config, build_arch(config))


def param_specs(config: InferenceConfig):
    return dense.param_specs_for(build_arch(config))


def param_shape_struct(config: InferenceConfig):
    return dense.param_shape_struct(config, build_arch(config))
