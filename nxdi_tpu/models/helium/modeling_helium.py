"""Helium (Kyutai) family — llama with the GPT-J interleaved-pair rope.

Reference: contrib/models/helium-1-2b. HF HeliumForCausalLM
(modeling_helium.py:154-189): GLM/GPT-J INTERLEAVED-pair rope over the full
head dim (repeat_interleave'd cos/sin, adjacent (2i, 2i+1) channel pairs);
everything else is the llama standard (optional q/k/v biases, o_proj
without)."""

from __future__ import annotations

from nxdi_tpu.config import InferenceConfig
from nxdi_tpu.models import dense
from nxdi_tpu.models.base import DecoderArch

build_inv_freq = dense.build_inv_freq


class HeliumInferenceConfig(dense.DenseInferenceConfig):
    pass


def build_arch(config: InferenceConfig, **overrides) -> DecoderArch:
    kwargs = dict(rope_interleaved=True)
    kwargs.update(overrides)
    return dense.build_arch(config, **kwargs)


def convert_hf_state_dict(state_dict, config: InferenceConfig):
    return dense.convert_hf_state_dict(state_dict, config, build_arch(config))


def param_specs(config: InferenceConfig):
    return dense.param_specs_for(build_arch(config))


def param_shape_struct(config: InferenceConfig):
    return dense.param_shape_struct(config, build_arch(config))
