"""Image-to-text application: vision encoder + CTE + TKG orchestration.

The analog of the reference's image-to-text base (models/
image_to_text_model_base.py:34,118 and image_to_text_model_wrapper.py:19):
a vision-encoder submodel produces projected image features; the
context-encoding graph merges them into the token-embedding stream at the
image-placeholder positions (models/base.py image_token_id merge); token
generation runs unchanged.

The vision encoder compiles as its own jitted program over the ``vision`` /
``projector`` sub-pytrees (reference: EncoderModelInstance,
model_wrapper.py:1616). Vision params are replicated — towers are small
relative to the LM; TP sharding of the tower is a later optimization.
"""

from __future__ import annotations

from functools import partial

import jax
import numpy as np

from nxdi_tpu.runtime.application import TAG_PREFIX_PREFILL, TpuModelForCausalLM
from nxdi_tpu.runtime.model_wrapper import TAG_CONTEXT_ENCODING

TAG_VISION_ENCODER = "vision_encoder_model"


class ImageToTextForCausalLM(TpuModelForCausalLM):
    """CausalLM whose prefill consumes image features (reference:
    NeuronBaseForImageToText three-submodel flow).

    The model family module must additionally expose:
      - ``build_vision_arch(config)`` -> static vision arch,
      - ``convert_vision_params(state_dict, config)`` -> {"vision", "projector"},
      - ``vision_shape_struct(config)`` -> matching ShapeDtypeStruct pytree,
      - ``encode_images(vision_arch, params, pixel_values)`` -> (B, N, hidden),
      - ``num_image_tokens(config)`` and ``config.image_token_index``.
    """

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        for attr in ("build_vision_arch", "convert_vision_params", "encode_images"):
            if not hasattr(self.family, attr):
                raise ValueError(
                    f"model family {self.family.__name__} does not expose {attr}; "
                    "not an image-to-text family"
                )
        self._encode_jit = None

    # -- params: text + vision/projector sub-pytrees --
    def build_params(self):
        # one checkpoint read shared by the text + vision conversions
        return self.build_params_with_extras(
            super().build_params, self.family.convert_vision_params
        )

    def build_params_struct(self):
        struct = super().build_params_struct()
        struct.update(self.family.vision_shape_struct(self.config))
        return struct

    def param_specs(self):
        from jax.sharding import PartitionSpec as P

        specs = super().param_specs()
        struct = self.family.vision_shape_struct(self.config)
        specs.update(jax.tree_util.tree_map(lambda _: P(), struct))
        return specs

    # -- submodels: CTE takes image_embeds; vision encoder is its own program --
    def enable_models(self) -> None:
        super().enable_models()
        import jax.numpy as jnp

        N = self.family.num_image_tokens(self.config)
        # every prefill-shaped submodel must carry the image inputs — a
        # prefix/chunked continuation prefill can also contain placeholders
        for tag in (TAG_CONTEXT_ENCODING, TAG_PREFIX_PREFILL):
            w = self.models.get(tag)
            if w is None:
                continue
            w.extra_inputs["image_embeds"] = ((N, self.config.hidden_size), jnp.float32)
            w.forward_kwargs["image_token_id"] = int(self.config.image_token_index)

    def encode_images(self, pixel_values: np.ndarray):
        """Run the vision tower + projector (compiled on first use per shape;
        reference: the vision encoder submodel invoked before CTE)."""
        if self._encode_jit is None:
            varch = self.family.build_vision_arch(self.config)
            self._encode_jit = jax.jit(partial(self.family.encode_images, varch))
        with jax.set_mesh(self.mesh):
            return self._encode_jit(
                {"vision": self.params["vision"], "projector": self.params["projector"]},
                np.asarray(pixel_values, dtype=np.float32),
            )

    def forward(self, input_ids, position_ids, pixel_values=None, **kwargs):
        if pixel_values is not None:
            kwargs["image_embeds"] = self.encode_images(pixel_values)
        if "image_embeds" in kwargs:
            n_placeholders = int(
                (np.asarray(input_ids) == int(self.config.image_token_index)).sum(axis=1).max()
            )
            n_feats = kwargs["image_embeds"].shape[1]
            if n_placeholders > n_feats:
                raise ValueError(
                    f"prompt contains {n_placeholders} image-placeholder tokens "
                    f"but the vision encoder produced only {n_feats} features "
                    "(image features and image tokens do not match)"
                )
        return super().forward(input_ids, position_ids, **kwargs)
