"""Shared base for cross-attention vision-language applications (mllama,
idefics): vision params riding the text pytree, cross-KV entries in the
donated cache, and the common unsupported-mode guard.

Reference analog: the multimodal KV manager + image-to-text wrappers
(modules/kvcache/multimodal_kv_cache_manager.py, image_to_text_model_wrapper
.py) that both reference families build on."""

from __future__ import annotations

from typing import Tuple

import jax

from nxdi_tpu.kvcache.kv_cache import kv_cache_partition_spec
from nxdi_tpu.runtime.application import TpuModelForCausalLM


class CrossAttentionVLApplication(TpuModelForCausalLM):
    """Subclasses set ``FAMILY_NAME`` (for error text) and implement
    ``_cross_kv_shape()`` -> (n_cross, B, KV, T, D); ``self.family`` must
    expose convert_vision_params / vision_shape_struct."""

    FAMILY_NAME = "cross-attention VL model"

    def _cross_kv_shape(self) -> Tuple[int, ...]:
        raise NotImplementedError

    def _reject_unsupported(self):
        tc = self.tpu_config
        for flag, why in (
            (tc.async_mode, "async (device-resident) decode"),
            (tc.is_block_kv_layout, "paged KV layout"),
            (tc.lora_config is not None, "LoRA serving"),
            (tc.speculation_length > 0, "speculative decoding"),
            (tc.enable_fused_speculation, "fused speculation"),
            (tc.is_medusa, "medusa"),
            (getattr(tc, "pp_degree", 1) > 1, "pipeline parallel"),
            (tc.is_prefix_caching or tc.is_chunked_prefill, "prefix/chunked prefill"),
            (tc.is_continuous_batching, "continuous batching (cross-KV is not "
             "seq-id routed yet)"),
            (tc.kv_quant_config is not None,
             "KV-cache quantization (untested with the cross-KV store)"),
        ):
            if flag:
                raise NotImplementedError(
                    f"{self.FAMILY_NAME} does not support {why} yet"
                )

    # -- params: text + vision sub-pytrees from ONE checkpoint read --
    def build_params(self):
        return self.build_params_with_extras(
            super().build_params, self.family.convert_vision_params
        )

    def build_params_struct(self):
        struct = super().build_params_struct()
        struct.update(self.family.vision_shape_struct(self.config))
        return struct

    def param_specs(self):
        from jax.sharding import PartitionSpec as P

        specs = super().param_specs()
        struct = self.family.vision_shape_struct(self.config)
        specs.update(jax.tree_util.tree_map(lambda _: P(), struct))
        return specs

    # -- cache: self-attn KV + cross-attn KV --
    def _cross_cache_struct(self):
        from nxdi_tpu.config import to_jax_dtype

        # COMPUTE dtype, not the (possibly quantized) self-attn store dtype:
        # the cross store has no scale plumbing, so a quantized cast would
        # silently corrupt the vision keys — under kv_quant_config only the
        # position-addressed self stacks quantize (guarded above anyway)
        dt = to_jax_dtype(self.family.build_arch(self.config).text.dtype)
        shape = self._cross_kv_shape()
        return {
            "cross_k": jax.ShapeDtypeStruct(shape, dt),
            "cross_v": jax.ShapeDtypeStruct(shape, dt),
        }

    def _cache_struct(self):
        struct = super()._cache_struct()
        struct.update(self._cross_cache_struct())
        return struct

    def init_cache_host(self):
        import jax.numpy as jnp

        cache = super().init_cache_host()
        for k, s in self._cross_cache_struct().items():
            cache[k] = jnp.zeros(s.shape, s.dtype)
        return cache

    def cache_partition_specs(self):
        specs = dict(kv_cache_partition_spec(self.tpu_config))
        specs["cross_k"] = specs["k"]
        specs["cross_v"] = specs["k"]
        return specs
