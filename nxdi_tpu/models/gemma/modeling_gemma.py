"""Gemma (v1) family — (1+w) float32 RMSNorms, sqrt(H) embed scale, tied head.

Reference: contrib/models/gemma-2b-it. HF GemmaForCausalLM
(modeling_gemma.py:46-260): the gemma norm convention and embedding
normalizer but NONE of gemma2's extras — standard pre/post block norms (no
sandwich), no softcapping, head_dim**-0.5 scaling, one rope table."""

from __future__ import annotations

from nxdi_tpu.config import InferenceConfig
from nxdi_tpu.models import dense
from nxdi_tpu.models.base import DecoderArch

build_inv_freq = dense.build_inv_freq


class GemmaInferenceConfig(dense.DenseInferenceConfig):
    REQUIRED = dense.DenseInferenceConfig.REQUIRED + ["head_dim"]

    def add_derived_config(self):
        if getattr(self, "hidden_activation", None):
            self.hidden_act = self.hidden_activation
        elif not hasattr(self, "hidden_act"):
            self.hidden_act = "gelu_pytorch_tanh"
        super().add_derived_config()


def build_arch(config: InferenceConfig, **overrides) -> DecoderArch:
    kwargs = dict(
        gemma_norm=True,
        embed_scale=float(config.hidden_size) ** 0.5,
        tie_word_embeddings=bool(getattr(config, "tie_word_embeddings", True)),
    )
    kwargs.update(overrides)
    return dense.build_arch(config, **kwargs)


def convert_hf_state_dict(state_dict, config: InferenceConfig):
    return dense.convert_hf_state_dict(state_dict, config, build_arch(config))


def param_specs(config: InferenceConfig):
    return dense.param_specs_for(build_arch(config))


def param_shape_struct(config: InferenceConfig):
    return dense.param_shape_struct(config, build_arch(config))
