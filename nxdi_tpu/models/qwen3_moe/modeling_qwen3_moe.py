"""Qwen3-MoE family (Qwen3-30B-A3B, Qwen3-235B-A22B).

Reference: models/qwen3_moe/modeling_qwen3_moe.py (544 LoC) — the flagship MoE
benchmark model (BASELINE.md Qwen3-235B numbers). Qwen3 attention traits
(qk_norm, explicit head_dim) + sparse MoE feed-forward with configurable
``norm_topk_prob`` and per-expert ``moe_intermediate_size``.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np


from nxdi_tpu.config import InferenceConfig
from nxdi_tpu.models import dense
from nxdi_tpu.models.base import DecoderArch
from nxdi_tpu.ops.moe import MoEArch, convert_hf_experts, moe_parallel_fields

build_inv_freq = dense.build_inv_freq


class Qwen3MoeInferenceConfig(dense.DenseInferenceConfig):
    REQUIRED = dense.DenseInferenceConfig.REQUIRED + [
        "num_experts",
        "num_experts_per_tok",
        "moe_intermediate_size",
    ]

    def add_derived_config(self):
        super().add_derived_config()
        if not hasattr(self, "norm_topk_prob"):
            # HF Qwen3MoeConfig default — saved configs omit default values
            self.norm_topk_prob = False
        # dense-layer interleaving is not supported yet; validate it is off
        if getattr(self, "mlp_only_layers", None):
            raise NotImplementedError("qwen3_moe mlp_only_layers not supported yet")
        if getattr(self, "decoder_sparse_step", 1) != 1:
            raise NotImplementedError("qwen3_moe decoder_sparse_step != 1 not supported yet")


def _moe_arch(config: InferenceConfig) -> MoEArch:
    return MoEArch(
        num_experts=config.num_experts,
        top_k=config.num_experts_per_tok,
        intermediate_size=config.moe_intermediate_size,
        hidden_act=getattr(config, "hidden_act", "silu"),
        norm_topk_prob=config.norm_topk_prob,
        **moe_parallel_fields(config.tpu_config, config.num_experts),
    )


def build_arch(config: InferenceConfig, **overrides) -> DecoderArch:
    return dense.build_arch(config, **{"qk_norm": True, "moe": _moe_arch(config), **overrides})


def convert_hf_state_dict(
    state_dict: Dict[str, np.ndarray], config: InferenceConfig
) -> Dict[str, Any]:
    arch = build_arch(config)

    def ff(get, has, cast, pre):
        return "moe", convert_hf_experts(
            get,
            cast,
            arch.moe.num_experts,
            pre + "mlp.gate.weight",
            lambda j, proj: f"{pre}mlp.experts.{j}.{proj}_proj.weight",
        )

    return dense.convert_hf_state_dict(state_dict, config, arch, ff_converter=ff)


def param_specs(config: InferenceConfig):
    return dense.param_specs_for(build_arch(config))


def param_shape_struct(config: InferenceConfig):
    return dense.param_shape_struct(config, build_arch(config))
