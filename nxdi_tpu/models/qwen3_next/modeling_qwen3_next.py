"""Qwen3Next family — hybrid linear-attention (GatedDeltaNet) + full attention.

Reference: models/qwen3_next/modeling_qwen3_next.py (1205 LoC):
``NeuronQwen3NextGatedDeltaNet`` linear attention with causal conv1d
(:347-620) interleaved with gated full-attention layers (:281).

TPU-native mapping:
  - the stack is HETEROGENEOUS (most layers are linear attention, every Nth is
    full attention, MLPs may be sparse MoE or dense) so the forward unrolls
    layers in Python instead of the homogeneous ``lax.scan`` the dense
    families use — compile time grows with depth, runtime does not;
  - the gated delta rule runs as a ``lax.scan`` over the sequence in fp32
    (prefill); decode advances the recurrent state one token per dispatch;
  - state lives in the cache pytree: per-full-layer KV slabs plus per-linear-
    layer causal-conv windows (last k inputs) and delta-rule states
    (B, Hv, dk, dv);
  - CTE right-padding is masked out of the state updates (decay frozen, beta
    zeroed, conv window gathered at the true last token) so bucket padding
    never pollutes the recurrence.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from nxdi_tpu.config import InferenceConfig
from nxdi_tpu.models import dense
from nxdi_tpu.ops import attention as attn_ops
from nxdi_tpu.ops import sampling as sampling_ops
from nxdi_tpu.ops.norms import rms_norm
from nxdi_tpu.ops.rope import default_inv_freq, rope_cos_sin, rotate_half


@dataclass(frozen=True)
class Qwen3NextArch:
    num_layers: int
    hidden_size: int
    intermediate_size: int
    vocab_size: int
    vocab_pad: int
    rms_norm_eps: float
    layer_types: Tuple[str, ...]  # "linear_attention" | "full_attention"
    # full attention
    num_attention_heads: int
    num_kv_heads: int
    head_dim: int
    rotary_dim: int
    # linear attention (GatedDeltaNet)
    num_v_heads: int
    num_k_heads: int
    head_k_dim: int
    head_v_dim: int
    conv_kernel: int
    # MoE (None -> dense MLP)
    num_experts: int = 0
    top_k: int = 0
    moe_intermediate_size: int = 0
    shared_expert_intermediate_size: int = 0
    norm_topk_prob: bool = True
    moe_dispatch: str = "sparse"
    tie_word_embeddings: bool = False
    dtype: str = "float32"

    @property
    def key_dim(self) -> int:
        return self.head_k_dim * self.num_k_heads

    @property
    def value_dim(self) -> int:
        return self.head_v_dim * self.num_v_heads

    @property
    def conv_dim(self) -> int:
        return self.key_dim * 2 + self.value_dim

    @property
    def n_full(self) -> int:
        return sum(t == "full_attention" for t in self.layer_types)

    @property
    def n_linear(self) -> int:
        return sum(t == "linear_attention" for t in self.layer_types)


class Qwen3NextInferenceConfig(dense.DenseInferenceConfig):
    REQUIRED = dense.DenseInferenceConfig.REQUIRED + [
        "linear_num_value_heads",
        "linear_num_key_heads",
        "linear_key_head_dim",
        "linear_value_head_dim",
        "linear_conv_kernel_dim",
    ]

    def add_derived_config(self):
        super().add_derived_config()
        defaults = {
            "partial_rotary_factor": 0.25,
            "layer_types": None,
            "num_experts": 0,
            "num_experts_per_tok": 0,
            "moe_intermediate_size": 0,
            "shared_expert_intermediate_size": 0,
            "norm_topk_prob": True,
            "decoder_sparse_step": 1,
            "mlp_only_layers": [],
            "head_dim": self.hidden_size // self.num_attention_heads,
        }
        for k, v in defaults.items():
            if not hasattr(self, k) or getattr(self, k) is None:
                setattr(self, k, v)


def _layer_types(config: InferenceConfig) -> Tuple[str, ...]:
    lt = getattr(config, "layer_types", None)
    if lt:
        return tuple(lt)
    # HF default pattern: every 4th layer full attention
    return tuple(
        "full_attention" if (i + 1) % 4 == 0 else "linear_attention"
        for i in range(config.num_hidden_layers)
    )


def _uses_moe(config: InferenceConfig, i: int) -> bool:
    return (
        config.num_experts > 0
        and i not in (config.mlp_only_layers or [])
        and (i + 1) % (config.decoder_sparse_step or 1) == 0
    )


def build_arch(config: InferenceConfig, **overrides) -> Qwen3NextArch:
    types = _layer_types(config)
    moe_layers = [_uses_moe(config, i) for i in range(config.num_hidden_layers)]
    if any(moe_layers) and not all(moe_layers):
        raise NotImplementedError(
            "qwen3_next with MIXED dense/MoE MLP layers is not supported yet"
        )
    from nxdi_tpu.config import dtype_name

    vocab, vocab_pad = dense.padded_vocab(config)
    kwargs = dict(
        num_layers=config.num_hidden_layers,
        hidden_size=config.hidden_size,
        intermediate_size=config.intermediate_size,
        vocab_size=vocab,
        vocab_pad=vocab_pad,
        rms_norm_eps=config.rms_norm_eps,
        layer_types=types,
        num_attention_heads=config.num_attention_heads,
        num_kv_heads=config.num_key_value_heads,
        head_dim=config.head_dim,
        rotary_dim=int(config.head_dim * config.partial_rotary_factor),
        num_v_heads=config.linear_num_value_heads,
        num_k_heads=config.linear_num_key_heads,
        head_k_dim=config.linear_key_head_dim,
        head_v_dim=config.linear_value_head_dim,
        conv_kernel=config.linear_conv_kernel_dim,
        num_experts=config.num_experts if any(moe_layers) else 0,
        top_k=config.num_experts_per_tok,
        moe_intermediate_size=config.moe_intermediate_size,
        shared_expert_intermediate_size=config.shared_expert_intermediate_size,
        norm_topk_prob=bool(config.norm_topk_prob),
        moe_dispatch=getattr(config.tpu_config, "moe_dispatch", "sparse"),
        tie_word_embeddings=getattr(config, "tie_word_embeddings", False),
        dtype=dtype_name(config.tpu_config.dtype),
    )
    kwargs.update(overrides)
    return Qwen3NextArch(**kwargs)


def build_inv_freq(config: InferenceConfig) -> np.ndarray:
    rotary_dim = int(config.head_dim * config.partial_rotary_factor)
    return default_inv_freq(rotary_dim, getattr(config, "rope_theta", 10000.0))


def _g_norm(arch, x, w):
    """(1+w) float32 rms norm (Qwen3NextRMSNorm)."""
    return rms_norm(x, w, arch.rms_norm_eps, gemma_style=True)


# ---------------------------------------------------------------------------
# Linear attention (GatedDeltaNet)
# ---------------------------------------------------------------------------

def _l2norm(x, eps=1e-6):
    xf = x.astype(jnp.float32)
    return xf * jax.lax.rsqrt(jnp.sum(xf * xf, axis=-1, keepdims=True) + eps)


def _split_qkvz_ba(arch: Qwen3NextArch, qkvz, ba):
    """HF's interleaved per-k-head ordering (fix_query_key_value_ordering)."""
    B, S = qkvz.shape[:2]
    gk, gv = arch.num_k_heads, arch.num_v_heads
    r = gv // gk
    dk, dv = arch.head_k_dim, arch.head_v_dim
    qkvz = qkvz.reshape(B, S, gk, 2 * dk + 2 * r * dv)
    q = qkvz[..., :dk]
    k = qkvz[..., dk : 2 * dk]
    v = qkvz[..., 2 * dk : 2 * dk + r * dv].reshape(B, S, gv, dv)
    z = qkvz[..., 2 * dk + r * dv :].reshape(B, S, gv, dv)
    ba = ba.reshape(B, S, gk, 2 * r)
    b = ba[..., :r].reshape(B, S, gv)
    a = ba[..., r:].reshape(B, S, gv)
    return q, k, v, z, b, a


def _delta_rule_scan(q, k, v, g, beta, state0):
    """Gated delta rule over the sequence (fp32; HF
    torch_recurrent_gated_delta_rule semantics with in-kernel qk l2 norm).

    q/k: (B, S, Hv, dk); v: (B, S, Hv, dv); g/beta: (B, S, Hv);
    state0: (B, Hv, dk, dv). Returns (out (B, S, Hv, dv), final state).
    """
    q = _l2norm(q) * (q.shape[-1] ** -0.5)
    k = _l2norm(k)
    v = v.astype(jnp.float32)
    g = g.astype(jnp.float32)
    beta = beta.astype(jnp.float32)

    def step(state, xs):
        q_t, k_t, v_t, g_t, b_t = xs  # (B, Hv, d*) / (B, Hv)
        decay = jnp.exp(g_t)[..., None, None]
        state = state * decay
        kv_mem = jnp.einsum("bhkv,bhk->bhv", state, k_t)
        delta = (v_t - kv_mem) * b_t[..., None]
        state = state + jnp.einsum("bhk,bhv->bhkv", k_t, delta)
        out_t = jnp.einsum("bhkv,bhk->bhv", state, q_t)
        return state, out_t

    xs = tuple(jnp.swapaxes(x, 0, 1) for x in (q, k, v, g, beta))
    state, outs = jax.lax.scan(step, state0.astype(jnp.float32), xs)
    return jnp.swapaxes(outs, 0, 1), state


def linear_attention_layer(
    arch: Qwen3NextArch,
    lp: Dict[str, Any],
    hidden,  # (B, S, H) already input-normed
    conv_state,  # (B, conv_dim, kernel)
    rec_state,  # (B, Hv, dk, dv) fp32
    valid,  # (B, S) bool — False on padded positions
    is_decode: bool,
):
    B, S, _ = hidden.shape
    dt = hidden.dtype
    qkvz = hidden @ lp["in_proj_qkvz"]
    ba = hidden @ lp["in_proj_ba"]
    q, k, v, z, b, a = _split_qkvz_ba(arch, qkvz, ba)

    mixed = jnp.concatenate(
        [q.reshape(B, S, -1), k.reshape(B, S, -1), v.reshape(B, S, -1)], axis=-1
    )  # (B, S, conv_dim)
    mixed = jnp.where(valid[..., None], mixed, 0.0)
    x_ch = jnp.swapaxes(mixed, 1, 2)  # (B, conv_dim, S)
    K = arch.conv_kernel
    w = lp["conv1d"]  # (conv_dim, K)

    if is_decode:
        # shift the window, append the current input, depthwise dot (HF
        # causal_conv1d_update)
        conv_state = jnp.concatenate([conv_state[:, :, 1:], x_ch], axis=-1)
        conv_out = jnp.sum(conv_state * w[None], axis=-1, keepdims=True)  # (B,C,1)
        new_conv = conv_state
    else:
        padded = jnp.pad(x_ch, ((0, 0), (0, 0), (K - 1, 0)))
        conv_out = jax.lax.conv_general_dilated(
            padded.astype(jnp.float32),
            w[:, None, :].astype(jnp.float32),
            (1,),
            [(0, 0)],
            dimension_numbers=("NCW", "OIW", "NCW"),
            feature_group_count=arch.conv_dim,
        ).astype(dt)
        # conv window = last K REAL inputs per row (gathered at the true end;
        # bucket padding beyond last_token_index must not enter the state)
        lti = jnp.sum(valid.astype(jnp.int32), axis=1) - 1  # (B,)
        idx = lti[:, None] - (K - 1) + jnp.arange(K, dtype=jnp.int32)[None, :]
        take = jnp.clip(idx, 0, S - 1)
        gathered = jnp.take_along_axis(
            x_ch, jnp.broadcast_to(take[:, None, :], (B, arch.conv_dim, K)), axis=2
        )
        new_conv = jnp.where((idx >= 0)[:, None, :], gathered, 0.0).astype(conv_state.dtype)

    conv_out = jax.nn.silu(conv_out.astype(jnp.float32)).astype(dt)
    mixed = jnp.swapaxes(conv_out, 1, 2)  # (B, S, conv_dim)
    kd, vd = arch.key_dim, arch.value_dim
    q = mixed[..., :kd].reshape(B, S, arch.num_k_heads, arch.head_k_dim)
    k = mixed[..., kd : 2 * kd].reshape(B, S, arch.num_k_heads, arch.head_k_dim)
    v = mixed[..., 2 * kd :].reshape(B, S, arch.num_v_heads, arch.head_v_dim)

    beta = jax.nn.sigmoid(b.astype(jnp.float32))
    g = -jnp.exp(lp["A_log"].astype(jnp.float32)) * jax.nn.softplus(
        a.astype(jnp.float32) + lp["dt_bias"].astype(jnp.float32)
    )
    # freeze the recurrence on padded positions: no decay, no write
    g = jnp.where(valid[..., None], g, 0.0)
    beta = jnp.where(valid[..., None], beta, 0.0)

    r = arch.num_v_heads // arch.num_k_heads
    if r > 1:
        q = jnp.repeat(q, r, axis=2)
        k = jnp.repeat(k, r, axis=2)

    core, new_rec = _delta_rule_scan(q, k, v, g, beta, rec_state)
    core = core.astype(dt)

    # gated per-head rms norm then silu(z) gate (Qwen3NextRMSNormGated)
    cf = core.astype(jnp.float32)
    var = jnp.mean(cf * cf, axis=-1, keepdims=True)
    normed = (cf * jax.lax.rsqrt(var + arch.rms_norm_eps)).astype(dt) * lp["norm"]
    out = (normed.astype(jnp.float32) * jax.nn.silu(z.astype(jnp.float32))).astype(dt)
    out = out.reshape(B, S, arch.value_dim) @ lp["out_proj"]
    return out, new_conv, new_rec


# ---------------------------------------------------------------------------
# Full attention (gated, partial rotary)
# ---------------------------------------------------------------------------

def full_attention_layer(
    arch: Qwen3NextArch,
    lp: Dict[str, Any],
    hidden,
    cos,
    sin,
    k_cache,  # (B, KV, W, D)
    v_cache,
    position_ids,
    attend_to_cache: bool,
    kv_window: Optional[int],
):
    B, S, _ = hidden.shape
    H, KV, D = arch.num_attention_heads, arch.num_kv_heads, arch.head_dim

    qg = (hidden @ lp["q_proj"]).reshape(B, S, H, 2 * D)
    q, gate = qg[..., :D], qg[..., D:].reshape(B, S, H * D)
    k = (hidden @ lp["k_proj"]).reshape(B, S, KV, D)
    v = (hidden @ lp["v_proj"]).reshape(B, S, KV, D)
    q = _g_norm(arch, q, lp["q_norm"])
    k = _g_norm(arch, k, lp["k_norm"])

    q = jnp.swapaxes(q, 1, 2)
    k = jnp.swapaxes(k, 1, 2)
    v = jnp.swapaxes(v, 1, 2)

    # partial rotary: rope the first rotary_dim dims only
    rd = arch.rotary_dim
    cosb = cos[:, None].astype(jnp.float32)
    sinb = sin[:, None].astype(jnp.float32)

    def rope(x):
        xr = x[..., :rd].astype(jnp.float32)
        out = xr * cosb + rotate_half(xr) * sinb
        return jnp.concatenate([out.astype(x.dtype), x[..., rd:]], axis=-1)

    q, k = rope(q), rope(k)

    # exact-position KV write
    pos = position_ids
    b_idx = jnp.arange(B, dtype=jnp.int32)[:, None]
    new_k = k_cache.at[b_idx, :, pos].set(jnp.swapaxes(k, 1, 2).astype(k_cache.dtype), mode="drop")
    new_v = v_cache.at[b_idx, :, pos].set(jnp.swapaxes(v, 1, 2).astype(v_cache.dtype), mode="drop")

    if attend_to_cache:
        W = kv_window if kv_window is not None else new_k.shape[2]
        kk = new_k[:, :, :W].astype(q.dtype)
        vv = new_v[:, :, :W].astype(q.dtype)
        kv_pos = jnp.broadcast_to(jnp.arange(W, dtype=jnp.int32)[None, :], (B, W))
        ctx = attn_ops.attention_with_positions(q, kk, vv, position_ids, kv_pos)
    else:
        ctx = attn_ops.attention_with_positions(q, k, v, position_ids, position_ids)

    ctx = jnp.swapaxes(ctx, 1, 2).reshape(B, S, H * D)
    ctx = ctx * jax.nn.sigmoid(gate.astype(jnp.float32)).astype(ctx.dtype)
    return ctx @ lp["o_proj"], new_k, new_v


def _mlp(arch: Qwen3NextArch, lp, x):
    gate = jax.nn.silu(x @ lp["gate_proj"])
    return (gate * (x @ lp["up_proj"])) @ lp["down_proj"]


def _moe_arch(arch: Qwen3NextArch):
    from nxdi_tpu.ops.moe import MoEArch

    return MoEArch(
        num_experts=arch.num_experts,
        top_k=arch.top_k,
        intermediate_size=arch.moe_intermediate_size,
        hidden_act="silu",
        norm_topk_prob=arch.norm_topk_prob,
        dispatch=arch.moe_dispatch,
        shared_expert_intermediate_size=arch.shared_expert_intermediate_size,
        shared_expert_gated=True,
    )


def _moe(arch: Qwen3NextArch, lp, x):
    # the qwen-style router (softmax -> top-k -> renorm) + sigmoid-gated
    # shared expert IS the shared MoE machinery — reuse it (ops/moe.py)
    from nxdi_tpu.ops.moe import moe_block

    return moe_block(arch, _moe_arch(arch), lp, x)


# ---------------------------------------------------------------------------
# Forward (ModelWrapper contract)
# ---------------------------------------------------------------------------

def qwen3next_forward(
    arch: Qwen3NextArch,
    inv_freq: np.ndarray,
    params: Dict[str, Any],
    cache: Dict[str, jax.Array],
    batch: Dict[str, jax.Array],
    *,
    attend_to_cache: bool,
    kv_window: Optional[int] = None,
    policy=None,
    layout=None,
    gather_last_token: bool = True,
    output_logits: bool = False,
    output_all_logits: bool = False,
    on_device_sampling: bool = True,
    do_sample: bool = False,
    global_topk: int = 256,
    deterministic: bool = False,
    return_next_inputs: bool = False,
    **_unused,
):
    from nxdi_tpu.config import to_jax_dtype

    input_ids = batch["input_ids"]
    position_ids = batch["position_ids"]
    dt = to_jax_dtype(arch.dtype)
    B, S = input_ids.shape

    hidden = jnp.take(params["embed_tokens"], input_ids, axis=0).astype(dt)
    cos, sin = rope_cos_sin(position_ids, inv_freq, dtype=jnp.float32)

    if attend_to_cache:
        valid = jnp.ones((B, S), bool)  # decode: every position is real
    else:
        lti = batch["last_token_index"]
        valid = jnp.arange(S, dtype=jnp.int32)[None, :] <= lti[:, None]

    from nxdi_tpu.models.state_routing import put_rows, take_rows

    sids = batch.get("seq_ids")  # continuous batching: row i -> cache line
    new_k, new_v = cache["k"], cache["v"]
    new_conv, new_rec = cache["conv"], cache["rec"]
    fi = li = 0
    for i, lt in enumerate(arch.layer_types):
        lp = params["layers"][i]
        h = _g_norm(arch, hidden, lp["input_layernorm"])
        if lt == "linear_attention":
            out, c_new, r_new = linear_attention_layer(
                arch, lp["linear_attn"], h,
                take_rows(new_conv[li], sids), take_rows(new_rec[li], sids),
                valid, is_decode=attend_to_cache,
            )
            new_conv = put_rows(new_conv, li, c_new, sids)
            new_rec = put_rows(new_rec, li, r_new, sids)
            li += 1
        else:
            out, k_new, v_new = full_attention_layer(
                arch, lp["self_attn"], h, cos, sin,
                take_rows(new_k[fi], sids), take_rows(new_v[fi], sids),
                position_ids, attend_to_cache, kv_window,
            )
            new_k = put_rows(new_k, fi, k_new, sids)
            new_v = put_rows(new_v, fi, v_new, sids)
            fi += 1
        hidden = hidden + out
        h = _g_norm(arch, hidden, lp["post_attention_layernorm"])
        if arch.num_experts:
            hidden = hidden + _moe(arch, lp["mlp"], h)
        else:
            hidden = hidden + _mlp(arch, lp["mlp"], h)

    hidden = _g_norm(arch, hidden, params["norm"])
    lm_head = params.get("lm_head")
    if lm_head is None:
        lm_head = jnp.swapaxes(params["embed_tokens"], 0, 1)
    if gather_last_token and not output_all_logits:
        idx = batch["last_token_index"][:, None, None]
        hidden = jnp.take_along_axis(
            hidden, jnp.broadcast_to(idx, (B, 1, hidden.shape[2])), axis=1
        )
    logits = (hidden @ lm_head.astype(hidden.dtype)).astype(jnp.float32)
    logits = sampling_ops.mask_padded_logits(logits, arch.vocab_pad)

    outputs: Dict[str, jax.Array] = {}
    if on_device_sampling:
        tokens = sampling_ops.sample(
            logits[:, -1, :],
            batch["sampling_params"],
            rng=batch.get("rng"),
            do_sample=do_sample,
            global_topk=global_topk,
            deterministic=deterministic,
        )
        outputs["tokens"] = tokens[:, None]
    if output_logits or output_all_logits or not on_device_sampling:
        outputs["logits"] = logits[..., : arch.vocab_size - arch.vocab_pad]
    new_cache = {"k": new_k, "v": new_v, "conv": new_conv, "rec": new_rec}
    return outputs, new_cache


# ---------------------------------------------------------------------------
# Conversion / specs / struct
# ---------------------------------------------------------------------------

def convert_hf_state_dict(
    state_dict: Dict[str, np.ndarray], config: InferenceConfig
) -> Dict[str, Any]:
    arch = build_arch(config)
    dt = dense.np_dtype(arch.dtype)

    def get(name):
        for k in (name, f"model.{name}"):
            if k in state_dict:
                return np.asarray(state_dict[k], dtype=dt)
        raise KeyError(name)

    layers = []
    for i, lt in enumerate(arch.layer_types):
        pre = f"layers.{i}."
        lp: Dict[str, Any] = {
            "input_layernorm": get(pre + "input_layernorm.weight"),
            "post_attention_layernorm": get(pre + "post_attention_layernorm.weight"),
        }
        if lt == "linear_attention":
            la = pre + "linear_attn."
            lp["linear_attn"] = {
                "in_proj_qkvz": get(la + "in_proj_qkvz.weight").T,
                "in_proj_ba": get(la + "in_proj_ba.weight").T,
                "conv1d": get(la + "conv1d.weight")[:, 0, :],  # (C, 1, K) -> (C, K)
                "dt_bias": get(la + "dt_bias"),
                "A_log": get(la + "A_log"),
                "norm": get(la + "norm.weight"),
                "out_proj": get(la + "out_proj.weight").T,
            }
        else:
            sa = pre + "self_attn."
            lp["self_attn"] = {
                "q_proj": get(sa + "q_proj.weight").T,
                "k_proj": get(sa + "k_proj.weight").T,
                "v_proj": get(sa + "v_proj.weight").T,
                "o_proj": get(sa + "o_proj.weight").T,
                "q_norm": get(sa + "q_norm.weight"),
                "k_norm": get(sa + "k_norm.weight"),
            }
        if arch.num_experts:
            mp = pre + "mlp."
            E = arch.num_experts
            lp["mlp"] = {
                "router": {"w": get(mp + "gate.weight").T},
                "experts": {
                    "gate_proj": {"w": np.stack(
                        [get(mp + f"experts.{j}.gate_proj.weight").T for j in range(E)]
                    )},
                    "up_proj": {"w": np.stack(
                        [get(mp + f"experts.{j}.up_proj.weight").T for j in range(E)]
                    )},
                    "down_proj": {"w": np.stack(
                        [get(mp + f"experts.{j}.down_proj.weight").T for j in range(E)]
                    )},
                },
                "shared_expert": {
                    "gate_proj": {"w": get(mp + "shared_expert.gate_proj.weight").T},
                    "up_proj": {"w": get(mp + "shared_expert.up_proj.weight").T},
                    "down_proj": {"w": get(mp + "shared_expert.down_proj.weight").T},
                },
                "shared_expert_gate": {"w": get(mp + "shared_expert_gate.weight").T},
            }
        else:
            lp["mlp"] = {
                "gate_proj": get(pre + "mlp.gate_proj.weight").T,
                "up_proj": get(pre + "mlp.up_proj.weight").T,
                "down_proj": get(pre + "mlp.down_proj.weight").T,
            }
        layers.append(lp)

    embed = get("embed_tokens.weight")
    if arch.vocab_pad:
        embed = np.concatenate(
            [embed, np.zeros((arch.vocab_pad, embed.shape[1]), dtype=dt)], axis=0
        )
    params: Dict[str, Any] = {
        "embed_tokens": embed,
        "layers": layers,
        "norm": get("norm.weight"),
    }
    if not arch.tie_word_embeddings:
        head = (
            np.asarray(state_dict["lm_head.weight"], dtype=dt)
            if "lm_head.weight" in state_dict
            else embed[: config.vocab_size]
        )
        if arch.vocab_pad and head.shape[0] < arch.vocab_size:
            head = np.concatenate(
                [head, np.zeros((arch.vocab_pad, head.shape[1]), dtype=dt)], axis=0
            )
        params["lm_head"] = head.T
    return params


def param_specs(config: InferenceConfig):
    """TP layout over the heterogeneous stack. Every sharded dim is
    HEAD-BLOCK aligned so a plain dim shard keeps whole heads per rank:
    ``in_proj_qkvz``/``in_proj_ba`` pack per-K-HEAD blocks (the reshape in
    :func:`_split_qkvz_ba`), so their output dims shard when tp divides
    num_k_heads; the gated-attention q packs (head, 2, D) blocks. Dims that
    don't divide stay replicated (GSPMD reshards activations around them —
    notably the causal conv, whose channel layout is section- not
    head-contiguous and is left replicated on purpose)."""
    from jax.sharding import PartitionSpec as P

    from nxdi_tpu.parallel.mesh import AXIS_MP

    arch = build_arch(config)
    tp = config.tpu_config.tp_degree
    struct = param_shape_struct(config)
    specs = jax.tree_util.tree_map(lambda _: P(), struct)

    def col(ok):  # shard output dim
        return P(None, AXIS_MP) if ok else P()

    def row(ok):  # shard input dim
        return P(AXIS_MP, None) if ok else P()

    gk_ok = tp > 1 and arch.num_k_heads % tp == 0
    gv_ok = tp > 1 and arch.num_v_heads % tp == 0
    h_ok = tp > 1 and arch.num_attention_heads % tp == 0
    kv_ok = tp > 1 and arch.num_kv_heads % tp == 0
    i_ok = tp > 1 and arch.intermediate_size % tp == 0

    if tp > 1:
        specs["embed_tokens"] = P(AXIS_MP, None)  # vocab is tp-padded
        if "lm_head" in specs:
            specs["lm_head"] = P(None, AXIS_MP)
    for li, lt in enumerate(arch.layer_types):
        lp = specs["layers"][li]
        if lt == "linear_attention":
            la = lp["linear_attn"]
            la["in_proj_qkvz"] = col(gk_ok and gv_ok)
            la["in_proj_ba"] = col(gk_ok and gv_ok)
            la["out_proj"] = row(gv_ok)
        else:
            sa = lp["self_attn"]
            sa["q_proj"] = col(h_ok)
            sa["k_proj"] = col(kv_ok)
            sa["v_proj"] = col(kv_ok)
            sa["o_proj"] = row(h_ok)
        mlp = lp["mlp"]
        if arch.num_experts:
            e_ok = tp > 1 and arch.num_experts % tp == 0
            mi_ok = tp > 1 and arch.moe_intermediate_size % tp == 0
            si_ok = tp > 1 and arch.shared_expert_intermediate_size % tp == 0
            ex = mlp["experts"]
            if e_ok:
                for name in ("gate_proj", "up_proj", "down_proj"):
                    ex[name]["w"] = P(AXIS_MP, None, None)
            elif mi_ok:
                ex["gate_proj"]["w"] = P(None, None, AXIS_MP)
                ex["up_proj"]["w"] = P(None, None, AXIS_MP)
                ex["down_proj"]["w"] = P(None, AXIS_MP, None)
            sh = mlp["shared_expert"]
            sh["gate_proj"]["w"] = col(si_ok)
            sh["up_proj"]["w"] = col(si_ok)
            sh["down_proj"]["w"] = row(si_ok)
        else:
            mlp["gate_proj"] = col(i_ok)
            mlp["up_proj"] = col(i_ok)
            mlp["down_proj"] = row(i_ok)
    return specs


def param_shape_struct(config: InferenceConfig):
    from nxdi_tpu.config import to_jax_dtype

    arch = build_arch(config)
    dt = to_jax_dtype(arch.dtype)
    Hd = arch.hidden_size

    def s(*shape):
        return jax.ShapeDtypeStruct(shape, dt)

    layers = []
    for lt in arch.layer_types:
        lp: Dict[str, Any] = {
            "input_layernorm": s(Hd),
            "post_attention_layernorm": s(Hd),
        }
        if lt == "linear_attention":
            lp["linear_attn"] = {
                "in_proj_qkvz": s(Hd, arch.key_dim * 2 + arch.value_dim * 2),
                "in_proj_ba": s(Hd, arch.num_v_heads * 2),
                "conv1d": s(arch.conv_dim, arch.conv_kernel),
                "dt_bias": s(arch.num_v_heads),
                "A_log": s(arch.num_v_heads),
                "norm": s(arch.head_v_dim),
                "out_proj": s(arch.value_dim, Hd),
            }
        else:
            H, KV, D = arch.num_attention_heads, arch.num_kv_heads, arch.head_dim
            lp["self_attn"] = {
                "q_proj": s(Hd, H * 2 * D),
                "k_proj": s(Hd, KV * D),
                "v_proj": s(Hd, KV * D),
                "o_proj": s(H * D, Hd),
                "q_norm": s(D),
                "k_norm": s(D),
            }
        if arch.num_experts:
            E, I, SI = arch.num_experts, arch.moe_intermediate_size, arch.shared_expert_intermediate_size
            lp["mlp"] = {
                "router": {"w": s(Hd, E)},
                "experts": {
                    "gate_proj": {"w": s(E, Hd, I)},
                    "up_proj": {"w": s(E, Hd, I)},
                    "down_proj": {"w": s(E, I, Hd)},
                },
                "shared_expert": {
                    "gate_proj": {"w": s(Hd, SI)},
                    "up_proj": {"w": s(Hd, SI)},
                    "down_proj": {"w": s(SI, Hd)},
                },
                "shared_expert_gate": {"w": s(Hd, 1)},
            }
        else:
            lp["mlp"] = {
                "gate_proj": s(Hd, arch.intermediate_size),
                "up_proj": s(Hd, arch.intermediate_size),
                "down_proj": s(arch.intermediate_size, Hd),
            }
        layers.append(lp)
    struct = {"embed_tokens": s(arch.vocab_size, Hd), "layers": layers, "norm": s(Hd)}
    if not arch.tie_word_embeddings:
        struct["lm_head"] = s(Hd, arch.vocab_size)
    return struct


# ---------------------------------------------------------------------------
# Application
# ---------------------------------------------------------------------------

def cache_shapes(arch: Qwen3NextArch, batch_size: int, seq_len: int):
    from nxdi_tpu.config import to_jax_dtype

    dt = to_jax_dtype(arch.dtype)
    return {
        "k": ((arch.n_full, batch_size, arch.num_kv_heads, seq_len, arch.head_dim), dt),
        "v": ((arch.n_full, batch_size, arch.num_kv_heads, seq_len, arch.head_dim), dt),
        "conv": ((arch.n_linear, batch_size, arch.conv_dim, arch.conv_kernel), dt),
        "rec": (
            (arch.n_linear, batch_size, arch.num_v_heads, arch.head_k_dim, arch.head_v_dim),
            jnp.float32,
        ),
    }


def make_cache_host(arch: Qwen3NextArch, batch_size: int, seq_len: int):
    return {
        k: jnp.zeros(shape, dt)
        for k, (shape, dt) in cache_shapes(arch, batch_size, seq_len).items()
    }


from nxdi_tpu.runtime.application import TpuModelForCausalLM  # noqa: E402


class Qwen3NextForCausalLM(TpuModelForCausalLM):
    """Application wired to the heterogeneous forward + state cache (the CLI
    resolves it via the family module's APPLICATION_CLS)."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        tc = self.tpu_config
        unsupported = [
            ("async_mode", tc.async_mode),
            ("is_prefix_caching", tc.is_prefix_caching),
            ("is_chunked_prefill", tc.is_chunked_prefill),
            ("is_block_kv_layout", tc.is_block_kv_layout),
            ("speculation", tc.speculation_length > 0 or tc.is_medusa),
            ("tensor_capture_config", tc.tensor_capture_config is not None),
            # raw-array param layout: the quantizer/LoRA rewrites would no-op
            ("quantized", tc.quantized),
            ("lora_config", tc.lora_config is not None),
        ]
        bad = [name for name, val in unsupported if val]
        if bad:
            raise ValueError(
                "qwen3_next does not support: " + ", ".join(bad) + " — the "
                "linear-attention recurrence needs dedicated state routing for "
                "these modes (conv/delta states are not paged)"
            )

    def enable_models(self) -> None:
        super().enable_models()
        for wrapper in self.models.values():
            wrapper.forward_fn = qwen3next_forward

    def _arch(self):
        return build_arch(self.config)

    def cache_partition_specs(self):
        from jax.sharding import PartitionSpec as P

        from nxdi_tpu.parallel.mesh import AXIS_MP

        arch = self._arch()
        tp = self.tpu_config.tp_degree
        kv = AXIS_MP if (tp > 1 and arch.num_kv_heads % tp == 0) else None
        gv = AXIS_MP if (tp > 1 and arch.num_v_heads % tp == 0) else None
        return {
            "k": P(None, None, kv, None, None),
            "v": P(None, None, kv, None, None),
            "conv": P(),  # section-contiguous channels: stays replicated
            "rec": P(None, None, gv, None, None),
        }

    def init_cache_host(self):
        tc = self.tpu_config
        return make_cache_host(
            self._arch(), tc.kv_cache_batch_size + tc.kv_cache_padding_size, tc.seq_len
        )

    def _cache_struct(self):
        tc = self.tpu_config
        shapes = cache_shapes(
            self._arch(), tc.kv_cache_batch_size + tc.kv_cache_padding_size, tc.seq_len
        )
        return {k: jax.ShapeDtypeStruct(shape, dt) for k, (shape, dt) in shapes.items()}


APPLICATION_CLS = Qwen3NextForCausalLM
