"""Arcee (AFM) family — llama geometry with a NON-gated squared-ReLU MLP.

Reference: contrib/models/AFM-4.5B-Base. HF ArceeForCausalLM
(modeling_arcee.py:50-61): ``up_proj``/``down_proj`` only (no gate) with
``relu2`` (squared ReLU) activation; everything else is the llama
standard."""

from __future__ import annotations

from nxdi_tpu.config import InferenceConfig
from nxdi_tpu.models import dense
from nxdi_tpu.models.base import DecoderArch

build_inv_freq = dense.build_inv_freq


class ArceeInferenceConfig(dense.DenseInferenceConfig):
    def add_derived_config(self):
        if not hasattr(self, "hidden_act"):
            self.hidden_act = "relu2"
        super().add_derived_config()


def build_arch(config: InferenceConfig, **overrides) -> DecoderArch:
    kwargs = dict(
        gated_mlp=False,
        hidden_act=getattr(config, "hidden_act", "relu2"),
        attention_bias=bool(getattr(config, "attention_bias", False)),
        mlp_bias=bool(getattr(config, "mlp_bias", False)),
    )
    kwargs.update(overrides)
    return dense.build_arch(config, **kwargs)


def convert_hf_state_dict(state_dict, config: InferenceConfig):
    arch = build_arch(config)

    def ff(get, has, cast, pre):
        mlp = {
            "up_proj": {"w": cast(get(pre + "mlp.up_proj.weight").T)},
            "down_proj": {"w": cast(get(pre + "mlp.down_proj.weight").T)},
        }
        if arch.mlp_bias:
            mlp["up_proj"]["b"] = cast(get(pre + "mlp.up_proj.bias"))
            mlp["down_proj"]["b"] = cast(get(pre + "mlp.down_proj.bias"))
        return "mlp", mlp

    return dense.convert_hf_state_dict(state_dict, config, arch, ff_converter=ff)


def param_specs(config: InferenceConfig):
    return dense.param_specs_for(build_arch(config))


def param_shape_struct(config: InferenceConfig):
    return dense.param_shape_struct(config, build_arch(config))
