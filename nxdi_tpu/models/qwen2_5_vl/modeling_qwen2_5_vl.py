"""Qwen2.5-VL — qwen2-vl M-RoPE text decoder + WINDOWED-attention ViT.

Reference: contrib/models/Qwen2.5-VL-* (community hub). Deltas vs qwen2-vl,
all in the vision tower (HF ``Qwen2_5_VisionTransformerPretrainedModel``):
  - RMSNorm block norms and a gated (SwiGLU) vision MLP with biases;
  - WINDOW attention: patches permuted into window-contiguous order
    (``get_window_index``), most layers attend within their window segment,
    ``fullatt_block_indexes`` layers attend the whole image; features are
    un-permuted after the merger.
The window permutation, both segment-id vectors, and the (permuted) 2-D rope
table are tiny host-side numpy per image grid — static per compiled program,
exactly like qwen2-vl's tables. The text side (M-RoPE llama/qwen2 decoder and
the host 3-D rope index) is shared with qwen2_vl verbatim.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from nxdi_tpu.config import InferenceConfig
from nxdi_tpu.models import dense
from nxdi_tpu.models.qwen2_vl.modeling_qwen2_vl import (  # shared text-side pieces
    Qwen2VLInferenceConfig,
    build_arch,
    build_inv_freq,
    convert_hf_state_dict,
    get_rope_index,
    num_image_tokens,
    param_shape_struct,
    param_specs,
)

__all__ = [
    "Qwen2_5_VLInferenceConfig", "build_arch", "build_inv_freq",
    "convert_hf_state_dict", "param_specs", "param_shape_struct",
    "get_rope_index", "num_image_tokens",
]


class Qwen2_5_VLInferenceConfig(Qwen2VLInferenceConfig):
    pass


@dataclass(frozen=True)
class Qwen25VLVisionArch:
    embed_dim: int  # vision_config.hidden_size
    depth: int
    num_heads: int
    intermediate_size: int
    patch_size: int
    temporal_patch_size: int
    in_channels: int
    spatial_merge_size: int
    out_hidden: int
    window_size: int
    fullatt_indexes: Tuple[int, ...]
    hidden_act: str = "silu"

    @property
    def head_dim(self) -> int:
        return self.embed_dim // self.num_heads


def build_vision_arch(config: InferenceConfig) -> Qwen25VLVisionArch:
    vc = config.vision_config
    return Qwen25VLVisionArch(
        embed_dim=vc["hidden_size"],
        depth=vc["depth"],
        num_heads=vc["num_heads"],
        intermediate_size=vc["intermediate_size"],
        patch_size=vc["patch_size"],
        temporal_patch_size=vc.get("temporal_patch_size", 2),
        in_channels=vc.get("in_channels", 3),
        spatial_merge_size=vc.get("spatial_merge_size", 2),
        out_hidden=vc["out_hidden_size"],
        window_size=vc["window_size"],
        fullatt_indexes=tuple(vc["fullatt_block_indexes"]),
        hidden_act=vc.get("hidden_act", "silu"),
    )


def window_order(varch: Qwen25VLVisionArch, grid_thw):
    """Host: (perm over merge-groups, window segment ids per PATCH in the
    permuted order, image segment ids per patch in the permuted order) —
    HF get_window_index semantics, with padded window cells dropped."""
    m = varch.spatial_merge_size
    vit_win = varch.window_size // m // varch.patch_size
    perm = []
    win_seg = []
    img_seg = []
    base = 0
    wid = 0
    for img_i, (t, h, w) in enumerate(grid_thw):
        t, h, w = int(t), int(h), int(w)
        gh, gw = h // m, w // m
        idx = np.arange(gh * gw).reshape(gh, gw)
        pad_h = (-gh) % vit_win
        pad_w = (-gw) % vit_win
        padded = np.full((gh + pad_h, gw + pad_w), -1, np.int64)
        padded[:gh, :gw] = idx
        nwh, nww = (gh + pad_h) // vit_win, (gw + pad_w) // vit_win
        padded = padded.reshape(nwh, vit_win, nww, vit_win).transpose(0, 2, 1, 3)
        for win in padded.reshape(-1, vit_win * vit_win):
            cells = win[win >= 0]
            if len(cells) == 0:
                continue
            perm.extend((cells + base).tolist())
            win_seg.extend([wid] * (len(cells) * m * m))
            img_seg.extend([img_i] * (len(cells) * m * m))
            wid += 1
        base += gh * gw
    return (
        np.asarray(perm, np.int64),
        np.asarray(win_seg, np.int32),
        np.asarray(img_seg, np.int32),
    )


def vision_rot_table_perm(varch, grid_thw, perm):
    """(N, head_dim) rope phases in the WINDOW-permuted patch order."""
    from nxdi_tpu.models.qwen2_vl.modeling_qwen2_vl import vision_rot_table

    class _V:  # duck-typed view for the shared table builder
        spatial_merge_size = varch.spatial_merge_size
        head_dim = varch.head_dim

    tab = vision_rot_table(_V, grid_thw)  # (N, head_dim), merge-group order
    m2 = varch.spatial_merge_size ** 2
    tab = tab.reshape(-1, m2, tab.shape[-1])[perm].reshape(-1, tab.shape[-1])
    return tab


def vision_forward(
    varch: Qwen25VLVisionArch,
    params: Dict[str, Any],
    patches,  # (N, C*Tp*P*P) in the ORIGINAL processor order
    perm,  # (N/m2,) window permutation over merge groups
    phases,  # (N, head_dim) rope table, permuted order
    win_seg,  # (N,) window segment id per permuted patch
    img_seg,  # (N,) image segment id per permuted patch
    layer_full,  # (depth,) bool: layer attends image-wide
):
    from nxdi_tpu.models.base import ACT_FNS

    v = params["vision"]
    nh, d = varch.num_heads, varch.head_dim
    E = varch.embed_dim
    m2 = varch.spatial_merge_size ** 2
    h = patches @ v["patch_embedding"]
    N = h.shape[0]
    h = h.reshape(N // m2, m2, E)[perm].reshape(N, E)  # window order

    cos = jnp.cos(phases)[:, None, :]
    sin = jnp.sin(phases)[:, None, :]
    win_mask = win_seg[:, None] == win_seg[None, :]
    img_mask = img_seg[:, None] == img_seg[None, :]
    act = ACT_FNS[varch.hidden_act]

    def rms(x, w):
        xf = x.astype(jnp.float32)
        return (xf * jax.lax.rsqrt(jnp.mean(xf * xf, -1, keepdims=True) + 1e-6)).astype(x.dtype) * w

    def rot(x):
        half = x.shape[-1] // 2
        return jnp.concatenate([-x[..., half:], x[..., :half]], axis=-1)

    def body(carry, xs):
        lp, full = xs
        mask = jnp.where(full, img_mask, win_mask)
        y = rms(carry, lp["norm1"])
        qkv = y @ lp["qkv"]["w"] + lp["qkv"]["b"]
        q, k, val = jnp.split(qkv.reshape(N, 3, nh, d), 3, axis=1)
        q, k, val = q[:, 0], k[:, 0], val[:, 0]
        qf, kf = q.astype(jnp.float32), k.astype(jnp.float32)
        q = qf * cos + rot(qf) * sin
        k = kf * cos + rot(kf) * sin
        s = jnp.einsum("qhd,khd->hqk", q, k, preferred_element_type=jnp.float32)
        s = s * (d ** -0.5)
        s = jnp.where(mask[None], s, -3.4028235e38)
        w = jax.nn.softmax(s, axis=-1).astype(val.dtype)
        attn = jnp.einsum("hqk,khd->qhd", w, val).reshape(N, nh * d)
        carry = carry + attn @ lp["proj"]["w"] + lp["proj"]["b"]
        y = rms(carry, lp["norm2"])
        gate = act(y @ lp["gate_proj"]["w"] + lp["gate_proj"]["b"])
        up = y @ lp["up_proj"]["w"] + lp["up_proj"]["b"]
        ff = (gate * up) @ lp["down_proj"]["w"] + lp["down_proj"]["b"]
        return carry + ff, None

    h, _ = jax.lax.scan(body, h, (v["blocks"], jnp.asarray(layer_full)))

    mg = params["merger"]
    h = rms(h, mg["ln_q"])
    h = h.reshape(N // m2, m2 * E)
    h = jax.nn.gelu(h @ mg["fc1"]["w"] + mg["fc1"]["b"], approximate=False)
    h = h @ mg["fc2"]["w"] + mg["fc2"]["b"]  # (N/m2, out) in window order
    inv = jnp.argsort(jnp.asarray(perm))
    return h[inv]


encode_images = vision_forward  # family-protocol presence


def convert_vision_params(state_dict, config: InferenceConfig) -> Dict[str, Any]:
    varch = build_vision_arch(config)

    def get(name):
        for k in (f"model.visual.{name}", f"visual.{name}"):
            if k in state_dict:
                return state_dict[k]
        raise KeyError(f"missing vision weight {name}")

    f32 = lambda x: np.asarray(x, np.float32)  # noqa: E731
    conv = get("patch_embed.proj.weight")
    blocks = []
    for i in range(varch.depth):
        p = f"blocks.{i}."
        blocks.append({
            "norm1": f32(get(p + "norm1.weight")),
            "norm2": f32(get(p + "norm2.weight")),
            "qkv": {"w": f32(get(p + "attn.qkv.weight").T), "b": f32(get(p + "attn.qkv.bias"))},
            "proj": {"w": f32(get(p + "attn.proj.weight").T), "b": f32(get(p + "attn.proj.bias"))},
            "gate_proj": {"w": f32(get(p + "mlp.gate_proj.weight").T), "b": f32(get(p + "mlp.gate_proj.bias"))},
            "up_proj": {"w": f32(get(p + "mlp.up_proj.weight").T), "b": f32(get(p + "mlp.up_proj.bias"))},
            "down_proj": {"w": f32(get(p + "mlp.down_proj.weight").T), "b": f32(get(p + "mlp.down_proj.bias"))},
        })
    return {
        "vision": {
            "patch_embedding": f32(conv.reshape(varch.embed_dim, -1).T),
            "blocks": dense.tree_stack(blocks),
        },
        "merger": {
            "ln_q": f32(get("merger.ln_q.weight")),
            "fc1": {"w": f32(get("merger.mlp.0.weight").T), "b": f32(get("merger.mlp.0.bias"))},
            "fc2": {"w": f32(get("merger.mlp.2.weight").T), "b": f32(get("merger.mlp.2.bias"))},
        },
    }


def vision_shape_struct(config: InferenceConfig) -> Dict[str, Any]:
    varch = build_vision_arch(config)
    E, I, L = varch.embed_dim, varch.intermediate_size, varch.depth
    P2 = varch.in_channels * varch.temporal_patch_size * varch.patch_size ** 2
    m2E = varch.spatial_merge_size ** 2 * E

    def s(*shape):
        return jax.ShapeDtypeStruct(shape, np.float32)

    return {
        "vision": {
            "patch_embedding": s(P2, E),
            "blocks": {
                "norm1": s(L, E),
                "norm2": s(L, E),
                "qkv": {"w": s(L, E, 3 * E), "b": s(L, 3 * E)},
                "proj": {"w": s(L, E, E), "b": s(L, E)},
                "gate_proj": {"w": s(L, E, I), "b": s(L, I)},
                "up_proj": {"w": s(L, E, I), "b": s(L, I)},
                "down_proj": {"w": s(L, I, E), "b": s(L, E)},
            },
        },
        "merger": {
            "ln_q": s(E),
            "fc1": {"w": s(m2E, m2E), "b": s(m2E)},
            "fc2": {"w": s(m2E, varch.out_hidden), "b": s(varch.out_hidden)},
        },
    }


class Qwen2_5_VLForConditionalGeneration:
    def __new__(cls, *args, **kwargs):
        from nxdi_tpu.models.qwen2_5_vl.application import Qwen25VLApplication

        return Qwen25VLApplication(*args, **kwargs)
