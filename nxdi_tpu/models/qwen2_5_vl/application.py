"""Qwen2.5-VL application — windowed vision program + M-RoPE threading
(reference: contrib Qwen2.5-VL; shares the qwen2_vl app flow)."""

from __future__ import annotations

from functools import partial

import jax
import numpy as np

from nxdi_tpu.models.qwen2_5_vl import modeling_qwen2_5_vl as mq
from nxdi_tpu.models.qwen2_vl.application import Qwen2VLApplication


class Qwen25VLApplication(Qwen2VLApplication):
    def __init__(self, *args, **kwargs):
        kwargs.setdefault("model_family", mq)
        super().__init__(*args, **kwargs)

    def encode_images(self, pixel_values, image_grid_thw):
        varch = mq.build_vision_arch(self.config)
        grid = tuple(tuple(int(x) for x in g) for g in np.asarray(image_grid_thw))
        if grid not in self._vision_jit:
            self._vision_jit[grid] = jax.jit(
                partial(mq.vision_forward, varch), static_argnums=()
            )
        perm, win_seg, img_seg = mq.window_order(varch, grid)
        phases = mq.vision_rot_table_perm(varch, grid, perm)
        layer_full = np.array(
            [i in varch.fullatt_indexes for i in range(varch.depth)], bool
        )
        with jax.set_mesh(self.mesh):
            return self._vision_jit[grid](
                {"vision": self.params["vision"], "merger": self.params["merger"]},
                np.asarray(pixel_values, np.float32),
                perm,
                phases,
                win_seg,
                img_seg,
                layer_full,
            )
