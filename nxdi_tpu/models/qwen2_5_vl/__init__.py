from nxdi_tpu.models.qwen2_5_vl import modeling_qwen2_5_vl  # noqa: F401
