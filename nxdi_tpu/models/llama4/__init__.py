from nxdi_tpu.models.llama4 import modeling_llama4
