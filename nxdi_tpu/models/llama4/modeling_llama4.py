"""Llama4 text family.

Reference: models/llama4/ (3245 LoC: text+vision, chunked attention, 16E/128E
MoE). This module is the TEXT decoder; the vision encoder rides the
image-to-text application (models/image_to_text.py).

Distinguishing traits handled by the shared decoder (models/base.py):
  - adjacent-pair (GPT-J style) rope with some layers skipping rope entirely
    (``no_rope_layers``; per-layer ``use_rope`` scan flag);
  - unweighted L2 qk-norm AFTER rope on rope layers (``qk_l2norm``);
  - per-position query temperature tuning on no-rope layers
    (``attn_temperature_tuning``);
  - chunked attention on rope layers (``attention_chunk_size``; the no-rope
    layers attend globally — reference: attention_base.py:2559 chunked paths);
  - MoE with sigmoid top-k scores scaling the expert INPUT plus an always-on
    shared expert (ops/moe.py ``llama4_router``).

Heterogeneous dense/MoE stacks (interleave_moe_layer_step > 1, the 128E
model) are not supported yet — the layer scan requires a homogeneous stack.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from nxdi_tpu.config import InferenceConfig
from nxdi_tpu.models import dense
from nxdi_tpu.models.base import DecoderArch
from nxdi_tpu.ops.moe import MoEArch, moe_parallel_fields
from nxdi_tpu.parallel.layers import REPLICATED

build_inv_freq = dense.build_inv_freq


class Llama4InferenceConfig(dense.DenseInferenceConfig):
    REQUIRED = dense.DenseInferenceConfig.REQUIRED + [
        "num_local_experts",
        "num_experts_per_tok",
    ]

    def add_derived_config(self):
        from nxdi_tpu.config import promote_text_config

        promote_text_config(self)  # composite 'llama4' checkpoints
        super().add_derived_config()
        defaults = {
            "no_rope_layers": None,
            "attention_chunk_size": None,
            "use_qk_norm": True,
            "attn_temperature_tuning": True,
            "floor_scale": 8192.0,
            "attn_scale": 0.1,
            "interleave_moe_layer_step": 1,
        }
        for k, v in defaults.items():
            if not hasattr(self, k):
                setattr(self, k, v)


def _moe_arch(config: InferenceConfig) -> MoEArch:
    step = getattr(config, "interleave_moe_layer_step", 1) or 1
    if step != 1:
        raise NotImplementedError(
            "llama4 with interleave_moe_layer_step > 1 (dense/MoE interleaved "
            "stack, the 128E model) is not supported yet: the layer scan needs "
            "a homogeneous stack"
        )
    return MoEArch(
        num_experts=config.num_local_experts,
        top_k=config.num_experts_per_tok,
        intermediate_size=config.intermediate_size,
        llama4_router=True,
        shared_expert_intermediate_size=config.intermediate_size,
        **moe_parallel_fields(config.tpu_config, config.num_local_experts),
    )


def build_arch(config: InferenceConfig, **overrides) -> DecoderArch:
    kwargs = dict(
        moe=_moe_arch(config),
        rope_interleaved=True,
        qk_l2norm=bool(getattr(config, "use_qk_norm", True)),
        chunk_size=getattr(config, "attention_chunk_size", None),
        attn_temperature_tuning=bool(getattr(config, "attn_temperature_tuning", True)),
        floor_scale=float(getattr(config, "floor_scale", 8192.0)),
        attn_scale=float(getattr(config, "attn_scale", 0.1)),
    )
    kwargs.update(overrides)
    return dense.build_arch(config, **kwargs)


def _use_rope_flags(config: InferenceConfig) -> np.ndarray:
    nrl = getattr(config, "no_rope_layers", None)
    L = config.num_hidden_layers
    if nrl:
        return np.array([bool(v) for v in nrl], dtype=bool)  # 1 = USE rope
    interval = getattr(config, "no_rope_layer_interval", 4) or 4
    return np.array([(i + 1) % interval != 0 for i in range(L)], dtype=bool)


def convert_hf_state_dict(
    state_dict: Dict[str, np.ndarray], config: InferenceConfig
) -> Dict[str, Any]:
    arch = build_arch(config)
    inter = arch.moe.intermediate_size

    def ff(get, has, cast, pre):
        src = pre + "feed_forward."
        gu = np.asarray(get(src + "experts.gate_up_proj"))  # (E, H, 2I) chunked
        return "moe", {
            "router": {"w": cast(np.asarray(get(src + "router.weight")).T)},
            "experts": {
                "gate_proj": {"w": cast(gu[..., :inter])},
                "up_proj": {"w": cast(gu[..., inter:])},
                "down_proj": {"w": cast(np.asarray(get(src + "experts.down_proj")))},
            },
            "shared_expert": {
                "gate_proj": {"w": cast(np.asarray(get(src + "shared_expert.gate_proj.weight")).T)},
                "up_proj": {"w": cast(np.asarray(get(src + "shared_expert.up_proj.weight")).T)},
                "down_proj": {"w": cast(np.asarray(get(src + "shared_expert.down_proj.weight")).T)},
            },
        }

    params = dense.convert_hf_state_dict(state_dict, config, arch, ff_converter=ff)
    params["layers"]["use_rope"] = _use_rope_flags(config)
    return params


def param_specs(config: InferenceConfig):
    specs = dense.param_specs_for(build_arch(config))
    specs["layers"]["use_rope"] = REPLICATED
    return specs


def param_shape_struct(config: InferenceConfig):
    import jax
    import jax.numpy as jnp

    struct = dense.param_shape_struct(config, build_arch(config))
    struct["layers"]["use_rope"] = jax.ShapeDtypeStruct(
        (config.num_hidden_layers,), jnp.bool_
    )
    return struct
