"""Llama4 text family.

Reference: models/llama4/ (3245 LoC: text+vision, chunked attention, 16E/128E
MoE). This module is the TEXT decoder; the vision encoder rides the
image-to-text application (models/image_to_text.py).

Distinguishing traits handled by the shared decoder (models/base.py):
  - adjacent-pair (GPT-J style) rope with some layers skipping rope entirely
    (``no_rope_layers``; per-layer ``use_rope`` scan flag);
  - unweighted L2 qk-norm AFTER rope on rope layers (``qk_l2norm``);
  - per-position query temperature tuning on no-rope layers
    (``attn_temperature_tuning``);
  - chunked attention on rope layers (``attention_chunk_size``; the no-rope
    layers attend globally — reference: attention_base.py:2559 chunked paths);
  - MoE with sigmoid top-k scores scaling the expert INPUT plus an always-on
    shared expert (ops/moe.py ``llama4_router``).

Heterogeneous dense/MoE stacks (interleave_moe_layer_step > 1, the 128E
model) are not supported yet — the layer scan requires a homogeneous stack.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from nxdi_tpu.config import InferenceConfig
from nxdi_tpu.models import dense
from nxdi_tpu.models.base import DecoderArch
from nxdi_tpu.ops.moe import MoEArch, moe_parallel_fields
from nxdi_tpu.parallel.layers import REPLICATED

build_inv_freq = dense.build_inv_freq


class Llama4InferenceConfig(dense.DenseInferenceConfig):
    REQUIRED = dense.DenseInferenceConfig.REQUIRED + [
        "num_local_experts",
        "num_experts_per_tok",
    ]

    def add_derived_config(self):
        from nxdi_tpu.config import promote_text_config

        promote_text_config(self)  # composite 'llama4' checkpoints
        super().add_derived_config()
        defaults = {
            "no_rope_layers": None,
            "attention_chunk_size": None,
            "use_qk_norm": True,
            "attn_temperature_tuning": True,
            "floor_scale": 8192.0,
            "attn_scale": 0.1,
            "interleave_moe_layer_step": 1,
        }
        for k, v in defaults.items():
            if not hasattr(self, k):
                setattr(self, k, v)


def _moe_arch(config: InferenceConfig) -> MoEArch:
    step = getattr(config, "interleave_moe_layer_step", 1) or 1
    if step != 1:
        raise NotImplementedError(
            "llama4 with interleave_moe_layer_step > 1 (dense/MoE interleaved "
            "stack, the 128E model) is not supported yet: the layer scan needs "
            "a homogeneous stack"
        )
    return MoEArch(
        num_experts=config.num_local_experts,
        top_k=config.num_experts_per_tok,
        intermediate_size=config.intermediate_size,
        llama4_router=True,
        shared_expert_intermediate_size=config.intermediate_size,
        **moe_parallel_fields(config.tpu_config, config.num_local_experts),
    )


def build_arch(config: InferenceConfig, **overrides) -> DecoderArch:
    kwargs = dict(
        moe=_moe_arch(config),
        rope_interleaved=True,
        qk_l2norm=bool(getattr(config, "use_qk_norm", True)),
        chunk_size=getattr(config, "attention_chunk_size", None),
        attn_temperature_tuning=bool(getattr(config, "attn_temperature_tuning", True)),
        floor_scale=float(getattr(config, "floor_scale", 8192.0)),
        attn_scale=float(getattr(config, "attn_scale", 0.1)),
    )
    kwargs.update(overrides)
    return dense.build_arch(config, **kwargs)


def _use_rope_flags(config: InferenceConfig) -> np.ndarray:
    nrl = getattr(config, "no_rope_layers", None)
    L = config.num_hidden_layers
    if nrl:
        return np.array([bool(v) for v in nrl], dtype=bool)  # 1 = USE rope
    interval = getattr(config, "no_rope_layer_interval", 4) or 4
    return np.array([(i + 1) % interval != 0 for i in range(L)], dtype=bool)


def convert_hf_state_dict(
    state_dict: Dict[str, np.ndarray], config: InferenceConfig
) -> Dict[str, Any]:
    # composite (vision) checkpoints nest the text side under language_model.*
    if any(k.startswith(("language_model.", "model.language_model.")) for k in state_dict):
        stripped = {}
        for k, v in state_dict.items():
            for prefix in ("model.language_model.", "language_model.model.", "language_model."):
                if k.startswith(prefix):
                    stripped[k[len(prefix):]] = v
                    break
            else:
                if k in ("lm_head.weight", "language_model.lm_head.weight"):
                    stripped["lm_head.weight"] = v
        state_dict = stripped
    arch = build_arch(config)
    inter = arch.moe.intermediate_size

    def ff(get, has, cast, pre):
        src = pre + "feed_forward."
        gu = np.asarray(get(src + "experts.gate_up_proj"))  # (E, H, 2I) chunked
        return "moe", {
            "router": {"w": cast(np.asarray(get(src + "router.weight")).T)},
            "experts": {
                "gate_proj": {"w": cast(gu[..., :inter])},
                "up_proj": {"w": cast(gu[..., inter:])},
                "down_proj": {"w": cast(np.asarray(get(src + "experts.down_proj")))},
            },
            "shared_expert": {
                "gate_proj": {"w": cast(np.asarray(get(src + "shared_expert.gate_proj.weight")).T)},
                "up_proj": {"w": cast(np.asarray(get(src + "shared_expert.up_proj.weight")).T)},
                "down_proj": {"w": cast(np.asarray(get(src + "shared_expert.down_proj.weight")).T)},
            },
        }

    params = dense.convert_hf_state_dict(state_dict, config, arch, ff_converter=ff)
    params["layers"]["use_rope"] = _use_rope_flags(config)
    return params


def param_specs(config: InferenceConfig):
    specs = dense.param_specs_for(build_arch(config))
    specs["layers"]["use_rope"] = REPLICATED
    return specs


def param_shape_struct(config: InferenceConfig):
    import jax
    import jax.numpy as jnp

    struct = dense.param_shape_struct(config, build_arch(config))
    struct["layers"]["use_rope"] = jax.ShapeDtypeStruct(
        (config.num_hidden_layers,), jnp.bool_
    )
    return struct


# ---------------------------------------------------------------------------
# Vision tower (reference: the llama4 vision side of models/llama4/, ~2000 LoC
# of its 3245; HF Llama4VisionModel semantics)
# ---------------------------------------------------------------------------

from dataclasses import dataclass as _dataclass  # noqa: E402


@_dataclass(frozen=True)
class Llama4VisionArch:
    hidden_size: int
    intermediate_size: int
    num_layers: int
    num_heads: int
    image_size: int
    patch_size: int
    num_channels: int
    pixel_shuffle_ratio: float
    projector_input_dim: int
    projector_output_dim: int
    norm_eps: float
    rope_theta: float
    vision_output_dim: int
    text_hidden: int

    @property
    def num_patches(self) -> int:  # EXCLUDING the (appended) cls token
        return (self.image_size // self.patch_size) ** 2


def build_vision_arch(config: InferenceConfig) -> Llama4VisionArch:
    vc = config.vision_config
    if not isinstance(vc, dict):
        vc = vc.to_dict()
    return Llama4VisionArch(
        hidden_size=vc["hidden_size"],
        intermediate_size=vc["intermediate_size"],
        num_layers=vc["num_hidden_layers"],
        num_heads=vc["num_attention_heads"],
        image_size=vc["image_size"],
        patch_size=vc["patch_size"],
        num_channels=vc.get("num_channels", 3),
        pixel_shuffle_ratio=vc.get("pixel_shuffle_ratio", 0.5),
        projector_input_dim=vc["projector_input_dim"],
        projector_output_dim=vc["projector_output_dim"],
        norm_eps=vc.get("norm_eps", 1e-5),
        rope_theta=vc.get("rope_theta", 10000.0),
        vision_output_dim=vc["vision_output_dim"],
        text_hidden=config.hidden_size,
    )


def _vision_freqs(varch: Llama4VisionArch) -> np.ndarray:
    """(N+1, D/2, 2) [cos, sin] 2-D rope phases, cls row zeroed (HF
    Llama4VisionRotaryEmbedding — the cls token gets identity rotation)."""
    idx = varch.image_size // varch.patch_size
    D = varch.hidden_size // varch.num_heads
    fd = D // 2
    img = np.arange(idx ** 2)
    fx = (img % idx + 1).astype(np.float64)
    fy = (img // idx + 1).astype(np.float64)
    rope_freq = 1.0 / (
        varch.rope_theta ** (np.arange(0, fd, 2)[: fd // 2] / fd)
    )
    freqs_x = np.repeat(fx[:, None] * rope_freq[None, :], 2, axis=-1)
    freqs_y = np.repeat(fy[:, None] * rope_freq[None, :], 2, axis=-1)
    freqs = np.concatenate([freqs_x, freqs_y], axis=-1)[:, ::2]  # (N, D/2)
    freqs = np.concatenate([freqs, np.zeros((1, freqs.shape[1]))], axis=0)
    return np.stack([np.cos(freqs), np.sin(freqs)], axis=-1).astype(np.float32)


def encode_images(varch: Llama4VisionArch, params, pixel_values):
    """(BT, C, H, W) tiles -> (B?, merged_tokens, text_hidden) — unfold patch
    embed, cls APPENDED, learned positions, pre-LN, 2-D complex rope layers,
    post-LN, pixel shuffle + MLP2 adapter, projector."""
    import jax
    import jax.numpy as jnp

    from nxdi_tpu.ops.norms import layer_norm

    v = params["vision"]
    BT, C, HI, WI = pixel_values.shape
    P = varch.patch_size
    g = HI // P
    E = varch.hidden_size
    nh = varch.num_heads
    d = E // nh

    # unfold == patchify: (BT, gh, gw, C, P, P) -> rows flattened (C, ph, pw)
    x = pixel_values.reshape(BT, C, g, P, g, P)
    x = x.transpose(0, 2, 4, 1, 3, 5).reshape(BT, g * g, C * P * P)
    h = x @ v["patch_embedding"]
    cls = jnp.broadcast_to(v["class_embedding"][None, None, :], (BT, 1, E))
    h = jnp.concatenate([h, cls], axis=1)  # cls LAST (llama4 quirk)
    h = h + v["positional_embedding"][None]
    h = layer_norm(h, v["ln_pre"]["w"], v["ln_pre"]["b"], eps=1e-5)

    cs = jnp.asarray(_vision_freqs(varch))  # (N+1, D/2, 2)
    cos, sin = cs[None, :, None, :, 0], cs[None, :, None, :, 1]  # (1, N+1, 1, D/2)

    def rot(x_):  # adjacent-pair complex multiply
        xr = x_.reshape(x_.shape[:-1] + (d // 2, 2))
        a, b = xr[..., 0], xr[..., 1]
        return jnp.stack([a * cos - b * sin, a * sin + b * cos], axis=-1).reshape(x_.shape)

    def layer(carry, lp):
        N = carry.shape[1]
        y = layer_norm(carry, lp["ln1"]["w"], lp["ln1"]["b"], eps=1e-5)
        q = (y @ lp["q_proj"]["w"] + lp["q_proj"]["b"]).reshape(BT, N, nh, d)
        k = (y @ lp["k_proj"]["w"] + lp["k_proj"]["b"]).reshape(BT, N, nh, d)
        val = (y @ lp["v_proj"]["w"] + lp["v_proj"]["b"]).reshape(BT, N, nh, d)
        q, k = rot(q.astype(jnp.float32)), rot(k.astype(jnp.float32))
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
        w = jax.nn.softmax(s * (d ** -0.5), axis=-1).astype(val.dtype)
        attn = jnp.einsum("bhqk,bkhd->bqhd", w, val).reshape(BT, N, E)
        carry = carry + attn @ lp["o_proj"]["w"] + lp["o_proj"]["b"]
        y = layer_norm(carry, lp["ln2"]["w"], lp["ln2"]["b"], eps=1e-5)
        ff = jax.nn.gelu(y @ lp["fc1"]["w"] + lp["fc1"]["b"], approximate=False)
        ff = ff @ lp["fc2"]["w"] + lp["fc2"]["b"]
        return carry + ff, None

    h, _ = jax.lax.scan(layer, h, v["layers"])
    h = layer_norm(h, v["ln_post"]["w"], v["ln_post"]["b"], eps=1e-5)
    h = h[:, :-1]  # drop cls

    # pixel shuffle (HF pixel_shuffle): (BT, N, C) -> (BT, N*r^2? ...)
    r = varch.pixel_shuffle_ratio
    ps = int(varch.num_patches ** 0.5)
    ch = h.shape[-1]
    t = h.reshape(BT, ps, ps, ch)
    t = t.reshape(BT, ps, int(ps * r), int(ch / r)).transpose(0, 2, 1, 3)
    t = t.reshape(BT, int(ps * r), int(ps * r), int(ch / (r * r))).transpose(0, 2, 1, 3)
    t = t.reshape(BT, -1, int(ch / (r * r)))
    # MLP2 adapter: gelu(fc1) -> gelu(fc2)
    a = v["adapter"]
    t = jax.nn.gelu(t @ a["fc1"]["w"], approximate=False)
    t = jax.nn.gelu(t @ a["fc2"]["w"], approximate=False)
    # (BT, merged, text_hidden): one tile per image per batch row — the
    # image-to-text base distributes rows by placeholder counts
    return t @ params["projector"]["w"]


def num_image_tokens(config: InferenceConfig) -> int:
    varch = build_vision_arch(config)
    per_tile = int(varch.num_patches * varch.pixel_shuffle_ratio ** 2)
    return int(getattr(config, "max_image_tokens", 0) or per_tile)


def convert_vision_params(state_dict, config: InferenceConfig):
    varch = build_vision_arch(config)

    def get(name):
        for k in (f"model.{name}", name):
            if k in state_dict:
                return state_dict[k]
        raise KeyError(f"missing vision weight {name}")

    f32 = lambda x: np.asarray(x, np.float32)  # noqa: E731
    layers = []
    for i in range(varch.num_layers):
        p = f"vision_model.model.layers.{i}."
        layers.append({
            "ln1": {"w": f32(get(p + "input_layernorm.weight")),
                    "b": f32(get(p + "input_layernorm.bias"))},
            "ln2": {"w": f32(get(p + "post_attention_layernorm.weight")),
                    "b": f32(get(p + "post_attention_layernorm.bias"))},
            "q_proj": {"w": f32(get(p + "self_attn.q_proj.weight").T),
                       "b": f32(get(p + "self_attn.q_proj.bias"))},
            "k_proj": {"w": f32(get(p + "self_attn.k_proj.weight").T),
                       "b": f32(get(p + "self_attn.k_proj.bias"))},
            "v_proj": {"w": f32(get(p + "self_attn.v_proj.weight").T),
                       "b": f32(get(p + "self_attn.v_proj.bias"))},
            "o_proj": {"w": f32(get(p + "self_attn.o_proj.weight").T),
                       "b": f32(get(p + "self_attn.o_proj.bias"))},
            "fc1": {"w": f32(get(p + "mlp.fc1.weight").T), "b": f32(get(p + "mlp.fc1.bias"))},
            "fc2": {"w": f32(get(p + "mlp.fc2.weight").T), "b": f32(get(p + "mlp.fc2.bias"))},
        })
    import jax.tree_util as jtu

    stack = lambda ls: jtu.tree_map(lambda *xs: np.stack(xs), *ls)  # noqa: E731
    return {
        "vision": {
            "patch_embedding": f32(get("vision_model.patch_embedding.linear.weight").T),
            "class_embedding": f32(get("vision_model.class_embedding")),
            "positional_embedding": f32(get("vision_model.positional_embedding_vlm")),
            "ln_pre": {"w": f32(get("vision_model.layernorm_pre.weight")),
                       "b": f32(get("vision_model.layernorm_pre.bias"))},
            "ln_post": {"w": f32(get("vision_model.layernorm_post.weight")),
                        "b": f32(get("vision_model.layernorm_post.bias"))},
            "layers": stack(layers),
            "adapter": {
                "fc1": {"w": f32(get("vision_model.vision_adapter.mlp.fc1.weight").T)},
                "fc2": {"w": f32(get("vision_model.vision_adapter.mlp.fc2.weight").T)},
            },
        },
        "projector": {"w": f32(get("multi_modal_projector.linear_1.weight").T)},
    }


def vision_shape_struct(config: InferenceConfig):
    import jax

    varch = build_vision_arch(config)
    E, I, L = varch.hidden_size, varch.intermediate_size, varch.num_layers
    nP = varch.num_patches + 1

    def s(*shape):
        return jax.ShapeDtypeStruct(shape, np.float32)

    return {
        "vision": {
            "patch_embedding": s(varch.num_channels * varch.patch_size ** 2, E),
            "class_embedding": s(E),
            "positional_embedding": s(nP, E),
            "ln_pre": {"w": s(E), "b": s(E)},
            "ln_post": {"w": s(E), "b": s(E)},
            "layers": {
                "ln1": {"w": s(L, E), "b": s(L, E)},
                "ln2": {"w": s(L, E), "b": s(L, E)},
                "q_proj": {"w": s(L, E, E), "b": s(L, E)},
                "k_proj": {"w": s(L, E, E), "b": s(L, E)},
                "v_proj": {"w": s(L, E, E), "b": s(L, E)},
                "o_proj": {"w": s(L, E, E), "b": s(L, E)},
                "fc1": {"w": s(L, E, I), "b": s(L, I)},
                "fc2": {"w": s(L, I, E), "b": s(L, E)},
            },
            "adapter": {
                "fc1": {"w": s(varch.intermediate_size, varch.projector_input_dim)},
                "fc2": {"w": s(varch.projector_input_dim, varch.projector_output_dim)},
            },
        },
        "projector": {"w": s(varch.vision_output_dim, varch.text_hidden)},
    }
