"""VaultGemma family — gemma2's softcaps + alternating windows WITHOUT the
sandwich norms.

Reference: contrib/models/vaultgemma-1b. HF VaultGemmaForCausalLM
(modeling_vaultgemma.py:163-290): two norms per layer only —
``input_layernorm`` (pre-attention) and ``pre_feedforward_layernorm``
(pre-MLP, mapped onto the post_attention_layernorm slot); gemma (1+w) f32
norms, sqrt(H) embed scale, query_pre_attn_scalar softmax scaling, attn +
final logit softcapping, ``layer_types`` sliding pattern, one rope table."""

from __future__ import annotations

import numpy as np

from nxdi_tpu.config import InferenceConfig
from nxdi_tpu.models import dense
from nxdi_tpu.models.base import DecoderArch
from nxdi_tpu.parallel.layers import REPLICATED

build_inv_freq = dense.build_inv_freq


class VaultGemmaInferenceConfig(dense.DenseInferenceConfig):
    REQUIRED = dense.DenseInferenceConfig.REQUIRED + ["head_dim"]

    def add_derived_config(self):
        if getattr(self, "hidden_activation", None):
            self.hidden_act = self.hidden_activation
        elif not hasattr(self, "hidden_act"):
            self.hidden_act = "gelu_pytorch_tanh"
        super().add_derived_config()
        defaults = {
            "query_pre_attn_scalar": self.head_dim,
            "sliding_window": None,
            "attn_logit_softcapping": None,
            "final_logit_softcapping": None,
        }
        for k, v in defaults.items():
            if not hasattr(self, k):
                setattr(self, k, v)
        if not hasattr(self, "layer_types") or self.layer_types is None:
            self.layer_types = [
                "sliding_attention" if i % 2 == 0 else "full_attention"
                for i in range(self.num_hidden_layers)
            ]


def _sliding_flags(config):
    return np.array(
        [t == "sliding_attention" for t in config.layer_types], dtype=bool
    )


def build_arch(config: InferenceConfig, **overrides) -> DecoderArch:
    sw = getattr(config, "sliding_window", None)
    kwargs = dict(
        gemma_norm=True,
        embed_scale=float(config.hidden_size) ** 0.5,
        sliding_window=sw,
        # window_sized_kv: full-attention layers stay off the ring
        kv_window_pattern=tuple(_sliding_flags(config)) if sw else None,
        attention_scale=float(config.query_pre_attn_scalar) ** -0.5,
        attn_logit_softcap=getattr(config, "attn_logit_softcapping", None),
        final_logit_softcap=getattr(config, "final_logit_softcapping", None),
        tie_word_embeddings=bool(getattr(config, "tie_word_embeddings", True)),
    )
    kwargs.update(overrides)
    return dense.build_arch(config, **kwargs)


def convert_hf_state_dict(state_dict, config: InferenceConfig):
    arch = build_arch(config)
    sd = dict(state_dict)
    for k in list(sd):
        if "pre_feedforward_layernorm." in k:
            sd[k.replace("pre_feedforward_layernorm", "post_attention_layernorm")] = sd.pop(k)
    params = dense.convert_hf_state_dict(sd, config, arch)
    if getattr(config, "sliding_window", None):
        flags = _sliding_flags(config)
        if not flags.all():  # mixed/none: per-layer flags ride the scan
            params["layers"]["use_sliding_window"] = flags
    return params


def param_specs(config: InferenceConfig):
    specs = dense.param_specs_for(build_arch(config))
    if getattr(config, "sliding_window", None) and not _sliding_flags(config).all():
        specs["layers"]["use_sliding_window"] = REPLICATED
    return specs


def param_shape_struct(config: InferenceConfig):
    import jax
    import jax.numpy as jnp

    struct = dense.param_shape_struct(config, build_arch(config))
    if getattr(config, "sliding_window", None) and not _sliding_flags(config).all():
        struct["layers"]["use_sliding_window"] = jax.ShapeDtypeStruct(
            (config.num_hidden_layers,), jnp.bool_
        )
    return struct
