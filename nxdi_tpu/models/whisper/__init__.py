from nxdi_tpu.models.whisper import modeling_whisper
