"""Whisper — audio encoder-decoder (speech-to-text).

Reference: models/whisper/ (951 LoC): ``NeuronAudioEncoder``
(modeling_whisper.py:304), ``NeuronTextDecoder`` (:345) and the separate
encoder/decoder applications (:571-677).

TPU-native mapping:
  - the audio encoder (two gelu convs + sinusoid positions + pre-LN
    transformer) jits as one program; convs lower to XLA's conv which tiles
    onto the MXU;
  - cross-attention K/V are computed ONCE per utterance from the encoder
    output and carried in the cache pytree alongside the self-attention KV
    cache (the reference's encoder application hands its output to the
    decoder application the same way);
  - the decoder step is a fixed-shape jitted program with the self-KV cache
    donated, greedy-sampled on device; one dispatch per token.

TP: attention projections shard by heads (column q/k/v, row out) and the
FFNs on their intermediate dim whenever tp divides them (see
:func:`param_specs`); GSPMD inserts the collectives. Dims that don't divide
stay replicated, so any tp degree is safe.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from nxdi_tpu.config import InferenceConfig
from nxdi_tpu.ops.norms import layer_norm


@dataclass(frozen=True)
class WhisperArch:
    d_model: int
    encoder_layers: int
    decoder_layers: int
    encoder_heads: int
    decoder_heads: int
    encoder_ffn: int
    decoder_ffn: int
    num_mel_bins: int
    max_source_positions: int
    max_target_positions: int
    vocab_size: int
    eps: float = 1e-5


class WhisperInferenceConfig(InferenceConfig):
    REQUIRED = [
        "d_model",
        "encoder_layers",
        "decoder_layers",
        "encoder_attention_heads",
        "decoder_attention_heads",
        "num_mel_bins",
        "max_source_positions",
        "max_target_positions",
        "vocab_size",
    ]

    def add_derived_config(self):
        if not hasattr(self, "encoder_ffn_dim"):
            self.encoder_ffn_dim = 4 * self.d_model
        if not hasattr(self, "decoder_ffn_dim"):
            self.decoder_ffn_dim = 4 * self.d_model


def build_arch(config: InferenceConfig) -> WhisperArch:
    return WhisperArch(
        d_model=config.d_model,
        encoder_layers=config.encoder_layers,
        decoder_layers=config.decoder_layers,
        encoder_heads=config.encoder_attention_heads,
        decoder_heads=config.decoder_attention_heads,
        encoder_ffn=config.encoder_ffn_dim,
        decoder_ffn=config.decoder_ffn_dim,
        num_mel_bins=config.num_mel_bins,
        max_source_positions=config.max_source_positions,
        max_target_positions=config.max_target_positions,
        vocab_size=config.vocab_size,
    )


# ---------------------------------------------------------------------------
# Blocks
# ---------------------------------------------------------------------------

def _attn(p, q_in, kv_in, num_heads: int, mask=None, kv_override=None):
    """Whisper attention: q/v/out biased, k unbiased (HF layout). ``kv_override``
    supplies precomputed (k, v) — the cached cross-attention path."""
    B, Sq, Dm = q_in.shape
    D = Dm // num_heads
    q = (q_in @ p["q_proj"]["w"] + p["q_proj"]["b"]).reshape(B, Sq, num_heads, D)
    q = jnp.swapaxes(q, 1, 2) * (D ** -0.5)
    if kv_override is not None:
        k, v = kv_override
    else:
        Skv = kv_in.shape[1]
        k = jnp.swapaxes((kv_in @ p["k_proj"]["w"]).reshape(B, Skv, num_heads, D), 1, 2)
        v = jnp.swapaxes(
            (kv_in @ p["v_proj"]["w"] + p["v_proj"]["b"]).reshape(B, Skv, num_heads, D), 1, 2
        )
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32)
    if mask is not None:
        scores = jnp.where(mask, scores, -30000.0)
    w = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
    ctx = jnp.einsum("bhqk,bhkd->bhqd", w, v)
    ctx = jnp.swapaxes(ctx, 1, 2).reshape(B, Sq, Dm)
    return ctx @ p["out_proj"]["w"] + p["out_proj"]["b"]


def whisper_encode(arch: WhisperArch, params: Dict[str, Any], input_features):
    """(B, mel, T) -> (B, T//2, d_model) (reference: NeuronAudioEncoder)."""
    p = params["encoder"]
    x = jnp.swapaxes(input_features, 1, 2)  # (B, T, mel)
    # conv1: k=3 stride=1 pad=1; conv2: k=3 stride=2 pad=1 (gelu both)
    x = jax.lax.conv_general_dilated(
        x, p["conv1"]["w"], (1,), [(1, 1)], dimension_numbers=("NWC", "WIO", "NWC")
    ) + p["conv1"]["b"]
    x = jax.nn.gelu(x, approximate=False)
    x = jax.lax.conv_general_dilated(
        x, p["conv2"]["w"], (2,), [(1, 1)], dimension_numbers=("NWC", "WIO", "NWC")
    ) + p["conv2"]["b"]
    x = jax.nn.gelu(x, approximate=False)
    x = x + p["embed_positions"][None, : x.shape[1]]

    def body(h, lp):
        y = layer_norm(h, lp["self_attn_layer_norm"]["w"], lp["self_attn_layer_norm"]["b"])
        h = h + _attn(lp["self_attn"], y, y, arch.encoder_heads)
        y = layer_norm(h, lp["final_layer_norm"]["w"], lp["final_layer_norm"]["b"])
        y = jax.nn.gelu(y @ lp["fc1"]["w"] + lp["fc1"]["b"], approximate=False)
        h = h + (y @ lp["fc2"]["w"] + lp["fc2"]["b"])
        return h, None

    x, _ = jax.lax.scan(body, x, p["layers"])
    return layer_norm(x, p["layer_norm"]["w"], p["layer_norm"]["b"])


def whisper_cross_kv(arch: WhisperArch, params: Dict[str, Any], enc_out):
    """Per-decoder-layer cross K/V from the encoder output, computed once
    (reference: the decoder consumes encoder states each step; caching the
    projections trades a little HBM for per-token matmuls)."""
    B, S, Dm = enc_out.shape
    H = arch.decoder_heads
    D = Dm // H

    def per_layer(carry, lp):
        a = lp["encoder_attn"]
        k = jnp.swapaxes((enc_out @ a["k_proj"]["w"]).reshape(B, S, H, D), 1, 2)
        v = jnp.swapaxes(
            (enc_out @ a["v_proj"]["w"] + a["v_proj"]["b"]).reshape(B, S, H, D), 1, 2
        )
        return carry, (k, v)

    _, (ks, vs) = jax.lax.scan(per_layer, None, params["decoder"]["layers"])
    return {"cross_k": ks, "cross_v": vs}  # (L, B, H, S_enc, D)


def whisper_decode_step(
    arch: WhisperArch,
    params: Dict[str, Any],
    cache: Dict[str, jax.Array],  # {"k","v","cross_k","cross_v"}
    batch: Dict[str, jax.Array],
    *,
    kv_window: int,
    suppress_tokens: tuple = (),
) -> Any:
    """One decoder dispatch over S_new tokens (prefill and single-token decode
    are the same program shape-family; reference: NeuronTextDecoder :345)."""
    p = params["decoder"]
    ids = batch["input_ids"]
    positions = batch["position_ids"]
    B, S = ids.shape
    H = arch.decoder_heads
    Dm = arch.d_model
    D = Dm // H

    h = jnp.take(p["embed_tokens"], ids, axis=0)
    h = h + jnp.take(p["embed_positions"], positions, axis=0)

    def body(carry, xs):
        h = carry
        lp, k_l, v_l, ck, cv = xs
        # self attention with exact-position KV writes (kvcache semantics)
        y = layer_norm(h, lp["self_attn_layer_norm"]["w"], lp["self_attn_layer_norm"]["b"])
        q = (y @ lp["self_attn"]["q_proj"]["w"] + lp["self_attn"]["q_proj"]["b"])
        k_new = (y @ lp["self_attn"]["k_proj"]["w"]).reshape(B, S, H, D)
        v_new = (y @ lp["self_attn"]["v_proj"]["w"] + lp["self_attn"]["v_proj"]["b"]).reshape(B, S, H, D)
        # cache layout (B, H, W, D); scatter at [b, :, pos] takes (B, S, H, D)
        b_idx = jnp.arange(B, dtype=jnp.int32)[:, None]
        k_l = k_l.at[b_idx, :, positions].set(k_new, mode="drop")
        v_l = v_l.at[b_idx, :, positions].set(v_new, mode="drop")
        kk = k_l[:, :, :kv_window]
        vv = v_l[:, :, :kv_window]
        kv_pos = jnp.arange(kv_window, dtype=jnp.int32)[None, :]
        mask = kv_pos[:, None, :] <= positions[:, :, None]  # (B, S, W)
        q = jnp.swapaxes(q.reshape(B, S, H, D), 1, 2) * (D ** -0.5)
        scores = jnp.einsum("bhqd,bhkd->bhqk", q, kk).astype(jnp.float32)
        scores = jnp.where(mask[:, None], scores, -30000.0)
        w = jax.nn.softmax(scores, axis=-1).astype(vv.dtype)
        ctx = jnp.einsum("bhqk,bhkd->bhqd", w, vv)
        ctx = jnp.swapaxes(ctx, 1, 2).reshape(B, S, Dm)
        h = h + (ctx @ lp["self_attn"]["out_proj"]["w"] + lp["self_attn"]["out_proj"]["b"])

        # cross attention over the precomputed encoder K/V (no mask)
        y = layer_norm(h, lp["encoder_attn_layer_norm"]["w"], lp["encoder_attn_layer_norm"]["b"])
        h = h + _attn(lp["encoder_attn"], y, None, H, kv_override=(ck, cv))

        y = layer_norm(h, lp["final_layer_norm"]["w"], lp["final_layer_norm"]["b"])
        y = jax.nn.gelu(y @ lp["fc1"]["w"] + lp["fc1"]["b"], approximate=False)
        h = h + (y @ lp["fc2"]["w"] + lp["fc2"]["b"])
        return h, (k_l, v_l)

    h, (new_k, new_v) = jax.lax.scan(
        body, h, (p["layers"], cache["k"], cache["v"], cache["cross_k"], cache["cross_v"])
    )
    h = layer_norm(h, p["layer_norm"]["w"], p["layer_norm"]["b"])
    # proj_out shares the token embedding (HF whisper ties them)
    logits = (h @ params["proj_out"]).astype(jnp.float32)
    idx = batch["last_token_index"][:, None, None]
    last = jnp.take_along_axis(
        logits, jnp.broadcast_to(idx, (B, 1, logits.shape[-1])), axis=1
    )[:, 0]
    if suppress_tokens:
        # HF masks suppressed ids to -inf before argmax (whisper generation
        # config suppress_tokens / begin_suppress_tokens)
        last = last.at[:, jnp.asarray(suppress_tokens, jnp.int32)].set(-jnp.inf)
    tokens = jnp.argmax(last, axis=-1).astype(jnp.int32)
    new_cache = dict(cache)
    new_cache["k"] = new_k
    new_cache["v"] = new_v
    return {"tokens": tokens[:, None], "logits": logits}, new_cache


# ---------------------------------------------------------------------------
# Conversion
# ---------------------------------------------------------------------------

def convert_hf_state_dict(sd: Dict[str, np.ndarray], config: InferenceConfig):
    arch = build_arch(config)
    f32 = np.float32

    def get(name):
        for k in (name, f"model.{name}"):
            if k in sd:
                return np.asarray(sd[k], dtype=f32)
        raise KeyError(name)

    def lin(prefix, bias=True):
        out = {"w": get(prefix + ".weight").T}
        if bias:
            out["b"] = get(prefix + ".bias")
        return out

    def ln(prefix):
        return {"w": get(prefix + ".weight"), "b": get(prefix + ".bias")}

    def attn(prefix):
        return {
            "q_proj": lin(prefix + ".q_proj"),
            "k_proj": lin(prefix + ".k_proj", bias=False),
            "v_proj": lin(prefix + ".v_proj"),
            "out_proj": lin(prefix + ".out_proj"),
        }

    def enc_layer(i):
        pre = f"encoder.layers.{i}"
        return {
            "self_attn": attn(pre + ".self_attn"),
            "self_attn_layer_norm": ln(pre + ".self_attn_layer_norm"),
            "fc1": lin(pre + ".fc1"),
            "fc2": lin(pre + ".fc2"),
            "final_layer_norm": ln(pre + ".final_layer_norm"),
        }

    def dec_layer(i):
        pre = f"decoder.layers.{i}"
        return {
            "self_attn": attn(pre + ".self_attn"),
            "self_attn_layer_norm": ln(pre + ".self_attn_layer_norm"),
            "encoder_attn": attn(pre + ".encoder_attn"),
            "encoder_attn_layer_norm": ln(pre + ".encoder_attn_layer_norm"),
            "fc1": lin(pre + ".fc1"),
            "fc2": lin(pre + ".fc2"),
            "final_layer_norm": ln(pre + ".final_layer_norm"),
        }

    import jax.tree_util as jtu

    stack = lambda ls: jtu.tree_map(lambda *xs: np.stack(xs), *ls)  # noqa: E731

    embed = get("decoder.embed_tokens.weight")
    proj_out = np.asarray(sd.get("proj_out.weight", embed), dtype=f32)
    return {
        "encoder": {
            # HF conv weight (out, in, k) -> XLA WIO (k, in, out)
            "conv1": {"w": get("encoder.conv1.weight").transpose(2, 1, 0),
                      "b": get("encoder.conv1.bias")},
            "conv2": {"w": get("encoder.conv2.weight").transpose(2, 1, 0),
                      "b": get("encoder.conv2.bias")},
            "embed_positions": get("encoder.embed_positions.weight"),
            "layers": stack([enc_layer(i) for i in range(arch.encoder_layers)]),
            "layer_norm": ln("encoder.layer_norm"),
        },
        "decoder": {
            "embed_tokens": embed,
            "embed_positions": get("decoder.embed_positions.weight"),
            "layers": stack([dec_layer(i) for i in range(arch.decoder_layers)]),
            "layer_norm": ln("decoder.layer_norm"),
        },
        "proj_out": proj_out.T,
    }


# ---------------------------------------------------------------------------
# Application (reference: separate encoder/decoder apps, modeling_whisper.py:571)
# ---------------------------------------------------------------------------

def param_specs(config: InferenceConfig):
    """PartitionSpec tree matching convert_hf_state_dict: head-sharded
    attention + intermediate-sharded FFN when tp divides (reference analog:
    the TP ColumnParallel/RowParallel wiring of the encoder/decoder apps)."""
    from jax.sharding import PartitionSpec as P

    from nxdi_tpu.parallel.mesh import AXIS_MP

    arch = build_arch(config)
    tp = config.tpu_config.tp_degree

    def lin_col(ok, bias=True):
        out = {"w": P(None, None, AXIS_MP) if ok else P(None, None, None)}
        if bias:
            out["b"] = P(None, AXIS_MP) if ok else P(None, None)
        return out

    def lin_row(ok, bias=True):
        out = {"w": P(None, AXIS_MP, None) if ok else P(None, None, None)}
        if bias:
            out["b"] = P(None, None)
        return out

    def ln():
        return {"w": P(None, None), "b": P(None, None)}

    def attn(heads):
        ok = tp > 1 and heads % tp == 0
        return {
            "q_proj": lin_col(ok),
            "k_proj": lin_col(ok, bias=False),
            "v_proj": lin_col(ok),
            "out_proj": lin_row(ok),
        }

    def layers(heads, ffn, cross=False):
        ok_f = tp > 1 and ffn % tp == 0
        lp = {
            "self_attn": attn(heads),
            "self_attn_layer_norm": ln(),
            "fc1": lin_col(ok_f),
            "fc2": lin_row(ok_f),
            "final_layer_norm": ln(),
        }
        if cross:
            lp["encoder_attn"] = attn(heads)
            lp["encoder_attn_layer_norm"] = ln()
        return lp

    rep = P()
    rep2 = {"w": rep, "b": rep}
    return {
        "encoder": {
            "conv1": rep2,
            "conv2": rep2,
            "embed_positions": rep,
            "layers": layers(arch.encoder_heads, arch.encoder_ffn),
            "layer_norm": rep2,
        },
        "decoder": {
            "embed_tokens": rep,
            "embed_positions": rep,
            "layers": layers(arch.decoder_heads, arch.decoder_ffn, cross=True),
            "layer_norm": rep2,
        },
        "proj_out": P(None, AXIS_MP)
        if tp > 1 and config.vocab_size % tp == 0
        else rep,
    }


class WhisperForConditionalGeneration:
    """Greedy speech-to-text: encode once, then one decoder dispatch per token."""

    def __init__(self, model_path: str, config: InferenceConfig, model_family=None):
        self.model_path = model_path
        self.config = config
        self.tpu_config = config.tpu_config
        self.arch = build_arch(config)
        self.mesh = None
        self.params = None
        self.is_loaded = False
        self._programs: Dict[Any, Any] = {}

    def get_state_dict(self):
        from nxdi_tpu import checkpoint as ckpt

        return ckpt.load_state_dict(self.model_path)

    def load(self, compiled_model_path: Optional[str] = None) -> None:
        from nxdi_tpu.parallel.layers import shard_pytree
        from nxdi_tpu.parallel.mesh import mesh_from_config

        self.mesh = mesh_from_config(self.tpu_config)
        # context manager, NOT the process-global setter: other apps jitted
        # later in the same process must not inherit the whisper mesh
        with jax.set_mesh(self.mesh):
            params_host = convert_hf_state_dict(self.get_state_dict(), self.config)
            self.params = shard_pytree(
                params_host, param_specs(self.config), self.mesh
            )
        self.is_loaded = True

    def _program(self, key, fn):
        # mesh scoped at CALL time (jit resolves the context mesh per call,
        # not at wrapping time) — keeps this app's mesh out of global state
        if key not in self._programs:
            jitted = jax.jit(fn)

            def call(*args, _jitted=jitted, **kw):
                with jax.set_mesh(self.mesh):
                    return _jitted(*args, **kw)

            self._programs[key] = call
        return self._programs[key]

    def encode(self, input_features: np.ndarray):
        fn = self._program("encode", partial(whisper_encode, self.arch))
        return fn(self.params, np.asarray(input_features, np.float32))

    def generate(
        self,
        input_features: np.ndarray,
        decoder_input_ids: np.ndarray,
        max_new_tokens: int = 32,
        eos_token_id: Optional[int] = None,
        suppress_tokens: Optional[list] = None,
        begin_suppress_tokens: Optional[list] = None,
        forced_decoder_ids: Optional[list] = None,
    ) -> np.ndarray:
        """Greedy transcription loop (reference: the decoder application's
        generation loop). Token suppression mirrors HF whisper generation:
        ``suppress_tokens`` masked at every step, ``begin_suppress_tokens``
        additionally at the FIRST generated position, ``forced_decoder_ids``
        ([(pos, id), ...]) override sampled tokens at given positions. Values
        default to the model config when present."""
        if not self.is_loaded:
            raise RuntimeError("call load() before generate()")
        if suppress_tokens is None:
            suppress_tokens = getattr(self.config, "suppress_tokens", None) or []
        if begin_suppress_tokens is None:
            begin_suppress_tokens = getattr(self.config, "begin_suppress_tokens", None) or []
        if forced_decoder_ids is None:
            forced_decoder_ids = getattr(self.config, "forced_decoder_ids", None) or []
        forced = {int(p): int(t) for p, t in forced_decoder_ids}
        sup = tuple(int(t) for t in suppress_tokens)
        sup_begin = tuple(sorted(set(sup) | {int(t) for t in begin_suppress_tokens}))
        # HF applies begin_suppress at the first position NOT overridden by
        # forced decoder ids (begin_index skips past the forced prefix)
        enc_out = self.encode(input_features)
        cross = self._program("cross", partial(whisper_cross_kv, self.arch))(
            self.params, enc_out
        )

        B, S0 = decoder_input_ids.shape
        W = min(self.arch.max_target_positions, S0 + max_new_tokens)
        H, D = self.arch.decoder_heads, self.arch.d_model // self.arch.decoder_heads
        cache = {
            "k": jnp.zeros((self.arch.decoder_layers, B, H, W, D), jnp.float32),
            "v": jnp.zeros((self.arch.decoder_layers, B, H, W, D), jnp.float32),
            "cross_k": cross["cross_k"],
            "cross_v": cross["cross_v"],
        }

        begin_pos = S0
        while begin_pos in forced:
            begin_pos += 1
        prefill_sup = sup_begin if begin_pos == S0 else sup
        step = self._program(
            ("prefill", S0, W, prefill_sup),
            partial(whisper_decode_step, self.arch, kv_window=W, suppress_tokens=prefill_sup),
        )
        batch = {
            "input_ids": jnp.asarray(decoder_input_ids, jnp.int32),
            "position_ids": jnp.tile(jnp.arange(S0, dtype=jnp.int32), (B, 1)),
            "last_token_index": jnp.full((B,), S0 - 1, jnp.int32),
        }
        out, cache = step(self.params, cache, batch)
        first = np.asarray(out["tokens"])[:, 0]
        if S0 in forced:
            first = np.full_like(first, forced[S0])
        tokens = [first]

        def decode_program(step_sup):
            return self._program(
                ("decode", W, step_sup),
                partial(whisper_decode_step, self.arch, kv_window=W, suppress_tokens=step_sup),
            )
        finished = np.zeros((B,), dtype=bool)
        if eos_token_id is not None:
            finished |= tokens[-1] == eos_token_id
        pos = S0
        while pos < W and len(tokens) < max_new_tokens and not finished.all():
            batch = {
                "input_ids": jnp.asarray(tokens[-1][:, None], jnp.int32),
                "position_ids": jnp.full((B, 1), pos, jnp.int32),
                "last_token_index": jnp.zeros((B,), jnp.int32),
            }
            # the step that samples sequence position pos+1 carries the
            # begin-suppress mask iff that is the first non-forced position
            step_sup = sup_begin if (pos + 1) == begin_pos else sup
            out, cache = decode_program(step_sup)(self.params, cache, batch)
            nxt = np.asarray(out["tokens"])[:, 0]
            if pos + 1 in forced:
                nxt = np.full_like(nxt, forced[pos + 1])
            if eos_token_id is not None:
                nxt = np.where(finished, eos_token_id, nxt)
            tokens.append(nxt)
            if eos_token_id is not None:
                finished |= nxt == eos_token_id
            pos += 1

        gen = np.stack(tokens, axis=1)
        return np.concatenate([decoder_input_ids, gen], axis=1)
