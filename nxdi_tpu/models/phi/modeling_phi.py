"""Phi (phi-1/1.5/2) family — parallel attention+MLP block with ONE shared
LayerNorm, partial rotary, biased everything including the lm_head.

Reference: contrib/models/phi-1_5. HF PhiForCausalLM
(modeling_phi.py:100-260): ``hidden = attn(ln(x)) + mlp(ln(x)) + x`` with a
single ``input_layernorm`` (aliased onto the parallel block's MLP slot at
conversion); ``rotary_ndims = head_dim * partial_rotary_factor``; gelu_new
``fc1``/``fc2``; model-level ``final_layernorm``; lm_head WITH bias
(params["lm_head_bias"])."""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from nxdi_tpu.config import InferenceConfig
from nxdi_tpu.models import dense
from nxdi_tpu.models.base import DecoderArch
from nxdi_tpu.ops.rope import default_inv_freq


class PhiInferenceConfig(dense.DenseInferenceConfig):
    REQUIRED = [
        "hidden_size", "num_attention_heads", "num_hidden_layers",
        "vocab_size", "intermediate_size",
    ]

    def add_derived_config(self):
        if not hasattr(self, "num_key_value_heads") or self.num_key_value_heads is None:
            self.num_key_value_heads = self.num_attention_heads
        self.rms_norm_eps = getattr(self, "layer_norm_eps", 1e-5)
        if not hasattr(self, "partial_rotary_factor"):
            self.partial_rotary_factor = 0.5
        if not hasattr(self, "hidden_act"):
            self.hidden_act = "gelu_new"
        self.tie_word_embeddings = False
        super().add_derived_config()
        if getattr(self, "qk_layernorm", False):
            raise NotImplementedError("phi qk_layernorm is not supported yet")


def _rotary_dim(config) -> int:
    head_dim = config.hidden_size // config.num_attention_heads
    return int(head_dim * config.partial_rotary_factor)


def build_arch(config: InferenceConfig, **overrides) -> DecoderArch:
    kwargs = dict(
        layernorm=True,
        parallel_block=True,
        gated_mlp=False,
        attention_bias=True,
        attention_o_bias=True,
        mlp_bias=True,
        rotary_dim=_rotary_dim(config),
        hidden_act=getattr(config, "hidden_act", "gelu_new"),
        tie_word_embeddings=False,
    )
    kwargs.update(overrides)
    return dense.build_arch(config, **kwargs)


def build_inv_freq(config: InferenceConfig) -> np.ndarray:
    return default_inv_freq(_rotary_dim(config), getattr(config, "rope_theta", 10000.0))


def convert_hf_state_dict(
    state_dict: Dict[str, np.ndarray], config: InferenceConfig
) -> Dict[str, Any]:
    arch = build_arch(config)
    dt = dense.np_dtype(arch.dtype)
    L = arch.num_layers

    def src(name):
        for k in (name, f"model.{name}"):
            if k in state_dict:
                return np.asarray(state_dict[k])
        raise KeyError(name)

    sd: Dict[str, np.ndarray] = {
        "embed_tokens.weight": src("embed_tokens.weight"),
        "norm.weight": src("final_layernorm.weight"),
        "lm_head.weight": np.asarray(state_dict["lm_head.weight"]),
    }
    norm_biases: Dict[str, np.ndarray] = {"norm": src("final_layernorm.bias")}
    for i in range(L):
        pre = f"layers.{i}."
        for proj in ("q", "k", "v"):
            sd[pre + f"self_attn.{proj}_proj.weight"] = src(pre + f"self_attn.{proj}_proj.weight")
            sd[pre + f"self_attn.{proj}_proj.bias"] = src(pre + f"self_attn.{proj}_proj.bias")
        sd[pre + "self_attn.o_proj.weight"] = src(pre + "self_attn.dense.weight")
        sd[pre + "self_attn.o_proj.bias"] = src(pre + "self_attn.dense.bias")
        # ONE norm: alias onto both parallel-block slots
        sd[pre + "input_layernorm.weight"] = src(pre + "input_layernorm.weight")
        sd[pre + "post_attention_layernorm.weight"] = src(pre + "input_layernorm.weight")
        norm_biases[f"layers.{i}.input"] = src(pre + "input_layernorm.bias")
        norm_biases[f"layers.{i}.post"] = src(pre + "input_layernorm.bias")
        sd[pre + "mlp.up_proj.weight"] = src(pre + "mlp.fc1.weight")
        sd[pre + "mlp.up_proj.bias"] = src(pre + "mlp.fc1.bias")
        sd[pre + "mlp.down_proj.weight"] = src(pre + "mlp.fc2.weight")
        sd[pre + "mlp.down_proj.bias"] = src(pre + "mlp.fc2.bias")

    def ff(get, has, cast, pre):
        return "mlp", {
            "up_proj": {"w": cast(get(pre + "mlp.up_proj.weight").T),
                        "b": cast(get(pre + "mlp.up_proj.bias"))},
            "down_proj": {"w": cast(get(pre + "mlp.down_proj.weight").T),
                          "b": cast(get(pre + "mlp.down_proj.bias"))},
        }

    params = dense.convert_hf_state_dict(sd, config, arch, ff_converter=ff)
    dense.attach_norm_biases(
        params,
        [norm_biases[f"layers.{i}.input"] for i in range(L)],
        [norm_biases[f"layers.{i}.post"] for i in range(L)],
        norm_biases["norm"], dt,
    )
    head_bias = np.asarray(state_dict["lm_head.bias"], dtype=np.float32)
    if arch.vocab_pad:
        head_bias = np.concatenate([head_bias, np.zeros(arch.vocab_pad, np.float32)])
    params["lm_head_bias"] = head_bias
    return params


def param_specs(config: InferenceConfig):
    from jax.sharding import PartitionSpec as P

    from nxdi_tpu.parallel.mesh import AXIS_MP

    specs = dense.biased_layernorm_specs(dense.param_specs_for(build_arch(config)))
    specs["lm_head_bias"] = P(AXIS_MP)  # vocab-parallel, like the head columns
    return specs


def param_shape_struct(config: InferenceConfig):
    import jax
    import jax.numpy as jnp

    from nxdi_tpu.config import to_jax_dtype

    arch = build_arch(config)
    struct = dense.biased_layernorm_struct(
        dense.param_shape_struct(config, arch),
        arch.num_layers, arch.hidden_size, to_jax_dtype(arch.dtype),
    )
    struct["lm_head_bias"] = jax.ShapeDtypeStruct((arch.vocab_size,), jnp.float32)
    return struct
