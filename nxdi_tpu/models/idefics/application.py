"""Idefics application — vision encoder (+ perceiver) feeding gated
cross-attention CausalLM; the mllama pattern (cross K/V written into the
donated cache pytree at prefill, reused at decode).

Reference: contrib/models/idefics-9b-instruct (vision submodel + text model
with per-interval gated cross blocks)."""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import numpy as np

from nxdi_tpu.models.cross_attention_app import CrossAttentionVLApplication
from nxdi_tpu.models.idefics import modeling_idefics as mi
from nxdi_tpu.runtime.model_wrapper import TAG_CONTEXT_ENCODING


class IdeficsApplication(CrossAttentionVLApplication):
    FAMILY_NAME = "idefics"

    def __init__(self, *args, **kwargs):
        kwargs.setdefault("model_family", mi)
        super().__init__(*args, **kwargs)
        self._reject_unsupported()
        self._encode_jit = None
        # last prompt image-mask row per batch line (HF generation repeats
        # image_attention_mask[:, -1:] for every generated token)
        self._last_imask: Optional[np.ndarray] = None
        self._arch = mi.build_arch(self.config)

    def _cross_kv_shape(self):
        arch = self._arch
        t = arch.text
        B = self.tpu_config.kv_cache_batch_size + self.tpu_config.kv_cache_padding_size
        return (arch.n_cross, B, t.num_kv_heads, arch.t_img, t.head_dim)

    # -- submodels --
    def enable_models(self) -> None:
        import jax.numpy as jnp

        super().enable_models()
        arch = self._arch
        M = arch.max_images
        for tag, w in self.models.items():
            w.forward_fn = mi.causal_lm_forward
            w.forward_kwargs.pop("output_all_logits", None)
            w.forward_kwargs.pop("tensor_capture", None)
            w.forward_kwargs.pop("return_next_inputs", None)
            if w.forward_kwargs.pop("dp_sampling", False):
                raise NotImplementedError("idefics does not support dp_sampling yet")
            if tag == TAG_CONTEXT_ENCODING:
                w.extra_inputs["image_states"] = (
                    (arch.t_img, arch.vision_dim), jnp.float32,
                )
                w.extra_inputs["image_attention_mask"] = (
                    (self.tpu_config.max_context_length, M), jnp.float32,
                )
            else:
                w.extra_inputs["image_attention_mask"] = ((1, M), jnp.float32)

    # -- vision program --
    def encode_images(self, pixel_values):
        if self._encode_jit is None:
            varch = mi.build_vision_arch(self.config)
            self._encode_jit = jax.jit(
                partial(mi.encode_images, self.config, varch)
            )
        with jax.set_mesh(self.mesh):
            return self._encode_jit(
                {k: self.params[k] for k in ("vision", "perceiver")
                 if k in self.params},
                np.asarray(pixel_values, np.float32),
            )

    # -- dispatch --
    def forward(
        self,
        input_ids,
        position_ids,
        pixel_values=None,
        image_attention_mask=None,
        **kwargs,
    ):
        arch = self._arch
        M = arch.max_images
        B, S = np.asarray(input_ids).shape
        if S > 1:  # prefill
            if pixel_values is None:
                raise NotImplementedError(
                    "idefics prefill requires images (text-only prefill would "
                    "need a cross-layer-free compiled variant)"
                )
            pv = np.asarray(pixel_values, np.float32)
            if pv.shape[1] != M:
                raise ValueError(
                    f"pixel_values carries {pv.shape[1]} images but the "
                    f"compiled graphs expect max_num_images={M}"
                )
            kwargs["image_states"] = np.asarray(self.encode_images(pv))
            if image_attention_mask is None:
                raise ValueError("image_attention_mask is required at prefill")
            im = np.asarray(image_attention_mask, np.float32)  # (B, S, M)
            S_cap = self.tpu_config.max_context_length
            pad = np.zeros((B, S_cap, M), np.float32)
            pad[:, : im.shape[1]] = im[:, :S_cap]
            kwargs["image_attention_mask"] = pad
            lti = kwargs.get("last_token_index")
            last = (
                np.asarray(lti, np.int64)
                if lti is not None
                else np.full((B,), im.shape[1] - 1, np.int64)
            )
            self._last_imask = im[np.arange(B), np.minimum(last, im.shape[1] - 1)]
        else:
            if image_attention_mask is not None:
                im = np.asarray(image_attention_mask, np.float32).reshape(B, 1, M)
            elif self._last_imask is not None:
                im = self._last_imask[:B].reshape(B, 1, M)
            else:
                raise ValueError(
                    "decode before prefill: no image_attention_mask available"
                )
            kwargs["image_attention_mask"] = im
        return super().forward(input_ids, position_ids, **kwargs)
