"""Idefics (HuggingFace M4) — CLIP vision tower + optional perceiver
resampler + llama decoder with GATED cross-attention blocks every
``cross_layer_interval`` layers.

Reference: contrib/models/idefics-9b-instruct. HF IdeficsForVisionText2Text
(modeling_idefics.py:173-1200, perceiver.py:48-190):
  - decoupled embedding/lm_head: ``additional_vocab_size`` trainable rows
    appended to the frozen tables — merged into single [main | additional]
    tables at conversion (IdeficsDecoupledEmbedding/Linear semantics);
  - self layers are plain llama MHA (no biases; ``qk_layer_norms`` applies
    to the CROSS attention only — HF passes it solely to the gated cross
    block, modeling_idefics.py:701);
  - a gated cross block runs BEFORE every ``cross_layer_interval``-th self
    layer: q from text, k/v project the IMAGE states (vision embed dim),
    no rope; outputs zeroed for tokens attending no image
    (``cross_attention_gate``), then scaled by tanh(alpha) gates;
  - vision tower is CLIP with the CLS token KEPT and no trailing
    post-layernorm on the sequence features;
  - the perceiver resampler (idefics-9b: 64 latents x 6 blocks) compresses
    each image's patch sequence; k/v attend [context | latents].

Cross K/V are computed ONCE at prefill from the image states and live in
the donated cache pytree as ``cross_k``/``cross_v`` (the mllama pattern —
reference analog: multimodal_kv_cache_manager.py)."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from nxdi_tpu.config import InferenceConfig, to_jax_dtype
from nxdi_tpu.models import dense
from nxdi_tpu.models.base import (
    DecoderArch,
    mlp_block,
    run_decoder_layers,
)
from nxdi_tpu.ops import attention as attn_ops
from nxdi_tpu.ops import sampling as sampling_ops
from nxdi_tpu.ops import vision as vision_ops
from nxdi_tpu.ops.norms import layer_norm, rms_norm
from nxdi_tpu.ops.rope import rope_cos_sin
from nxdi_tpu.parallel import gqa
from nxdi_tpu.parallel.layers import constrain
from nxdi_tpu.parallel.policy import DEFAULT_POLICY
from nxdi_tpu.kvcache.kv_cache import DEFAULT_KV_LAYOUT

def __getattr__(name):
    # lazy APPLICATION_CLS: application.py imports this module, so a
    # top-level import back would be circular (the mimo_v2 pattern); the
    # CLI / standard-spec loaders resolve the app class through this hook
    if name == "APPLICATION_CLS":
        from nxdi_tpu.models.idefics.application import IdeficsApplication

        return IdeficsApplication
    raise AttributeError(name)


class IdeficsInferenceConfig(dense.DenseInferenceConfig):
    REQUIRED = [
        "hidden_size", "num_attention_heads", "num_hidden_layers",
        "vocab_size", "intermediate_size", "vision_config",
    ]

    def add_derived_config(self):
        self.num_key_value_heads = self.num_attention_heads  # MHA
        if not hasattr(self, "additional_vocab_size"):
            self.additional_vocab_size = 0
        # merged [main | additional] vocab drives padding + sampling
        self.vocab_size = self.vocab_size + self.additional_vocab_size
        vc = self.vision_config
        if not isinstance(vc, dict):
            self.vision_config = vc.to_dict()
        pc = getattr(self, "perceiver_config", None)
        if pc is not None and not isinstance(pc, dict):
            self.perceiver_config = pc.to_dict()
        if not hasattr(self, "cross_layer_interval"):
            self.cross_layer_interval = 1
        if not hasattr(self, "qk_layer_norms"):
            self.qk_layer_norms = False
        if not hasattr(self, "use_resampler"):
            self.use_resampler = False
        # the number of image SLOTS the compiled graphs carry per request
        if not hasattr(self, "max_num_images"):
            self.max_num_images = 1
        self.rope_theta = 10000.0  # IdeficsEmbedding fixed base
        self.rope_scaling = None
        super().add_derived_config()


@dataclass(frozen=True)
class IdeficsArch:
    text: DecoderArch  # the SELF layers (cross blocks are extra, unrolled)
    cross_interval: int
    n_cross: int
    image_seq: int  # tokens per image fed to cross attention
    vision_dim: int  # width of the image states (vision embed dim)
    max_images: int

    @property
    def t_img(self) -> int:  # cross K/V length
        return self.max_images * self.image_seq

    def kv_cache_spec(self, batch_size, max_len, quant_dtype=None):
        # the self-attn stack's cache; cross K/V are extra pytree entries
        return self.text.kv_cache_spec(batch_size, max_len, quant_dtype)


def _image_seq_len(config: InferenceConfig) -> int:
    vc = config.vision_config
    if getattr(config, "use_resampler", False):
        return int(config.perceiver_config["resampler_n_latents"])
    return (vc["image_size"] // vc["patch_size"]) ** 2 + 1  # patches + CLS


def build_arch(config: InferenceConfig, **overrides) -> IdeficsArch:
    # NOTE: config.qk_layer_norms applies to the CROSS attention only — HF
    # passes it solely to IdeficsGatedCrossAttentionLayer (modeling_idefics
    # .py:701); the self layers are plain llama MHA.
    text = dense.build_arch(config, **overrides)
    L = config.num_hidden_layers
    interval = int(config.cross_layer_interval)
    return IdeficsArch(
        text=text,
        cross_interval=interval,
        n_cross=(L + interval - 1) // interval,
        image_seq=_image_seq_len(config),
        vision_dim=config.vision_config["embed_dim"],
        max_images=int(getattr(config, "max_num_images", 1)),
    )


def build_inv_freq(config: InferenceConfig) -> np.ndarray:
    from nxdi_tpu.ops.rope import default_inv_freq

    return default_inv_freq(dense.head_dim_of(config), 10000.0)


def build_vision_arch(config: InferenceConfig) -> vision_ops.ClipVisionArch:
    vc = config.vision_config
    return vision_ops.ClipVisionArch(
        hidden_size=vc["embed_dim"],
        intermediate_size=vc["intermediate_size"],
        num_layers=vc["num_hidden_layers"],
        num_heads=vc["num_attention_heads"],
        image_size=vc["image_size"],
        patch_size=vc["patch_size"],
        num_channels=vc.get("num_channels", 3),
        hidden_act=vc.get("hidden_act", "gelu"),
        layer_norm_eps=vc.get("layer_norm_eps", 1e-5),
        feature_layer=-1,  # full depth, no post-layernorm on the sequence
        drop_cls=False,  # idefics keeps the CLS token in the image states
    )


# ---------------------------------------------------------------------------
# Perceiver resampler (perceiver.py:48-190)
# ---------------------------------------------------------------------------

def perceiver_forward(config_p: Dict[str, Any], params: Dict[str, Any], context):
    """(B, T, Dv) -> (B, n_latents, Dv). ``config_p``: resampler_n_heads,
    resampler_head_dim, qk_layer_norms_perceiver."""
    nh = config_p["resampler_n_heads"]
    hd = config_p["resampler_head_dim"]
    qk_ln = bool(config_p.get("qk_layer_norms_perceiver", False))
    B = context.shape[0]
    lat = jnp.broadcast_to(
        params["latents"][None], (B,) + params["latents"].shape
    )

    def ln(p, x):
        return layer_norm(x, p["w"], p["b"], eps=1e-5)

    for blk in params["blocks"]:
        a = blk["attn"]
        ctx_n = ln(a["context_ln"], context)
        lat_n = ln(a["latents_ln"], lat)
        kv_in = jnp.concatenate([ctx_n, lat_n], axis=1)
        q = (lat_n @ a["q_proj"]).reshape(B, -1, nh, hd).swapaxes(1, 2)
        k = (kv_in @ a["k_proj"]).reshape(B, -1, nh, hd).swapaxes(1, 2)
        v = (kv_in @ a["v_proj"]).reshape(B, -1, nh, hd).swapaxes(1, 2)
        if qk_ln:
            q = ln(a["q_ln"], q)
            k = ln(a["k_ln"], k)
        scores = jnp.einsum("bhid,bhjd->bhij", q * (hd ** -0.5), k)
        w = jax.nn.softmax(scores, axis=-1)
        out = jnp.einsum("bhij,bhjd->bhid", w, v)
        out = out.swapaxes(1, 2).reshape(B, lat.shape[1], nh * hd)
        lat = lat + out @ a["out_proj"]
        m = blk["mlp"]
        y = ln(m["ln"], lat)
        lat = lat + jax.nn.relu(y @ m["fc"]) @ m["c_proj"]
    return ln(params["final_ln"], lat)


def encode_images(config: InferenceConfig, varch, params: Dict[str, Any], pixel_values):
    """pixel_values (B, M, C, H, W) -> image states (B, M*image_seq, Dv)."""
    B, M = pixel_values.shape[:2]
    flat = pixel_values.reshape((B * M,) + pixel_values.shape[2:])
    feat = vision_ops.clip_vision_forward(varch, params["vision"], flat)
    if getattr(config, "use_resampler", False):
        feat = perceiver_forward(
            {**config.perceiver_config,
             "qk_layer_norms_perceiver": config.perceiver_config.get(
                 "qk_layer_norms_perceiver", False)},
            params["perceiver"], feat,
        )
    seq = feat.shape[1]
    return feat.reshape(B, M * seq, feat.shape[-1])


# ---------------------------------------------------------------------------
# Gated cross-attention block (modeling_idefics.py:691-818)
# ---------------------------------------------------------------------------

def _cross_attention_layer(arch: IdeficsArch, lp, hidden, xk, xv, attend, policy):
    t = arch.text
    B, S, _ = hidden.shape
    H, D = t.num_attention_heads, t.head_dim

    y = rms_norm(hidden, lp["input_layernorm"], t.rms_norm_eps)
    q = (y @ lp["attn"]["q_proj"]["w"]).reshape(B, S, H, D)
    q = jnp.swapaxes(q, 1, 2)
    if "q_norm" in lp["attn"]:
        q = rms_norm(q, lp["attn"]["q_norm"], t.rms_norm_eps)
    q = constrain(q, policy.q)
    ctx = attn_ops.grouped_attention(q, xk, xv, attend, softmax_dtype=jnp.float32)
    ctx = jnp.swapaxes(ctx, 1, 2).reshape(B, S, H * D)
    attn_out = ctx @ lp["attn"]["o_proj"]["w"]
    # zero rows attending no image, THEN the tanh(alpha) gate
    gate_rows = jnp.any(attend, axis=-1, keepdims=True)
    attn_out = jnp.where(gate_rows, attn_out, 0.0)
    hidden = hidden + jnp.tanh(lp["alpha_cross_attn"]) * attn_out

    y = rms_norm(hidden, lp["post_attention_layernorm"], t.rms_norm_eps)
    ff = mlp_block(t, lp["mlp"], y)
    hidden = hidden + jnp.tanh(lp["alpha_dense"]) * ff
    return constrain(hidden, policy.hidden)


def _compute_cross_kv(arch: IdeficsArch, lp, image_states, policy):
    t = arch.text
    B, T, _ = image_states.shape
    KV, D = t.num_kv_heads, t.head_dim
    k = (image_states @ lp["attn"]["k_proj"]["w"]).reshape(B, T, KV, D)
    v = (image_states @ lp["attn"]["v_proj"]["w"]).reshape(B, T, KV, D)
    k = jnp.swapaxes(k, 1, 2)
    if "k_norm" in lp["attn"]:
        k = rms_norm(k, lp["attn"]["k_norm"], t.rms_norm_eps)
    v = jnp.swapaxes(v, 1, 2)
    return constrain(k, policy.kv), constrain(v, policy.kv)


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------

def causal_lm_forward(
    arch: IdeficsArch,
    inv_freq: np.ndarray,
    params: Dict[str, Any],
    cache: Dict[str, jax.Array],
    batch: Dict[str, jax.Array],
    *,
    attend_to_cache: bool,
    kv_window: Optional[int] = None,
    policy=DEFAULT_POLICY,
    layout=DEFAULT_KV_LAYOUT,
    gather_last_token: bool = True,
    output_logits: bool = False,
    on_device_sampling: bool = True,
    do_sample: bool = False,
    global_topk: int = 256,
    deterministic: bool = False,
):
    """One submodel forward: a gated cross block BEFORE every
    ``cross_interval``-th self layer (IdeficsModel.forward layer loop),
    dense self segments scanned in between."""
    t = arch.text
    compute_dtype = to_jax_dtype(t.dtype)
    input_ids = batch["input_ids"]
    position_ids = batch["position_ids"]
    B, S = input_ids.shape

    hidden = jnp.take(params["embed_tokens"], input_ids, axis=0).astype(compute_dtype)
    hidden = constrain(hidden, policy.hidden)
    cos, sin = rope_cos_sin(position_ids, np.asarray(inv_freq), dtype=jnp.float32)
    cache_spec = t.kv_cache_spec(cache["k"].shape[1], cache["k"].shape[3])

    # (B, S_fixed, max_images) 1/0 -> (B, S, T_img) bool over image tokens
    xmask = batch["image_attention_mask"][:, :S].astype(jnp.float32)
    attend = jnp.repeat(xmask, arch.image_seq, axis=2) > 0

    if attend_to_cache:
        xk_all, xv_all = cache["cross_k"], cache["cross_v"]
    else:
        xk_list, xv_list = [], []

    L = t.num_layers
    interval = arch.cross_interval
    k_segs, v_segs = [], []
    for lo in range(0, L, interval):
        hi = min(lo + interval, L)
        ordinal = lo // interval
        lp = jax.tree_util.tree_map(lambda x: x[ordinal], params["cross"])
        if attend_to_cache:
            xk = xk_all[ordinal].astype(compute_dtype)
            xv = xv_all[ordinal].astype(compute_dtype)
        else:
            xk, xv = _compute_cross_kv(
                arch, lp, batch["image_states"].astype(compute_dtype), policy
            )
            xk_list.append(xk)
            xv_list.append(xv)
        hidden = _cross_attention_layer(arch, lp, hidden, xk, xv, attend, policy)

        seg = jax.tree_util.tree_map(lambda x: x[lo:hi], params["layers"])
        k_sl = jax.lax.slice_in_dim(cache["k"], lo, hi, axis=0)
        v_sl = jax.lax.slice_in_dim(cache["v"], lo, hi, axis=0)
        hidden, seg_cache = run_decoder_layers(
            t, seg, hidden, cos, sin, {"k": k_sl, "v": v_sl},
            position_ids, cache_spec, attend_to_cache, kv_window=kv_window,
            policy=policy, layout=layout,
        )
        k_segs.append(seg_cache["k"])
        v_segs.append(seg_cache["v"])

    cat = lambda xs: xs[0] if len(xs) == 1 else jnp.concatenate(xs, axis=0)  # noqa: E731
    new_cache = {"k": cat(k_segs), "v": cat(v_segs)}
    if attend_to_cache:
        new_cache["cross_k"], new_cache["cross_v"] = xk_all, xv_all
    else:
        store = cache["cross_k"].dtype
        new_cache["cross_k"] = jnp.stack(xk_list).astype(store)
        new_cache["cross_v"] = jnp.stack(xv_list).astype(store)

    hidden = rms_norm(hidden, params["norm"], t.rms_norm_eps)
    lm_head = params["lm_head"]  # decoupled head is never tied
    if gather_last_token:
        idx = batch["last_token_index"][:, None, None]
        hidden = jnp.take_along_axis(
            hidden, jnp.broadcast_to(idx, (B, 1, hidden.shape[2])), axis=1
        )
    logits = (hidden @ lm_head.astype(hidden.dtype)).astype(jnp.float32)
    logits = constrain(logits, policy.logits)
    logits = sampling_ops.mask_padded_logits(logits, t.vocab_pad)

    outputs: Dict[str, jax.Array] = {}
    if on_device_sampling:
        outputs["tokens"] = sampling_ops.sample(
            logits[:, -1, :],
            batch["sampling_params"],
            rng=batch.get("rng"),
            do_sample=do_sample,
            global_topk=global_topk,
            deterministic=deterministic,
        )[:, None]
    if output_logits or not on_device_sampling:
        outputs["logits"] = logits
    return outputs, new_cache


# ---------------------------------------------------------------------------
# Checkpoint conversion
# ---------------------------------------------------------------------------

def _merge_decoupled(main: np.ndarray, additional: Optional[np.ndarray]):
    if additional is None or additional.size == 0:
        return main
    return np.concatenate([main, additional], axis=0)


def convert_hf_state_dict(
    state_dict: Dict[str, np.ndarray], config: InferenceConfig
) -> Dict[str, Any]:
    arch = build_arch(config)
    t = arch.text

    def src(name, default=None):
        for k in (name, f"model.{name}"):
            if k in state_dict:
                return np.asarray(state_dict[k])
        if default is not None:
            return default
        raise KeyError(name)

    def opt(name):
        for k in (name, f"model.{name}"):
            if k in state_dict:
                return np.asarray(state_dict[k])
        return None

    # text self layers: dense layout with merged decoupled embed/head
    sd: Dict[str, np.ndarray] = {
        "embed_tokens.weight": _merge_decoupled(
            src("embed_tokens.weight"),
            opt("embed_tokens.additional_embedding.weight"),
        ),
        "norm.weight": src("norm.weight"),
        "lm_head.weight": _merge_decoupled(
            np.asarray(state_dict["lm_head.weight"]),
            (np.asarray(state_dict["lm_head.additional_fc.weight"])
             if "lm_head.additional_fc.weight" in state_dict else None),
        ),
    }
    for i in range(t.num_layers):
        pre = f"layers.{i}."
        for name in (
            "self_attn.q_proj.weight", "self_attn.k_proj.weight",
            "self_attn.v_proj.weight", "self_attn.o_proj.weight",
            "input_layernorm.weight", "post_attention_layernorm.weight",
            "mlp.gate_proj.weight", "mlp.up_proj.weight", "mlp.down_proj.weight",
        ):
            sd[pre + name] = src(pre + name)
    params = dense.convert_hf_state_dict(sd, config, t)

    # cross blocks: one pytree stacked over ordinals
    dt = dense.np_dtype(t.dtype)
    plan = dense.gqa_plan(config)
    D = t.head_dim
    cast = lambda x: np.asarray(x, dtype=dt)  # noqa: E731
    cross_layers = []
    for j in range(arch.n_cross):
        pre = f"gated_cross_attn_layers.{j}."
        attn = {
            "q_proj": {"w": cast(gqa.convert_q(src(pre + "cross_attn.q_proj.weight"), D, plan).T)},
            "k_proj": {"w": cast(gqa.convert_kv(src(pre + "cross_attn.k_proj.weight"), D, plan).T)},
            "v_proj": {"w": cast(gqa.convert_kv(src(pre + "cross_attn.v_proj.weight"), D, plan).T)},
            "o_proj": {"w": cast(gqa.convert_o(src(pre + "cross_attn.o_proj.weight"), D, plan).T)},
        }
        if opt(pre + "cross_attn.q_layer_norm.weight") is not None:
            attn["q_norm"] = cast(src(pre + "cross_attn.q_layer_norm.weight"))
            attn["k_norm"] = cast(src(pre + "cross_attn.k_layer_norm.weight"))
        cross_layers.append({
            "input_layernorm": cast(src(pre + "input_layernorm.weight")),
            "post_attention_layernorm": cast(src(pre + "post_attention_layernorm.weight")),
            "alpha_cross_attn": np.asarray(src(pre + "alpha_cross_attn"), np.float32),
            "alpha_dense": np.asarray(src(pre + "alpha_dense"), np.float32),
            "attn": attn,
            "mlp": {
                "gate_proj": {"w": cast(src(pre + "mlp.gate_proj.weight").T)},
                "up_proj": {"w": cast(src(pre + "mlp.up_proj.weight").T)},
                "down_proj": {"w": cast(src(pre + "mlp.down_proj.weight").T)},
            },
        })
    params["cross"] = dense.tree_stack(cross_layers)
    return params


def convert_vision_params(
    state_dict: Dict[str, np.ndarray], config: InferenceConfig
) -> Dict[str, Any]:
    varch = build_vision_arch(config)
    out: Dict[str, Any] = {
        "vision": vision_ops.convert_clip_vision(
            state_dict, varch, prefix="vision_model."
        ),
    }
    if getattr(config, "use_resampler", False):
        def get(name):
            for k in (f"model.perceiver_resampler.{name}", f"perceiver_resampler.{name}"):
                if k in state_dict:
                    return np.asarray(state_dict[k], np.float32)
            raise KeyError(name)

        def has(name):
            return (f"model.perceiver_resampler.{name}" in state_dict
                    or f"perceiver_resampler.{name}" in state_dict)

        depth = int(config.perceiver_config["resampler_depth"])
        blocks = []
        for j in range(depth):
            a = {
                "context_ln": {"w": get(f"blocks.{j}.0.context_layer_norm.weight"),
                               "b": get(f"blocks.{j}.0.context_layer_norm.bias")},
                "latents_ln": {"w": get(f"blocks.{j}.0.latents_layer_norm.weight"),
                               "b": get(f"blocks.{j}.0.latents_layer_norm.bias")},
                "q_proj": get(f"blocks.{j}.0.q_proj.weight").T,
                "k_proj": get(f"blocks.{j}.0.k_proj.weight").T,
                "v_proj": get(f"blocks.{j}.0.v_proj.weight").T,
                "out_proj": get(f"blocks.{j}.0.output_proj.weight").T,
            }
            if has(f"blocks.{j}.0.q_layer_norm.weight"):
                a["q_ln"] = {"w": get(f"blocks.{j}.0.q_layer_norm.weight"),
                             "b": get(f"blocks.{j}.0.q_layer_norm.bias")}
                a["k_ln"] = {"w": get(f"blocks.{j}.0.k_layer_norm.weight"),
                             "b": get(f"blocks.{j}.0.k_layer_norm.bias")}
            m = {
                "ln": {"w": get(f"blocks.{j}.1.ln.weight"),
                       "b": get(f"blocks.{j}.1.ln.bias")},
                "fc": get(f"blocks.{j}.1.fc.weight").T,
                "c_proj": get(f"blocks.{j}.1.c_proj.weight").T,
            }
            blocks.append({"attn": a, "mlp": m})
        out["perceiver"] = {
            "latents": get("latents"),
            "blocks": blocks,
            "final_ln": {"w": get("layer_norm.weight"), "b": get("layer_norm.bias")},
        }
    return out


def vision_shape_struct(config: InferenceConfig) -> Dict[str, Any]:
    varch = build_vision_arch(config)
    Hv, Iv, L = varch.hidden_size, varch.intermediate_size, varch.num_layers
    P2 = varch.num_channels * varch.patch_size ** 2
    s = lambda *shape: jax.ShapeDtypeStruct(shape, np.float32)  # noqa: E731
    lin = lambda i, o: {"w": s(L, i, o), "b": s(L, o)}  # noqa: E731
    out: Dict[str, Any] = {
        "vision": {
            "patch_embedding": s(P2, Hv),
            "class_embedding": s(Hv),
            "position_embedding": s(varch.num_patches + 1, Hv),
            "pre_layernorm": {"w": s(Hv), "b": s(Hv)},
            "layers": {
                "attn": {
                    n: lin(Hv, Hv) for n in ("q_proj", "k_proj", "v_proj", "out_proj")
                },
                "ln1": {"w": s(L, Hv), "b": s(L, Hv)},
                "ln2": {"w": s(L, Hv), "b": s(L, Hv)},
                "fc1": lin(Hv, Iv),
                "fc2": lin(Iv, Hv),
            },
        },
    }
    if getattr(config, "use_resampler", False):
        pc = config.perceiver_config
        nh, hd = pc["resampler_n_heads"], pc["resampler_head_dim"]
        inner = nh * hd
        inter = Hv * 4
        n_lat = pc["resampler_n_latents"]
        lnp = {"w": s(Hv), "b": s(Hv)}

        def blk():
            a = {
                "context_ln": dict(lnp), "latents_ln": dict(lnp),
                "q_proj": s(Hv, inner), "k_proj": s(Hv, inner),
                "v_proj": s(Hv, inner), "out_proj": s(inner, Hv),
            }
            if pc.get("qk_layer_norms_perceiver", False):
                a["q_ln"] = {"w": s(hd), "b": s(hd)}
                a["k_ln"] = {"w": s(hd), "b": s(hd)}
            return {
                "attn": a,
                "mlp": {"ln": dict(lnp), "fc": s(Hv, inter), "c_proj": s(inter, Hv)},
            }

        out["perceiver"] = {
            "latents": s(n_lat, Hv),
            "blocks": [blk() for _ in range(int(pc["resampler_depth"]))],
            "final_ln": dict(lnp),
        }
    return out


def param_specs(config: InferenceConfig):
    from jax.sharding import PartitionSpec as P

    from nxdi_tpu.parallel.layers import (
        COLUMN_PARALLEL, REPLICATED, ROW_PARALLEL,
    )

    arch = build_arch(config)
    specs = dense.param_specs_for(arch.text)

    def stack(tree):  # prepend the cross-ordinal stack dim to every spec
        return jax.tree_util.tree_map(
            lambda sp: P(*((None,) + tuple(sp))), tree,
            is_leaf=lambda x: isinstance(x, P),
        )

    cross = {
        "input_layernorm": REPLICATED,
        "post_attention_layernorm": REPLICATED,
        "alpha_cross_attn": REPLICATED,
        "alpha_dense": REPLICATED,
        "attn": {
            "q_proj": {"w": COLUMN_PARALLEL},
            "k_proj": {"w": COLUMN_PARALLEL},
            "v_proj": {"w": COLUMN_PARALLEL},
            "o_proj": {"w": ROW_PARALLEL},
        },
        "mlp": {
            "gate_proj": {"w": COLUMN_PARALLEL},
            "up_proj": {"w": COLUMN_PARALLEL},
            "down_proj": {"w": ROW_PARALLEL},
        },
    }
    if getattr(config, "qk_layer_norms", False):
        cross["attn"]["q_norm"] = REPLICATED
        cross["attn"]["k_norm"] = REPLICATED
    specs["cross"] = stack(cross)
    return specs


def param_shape_struct(config: InferenceConfig):
    arch = build_arch(config)
    t = arch.text
    struct = dense.param_shape_struct(config, t)
    dt = to_jax_dtype(t.dtype)
    N, hs, D = arch.n_cross, t.hidden_size, t.head_dim
    H, KV = t.num_attention_heads, t.num_kv_heads
    inter = t.intermediate_size
    Dv = arch.vision_dim
    s = lambda *shape: jax.ShapeDtypeStruct(shape, dt)  # noqa: E731
    cross: Dict[str, Any] = {
        "input_layernorm": s(N, hs),
        "post_attention_layernorm": s(N, hs),
        "alpha_cross_attn": jax.ShapeDtypeStruct(
            (N,) + _alpha_shape(config), np.float32
        ),
        "alpha_dense": jax.ShapeDtypeStruct(
            (N,) + _alpha_shape(config), np.float32
        ),
        "attn": {
            "q_proj": {"w": s(N, hs, H * D)},
            "k_proj": {"w": s(N, Dv, KV * D)},
            "v_proj": {"w": s(N, Dv, KV * D)},
            "o_proj": {"w": s(N, H * D, hs)},
        },
        "mlp": {
            "gate_proj": {"w": s(N, hs, inter)},
            "up_proj": {"w": s(N, hs, inter)},
            "down_proj": {"w": s(N, inter, hs)},
        },
    }
    if getattr(config, "qk_layer_norms", False):
        cross["attn"]["q_norm"] = s(N, D)
        cross["attn"]["k_norm"] = s(N, D)
    struct["cross"] = cross
    return struct


def _alpha_shape(config) -> Tuple[int, ...]:
    if getattr(config, "alpha_type", "float") == "vector":
        return (1, 1, config.hidden_size)
    return (1,)
