"""EXAONE 4.0 family — post-block norms + per-head qk norm + hybrid
sliding/global attention with global NoPE.

Reference: contrib/models/EXAONE-4.0-1.2B. HF Exaone4ForCausalLM
(modeling_exaone4.py:107-230):
  - NO input norms; RMSNorm on the attention/MLP OUTPUT before the residual
    (the olmo2 ``post_block_norm`` ordering) — HF names them
    post_attention_layernorm / post_feedforward_layernorm;
  - qwen3-style per-head q/k rmsnorm BEFORE rope;
  - hybrid models (``sliding_window`` set): ``layer_types`` marks sliding
    layers; GLOBAL layers skip rope entirely ("global NoPE") — both ride the
    layer scan as per-layer flags (use_sliding_window / use_rope)."""

from __future__ import annotations

import numpy as np

from nxdi_tpu.config import InferenceConfig
from nxdi_tpu.models import dense
from nxdi_tpu.models.base import DecoderArch
from nxdi_tpu.parallel.layers import REPLICATED

build_inv_freq = dense.build_inv_freq


class Exaone4InferenceConfig(dense.DenseInferenceConfig):
    def add_derived_config(self):
        super().add_derived_config()
        if not hasattr(self, "sliding_window"):
            self.sliding_window = None
        if not hasattr(self, "layer_types") or self.layer_types is None:
            if self.sliding_window:
                pat = getattr(self, "sliding_window_pattern", 4) or 4
                # "LLLG" / 4: every pat-th layer is global
                self.layer_types = [
                    "full_attention" if (i + 1) % pat == 0 else "sliding_attention"
                    for i in range(self.num_hidden_layers)
                ]
            else:
                self.layer_types = ["full_attention"] * self.num_hidden_layers


def build_arch(config: InferenceConfig, **overrides) -> DecoderArch:
    sw = getattr(config, "sliding_window", None)
    kwargs = dict(
        post_block_norm=True,
        qk_norm=True,
        sliding_window=sw,
        # window_sized_kv: full-attention layers stay off the ring
        kv_window_pattern=(
            tuple(bool(f) for f in _layer_flags(config)[0]) if sw else None
        ),
    )
    kwargs.update(overrides)
    return dense.build_arch(config, **kwargs)


def _layer_flags(config):
    """Hybrid models only: sliding layers attend windowed AND are the only
    layers that rope (global NoPE)."""
    sliding = np.array(
        [t == "sliding_attention" for t in config.layer_types], dtype=bool
    )
    return sliding, sliding.copy()


def convert_hf_state_dict(state_dict, config: InferenceConfig):
    arch = build_arch(config)
    # alias the post-norms onto the post_block_norm keys (olmo2 convention):
    # HF post_attention -> "input_layernorm" (attn post-norm),
    # HF post_feedforward -> "post_attention_layernorm" (mlp post-norm)
    sd = dict(state_dict)
    for i in range(config.num_hidden_layers):
        for pre in ("model.layers.", "layers."):
            p = f"{pre}{i}."
            if p + "post_attention_layernorm.weight" not in sd:
                continue
            sd[p + "input_layernorm.weight"] = sd[p + "post_attention_layernorm.weight"]
            sd[p + "post_attention_layernorm.weight"] = sd.pop(
                p + "post_feedforward_layernorm.weight"
            )
    params = dense.convert_hf_state_dict(sd, config, arch)
    if getattr(config, "sliding_window", None):
        sliding, use_rope = _layer_flags(config)
        params["layers"]["use_sliding_window"] = sliding
        params["layers"]["use_rope"] = use_rope
    return params


def param_specs(config: InferenceConfig):
    specs = dense.param_specs_for(build_arch(config))
    if getattr(config, "sliding_window", None):
        specs["layers"]["use_sliding_window"] = REPLICATED
        specs["layers"]["use_rope"] = REPLICATED
    return specs


def param_shape_struct(config: InferenceConfig):
    import jax
    import jax.numpy as jnp

    struct = dense.param_shape_struct(config, build_arch(config))
    if getattr(config, "sliding_window", None):
        L = config.num_hidden_layers
        struct["layers"]["use_sliding_window"] = jax.ShapeDtypeStruct((L,), jnp.bool_)
        struct["layers"]["use_rope"] = jax.ShapeDtypeStruct((L,), jnp.bool_)
    return struct
