"""MiMo-V2-Flash — hybrid full/sliding-window MoE decoder with asymmetric
q/k vs v head widths (the reference's second published-benchmark model).

Reference: models/mimo_v2/modeling_mimo_v2.py (1975 LoC). Architectural
pieces and how they land here:
  - hybrid_layer_pattern: per-layer full vs sliding-window attention with
    INDEPENDENT head counts, head dims, and rope theta per type (:276) —
    expressed as two DecoderArch variants walked in depth-ordered segments,
    each type owning its own layer-stacked KV cache.
  - asymmetric q/k head_dim (192) vs v head_dim (128) (:324) —
    DecoderArch.v_head_dim; the cache stores v at its own width.
  - partial rotary (partial_rotary_factor, even-rounded) per type ->
    DecoderArch.rotary_dim.
  - moe_layer_freq: per-layer MoE or dense MLP (:888) — segments also split
    on the ff-type boundary; sigmoid router, renormalized top-k.

HF weight layout: llama-style attention; router ``mlp.gate``; experts
``mlp.experts.{i}.gate/up/down_proj``; dense layers ``mlp.gate/up/down_proj``.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Tuple

import jax
import numpy as np

from nxdi_tpu.config import InferenceConfig
from nxdi_tpu.models import dense
from nxdi_tpu.models.base import DecoderArch
from nxdi_tpu.ops.moe import MoEArch, convert_hf_experts, moe_parallel_fields
from nxdi_tpu.ops.rope import inv_freq_from_hf_config
from nxdi_tpu.parallel import gqa


class MiMoV2InferenceConfig(dense.DenseInferenceConfig):
    REQUIRED = [
        "hidden_size", "num_hidden_layers", "num_attention_heads",
        "num_key_value_heads", "head_dim", "v_head_dim", "vocab_size",
        "hybrid_layer_pattern", "moe_layer_freq", "n_routed_experts",
        "num_experts_per_tok", "moe_intermediate_size", "partial_rotary_factor",
        "sliding_window", "swa_head_dim", "swa_v_head_dim",
        "swa_num_attention_heads", "swa_num_key_value_heads", "swa_rope_theta",
        "rope_theta",
    ]

    def add_derived_config(self):
        if not hasattr(self, "rms_norm_eps"):
            self.rms_norm_eps = getattr(self, "layernorm_epsilon", 1e-6)
        if not hasattr(self, "intermediate_size"):
            # dense layers use the plain intermediate size; experts use
            # moe_intermediate_size
            self.intermediate_size = getattr(
                self, "dense_intermediate_size", self.moe_intermediate_size
            )
        super().add_derived_config()


def _rope_dim(head_dim: int, factor: float) -> int:
    rd = int(head_dim * factor)
    return rd - (rd % 2)


@dataclass(frozen=True)
class MiMoV2Arch:
    """Two per-type decoder arches + the depth-ordered segment walk.

    Each schedule entry: (attn_type, type_lo, type_hi, seg_idx) — half-open
    type-local layer range into that type's stacked params/cache, and the
    index of the stacked params segment in ``params["segments"]``."""

    full: DecoderArch
    swa: DecoderArch
    schedule: Tuple[Tuple[str, int, int, int], ...]
    swa_theta: float

    # the app sizes the FULL-type cache through the usual path
    def kv_cache_spec(self, batch_size, max_len, quant_dtype=None):
        return self.full.kv_cache_spec(batch_size, max_len, quant_dtype=quant_dtype)

    @property
    def num_layers(self):
        return self.full.num_layers + self.swa.num_layers

    @property
    def kv_window_pattern(self):
        """Depth-ordered window flags (schedule order) — lets the wrapper's
        layout selection keep the CONTIGUOUS layout primary under
        window_sized_kv (only the swa stack rides the ring; see
        application.py / kv_layout_from_config)."""
        flags = []
        for kind, lo, hi, _ in self.schedule:
            flags.extend([kind == "swa"] * (hi - lo))
        return tuple(flags)

    def __getattr__(self, name):
        # the runtime reads generic decoder attrs (vocab, dtype, sampler
        # wiring) — proxy them to the full-attention arch
        return getattr(object.__getattribute__(self, "full"), name)


def _moe_arch(config: InferenceConfig) -> MoEArch:
    return MoEArch(
        num_experts=config.n_routed_experts,
        top_k=config.num_experts_per_tok,
        intermediate_size=config.moe_intermediate_size,
        hidden_act=getattr(config, "hidden_act", "silu"),
        norm_topk_prob=bool(getattr(config, "norm_topk_prob", True)),
        sigmoid_routing=str(getattr(config, "scoring_func", "sigmoid")) == "sigmoid",
        **moe_parallel_fields(config.tpu_config, config.n_routed_experts),
    )


def _layer_types(config) -> List[str]:
    return ["swa" if p == 1 else "full" for p in config.hybrid_layer_pattern]


def _layer_moe(config) -> List[bool]:
    return [bool(f) for f in config.moe_layer_freq]


def build_arch(config: InferenceConfig, **overrides) -> MiMoV2Arch:
    tp = config.tpu_config.tp_degree
    prf = float(config.partial_rotary_factor)
    types = _layer_types(config)
    moe = _moe_arch(config)

    def type_arch(kind: str) -> DecoderArch:
        if kind == "swa":
            heads, kv = config.swa_num_attention_heads, config.swa_num_key_value_heads
            hd, vd = config.swa_head_dim, config.swa_v_head_dim
            window = config.sliding_window
        else:
            heads, kv = config.num_attention_heads, config.num_key_value_heads
            hd, vd = config.head_dim, config.v_head_dim
            window = None
        plan = gqa.plan_gqa_sharding(tp, heads, kv)
        return dense.build_arch(
            config,
            num_layers=types.count(kind),
            num_attention_heads=plan.target_heads,
            num_kv_heads=plan.target_kv,
            head_dim=hd,
            v_head_dim=None if vd == hd else vd,
            sliding_window=window,
            rotary_dim=(lambda rd: rd if rd < hd else None)(_rope_dim(hd, prf)),
            moe=moe,
            **overrides,
        )

    # depth walk, splitting segments on (type, ff-kind) boundaries
    uses_moe = _layer_moe(config)
    schedule = []
    counters = {"full": 0, "swa": 0}
    seg_idx = -1
    prev = None
    for i, kind in enumerate(types):
        key = (kind, uses_moe[i])
        lo = counters[kind]
        if key == prev:
            t, a, b, s = schedule[-1]
            schedule[-1] = (t, a, b + 1, s)
        else:
            seg_idx += 1
            schedule.append((kind, lo, lo + 1, seg_idx))
            prev = key
        counters[kind] += 1
    return MiMoV2Arch(
        full=type_arch("full"),
        swa=type_arch("swa"),
        schedule=tuple(schedule),
        swa_theta=float(getattr(config, "swa_rope_theta", 10000.0)),
    )


def build_inv_freq(config: InferenceConfig) -> Dict[str, np.ndarray]:
    prf = float(config.partial_rotary_factor)
    return {
        "full": inv_freq_from_hf_config(
            _rope_dim(config.head_dim, prf), config.rope_theta, None
        ),
        "swa": inv_freq_from_hf_config(
            _rope_dim(config.swa_head_dim, prf),
            getattr(config, "swa_rope_theta", 10000.0),
            None,
        ),
    }


# ---------------------------------------------------------------------------
# Forward — segment walk over two attention types
# ---------------------------------------------------------------------------


def causal_lm_forward(
    arch: MiMoV2Arch,
    inv_freq,
    params: Dict[str, Any],
    cache: Dict[str, jax.Array],
    batch: Dict[str, jax.Array],
    *,
    attend_to_cache: bool,
    kv_window=None,
    policy=None,
    layout=None,
    gather_last_token: bool = True,
    output_logits: bool = False,
    output_all_logits: bool = False,
    on_device_sampling: bool = True,
    do_sample: bool = False,
    global_topk: int = 256,
    deterministic: bool = False,
    **_unused,
):
    import jax.numpy as jnp

    from nxdi_tpu.config import to_jax_dtype
    from nxdi_tpu.kvcache.kv_cache import DEFAULT_KV_LAYOUT
    from nxdi_tpu.models.base import constrain, run_decoder_layers
    from nxdi_tpu.ops import sampling as sampling_ops
    from nxdi_tpu.ops.norms import rms_norm
    from nxdi_tpu.ops.rope import rope_cos_sin
    from nxdi_tpu.parallel.policy import DEFAULT_POLICY

    policy = policy or DEFAULT_POLICY
    layout = layout or DEFAULT_KV_LAYOUT
    t = arch.full
    compute_dtype = to_jax_dtype(t.dtype)
    input_ids = batch["input_ids"]
    position_ids = batch["position_ids"]
    B = input_ids.shape[0]

    hidden = jnp.take(params["embed_tokens"], input_ids, axis=0).astype(compute_dtype)
    hidden = constrain(hidden, policy.hidden)
    cos_full, sin_full = rope_cos_sin(position_ids, np.asarray(inv_freq["full"]))
    cos_swa, sin_swa = rope_cos_sin(position_ids, np.asarray(inv_freq["swa"]))

    caches = {
        "full": (cache["k"], cache["v"]),
        "swa": (cache["k_swa"], cache["v_swa"]),
    }
    # window-sized swa stack: when the swa cache holds fewer slots than the
    # full stack it is a W-slot ring — swa segments then read/write through
    # the ring layout (reference: per-layer window-sized caches,
    # kv_cache_manager.py:195-210); the full stack keeps the primary layout
    layouts = {"full": layout, "swa": layout}
    if cache["k_swa"].shape[3] < cache["k"].shape[3]:
        from nxdi_tpu.kvcache.kv_cache import WindowKVLayout

        layouts["swa"] = WindowKVLayout(
            window=cache["k_swa"].shape[3],
            route_by_seq_id=getattr(layout, "route_by_seq_id", False),
        )
    # full layout-input pass-through: seq_ids (continuous batching),
    # write_positions (spec verify windows), attn_mask, last_token_index
    # (the ring write's keep-mask under right padding — WindowKVLayout.update)
    from nxdi_tpu.models.base import collect_cache_inputs

    cache_inputs = collect_cache_inputs(batch) or None
    seg_new = {"full": {}, "swa": {}}  # type -> {lo: (k, v)}
    for kind, lo, hi, seg_idx in arch.schedule:
        ta = arch.full if kind == "full" else arch.swa
        ck, cv = caches[kind]
        k_sl = jax.lax.slice_in_dim(ck, lo, hi, axis=0)
        v_sl = jax.lax.slice_in_dim(cv, lo, hi, axis=0)
        spec = ta.kv_cache_spec(ck.shape[1], ck.shape[3])
        cs = (cos_full, sin_full) if kind == "full" else (cos_swa, sin_swa)
        hidden, seg_cache = run_decoder_layers(
            ta, params["segments"][seg_idx], hidden, cs[0], cs[1],
            {"k": k_sl, "v": v_sl}, position_ids, spec, attend_to_cache,
            kv_window=kv_window, policy=policy, layout=layouts[kind],
            cache_inputs=cache_inputs,
        )
        seg_new[kind][lo] = seg_cache

    def rebuild(kind):
        parts = [seg_new[kind][lo] for lo in sorted(seg_new[kind])]
        if not parts:
            z = caches[kind]
            return z[0], z[1]
        ks = [p["k"] for p in parts]
        vs = [p["v"] for p in parts]
        cat = lambda xs: xs[0] if len(xs) == 1 else jnp.concatenate(xs, axis=0)  # noqa: E731
        return cat(ks), cat(vs)

    new_cache = {}
    new_cache["k"], new_cache["v"] = rebuild("full")
    new_cache["k_swa"], new_cache["v_swa"] = rebuild("swa")

    hidden = rms_norm(hidden, params["norm"], t.rms_norm_eps)
    lm_head = params.get("lm_head")
    if lm_head is None:
        lm_head = jnp.swapaxes(params["embed_tokens"], 0, 1)
    if gather_last_token and not output_all_logits:
        idx = batch["last_token_index"][:, None, None]
        hidden = jnp.take_along_axis(
            hidden, jnp.broadcast_to(idx, (B, 1, hidden.shape[2])), axis=1
        )
    logits = (hidden @ lm_head.astype(hidden.dtype)).astype(jnp.float32)
    logits = constrain(logits, policy.logits)
    logits = sampling_ops.mask_padded_logits(logits, t.vocab_pad)

    if output_all_logits and gather_last_token:
        # ungathered hidden: the sampler still needs the TRUE last position,
        # not the bucket-padded tail (base.py:1464-1469)
        idx = batch["last_token_index"][:, None, None]
        last_logits = jnp.take_along_axis(
            logits, jnp.broadcast_to(idx, (B, 1, logits.shape[2])), axis=1
        )
    else:
        last_logits = logits

    outputs: Dict[str, jax.Array] = {}
    if on_device_sampling:
        outputs["tokens"] = sampling_ops.sample(
            last_logits[:, -1, :],
            batch["sampling_params"],
            rng=batch.get("rng"),
            do_sample=do_sample,
            global_topk=global_topk,
            deterministic=deterministic,
        )[:, None]
    if output_logits or output_all_logits or not on_device_sampling:
        outputs["logits"] = logits[..., : t.vocab_size - t.vocab_pad]
    return outputs, new_cache


# ---------------------------------------------------------------------------
# Conversion / specs / structs
# ---------------------------------------------------------------------------


def _convert_layer(state_dict, config, arch: MiMoV2Arch, i: int, kind: str, use_moe: bool):
    ta = arch.full if kind == "full" else arch.swa
    tp = config.tpu_config.tp_degree
    if kind == "swa":
        plan = gqa.plan_gqa_sharding(
            tp, config.swa_num_attention_heads, config.swa_num_key_value_heads
        )
    else:
        plan = gqa.plan_gqa_sharding(
            tp, config.num_attention_heads, config.num_key_value_heads
        )
    D = ta.head_dim
    Dv = ta.v_head_dim or D
    dt = dense.np_dtype(ta.dtype)
    cast = lambda x: np.asarray(x, dt)  # noqa: E731
    pre = f"model.layers.{i}."

    def get(name):
        for k in (pre + name, pre.replace("model.", "", 1) + name):
            if k in state_dict:
                return state_dict[k]
        raise KeyError(pre + name)

    layer = {
        "input_layernorm": cast(get("input_layernorm.weight")),
        "post_attention_layernorm": cast(get("post_attention_layernorm.weight")),
        "attn": {
            "q_proj": {"w": cast(gqa.convert_q(get("self_attn.q_proj.weight"), D, plan).T)},
            "k_proj": {"w": cast(gqa.convert_kv(get("self_attn.k_proj.weight"), D, plan).T)},
            "v_proj": {"w": cast(gqa.convert_kv(get("self_attn.v_proj.weight"), Dv, plan).T)},
            "o_proj": {"w": cast(gqa.convert_o(get("self_attn.o_proj.weight"), Dv, plan).T)},
        },
    }
    if use_moe:
        layer["moe"] = convert_hf_experts(
            get,
            cast,
            arch.full.moe.num_experts,
            "mlp.gate.weight",
            lambda j, proj: f"mlp.experts.{j}.{proj}_proj.weight",
        )
    else:
        layer["mlp"] = {
            "gate_proj": {"w": cast(get("mlp.gate_proj.weight").T)},
            "up_proj": {"w": cast(get("mlp.up_proj.weight").T)},
            "down_proj": {"w": cast(get("mlp.down_proj.weight").T)},
        }
    return layer


def convert_hf_state_dict(
    state_dict: Dict[str, np.ndarray], config: InferenceConfig
) -> Dict[str, Any]:
    arch = build_arch(config)
    types = _layer_types(config)
    uses_moe = _layer_moe(config)
    dt = dense.np_dtype(arch.full.dtype)

    # group depth-contiguous layers into the schedule's segments
    segments: List[Any] = []
    bucket: List[Any] = []
    prev = None
    for i, kind in enumerate(types):
        key = (kind, uses_moe[i])
        if prev is not None and key != prev:
            segments.append(dense.tree_stack(bucket))
            bucket = []
        bucket.append(_convert_layer(state_dict, config, arch, i, kind, uses_moe[i]))
        prev = key
    segments.append(dense.tree_stack(bucket))
    assert len(segments) == len({s for (_, _, _, s) in arch.schedule})

    def top(name):
        for k in (f"model.{name}", name):
            if k in state_dict:
                return state_dict[k]
        raise KeyError(name)

    embed = np.asarray(top("embed_tokens.weight"))
    if arch.full.vocab_pad:
        embed = np.concatenate(
            [embed, np.zeros((arch.full.vocab_pad, embed.shape[1]), embed.dtype)]
        )
    params: Dict[str, Any] = {
        "embed_tokens": np.asarray(embed, dt),
        "segments": segments,
        "norm": np.asarray(top("norm.weight"), dt),
    }
    if not arch.full.tie_word_embeddings:
        head = np.asarray(state_dict["lm_head.weight"])
        if arch.full.vocab_pad:
            head = np.concatenate(
                [head, np.zeros((arch.full.vocab_pad, head.shape[1]), head.dtype)]
            )
        params["lm_head"] = np.asarray(head.T, dt)
    return params


def _map_segments(config, per_layer_fn, top_fn):
    """Build the segments-list structure by mapping a per-layer constructor."""
    arch = build_arch(config)
    types = _layer_types(config)
    uses_moe = _layer_moe(config)
    segs, bucket, prev = [], [], None
    for i, kind in enumerate(types):
        key = (kind, uses_moe[i])
        if prev is not None and key != prev:
            segs.append(bucket)
            bucket = []
        bucket.append(per_layer_fn(arch, kind, uses_moe[i]))
        prev = key
    segs.append(bucket)
    return top_fn(arch, segs)


def param_specs(config: InferenceConfig):
    from jax.sharding import PartitionSpec as P

    from nxdi_tpu.models.base import attention_param_specs, mlp_param_specs
    from nxdi_tpu.ops.moe import expert_parallel_specs
    from nxdi_tpu.parallel.layers import REPLICATED, VOCAB_PARALLEL

    def per_layer(arch, kind, use_moe):
        ta = arch.full if kind == "full" else arch.swa
        layer = {
            "input_layernorm": REPLICATED,
            "post_attention_layernorm": REPLICATED,
            "attn": attention_param_specs(ta),
        }
        if use_moe:
            layer["moe"] = expert_parallel_specs(ta.moe)
        else:
            layer["mlp"] = mlp_param_specs(ta)
        return layer

    def top(arch, segs):
        def stack(tree):
            return jax.tree_util.tree_map(
                lambda sp: P(*((None,) + tuple(sp))),
                tree,
                is_leaf=lambda x: isinstance(x, P),
            )

        specs = {
            "embed_tokens": VOCAB_PARALLEL,
            "segments": [stack(s[0]) for s in segs],
            "norm": REPLICATED,
        }
        if not arch.full.tie_word_embeddings:
            from nxdi_tpu.parallel.layers import COLUMN_PARALLEL

            specs["lm_head"] = COLUMN_PARALLEL
        return specs

    return _map_segments(config, per_layer, top)


def param_shape_struct(config: InferenceConfig):
    arch = build_arch(config)
    types = _layer_types(config)
    uses_moe = _layer_moe(config)
    dt = dense.np_dtype(arch.full.dtype)
    H = arch.full.hidden_size

    def s(*shape):
        return jax.ShapeDtypeStruct(shape, dt)

    def layer_struct(kind, use_moe, n):
        ta = arch.full if kind == "full" else arch.swa
        D, Dv = ta.head_dim, ta.v_head_dim or ta.head_dim
        NH, NKV = ta.num_attention_heads, ta.num_kv_heads
        layer = {
            "input_layernorm": s(n, H),
            "post_attention_layernorm": s(n, H),
            "attn": {
                "q_proj": {"w": s(n, H, NH * D)},
                "k_proj": {"w": s(n, H, NKV * D)},
                "v_proj": {"w": s(n, H, NKV * Dv)},
                "o_proj": {"w": s(n, NH * Dv, H)},
            },
        }
        if use_moe:
            m = ta.moe
            layer["moe"] = {
                "router": {"w": s(n, H, m.num_experts)},
                "experts": {
                    "gate_proj": {"w": s(n, m.num_experts, H, m.intermediate_size)},
                    "up_proj": {"w": s(n, m.num_experts, H, m.intermediate_size)},
                    "down_proj": {"w": s(n, m.num_experts, m.intermediate_size, H)},
                },
            }
        else:
            I = config.intermediate_size
            layer["mlp"] = {
                "gate_proj": {"w": s(n, H, I)},
                "up_proj": {"w": s(n, H, I)},
                "down_proj": {"w": s(n, I, H)},
            }
        return layer

    segs, run, prev = [], 0, None
    order = []
    for i, kind in enumerate(types):
        key = (kind, uses_moe[i])
        if prev is not None and key != prev:
            order.append((prev, run))
            run = 0
        run += 1
        prev = key
    order.append((prev, run))
    for (kind, use_moe), n in order:
        segs.append(layer_struct(kind, use_moe, n))

    V = arch.full.vocab_size
    struct = {
        "embed_tokens": s(V, H),
        "segments": segs,
        "norm": s(H),
    }
    if not arch.full.tie_word_embeddings:
        struct["lm_head"] = s(H, V)
    return struct


class MiMoV2ForCausalLM:
    def __new__(cls, *args, **kwargs):
        from nxdi_tpu.models.mimo_v2.application import MiMoV2Application

        return MiMoV2Application(*args, **kwargs)


def __getattr__(name):
    # lazy APPLICATION_CLS: application.py imports this module, so a
    # top-level import back would be circular
    if name == "APPLICATION_CLS":
        from nxdi_tpu.models.mimo_v2.application import MiMoV2Application

        return MiMoV2Application
    raise AttributeError(name)
