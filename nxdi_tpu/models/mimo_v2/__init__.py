from nxdi_tpu.models.mimo_v2 import modeling_mimo_v2  # noqa: F401
