"""MiMo-V2 application — dual-type KV cache (full + sliding-window stacks).

Reference: NeuronMiMoV2ForCausalLM (models/mimo_v2/modeling_mimo_v2.py:1265);
the reference sizes one cache at the max kv-head count across types, here
each type owns a correctly-shaped stack."""

from __future__ import annotations

import jax

from nxdi_tpu.kvcache.kv_cache import kv_cache_partition_spec
from nxdi_tpu.models.mimo_v2 import modeling_mimo_v2 as mv
from nxdi_tpu.runtime.application import TpuModelForCausalLM


class MiMoV2Application(TpuModelForCausalLM):
    def __init__(self, *args, **kwargs):
        kwargs.setdefault("model_family", mv)
        super().__init__(*args, **kwargs)
        tc = self.tpu_config
        for flag, why in (
            (tc.async_mode, "async (device-resident) decode"),
            (tc.is_block_kv_layout, "paged KV layout"),
            (tc.lora_config is not None, "LoRA serving"),
            (tc.enable_fused_speculation or tc.is_medusa,
             "fused/medusa speculative decoding"),
            (getattr(tc, "pp_degree", 1) > 1, "pipeline parallel"),
            (tc.is_prefix_caching or tc.is_chunked_prefill, "prefix/chunked prefill"),
        ):
            if flag:
                raise NotImplementedError(f"mimo_v2 does not support {why} yet")

    def _interleaved_window_split(self, arch=None, family=None, config=None):
        return None  # mimo manages its own dual stacks (k_swa/v_swa)

    def _cache_spec(self, family=None, config=None):
        # the FULL-attention stack always keeps seq_len slots; window_sized_kv
        # shrinks only the swa stack (see _swa_cache_struct)
        arch = mv.build_arch(self.config)
        tc = self.tpu_config
        return arch.kv_cache_spec(
            tc.kv_cache_batch_size + tc.kv_cache_padding_size,
            tc.seq_len,
            quant_dtype=(tc.kv_quant_config.dtype if tc.kv_quant_config else None),
        )

    def _swa_cache_struct(self):
        arch = mv.build_arch(self.config)
        tc = self.tpu_config
        B = tc.kv_cache_batch_size + tc.kv_cache_padding_size
        # window_sized_kv shrinks ONLY the sliding-window stack to a W-slot
        # ring; full-attention layers keep the seq_len stack (reference:
        # per-layer window-sized cache shapes, kv_cache_manager.py:195-210)
        max_len = tc.seq_len
        if getattr(tc, "window_sized_kv", False):
            # window_ring_slots over-provisions by spec_len+1 under linear
            # speculation so rejected-draft writes never clobber live rows
            max_len = min(max_len, tc.window_ring_slots)
        spec = arch.swa.kv_cache_spec(
            B, max_len,
            quant_dtype=(tc.kv_quant_config.dtype if tc.kv_quant_config else None),
        )
        return {
            "k_swa": jax.ShapeDtypeStruct(spec.shape, spec.store_dtype),
            "v_swa": jax.ShapeDtypeStruct(spec.shape_v, spec.store_dtype),
        }

    def _cache_struct(self):
        struct = super()._cache_struct()
        struct.update(self._swa_cache_struct())
        return struct

    def init_cache_host(self):
        import jax.numpy as jnp

        cache = super().init_cache_host()
        for k, s in self._swa_cache_struct().items():
            cache[k] = jnp.zeros(s.shape, s.dtype)
        return cache

    def cache_partition_specs(self):
        specs = dict(kv_cache_partition_spec(self.tpu_config))
        specs["k_swa"] = specs["k"]
        specs["v_swa"] = specs["v"]
        return specs

    def enable_models(self) -> None:
        super().enable_models()
        for w in self.models.values():
            w.forward_fn = mv.causal_lm_forward
            w.forward_kwargs.pop("tensor_capture", None)
            w.forward_kwargs.pop("return_next_inputs", None)
            if w.forward_kwargs.pop("dp_sampling", False):
                raise NotImplementedError(
                    "mimo_v2 does not support dp_sampling yet"
                )
