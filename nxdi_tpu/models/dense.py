"""Shared implementation for dense (non-MoE) decoder families.

The reference gives every family its own ``modeling_*.py`` whose attention/MLP
are thin subclasses of the shared base modules (e.g. models/qwen2/modeling_qwen2.py
~283 LoC over NeuronAttentionBase). Here the analogous sharing is functional:
family modules (llama, qwen2, qwen3, mistral, ...) define an InferenceConfig
subclass and a ``build_arch`` that sets family-specific :class:`DecoderArch`
flags; everything else — checkpoint conversion, rope, param specs — is the
generic code in this module.
"""

from __future__ import annotations

import math
from typing import Any, Dict

import ml_dtypes
import numpy as np

from nxdi_tpu.config import InferenceConfig, dtype_name
from nxdi_tpu.models.base import DecoderArch, decoder_param_specs
from nxdi_tpu.ops.rope import inv_freq_from_hf_config
from nxdi_tpu.parallel import gqa

_NP_DTYPES = {
    "bfloat16": ml_dtypes.bfloat16,
    "float32": np.float32,
    "float16": np.float16,
}


def np_dtype(name: str):
    return _NP_DTYPES[name]


def gqa_plan(config: InferenceConfig) -> gqa.GQAPlan:
    return gqa.plan_gqa_sharding(
        config.tpu_config.tp_degree, config.num_attention_heads, config.num_key_value_heads
    )


def planned_head_counts(config: InferenceConfig):
    """Padded (q_heads, kv_heads) for the configured tp degree (parallel/gqa.py)."""
    plan = gqa_plan(config)
    return plan.target_heads, plan.target_kv


def padded_vocab(config: InferenceConfig):
    tp = config.tpu_config.tp_degree
    padded = math.ceil(config.vocab_size / tp) * tp
    return padded, padded - config.vocab_size


def head_dim_of(config: InferenceConfig) -> int:
    """Explicit head_dim when the HF config carries one (qwen3; some configs
    store an explicit ``None``), else hidden/heads."""
    hd = getattr(config, "head_dim", None)
    return hd if hd is not None else config.hidden_size // config.num_attention_heads


def build_arch(config: InferenceConfig, **overrides) -> DecoderArch:
    """Generic DecoderArch builder. Family modules call this with their
    distinguishing flags as overrides (qk_norm for qwen3, sliding_window for
    mistral, ...)."""
    heads, kv_heads = planned_head_counts(config)
    vocab, vocab_pad = padded_vocab(config)
    kwargs = dict(
        num_layers=config.num_hidden_layers,
        hidden_size=config.hidden_size,
        num_attention_heads=heads,
        num_kv_heads=kv_heads,
        head_dim=head_dim_of(config),
        intermediate_size=config.intermediate_size,
        vocab_size=vocab,
        vocab_pad=vocab_pad,
        rms_norm_eps=config.rms_norm_eps,
        hidden_act=getattr(config, "hidden_act", "silu"),
        attention_bias=getattr(config, "attention_bias", False),
        mlp_bias=getattr(config, "mlp_bias", False),
        tie_word_embeddings=getattr(config, "tie_word_embeddings", False),
        dtype=dtype_name(config.tpu_config.dtype),
        rope_mscale=rope_mscale_from_config(config),
        attn_kernel_enabled=bool(config.tpu_config.attn_kernel_enabled),
        attn_tkg_kernel_enabled=bool(config.tpu_config.attn_tkg_kernel_enabled),
        attn_block_tkg_kernel_enabled=bool(
            config.tpu_config.attn_block_tkg_kernel_enabled
        ),
        fused_qkv=bool(getattr(config.tpu_config, "fused_qkv", False)),
        fused_qkv_tp=(
            int(config.tpu_config.tp_degree)
            if getattr(config.tpu_config, "fused_qkv", False)
            else 1
        ),
        qkv_kernel_enabled=bool(
            getattr(config.tpu_config, "qkv_kernel_enabled", False)
        ),
        mlp_kernel_enabled=bool(
            getattr(config.tpu_config, "mlp_kernel_enabled", False)
        ),
        pp_degree=int(getattr(config.tpu_config, "pp_degree", 1) or 1),
        pp_microbatches=int(getattr(config.tpu_config, "pp_microbatches", 0) or 0),
        act_quant=getattr(config.tpu_config, "activation_quantization_type", None),
        act_clamp=getattr(config.tpu_config, "quantize_clamp_bound", None),
    )
    kwargs.update(overrides)
    return DecoderArch(**kwargs)


def build_inv_freq(config: InferenceConfig) -> np.ndarray:
    return inv_freq_from_hf_config(
        head_dim_of(config),
        getattr(config, "rope_theta", 10000.0),
        getattr(config, "rope_scaling", None),
        max_position_embeddings=getattr(config, "max_position_embeddings", 4096),
    )


def rope_mscale_from_config(config: InferenceConfig) -> float:
    """YaRN attention factor for cos/sin scaling (1.0 for non-yarn ropes)."""
    rs = getattr(config, "rope_scaling", None)
    if rs and rs.get("rope_type", rs.get("type")) == "yarn":
        from nxdi_tpu.ops.rope import yarn_inv_freq

        return yarn_inv_freq(
            head_dim_of(config),
            getattr(config, "rope_theta", 10000.0),
            rs,
            getattr(config, "max_position_embeddings", 4096),
        )[1]
    return 1.0


def fuse_qkv_weights(ws, tp: int) -> np.ndarray:
    """Interleave q/k/v weights (each (H_in, out)) into one fused weight whose
    column-shards are self-contained per tp rank: [rank0: q|k|v | rank1: ...]
    (reference: the fused Wqkv weight, gqa.py:582-599; here the interleave
    replaces the reference's per-rank preshard hook). attention_block's split
    regroups the logical view by rank block (models/base.py)."""
    h_in = ws[0].shape[0]
    outs = [w.shape[1] for w in ws]
    for o in outs:
        if o % tp:
            raise ValueError(
                f"fused_qkv: projection width {o} is not divisible by "
                f"tp_degree {tp} — disable fused_qkv for this model/tp"
            )
    parts = [w.reshape(h_in, tp, o // tp) for w, o in zip(ws, outs)]
    return np.concatenate(parts, axis=-1).reshape(h_in, sum(outs))


def fuse_qkv_biases(bs, tp: int) -> np.ndarray:
    parts = [b.reshape(tp, b.shape[0] // tp) for b in bs]
    return np.concatenate(parts, axis=-1).reshape(-1)


def convert_hf_state_dict(
    state_dict: Dict[str, np.ndarray],
    config: InferenceConfig,
    arch: DecoderArch,
    ff_converter=None,
) -> Dict[str, Any]:
    """HF llama-layout checkpoint -> layer-stacked params pytree.

    Does the reference's preshard-hook work (gqa.py:353 replicate_kv, head and
    vocab padding) once, on host, so device params shard evenly over tp.
    Weights are transposed to (in, out) layout (see parallel/layers.py).
    Covers the whole llama lineage (llama, qwen2 w/ qkv bias, qwen3 w/ q/k
    norms, mistral) — their HF state dicts share key names. MoE families pass
    ``ff_converter(get, has, cast, layer_prefix) -> (key, params)`` to replace
    the dense-MLP conversion per layer (e.g. ("moe", {...})).
    """
    dt = np_dtype(arch.dtype)
    plan = gqa_plan(config)
    if (plan.target_heads, plan.target_kv) != (arch.num_attention_heads, arch.num_kv_heads):
        raise ValueError(
            f"arch head counts ({arch.num_attention_heads}, {arch.num_kv_heads}) do not "
            f"match the GQA plan from config ({plan.target_heads}, {plan.target_kv})"
        )
    D = arch.head_dim

    def get(name):
        for k in (name, f"model.{name}"):
            if k in state_dict:
                return state_dict[k]
        raise KeyError(f"Missing weight {name}; available sample: {list(state_dict)[:8]}")

    def has(name):
        return name in state_dict or f"model.{name}" in state_dict

    def cast(x):
        return np.asarray(x, dtype=dt)

    layers = []
    for i in range(arch.num_layers):
        pre = f"layers.{i}."
        q = gqa.convert_q(get(pre + "self_attn.q_proj.weight"), D, plan)
        k = gqa.convert_kv(get(pre + "self_attn.k_proj.weight"), D, plan)
        v = gqa.convert_kv(get(pre + "self_attn.v_proj.weight"), D, plan)
        o = gqa.convert_o(get(pre + "self_attn.o_proj.weight"), D, plan)
        attn: Dict[str, Any] = {
            "q_proj": {"w": cast(q.T)},
            "k_proj": {"w": cast(k.T)},
            "v_proj": {"w": cast(v.T)},
            "o_proj": {"w": cast(o.T)},
        }
        if arch.attention_bias:
            qb = gqa.convert_q(get(pre + "self_attn.q_proj.bias")[:, None], D, plan)[:, 0]
            kb = gqa.convert_kv(get(pre + "self_attn.k_proj.bias")[:, None], D, plan)[:, 0]
            vb = gqa.convert_kv(get(pre + "self_attn.v_proj.bias")[:, None], D, plan)[:, 0]
            attn["q_proj"]["b"] = cast(qb)
            attn["k_proj"]["b"] = cast(kb)
            attn["v_proj"]["b"] = cast(vb)
        if arch.attention_o_bias:
            attn["o_proj"]["b"] = cast(get(pre + "self_attn.o_proj.bias"))
        if arch.qk_norm:
            attn["q_norm"] = cast(get(pre + "self_attn.q_norm.weight"))
            attn["k_norm"] = cast(get(pre + "self_attn.k_norm.weight"))
        if arch.fused_qkv:
            tp = arch.fused_qkv_tp
            qp, kp, vp = attn.pop("q_proj"), attn.pop("k_proj"), attn.pop("v_proj")
            fused = {"w": fuse_qkv_weights([qp["w"], kp["w"], vp["w"]], tp)}
            if "b" in qp:
                fused["b"] = fuse_qkv_biases([qp["b"], kp["b"], vp["b"]], tp)
            attn["qkv_proj"] = fused
        layer = {
            "input_layernorm": cast(get(pre + "input_layernorm.weight")),
            "post_attention_layernorm": cast(get(pre + "post_attention_layernorm.weight")),
            "attn": attn,
        }
        if ff_converter is not None:
            key, ff = ff_converter(get, has, cast, pre)
            layer[key] = ff
        else:
            mlp = {
                "gate_proj": {"w": cast(get(pre + "mlp.gate_proj.weight").T)},
                "up_proj": {"w": cast(get(pre + "mlp.up_proj.weight").T)},
                "down_proj": {"w": cast(get(pre + "mlp.down_proj.weight").T)},
            }
            if arch.mlp_bias:
                for proj in ("gate_proj", "up_proj", "down_proj"):
                    mlp[proj]["b"] = cast(get(f"{pre}mlp.{proj}.bias"))
            layer["mlp"] = mlp
        layers.append(layer)

    stacked = tree_stack(layers)

    embed = get("embed_tokens.weight")
    if arch.vocab_pad:
        embed = np.concatenate(
            [embed, np.zeros((arch.vocab_pad, embed.shape[1]), dtype=embed.dtype)], axis=0
        )
    params: Dict[str, Any] = {
        "embed_tokens": cast(embed),
        "layers": stacked,
        "norm": cast(get("norm.weight")),
    }
    if not arch.tie_word_embeddings:
        if has("lm_head.weight"):
            head = get("lm_head.weight")
        else:  # some checkpoints tie without the config flag
            head = embed[: config.vocab_size]
        if arch.vocab_pad:
            head = np.concatenate(
                [head, np.zeros((arch.vocab_pad, head.shape[1]), dtype=head.dtype)], axis=0
            )
        params["lm_head"] = cast(head.T)
    return params


def param_shape_struct(config: InferenceConfig, arch: DecoderArch):
    """ShapeDtypeStruct pytree matching :func:`convert_hf_state_dict` output —
    AOT compile needs shapes before weights exist (reference compiles from a
    lazy checkpoint_loader_fn the same way, application_base.py:628)."""
    import jax

    from nxdi_tpu.config import to_jax_dtype
    from nxdi_tpu.ops import moe as moe_ops

    dt = to_jax_dtype(arch.dtype)
    H, KV, D = arch.num_attention_heads, arch.num_kv_heads, arch.head_dim
    hs, inter, V, L = arch.hidden_size, arch.intermediate_size, arch.vocab_size, arch.num_layers

    def s(*shape):
        return jax.ShapeDtypeStruct(shape, dt)

    if arch.fused_qkv:
        T = (H + 2 * KV) * D
        attn = {
            "qkv_proj": {"w": s(L, hs, T)},
            "o_proj": {"w": s(L, H * D, hs)},
        }
        if arch.attention_bias:
            attn["qkv_proj"]["b"] = s(L, T)
    else:
        attn = {
            "q_proj": {"w": s(L, hs, H * D)},
            "k_proj": {"w": s(L, hs, KV * D)},
            "v_proj": {"w": s(L, hs, KV * D)},
            "o_proj": {"w": s(L, H * D, hs)},
        }
        if arch.attention_bias:
            attn["q_proj"]["b"] = s(L, H * D)
            attn["k_proj"]["b"] = s(L, KV * D)
            attn["v_proj"]["b"] = s(L, KV * D)
    if arch.attention_o_bias:
        attn["o_proj"]["b"] = s(L, hs)
    if arch.qk_norm:
        attn["q_norm"] = s(L, D)
        attn["k_norm"] = s(L, D)
    layers = {
        "input_layernorm": s(L, hs),
        "post_attention_layernorm": s(L, hs),
        "attn": attn,
    }
    if arch.moe is not None:
        layers["moe"] = moe_ops.moe_shape_struct(arch.moe, hs, L, dt)
    else:
        mlp = {
            "up_proj": {"w": s(L, hs, inter)},
            "down_proj": {"w": s(L, inter, hs)},
        }
        if arch.gated_mlp:
            mlp["gate_proj"] = {"w": s(L, hs, inter)}
        if arch.mlp_bias:
            if arch.gated_mlp:
                mlp["gate_proj"]["b"] = s(L, inter)
            mlp["up_proj"]["b"] = s(L, inter)
            mlp["down_proj"]["b"] = s(L, hs)
        layers["mlp"] = mlp
    params = {
        "embed_tokens": s(V, hs),
        "layers": layers,
        "norm": s(hs),
    }
    if not arch.tie_word_embeddings:
        params["lm_head"] = s(hs, V)
    return params


def attach_norm_biases(params, input_biases, post_biases, final_bias, dtype):
    """Biased-LayerNorm families (gpt2 lineage, fairseq lineage, falcon,
    persimmon, phi): replace the weight-only block-norm arrays with
    ``{"w","b"}`` dicts (the _norm dict contract, models/base.py) from
    per-layer bias lists + the model-level final-norm bias."""
    params["layers"]["input_layernorm"] = {
        "w": params["layers"]["input_layernorm"],
        "b": np.stack(input_biases).astype(dtype),
    }
    params["layers"]["post_attention_layernorm"] = {
        "w": params["layers"]["post_attention_layernorm"],
        "b": np.stack(post_biases).astype(dtype),
    }
    params["norm"] = {"w": params["norm"], "b": np.asarray(final_bias, dtype=dtype)}
    return params


def biased_layernorm_specs(specs):
    from jax.sharding import PartitionSpec as P

    from nxdi_tpu.parallel.layers import REPLICATED

    for key in ("input_layernorm", "post_attention_layernorm"):
        specs["layers"][key] = {"w": REPLICATED, "b": REPLICATED}
    specs["norm"] = {"w": P(), "b": P()}
    return specs


def biased_layernorm_struct(struct, L, H, jax_dtype):
    import jax

    def s(*shape):
        return jax.ShapeDtypeStruct(shape, jax_dtype)

    for key in ("input_layernorm", "post_attention_layernorm"):
        struct["layers"][key] = {"w": s(L, H), "b": s(L, H)}
    struct["norm"] = {"w": s(H), "b": s(H)}
    return struct


def tree_stack(trees):
    """Stack a list of identical pytrees along a new leading (layer) axis."""
    import jax

    return jax.tree_util.tree_map(lambda *xs: np.stack(xs, axis=0), *trees)


def param_specs_for(arch: DecoderArch):
    return decoder_param_specs(arch)


class DenseInferenceConfig(InferenceConfig):
    """Common hyperparameter surface for the llama lineage."""

    REQUIRED = [
        "hidden_size",
        "num_attention_heads",
        "num_hidden_layers",
        "num_key_value_heads",
        "vocab_size",
        "intermediate_size",
        "rms_norm_eps",
    ]

    def add_derived_config(self):
        super().add_derived_config()
        defaults = {
            "rope_theta": 10000.0,
            "rope_scaling": None,
            "tie_word_embeddings": False,
            "hidden_act": "silu",
            "attention_bias": False,
            "mlp_bias": False,
        }
        for k, v in defaults.items():
            if not hasattr(self, k):
                setattr(self, k, v)
