from nxdi_tpu.models.llava import modeling_llava
