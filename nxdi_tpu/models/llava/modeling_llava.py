"""Llava family — CLIP vision tower + llama language model.

Reference: the image-to-text stack (models/image_to_text_model_base.py,
contrib llava model). The language model is the shared dense decoder; the
vision tower + 2-layer projector live in ops/vision.py. Checkpoints use the
HF llava layout (model.vision_tower.*, model.multi_modal_projector.*,
model.language_model.*, top-level lm_head).
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import numpy as np

from nxdi_tpu.config import InferenceConfig
from nxdi_tpu.models import dense
from nxdi_tpu.ops import vision as vision_ops


class LlavaInferenceConfig(dense.DenseInferenceConfig):
    """HF llava configs nest text/vision configs; promote the text fields to
    the top level (the decoder pipeline reads them there) and keep the vision
    dict for the tower arch."""

    REQUIRED = ["text_config", "vision_config", "image_token_index"]

    def add_derived_config(self):
        from nxdi_tpu.config import promote_text_config

        promote_text_config(self)
        vc = self.vision_config
        if not isinstance(vc, dict):
            self.vision_config = vc.to_dict()
        super().add_derived_config()


from nxdi_tpu.checkpoint import strip_language_model_prefix as _strip_text_prefix


def build_arch(config: InferenceConfig, **overrides):
    return dense.build_arch(config, **overrides)


def build_inv_freq(config: InferenceConfig) -> np.ndarray:
    return dense.build_inv_freq(config)


def _is_pixtral(config: InferenceConfig) -> bool:
    return config.vision_config.get("model_type") == "pixtral"


def build_vision_arch(config: InferenceConfig):
    vc = config.vision_config
    if _is_pixtral(config):
        strategy = getattr(config, "vision_feature_select_strategy", "full")
        if strategy != "full":
            raise NotImplementedError(
                f"pixtral vision supports vision_feature_select_strategy='full' "
                f"only (got {strategy!r}); the CLS-dropping 'default' strategy "
                "belongs to CLIP-style towers"
            )
        fl = getattr(config, "vision_feature_layer", -1)
        return vision_ops.PixtralVisionArch(
            hidden_size=vc["hidden_size"],
            intermediate_size=vc["intermediate_size"],
            num_layers=vc["num_hidden_layers"],
            num_heads=vc["num_attention_heads"],
            image_size=vc["image_size"],
            patch_size=vc["patch_size"],
            num_channels=vc.get("num_channels", 3),
            rope_theta=vc.get("rope_theta", 10000.0),
            rms_norm_eps=vc.get("rms_norm_eps", 1e-5),
            hidden_act=vc.get("hidden_act", "gelu"),
            feature_layer=fl if fl is not None else -1,
            projector_act=getattr(config, "projector_hidden_act", "gelu"),
        )
    return vision_ops.ClipVisionArch(
        hidden_size=vc["hidden_size"],
        intermediate_size=vc["intermediate_size"],
        num_layers=vc["num_hidden_layers"],
        num_heads=vc["num_attention_heads"],
        image_size=vc["image_size"],
        patch_size=vc["patch_size"],
        num_channels=vc.get("num_channels", 3),
        hidden_act=vc.get("hidden_act", "quick_gelu"),
        layer_norm_eps=vc.get("layer_norm_eps", 1e-5),
        feature_layer=getattr(config, "vision_feature_layer", -2),
        drop_cls=getattr(config, "vision_feature_select_strategy", "default") == "default",
        projector_act=getattr(config, "projector_hidden_act", "gelu"),
    )


def num_image_tokens(config: InferenceConfig) -> int:
    return build_vision_arch(config).num_patches


def convert_hf_state_dict(
    state_dict: Dict[str, np.ndarray], config: InferenceConfig
) -> Dict[str, Any]:
    return dense.convert_hf_state_dict(
        _strip_text_prefix(state_dict), config, build_arch(config)
    )


def convert_vision_params(
    state_dict: Dict[str, np.ndarray], config: InferenceConfig
) -> Dict[str, Any]:
    varch = build_vision_arch(config)
    if isinstance(varch, vision_ops.PixtralVisionArch):
        vision = vision_ops.convert_pixtral_vision(state_dict, varch)
    else:
        vision = vision_ops.convert_clip_vision(state_dict, varch)
    return {
        "vision": vision,
        "projector": vision_ops.convert_llava_projector(state_dict),
    }


def encode_images(varch, params: Dict[str, Any], pixel_values):
    if isinstance(varch, vision_ops.PixtralVisionArch):
        feat = vision_ops.pixtral_vision_forward(varch, params["vision"], pixel_values)
    else:
        feat = vision_ops.clip_vision_forward(varch, params["vision"], pixel_values)
    return vision_ops.project_image_features(varch, params["projector"], feat)


def _struct(*shape):
    return jax.ShapeDtypeStruct(shape, np.float32)


def _projector_struct(vision_hidden: int, text_hidden: int) -> Dict[str, Any]:
    s = _struct
    return {
        "linear_1": {"w": s(vision_hidden, text_hidden), "b": s(text_hidden)},
        "linear_2": {"w": s(text_hidden, text_hidden), "b": s(text_hidden)},
    }


def vision_shape_struct(config: InferenceConfig) -> Dict[str, Any]:
    """ShapeDtypeStructs matching convert_vision_params (for AOT compile)."""
    varch = build_vision_arch(config)
    if isinstance(varch, vision_ops.PixtralVisionArch):
        return _pixtral_shape_struct(config, varch)
    Hv, Iv, L = varch.hidden_size, varch.intermediate_size, varch.num_layers
    P2 = varch.num_channels * varch.patch_size ** 2
    s = _struct

    lin = lambda i, o: {"w": s(L, i, o), "b": s(L, o)}  # noqa: E731
    return {
        "vision": {
            "patch_embedding": s(P2, Hv),
            "class_embedding": s(Hv),
            "position_embedding": s(varch.num_patches + 1, Hv),
            "pre_layernorm": {"w": s(Hv), "b": s(Hv)},
            "layers": {
                "attn": {
                    n: lin(Hv, Hv) for n in ("q_proj", "k_proj", "v_proj", "out_proj")
                },
                "ln1": {"w": s(L, Hv), "b": s(L, Hv)},
                "ln2": {"w": s(L, Hv), "b": s(L, Hv)},
                "fc1": lin(Hv, Iv),
                "fc2": lin(Iv, Hv),
            },
        },
        "projector": _projector_struct(Hv, config.hidden_size),
    }


def param_specs(config: InferenceConfig):
    return dense.param_specs_for(build_arch(config))


def param_shape_struct(config: InferenceConfig):
    return dense.param_shape_struct(config, build_arch(config))


def _pixtral_shape_struct(config: InferenceConfig, varch) -> Dict[str, Any]:
    Hv, Iv, L = varch.hidden_size, varch.intermediate_size, varch.num_layers
    P2 = varch.num_channels * varch.patch_size ** 2
    s = _struct

    return {
        "vision": {
            "patch_embedding": s(P2, Hv),
            "ln_pre": s(Hv),
            "rope_table": s(varch.num_patches, Hv // varch.num_heads),
            "layers": {
                "q_proj": s(L, Hv, Hv),
                "k_proj": s(L, Hv, Hv),
                "v_proj": s(L, Hv, Hv),
                "o_proj": s(L, Hv, Hv),
                "attention_norm": s(L, Hv),
                "ffn_norm": s(L, Hv),
                "gate_proj": s(L, Hv, Iv),
                "up_proj": s(L, Hv, Iv),
                "down_proj": s(L, Iv, Hv),
            },
        },
        "projector": _projector_struct(Hv, config.hidden_size),
    }
