"""GPTBigCode (santacoder/starcoder) family — gpt2 layout with MQA.

Reference: contrib/models/gpt_bigcode-santacoder. HF GPTBigCodeForCausalLM
(modeling_gpt_bigcode.py:123-270): ``c_attn`` is a fused nn.Linear (NOT
Conv1D — rows are [H | kv | kv] with ONE kv head when ``multi_query``),
learned ``wpe`` positions (no offset), biased LayerNorms, non-gated
gelu_pytorch_tanh MLP, tied head."""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from nxdi_tpu.config import InferenceConfig
from nxdi_tpu.models import dense
from nxdi_tpu.models.base import DecoderArch
from nxdi_tpu.parallel.layers import REPLICATED


class GPTBigCodeInferenceConfig(dense.DenseInferenceConfig):
    REQUIRED = ["n_embd", "n_head", "n_layer", "vocab_size", "n_positions"]

    def add_derived_config(self):
        self.hidden_size = self.n_embd
        self.num_attention_heads = self.n_head
        self.num_hidden_layers = self.n_layer
        self.num_key_value_heads = 1 if getattr(self, "multi_query", True) else self.n_head
        self.intermediate_size = getattr(self, "n_inner", None) or 4 * self.n_embd
        self.rms_norm_eps = getattr(self, "layer_norm_epsilon", 1e-5)
        self.hidden_act = getattr(self, "activation_function", "gelu_pytorch_tanh")
        self.tie_word_embeddings = True
        super().add_derived_config()


def build_arch(config: InferenceConfig, **overrides) -> DecoderArch:
    kwargs = dict(
        learned_pos_embeds=True,
        no_rope=True,
        layernorm=True,
        gated_mlp=False,
        attention_bias=True,
        attention_o_bias=True,
        mlp_bias=True,
        tie_word_embeddings=True,
        hidden_act=getattr(config, "activation_function", "gelu_pytorch_tanh"),
    )
    kwargs.update(overrides)
    return dense.build_arch(config, **kwargs)


def build_inv_freq(config: InferenceConfig) -> np.ndarray:
    from nxdi_tpu.ops.rope import default_inv_freq

    return default_inv_freq(config.n_embd // config.n_head, 10000.0)


def convert_hf_state_dict(
    state_dict: Dict[str, np.ndarray], config: InferenceConfig
) -> Dict[str, Any]:
    arch = build_arch(config)
    H = config.hidden_size
    D = H // config.num_attention_heads
    kv_dim = config.num_key_value_heads * D

    def src(name):
        for k in (name, f"transformer.{name}"):
            if k in state_dict:
                return np.asarray(state_dict[k])
        raise KeyError(name)

    sd: Dict[str, np.ndarray] = {
        "embed_tokens.weight": src("wte.weight"),
        "norm.weight": src("ln_f.weight"),
    }
    norm_biases: Dict[str, np.ndarray] = {"norm": src("ln_f.bias")}
    for i in range(arch.num_layers):
        pre = f"h.{i}."
        dst = f"layers.{i}."
        ca_w = src(pre + "attn.c_attn.weight")  # ((H + 2*kv), H) out,in
        ca_b = src(pre + "attn.c_attn.bias")
        if getattr(config, "multi_query", True):
            # MQA: flat [q-heads | k | v] row blocks
            qw, kw, vw = ca_w[:H], ca_w[H : H + kv_dim], ca_w[H + kv_dim :]
            qb, kb, vb = ca_b[:H], ca_b[H : H + kv_dim], ca_b[H + kv_dim :]
        else:
            # MHA: HF views rows per-HEAD as [q,k,v] interleave
            heads = config.num_attention_heads
            D = H // heads

            def deint(w):
                t = w.reshape((heads, 3, D) + w.shape[1:])
                return tuple(
                    t[:, j].reshape((heads * D,) + w.shape[1:]) for j in range(3)
                )

            (qw, kw, vw), (qb, kb, vb) = deint(ca_w), deint(ca_b)
        sd[dst + "self_attn.q_proj.weight"] = qw
        sd[dst + "self_attn.k_proj.weight"] = kw
        sd[dst + "self_attn.v_proj.weight"] = vw
        sd[dst + "self_attn.q_proj.bias"] = qb
        sd[dst + "self_attn.k_proj.bias"] = kb
        sd[dst + "self_attn.v_proj.bias"] = vb
        sd[dst + "self_attn.o_proj.weight"] = src(pre + "attn.c_proj.weight")
        sd[dst + "self_attn.o_proj.bias"] = src(pre + "attn.c_proj.bias")
        sd[dst + "mlp.up_proj.weight"] = src(pre + "mlp.c_fc.weight")
        sd[dst + "mlp.up_proj.bias"] = src(pre + "mlp.c_fc.bias")
        sd[dst + "mlp.down_proj.weight"] = src(pre + "mlp.c_proj.weight")
        sd[dst + "mlp.down_proj.bias"] = src(pre + "mlp.c_proj.bias")
        sd[dst + "input_layernorm.weight"] = src(pre + "ln_1.weight")
        sd[dst + "post_attention_layernorm.weight"] = src(pre + "ln_2.weight")
        norm_biases[f"layers.{i}.input"] = src(pre + "ln_1.bias")
        norm_biases[f"layers.{i}.post"] = src(pre + "ln_2.bias")

    def ff(get, has, cast, pre):
        return "mlp", {
            "up_proj": {"w": cast(get(pre + "mlp.up_proj.weight").T),
                        "b": cast(get(pre + "mlp.up_proj.bias"))},
            "down_proj": {"w": cast(get(pre + "mlp.down_proj.weight").T),
                          "b": cast(get(pre + "mlp.down_proj.bias"))},
        }

    params = dense.convert_hf_state_dict(sd, config, arch, ff_converter=ff)
    dt = dense.np_dtype(arch.dtype)
    L = arch.num_layers
    dense.attach_norm_biases(
        params,
        [norm_biases[f"layers.{i}.input"] for i in range(L)],
        [norm_biases[f"layers.{i}.post"] for i in range(L)],
        norm_biases["norm"], dt,
    )
    params["position_embeddings"] = np.asarray(src("wpe.weight"), dtype=dt)
    return params


def param_specs(config: InferenceConfig):
    specs = dense.biased_layernorm_specs(dense.param_specs_for(build_arch(config)))
    specs["position_embeddings"] = REPLICATED
    return specs


def param_shape_struct(config: InferenceConfig):
    import jax

    from nxdi_tpu.config import to_jax_dtype

    arch = build_arch(config)
    dt = to_jax_dtype(arch.dtype)
    struct = dense.biased_layernorm_struct(
        dense.param_shape_struct(config, arch),
        arch.num_layers, arch.hidden_size, dt,
    )
    struct["position_embeddings"] = jax.ShapeDtypeStruct(
        (config.n_positions, arch.hidden_size), dt
    )
    return struct
