"""Shared conversion for the fairseq-descended decoder families (OPT, BioGPT,
XGLM): pre-norm biased LayerNorms (``self_attn_layer_norm`` /
``final_layer_norm``), non-gated ``fc1``/``fc2`` MLP, q/k/v/out projections
with biases, learned-or-sinusoidal ABSOLUTE position embeddings with the
fairseq +2 offset (baked into the table at conversion), optional sqrt(H)
embedding scale, and a model-level final LayerNorm.

Reference analogs: contrib/models/opt-1.3b, biogpt, xglm-564M — each a torch
module graph over the same fairseq decoder layout."""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import numpy as np

from nxdi_tpu.config import InferenceConfig
from nxdi_tpu.models import dense
from nxdi_tpu.models.base import DecoderArch
from nxdi_tpu.parallel.layers import REPLICATED


def build_arch(config: InferenceConfig, **overrides) -> DecoderArch:
    kwargs = dict(
        layernorm=True,
        learned_pos_embeds=True,
        no_rope=True,
        gated_mlp=False,
        attention_bias=True,
        attention_o_bias=True,
        mlp_bias=True,
    )
    kwargs.update(overrides)
    return dense.build_arch(config, **kwargs)


def build_inv_freq(config: InferenceConfig) -> np.ndarray:
    # unused (no_rope) but the pipeline expects a frequency table
    from nxdi_tpu.ops.rope import default_inv_freq

    return default_inv_freq(dense.head_dim_of(config), 10000.0)


def sinusoid_table(num_positions: int, dim: int, padding_idx: Optional[int]) -> np.ndarray:
    """fairseq/tensor2tensor sinusoid (XGLMSinusoidalPositionalEmbedding
    .get_embedding): [sin | cos] halves, zero-padded if odd."""
    half = dim // 2
    freq = np.exp(np.arange(half, dtype=np.float64) * -(np.log(10000.0) / (half - 1)))
    ang = np.arange(num_positions, dtype=np.float64)[:, None] * freq[None, :]
    emb = np.concatenate([np.sin(ang), np.cos(ang)], axis=1).astype(np.float32)
    if dim % 2 == 1:
        emb = np.concatenate([emb, np.zeros((num_positions, 1), np.float32)], axis=1)
    if padding_idx is not None:
        emb[padding_idx] = 0.0
    return emb


def convert_hf_state_dict(
    state_dict: Dict[str, np.ndarray],
    config: InferenceConfig,
    arch: DecoderArch,
    *,
    prefix: str,
    embed_key: str = "embed_tokens.weight",
    pos_table: Optional[Callable[[], np.ndarray]] = None,
    pos_key: str = "embed_positions.weight",
    pos_offset: int = 2,
    final_norm_key: str = "final_layer_norm",
) -> Dict[str, Any]:
    """Normalize the fairseq layout into the dense layout. ``prefix`` is the
    HF submodule path (``model.decoder.`` for OPT, ``biogpt.`` for BioGPT,
    ``model.`` for XGLM). ``pos_table`` generates the position table when it
    is not a checkpoint weight (XGLM's sinusoid buffer)."""
    dt = dense.np_dtype(arch.dtype)
    L = arch.num_layers

    def src(name):
        for k in (prefix + name, name):
            if k in state_dict:
                return np.asarray(state_dict[k])
        raise KeyError(prefix + name)

    sd: Dict[str, np.ndarray] = {
        "embed_tokens.weight": src(embed_key),
        "norm.weight": src(final_norm_key + ".weight"),
    }
    for head_key in ("lm_head.weight", "output_projection.weight"):
        if head_key in state_dict:
            sd["lm_head.weight"] = np.asarray(state_dict[head_key])
            break
    norm_biases: Dict[str, np.ndarray] = {"norm": src(final_norm_key + ".bias")}
    for i in range(L):
        pre = f"layers.{i}."
        for proj in ("q", "k", "v"):
            sd[pre + f"self_attn.{proj}_proj.weight"] = src(pre + f"self_attn.{proj}_proj.weight")
            sd[pre + f"self_attn.{proj}_proj.bias"] = src(pre + f"self_attn.{proj}_proj.bias")
        sd[pre + "self_attn.o_proj.weight"] = src(pre + "self_attn.out_proj.weight")
        sd[pre + "self_attn.o_proj.bias"] = src(pre + "self_attn.out_proj.bias")
        sd[pre + "input_layernorm.weight"] = src(pre + "self_attn_layer_norm.weight")
        sd[pre + "post_attention_layernorm.weight"] = src(pre + "final_layer_norm.weight")
        norm_biases[f"layers.{i}.input"] = src(pre + "self_attn_layer_norm.bias")
        norm_biases[f"layers.{i}.post"] = src(pre + "final_layer_norm.bias")
        sd[pre + "mlp.up_proj.weight"] = src(pre + "fc1.weight")
        sd[pre + "mlp.up_proj.bias"] = src(pre + "fc1.bias")
        sd[pre + "mlp.down_proj.weight"] = src(pre + "fc2.weight")
        sd[pre + "mlp.down_proj.bias"] = src(pre + "fc2.bias")

    def ff(get, has, cast, pre):
        return "mlp", {
            "up_proj": {"w": cast(get(pre + "mlp.up_proj.weight").T),
                        "b": cast(get(pre + "mlp.up_proj.bias"))},
            "down_proj": {"w": cast(get(pre + "mlp.down_proj.weight").T),
                          "b": cast(get(pre + "mlp.down_proj.bias"))},
        }

    params = dense.convert_hf_state_dict(sd, config, arch, ff_converter=ff)
    dense.attach_norm_biases(
        params,
        [norm_biases[f"layers.{i}.input"] for i in range(L)],
        [norm_biases[f"layers.{i}.post"] for i in range(L)],
        norm_biases["norm"], dt,
    )
    if pos_table is not None:
        table = np.asarray(pos_table())
    else:
        table = np.asarray(src(pos_key))
    # fairseq offset: positions are looked up at position_ids + 2 — slice the
    # first two rows off so runtime lookups are plain position_ids
    params["position_embeddings"] = table[pos_offset:].astype(dt)
    return params


def param_specs(arch: DecoderArch):
    specs = dense.biased_layernorm_specs(dense.param_specs_for(arch))
    specs["position_embeddings"] = REPLICATED
    return specs


def param_shape_struct(config: InferenceConfig, arch: DecoderArch, num_positions: int):
    import jax

    from nxdi_tpu.config import to_jax_dtype

    dt = to_jax_dtype(arch.dtype)
    struct = dense.biased_layernorm_struct(
        dense.param_shape_struct(config, arch),
        arch.num_layers, arch.hidden_size, dt,
    )
    struct["position_embeddings"] = jax.ShapeDtypeStruct(
        (num_positions, arch.hidden_size), dt
    )
    return struct
