from nxdi_tpu.models.gpt_oss import modeling_gpt_oss
