"""gpt-oss family — attention sinks, interleaved sliding window, biased MoE.

Reference: models/gpt_oss/modeling_gpt_oss.py (2034 LoC) with the LearnedSink
module (modules/attention/sink.py), interleaved sliding-window KV manager
(modules/kvcache/gpt_oss_kv_cache_manager.py) and MXFP4 layout transforms
(mx_layout_transform.py — MXFP4 is not implemented here yet; bf16/int8/fp8
paths serve the weights).

Architecture traits handled by the shared decoder (models/base.py):
  - learned per-head attention-sink logits joining the softmax and dropping
    their mass (``attention_sink`` + ``attn["sink"]`` params);
  - alternating sliding/full attention layers via the ``use_sliding_window``
    per-layer scan flag (one KV cache sized seq_len; the reference's
    window-sized interleaved caches are a memory optimization to revisit);
  - q/k/v/o projection biases;
  - YaRN rope with the attention factor folded into cos/sin (rope_mscale);
  - MoE: router takes top-k of logits then softmaxes them; experts carry
    biases and the clamped glu (up+1)*gate*sigmoid(1.702*gate) (ops/moe.py
    gptoss_glu).
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from nxdi_tpu.config import InferenceConfig
from nxdi_tpu.models import dense
from nxdi_tpu.models.base import DecoderArch
from nxdi_tpu.ops.moe import MoEArch, moe_parallel_fields
from nxdi_tpu.parallel import gqa
from nxdi_tpu.parallel.layers import REPLICATED

GLU_ALPHA = 1.702
GLU_LIMIT = 7.0


class GptOssInferenceConfig(dense.DenseInferenceConfig):
    REQUIRED = dense.DenseInferenceConfig.REQUIRED + [
        "num_local_experts",
        "num_experts_per_tok",
        "head_dim",
    ]

    def add_derived_config(self):
        super().add_derived_config()
        self.attention_bias = True
        if not hasattr(self, "sliding_window"):
            self.sliding_window = None


def _moe_arch(config: InferenceConfig) -> MoEArch:
    return MoEArch(
        num_experts=config.num_local_experts,
        top_k=config.num_experts_per_tok,
        intermediate_size=config.intermediate_size,
        topk_softmax=True,
        router_bias=True,
        expert_bias=True,
        gptoss_glu=True,
        glu_limit=GLU_LIMIT,
        glu_alpha=GLU_ALPHA,
        **moe_parallel_fields(config.tpu_config, config.num_local_experts),
    )


build_inv_freq = dense.build_inv_freq  # yarn handled generically (ops/rope.py)


def _layer_is_sliding(config: InferenceConfig, i: int) -> bool:
    lt = getattr(config, "layer_types", None)
    if lt:
        return lt[i] == "sliding_attention"
    return i % 2 == 0  # gpt-oss default: even layers sliding


def build_arch(config: InferenceConfig, **overrides) -> DecoderArch:
    # rope_mscale (yarn attention factor) is set by dense.build_arch
    sw = getattr(config, "sliding_window", None)
    kwargs = dict(
        moe=_moe_arch(config),
        attention_sink=True,
        attention_o_bias=True,
        sliding_window=sw,
        # interleaved ring stacks under window_sized_kv (reference:
        # gpt_oss_kv_cache_manager.py interleaved window-sized caches)
        kv_window_pattern=(
            tuple(_layer_is_sliding(config, i) for i in range(config.num_hidden_layers))
            if sw
            else None
        ),
    )
    kwargs.update(overrides)
    return dense.build_arch(config, **kwargs)


def convert_hf_state_dict(
    state_dict: Dict[str, np.ndarray], config: InferenceConfig
) -> Dict[str, Any]:
    arch = build_arch(config)
    E, inter = arch.moe.num_experts, arch.moe.intermediate_size
    plan = dense.gqa_plan(config)

    def get(name):
        for k in (name, f"model.{name}"):
            if k in state_dict:
                return state_dict[k]
        raise KeyError(name)

    def ff(g, has, cast, pre):
        src = pre + "mlp."
        gu = np.asarray(get(src + "experts.gate_up_proj"))  # (E, H, 2I) interleaved
        gub = np.asarray(get(src + "experts.gate_up_proj_bias"))  # (E, 2I)
        return "moe", {
            "router": {
                "w": cast(np.asarray(get(src + "router.weight")).T),
                "b": cast(np.asarray(get(src + "router.bias"))),
            },
            "experts": {
                "gate_proj": {"w": cast(gu[..., ::2]), "b": cast(gub[..., ::2])},
                "up_proj": {"w": cast(gu[..., 1::2]), "b": cast(gub[..., 1::2])},
                "down_proj": {
                    "w": cast(np.asarray(get(src + "experts.down_proj"))),
                    "b": cast(np.asarray(get(src + "experts.down_proj_bias"))),
                },
            },
        }

    params = dense.convert_hf_state_dict(state_dict, config, arch, ff_converter=ff)

    dt = dense.np_dtype(arch.dtype)
    L = arch.num_layers
    sinks = []
    for i in range(L):
        s = np.asarray(get(f"layers.{i}.self_attn.sinks"), dtype=dt)
        # sinks follow the q-head order: apply the same head permutation/pad
        # the q weights get (padded heads' sink value is irrelevant — their
        # o_proj columns are zero)
        sinks.append(gqa.convert_q(s[:, None], 1, plan)[:, 0])
    params["layers"]["attn"]["sink"] = np.stack(sinks)
    params["layers"]["use_sliding_window"] = np.array(
        [_layer_is_sliding(config, i) for i in range(L)], dtype=bool
    )
    return params


def param_specs(config: InferenceConfig):
    specs = dense.param_specs_for(build_arch(config))
    specs["layers"]["attn"]["sink"] = REPLICATED
    specs["layers"]["use_sliding_window"] = REPLICATED
    return specs


def param_shape_struct(config: InferenceConfig):
    import jax
    import jax.numpy as jnp

    from nxdi_tpu.config import to_jax_dtype

    arch = build_arch(config)
    struct = dense.param_shape_struct(config, arch)
    dt = to_jax_dtype(arch.dtype)
    L = arch.num_layers
    struct["layers"]["attn"]["sink"] = jax.ShapeDtypeStruct(
        (L, arch.num_attention_heads), dt
    )
    struct["layers"]["use_sliding_window"] = jax.ShapeDtypeStruct((L,), jnp.bool_)
    return struct
