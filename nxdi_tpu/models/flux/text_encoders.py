"""Flux text encoders — CLIP-L text tower + T5 (v1.1 gated) encoder.

Reference: models/diffusers/flux/clip/modeling_clip.py (601 LoC) and
models/diffusers/flux/t5/modeling_t5.py (903 LoC) — separate TP-sharded
encoder applications whose outputs (CLIP pooled embedding, T5 last hidden
state) are handed to the flux transformer application
(flux/application.py:133-429).

TPU-native design: both encoders are stateless fixed-shape programs under
:class:`~nxdi_tpu.runtime.encoder.EncoderApplication` — per-layer weights are
stacked and the block loop is one ``lax.scan`` (traced once, MXU-tiled by
XLA); TP comes from PartitionSpecs on the stacked weights (column-sharded
q/k/v + fc-in, row-sharded out + fc-out) with GSPMD inserting the collectives,
replacing the reference's ColumnParallelLinear/RowParallelLinear wiring.

Numerics contracts (golden-tested against ``transformers`` CLIPTextModel /
T5EncoderModel in tests/integration/test_flux_text_encoders.py):
  - CLIP: learned position embeddings, pre-LN blocks, quick-gelu MLP, causal
    mask, final LN; pooled output = hidden state at the EOS position
    (argmax-of-ids when eos_token_id == 2, first-eos otherwise — the two HF
    behaviors).
  - T5: RMS layernorm without mean subtraction, NO attention scaling (folded
    into init), shared relative-position bias from block 0, gated-gelu FF,
    no biases anywhere, final RMS norm.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from nxdi_tpu.config import InferenceConfig


class FluxTextConfig(InferenceConfig):
    """Holds BOTH encoder hyperparameter dicts: ``clip`` and ``t5``."""

    REQUIRED = ["clip", "t5"]

    def add_derived_config(self):
        pass


@dataclass(frozen=True)
class ClipTextArch:
    vocab_size: int
    hidden_size: int
    num_layers: int
    num_heads: int
    intermediate_size: int
    max_positions: int
    eos_token_id: int
    layer_norm_eps: float = 1e-5

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads


@dataclass(frozen=True)
class T5Arch:
    vocab_size: int
    d_model: int
    num_layers: int
    num_heads: int
    d_kv: int
    d_ff: int
    rel_buckets: int
    rel_max_distance: int
    layer_norm_eps: float = 1e-6


@dataclass(frozen=True)
class FluxTextArch:
    clip: ClipTextArch
    t5: T5Arch


def build_arch(config: InferenceConfig) -> FluxTextArch:
    c, t = dict(config.clip), dict(config.t5)
    return FluxTextArch(
        clip=ClipTextArch(
            vocab_size=c["vocab_size"],
            hidden_size=c["hidden_size"],
            num_layers=c["num_hidden_layers"],
            num_heads=c["num_attention_heads"],
            intermediate_size=c["intermediate_size"],
            max_positions=c["max_position_embeddings"],
            eos_token_id=c.get("eos_token_id", 2),
            layer_norm_eps=c.get("layer_norm_eps", 1e-5),
        ),
        t5=T5Arch(
            vocab_size=t["vocab_size"],
            d_model=t["d_model"],
            num_layers=t["num_layers"],
            num_heads=t["num_heads"],
            d_kv=t["d_kv"],
            d_ff=t["d_ff"],
            rel_buckets=t.get("relative_attention_num_buckets", 32),
            rel_max_distance=t.get("relative_attention_max_distance", 128),
            layer_norm_eps=t.get("layer_norm_epsilon", 1e-6),
        ),
    )


# ---------------------------------------------------------------------------
# CLIP text encoder
# ---------------------------------------------------------------------------


def _ln(x, w, b, eps):
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, -1, keepdims=True)
    var = jnp.mean((xf - mu) ** 2, -1, keepdims=True)
    return (((xf - mu) * jax.lax.rsqrt(var + eps)) * w + b).astype(x.dtype)


def _quick_gelu(x):
    return x * jax.nn.sigmoid(1.702 * x)


def clip_text_forward(arch: FluxTextArch, params, input_ids):
    """(B, S) int32 -> (last_hidden (B, S, H), pooled (B, H))."""
    a = arch.clip
    B, S = input_ids.shape
    x = params["token_embedding"][input_ids] + params["position_embedding"][None, :S]
    H, D = a.num_heads, a.head_dim
    causal = jnp.tril(jnp.ones((S, S), jnp.bool_))

    def block(x, lp):
        h = _ln(x, lp["ln1"]["w"], lp["ln1"]["b"], a.layer_norm_eps)
        q = (h @ lp["q"]["w"] + lp["q"]["b"]).reshape(B, S, H, D)
        k = (h @ lp["k"]["w"] + lp["k"]["b"]).reshape(B, S, H, D)
        v = (h @ lp["v"]["w"] + lp["v"]["b"]).reshape(B, S, H, D)
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
        s = jnp.where(causal[None, None], s * (D**-0.5), -jnp.inf)
        w = jax.nn.softmax(s, axis=-1).astype(v.dtype)
        attn = jnp.einsum("bhqk,bkhd->bqhd", w, v).reshape(B, S, H * D)
        x = x + attn @ lp["o"]["w"] + lp["o"]["b"]
        h = _ln(x, lp["ln2"]["w"], lp["ln2"]["b"], a.layer_norm_eps)
        x = x + _quick_gelu(h @ lp["fc1"]["w"] + lp["fc1"]["b"]) @ lp["fc2"]["w"] + lp["fc2"]["b"]
        return x, None

    x, _ = jax.lax.scan(block, x, params["layers"])
    x = _ln(x, params["final_ln"]["w"], params["final_ln"]["b"], a.layer_norm_eps)
    # pooled: HF picks argmax(ids) when eos==2 (original CLIP vocab has the
    # eos as the numerically largest special id), first-eos otherwise
    if a.eos_token_id == 2:
        pos = jnp.argmax(input_ids, axis=-1)
    else:
        pos = jnp.argmax((input_ids == a.eos_token_id).astype(jnp.int32), axis=-1)
    pooled = x[jnp.arange(B), pos]
    return x, pooled


# ---------------------------------------------------------------------------
# T5 encoder
# ---------------------------------------------------------------------------


def _t5_rms(x, w, eps):
    xf = x.astype(jnp.float32)
    return (xf * jax.lax.rsqrt(jnp.mean(xf * xf, -1, keepdims=True) + eps)).astype(
        x.dtype
    ) * w


def _t5_rel_bucket(rel_pos, num_buckets, max_distance):
    """Bidirectional bucket map (transformers T5Attention._relative_position_bucket)."""
    nb = num_buckets // 2
    out = jnp.where(rel_pos > 0, nb, 0)
    n = jnp.abs(rel_pos)
    max_exact = nb // 2
    large = max_exact + (
        jnp.log(jnp.maximum(n, 1).astype(jnp.float32) / max_exact)
        / np.log(max_distance / max_exact)
        * (nb - max_exact)
    ).astype(jnp.int32)
    large = jnp.minimum(large, nb - 1)
    return out + jnp.where(n < max_exact, n, large)


def t5_encode(arch: FluxTextArch, params, input_ids):
    """(B, S) int32 -> last hidden state (B, S, d_model)."""
    a = arch.t5
    B, S = input_ids.shape
    x = params["embed_tokens"][input_ids]
    # shared relative position bias from block 0: (1, heads, S, S)
    pos = jnp.arange(S)
    rel = pos[None, :] - pos[:, None]  # memory - query
    bucket = _t5_rel_bucket(rel, a.rel_buckets, a.rel_max_distance)
    bias = params["rel_bias"][bucket]  # (S, S, heads)
    bias = jnp.transpose(bias, (2, 0, 1))[None]

    def block(x, lp):
        h = _t5_rms(x, lp["ln1"], a.layer_norm_eps)
        q = (h @ lp["q"]).reshape(B, S, a.num_heads, a.d_kv)
        k = (h @ lp["k"]).reshape(B, S, a.num_heads, a.d_kv)
        v = (h @ lp["v"]).reshape(B, S, a.num_heads, a.d_kv)
        # T5: no 1/sqrt(d) — the scale is folded into initialization
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
        w = jax.nn.softmax(s + bias, axis=-1).astype(v.dtype)
        attn = jnp.einsum("bhqk,bkhd->bqhd", w, v).reshape(B, S, a.num_heads * a.d_kv)
        x = x + attn @ lp["o"]
        h = _t5_rms(x, lp["ln2"], a.layer_norm_eps)
        gated = jax.nn.gelu(h @ lp["wi_0"], approximate=True) * (h @ lp["wi_1"])
        x = x + gated @ lp["wo"]
        return x, None

    x, _ = jax.lax.scan(block, x, params["layers"])
    return _t5_rms(x, params["final_ln"], a.layer_norm_eps)


# ---------------------------------------------------------------------------
# Family protocol: programs, converter, specs
# ---------------------------------------------------------------------------

ENCODER_PROGRAMS = {
    "clip_text": (clip_text_forward, "clip"),
    "t5_text": (t5_encode, "t5"),
}


def convert_hf_state_dict(state_dict, config):
    """Convert a MERGED HF state dict with ``clip.`` / ``t5.`` key prefixes
    (CLIPTextModel and T5EncoderModel respectively, as the reference loads
    them from the two text-encoder subfolders of a flux checkpoint)."""
    arch = build_arch(config)

    def get(k):
        return np.asarray(state_dict[k])

    def stack(trees):
        return jax.tree_util.tree_map(lambda *xs: np.stack(xs), *trees)

    def clip_layer(i):
        p = f"clip.text_model.encoder.layers.{i}."

        def lin(name):
            return {"w": get(p + name + ".weight").T, "b": get(p + name + ".bias")}

        return {
            "ln1": {"w": get(p + "layer_norm1.weight"), "b": get(p + "layer_norm1.bias")},
            "ln2": {"w": get(p + "layer_norm2.weight"), "b": get(p + "layer_norm2.bias")},
            "q": lin("self_attn.q_proj"),
            "k": lin("self_attn.k_proj"),
            "v": lin("self_attn.v_proj"),
            "o": lin("self_attn.out_proj"),
            "fc1": lin("mlp.fc1"),
            "fc2": lin("mlp.fc2"),
        }

    def t5_layer(i):
        p = f"t5.encoder.block.{i}."
        return {
            "ln1": get(p + "layer.0.layer_norm.weight"),
            "ln2": get(p + "layer.1.layer_norm.weight"),
            "q": get(p + "layer.0.SelfAttention.q.weight").T,
            "k": get(p + "layer.0.SelfAttention.k.weight").T,
            "v": get(p + "layer.0.SelfAttention.v.weight").T,
            "o": get(p + "layer.0.SelfAttention.o.weight").T,
            "wi_0": get(p + "layer.1.DenseReluDense.wi_0.weight").T,
            "wi_1": get(p + "layer.1.DenseReluDense.wi_1.weight").T,
            "wo": get(p + "layer.1.DenseReluDense.wo.weight").T,
        }

    return {
        "clip": {
            "token_embedding": get("clip.text_model.embeddings.token_embedding.weight"),
            "position_embedding": get(
                "clip.text_model.embeddings.position_embedding.weight"
            ),
            "layers": stack([clip_layer(i) for i in range(arch.clip.num_layers)]),
            "final_ln": {
                "w": get("clip.text_model.final_layer_norm.weight"),
                "b": get("clip.text_model.final_layer_norm.bias"),
            },
        },
        "t5": {
            "embed_tokens": get("t5.shared.weight"),
            "rel_bias": get(
                "t5.encoder.block.0.layer.0.SelfAttention.relative_attention_bias.weight"
            ),
            "layers": stack([t5_layer(i) for i in range(arch.t5.num_layers)]),
            "final_ln": get("t5.encoder.final_layer_norm.weight"),
        },
    }


def param_specs(config: InferenceConfig):
    """TP layout (reference: the Column/RowParallel wiring of both encoder
    apps): q/k/v and fc-in column-sharded over the model-parallel axis, out
    and fc-out row-sharded; T5 relative bias sharded over heads."""
    from jax.sharding import PartitionSpec as P

    from nxdi_tpu.parallel.mesh import AXIS_MP

    arch = build_arch(config)
    tp = config.tpu_config.tp_degree

    def clip_specs():
        a = arch.clip
        ok_h = tp > 1 and a.num_heads % tp == 0
        ok_f = tp > 1 and a.intermediate_size % tp == 0

        def col(ok):
            return {"w": P(None, None, AXIS_MP) if ok else P(), "b": P(None, AXIS_MP) if ok else P()}

        def row(ok):
            return {"w": P(None, AXIS_MP, None) if ok else P(), "b": P()}

        ln = {"w": P(), "b": P()}
        return {
            "token_embedding": P(),
            "position_embedding": P(),
            "layers": {
                "ln1": ln, "ln2": ln,
                "q": col(ok_h), "k": col(ok_h), "v": col(ok_h), "o": row(ok_h),
                "fc1": col(ok_f), "fc2": row(ok_f),
            },
            "final_ln": dict(ln),
        }

    def t5_specs():
        a = arch.t5
        ok_h = tp > 1 and a.num_heads % tp == 0
        ok_f = tp > 1 and a.d_ff % tp == 0
        col_h = P(None, None, AXIS_MP) if ok_h else P()
        row_h = P(None, AXIS_MP, None) if ok_h else P()
        return {
            "embed_tokens": P(),
            "rel_bias": P(None, AXIS_MP) if ok_h else P(),
            "layers": {
                "ln1": P(), "ln2": P(),
                "q": col_h, "k": col_h, "v": col_h, "o": row_h,
                "wi_0": P(None, None, AXIS_MP) if ok_f else P(),
                "wi_1": P(None, None, AXIS_MP) if ok_f else P(),
                "wo": P(None, AXIS_MP, None) if ok_f else P(),
            },
            "final_ln": P(),
        }

    return {"clip": clip_specs(), "t5": t5_specs()}


def param_shape_struct(config: InferenceConfig):
    arch = build_arch(config)
    c, t = arch.clip, arch.t5

    def s(*shape):
        return jax.ShapeDtypeStruct(shape, np.float32)

    L = c.num_layers
    lin = lambda i, o: {"w": s(L, i, o), "b": s(L, o)}  # noqa: E731
    ln = lambda: {"w": s(L, c.hidden_size), "b": s(L, c.hidden_size)}  # noqa: E731
    clip = {
        "token_embedding": s(c.vocab_size, c.hidden_size),
        "position_embedding": s(c.max_positions, c.hidden_size),
        "layers": {
            "ln1": ln(), "ln2": ln(),
            "q": lin(c.hidden_size, c.hidden_size),
            "k": lin(c.hidden_size, c.hidden_size),
            "v": lin(c.hidden_size, c.hidden_size),
            "o": lin(c.hidden_size, c.hidden_size),
            "fc1": lin(c.hidden_size, c.intermediate_size),
            "fc2": lin(c.intermediate_size, c.hidden_size),
        },
        "final_ln": {"w": s(c.hidden_size), "b": s(c.hidden_size)},
    }
    Lt, inner = t.num_layers, t.num_heads * t.d_kv
    t5 = {
        "embed_tokens": s(t.vocab_size, t.d_model),
        "rel_bias": s(t.rel_buckets, t.num_heads),
        "layers": {
            "ln1": s(Lt, t.d_model), "ln2": s(Lt, t.d_model),
            "q": s(Lt, t.d_model, inner), "k": s(Lt, t.d_model, inner),
            "v": s(Lt, t.d_model, inner), "o": s(Lt, inner, t.d_model),
            "wi_0": s(Lt, t.d_model, t.d_ff), "wi_1": s(Lt, t.d_model, t.d_ff),
            "wo": s(Lt, t.d_ff, t.d_model),
        },
        "final_ln": s(t.d_model),
    }
    return {"clip": clip, "t5": t5}
