"""Flux (rectified-flow DiT) — diffusion text-to-image pipeline.

Reference: models/diffusers/ (3772 LoC) + flux/application.py:133-429 — a
multi-submodel application orchestrating text encoders, the flux transformer
(double-stream + single-stream DiT blocks), and the VAE decoder, with the
denoising loop on the host.

The ``diffusers`` package is not available in this environment, so there is
no HF golden; per the build plan this module provides the full multi-app
orchestration with handmade numerics checks (tests/integration/test_flux.py):
shape/finiteness/determinism of every submodel, scheduler integration on an
analytically-solvable flow, and end-to-end pipeline execution on random
weights.

Architecture implemented (FluxTransformer2DModel semantics):
  - sinusoidal timestep + guidance embeddings -> MLPs, plus pooled text
    projection, summed into the modulation stream ``temb``;
  - 3-axis rope over (id, y, x) position ids for the joint txt+img sequence;
  - N double-stream blocks: separate img/txt streams with AdaLN-Zero
    modulation, one JOINT attention over the concatenated sequence, per-head
    qk rmsnorm;
  - M single-stream blocks: concatenated stream, parallel attention + MLP
    fused by one output projection, AdaLN modulation;
  - final AdaLN-continuous norm + linear to patch channels.
VAE decoder: conv-in -> mid resnets -> nearest-upsample stages -> groupnorm
silu conv-out, with the scaling/shift factor applied to latents.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from nxdi_tpu.config import InferenceConfig


class FluxInferenceConfig(InferenceConfig):
    REQUIRED = [
        "num_layers", "num_single_layers", "attention_head_dim",
        "num_attention_heads", "joint_attention_dim", "pooled_projection_dim",
        "in_channels",
    ]

    def add_derived_config(self):
        if not hasattr(self, "axes_dims_rope"):
            self.axes_dims_rope = [16, 56, 56]
        if not hasattr(self, "guidance_embeds"):
            self.guidance_embeds = True
        if not hasattr(self, "vae_channels"):
            self.vae_channels = 64
        if not hasattr(self, "vae_latent_channels"):
            self.vae_latent_channels = self.in_channels // 4


@dataclass(frozen=True)
class FluxArch:
    num_layers: int  # double-stream blocks
    num_single_layers: int
    num_heads: int
    head_dim: int
    joint_dim: int  # T5 feature width
    pooled_dim: int  # CLIP pooled width
    in_channels: int  # packed latent patch channels
    axes_dims: Tuple[int, ...]  # rope split per (id, y, x)
    guidance: bool
    vae_channels: int
    vae_latent_channels: int

    @property
    def inner(self) -> int:
        return self.num_heads * self.head_dim


def build_arch(config: InferenceConfig) -> FluxArch:
    return FluxArch(
        num_layers=config.num_layers,
        num_single_layers=config.num_single_layers,
        num_heads=config.num_attention_heads,
        head_dim=config.attention_head_dim,
        joint_dim=config.joint_attention_dim,
        pooled_dim=config.pooled_projection_dim,
        in_channels=config.in_channels,
        axes_dims=tuple(config.axes_dims_rope),
        guidance=bool(config.guidance_embeds),
        vae_channels=config.vae_channels,
        vae_latent_channels=config.vae_latent_channels,
    )


# ---------------------------------------------------------------------------
# Building blocks
# ---------------------------------------------------------------------------


def _sinusoidal(t, dim, max_period=10000.0):
    half = dim // 2
    freqs = jnp.exp(-np.log(max_period) * jnp.arange(half, dtype=jnp.float32) / half)
    args = t.astype(jnp.float32)[:, None] * freqs[None, :]
    return jnp.concatenate([jnp.cos(args), jnp.sin(args)], axis=-1)


def _mlp(p, x, act=jax.nn.silu):
    return act(x @ p["fc1"]["w"] + p["fc1"]["b"]) @ p["fc2"]["w"] + p["fc2"]["b"]


def _rms(x, w, eps=1e-6):
    xf = x.astype(jnp.float32)
    return (xf * jax.lax.rsqrt(jnp.mean(xf * xf, -1, keepdims=True) + eps)).astype(
        x.dtype
    ) * w


def _layer_norm_noaffine(x, eps=1e-6):
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, -1, keepdims=True)
    var = jnp.mean((xf - mu) ** 2, -1, keepdims=True)
    return ((xf - mu) * jax.lax.rsqrt(var + eps)).astype(x.dtype)


def rope_table(arch: FluxArch, ids):
    """(S, sum(axes_dims)/2, 2) cos/sin from 3-axis position ids (S, 3)."""
    comps = []
    for i, d in enumerate(arch.axes_dims):
        freqs = 1.0 / (10000.0 ** (np.arange(0, d, 2, dtype=np.float64) / d))
        ph = np.asarray(ids)[:, i : i + 1].astype(np.float64) * freqs[None]
        comps.append(ph)
    ph = np.concatenate(comps, axis=-1)  # (S, head_dim/2)
    return np.stack([np.cos(ph), np.sin(ph)], axis=-1).astype(np.float32)


def _apply_rope(x, tab):
    # x (B, S, H, D): adjacent-pair rotation with per-position phases
    cos = tab[None, :, None, :, 0]
    sin = tab[None, :, None, :, 1]
    xr = x.astype(jnp.float32).reshape(x.shape[:-1] + (x.shape[-1] // 2, 2))
    a, b = xr[..., 0], xr[..., 1]
    out = jnp.stack([a * cos - b * sin, a * sin + b * cos], axis=-1)
    return out.reshape(x.shape).astype(x.dtype)


def _joint_attention(arch, q, k, v):
    B, S, H, D = q.shape
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
    w = jax.nn.softmax(s * (D ** -0.5), axis=-1).astype(v.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", w, v).reshape(B, S, H * D)


def time_text_embed(arch: FluxArch, p, timestep, guidance, pooled):
    temb = _mlp(p["time"], _sinusoidal(timestep * 1000.0, 256))
    if arch.guidance:
        temb = temb + _mlp(p["guidance"], _sinusoidal(guidance * 1000.0, 256))
    return temb + _mlp(p["text"], pooled)


def _modulation(p, temb, n):
    """AdaLN: silu(temb) @ W -> n chunks of inner width."""
    out = jax.nn.silu(temb) @ p["w"] + p["b"]
    return jnp.split(out[:, None, :], n, axis=-1)


def flux_transformer_forward(
    arch: FluxArch,
    params: Dict[str, Any],
    hidden,  # (B, S_img, in_channels) packed latents
    encoder_hidden,  # (B, S_txt, joint_dim)
    pooled,  # (B, pooled_dim)
    timestep,  # (B,) in [0, 1]
    guidance,  # (B,)
    rope_tab,  # (S_txt + S_img, head_dim/2, 2) from rope_table
):
    H, D = arch.num_heads, arch.head_dim
    S_txt = encoder_hidden.shape[1]
    temb = time_text_embed(arch, params["time_text_embed"], timestep, guidance, pooled)
    img = hidden @ params["x_embedder"]["w"] + params["x_embedder"]["b"]
    txt = encoder_hidden @ params["context_embedder"]["w"] + params["context_embedder"]["b"]
    B, S_img, _ = img.shape

    def double_block(carry, lp):
        img, txt = carry
        # img stream modulation (AdaLN-Zero: shift/scale/gate for attn + mlp)
        i_sh1, i_sc1, i_g1, i_sh2, i_sc2, i_g2 = _modulation(lp["img_mod"], temb, 6)
        t_sh1, t_sc1, t_g1, t_sh2, t_sc2, t_g2 = _modulation(lp["txt_mod"], temb, 6)
        img_n = _layer_norm_noaffine(img) * (1 + i_sc1) + i_sh1
        txt_n = _layer_norm_noaffine(txt) * (1 + t_sc1) + t_sh1

        def qkv(x, p):
            S = x.shape[1]
            q = (x @ p["q"]["w"] + p["q"]["b"]).reshape(B, S, H, D)
            k = (x @ p["k"]["w"] + p["k"]["b"]).reshape(B, S, H, D)
            v = (x @ p["v"]["w"] + p["v"]["b"]).reshape(B, S, H, D)
            return _rms(q, p["q_norm"]), _rms(k, p["k_norm"]), v

        iq, ik, iv = qkv(img_n, lp["img_attn"])
        tq, tk, tv = qkv(txt_n, lp["txt_attn"])
        # joint sequence order: [txt, img] (flux convention)
        q = jnp.concatenate([tq, iq], axis=1)
        k = jnp.concatenate([tk, ik], axis=1)
        v = jnp.concatenate([tv, iv], axis=1)
        q, k = _apply_rope(q, rope_tab), _apply_rope(k, rope_tab)
        attn = _joint_attention(arch, q, k, v)
        t_attn, i_attn = attn[:, :S_txt], attn[:, S_txt:]
        img = img + i_g1 * (i_attn @ lp["img_attn"]["o"]["w"] + lp["img_attn"]["o"]["b"])
        txt = txt + t_g1 * (t_attn @ lp["txt_attn"]["o"]["w"] + lp["txt_attn"]["o"]["b"])

        img_n2 = _layer_norm_noaffine(img) * (1 + i_sc2) + i_sh2
        txt_n2 = _layer_norm_noaffine(txt) * (1 + t_sc2) + t_sh2
        img = img + i_g2 * _mlp(lp["img_mlp"], img_n2, act=lambda x: jax.nn.gelu(x, approximate=True))
        txt = txt + t_g2 * _mlp(lp["txt_mlp"], txt_n2, act=lambda x: jax.nn.gelu(x, approximate=True))
        return (img, txt), None

    (img, txt), _ = jax.lax.scan(double_block, (img, txt), params["double_blocks"])

    x = jnp.concatenate([txt, img], axis=1)  # (B, S, inner)

    def single_block(carry, lp):
        x = carry
        sh, sc, gate = _modulation(lp["mod"], temb, 3)
        xn = _layer_norm_noaffine(x) * (1 + sc) + sh
        S = x.shape[1]
        q = (xn @ lp["q"]["w"] + lp["q"]["b"]).reshape(B, S, H, D)
        k = (xn @ lp["k"]["w"] + lp["k"]["b"]).reshape(B, S, H, D)
        v = (xn @ lp["v"]["w"] + lp["v"]["b"]).reshape(B, S, H, D)
        q, k = _rms(q, lp["q_norm"]), _rms(k, lp["k_norm"])
        q, k = _apply_rope(q, rope_tab), _apply_rope(k, rope_tab)
        attn = _joint_attention(arch, q, k, v)
        mlp = jax.nn.gelu(xn @ lp["mlp_in"]["w"] + lp["mlp_in"]["b"], approximate=True)
        fused = jnp.concatenate([attn, mlp], axis=-1)
        x = x + gate * (fused @ lp["out"]["w"] + lp["out"]["b"])
        return x, None

    x, _ = jax.lax.scan(single_block, x, params["single_blocks"])
    img = x[:, S_txt:]

    sh, sc = _modulation(params["norm_out"], temb, 2)
    img = _layer_norm_noaffine(img) * (1 + sc) + sh
    return img @ params["proj_out"]["w"] + params["proj_out"]["b"]


# ---------------------------------------------------------------------------
# VAE decoder (compact conv decoder; reference: the diffusers VAE app)
# ---------------------------------------------------------------------------


def _conv(p, x):  # x NHWC, w (kh, kw, cin, cout)
    return (
        jax.lax.conv_general_dilated(
            x, p["w"], (1, 1), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC")
        )
        + p["b"]
    )


def _group_norm(x, w, b, groups=8, eps=1e-6):
    B, Hh, Ww, C = x.shape
    xf = x.astype(jnp.float32).reshape(B, Hh, Ww, groups, C // groups)
    mu = jnp.mean(xf, axis=(1, 2, 4), keepdims=True)
    var = jnp.mean((xf - mu) ** 2, axis=(1, 2, 4), keepdims=True)
    xf = (xf - mu) * jax.lax.rsqrt(var + eps)
    return (xf.reshape(B, Hh, Ww, C) * w + b).astype(x.dtype)


def _resnet(p, x):
    h = _conv(p["conv1"], jax.nn.silu(_group_norm(x, p["norm1"]["w"], p["norm1"]["b"])))
    h = _conv(p["conv2"], jax.nn.silu(_group_norm(h, p["norm2"]["w"], p["norm2"]["b"])))
    if "skip" in p:
        x = _conv(p["skip"], x)
    return x + h


def vae_decode(arch: FluxArch, params: Dict[str, Any], latents):
    """(B, h, w, latent_ch) -> (B, 8h, 8w, 3) image in [-1, 1]."""
    p = params
    x = latents / p["scaling_factor"] + p["shift_factor"]
    x = _conv(p["conv_in"], x)
    x = _resnet(p["mid1"], x)
    x = _resnet(p["mid2"], x)
    for i in range(3):  # 3 nearest-neighbor x2 upsample stages -> x8
        up = p[f"up{i}"]
        x = _resnet(up["res"], x)
        B, Hh, Ww, C = x.shape
        x = jax.image.resize(x, (B, Hh * 2, Ww * 2, C), "nearest")
        x = _conv(up["conv"], x)
    x = jax.nn.silu(_group_norm(x, p["norm_out"]["w"], p["norm_out"]["b"]))
    return jnp.tanh(_conv(p["conv_out"], x))


# ---------------------------------------------------------------------------
# Scheduler (rectified flow / Euler, reference: the flux application loop)
# ---------------------------------------------------------------------------


def flow_match_sigmas(num_steps: int, shift: float = 1.0) -> np.ndarray:
    """FlowMatchEuler sigma schedule: t in (1, 0], time-shifted."""
    sigmas = np.linspace(1.0, 1.0 / num_steps, num_steps)
    sigmas = shift * sigmas / (1 + (shift - 1) * sigmas)
    return np.append(sigmas, 0.0).astype(np.float32)


def euler_step(latents, velocity, sigma, sigma_next):
    """x_{t+1} = x_t + (sigma_next - sigma) * v (rectified flow ODE)."""
    return latents + (sigma_next - sigma) * velocity


# ---------------------------------------------------------------------------
# Application
# ---------------------------------------------------------------------------

ENCODER_PROGRAMS = {
    "transformer": (flux_transformer_forward, "transformer"),
    "vae_decoder": (vae_decode, "vae"),
}


def param_specs(config: InferenceConfig):
    from jax.sharding import PartitionSpec as P

    return jax.tree_util.tree_map(lambda _: P(), param_shape_struct(config))


def convert_hf_state_dict(state_dict, config):
    """Convert a diffusers ``FluxTransformer2DModel`` state dict into the
    scanned param tree (reference: the flux application loading the
    transformer subfolder of a flux checkpoint, flux/application.py:133-429).

    Accepts keys with or without a ``transformer.`` prefix. Layout contracts
    encoded here (golden-tested in test_flux.py against a torch restatement
    that consumes this exact layout):
      - ``norm1.linear`` / ``norm1_context.linear`` -> img/txt AdaLN-Zero
        modulation, chunk order (shift, scale, gate) x (attn, mlp) — same as
        ours, no permutation;
      - ``attn.to_{q,k,v}`` + ``attn.norm_q/k`` = img stream,
        ``attn.add_{q,k,v}_proj`` + ``attn.norm_added_q/k`` = txt stream,
        ``attn.to_out.0`` / ``attn.to_add_out`` the two output projections;
      - single blocks fuse [attn | mlp] through one ``proj_out`` (our order);
      - final ``norm_out.linear`` emits (scale, shift) in diffusers'
        AdaLayerNormContinuous — SWAPPED to our (shift, scale) order here.

    VAE weights are NOT converted by this function: the compact VAE decoder
    uses its own layout (see param_shape_struct); supply ``state_dict['vae']``
    as an already-structured tree to pass it through.
    """
    arch = build_arch(config)
    inner = arch.inner

    pref = "transformer." if any(k.startswith("transformer.") for k in state_dict) else ""

    def get(k):
        return np.asarray(state_dict[pref + k])

    def lin(k):
        return {"w": get(k + ".weight").T, "b": get(k + ".bias")}

    def swap_halves(p):
        """(scale, shift) -> (shift, scale) on the output dim."""
        w, b = p["w"], p["b"]
        return {
            "w": np.concatenate([w[:, inner:], w[:, :inner]], axis=1),
            "b": np.concatenate([b[inner:], b[:inner]]),
        }

    def emb_mlp(base):
        return {"fc1": lin(base + ".linear_1"), "fc2": lin(base + ".linear_2")}

    def stack(trees):
        return jax.tree_util.tree_map(lambda *xs: np.stack(xs), *trees)

    def dbl(i):
        p = f"transformer_blocks.{i}."
        return {
            "img_mod": lin(p + "norm1.linear"),
            "txt_mod": lin(p + "norm1_context.linear"),
            "img_attn": {
                "q": lin(p + "attn.to_q"), "k": lin(p + "attn.to_k"),
                "v": lin(p + "attn.to_v"), "o": lin(p + "attn.to_out.0"),
                "q_norm": get(p + "attn.norm_q.weight"),
                "k_norm": get(p + "attn.norm_k.weight"),
            },
            "txt_attn": {
                "q": lin(p + "attn.add_q_proj"), "k": lin(p + "attn.add_k_proj"),
                "v": lin(p + "attn.add_v_proj"), "o": lin(p + "attn.to_add_out"),
                "q_norm": get(p + "attn.norm_added_q.weight"),
                "k_norm": get(p + "attn.norm_added_k.weight"),
            },
            "img_mlp": {"fc1": lin(p + "ff.net.0.proj"), "fc2": lin(p + "ff.net.2")},
            "txt_mlp": {"fc1": lin(p + "ff_context.net.0.proj"),
                        "fc2": lin(p + "ff_context.net.2")},
        }

    def sgl(i):
        p = f"single_transformer_blocks.{i}."
        return {
            "mod": lin(p + "norm.linear"),
            "q": lin(p + "attn.to_q"), "k": lin(p + "attn.to_k"),
            "v": lin(p + "attn.to_v"),
            "q_norm": get(p + "attn.norm_q.weight"),
            "k_norm": get(p + "attn.norm_k.weight"),
            "mlp_in": lin(p + "proj_mlp"),
            "out": lin(p + "proj_out"),
        }

    transformer = {
        "time_text_embed": {
            "time": emb_mlp("time_text_embed.timestep_embedder"),
            "text": emb_mlp("time_text_embed.text_embedder"),
            **(
                {"guidance": emb_mlp("time_text_embed.guidance_embedder")}
                if arch.guidance
                else {}
            ),
        },
        "x_embedder": lin("x_embedder"),
        "context_embedder": lin("context_embedder"),
        "double_blocks": stack([dbl(i) for i in range(arch.num_layers)]),
        "single_blocks": stack([sgl(i) for i in range(arch.num_single_layers)]),
        "norm_out": swap_halves(lin("norm_out.linear")),
        "proj_out": lin("proj_out"),
    }
    out = {"transformer": transformer}
    if "vae" in state_dict:
        out["vae"] = state_dict["vae"]
    return out


def param_shape_struct(config: InferenceConfig):
    arch = build_arch(config)
    inner, D = arch.inner, arch.head_dim
    mlp_dim = 4 * inner

    def s(*shape):
        return jax.ShapeDtypeStruct(shape, np.float32)

    def lin(i, o, n=None):
        pre = (n,) if n is not None else ()
        return {"w": s(*pre, i, o), "b": s(*pre, o)}

    def emb_mlp(i, n=None):
        return {"fc1": lin(i, inner, n), "fc2": lin(inner, inner, n)}

    def attn(n):
        return {
            "q": lin(inner, inner, n), "k": lin(inner, inner, n),
            "v": lin(inner, inner, n), "o": lin(inner, inner, n),
            "q_norm": s(n, D), "k_norm": s(n, D),
        }

    nD, nS = arch.num_layers, arch.num_single_layers
    transformer = {
        "time_text_embed": {
            "time": emb_mlp(256),
            "text": emb_mlp(arch.pooled_dim),
            **({"guidance": emb_mlp(256)} if arch.guidance else {}),
        },
        "x_embedder": lin(arch.in_channels, inner),
        "context_embedder": lin(arch.joint_dim, inner),
        "double_blocks": {
            "img_mod": lin(inner, 6 * inner, nD),
            "txt_mod": lin(inner, 6 * inner, nD),
            "img_attn": attn(nD),
            "txt_attn": attn(nD),
            "img_mlp": {"fc1": lin(inner, mlp_dim, nD), "fc2": lin(mlp_dim, inner, nD)},
            "txt_mlp": {"fc1": lin(inner, mlp_dim, nD), "fc2": lin(mlp_dim, inner, nD)},
        },
        "single_blocks": {
            "mod": lin(inner, 3 * inner, nS),
            "q": lin(inner, inner, nS), "k": lin(inner, inner, nS),
            "v": lin(inner, inner, nS), "q_norm": s(nS, D), "k_norm": s(nS, D),
            "mlp_in": lin(inner, mlp_dim, nS),
            "out": lin(inner + mlp_dim, inner, nS),
        },
        "norm_out": lin(inner, 2 * inner),
        "proj_out": lin(inner, arch.in_channels),
    }
    C = arch.vae_channels
    conv = lambda ci, co: {"w": s(3, 3, ci, co), "b": s(co)}  # noqa: E731
    gn = lambda c: {"w": s(c), "b": s(c)}  # noqa: E731
    res = lambda c: {"norm1": gn(c), "conv1": conv(c, c), "norm2": gn(c), "conv2": conv(c, c)}  # noqa: E731
    vae = {
        "scaling_factor": s(),
        "shift_factor": s(),
        "conv_in": conv(arch.vae_latent_channels, C),
        "mid1": res(C), "mid2": res(C),
        "up0": {"res": res(C), "conv": conv(C, C)},
        "up1": {"res": res(C), "conv": conv(C, C)},
        "up2": {"res": res(C), "conv": conv(C, C)},
        "norm_out": gn(C),
        "conv_out": conv(C, 3),
    }
    return {"transformer": transformer, "vae": vae}


class FluxPipeline:
    """Text-to-image orchestration (reference: flux/application.py:133-429):
    CLIP + T5 text encoders -> host denoising loop over the compiled
    transformer -> VAE decode, each submodel a separately-compiled encoder
    program, mirroring the reference's multi-application pipeline. The
    pipeline also accepts precomputed embeddings directly (the reference's
    embedding hand-off between its text-encoder and transformer apps)."""

    def __init__(self, model_path: str, config, params=None,
                 text_config=None, text_params=None):
        from nxdi_tpu.models.flux import modeling_flux
        from nxdi_tpu.runtime.encoder import EncoderApplication

        self.app = EncoderApplication(model_path, config, model_family=modeling_flux)
        if params is not None:
            from nxdi_tpu.parallel.layers import shard_pytree
            from nxdi_tpu.parallel.mesh import mesh_from_config

            self.app.mesh = mesh_from_config(config.tpu_config)
            self.app.params = shard_pytree(
                params, param_specs(config), self.app.mesh
            )
            self.app.is_loaded = True
        self.arch = self.app.arch
        self.text_app = None
        if text_config is not None:
            from nxdi_tpu.models.flux import text_encoders

            self.text_app = EncoderApplication(
                model_path, text_config, model_family=text_encoders
            )
            if text_params is not None:
                from nxdi_tpu.parallel.layers import shard_pytree
                from nxdi_tpu.parallel.mesh import mesh_from_config

                self.text_app.mesh = mesh_from_config(text_config.tpu_config)
                self.text_app.params = shard_pytree(
                    text_params, text_encoders.param_specs(text_config),
                    self.text_app.mesh,
                )
                self.text_app.is_loaded = True

    def encode_prompt(self, clip_ids, t5_ids):
        """(B, S_clip) + (B, S_t5) token ids -> (prompt_embeds, pooled):
        T5 last hidden state is the transformer's joint text stream, CLIP's
        EOS-pooled state the modulation conditioning (reference: the two
        text-encoder applications feeding the flux transformer)."""
        if self.text_app is None:
            raise ValueError(
                "FluxPipeline built without text_config/text_params; pass "
                "prompt_embeds/pooled_embeds directly or supply the encoders"
            )
        _, pooled = self.text_app.forward("clip_text", np.asarray(clip_ids, np.int32))
        prompt_embeds = self.text_app.forward("t5_text", np.asarray(t5_ids, np.int32))
        return np.asarray(prompt_embeds), np.asarray(pooled)

    def __call__(
        self,
        prompt_embeds=None,  # (B, S_txt, joint_dim)
        pooled_embeds=None,  # (B, pooled_dim)
        height: int = 64,
        width: int = 64,
        num_steps: int = 4,
        guidance_scale: float = 3.5,
        seed: int = 0,
        clip_ids=None,  # (B, S_clip) token ids — runs the CLIP encoder
        t5_ids=None,  # (B, S_t5) token ids — runs the T5 encoder
    ):
        if prompt_embeds is None:
            if clip_ids is None or t5_ids is None:
                raise ValueError(
                    "pass either prompt_embeds+pooled_embeds or clip_ids+t5_ids"
                )
            prompt_embeds, pooled_embeds = self.encode_prompt(clip_ids, t5_ids)
        elif pooled_embeds is None:
            raise ValueError(
                "prompt_embeds requires pooled_embeds (the CLIP conditioning "
                "vector); pass both, or clip_ids+t5_ids to run the encoders"
            )
        arch = self.arch
        B = prompt_embeds.shape[0]
        h, w = height // 16, width // 16  # 8x VAE + 2x2 patch packing
        S_img, S_txt = h * w, prompt_embeds.shape[1]
        rng = np.random.default_rng(seed)
        latents = rng.standard_normal((B, S_img, arch.in_channels)).astype(np.float32)

        txt_ids = np.zeros((S_txt, 3), np.int64)
        img_ids = np.stack(
            [
                np.zeros(S_img),
                np.repeat(np.arange(h), w),
                np.tile(np.arange(w), h),
            ],
            axis=-1,
        )
        tab = rope_table(arch, np.concatenate([txt_ids, img_ids], axis=0))

        sigmas = flow_match_sigmas(num_steps)
        guidance = np.full((B,), guidance_scale, np.float32)
        for i in range(num_steps):
            t = np.full((B,), sigmas[i], np.float32)
            v = self.app.forward(
                "transformer", latents, prompt_embeds, pooled_embeds, t, guidance, tab
            )
            latents = np.asarray(euler_step(latents, np.asarray(v), sigmas[i], sigmas[i + 1]))

        # unpack 2x2 patches -> (B, 2h, 2w, latent_ch) and decode
        lc = arch.vae_latent_channels
        lat = latents.reshape(B, h, w, 2, 2, lc).transpose(0, 1, 3, 2, 4, 5)
        lat = lat.reshape(B, 2 * h, 2 * w, lc)
        return np.asarray(self.app.forward("vae_decoder", lat))
