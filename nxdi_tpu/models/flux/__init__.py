from nxdi_tpu.models.flux import modeling_flux  # noqa: F401
