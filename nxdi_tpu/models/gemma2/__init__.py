from nxdi_tpu.models.gemma2 import modeling_gemma2
