"""Gemma2 family.

Reference scope: the gemma lineage modules tested in models/gemma3 (the
reference's contrib tree covers gemma2). Shares gemma3's machinery
(models/gemma3/modeling_gemma3.py here): (1+w) float32 norms, sandwich
pre/post feed-forward norms, sqrt(H) embedding scale, alternating
sliding/full attention — plus gemma2's distinguishing soft-capping of
attention scores AND final logits (cap * tanh(x / cap)), a single rope theta
for every layer, and query_pre_attn_scalar softmax scaling.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from nxdi_tpu.config import InferenceConfig
from nxdi_tpu.models import dense
from nxdi_tpu.models.base import DecoderArch

build_inv_freq = dense.build_inv_freq  # one theta; no local/global split


class Gemma2InferenceConfig(dense.DenseInferenceConfig):
    REQUIRED = dense.DenseInferenceConfig.REQUIRED + ["head_dim"]

    def add_derived_config(self):
        super().add_derived_config()
        if getattr(self, "hidden_act", None) in (None, "silu"):
            self.hidden_act = getattr(self, "hidden_activation", "gelu_pytorch_tanh")
        defaults = {
            "query_pre_attn_scalar": self.head_dim,
            "sliding_window": None,
            "attn_logit_softcapping": 50.0,
            "final_logit_softcapping": 30.0,
        }
        for k, v in defaults.items():
            if not hasattr(self, k):
                setattr(self, k, v)


def _layer_is_sliding(config: InferenceConfig, i: int) -> bool:
    lt = getattr(config, "layer_types", None)
    if lt:
        return lt[i] == "sliding_attention"
    return i % 2 == 0  # gemma2 default: even layers sliding


def build_arch(config: InferenceConfig, **overrides) -> DecoderArch:
    sw = getattr(config, "sliding_window", None)
    kwargs = dict(
        qk_norm=False,
        gemma_norm=True,
        sandwich_norm=True,
        embed_scale=float(config.hidden_size) ** 0.5,
        sliding_window=sw,
        attention_scale=float(config.query_pre_attn_scalar) ** -0.5,
        attn_logit_softcap=getattr(config, "attn_logit_softcapping", None),
        final_logit_softcap=getattr(config, "final_logit_softcapping", None),
        tie_word_embeddings=getattr(config, "tie_word_embeddings", True),
        # window_sized_kv: full-attention layers stay off the ring
        kv_window_pattern=(
            tuple(_layer_is_sliding(config, i)
                  for i in range(config.num_hidden_layers))
            if sw else None
        ),
    )
    kwargs.update(overrides)
    return dense.build_arch(config, **kwargs)


def convert_hf_state_dict(
    state_dict: Dict[str, np.ndarray], config: InferenceConfig
) -> Dict[str, Any]:
    from nxdi_tpu.models.gemma3.modeling_gemma3 import add_sandwich_params

    arch = build_arch(config)
    params = dense.convert_hf_state_dict(state_dict, config, arch)
    return add_sandwich_params(
        params, state_dict, config, arch, _layer_is_sliding, dual_rope=False
    )


def param_specs(config: InferenceConfig):
    from nxdi_tpu.models.gemma3.modeling_gemma3 import add_sandwich_specs

    specs = dense.param_specs_for(build_arch(config))
    return add_sandwich_specs(specs, dual_rope=False)


def param_shape_struct(config: InferenceConfig):
    from nxdi_tpu.models.gemma3.modeling_gemma3 import add_sandwich_struct

    arch = build_arch(config)
    struct = dense.param_shape_struct(config, arch)
    return add_sandwich_struct(struct, config, arch, dual_rope=False)
