"""Qwen2 / Qwen2.5 family (reference: models/qwen2/modeling_qwen2.py, 283 LoC).

Llama-lineage dense decoder distinguished by QKV projection biases
(``attention_bias=True``) and tied embeddings on the small variants. The HF
state dict shares llama's key layout, so conversion is the generic dense path.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from nxdi_tpu.config import InferenceConfig
from nxdi_tpu.models import dense
from nxdi_tpu.models.base import DecoderArch

build_inv_freq = dense.build_inv_freq


class Qwen2InferenceConfig(dense.DenseInferenceConfig):
    pass


def build_arch(config: InferenceConfig, **overrides) -> DecoderArch:
    # qwen2 always carries q/k/v biases (HF Qwen2Attention)
    return dense.build_arch(config, **{"attention_bias": True, **overrides})


def convert_hf_state_dict(
    state_dict: Dict[str, np.ndarray], config: InferenceConfig
) -> Dict[str, Any]:
    return dense.convert_hf_state_dict(state_dict, config, build_arch(config))


def param_specs(config: InferenceConfig):
    return dense.param_specs_for(build_arch(config))


