"""Pixtral / Mistral3 family — pixtral vision tower + mistral decoder.

Reference: models/pixtral/ (modeling_pixtral.py 400 LoC + modeling_pixtral_vision.py
~640 LoC) — the standalone pixtral image-to-text application the reference
promotes out of contrib (Mistral-Small-3.1 lineage: ``NeuronPixtralForCausalLM``
over ``NeuronPixtralVisionModel`` with the multi-modal projector).

Two HF layouts share this family:
  - ``mistral3`` (Mistral3ForConditionalGeneration): pixtral tower ->
    Mistral3MultiModalProjector = RMSNorm (text eps) -> spatial patch-merger
    (spatial_merge_size^2 unfold + biasless linear) -> linear_1/act/linear_2;
  - llava-layout pixtral (no ``spatial_merge_size``): plain 2-layer llava
    projector (also reachable via the llava family).

The text model is the shared dense decoder (mistral flags). The vision tower
is ops/vision.py ``pixtral_vision_forward`` (2-D rope ViT, no CLS).
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from nxdi_tpu.config import InferenceConfig, promote_text_config
from nxdi_tpu.models import dense
from nxdi_tpu.ops import vision as vision_ops


def __getattr__(name):
    if name == "APPLICATION_CLS":
        from nxdi_tpu.models.image_to_text import ImageToTextForCausalLM

        return ImageToTextForCausalLM
    raise AttributeError(name)


class PixtralInferenceConfig(dense.DenseInferenceConfig):
    REQUIRED = ["text_config", "vision_config", "image_token_index"]

    def add_derived_config(self):
        if not hasattr(self, "image_token_index") and hasattr(self, "image_token_id"):
            # mistral3 spells it image_token_id
            self.image_token_index = self.image_token_id
        promote_text_config(self)
        vc = self.vision_config
        if not isinstance(vc, dict):
            self.vision_config = vc.to_dict()
        super().add_derived_config()


Mistral3InferenceConfig = PixtralInferenceConfig


def build_arch(config: InferenceConfig, **overrides):
    # mistral text model: honor its sliding window when set
    from nxdi_tpu.models.mistral import modeling_mistral

    return modeling_mistral.build_arch(config, **overrides)


def build_inv_freq(config: InferenceConfig) -> np.ndarray:
    return dense.build_inv_freq(config)


from nxdi_tpu.checkpoint import strip_language_model_prefix as _strip_text_prefix


def convert_hf_state_dict(state_dict, config: InferenceConfig):
    return dense.convert_hf_state_dict(
        _strip_text_prefix(state_dict), config, build_arch(config)
    )


def param_specs(config: InferenceConfig):
    return dense.param_specs_for(build_arch(config))


def param_shape_struct(config: InferenceConfig):
    return dense.param_shape_struct(config, build_arch(config))


# -- vision protocol (ImageToTextForCausalLM) --


def _merge_size(config: InferenceConfig) -> int:
    return int(getattr(config, "spatial_merge_size", 1))


def build_vision_arch(config: InferenceConfig):
    vc = config.vision_config
    fl = getattr(config, "vision_feature_layer", -1)
    return vision_ops.PixtralVisionArch(
        hidden_size=vc["hidden_size"],
        intermediate_size=vc["intermediate_size"],
        num_layers=vc["num_hidden_layers"],
        num_heads=vc["num_attention_heads"],
        image_size=vc["image_size"],
        patch_size=vc["patch_size"],
        num_channels=vc.get("num_channels", 3),
        rope_theta=vc.get("rope_theta", 10000.0),
        rms_norm_eps=vc.get("rms_norm_eps", 1e-5),
        hidden_act=vc.get("hidden_act", "silu"),
        feature_layer=fl if fl is not None else -1,
        projector_act=getattr(config, "projector_hidden_act", "gelu"),
        projector_norm_eps=float(getattr(config, "rms_norm_eps", 1e-5)),
    )


def num_image_tokens(config: InferenceConfig) -> int:
    varch = build_vision_arch(config)
    m = _merge_size(config)
    return (varch.grid // m) ** 2


def convert_vision_params(state_dict, config: InferenceConfig):
    varch = build_vision_arch(config)
    vision = vision_ops.convert_pixtral_vision(state_dict, varch)
    if _merge_size(config) == 1:
        return {"vision": vision,
                "projector": vision_ops.convert_llava_projector(state_dict)}

    def get(name, optional=False):
        for k in ("multi_modal_projector." + name,
                  "model.multi_modal_projector." + name):
            if k in state_dict:
                return np.asarray(state_dict[k], dtype=np.float32)
        if optional:
            return None
        raise KeyError(name)

    def lin(name):
        out = {"w": get(name + ".weight").T}
        b = get(name + ".bias", optional=True)
        if b is not None:
            out["b"] = b
        return out

    return {
        "vision": vision,
        "projector": {
            "norm": get("norm.weight"),
            "merging_layer": get("patch_merger.merging_layer.weight").T,
            "linear_1": lin("linear_1"),
            "linear_2": lin("linear_2"),
        },
    }


def encode_images(varch, params: Dict[str, Any], pixel_values):
    """(B, C, H, W) full-resolution square images -> (B, N_merged, text_hidden).

    Mistral3 path (reference: NeuronLlavaMultiModalProjector + patch merger,
    modeling_pixtral_vision.py:194-221): RMSNorm over the tower features,
    spatial_merge_size^2 merge in torch-unfold channel-major order, then the
    two projector linears.
    """
    feat = vision_ops.pixtral_vision_forward(varch, params["vision"], pixel_values)
    p = params["projector"]
    if "merging_layer" not in p:
        return vision_ops.project_image_features(varch, p, feat)
    from nxdi_tpu.ops.norms import rms_norm

    feat = rms_norm(
        feat, p["norm"], varch.projector_norm_eps or varch.rms_norm_eps
    )
    B, N, d = feat.shape
    g = varch.grid
    # merge size is encoded in the merging layer's input width (d * m^2) —
    # the static weight shape, so no extra config threading into the jit
    m = int(round((p["merging_layer"].shape[0] // d) ** 0.5))
    gm = g // m
    # (g, g, d) -> (gm, m, gm, m, d) -> (gm, gm, d, m, m): torch unfold is
    # channel-major (d outer, kernel row, kernel col inner)
    feat = feat.reshape(B, g, g, d).reshape(B, gm, m, gm, m, d)
    feat = jnp.transpose(feat, (0, 1, 3, 5, 2, 4)).reshape(B, gm * gm, d * m * m)
    feat = feat @ p["merging_layer"]
    h = feat @ p["linear_1"]["w"]
    if "b" in p["linear_1"]:
        h = h + p["linear_1"]["b"]
    h = vision_ops.ACTS[varch.projector_act](h)
    h = h @ p["linear_2"]["w"]
    if "b" in p["linear_2"]:
        h = h + p["linear_2"]["b"]
    return h


def vision_shape_struct(config: InferenceConfig) -> Dict[str, Any]:
    from nxdi_tpu.models.llava import modeling_llava

    varch = build_vision_arch(config)
    base = modeling_llava._pixtral_shape_struct(config, varch)
    if _merge_size(config) == 1:
        return base
    Hv = varch.hidden_size
    m = _merge_size(config)
    bias = bool(getattr(config, "multimodal_projector_bias", False))
    s = lambda *shape: jax.ShapeDtypeStruct(shape, np.float32)  # noqa: E731

    def lin(i, o):
        out = {"w": s(i, o)}
        if bias:
            out["b"] = s(o)
        return out

    base["projector"] = {
        "norm": s(Hv),
        "merging_layer": s(Hv * m * m, Hv),
        "linear_1": lin(Hv, config.hidden_size),
        "linear_2": lin(config.hidden_size, config.hidden_size),
    }
    return base
