"""Falcon family — fused query_key_value with three historical layouts,
parallel attention+MLP residual, biased LayerNorms, non-gated gelu MLP.

Reference: contrib/models/falcon-7b. HF FalconForCausalLM
(modeling_falcon.py:186-640):
  - falcon-7b: ``multi_query`` (ONE kv head appended after the query rows),
    ``parallel_attn`` with a SINGLE shared input_layernorm (aliased onto the
    parallel block's MLP slot at conversion);
  - falcon-40b/180b (``new_decoder_architecture``): per-kv-group interleaved
    [gxq | k | v] qkv rows, distinct ``ln_attn``/``ln_mlp`` parallel norms;
  - falcon-rw (neither): per-head [q,k,v] interleave, sequential residual.
ALiBi checkpoints are rejected loudly (rope only)."""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from nxdi_tpu.config import InferenceConfig
from nxdi_tpu.models import dense
from nxdi_tpu.models.base import DecoderArch

build_inv_freq = dense.build_inv_freq


class FalconInferenceConfig(dense.DenseInferenceConfig):
    REQUIRED = ["hidden_size", "num_attention_heads", "num_hidden_layers", "vocab_size"]

    def add_derived_config(self):
        if getattr(self, "new_decoder_architecture", False):
            self.num_key_value_heads = getattr(
                self, "num_kv_heads", self.num_attention_heads
            )
        elif getattr(self, "multi_query", True):
            self.num_key_value_heads = 1
        else:
            self.num_key_value_heads = self.num_attention_heads
        self.intermediate_size = getattr(self, "ffn_hidden_size", None) or (
            4 * self.hidden_size
        )
        self.rms_norm_eps = getattr(self, "layer_norm_epsilon", 1e-5)
        self.hidden_act = getattr(self, "activation", "gelu")
        if not hasattr(self, "tie_word_embeddings"):
            self.tie_word_embeddings = True
        super().add_derived_config()
        if getattr(self, "alibi", False):
            raise NotImplementedError("falcon ALiBi checkpoints are not supported (rope only)")


def _parallel(config) -> bool:
    return bool(getattr(config, "parallel_attn", True)) or bool(
        getattr(config, "new_decoder_architecture", False)
    )


def build_arch(config: InferenceConfig, **overrides) -> DecoderArch:
    bias = bool(getattr(config, "bias", False))
    kwargs = dict(
        layernorm=True,
        gated_mlp=False,
        parallel_block=_parallel(config),
        attention_bias=bias,
        attention_o_bias=bias,
        mlp_bias=bias,
        hidden_act=getattr(config, "activation", "gelu"),
        tie_word_embeddings=bool(getattr(config, "tie_word_embeddings", True)),
    )
    kwargs.update(overrides)
    return dense.build_arch(config, **kwargs)


def _split_qkv(w: np.ndarray, config, D: int):
    """HF fused query_key_value rows -> (q, k, v) in HF (out, in) layout.
    Mirrors FalconAttention._split_heads (modeling_falcon.py:229-258)."""
    heads = config.num_attention_heads
    if getattr(config, "new_decoder_architecture", False):
        kv = config.num_key_value_heads
        g = heads // kv
        blocks = w.reshape(kv, g + 2, D, -1) if w.ndim == 2 else w.reshape(kv, g + 2, D)
        q = blocks[:, :g].reshape((heads * D,) + w.shape[1:])
        k = blocks[:, g].reshape((kv * D,) + w.shape[1:])
        v = blocks[:, g + 1].reshape((kv * D,) + w.shape[1:])
    elif getattr(config, "multi_query", True):
        q = w[: heads * D]
        k = w[heads * D : (heads + 1) * D]
        v = w[(heads + 1) * D :]
    else:
        t = w.reshape((heads, 3, D) + w.shape[1:])
        q = t[:, 0].reshape((heads * D,) + w.shape[1:])
        k = t[:, 1].reshape((heads * D,) + w.shape[1:])
        v = t[:, 2].reshape((heads * D,) + w.shape[1:])
    return q, k, v


def convert_hf_state_dict(
    state_dict: Dict[str, np.ndarray], config: InferenceConfig
) -> Dict[str, Any]:
    arch = build_arch(config)
    D = config.hidden_size // config.num_attention_heads
    two_ln = bool(getattr(config, "new_decoder_architecture", False)) and (
        getattr(config, "num_ln_in_parallel_attn", None) in (None, 2)
    )

    def src(name):
        for k in (name, f"transformer.{name}"):
            if k in state_dict:
                return np.asarray(state_dict[k])
        raise KeyError(name)

    def has(name):
        return name in state_dict or f"transformer.{name}" in state_dict

    sd: Dict[str, np.ndarray] = {
        "embed_tokens.weight": src("word_embeddings.weight"),
        "norm.weight": src("ln_f.weight"),
    }
    if "lm_head.weight" in state_dict:
        sd["lm_head.weight"] = np.asarray(state_dict["lm_head.weight"])
    norm_biases: Dict[str, np.ndarray] = {"norm": src("ln_f.bias")}
    for i in range(arch.num_layers):
        pre = f"h.{i}."
        dst = f"layers.{i}."
        qw, kw, vw = _split_qkv(src(pre + "self_attention.query_key_value.weight"), config, D)
        sd[dst + "self_attn.q_proj.weight"] = qw
        sd[dst + "self_attn.k_proj.weight"] = kw
        sd[dst + "self_attn.v_proj.weight"] = vw
        if arch.attention_bias:
            qb, kb, vb = _split_qkv(src(pre + "self_attention.query_key_value.bias"), config, D)
            sd[dst + "self_attn.q_proj.bias"] = qb
            sd[dst + "self_attn.k_proj.bias"] = kb
            sd[dst + "self_attn.v_proj.bias"] = vb
        sd[dst + "self_attn.o_proj.weight"] = src(pre + "self_attention.dense.weight")
        if arch.attention_o_bias:
            sd[dst + "self_attn.o_proj.bias"] = src(pre + "self_attention.dense.bias")
        sd[dst + "mlp.up_proj.weight"] = src(pre + "mlp.dense_h_to_4h.weight")
        sd[dst + "mlp.down_proj.weight"] = src(pre + "mlp.dense_4h_to_h.weight")
        if arch.mlp_bias:
            sd[dst + "mlp.up_proj.bias"] = src(pre + "mlp.dense_h_to_4h.bias")
            sd[dst + "mlp.down_proj.bias"] = src(pre + "mlp.dense_4h_to_h.bias")
        if two_ln:
            sd[dst + "input_layernorm.weight"] = src(pre + "ln_attn.weight")
            sd[dst + "post_attention_layernorm.weight"] = src(pre + "ln_mlp.weight")
            norm_biases[f"layers.{i}.input"] = src(pre + "ln_attn.bias")
            norm_biases[f"layers.{i}.post"] = src(pre + "ln_mlp.bias")
        else:
            sd[dst + "input_layernorm.weight"] = src(pre + "input_layernorm.weight")
            norm_biases[f"layers.{i}.input"] = src(pre + "input_layernorm.bias")
            if has(pre + "post_attention_layernorm.weight"):  # sequential falcon-rw
                sd[dst + "post_attention_layernorm.weight"] = src(
                    pre + "post_attention_layernorm.weight"
                )
                norm_biases[f"layers.{i}.post"] = src(pre + "post_attention_layernorm.bias")
            else:  # parallel_attn single norm: alias onto the MLP slot
                sd[dst + "post_attention_layernorm.weight"] = sd[dst + "input_layernorm.weight"]
                norm_biases[f"layers.{i}.post"] = norm_biases[f"layers.{i}.input"]

    def ff(get, has_, cast, pre):
        mlp = {
            "up_proj": {"w": cast(get(pre + "mlp.up_proj.weight").T)},
            "down_proj": {"w": cast(get(pre + "mlp.down_proj.weight").T)},
        }
        if arch.mlp_bias:
            mlp["up_proj"]["b"] = cast(get(pre + "mlp.up_proj.bias"))
            mlp["down_proj"]["b"] = cast(get(pre + "mlp.down_proj.bias"))
        return "mlp", mlp

    params = dense.convert_hf_state_dict(sd, config, arch, ff_converter=ff)
    dt = dense.np_dtype(arch.dtype)
    L = arch.num_layers
    return dense.attach_norm_biases(
        params,
        [norm_biases[f"layers.{i}.input"] for i in range(L)],
        [norm_biases[f"layers.{i}.post"] for i in range(L)],
        norm_biases["norm"], dt,
    )


def param_specs(config: InferenceConfig):
    return dense.biased_layernorm_specs(dense.param_specs_for(build_arch(config)))


def param_shape_struct(config: InferenceConfig):
    from nxdi_tpu.config import to_jax_dtype

    arch = build_arch(config)
    return dense.biased_layernorm_struct(
        dense.param_shape_struct(config, arch),
        arch.num_layers, arch.hidden_size, to_jax_dtype(arch.dtype),
    )
