"""Falcon-H1 — PARALLEL attention + Mamba2 (SSD) hybrid with muP multipliers.

Reference: contrib/models/Falcon-H1-0.5B-Instruct (the last distinct-machinery
SSM hybrid of the contrib hub). Every layer runs a full GQA attention branch
AND a Mamba2 mixer branch over the SAME input norm, summing both into the
residual (HF ``FalconH1DecoderLayer``), followed by a gated MLP with scalar
multipliers sprinkled muP-style on embeddings / keys / branch outputs / MLP
gate / logits.

TPU-native mapping (the qwen3_next/lfm2/recurrentgemma recurrent-state
pattern, models/state_routing.py seq-id routing included):
  - ``k``/``v``:  (L, B, KV, S, D) full-length exact-position stacks,
  - ``conv``:     (L, B, conv_dim, K) causal-conv tails over [x|B|C],
  - ``ssm``:      (L, B, Hm, P, N) f32 Mamba2 states.
  - The SSM runs as a SEQUENTIAL ``lax.scan`` over positions in f32 — the
    mathematically-equivalent recurrence of HF's chunked SSD prefill
    (torch_forward, modeling_falcon_h1.py:777-990):
        dt      = softplus(dt_raw + dt_bias)            (B, Hm)
        state   = state * exp(dt * A) + dt * B ⊗ x      (B, Hm, P, N)
        y       = state · C + D * x
  - right padding freezes the recurrence (dt forced to 0 on pad lanes — HF
    instead zeroes padded inputs via apply_mask_to_padding_states and trusts
    left padding) and conv tails keep the last K REAL columns per row.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from nxdi_tpu.config import InferenceConfig, dtype_name
from nxdi_tpu.models import dense
from nxdi_tpu.models.state_routing import put_rows, take_rows
from nxdi_tpu.ops import attention as attn_ops
from nxdi_tpu.ops import sampling as sampling_ops
from nxdi_tpu.ops.norms import rms_norm
from nxdi_tpu.ops.rope import apply_rotary_pos_emb, default_inv_freq, rope_cos_sin
from nxdi_tpu.parallel.layers import REPLICATED
from nxdi_tpu.parallel.mesh import AXIS_MP


@dataclass(frozen=True)
class FalconH1Arch:
    num_layers: int
    hidden_size: int
    intermediate_size: int
    vocab_size: int
    vocab_pad: int
    rms_norm_eps: float
    # attention
    num_attention_heads: int
    num_kv_heads: int
    head_dim: int
    attention_bias: bool
    # mamba2 mixer
    d_ssm: int
    mamba_heads: int  # Hm
    mamba_head_dim: int  # P
    d_state: int  # N
    n_groups: int  # G
    conv_kernel: int  # K
    conv_bias: bool
    proj_bias: bool
    projectors_bias: bool
    mamba_rms_norm: bool
    norm_before_gate: bool
    # muP multipliers
    embedding_multiplier: float
    lm_head_multiplier: float
    key_multiplier: float
    attention_in_multiplier: float
    attention_out_multiplier: float
    ssm_in_multiplier: float
    ssm_out_multiplier: float
    mlp_gate_multiplier: float
    mlp_down_multiplier: float
    ssm_multipliers: Tuple[float, ...] = field(default=(1.0,) * 5)
    tie_word_embeddings: bool = False
    dtype: str = "float32"

    @property
    def conv_dim(self) -> int:
        return self.d_ssm + 2 * self.n_groups * self.d_state

    @property
    def proj_dim(self) -> int:
        return self.d_ssm + self.conv_dim + self.mamba_heads


class FalconH1InferenceConfig(InferenceConfig):
    REQUIRED = [
        "hidden_size",
        "intermediate_size",
        "num_hidden_layers",
        "num_attention_heads",
        "num_key_value_heads",
        "vocab_size",
    ]

    def add_derived_config(self):
        defaults = dict(
            rms_norm_eps=1e-5,
            rope_theta=100000.0,
            attention_bias=False,
            mamba_d_ssm=None,
            mamba_expand=2,
            mamba_n_heads=128,
            mamba_d_head="auto",
            mamba_n_groups=1,
            mamba_d_state=256,
            mamba_d_conv=4,
            mamba_conv_bias=True,
            mamba_proj_bias=False,
            projectors_bias=False,
            mamba_rms_norm=False,
            mamba_norm_before_gate=True,
            embedding_multiplier=1.0,
            lm_head_multiplier=1.0,
            key_multiplier=1.0,
            attention_out_multiplier=1.0,
            attention_in_multiplier=1.0,
            ssm_in_multiplier=1.0,
            ssm_out_multiplier=1.0,
            mlp_multipliers=[1.0, 1.0],
            ssm_multipliers=[1.0] * 5,
            tie_word_embeddings=False,
        )
        for k, v in defaults.items():
            if not hasattr(self, k) or getattr(self, k) is None:
                setattr(self, k, v)
        if not hasattr(self, "head_dim") or self.head_dim is None:
            self.head_dim = self.hidden_size // self.num_attention_heads


def build_arch(config: InferenceConfig, **overrides) -> FalconH1Arch:
    d_ssm = (
        config.mamba_d_ssm
        if config.mamba_d_ssm is not None
        else int(config.mamba_expand * config.hidden_size)
    )
    d_head = config.mamba_d_head
    if d_head == "auto":
        d_head = d_ssm // config.mamba_n_heads
    vocab, vocab_pad = dense.padded_vocab(config)
    kwargs = dict(
        num_layers=config.num_hidden_layers,
        hidden_size=config.hidden_size,
        intermediate_size=config.intermediate_size,
        vocab_size=vocab,
        vocab_pad=vocab_pad,
        rms_norm_eps=config.rms_norm_eps,
        num_attention_heads=config.num_attention_heads,
        num_kv_heads=config.num_key_value_heads,
        head_dim=config.head_dim,
        attention_bias=bool(config.attention_bias),
        d_ssm=d_ssm,
        mamba_heads=config.mamba_n_heads,
        mamba_head_dim=int(d_head),
        d_state=config.mamba_d_state,
        n_groups=config.mamba_n_groups,
        conv_kernel=config.mamba_d_conv,
        conv_bias=bool(config.mamba_conv_bias),
        proj_bias=bool(config.mamba_proj_bias),
        projectors_bias=bool(config.projectors_bias),
        mamba_rms_norm=bool(config.mamba_rms_norm),
        norm_before_gate=bool(config.mamba_norm_before_gate),
        embedding_multiplier=float(config.embedding_multiplier),
        lm_head_multiplier=float(config.lm_head_multiplier),
        key_multiplier=float(config.key_multiplier),
        attention_in_multiplier=float(config.attention_in_multiplier),
        attention_out_multiplier=float(config.attention_out_multiplier),
        ssm_in_multiplier=float(config.ssm_in_multiplier),
        ssm_out_multiplier=float(config.ssm_out_multiplier),
        mlp_gate_multiplier=float(config.mlp_multipliers[0]),
        mlp_down_multiplier=float(config.mlp_multipliers[1]),
        ssm_multipliers=tuple(float(m) for m in config.ssm_multipliers),
        tie_word_embeddings=bool(config.tie_word_embeddings),
        dtype=dtype_name(config.tpu_config.dtype),
    )
    kwargs.update(overrides)
    return FalconH1Arch(**kwargs)


def build_inv_freq(config: InferenceConfig) -> np.ndarray:
    return default_inv_freq(config.head_dim, getattr(config, "rope_theta", 100000.0))


def _mup_vector(arch: FalconH1Arch) -> np.ndarray:
    """The per-section in_proj output multiplier (HF compute_mup_vector,
    modeling_falcon_h1.py:1172): [gate | x | B | C | dt] sections."""
    I, GN, Hm = arch.d_ssm, arch.n_groups * arch.d_state, arch.mamba_heads
    m = np.ones(arch.proj_dim, dtype=np.float32)
    z = arch.ssm_multipliers
    m[:I] *= z[0]
    m[I : 2 * I] *= z[1]
    m[2 * I : 2 * I + GN] *= z[2]
    m[2 * I + GN : 2 * I + 2 * GN] *= z[3]
    m[2 * I + 2 * GN :] *= z[4]
    return m


# ---------------------------------------------------------------------------
# Mamba2 mixer (sequential SSD recurrence)
# ---------------------------------------------------------------------------


def mamba_mixer(arch: FalconH1Arch, lp, x, conv_state, ssm_state, valid, is_decode):
    """HF FalconH1Mixer.torch_forward semantics via the sequential recurrence.

    x: (B, S, H) already input-normed; conv_state (B, conv_dim, K);
    ssm_state (B, Hm, P, N) f32; valid (B, S) bool."""
    B, S, _ = x.shape
    dt_ = x.dtype
    I, GN, Hm = arch.d_ssm, arch.n_groups * arch.d_state, arch.mamba_heads
    P, N, G, K = arch.mamba_head_dim, arch.d_state, arch.n_groups, arch.conv_kernel

    x_in = jnp.where(valid[..., None], x, 0.0) * jnp.asarray(
        arch.ssm_in_multiplier, dt_
    )
    proj = x_in @ lp["in_proj"]
    if arch.proj_bias:
        proj = proj + lp["in_proj_b"]
    proj = proj * lp["mup_vector"].astype(proj.dtype)
    gate = proj[..., :I]
    hbc = proj[..., I : I + arch.conv_dim]
    dt_raw = proj[..., I + arch.conv_dim :]  # (B, S, Hm)

    # causal depthwise conv over [x|B|C]
    hbc = jnp.where(valid[..., None], hbc, 0.0)
    x_ch = jnp.swapaxes(hbc, 1, 2)  # (B, conv_dim, S)
    w = lp["conv1d"]  # (conv_dim, K)
    if is_decode:
        window = jnp.concatenate([conv_state[:, :, 1:], x_ch], axis=-1)
        conv = jnp.sum(window * w[None], axis=-1, keepdims=True)  # (B, C, 1)
        new_conv = window
    else:
        padded = jnp.pad(x_ch, ((0, 0), (0, 0), (K - 1, 0)))
        conv = sum(
            padded[:, :, j : j + S] * w[:, j][None, :, None] for j in range(K)
        )
        # tail = last K REAL columns per row (right padding skipped)
        lti = jnp.sum(valid.astype(jnp.int32), axis=1) - 1
        idx = lti[:, None] - (K - 1) + jnp.arange(K, dtype=jnp.int32)[None, :]
        take = jnp.clip(idx, 0, S - 1)
        gathered = jnp.take_along_axis(
            x_ch, jnp.broadcast_to(take[:, None, :], (B, arch.conv_dim, K)), axis=2
        )
        new_conv = jnp.where((idx >= 0)[:, None, :], gathered, 0.0).astype(
            conv_state.dtype
        )
    if arch.conv_bias:
        conv = conv + lp["conv1d_b"][None, :, None]
    hbc = jax.nn.silu(jnp.swapaxes(conv, 1, 2).astype(jnp.float32)).astype(dt_)
    hbc = jnp.where(valid[..., None], hbc, 0.0)

    xs = hbc[..., :I].reshape(B, S, Hm, P).astype(jnp.float32)
    Bv = hbc[..., I : I + GN].reshape(B, S, G, N).astype(jnp.float32)
    Cv = hbc[..., I + GN :].reshape(B, S, G, N).astype(jnp.float32)
    rep = Hm // G
    Bv = jnp.repeat(Bv, rep, axis=2)  # (B, S, Hm, N)
    Cv = jnp.repeat(Cv, rep, axis=2)

    dt = jax.nn.softplus(dt_raw.astype(jnp.float32) + lp["dt_bias"].astype(jnp.float32))
    # freeze the recurrence on padded positions: no decay, no write
    dt = jnp.where(valid[..., None], dt, 0.0)  # (B, S, Hm)
    A = -jnp.exp(lp["A_log"].astype(jnp.float32))  # (Hm,)
    D = lp["D"].astype(jnp.float32)  # (Hm,)

    def step(state, ts):
        x_t, b_t, c_t, dt_t = ts  # (B,Hm,P), (B,Hm,N), (B,Hm,N), (B,Hm)
        dA = jnp.exp(dt_t * A[None, :])[..., None, None]  # (B,Hm,1,1)
        dBx = dt_t[..., None, None] * b_t[:, :, None, :] * x_t[..., None]
        state = state * dA + dBx
        y_t = jnp.einsum("bhpn,bhn->bhp", state, c_t) + D[None, :, None] * x_t
        return state, y_t

    ts = tuple(
        jnp.swapaxes(t, 0, 1) for t in (xs, Bv, Cv, dt)
    )
    new_ssm, ys = jax.lax.scan(step, ssm_state.astype(jnp.float32), ts)
    y = jnp.swapaxes(ys, 0, 1).reshape(B, S, I)  # (B, S, d_ssm) f32

    gate_f = gate.astype(jnp.float32)
    if arch.mamba_rms_norm:
        if not arch.norm_before_gate:
            y = y * jax.nn.silu(gate_f)
        yg = y.reshape(B, S, G, I // G)
        var = jnp.mean(yg * yg, axis=-1, keepdims=True)
        yg = yg * jax.lax.rsqrt(var + arch.rms_norm_eps)
        y = (yg * lp["norm"].reshape(G, I // G)[None, None]).reshape(B, S, I)
        if arch.norm_before_gate:
            y = y * jax.nn.silu(gate_f)
    else:
        y = y * jax.nn.silu(gate_f)

    out = y.astype(dt_) @ lp["out_proj"]
    if arch.projectors_bias:
        out = out + lp["out_proj_b"]
    return out, new_conv, new_ssm


def attention_layer(arch, lp, x, cos, sin, k_cache, v_cache, position_ids,
                    attend_to_cache):
    B, S, _ = x.shape
    H, KV, D = arch.num_attention_heads, arch.num_kv_heads, arch.head_dim
    q = x @ lp["q_w"]
    k = x @ lp["k_w"]
    v = x @ lp["v_w"]
    if arch.attention_bias:
        q, k, v = q + lp["q_b"], k + lp["k_b"], v + lp["v_b"]
    k = k * jnp.asarray(arch.key_multiplier, k.dtype)
    q = jnp.swapaxes(q.reshape(B, S, H, D), 1, 2)
    k = jnp.swapaxes(k.reshape(B, S, KV, D), 1, 2)
    v = jnp.swapaxes(v.reshape(B, S, KV, D), 1, 2)
    q, k = apply_rotary_pos_emb(q, k, cos, sin)

    pos = position_ids.astype(jnp.int32)
    b_idx = jnp.arange(B, dtype=jnp.int32)[:, None]
    new_k = k_cache.at[b_idx, :, pos].set(
        jnp.swapaxes(k, 1, 2).astype(k_cache.dtype), mode="drop"
    )
    new_v = v_cache.at[b_idx, :, pos].set(
        jnp.swapaxes(v, 1, 2).astype(v_cache.dtype), mode="drop"
    )
    if attend_to_cache:
        W = new_k.shape[2]
        kv_pos = jnp.broadcast_to(jnp.arange(W, dtype=jnp.int32)[None, :], (B, W))
        ctx = attn_ops.attention_with_positions(
            q, new_k.astype(q.dtype), new_v.astype(q.dtype), pos, kv_pos
        )
    else:
        ctx = attn_ops.attention_with_positions(q, k, v, pos, pos)
    ctx = jnp.swapaxes(ctx, 1, 2).reshape(B, S, H * D)
    out = ctx @ lp["o_w"]
    if arch.attention_bias:
        out = out + lp["o_b"]
    return out, new_k, new_v


def falcon_h1_forward(
    arch: FalconH1Arch,
    inv_freq: np.ndarray,
    params: Dict[str, Any],
    cache: Dict[str, jax.Array],
    batch: Dict[str, jax.Array],
    *,
    attend_to_cache: bool,
    kv_window: Optional[int] = None,
    policy=None,
    layout=None,
    gather_last_token: bool = True,
    output_logits: bool = False,
    output_all_logits: bool = False,
    on_device_sampling: bool = True,
    do_sample: bool = False,
    global_topk: int = 256,
    deterministic: bool = False,
    return_next_inputs: bool = False,
    **_unused,
):
    from nxdi_tpu.config import to_jax_dtype

    input_ids = batch["input_ids"]
    position_ids = batch["position_ids"]
    dt = to_jax_dtype(arch.dtype)
    B, S = input_ids.shape

    hidden = jnp.take(params["embed_tokens"], input_ids, axis=0).astype(dt)
    hidden = hidden * jnp.asarray(arch.embedding_multiplier, dt)
    cos, sin = rope_cos_sin(position_ids, np.asarray(inv_freq), dtype=jnp.float32)

    if attend_to_cache:
        valid = jnp.ones((B, S), bool)
    else:
        lti = batch["last_token_index"]
        valid = jnp.arange(S, dtype=jnp.int32)[None, :] <= lti[:, None]

    sids = batch.get("seq_ids")  # continuous batching: row i -> cache line
    new_k, new_v = cache["k"], cache["v"]
    new_conv, new_ssm = cache["conv"], cache["ssm"]
    a_in = jnp.asarray(arch.attention_in_multiplier, dt)
    a_out = jnp.asarray(arch.attention_out_multiplier, dt)
    s_out = jnp.asarray(arch.ssm_out_multiplier, dt)
    for i in range(arch.num_layers):
        lp = params["layers"][i]
        h = rms_norm(hidden, lp["input_layernorm"], arch.rms_norm_eps)
        m_out, c_new, s_new = mamba_mixer(
            arch, lp["mamba"], h,
            take_rows(new_conv[i], sids), take_rows(new_ssm[i], sids),
            valid, attend_to_cache,
        )
        new_conv = put_rows(new_conv, i, c_new, sids)
        new_ssm = put_rows(new_ssm, i, s_new, sids)
        at_out, k_new, v_new = attention_layer(
            arch, lp["attn"], h * a_in, cos, sin,
            take_rows(new_k[i], sids), take_rows(new_v[i], sids),
            position_ids, attend_to_cache,
        )
        new_k = put_rows(new_k, i, k_new, sids)
        new_v = put_rows(new_v, i, v_new, sids)
        hidden = hidden + m_out * s_out + at_out * a_out
        h = rms_norm(hidden, lp["pre_ff_layernorm"], arch.rms_norm_eps)
        ff = (h @ lp["up_w"]) * jax.nn.silu(
            (h @ lp["gate_w"]) * jnp.asarray(arch.mlp_gate_multiplier, dt)
        )
        hidden = hidden + (ff @ lp["down_w"]) * jnp.asarray(
            arch.mlp_down_multiplier, dt
        )

    hidden = rms_norm(hidden, params["norm"], arch.rms_norm_eps)
    lm_head = params.get("lm_head")
    if lm_head is None:
        lm_head = jnp.swapaxes(params["embed_tokens"], 0, 1)
    if gather_last_token and not output_all_logits:
        idx = batch["last_token_index"][:, None, None]
        hidden = jnp.take_along_axis(
            hidden, jnp.broadcast_to(idx, (B, 1, hidden.shape[2])), axis=1
        )
    logits = (hidden @ lm_head.astype(hidden.dtype)).astype(jnp.float32)
    logits = logits * arch.lm_head_multiplier
    logits = sampling_ops.mask_padded_logits(logits, arch.vocab_pad)

    outputs: Dict[str, jax.Array] = {}
    if on_device_sampling:
        tokens = sampling_ops.sample(
            logits[:, -1, :],
            batch["sampling_params"],
            rng=batch.get("rng"),
            do_sample=do_sample,
            global_topk=global_topk,
            deterministic=deterministic,
        )
        outputs["tokens"] = tokens[:, None]
    if output_logits or output_all_logits or not on_device_sampling:
        outputs["logits"] = logits[..., : arch.vocab_size - arch.vocab_pad]
    return outputs, {"k": new_k, "v": new_v, "conv": new_conv, "ssm": new_ssm}


# ---------------------------------------------------------------------------
# Conversion / specs / struct
# ---------------------------------------------------------------------------


def convert_hf_state_dict(state_dict, config: InferenceConfig):
    arch = build_arch(config)
    cast = lambda a: np.asarray(a, dtype=dense.np_dtype(arch.dtype))  # noqa: E731

    def get(name):
        for k in (name, f"model.{name}"):
            if k in state_dict:
                return np.asarray(state_dict[k])
        raise KeyError(name)

    def has(name):
        return name in state_dict or f"model.{name}" in state_dict

    layers = []
    for i in range(arch.num_layers):
        p = f"layers.{i}."
        attn = {
            "q_w": cast(get(p + "self_attn.q_proj.weight").T),
            "k_w": cast(get(p + "self_attn.k_proj.weight").T),
            "v_w": cast(get(p + "self_attn.v_proj.weight").T),
            "o_w": cast(get(p + "self_attn.o_proj.weight").T),
        }
        if arch.attention_bias:
            attn.update(
                q_b=cast(get(p + "self_attn.q_proj.bias")),
                k_b=cast(get(p + "self_attn.k_proj.bias")),
                v_b=cast(get(p + "self_attn.v_proj.bias")),
                o_b=cast(get(p + "self_attn.o_proj.bias")),
            )
        mamba = {
            "in_proj": cast(get(p + "mamba.in_proj.weight").T),
            "conv1d": cast(get(p + "mamba.conv1d.weight")[:, 0, :]),
            "dt_bias": np.asarray(get(p + "mamba.dt_bias"), np.float32),
            "A_log": np.asarray(get(p + "mamba.A_log"), np.float32),
            "D": np.asarray(get(p + "mamba.D"), np.float32),
            "out_proj": cast(get(p + "mamba.out_proj.weight").T),
            "mup_vector": _mup_vector(arch),
        }
        if arch.proj_bias:
            mamba["in_proj_b"] = cast(get(p + "mamba.in_proj.bias"))
        if arch.conv_bias:
            mamba["conv1d_b"] = cast(get(p + "mamba.conv1d.bias"))
        if arch.projectors_bias:
            mamba["out_proj_b"] = cast(get(p + "mamba.out_proj.bias"))
        if arch.mamba_rms_norm:
            mamba["norm"] = cast(get(p + "mamba.norm.weight"))
        layers.append({
            "input_layernorm": cast(get(p + "input_layernorm.weight")),
            "pre_ff_layernorm": cast(get(p + "pre_ff_layernorm.weight")),
            "attn": attn,
            "mamba": mamba,
            "gate_w": cast(get(p + "feed_forward.gate_proj.weight").T),
            "up_w": cast(get(p + "feed_forward.up_proj.weight").T),
            "down_w": cast(get(p + "feed_forward.down_proj.weight").T),
        })
    embed = cast(get("embed_tokens.weight"))
    if arch.vocab_pad:
        embed = np.concatenate(
            [embed, np.zeros((arch.vocab_pad, embed.shape[1]), embed.dtype)], axis=0
        )
    params = {
        "embed_tokens": embed,
        "norm": cast(get("final_layernorm.weight")),
        "layers": layers,
    }
    if not arch.tie_word_embeddings:
        head = (
            cast(np.asarray(state_dict["lm_head.weight"]))
            if "lm_head.weight" in state_dict
            else embed[: config.vocab_size]
        )
        if arch.vocab_pad and head.shape[0] < arch.vocab_size:
            head = np.concatenate(
                [head, np.zeros((arch.vocab_pad, head.shape[1]), head.dtype)], axis=0
            )
        params["lm_head"] = head.T
    return params


def param_specs(config: InferenceConfig):
    from jax.sharding import PartitionSpec as P

    arch = build_arch(config)
    tp = config.tpu_config.tp_degree
    h_ok = tp > 1 and arch.num_attention_heads % tp == 0
    kv_ok = h_ok and arch.num_kv_heads % tp == 0
    i_ok = tp > 1 and arch.intermediate_size % tp == 0
    col = P(None, AXIS_MP)
    row = P(AXIS_MP, None)

    layers = []
    for _ in range(arch.num_layers):
        attn = {
            "q_w": col if h_ok else REPLICATED,
            "k_w": col if kv_ok else REPLICATED,
            "v_w": col if kv_ok else REPLICATED,
            "o_w": row if h_ok else REPLICATED,
        }
        if arch.attention_bias:
            attn.update(
                q_b=P(AXIS_MP) if h_ok else REPLICATED,
                k_b=P(AXIS_MP) if kv_ok else REPLICATED,
                v_b=P(AXIS_MP) if kv_ok else REPLICATED,
                o_b=REPLICATED,
            )
        # the mamba mixer's [gate|x|B|C|dt] sections are interleaved across
        # the in_proj output — stays replicated (like the hybrid families'
        # conv stacks); attention + MLP + embeddings carry the TP scaling
        mamba = {k: REPLICATED for k in (
            "in_proj", "conv1d", "dt_bias", "A_log", "D", "out_proj",
            "mup_vector",
        )}
        if arch.proj_bias:
            mamba["in_proj_b"] = REPLICATED
        if arch.conv_bias:
            mamba["conv1d_b"] = REPLICATED
        if arch.projectors_bias:
            mamba["out_proj_b"] = REPLICATED
        if arch.mamba_rms_norm:
            mamba["norm"] = REPLICATED
        layers.append({
            "input_layernorm": REPLICATED,
            "pre_ff_layernorm": REPLICATED,
            "attn": attn,
            "mamba": mamba,
            "gate_w": col if i_ok else REPLICATED,
            "up_w": col if i_ok else REPLICATED,
            "down_w": row if i_ok else REPLICATED,
        })
    specs = {
        "embed_tokens": P(AXIS_MP, None) if h_ok else REPLICATED,
        "norm": REPLICATED,
        "layers": layers,
    }
    if not arch.tie_word_embeddings:
        specs["lm_head"] = P(None, AXIS_MP) if h_ok else REPLICATED
    return specs


def param_shape_struct(config: InferenceConfig):
    arch = build_arch(config)
    dt = dense.np_dtype(arch.dtype)

    def s(*shape, d=dt):
        return jax.ShapeDtypeStruct(shape, d)

    Hd = arch.hidden_size
    H, KV, D = arch.num_attention_heads, arch.num_kv_heads, arch.head_dim
    layers = []
    for _ in range(arch.num_layers):
        attn = {
            "q_w": s(Hd, H * D),
            "k_w": s(Hd, KV * D),
            "v_w": s(Hd, KV * D),
            "o_w": s(H * D, Hd),
        }
        if arch.attention_bias:
            attn.update(q_b=s(H * D), k_b=s(KV * D), v_b=s(KV * D), o_b=s(Hd))
        mamba = {
            "in_proj": s(Hd, arch.proj_dim),
            "conv1d": s(arch.conv_dim, arch.conv_kernel),
            "dt_bias": s(arch.mamba_heads, d=np.float32),
            "A_log": s(arch.mamba_heads, d=np.float32),
            "D": s(arch.mamba_heads, d=np.float32),
            "out_proj": s(arch.d_ssm, Hd),
            "mup_vector": s(arch.proj_dim, d=np.float32),
        }
        if arch.proj_bias:
            mamba["in_proj_b"] = s(arch.proj_dim)
        if arch.conv_bias:
            mamba["conv1d_b"] = s(arch.conv_dim)
        if arch.projectors_bias:
            mamba["out_proj_b"] = s(Hd)
        if arch.mamba_rms_norm:
            mamba["norm"] = s(arch.d_ssm)
        layers.append({
            "input_layernorm": s(Hd),
            "pre_ff_layernorm": s(Hd),
            "attn": attn,
            "mamba": mamba,
            "gate_w": s(Hd, arch.intermediate_size),
            "up_w": s(Hd, arch.intermediate_size),
            "down_w": s(arch.intermediate_size, Hd),
        })
    struct = {
        "embed_tokens": s(arch.vocab_size, Hd),
        "norm": s(Hd),
        "layers": layers,
    }
    if not arch.tie_word_embeddings:
        struct["lm_head"] = s(Hd, arch.vocab_size)
    return struct


# ---------------------------------------------------------------------------
# Cache + application
# ---------------------------------------------------------------------------


def cache_shapes(arch: FalconH1Arch, batch_size: int, seq_len: int):
    from nxdi_tpu.config import to_jax_dtype

    dt = to_jax_dtype(arch.dtype)
    L = arch.num_layers
    return {
        "k": ((L, batch_size, arch.num_kv_heads, seq_len, arch.head_dim), dt),
        "v": ((L, batch_size, arch.num_kv_heads, seq_len, arch.head_dim), dt),
        "conv": ((L, batch_size, arch.conv_dim, arch.conv_kernel), dt),
        "ssm": (
            (L, batch_size, arch.mamba_heads, arch.mamba_head_dim, arch.d_state),
            jnp.float32,
        ),
    }


from nxdi_tpu.runtime.application import TpuModelForCausalLM  # noqa: E402


class FalconH1ForCausalLM(TpuModelForCausalLM):
    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        tc = self.tpu_config
        unsupported = [
            ("async_mode", tc.async_mode),
            ("is_prefix_caching", tc.is_prefix_caching),
            ("is_chunked_prefill", tc.is_chunked_prefill),
            ("is_block_kv_layout", tc.is_block_kv_layout),
            ("speculation", tc.speculation_length > 0 or tc.is_medusa),
            ("tensor_capture_config", tc.tensor_capture_config is not None),
            # raw-array param layout: the quantizer/LoRA rewrites would no-op
            ("quantized", tc.quantized),
            ("lora_config", tc.lora_config is not None),
        ]
        bad = [name for name, val in unsupported if val]
        if bad:
            raise ValueError(
                "falcon_h1 does not support: " + ", ".join(bad) + " — the "
                "Mamba2 recurrence needs dedicated state routing for these "
                "modes (conv/ssm states are not paged)"
            )

    def enable_models(self) -> None:
        super().enable_models()
        for wrapper in self.models.values():
            wrapper.forward_fn = falcon_h1_forward

    def _arch(self):
        return build_arch(self.config)

    def cache_partition_specs(self):
        from jax.sharding import PartitionSpec as P

        arch = self._arch()
        tp = self.tpu_config.tp_degree
        kv = AXIS_MP if (tp > 1 and arch.num_kv_heads % tp == 0) else None
        return {
            "k": P(None, None, kv, None, None),
            "v": P(None, None, kv, None, None),
            "conv": P(),  # interleaved [x|B|C] sections: stays replicated
            "ssm": P(),
        }

    def init_cache_host(self):
        tc = self.tpu_config
        return {
            k: jnp.zeros(shape, dt)
            for k, (shape, dt) in cache_shapes(
                self._arch(),
                tc.kv_cache_batch_size + tc.kv_cache_padding_size,
                tc.seq_len,
            ).items()
        }

    def _cache_struct(self):
        tc = self.tpu_config
        shapes = cache_shapes(
            self._arch(), tc.kv_cache_batch_size + tc.kv_cache_padding_size, tc.seq_len
        )
        return {k: jax.ShapeDtypeStruct(shape, dt) for k, (shape, dt) in shapes.items()}


APPLICATION_CLS = FalconH1ForCausalLM
