"""The compiled decoder graph — pure-functional analog of the reference's
``NeuronBaseModel`` (models/model_base.py:99, forward :713).

What the reference expresses as a traced torch module mutating Parameter KV
caches, we express as a pure function over (params, kv_cache, batch) returning
(outputs, new_kv_cache), jitted per (submodel, bucket) with the cache donated.

Structure of one forward (reference: model_base.py:1264 ``get_model_output``):
  embed -> [scan over decoder layers: rmsnorm -> attention(+KV update) ->
  residual -> rmsnorm -> MLP -> residual] -> final rmsnorm -> last-token gather
  -> lm_head -> padded-logit mask -> on-device sampler.

The layer stack runs as ONE ``lax.scan`` over layer-stacked params and cache
(kvcache/kv_cache.py layout): a single compiled layer body regardless of depth,
which keeps XLA compile times flat as models grow. Heterogeneous stacks (e.g.
interleaved sliding-window layers) pass per-layer scalars through the scan xs.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import ml_dtypes
import numpy as np
from jax.sharding import PartitionSpec as P

from nxdi_tpu.kvcache.kv_cache import (
    DEFAULT_KV_LAYOUT,
    BlockKVCacheSpec,
    BlockKVLayout,
    ContiguousKVLayout,
    KVCacheSpec,
)
from nxdi_tpu.ops import attention as attn_ops
from nxdi_tpu.ops import kernels as attn_kernels
from nxdi_tpu.ops import moe as moe_ops
from nxdi_tpu.ops import quantization as quant_ops
from nxdi_tpu.ops import sampling as sampling_ops
from nxdi_tpu.ops.norms import rms_norm
from nxdi_tpu.ops.rope import apply_rotary_pos_emb, rope_cos_sin
from nxdi_tpu.parallel.layers import (
    COLUMN_PARALLEL,
    REPLICATED,
    ROW_PARALLEL,
    VOCAB_PARALLEL,
    constrain,
)
from nxdi_tpu.parallel.mesh import AXIS_MP, AXIS_PP
from nxdi_tpu.parallel.policy import DEFAULT_POLICY, ShardingPolicy

ACT_FNS: Dict[str, Callable] = {
    "silu": jax.nn.silu,
    "gelu": jax.nn.gelu,
    "gelu_pytorch_tanh": partial(jax.nn.gelu, approximate=True),
    "gelu_new": partial(jax.nn.gelu, approximate=True),
    "relu": jax.nn.relu,
    # squared ReLU (persimmon, arcee/AFM — HF ACT2FN["relu2"])
    "relu2": lambda x: jnp.square(jax.nn.relu(x)),
}


def xielu(x: jax.Array, alpha_p: jax.Array, alpha_n: jax.Array) -> jax.Array:
    """xIELU activation (apertus; arxiv 2411.13010). ``alpha_p``/``alpha_n``
    are the POST-softplus per-layer scalars (host-computed at conversion to
    reproduce HF's bfloat16 parameter rounding — XIELUActivation keeps its
    learnables in bf16 regardless of model dtype)."""
    xf = x.astype(jnp.float32)
    beta = jnp.float32(0.5)
    # HF stores eps as a bf16 buffer; bake the same rounding
    eps = jnp.float32(np.float32(np.asarray(-1e-6, dtype=ml_dtypes.bfloat16)))
    pos = alpha_p * xf * xf + beta * xf
    neg = (jnp.expm1(jnp.minimum(xf, eps)) - xf) * alpha_n + beta * xf
    return jnp.where(xf > 0, pos, neg).astype(x.dtype)

# Attention-strategy trace: attention_block appends the strategy each traced
# attention body actually chose (kernel vs XLA fallback). Strategy decisions
# are STATIC (flags, shapes, mesh layout), so recording at trace time is
# exact — the analog of the reference's FlashAttentionStrategy logging
# (attention_base.py:165,1330); model_wrapper snapshots this per
# (submodel, bucket) so silent kernel fallbacks are visible and assertable.
_STRATEGY_TRACE: list = []


def _record_strategy(name: str) -> None:
    _STRATEGY_TRACE.append(name)


@dataclass(frozen=True)
class DecoderArch:
    """Static (hashable) architecture description closed over by the jitted fns.

    Head/vocab counts are the PADDED values after GQA sharding planning
    (parallel/gqa.py) and vocab padding; original sizes are kept for masking.
    """

    num_layers: int
    hidden_size: int
    num_attention_heads: int
    num_kv_heads: int
    head_dim: int
    intermediate_size: int
    vocab_size: int  # padded
    vocab_pad: int  # rows added to reach vocab_size
    rms_norm_eps: float = 1e-5
    hidden_act: str = "silu"
    attention_bias: bool = False
    mlp_bias: bool = False
    qk_norm: bool = False  # qwen3-style per-head q/k rmsnorm
    sliding_window: Optional[int] = None
    chunk_size: Optional[int] = None  # llama4 chunked attention
    attention_scale: Optional[float] = None
    tie_word_embeddings: bool = False
    dtype: str = "bfloat16"
    softmax_dtype: str = "float32"
    # Pallas kernel gates (reference: attn_kernel_enabled flags config.py:418-533)
    attn_kernel_enabled: bool = False
    attn_tkg_kernel_enabled: bool = False
    attn_block_tkg_kernel_enabled: bool = False  # paged decode through table
    # fused projections (reference: fused_qkv gqa.py:530-683, qkv/mlp NKI
    # kernels modeling_llama.py:502-943). fused_qkv packs q/k/v into ONE
    # weight with per-tp-rank head-block interleave (dense.fuse_qkv_weights);
    # the kernel flags route the fused matmuls through ops/kernels/fused_proj.
    # All three are enforced loudly: ModelWrapper raises after lowering if an
    # enabled flag's strategy never engaged (no silent no-ops).
    fused_qkv: bool = False
    fused_qkv_tp: int = 1  # tp degree the fused weight was interleaved for
    qkv_kernel_enabled: bool = False
    mlp_kernel_enabled: bool = False
    # pipeline parallel: layer stack sharded over the pp mesh axis, GPipe
    # microbatch rotation in run_decoder_layers (reference: pp_degree,
    # models/config.py:366, application_base.py:158-163)
    pp_degree: int = 1
    pp_microbatches: int = 0  # 0 = pp_degree
    # dynamic activation quantization (reference: ActivationQuantizationType
    # config.py:434-517); weights themselves are quantized in the params pytree
    act_quant: Optional[str] = None
    act_clamp: Optional[float] = None
    # MoE feed-forward replaces the dense MLP when set (ops/moe.py)
    moe: Optional[moe_ops.MoEArch] = None
    # gemma lineage (reference: models/gemma3/modeling_gemma3.py): (1+w)
    # float32 norms, sandwich (pre+post) feed-forward norms, sqrt(H) embedding
    # scale; per-layer sliding-window/rope selection rides the layer scan as
    # params flags ("use_sliding_window", "use_local_rope")
    gemma_norm: bool = False
    sandwich_norm: bool = False
    embed_scale: Optional[float] = None
    # gpt-oss style learned attention-sink logits (params: attn["sink"] (H,))
    attention_sink: bool = False
    # gemma3-vision: prefill image-token spans attend each other
    # bidirectionally (HF token_type_ids_mask_function); needs image_token_id
    bidirectional_image_attention: bool = False
    # dbrx: weight-only LayerNorm instead of RMSNorm; qkv clamp
    layernorm: bool = False
    clip_qkv: Optional[float] = None
    # gpt2 lineage: learned position embeddings added to the token embeds
    # (params["position_embeddings"]), no rope, plain (non-gated) MLP
    learned_pos_embeds: bool = False
    no_rope: bool = False
    gated_mlp: bool = True
    # o_proj bias (gpt-oss; the llama lineage never has one)
    attention_o_bias: bool = False
    # Trinity/Afmoe gated attention: ctx *= sigmoid(gate_proj(attn input))
    # before o_proj (params: attn["gate_proj"]["w"], q-interleave sharded)
    attn_out_gate: bool = False
    # YaRN attention factor multiplying cos/sin (gpt-oss, deepseek)
    rope_mscale: float = 1.0
    # LongRoPE (phi3 128k): inv_freq arrives stacked (2, D/2) [short, long];
    # the long set activates in-graph when max(position)+1 exceeds this
    # (HF _longrope_frequency_update semantics)
    longrope_original_max: Optional[int] = None
    # Qwen2-VL M-RoPE: head_dim/2 frequency channels partitioned into
    # [temporal, height, width] sections; batch supplies (B, 3, S) position
    # streams as "mrope_position_ids" (reference: models/qwen2_vl/ M-RoPE)
    mrope_section: Optional[Tuple[int, ...]] = None
    mrope_interleaved: bool = False  # qwen3-vl channel-interleaved layout
    # partial rotary (minimax-m2 rotary_dim=64 of head_dim=128; phi lineage):
    # only the first rotary_dim channels rotate, the rest pass through
    rotary_dim: Optional[int] = None
    # minimax-m2 "per_layer" qk norm: RMSNorm over the FLAT projection output
    # (num_heads*head_dim) BEFORE head reshape/rope. Under GQA zero-padding
    # the q denominator must stay the TRUE (unpadded) width — padded entries
    # are exactly zero, so sum(x^2)/true_dim reproduces the unpadded mean;
    # replicated k heads preserve the mean, so k uses the plain mean.
    qk_norm_flat: bool = False
    qk_norm_flat_qdim: int = 0  # true (unpadded) q width
    # asymmetric value width (mimo-v2: q/k head_dim 192, v head_dim 128);
    # None = same as head_dim. Cache stores v at this width.
    v_head_dim: Optional[int] = None
    # Multi-head Latent Attention replaces the GQA attention when set
    # (ops/mla.py; deepseek lineage)
    mla: Optional[Any] = None
    # llama4 (reference: models/llama4/): adjacent-pair (GPT-J) rope layout,
    # unweighted L2 qk-norm AFTER rope, per-position query temperature tuning
    # on no-rope layers; per-layer rope/chunk gating rides the scan via the
    # "use_rope" params flag
    rope_interleaved: bool = False
    qk_l2norm: bool = False
    # gemma2 softcapping: cap*tanh(x/cap) on attention scores / final logits
    attn_logit_softcap: Optional[float] = None
    final_logit_softcap: Optional[float] = None
    attn_temperature_tuning: bool = False
    floor_scale: float = 8192.0
    attn_scale: float = 0.1
    # olmo2: NO input norms; RMSNorm applied to the attn/mlp OUTPUT before the
    # residual add. Params reuse the standard layer keys: "input_layernorm"
    # holds the post-ATTENTION norm, "post_attention_layernorm" the
    # post-FEEDFORWARD norm (conversion aliases them; HF Olmo2DecoderLayer).
    post_block_norm: bool = False
    # parallel residual (cohere/command-r, gpt-neox use_parallel_residual):
    # x + attn(norm1(x)) + mlp(norm2(x)) in ONE residual add; cohere aliases
    # norm2 to norm1 (same weights), gpt-neox keeps them distinct
    parallel_block: bool = False
    # granite: scalar multipliers on block outputs and logits
    # (HF GraniteForCausalLM residual_multiplier / logits_scaling)
    residual_multiplier: float = 1.0
    logits_scaling: float = 1.0
    # interleaved sliding-window stacks (gpt-oss alternating, gemma3 5-of-6):
    # per-layer True = sliding-window layer. With window_sized_kv the cache
    # splits into a full-length stack for False layers and a W-slot ring
    # stack for True layers (reference: per-layer window-sized cache shapes,
    # gpt_oss_kv_cache_manager.py, kv_cache_manager.py:195-210); the layer
    # scan runs over the pattern's repeating unit (run_decoder_layers).
    kv_window_pattern: Optional[Tuple[bool, ...]] = None

    @property
    def kv_pattern_period(self) -> int:
        """Smallest repeating unit of kv_window_pattern (the unit-scan body
        compiles one decoder block per unit position)."""
        pat = self.kv_window_pattern
        assert pat is not None
        L = len(pat)
        for p in range(1, L + 1):
            if L % p == 0 and all(pat[i] == pat[i % p] for i in range(L)):
                return p
        return L

    def kv_cache_spec(self, batch_size: int, max_len: int, quant_dtype=None) -> KVCacheSpec:
        if self.mla is not None:
            # latent cache: k holds the shared rotated rope key, v the normed
            # compressed kv latent (ops/mla.py)
            return KVCacheSpec(
                num_layers=self.num_layers,
                batch_size=batch_size,
                num_kv_heads=1,
                max_len=max_len,
                head_dim=self.mla.qk_rope_head_dim,
                v_head_dim=self.mla.kv_lora_rank,
                dtype=self.dtype,
                quant_dtype=quant_dtype,
            )
        return KVCacheSpec(
            num_layers=self.num_layers,
            batch_size=batch_size,
            num_kv_heads=self.num_kv_heads,
            max_len=max_len,
            head_dim=self.head_dim,
            dtype=self.dtype,
            quant_dtype=quant_dtype,
            v_head_dim=self.v_head_dim,
        )


# ---------------------------------------------------------------------------
# Parameter pytree layout + sharding specs
# ---------------------------------------------------------------------------

def attention_param_specs(arch: DecoderArch) -> Dict[str, Any]:
    if arch.fused_qkv:
        # one interleaved weight: column-sharding hands each rank exactly its
        # [q-heads | k-heads | v-heads] block (dense.fuse_qkv_weights)
        spec = {
            "qkv_proj": {"w": COLUMN_PARALLEL},
            "o_proj": {"w": ROW_PARALLEL},
        }
        if arch.attention_bias:
            spec["qkv_proj"]["b"] = P(AXIS_MP)
        if arch.attention_o_bias:
            spec["o_proj"]["b"] = REPLICATED
        if arch.qk_norm:
            spec["q_norm"] = REPLICATED
            spec["k_norm"] = REPLICATED
        return spec
    spec: Dict[str, Any] = {
        "q_proj": {"w": COLUMN_PARALLEL},
        "k_proj": {"w": COLUMN_PARALLEL},
        "v_proj": {"w": COLUMN_PARALLEL},
        "o_proj": {"w": ROW_PARALLEL},
    }
    if arch.attention_bias:
        # Qwen2-style layout: q/k/v carry biases, o_proj does not
        for name in ("q_proj", "k_proj", "v_proj"):
            spec[name]["b"] = P(AXIS_MP)
    if arch.attention_o_bias:  # gpt-oss
        spec["o_proj"]["b"] = REPLICATED
    if arch.qk_norm:
        spec["q_norm"] = REPLICATED
        spec["k_norm"] = REPLICATED
    if arch.attn_out_gate:  # Trinity/Afmoe
        spec["gate_proj"] = {"w": COLUMN_PARALLEL}
    return spec


def mlp_param_specs(arch: DecoderArch) -> Dict[str, Any]:
    spec: Dict[str, Any] = {
        "up_proj": {"w": COLUMN_PARALLEL},
        "down_proj": {"w": ROW_PARALLEL},
    }
    if arch.gated_mlp:
        spec["gate_proj"] = {"w": COLUMN_PARALLEL}
    if arch.mlp_bias:
        if arch.gated_mlp:
            spec["gate_proj"]["b"] = P(AXIS_MP)
        spec["up_proj"]["b"] = P(AXIS_MP)
        spec["down_proj"]["b"] = REPLICATED
    return spec


def decoder_param_specs(arch: DecoderArch) -> Dict[str, Any]:
    """PartitionSpec pytree matching the params pytree produced by the model's
    checkpoint converter. Layer-stacked leaves get their layer dim unsharded
    (P(None, ...) prefix is implicit: specs rank-match via GSPMD trailing rules,
    so we write them explicitly below)."""

    # layer-stacked leaves: the leading (layer) axis shards over pp when
    # pipeline parallel is on — each stage holds its contiguous layer slice
    layer_axis = AXIS_PP if arch.pp_degree > 1 else None

    def stack(spec_tree):
        # prepend the layer axis to every leaf spec
        return jax.tree_util.tree_map(
            lambda s: P(*((layer_axis,) + tuple(s))),
            spec_tree,
            is_leaf=lambda x: isinstance(x, P),
        )

    layer_specs = {
        "input_layernorm": REPLICATED,
        "post_attention_layernorm": REPLICATED,
        "attn": attention_param_specs(arch),
    }
    if arch.moe is not None:
        layer_specs["moe"] = moe_ops.expert_parallel_specs(arch.moe)
    else:
        layer_specs["mlp"] = mlp_param_specs(arch)
    specs = {
        "embed_tokens": VOCAB_PARALLEL,
        "layers": stack(layer_specs),
        "norm": REPLICATED,
    }
    if not arch.tie_word_embeddings:
        specs["lm_head"] = COLUMN_PARALLEL
    return specs


# ---------------------------------------------------------------------------
# Blocks
# ---------------------------------------------------------------------------

def _norm(arch, x, w):
    if isinstance(w, dict):  # biased LayerNorm (gpt2 lineage): {"w", "b"}
        from nxdi_tpu.ops.norms import layer_norm

        return layer_norm(x, w["w"], w.get("b"), eps=arch.rms_norm_eps)
    if arch.layernorm:
        from nxdi_tpu.ops.norms import layer_norm

        return layer_norm(x, w, eps=arch.rms_norm_eps)
    return rms_norm(x, w, arch.rms_norm_eps, gemma_style=arch.gemma_norm)


def _linear(x, p, act_quant=None, clamp=None, adapter_ids=None):
    """Linear over either a full-precision param dict ``{"w"[, "b"]}`` or a
    quantized one ``{"qw", "scale"[, "b"]}`` (ops/quantization.py). When the
    dict carries slot-stacked LoRA buffers (lora/serving.py) and the batch
    supplies ``adapter_ids``, each row adds its adapter's low-rank delta —
    the reference's multi-LoRA linear (lora_serving/lora_layer.py)."""
    if "qw" in p or "qw4" in p:
        y = quant_ops.quantized_linear(x, p, act_quant=act_quant, clamp_bound=clamp)
    else:
        y = x @ p["w"]
        if "b" in p:
            y = y + p["b"]
    if adapter_ids is not None and "lora_A" in p:
        A = p["lora_A"][adapter_ids].astype(x.dtype)  # (B, in, r)
        Bw = p["lora_B"][adapter_ids].astype(x.dtype)  # (B, r, out)
        s = p["lora_scale"][adapter_ids]  # (B,)
        delta = jnp.einsum("b...r,bro->b...o", jnp.einsum("b...i,bir->b...r", x, A), Bw)
        y = y + delta * s[(...,) + (None,) * (y.ndim - 1)].astype(y.dtype)
    return y


def attention_block(
    arch: DecoderArch,
    p_attn: Dict[str, Any],
    hidden: jax.Array,  # (B, S, hidden)
    cos: jax.Array,
    sin: jax.Array,
    k_cache_l: jax.Array,  # contiguous: (B, KV, W, D) view; block: (slots, KV, D)
    v_cache_l: jax.Array,
    position_ids: jax.Array,  # (B, S)
    cache_spec,  # KVCacheSpec | BlockKVCacheSpec
    attend_to_cache: bool,
    policy: ShardingPolicy = DEFAULT_POLICY,
    layout=DEFAULT_KV_LAYOUT,
    cache_inputs: Optional[Dict[str, jax.Array]] = None,
    adapter_ids: Optional[jax.Array] = None,
    window_enabled: Optional[jax.Array] = None,
    use_rope: Optional[jax.Array] = None,
    defer_write: bool = False,
    qkv_stacked=None,  # (w_s (L,H,T), b_s|None) + stacked_layer_idx: in-scan kernel
    layer_idx=None,  # GLOBAL layer index (per-layer KV-quant scale rows)
    stacked_layer_idx=None,  # segment-local index into the stacked weights
    tkg_stacked=None,  # (k_s, v_s, kv_len): stacked-cache fused decode kernel
    spec_window=None,  # (k_sp, v_sp, win_pos, slot): draft-window scratch
) -> Tuple[jax.Array, Tuple[jax.Array, jax.Array]]:
    """QKV -> RoPE -> KV update -> attention -> O (reference:
    attention_base.py:571 prep_qkv_tensors, :2075 attention_context_encode).

    ``spec_window`` (fused-speculation draft loop, speculation/fused.py):
    fresh K/V land in a small per-layer (B, KV, spec_len+1, D) scratch at
    column ``slot`` instead of the full cache; attention reads the OLD cache
    with ALL window positions masked (prior windows' stale rows live there)
    plus the scratch as the fresh segment — its per-row rope positions are
    ``win_pos`` and position causality hides the not-yet-written columns.
    Returns the updated scratch slices; the window commits to the full cache
    ONCE after the draft scan, not once per draft step.

    ``defer_write`` (decode hot path): instead of scattering fresh K/V into
    the cache slice and carrying the full slice through the layer scan (XLA
    round-trips the whole cache per layer), attend over the OLD cache with
    this step's slots masked out plus the fresh rows appended, and return
    only the fresh rows — run_decoder_layers commits them all in ONE scatter
    on the stacked cache after the scan. Bitwise-equivalent attention inputs
    (quantized caches round-trip the fresh rows through the store
    dtype/scale first, matching the non-deferred read-after-write); only the
    softmax summation order differs.

    ``attend_to_cache=False`` (context encoding): queries attend the fresh K/V
    only — O(S^2) not O(S * max_len). ``True`` (decode/speculation): attend the
    cache through the layout's read after the in-place update. ``layout``
    (kvcache/kv_cache.py) decides how K/V land: contiguous lines by
    (seq_id, position) or a paged block pool by slot mapping.
    """
    B, S, _ = hidden.shape
    H, KV, D = arch.num_attention_heads, arch.num_kv_heads, arch.head_dim
    Dv = arch.v_head_dim or D  # mimo-v2: value width differs from q/k

    aq, ac = arch.act_quant, arch.act_clamp

    def _o_proj(ctx2d):
        """Output projection, optionally gated (Trinity/Afmoe: the context is
        multiplied by sigmoid(gate_proj(attention input)) before o_proj —
        composes with every attention strategy since the gate acts on the
        kernel-agnostic context)."""
        if arch.attn_out_gate:
            g = jax.nn.sigmoid(
                (hidden @ p_attn["gate_proj"]["w"]).astype(jnp.float32)
            )
            ctx2d = (ctx2d.astype(jnp.float32) * g).astype(ctx2d.dtype)
        return _linear(ctx2d, p_attn["o_proj"], aq, ac, adapter_ids)
    if arch.fused_qkv:
        if "qkv_proj" not in p_attn:
            raise NotImplementedError(
                "fused_qkv is enabled but this model's params carry no fused "
                "qkv_proj weight — the family's converter does not support "
                "fused QKV; disable the flag"
            )
        pq = p_attn["qkv_proj"]
        Tq, Tk, Tv = H * D, KV * D, KV * Dv
        if arch.qkv_kernel_enabled:
            if adapter_ids is not None or ("w" not in pq and qkv_stacked is None):
                raise NotImplementedError(
                    "qkv_kernel_enabled requires an unquantized, non-LoRA "
                    "fused qkv_proj weight"
                )
            if qkv_stacked is not None:
                w_s, b_s = qkv_stacked
                qkv = attn_kernels.sharded_qkv_stacked_call(
                    hidden, w_s,
                    layer_idx if stacked_layer_idx is None else stacked_layer_idx,
                    b_s,
                )
            else:
                qkv = attn_kernels.sharded_qkv_call(hidden, pq["w"], pq.get("b"))
            if qkv is None:
                raise NotImplementedError(
                    "qkv_kernel_enabled: fused projection shape is not "
                    "kernel-eligible; disable the flag"
                )
            _record_strategy("qkv_fused_kernel")
        else:
            qkv = _linear(hidden, pq, aq, ac, adapter_ids)
            _record_strategy("qkv_fused_matmul")
        # undo the per-rank interleave on the LOGICAL view: rank blocks are
        # head blocks in order, so regrouping by rank reassembles q/k/v
        tp = arch.fused_qkv_tp
        t = qkv.reshape(B, S, tp, (Tq + Tk + Tv) // tp)
        q = t[..., : Tq // tp].reshape(B, S, Tq)
        k = t[..., Tq // tp : (Tq + Tk) // tp].reshape(B, S, Tk)
        v = t[..., (Tq + Tk) // tp :].reshape(B, S, Tv)
    else:
        q = _linear(hidden, p_attn["q_proj"], aq, ac, adapter_ids)
        k = _linear(hidden, p_attn["k_proj"], aq, ac, adapter_ids)
        v = _linear(hidden, p_attn["v_proj"], aq, ac, adapter_ids)
    if arch.clip_qkv is not None:  # dbrx clamps the qkv outputs
        q = jnp.clip(q, -arch.clip_qkv, arch.clip_qkv)
        k = jnp.clip(k, -arch.clip_qkv, arch.clip_qkv)
        v = jnp.clip(v, -arch.clip_qkv, arch.clip_qkv)
    if arch.qk_norm_flat:
        # minimax-m2: rmsnorm over the whole flattened projection, pre-reshape
        def flat_rms(x, w, denom):
            xf = x.astype(jnp.float32)
            ms = jnp.sum(xf * xf, axis=-1, keepdims=True) / denom
            return (xf * jax.lax.rsqrt(ms + arch.rms_norm_eps) * w).astype(x.dtype)

        q = flat_rms(q, p_attn["q_norm"], arch.qk_norm_flat_qdim or q.shape[-1])
        k = flat_rms(k, p_attn["k_norm"], k.shape[-1])
    q = q.reshape(B, S, H, D)
    k = k.reshape(B, S, KV, D)
    v = v.reshape(B, S, KV, Dv)

    if arch.qk_norm:
        q = _norm(arch, q, p_attn["q_norm"])
        k = _norm(arch, k, p_attn["k_norm"])

    q = jnp.swapaxes(q, 1, 2)  # (B, H, S, D)
    k = jnp.swapaxes(k, 1, 2)  # (B, KV, S, D)
    v = jnp.swapaxes(v, 1, 2)

    q = constrain(q, policy.q)
    k = constrain(k, policy.kv)
    v = constrain(v, policy.kv)

    rope_fn = apply_rotary_pos_emb
    if arch.rope_interleaved:
        from nxdi_tpu.ops.rope import apply_rotary_pos_emb_interleaved as rope_fn
    if arch.rotary_dim is not None and arch.rotary_dim < D:
        # partial rotary: rotate the first rotary_dim channels only
        # (cos/sin are built from a rotary_dim-sized frequency table)
        rd, base_rope = arch.rotary_dim, rope_fn

        def rope_fn(q_, k_, cos_, sin_):
            qr, kr = base_rope(q_[..., :rd], k_[..., :rd], cos_, sin_)
            return (
                jnp.concatenate([qr, q_[..., rd:]], axis=-1),
                jnp.concatenate([kr, k_[..., rd:]], axis=-1),
            )
    if arch.no_rope:
        pass  # gpt2 lineage: positions come from learned embeddings
    elif use_rope is None:
        q, k = rope_fn(q, k, cos, sin)
    else:
        # llama4: some layers skip rope entirely (per-layer scan flag)
        qr, kr = rope_fn(q, k, cos, sin)
        q = jnp.where(use_rope, qr, q)
        k = jnp.where(use_rope, kr, k)

    if arch.qk_l2norm:
        # llama4 unweighted qk norm, AFTER rope, on rope layers only
        from nxdi_tpu.ops.rope import l2_norm

        qn, kn = l2_norm(q, arch.rms_norm_eps), l2_norm(k, arch.rms_norm_eps)
        if use_rope is None:
            q, k = qn, kn
        else:
            q = jnp.where(use_rope, qn, q)
            k = jnp.where(use_rope, kn, k)

    if arch.attn_temperature_tuning and use_rope is not None:
        # per-position query temperature on NO-rope layers
        # (reference: llama4 attn temperature tuning)
        pos = position_ids.astype(jnp.float32)
        scales = (
            jnp.log1p(jnp.floor((pos + 1.0) / arch.floor_scale)) * arch.attn_scale + 1.0
        )[:, None, :, None]
        q = jnp.where(use_rope, q, (q * scales).astype(q.dtype))

    ci = dict(cache_inputs or {})
    ci["position_ids"] = position_ids
    if layer_idx is not None:
        # in-scan layer index (the scan's arange xs): per-layer KV-quant
        # scale selection (kv_cache.py _scale_for) and stacked kernels
        ci["layer_idx"] = layer_idx
    if not attend_to_cache and S > 1 and ci.get("write_positions") is None:
        # context encoding from a fresh cache: positions are the row arange
        # starting at 0, so the contiguous layout may take its slice-write
        # fast path instead of a B*S-row scatter (kv_cache.py update)
        ci["prefill_from_zero"] = True
    if spec_window is not None and attend_to_cache:
        # fused-speculation draft window (one commit per WINDOW): write the
        # fresh row into scratch column `slot`, then attend [old cache with
        # every window position masked] + [scratch] — rows written by earlier
        # draft steps are visible at their true positions, unwritten columns
        # sit at future positions the causal mask hides. Numerically this
        # attends exactly the same (position, value) set as the per-step
        # commit path; only the two-part summation split differs.
        k_sp, v_sp, win_pos, slot = spec_window
        k_sp = jax.lax.dynamic_update_slice(
            k_sp, k.astype(k_sp.dtype), (0, 0, slot, 0)
        )
        v_sp = jax.lax.dynamic_update_slice(
            v_sp, v.astype(v_sp.dtype), (0, 0, slot, 0)
        )
        kk, vv, kv_pos = layout.read(k_cache_l, v_cache_l, ci, cache_spec)
        kk = constrain(kk, policy.cache_kv)
        vv = constrain(vv, policy.cache_kv)
        kv_pos = jnp.where(kv_pos >= win_pos[:, :1], jnp.int32(2 ** 30), kv_pos)
        _record_strategy("tkg_spec_window_xla")
        ctx = attn_ops.attention_two_part(
            q, kk, vv, k_sp, v_sp, position_ids, kv_pos, win_pos,
            scale=arch.attention_scale,
            softmax_dtype=jnp.float32,
            sliding_window=arch.sliding_window,
            chunk_size=arch.chunk_size,
            sink=p_attn.get("sink") if arch.attention_sink else None,
            sliding_window_enabled=window_enabled,
            chunk_enabled=use_rope,
            logit_softcap=arch.attn_logit_softcap,
        )
        ctx = jnp.swapaxes(ctx, 1, 2).reshape(B, S, H * Dv)
        out = _o_proj(ctx)
        return out, (k_sp, v_sp)
    # run_decoder_layers is the single authority on eligibility; the mask
    # check repeats here only because tree-verify programs statically carry
    # attn_mask in their cache inputs
    defer = defer_write and attend_to_cache and ci.get("attn_mask") is None
    if defer:
        # OLD cache; this step's slots are masked below and the fresh rows
        # appended — no per-layer full-cache write-back
        kk, vv, kv_pos = layout.read(k_cache_l, v_cache_l, ci, cache_spec)
        kk = constrain(kk, policy.cache_kv)
        vv = constrain(vv, policy.cache_kv)
        store = cache_spec.store_dtype
        array_scales = getattr(layout, "has_array_scales", lambda: False)()
        if store != k.dtype or getattr(layout, "k_scale", 1.0) != 1.0 or array_scales:
            # quantized cache: round-trip the fresh rows through the store
            # dtype/scale so this step's numerics match the non-deferred
            # path (which attends the quantize->dequantize'd row) exactly
            if array_scales:
                ks = layout._scale_for("k", ci, stacked=False)
                vs = layout._scale_for("v", ci, stacked=False)
            else:
                ks = getattr(layout, "k_scale", 1.0)
                vs = getattr(layout, "v_scale", 1.0)
            clip = getattr(ContiguousKVLayout, "clip_to_store")
            k_att = (clip(k / ks, store).astype(store).astype(k.dtype) * ks).astype(k.dtype)
            v_att = (clip(v / vs, store).astype(store).astype(v.dtype) * vs).astype(v.dtype)
        else:
            k_att, v_att = k, v
        # STACKED fused TKG kernel (round-4): reads the OLD cache straight
        # from the (L, B, KV, S, D) stack via a scalar-prefetched layer
        # index — no per-layer cache slice ever materializes for the pallas
        # operand (the tax that made the per-layer kernel lose in round 3)
        if (
            tkg_stacked is not None
            and S == 1
            and stacked_layer_idx is not None
            and window_enabled is None
            and use_rope is None
            and ci.get("write_positions") is None
        ):
            k_s, v_s, kv_len_s = tkg_stacked
            ctx = attn_kernels.sharded_fused_decode_stacked_call(
                policy, q, k_s, v_s, k, v, position_ids, stacked_layer_idx,
                scale=arch.attention_scale,
                sliding_window=arch.sliding_window,
                chunk_size=arch.chunk_size,
                kv_len=kv_len_s,
            )
            if ctx is not None:
                _record_strategy("tkg_fused_kernel_stacked")
                ctx = jnp.swapaxes(ctx, 1, 2).reshape(B, S, H * Dv)
                out = _o_proj(ctx)
                return out, (k, v)
        # fused TKG kernel: strict-causal online softmax over the old cache
        # merged with the fresh row in ONE pallas pass — the kernel that
        # COMPOSES with deferred writes (reference: fused TKG kernels,
        # attention_base.py:1419-1994); two_part attention is the XLA fallback
        if (
            arch.attn_tkg_kernel_enabled
            and S == 1
            and isinstance(layout, ContiguousKVLayout)  # ring kv_pos wraps
            and arch.v_head_dim is None
            and not arch.attention_sink
            and arch.attn_logit_softcap is None
            and window_enabled is None
            and use_rope is None
            and ci.get("write_positions") is None
            and attn_kernels.fused_decode_kernel_supported(q.shape, kk.shape)
        ):
            ctx = attn_kernels.sharded_fused_decode_call(
                policy, q, kk, vv, k_att, v_att, position_ids, kv_pos,
                scale=arch.attention_scale,
                sliding_window=arch.sliding_window,
                chunk_size=arch.chunk_size,
            )
            if ctx is not None:
                _record_strategy("tkg_fused_kernel")
                ctx = jnp.swapaxes(ctx, 1, 2).reshape(B, S, H * Dv)
                out = _o_proj(ctx)
                return out, (k, v)
        _record_strategy("tkg_two_part_xla")
        wpos = ci.get("write_positions", position_ids).astype(jnp.int32)
        hit = jnp.any(kv_pos[:, None, :] == wpos[:, :, None], axis=1)
        kv_pos = jnp.where(hit, jnp.int32(2 ** 30), kv_pos)
        ctx = attn_ops.attention_two_part(
            q, kk, vv, k_att, v_att, position_ids, kv_pos, wpos,
            scale=arch.attention_scale,
            softmax_dtype=jnp.float32,
            sliding_window=arch.sliding_window,
            chunk_size=arch.chunk_size,
            sink=p_attn.get("sink") if arch.attention_sink else None,
            sliding_window_enabled=window_enabled,
            chunk_enabled=use_rope,
            logit_softcap=arch.attn_logit_softcap,
        )
        ctx = jnp.swapaxes(ctx, 1, 2).reshape(B, S, H * Dv)
        out = _o_proj(ctx)
        return out, (k, v)  # fresh rows only; committed after the scan

    new_k, new_v = layout.update(k_cache_l, v_cache_l, k, v, ci, cache_spec)

    if attend_to_cache:
        if ci and ci.get("bidir_spans") is not None and S > 1:
            # a cache-attending multi-token prefill (prefix caching / chunked
            # prefill) cannot honor the bidirectional image-span mask: span
            # ids restart per chunk, so same-image tokens in the cached
            # prefix could never match — reject at trace time instead of
            # silently computing causal-only attention
            raise NotImplementedError(
                "bidirectional image attention (gemma3-vision) does not "
                "compose with prefix-cached/chunked prefill; disable "
                "prefix caching for this model"
            )
        # mixed ragged dispatch (serving one-dispatch step): the packed
        # token stream carries per-token (row, position) tags and one
        # combined per-row block table, so prefill chunks and decode rows
        # share this single attention call — the chunk/fresh rows are
        # already scattered into the pool (update above), exactly like the
        # per-row paged paths below
        mixed_rids = ci.get("mixed_row_ids")
        if mixed_rids is not None and S > 1:
            rids = mixed_rids.astype(jnp.int32)  # (1, S); -1 = padding
            R = ci["last_token_index"].shape[0]  # rows per step (static)
            bt = ci["block_table"].reshape(R, -1)  # (R, Wt) per-row tables
            if (
                isinstance(layout, BlockKVLayout)
                and arch.v_head_dim is None
                and arch.attn_kernel_enabled
                and ci.get("attn_mask") is None
                and ci.get("write_positions") is None
                and not arch.attention_sink
                and arch.attn_logit_softcap is None
                and arch.sliding_window is None
                and arch.chunk_size is None
                and window_enabled is None
                and use_rope is None
                and attn_kernels.ragged_paged_kernel_supported(
                    q.shape, new_k.shape, layout.block_size
                )
            ):
                ctx = attn_kernels.sharded_ragged_paged_call(
                    policy, q, new_k, new_v, bt, rids[0], position_ids[0],
                    block_size=layout.block_size,
                    scale=arch.attention_scale,
                    k_scale=layout.k_scale,
                    v_scale=layout.v_scale,
                )
                if ctx is not None:
                    _record_strategy("mixed_ragged_kernel")
                    ctx = jnp.swapaxes(ctx, 1, 2).reshape(B, S, H * D)
                    out = _o_proj(ctx)
                    return out, (new_k, new_v)
            # XLA fallback: gather the combined window and rebuild the
            # ragged causal mask from the token tags — kv col g serves row
            # g // row_width at in-row position g % row_width; holes carry
            # the layout's poisoned 2**30 position
            kk, vv, kv_pos = layout.read(new_k, new_v, ci, cache_spec)
            kk = constrain(kk, policy.cache_kv)
            vv = constrain(vv, policy.cache_kv)
            W = kk.shape[2]
            row_width = W // R
            g = jnp.arange(W, dtype=jnp.int32)
            kv_row = g // row_width
            kv_in = g % row_width
            live = kv_pos[0] < jnp.int32(2 ** 30)
            mask = (
                (rids[:, :, None] == kv_row[None, None, :])
                & (kv_in[None, None, :] <= position_ids[:, :, None])
                & live[None, None, :]
            )
            _record_strategy("mixed_ragged_xla")
            ctx = attn_ops.grouped_attention(
                q, kk, vv, mask,
                scale=arch.attention_scale, softmax_dtype=jnp.float32,
            )
            ctx = jnp.swapaxes(ctx, 1, 2).reshape(B, S, H * Dv)
            out = _o_proj(ctx)
            return out, (new_k, new_v)
        # prefix-cache / chunked-prefill CTE through the block table: the
        # chunk is already scattered into the pool (update above), so the
        # kernel reads prefix + chunk in token order without materializing
        # the (B, KV, W, D) gather (reference: NKI block-CTE kernels,
        # attention_base.py:909,1083)
        if (
            isinstance(layout, BlockKVLayout)
            and arch.v_head_dim is None
            and arch.attn_kernel_enabled
            and S > 1
            and "block_table" in ci
            and ci.get("attn_mask") is None
            and ci.get("write_positions") is None
            and not arch.attention_sink
            and arch.attn_logit_softcap is None
            and arch.sliding_window is None
            and arch.chunk_size is None
            and window_enabled is None
            and use_rope is None
            and attn_kernels.paged_prefill_kernel_supported(
                q.shape, new_k.shape, layout.block_size
            )
        ):
            ctx = attn_kernels.sharded_paged_prefill_call(
                policy, q, new_k, new_v, ci["block_table"], position_ids,
                block_size=layout.block_size,
                scale=arch.attention_scale,
                k_scale=layout.k_scale,
                v_scale=layout.v_scale,
            )
            if ctx is not None:
                _record_strategy("cte_paged_kernel")
                ctx = jnp.swapaxes(ctx, 1, 2).reshape(B, S, H * D)
                out = _linear(
                    ctx, p_attn["o_proj"], arch.act_quant, arch.act_clamp, adapter_ids
                )
                return out, (new_k, new_v)
        # paged decode: read K/V straight through the block table inside the
        # kernel — skips the materialized O(table-width) gather of
        # BlockKVLayout.read (reference: NKI block-TKG kernel,
        # attention_base.py:50-162)
        if (
            isinstance(layout, BlockKVLayout)
            and arch.v_head_dim is None
            and arch.attn_block_tkg_kernel_enabled
            and S == 1
            and "block_table" in ci
            and ci.get("attn_mask") is None
            and not arch.attention_sink
            and arch.attn_logit_softcap is None
            and arch.sliding_window is None
            and arch.chunk_size is None
            and window_enabled is None
            and use_rope is None
            and attn_kernels.paged_decode_kernel_supported(
                q.shape, new_k.shape, layout.block_size
            )
        ):
            ctx = attn_kernels.sharded_paged_decode_call(
                policy, q, new_k, new_v, ci["block_table"], position_ids,
                block_size=layout.block_size,
                scale=arch.attention_scale,
                k_scale=layout.k_scale,
                v_scale=layout.v_scale,
            )
            if ctx is not None:
                _record_strategy("tkg_paged_kernel")
                ctx = jnp.swapaxes(ctx, 1, 2).reshape(B, S, H * D)
                out = _linear(
                    ctx, p_attn["o_proj"], arch.act_quant, arch.act_clamp, adapter_ids
                )
                return out, (new_k, new_v)
        kk, vv, kv_pos = layout.read(new_k, new_v, ci, cache_spec)
        kk = constrain(kk, policy.cache_kv)
        vv = constrain(vv, policy.cache_kv)
        mask_override = ci.get("attn_mask")
        if mask_override is not None:
            # explicit (B, S, W) mask — tree-attention verify passes
            # (speculation/token_tree.py) where causal-by-position is wrong.
            # Sink/softcap still apply; window/chunk masks cannot compose with
            # an override (applications reject those combinations up front).
            W = kk.shape[2]
            _record_strategy("attn_mask_override_xla")
            ctx = attn_ops.grouped_attention(
                q, kk, vv, mask_override[:, :, :W],
                scale=arch.attention_scale, softmax_dtype=jnp.float32,
                sink=p_attn.get("sink") if arch.attention_sink else None,
                logit_softcap=arch.attn_logit_softcap,
            )
            ctx = jnp.swapaxes(ctx, 1, 2).reshape(B, S, H * Dv)
            out = _o_proj(ctx)
            return out, (new_k, new_v)
        ctx = None
        if (
            arch.attn_tkg_kernel_enabled
            and arch.v_head_dim is None
            and not arch.attention_sink
            and arch.attn_logit_softcap is None
            and window_enabled is None
            and use_rope is None
            and attn_kernels.decode_kernel_supported(q.shape, kk.shape)
        ):
            ctx = attn_kernels.sharded_kernel_call(
                policy, q, kk, vv, position_ids, kv_pos,
                decode=True,
                scale=arch.attention_scale,
                sliding_window=arch.sliding_window,
                chunk_size=arch.chunk_size,
            )
        _record_strategy("tkg_xla" if ctx is None else "tkg_kernel")
        if ctx is None:
            ctx = attn_ops.attention_with_positions(
                q, kk, vv, position_ids, kv_pos,
                scale=arch.attention_scale,
                softmax_dtype=jnp.float32,
                sliding_window=arch.sliding_window,
                chunk_size=arch.chunk_size,
                sink=p_attn.get("sink") if arch.attention_sink else None,
                sliding_window_enabled=window_enabled,
                chunk_enabled=use_rope,
                logit_softcap=arch.attn_logit_softcap,
            )
    else:
        # gemma3-vision: image-span tokens attend each other BIDIRECTIONALLY
        # during prefill (HF token_type_ids_mask_function OR-ed into both the
        # full and sliding masks); spans are derived in-graph from input_ids
        # (causal_lm_forward), so only the CTE program pays for it
        bidir = ci.get("bidir_spans") if ci else None
        extra_or = None
        if bidir is not None and S > 1:
            extra_or = (bidir[:, None, :] == bidir[:, :, None]) & (
                bidir[:, :, None] > 0
            )
        ctx = None
        if (
            arch.attn_kernel_enabled
            and arch.v_head_dim is None
            and not arch.attention_sink
            and arch.attn_logit_softcap is None
            and window_enabled is None
            and use_rope is None
            and extra_or is None
            and attn_kernels.prefill_kernel_supported(q.shape, k.shape)
        ):
            ctx = attn_kernels.sharded_kernel_call(
                policy, q, k, v, position_ids, position_ids,
                decode=False,
                scale=arch.attention_scale,
                sliding_window=arch.sliding_window,
                chunk_size=arch.chunk_size,
            )
        _record_strategy("cte_xla" if ctx is None else "cte_flash_kernel")
        if ctx is None:
            ctx = attn_ops.attention_with_positions(
                q, k, v, position_ids, position_ids,
                scale=arch.attention_scale,
                softmax_dtype=jnp.float32,
                sliding_window=arch.sliding_window,
                chunk_size=arch.chunk_size,
                sink=p_attn.get("sink") if arch.attention_sink else None,
                sliding_window_enabled=window_enabled,
                chunk_enabled=use_rope,
                logit_softcap=arch.attn_logit_softcap,
                extra_or_mask=extra_or,
            )

    ctx = jnp.swapaxes(ctx, 1, 2).reshape(B, S, H * Dv)
    out = _o_proj(ctx)
    return out, (new_k, new_v)


def mlp_block(
    arch: DecoderArch, p_mlp: Dict[str, Any], x: jax.Array, adapter_ids=None,
    mlp_stacked=None, layer_idx=None, policy: ShardingPolicy = DEFAULT_POLICY,
) -> jax.Array:
    """Gated MLP (SwiGLU family) — or the plain 2-layer MLP for the gpt2
    lineage (gated_mlp=False). XLA fuses act+mul into the matmuls.

    ``mlp_kernel_enabled`` routes the gated path through the Pallas fused
    gate/up/down kernel (ops/kernels/fused_proj.py; reference: the NKI MLP
    kernel, modeling_llama.py:502-943) — ineligible configurations raise,
    they never silently fall back. Inside the layer scan the weights come
    STACKED (``mlp_stacked`` = (L,H,I)/(L,I,H) arrays + in-scan layer index):
    the kernel indexes them via scalar prefetch, avoiding the per-layer
    slice-copy a pallas operand on scan xs would materialize.

    ``policy.mlp_hidden`` (MLP-CP, reference: mlp_cp_degree
    config.py:364,374-375): when set, the input stream is constrained
    S-sharded on entry and the output re-replicates at the residual join —
    GSPMD inserts the scatter/gather pair the reference wires by hand."""
    if policy.mlp_hidden is not None and x.shape[1] > 1:
        x = constrain(x, policy.mlp_hidden)
    if arch.mlp_kernel_enabled:
        bad = None
        if not arch.gated_mlp:
            bad = "non-gated MLP"
        elif arch.mlp_bias:
            bad = "MLP biases"
        elif adapter_ids is not None:
            bad = "LoRA adapters"
        elif mlp_stacked is None and any(
            "w" not in p_mlp[k] for k in ("gate_proj", "up_proj", "down_proj")
        ):
            bad = "quantized weights"
        if bad is not None:
            raise NotImplementedError(
                f"mlp_kernel_enabled does not support {bad}; disable the flag"
            )
        if mlp_stacked is not None:
            gs, us, ds = mlp_stacked
            out = attn_kernels.sharded_fused_mlp_stacked_call(
                x, gs, us, ds, layer_idx, act=arch.hidden_act
            )
        else:
            out = attn_kernels.sharded_fused_mlp_call(
                x,
                p_mlp["gate_proj"]["w"],
                p_mlp["up_proj"]["w"],
                p_mlp["down_proj"]["w"],
                act=arch.hidden_act,
            )
        if out is None:
            raise NotImplementedError(
                f"mlp_kernel_enabled: MLP shape (act={arch.hidden_act!r}) is "
                "not kernel-eligible; disable the flag"
            )
        _record_strategy("mlp_fused_kernel")
        return out
    aq, ac = arch.act_quant, arch.act_clamp
    if arch.hidden_act == "xielu":
        # apertus: per-layer learnable activation scalars ride the scan with
        # the mlp params (p_mlp["xielu"] = {"alpha_p", "alpha_n"}, f32)
        a = p_mlp["xielu"]
        up = xielu(_linear(x, p_mlp["up_proj"], aq, ac, adapter_ids),
                   a["alpha_p"], a["alpha_n"])
        return _linear(up, p_mlp["down_proj"], aq, ac, adapter_ids)
    act = ACT_FNS[arch.hidden_act]
    if not arch.gated_mlp:
        up = act(_linear(x, p_mlp["up_proj"], aq, ac, adapter_ids))
        return _linear(up, p_mlp["down_proj"], aq, ac, adapter_ids)
    gate = act(_linear(x, p_mlp["gate_proj"], aq, ac, adapter_ids))
    up = _linear(x, p_mlp["up_proj"], aq, ac, adapter_ids)
    return _linear(gate * up, p_mlp["down_proj"], aq, ac, adapter_ids)


def decoder_layer(
    arch: DecoderArch,
    lp: Dict[str, Any],
    hidden: jax.Array,
    cos: jax.Array,
    sin: jax.Array,
    k_cache_l: jax.Array,
    v_cache_l: jax.Array,
    position_ids: jax.Array,
    cache_spec,
    attend_to_cache: bool,
    policy: ShardingPolicy = DEFAULT_POLICY,
    layout=DEFAULT_KV_LAYOUT,
    cache_inputs: Optional[Dict[str, jax.Array]] = None,
    adapter_ids: Optional[jax.Array] = None,
    defer_write: bool = False,
    mlp_stacked=None,
    qkv_stacked=None,
    layer_idx=None,  # GLOBAL layer index (per-layer KV-quant scale rows)
    stacked_layer_idx=None,  # segment-local index into the stacked weights
    tkg_stacked=None,  # (k_s, v_s, kv_len): stacked-cache fused decode kernel
    spec_window=None,  # (k_sp, v_sp, win_pos, slot): draft-window scratch
):
    if stacked_layer_idx is None:
        stacked_layer_idx = layer_idx
    # per-layer rope selection (gemma3 local/global thetas): cos/sin arrive
    # stacked (2, B, S, D) and the layer flag picks one inside the scan body
    if "use_local_rope" in lp:
        cos = jnp.where(lp["use_local_rope"], cos[1], cos[0])
        sin = jnp.where(lp["use_local_rope"], sin[1], sin[0])
    window_enabled = lp.get("use_sliding_window")
    use_rope = lp.get("use_rope")

    h = hidden if arch.post_block_norm else _norm(arch, hidden, lp["input_layernorm"])
    if "input_norm_skip" in lp:
        # per-layer scalar riding the scan xs: EAGLE drafts feed the fc output
        # straight into attention for their first layer (no input norm)
        h = jnp.where(lp["input_norm_skip"], hidden, h)
    if arch.mla is not None:
        from nxdi_tpu.ops.mla import mla_attention_block as attn_block_fn
    else:
        attn_block_fn = attention_block
    extra = {}
    if attn_block_fn is attention_block:
        extra["defer_write"] = defer_write
        extra["qkv_stacked"] = qkv_stacked
        extra["layer_idx"] = layer_idx
        extra["stacked_layer_idx"] = stacked_layer_idx
        extra["tkg_stacked"] = tkg_stacked
        extra["spec_window"] = spec_window
    attn_out, (nk, nv) = attn_block_fn(
        arch, lp["attn"], h, cos, sin, k_cache_l, v_cache_l,
        position_ids, cache_spec, attend_to_cache, policy, layout, cache_inputs,
        adapter_ids, window_enabled, use_rope, **extra,
    )
    if arch.parallel_block:
        # cohere / gpt-neox: attention and MLP read their (possibly shared)
        # pre-norms off the SAME residual input, one residual add
        h_mlp = _norm(arch, hidden, lp["post_attention_layernorm"])
        if arch.moe is not None and "moe" in lp:
            ff = moe_ops.moe_block(arch, arch.moe, lp["moe"], h_mlp, policy.hidden)
        else:
            ff = mlp_block(arch, lp["mlp"], h_mlp, adapter_ids, mlp_stacked, stacked_layer_idx, policy=policy)
        hidden = hidden + (attn_out + ff) * arch.residual_multiplier
    elif arch.post_block_norm:
        # olmo2: x + norm(attn(x)); x + norm(mlp(x))
        hidden = hidden + _norm(arch, attn_out, lp["input_layernorm"]) * arch.residual_multiplier
        ff = mlp_block(arch, lp["mlp"], hidden, adapter_ids, mlp_stacked, stacked_layer_idx, policy=policy)
        hidden = hidden + _norm(arch, ff, lp["post_attention_layernorm"]) * arch.residual_multiplier
    elif arch.sandwich_norm:
        # gemma lineage: post-norms applied to the block OUTPUT before the
        # residual add, and a dedicated pre-feedforward norm
        # (reference: NeuronGemma3DecoderLayer forward, modeling_gemma3.py:224)
        attn_out = _norm(arch, attn_out, lp["post_attention_layernorm"])
        hidden = hidden + attn_out
        h = _norm(arch, hidden, lp["pre_feedforward_layernorm"])
        # per-layer MoE-vs-dense decided by the params structure so segmented
        # stacks (deepseek-V3 first_k_dense_replace, minimax) mix both
        if arch.moe is not None and "moe" in lp:
            ff = moe_ops.moe_block(arch, arch.moe, lp["moe"], h, policy.hidden)
        else:
            ff = mlp_block(arch, lp["mlp"], h, adapter_ids, mlp_stacked, stacked_layer_idx, policy=policy)
        ff = _norm(arch, ff, lp["post_feedforward_layernorm"])
        hidden = hidden + ff
    else:
        hidden = hidden + attn_out * arch.residual_multiplier
        h = _norm(arch, hidden, lp["post_attention_layernorm"])
        if arch.moe is not None and "moe" in lp:
            hidden = hidden + moe_ops.moe_block(arch, arch.moe, lp["moe"], h, policy.hidden) * arch.residual_multiplier
        else:
            hidden = hidden + mlp_block(arch, lp["mlp"], h, adapter_ids, mlp_stacked, stacked_layer_idx, policy=policy) * arch.residual_multiplier
    hidden = constrain(hidden, policy.hidden)
    return hidden, (nk, nv)


def _pipelined_decoder_layers(
    arch, layer_params, hidden, cos, sin, cache, position_ids, step_fn,
    cache_inputs, adapter_ids, defer=False, policy=DEFAULT_POLICY,
    collect_hidden=False,
):
    """GPipe-style pipeline over the ``pp`` mesh axis.

    TPU-native pipeline parallel (reference: pp_degree through the NxD
    ModelBuilder, models/config.py:366, application_base.py:158-163 — the
    reference delegates the schedule to its builder; here it is explicit).
    Mechanism: ``shard_map`` manual over ``pp`` only (tp/ep/... stay under
    GSPMD), the layer-stacked params and the cache sharded on their leading
    layer dim so each stage owns a contiguous slice of layers + stage-local
    KV. The batch splits into M microbatches (``pp_microbatches`` deepens the
    split to shrink the bubble); for ``T = M + pp - 1`` ticks each stage
    scans its local layers over its current microbatch and hands the
    activations to the next stage with a ring ``ppermute`` — collectives
    ride ICI, bubble fraction (pp-1)/(M+pp-1).

    ``defer`` (decode hot path, round-2 weak #2): the scan emits only fresh
    K/V rows and each tick lands them with ONE stage-local in-place commit
    (the Pallas commit kernel addressed by microbatch line via seq-id
    routing) instead of round-tripping the stage's whole cache through the
    scan ys per tick. Bubble ticks commit with slot -1 (dropped).

    Non-deferred bubble ticks still compute (SPMD requires it) but write
    back the old cache values, so garbage never lands.
    """
    mesh = jax.sharding.get_abstract_mesh()
    pp = arch.pp_degree
    n_micro = arch.pp_microbatches or pp
    B = hidden.shape[0]
    if B % n_micro:
        raise ValueError(f"batch {B} not divisible by pp microbatches {n_micro}")
    mb = B // n_micro
    ci = cache_inputs or {}
    cos_baxis = 0 if cos.ndim == 3 else 1  # stacked rope variants: (2, B, S, D)

    def slice_b(x, i, axis=0):
        return jax.lax.dynamic_slice_in_dim(x, i * mb, mb, axis)

    def staged(params_local, k_local, v_local, hidden_all, cos_, sin_, pos_, ci_, ad_):
        stage = jax.lax.axis_index(AXIS_PP)

        def scan_body(mb_ctx):
            cos_m, sin_m, pos_m, ci_m, ad_m = mb_ctx

            def body(h, xs):
                lp, kl, vl = xs
                h, nk, nv = step_fn(
                    h, lp, kl, vl, cos_m, sin_m, pos_m, ci_m, ad_m, defer_=defer
                )
                return h, ((nk, nv, h) if collect_hidden else (nk, nv))

            return body

        def tick(t, carry):
            h, out, kl, vl, out_h = carry
            i = t - stage  # this stage's microbatch index at tick t
            i_c = jnp.clip(i, 0, n_micro - 1)
            valid = (i >= 0) & (i < n_micro)
            ctx = (
                slice_b(cos_, i_c, cos_baxis),
                slice_b(sin_, i_c, cos_baxis),
                slice_b(pos_, i_c),
                {k: slice_b(v, i_c) for k, v in ci_.items()},
                None if ad_ is None else slice_b(ad_, i_c),
            )
            k_mb = jax.lax.dynamic_slice_in_dim(kl, i_c * mb, mb, axis=1)
            v_mb = jax.lax.dynamic_slice_in_dim(vl, i_c * mb, mb, axis=1)
            if collect_hidden:
                h_out, (k_new, v_new, h_layers) = jax.lax.scan(
                    scan_body(ctx), h, (params_local, k_mb, v_mb)
                )
                # bank this stage's per-layer hiddens for microbatch i
                banked_h = jax.lax.dynamic_update_slice_in_dim(
                    out_h, h_layers[None], i_c, 0
                )
                out_h = jnp.where(valid, banked_h, out_h)
            else:
                h_out, (k_new, v_new) = jax.lax.scan(
                    scan_body(ctx), h, (params_local, k_mb, v_mb)
                )
            if defer:
                # k_new/v_new are FRESH ROWS (L_local, mb, KV, 1, D): land
                # them in the stage-local cache with one in-place commit at
                # the microbatch's cache lines; bubble ticks drop (slot -1).
                # Inside the pp-manual region the cache is STILL GSPMD-sharded
                # over the kv-head axes — the pallas call must run per kv
                # shard (a raw custom call would force the partitioner to
                # gather the stage cache every tick), so it nests a shard_map
                # over exactly those axes.
                from nxdi_tpu.ops.kernels import kv_commit

                pos_mb = slice_b(pos_, i_c).astype(jnp.int32)  # (mb, 1)
                slots = jnp.where(valid, pos_mb, -1)
                lines = i_c * mb + jnp.arange(mb, dtype=jnp.int32)
                if kv_commit.commit_rows_supported(
                    kl.shape, vl.shape, k_new.shape, v_new.shape
                ):
                    kv_ax = policy.cache_kv[1]
                    axes = tuple(
                        a for a in (
                            kv_ax if isinstance(kv_ax, (tuple, list)) else (kv_ax,)
                        )
                        if a is not None and a in mesh.axis_names
                    )
                    kr = k_new.astype(kl.dtype)
                    vr = v_new.astype(vl.dtype)
                    if axes:
                        cspec = P(None, None, kv_ax, None, None)
                        commit = jax.shard_map(
                            kv_commit.kv_commit_rows,
                            # the CONTEXT mesh (pp already manual here)
                            mesh=jax.sharding.get_abstract_mesh(),
                            in_specs=(cspec, cspec, cspec, cspec, P(None, None),
                                      P(None)),
                            out_specs=(cspec, cspec),
                            axis_names=set(axes),
                            # check_vma must be off: the commit kernel's
                            # aliased (donated) cache outputs carry the
                            # UNREDUCED vma of their inputs, and shard_map's
                            # varying-manual-axes check rejects the alias
                            # pair even though each shard only ever writes
                            # its own rows (replicated-slot semantics are
                            # preserved by construction — every shard gets
                            # identical slots/lines inputs)
                            check_vma=False,
                        )
                        kl, vl = commit(kl, vl, kr, vr, slots, lines)
                    else:
                        kl, vl = kv_commit.kv_commit_rows(kl, vl, kr, vr, slots, lines)
                else:
                    b_idx = lines[:, None]
                    sl = jnp.where(slots < 0, kl.shape[3], slots)

                    def put(cache_arr, rows):
                        vals = rows.astype(cache_arr.dtype).swapaxes(2, 3)

                        def per_layer(cl, rl):
                            return cl.at[b_idx, :, sl].set(rl, mode="drop")

                        return jax.vmap(per_layer)(cache_arr, vals)

                    kl, vl = put(kl, k_new), put(vl, v_new)
            else:
                # bubble ticks write back the old values (no-op update)
                k_new = jnp.where(valid, k_new, k_mb)
                v_new = jnp.where(valid, v_new, v_mb)
                kl = jax.lax.dynamic_update_slice_in_dim(kl, k_new, i_c * mb, axis=1)
                vl = jax.lax.dynamic_update_slice_in_dim(vl, v_new, i_c * mb, axis=1)
            # the last stage banks finished microbatches
            banked = jax.lax.dynamic_update_slice_in_dim(out, h_out[None], i_c, 0)
            out = jnp.where(valid & (stage == pp - 1), banked, out)
            # ring-shift activations to the next stage; stage 0 feeds the
            # next microbatch from the embedded input
            h_next = jax.lax.ppermute(
                h_out, AXIS_PP, [(s, (s + 1) % pp) for s in range(pp)]
            )
            feed = slice_b(hidden_all, jnp.clip(t + 1, 0, n_micro - 1))
            h = jnp.where(stage == 0, feed, h_next)
            return h, out, kl, vl, out_h

        h0 = slice_b(hidden_all, 0)
        out0 = jnp.zeros((n_micro,) + h0.shape, h0.dtype)
        n_local = jax.tree_util.tree_leaves(params_local)[0].shape[0]
        out_h0 = jnp.zeros((n_micro, n_local) + h0.shape, h0.dtype)
        h_fin, out, k_fin, v_fin, out_h = jax.lax.fori_loop(
            0, n_micro + pp - 1, tick, (h0, out0, k_local, v_local, out_h0)
        )
        # replicate the last stage's banked outputs to every stage
        out = jax.lax.psum(
            jnp.where(stage == pp - 1, out, jnp.zeros_like(out)), AXIS_PP
        )
        # (n_micro, L_local, mb, S, H) -> (L_local, n_micro, mb, S, H): the
        # layer axis leads so the pp out-spec stacks stages into global order
        return out, k_fin, v_fin, jnp.swapaxes(out_h, 0, 1)

    p_specs = jax.tree_util.tree_map(lambda _: P(AXIS_PP), layer_params)
    ci_specs = {k: P() for k in ci}
    out, new_k, new_v, out_h = jax.shard_map(
        staged,
        mesh=mesh,
        in_specs=(p_specs, P(AXIS_PP), P(AXIS_PP), P(), P(), P(), P(), ci_specs,
                  P() if adapter_ids is not None else None),
        out_specs=(P(), P(AXIS_PP), P(AXIS_PP), P(AXIS_PP)),
        axis_names={AXIS_PP},
        # check_vma off by necessity, not convenience: the GPipe body emits
        # `out` with out_specs=P() (replicated) but its value is only
        # meaningful on the LAST stage (earlier stages hold bubble garbage);
        # the ppermute ring then delivers the real rows. The vma checker
        # would demand a psum/all_gather to "prove" replication — a real
        # collective round the schedule neither needs nor wants. The
        # invariant (stage s's tick t output is consumed only by stage s+1
        # at tick t+1) is enforced by the ppermute wiring itself and
        # token-matched under pp in tests/integration/test_parallelism.py.
        check_vma=False,
    )(layer_params, cache["k"], cache["v"], hidden, cos, sin, position_ids, ci,
      adapter_ids)
    hidden_out = out.reshape((B,) + out.shape[2:])
    new_cache = {"k": new_k, "v": new_v}
    if collect_hidden:
        # (L, n_micro, mb, S, H) -> (L, B, S, H): microbatch i holds batch
        # rows [i*mb, (i+1)*mb) — contiguous, so a reshape reassembles
        L = out_h.shape[0]
        layer_h = out_h.reshape((L, B) + out_h.shape[3:])
        return hidden_out, new_cache, layer_h
    return hidden_out, new_cache


def _interleaved_window_scan(
    arch, layer_params, hidden, cos, sin, cache, position_ids, cache_spec,
    step_fn, defer, layout, policy, cache_inputs, adapter_ids,
    collect_hidden, layer_injections,
):
    """Unit scan over interleaved full/sliding-window layer stacks.

    TPU-native form of the reference's per-layer window-sized caches
    (gpt_oss_kv_cache_manager.py [403 LoC]; kv_cache_manager.py:195-210):
    full-attention layers read/write the full-length ``cache['k']/['v']``
    stack; sliding-window layers a W-slot ring stack ``['k_win']/['v_win']``
    (kvcache WindowKVLayout semantics). A single lax.scan cannot carry xs of
    two different sequence lengths, so the scan runs over the pattern's
    smallest REPEATING UNIT (gpt-oss [SWA, full] -> period 2; gemma3 5 local
    + 1 global -> period 6): one compiled body per unit position, L/period
    scan steps — compile cost grows with the pattern period, not the depth.

    Window kinds are STATIC per unit position, so sliding-window masks
    compile directly (no traced per-layer flag is needed, though flags
    riding the params stay correct). Deferred-write decode emits fresh rows
    per kind; commits land separately (ring rows at slot ``pos % W``).
    """
    from nxdi_tpu.kvcache.kv_cache import WindowKVLayout

    pat = arch.kv_window_pattern
    if pat is None or len(pat) != arch.num_layers:
        raise ValueError(
            "cache carries a k_win ring stack but arch.kv_window_pattern is "
            f"unset or mismatched (pattern {pat}, layers {arch.num_layers})"
        )
    if isinstance(layer_params, (list, tuple)):
        raise NotImplementedError(
            "interleaved window-sized KV requires a homogeneous layer stack"
        )
    p = arch.kv_pattern_period
    U = arch.num_layers // p
    f_idx = [j for j in range(p) if not pat[j]]
    w_idx = [j for j in range(p) if pat[j]]
    assert f_idx and w_idx, "cache split requires both full and window layers"
    win_layout = WindowKVLayout(
        window=cache["k_win"].shape[3],
        route_by_seq_id=getattr(layout, "route_by_seq_id", False),
    )

    def unit(x):
        return x.reshape((U, x.shape[0] // U) + x.shape[1:])

    unit_params = jax.tree_util.tree_map(unit, layer_params)
    kf, vf = unit(cache["k"]), unit(cache["v"])
    kw, vw = unit(cache["k_win"]), unit(cache["v_win"])
    inj_u = unit(layer_injections) if layer_injections is not None else None

    def unit_body(h, xs):
        lp_u, kf_u, vf_u, kw_u, vw_u, inj_unit = xs
        rows_f, rows_w, hs = [], [], []
        fi = wi = 0
        for j in range(p):
            lp = jax.tree_util.tree_map(lambda x: x[j], lp_u)
            if pat[j]:
                h, nk, nv = step_fn(
                    h, lp, kw_u[wi], vw_u[wi], cos, sin, position_ids,
                    cache_inputs, adapter_ids,
                    layout_=win_layout, windowable_=False,
                )
                rows_w.append((nk, nv))
                wi += 1
            else:
                h, nk, nv = step_fn(
                    h, lp, kf_u[fi], vf_u[fi], cos, sin, position_ids,
                    cache_inputs, adapter_ids,
                )
                rows_f.append((nk, nv))
                fi += 1
            if inj_unit is not None:  # deepstack: per-layer residual adds
                h = h + inj_unit[j].astype(h.dtype)
            if collect_hidden:
                hs.append(h)

        def stack(rows):
            return (
                jnp.stack([r[0] for r in rows]),
                jnp.stack([r[1] for r in rows]),
            )

        ys = (stack(rows_f), stack(rows_w))
        if collect_hidden:
            ys = ys + (jnp.stack(hs),)  # (p, B, S, hidden), layer order
        return h, ys

    hidden, ys_all = jax.lax.scan(
        unit_body, hidden, (unit_params, kf, vf, kw, vw, inj_u)
    )
    (ys_kf, ys_vf), (ys_kw, ys_vw) = ys_all[0], ys_all[1]

    def flat(y):  # (U, per_unit, ...) -> (L_kind, ...)
        return y.reshape((-1,) + y.shape[2:])

    if defer:
        ci_commit = dict(cache_inputs or {})
        ci_commit["position_ids"] = position_ids
        full_new = layout.commit_rows(
            {"k": cache["k"], "v": cache["v"]},
            flat(ys_kf), flat(ys_vf), ci_commit, cache_spec, policy=policy,
        )
        win_new = win_layout.commit_rows(
            {"k": cache["k_win"], "v": cache["v_win"]},
            flat(ys_kw), flat(ys_vw), ci_commit, cache_spec, policy=policy,
        )
    else:
        full_new = {"k": flat(ys_kf), "v": flat(ys_vf)}
        win_new = {"k": flat(ys_kw), "v": flat(ys_vw)}
    new_cache = {
        "k": full_new["k"],
        "v": full_new["v"],
        "k_win": win_new["k"],
        "v_win": win_new["v"],
    }
    if collect_hidden:
        # (U, p, B, S, hidden) -> (L, B, S, hidden) in global layer order
        layer_h = ys_all[2].reshape((-1,) + ys_all[2].shape[2:])
        return hidden, new_cache, layer_h
    return hidden, new_cache


def _extract_stacked_weights(arch: DecoderArch, seg):
    """Pull the layer-stacked MLP / fused-QKV weights out of a segment pytree
    when their Pallas kernels are enabled, so the scan does not slice them
    per layer (see run_decoder_layers). Returns (seg', mlp_stacked,
    qkv_stacked) — stacked entries are None when the kernel is off or the
    segment has no such weights (e.g. a MoE segment)."""
    mlp_st = qkv_st = None
    if (
        arch.mlp_kernel_enabled
        and isinstance(seg, dict)
        and isinstance(seg.get("mlp"), dict)
        and all(
            isinstance(seg["mlp"].get(k), dict) and "w" in seg["mlp"][k]
            for k in ("gate_proj", "up_proj", "down_proj")
        )
    ):
        mlp = {k: dict(v) if isinstance(v, dict) else v for k, v in seg["mlp"].items()}
        mlp_st = (
            mlp["gate_proj"].pop("w"),
            mlp["up_proj"].pop("w"),
            mlp["down_proj"].pop("w"),
        )
        seg = {**seg, "mlp": mlp}
    if (
        arch.qkv_kernel_enabled
        and isinstance(seg, dict)
        and isinstance(seg.get("attn"), dict)
        and isinstance(seg["attn"].get("qkv_proj"), dict)
        and "w" in seg["attn"]["qkv_proj"]
    ):
        attn = dict(seg["attn"])
        qp = dict(attn["qkv_proj"])
        qkv_st = (qp.pop("w"), qp.pop("b", None))
        attn["qkv_proj"] = qp
        seg = {**seg, "attn": attn}
    return seg, mlp_st, qkv_st


def run_decoder_layers(
    arch: DecoderArch,
    layer_params: Dict[str, Any],  # layer-stacked pytree
    hidden: jax.Array,
    cos: jax.Array,
    sin: jax.Array,
    cache: Dict[str, jax.Array],  # full (L, B, KV, S_max, D)
    position_ids: jax.Array,
    cache_spec: KVCacheSpec,
    attend_to_cache: bool,
    kv_window: Optional[int] = None,
    policy: ShardingPolicy = DEFAULT_POLICY,
    layout=DEFAULT_KV_LAYOUT,
    cache_inputs: Optional[Dict[str, jax.Array]] = None,
    collect_hidden: bool = False,
    adapter_ids: Optional[jax.Array] = None,
    layer_injections: Optional[jax.Array] = None,  # (L, B, S, hidden) or None
    layer_replacements: Optional[Tuple[jax.Array, jax.Array]] = None,
    spec_window_inputs: Optional[Tuple[jax.Array, jax.Array]] = None,
):
    """Scan the layer stack. Cache slices ride the scan as xs/ys.

    ``spec_window_inputs`` (win_pos (B, W), slot ()): engaged when the cache
    pytree carries ``k_spec``/``v_spec`` scratch stacks (the fused-speculation
    draft loop, speculation/fused.py) — fresh rows land in the scratch, the
    full cache is read-only, and the window commits ONCE after the draft scan.

    ``layer_replacements``: ((L, B, S, hidden) values, (L,) mask) — layers
    whose mask entry is nonzero have their output stream REPLACED by the
    given value (tensor-replacement debugging, the capture plumbing in
    reverse; reference: utils/tensor_replacement/registry.py). Homogeneous
    single-lap stacks only.

    ``layer_injections``: per-layer residual additions applied AFTER each
    layer (qwen3-vl deepstack: vision features summed into the first K
    layers' outputs at visual positions — reference: _deepstack_process).

    ``kv_window`` statically truncates the attended cache to the bucket's token
    budget (reference: per-bucket compiled TKG programs attend only bucket-many
    positions) while writes still target the full-length cache. Contiguous
    layout only — the block layout's window is its block-table width.

    ``collect_hidden`` additionally stacks each layer's output hidden state as
    scan ys — (L, B, S, hidden) — for EAGLE3's aux-feature taps (reference:
    model_base.py:1581). Costs L×B×S×H activation memory, so only submodels
    that need it compile with it; returns a 3-tuple then.
    """

    from nxdi_tpu.kvcache.kv_cache import WindowKVLayout

    # bucket re-windowing slices the cache S dim — meaningless for the paged
    # pool and for the ring layout (its S dim is slots, not positions)
    windowable = not isinstance(layout, (BlockKVLayout, WindowKVLayout))
    # deferred cache writes (decode hot path): the scan emits only fresh K/V
    # rows; they commit in ONE scatter on the stacked cache below — carrying
    # full cache slices through the scan as ys round-trips the whole cache
    # per layer (measured ~6x the pure-attention cost on v5e)
    # (the TKG kernel no longer disables defer: the fused decode kernel in
    # attention_block implements two-part attention in one pallas pass, and
    # ineligible layer shapes fall back to the XLA two_part path per layer)
    defer = (
        attend_to_cache
        and arch.pp_degree == 1
        and arch.mla is None
        and isinstance(layout, ContiguousKVLayout)
        and (cache_inputs or {}).get("attn_mask") is None
    )
    spec_mode = "k_spec" in cache
    if spec_mode and (
        not attend_to_cache
        or arch.pp_degree > 1
        or arch.mla is not None
        or "k_win" in cache
        or not isinstance(layout, ContiguousKVLayout)
        or (cache_inputs or {}).get("attn_mask") is not None
        or spec_window_inputs is None
    ):
        raise NotImplementedError(
            "the speculation-window scratch rides the plain contiguous decode "
            "path only (speculation/fused.py gates eligibility)"
        )

    def _step(h, lp, kl, vl, cos_, sin_, pos_, ci_, ad_, layout_=None,
              windowable_=None, defer_=None, mlp_stacked=None,
              qkv_stacked=None, layer_idx=None, stacked_layer_idx=None,
              tkg_stacked=None, spec_window=None):
        """One decoder layer with the bucket's static KV window applied.
        ``layout_``/``windowable_``/``defer_`` override the stack-wide
        defaults for the interleaved-window unit scan (ring slices use the
        ring layout) and the pipelined path (stage-local deferred commit)."""
        lay = layout if layout_ is None else layout_
        win_ok = windowable if windowable_ is None else windowable_
        dfr = defer if defer_ is None else defer_
        if spec_window is not None:
            # the scratch IS the write target: ys carry its updated slices
            # (the same plumbing as deferred fresh rows), commit happens once
            # in the caller
            dfr = True
        stk = dict(mlp_stacked=mlp_stacked, qkv_stacked=qkv_stacked,
                   layer_idx=layer_idx, stacked_layer_idx=stacked_layer_idx,
                   tkg_stacked=tkg_stacked, spec_window=spec_window)
        if (win_ok and kv_window is not None and kv_window < kl.shape[2]
                and attend_to_cache and tkg_stacked is None):
            k_win, v_win = kl[:, :, :kv_window], vl[:, :, :kv_window]
            h, (nkw, nvw) = decoder_layer(
                arch, lp, h, cos_, sin_, k_win, v_win, pos_, cache_spec,
                attend_to_cache, policy, lay, ci_, ad_, defer_write=dfr, **stk,
            )
            if dfr:
                nk, nv = nkw, nvw  # fresh rows, committed after the scan
            else:
                nk = jax.lax.dynamic_update_slice(kl, nkw, (0, 0, 0, 0))
                nv = jax.lax.dynamic_update_slice(vl, nvw, (0, 0, 0, 0))
        else:
            h, (nk, nv) = decoder_layer(
                arch, lp, h, cos_, sin_, kl, vl, pos_, cache_spec,
                attend_to_cache, policy, lay, ci_, ad_, defer_write=dfr, **stk,
            )
        return h, nk, nv

    if arch.pp_degree > 1:
        segments_chk = (
            list(layer_params) if isinstance(layer_params, (list, tuple)) else [layer_params]
        )
        if layer_injections is not None:
            raise NotImplementedError(
                "deepstack layer injections are not supported under "
                "pipeline parallel"
            )
        if layer_replacements is not None:
            raise NotImplementedError(
                "tensor replacement at layer outputs is not supported under "
                "pipeline parallel — bisect on a tp-only config"
            )
        # deferred commit applies under pp too (stage-local in-place commit
        # each tick; see _pipelined_decoder_layers) — decode-shaped only
        defer_pp = (
            attend_to_cache
            and arch.mla is None
            and isinstance(layout, ContiguousKVLayout)
            and not getattr(layout, "route_by_seq_id", False)
            and getattr(layout, "k_scale", 1.0) == 1.0
            and getattr(layout, "v_scale", 1.0) == 1.0
            and not getattr(layout, "has_array_scales", lambda: False)()
            and cache["k"].dtype == cache_spec.compute_dtype  # no quant store
            and position_ids.shape[1] == 1
            and (cache_inputs or {}).get("attn_mask") is None
            and (cache_inputs or {}).get("write_positions") is None
        )
        # Heterogeneous segment stacks (deepseek-V3 first_k_dense + MoE rest,
        # minimax) pipeline as MULTI-LAP virtual stages: each segment runs one
        # full GPipe rotation over the pp mesh (stage s holds each segment's
        # s-th layer slice — the looping-pipeline schedule), activations carry
        # between laps (reference analog: generation_minimax_m2_pp_demo.py).
        # Cost: one bubble set per segment.
        pks, pvs, phs = [], [], []
        off_pp = 0
        for seg in segments_chk:
            n_seg = jax.tree_util.tree_leaves(seg)[0].shape[0]
            if n_seg % arch.pp_degree:
                raise ValueError(
                    f"segment of {n_seg} layers is not divisible by pp_degree "
                    f"({arch.pp_degree}) — each pipeline lap needs equal "
                    "per-stage layer slices"
                )
            seg_cache = {
                "k": jax.lax.slice_in_dim(cache["k"], off_pp, off_pp + n_seg, axis=0),
                "v": jax.lax.slice_in_dim(cache["v"], off_pp, off_pp + n_seg, axis=0),
            }
            res = _pipelined_decoder_layers(
                arch, seg, hidden, cos, sin, seg_cache, position_ids,
                _step, cache_inputs, adapter_ids, defer=defer_pp,
                policy=policy, collect_hidden=collect_hidden,
            )
            if collect_hidden:
                hidden, seg_new, seg_h = res
                phs.append(seg_h)
            else:
                hidden, seg_new = res
            pks.append(seg_new["k"])
            pvs.append(seg_new["v"])
            off_pp += n_seg
        cat_pp = (lambda xs: xs[0] if len(xs) == 1 else jnp.concatenate(xs, axis=0))
        new_cache = {"k": cat_pp(pks), "v": cat_pp(pvs)}
        if collect_hidden:
            return hidden, new_cache, cat_pp(phs)
        return hidden, new_cache

    if "k_win" in cache:
        if layer_replacements is not None:
            raise NotImplementedError(
                "tensor replacement at layer outputs is not supported with "
                "interleaved window KV stacks — bisect with a full-attention "
                "cache layout"
            )
        return _interleaved_window_scan(
            arch, layer_params, hidden, cos, sin, cache, position_ids,
            cache_spec, _step, defer, layout, policy, cache_inputs,
            adapter_ids, collect_hidden, layer_injections,
        )

    # Heterogeneous stacks (deepseek-V3 first_k_dense_replace, minimax) arrive
    # as a LIST of layer-stacked segments — e.g. [dense-MLP head, MoE rest] —
    # each scanned over its static slice of the cache. Homogeneous models pass
    # the single stacked pytree unchanged.
    segments = (
        list(layer_params) if isinstance(layer_params, (list, tuple)) else [layer_params]
    )
    # stacked-cache fused TKG kernel eligibility (round-4): the kernel reads
    # the OLD cache from the full stack via scalar-prefetched layer index, so
    # the scan's per-layer cache slices are never pallas operands (round-3's
    # slice-copy tax). Conditions mirror the deferred-commit contract.
    _has_layer_flags = any(
        isinstance(sg, dict)
        and any(k in sg for k in ("use_sliding_window", "use_rope", "use_local_rope"))
        for sg in segments
    )
    use_stacked_tkg = (
        arch.attn_tkg_kernel_enabled
        and defer
        and not spec_mode
        and position_ids.shape[1] == 1
        # flash decoding (KV-S sharded) and per-layer window/rope flags fall
        # back per layer inside attention_block — skipping the kv_window
        # slice for them would regress the XLA path to the full cache
        and policy.cache_kv[2] is None
        and not _has_layer_flags
        and arch.v_head_dim is None
        and not arch.attention_sink
        and arch.attn_logit_softcap is None
        and not getattr(layout, "route_by_seq_id", False)
        and getattr(layout, "k_scale", 1.0) == 1.0
        and getattr(layout, "v_scale", 1.0) == 1.0
        and not getattr(layout, "has_array_scales", lambda: False)()
        and cache["k"].dtype == cache_spec.compute_dtype
        and (cache_inputs or {}).get("write_positions") is None
        and attn_kernels.fused_decode_kernel_supported(
            (position_ids.shape[0], arch.num_attention_heads, 1, arch.head_dim),
            cache["k"].shape[1:],
        )
    )

    ks, vs, hs = [], [], []
    off = 0
    for seg in segments:
        # kernel-stacked weights: keep the big MLP/QKV weights OUT of the
        # scanned xs (a pallas operand on a scan slice materializes a full
        # per-layer weight copy) — the kernels index the stacked arrays via
        # scalar-prefetched layer index instead
        seg, mlp_st, qkv_st = _extract_stacked_weights(arch, seg)
        n_seg = jax.tree_util.tree_leaves(seg)[0].shape[0]

        def body(h, xs, mlp_st=mlp_st, qkv_st=qkv_st, seg_off=off,
                 tkg_st=None):
            # xs carries the GLOBAL layer index (for per-layer KV-quant scale
            # rows, kv_cache._scale_for); the per-SEGMENT stacked kernel
            # weights index with the segment-local offset
            lp, kl, vl, ksp, vsp, inj, li, repl = xs
            li_local = li - jnp.int32(seg_off)
            spec_win = None
            if ksp is not None:
                spec_win = (ksp, vsp) + spec_window_inputs
            h, nk, nv = _step(
                h, lp, kl, vl, cos, sin, position_ids, cache_inputs,
                adapter_ids, mlp_stacked=mlp_st, qkv_stacked=qkv_st,
                layer_idx=li, stacked_layer_idx=li_local, tkg_stacked=tkg_st,
                spec_window=spec_win,
            )
            if inj is not None:
                h = h + inj.astype(h.dtype)
            if repl is not None:
                rv, rm = repl
                h = jnp.where(rm > 0, rv.astype(h.dtype), h)
            return h, ((nk, nv, h) if collect_hidden else (nk, nv))

        k_seg = jax.lax.slice_in_dim(cache["k"], off, off + n_seg, axis=0)
        v_seg = jax.lax.slice_in_dim(cache["v"], off, off + n_seg, axis=0)
        ksp_seg = vsp_seg = None
        if spec_mode:
            ksp_seg = jax.lax.slice_in_dim(cache["k_spec"], off, off + n_seg, axis=0)
            vsp_seg = jax.lax.slice_in_dim(cache["v_spec"], off, off + n_seg, axis=0)
        if use_stacked_tkg:
            from functools import partial as _partial

            body = _partial(body, tkg_st=(k_seg, v_seg, kv_window))
        inj_seg = (
            jax.lax.slice_in_dim(layer_injections, off, off + n_seg, axis=0)
            if layer_injections is not None
            else None
        )
        repl_seg = (
            (
                jax.lax.slice_in_dim(layer_replacements[0], off, off + n_seg, axis=0),
                jax.lax.slice_in_dim(layer_replacements[1], off, off + n_seg, axis=0),
            )
            if layer_replacements is not None
            else None
        )
        xs = (seg, k_seg, v_seg, ksp_seg, vsp_seg, inj_seg,
              off + jnp.arange(n_seg, dtype=jnp.int32), repl_seg)
        hidden, ys = jax.lax.scan(body, hidden, xs)
        off += n_seg
        if collect_hidden:
            ks.append(ys[0]); vs.append(ys[1]); hs.append(ys[2])
        else:
            ks.append(ys[0]); vs.append(ys[1])
    cat = (lambda xs: xs[0] if len(xs) == 1 else jnp.concatenate(xs, axis=0))
    if spec_mode:
        # full cache untouched; the scratch stacks carry this step's rows and
        # the whole window commits once, after the draft scan (fused.py)
        new_cache = {
            "k": cache["k"],
            "v": cache["v"],
            "k_spec": cat(ks),
            "v_spec": cat(vs),
        }
    elif defer:
        ci_commit = dict(cache_inputs or {})
        ci_commit["position_ids"] = position_ids
        new_cache = layout.commit_rows(
            cache, cat(ks), cat(vs), ci_commit, cache_spec, policy=policy
        )
    else:
        new_cache = {"k": cat(ks), "v": cat(vs)}
    if collect_hidden:
        return hidden, new_cache, cat(hs)
    return hidden, new_cache


# ---------------------------------------------------------------------------
# Full forward
# ---------------------------------------------------------------------------

# the layout-input keys every KV layout may consume (ContiguousKVLayout /
# BlockKVLayout / WindowKVLayout .get what they need); single source of truth
# for causal_lm_forward and the custom family forwards (e.g. mimo_v2)
CACHE_INPUT_KEYS = ("seq_ids", "slot_mapping", "block_table",
                    "write_positions", "attn_mask", "last_token_index",
                    "mixed_row_ids")


def collect_cache_inputs(batch: Dict[str, jax.Array]) -> Dict[str, jax.Array]:
    return {k: batch[k] for k in CACHE_INPUT_KEYS if k in batch}


def causal_lm_forward(
    arch: DecoderArch,
    inv_freq: np.ndarray,
    params: Dict[str, Any],
    cache: Dict[str, jax.Array],
    batch: Dict[str, jax.Array],
    *,
    attend_to_cache: bool,
    kv_window: Optional[int] = None,
    policy: ShardingPolicy = DEFAULT_POLICY,
    layout=DEFAULT_KV_LAYOUT,
    gather_last_token: bool = True,
    output_logits: bool = False,
    output_all_logits: bool = False,
    output_argmax_all: bool = False,
    output_logit_stats: bool = False,
    on_device_sampling: bool = True,
    do_sample: bool = False,
    global_topk: int = 256,
    deterministic: bool = False,
    dp_sampling: bool = False,
    return_next_inputs: bool = False,
    output_hidden: bool = False,
    aux_hidden_indices: Optional[Tuple[int, ...]] = None,
    image_token_id: Optional[int] = None,
    tensor_capture: Optional[Tuple[str, ...]] = None,
    tensor_replacement: Optional[Tuple[str, ...]] = None,
    mixed_rows: bool = False,
) -> Tuple[Dict[str, jax.Array], Dict[str, jax.Array]]:
    """One submodel forward (reference: model_base.py:713 NeuronBaseModel.forward).

    ``batch`` keys: input_ids (B,S) i32, position_ids (B,S) i32,
    last_token_index (B,) i32, sampling_params (B,3) f32, rng key.
    Returns (outputs, new_cache); outputs has "tokens" and/or "logits".

    ``mixed_rows`` (the serving engine's one-dispatch mixed step): the batch
    dim is 1 and the scheduler's ROWS live along the packed token axis,
    tagged by ``mixed_row_ids``; ``last_token_index`` is (R,) packed indices
    of each row's newest token and ``sampling_params`` is (R, 3), so the
    lm_head/sampling tail runs with R as its batch dim.
    """
    from nxdi_tpu.config import to_jax_dtype

    input_ids = batch["input_ids"]
    position_ids = batch["position_ids"]
    compute_dtype = to_jax_dtype(arch.dtype)

    hidden = jnp.take(params["embed_tokens"], input_ids, axis=0).astype(compute_dtype)
    if arch.embed_scale is not None:
        # gemma scales embeddings by sqrt(hidden) AFTER the dtype downcast
        # (reference: modeling_gemma3.py:238-241)
        hidden = hidden * jnp.asarray(arch.embed_scale, compute_dtype)
    if arch.learned_pos_embeds:
        hidden = hidden + jnp.take(
            params["position_embeddings"], position_ids, axis=0
        ).astype(compute_dtype)
    if image_token_id is not None and "image_embeds" in batch:
        # multimodal prefill: replace image-placeholder token embeddings with
        # the projected vision features, row-local order (reference: the
        # image-to-text CTE merging vision embeds, image_to_text_model_base.py)
        img = batch["image_embeds"].astype(compute_dtype)  # (B, N, hidden)
        is_img = input_ids == image_token_id  # (B, S)
        idx = jnp.clip(jnp.cumsum(is_img, axis=1) - 1, 0, img.shape[1] - 1)
        gathered = jnp.take_along_axis(
            img, idx[:, :, None].astype(jnp.int32), axis=1
        )
        hidden = jnp.where(is_img[:, :, None], gathered, hidden)
    if "fc" in params:
        # EAGLE draft input: concat(token embedding, previous-position feature)
        # projected back to the hidden size (reference: the EAGLE draft fc,
        # modeling_llama.py:1408, fed target hidden states model_base.py:1581).
        feats = batch["prev_hidden"][:, : input_ids.shape[1]].astype(compute_dtype)
        hidden = _linear(
            jnp.concatenate([hidden, feats], axis=-1),
            params["fc"], arch.act_quant, arch.act_clamp,
        )
    if tensor_replacement and "embeds" in tensor_replacement:
        # tensor replacement (capture in reverse, reference:
        # utils/tensor_replacement/registry.py): swap the post-embedding
        # stream for the injected host tensor when its mask is set — one
        # compiled program serves both plain (zero mask) and replaced runs
        hidden = jnp.where(
            batch["tr_embeds_mask"][0] > 0,
            batch["tr_embeds"].astype(compute_dtype), hidden,
        )
    hidden = constrain(hidden, policy.hidden)
    inv_freq = np.asarray(inv_freq)
    if arch.mrope_section is not None and "mrope_position_ids" in batch:
        from nxdi_tpu.ops.rope import mrope_cos_sin

        cos, sin = mrope_cos_sin(
            batch["mrope_position_ids"][..., : input_ids.shape[1]],
            inv_freq, arch.mrope_section, dtype=jnp.float32,
            interleaved=arch.mrope_interleaved,
        )
    elif arch.longrope_original_max is not None and inv_freq.ndim == 2:
        # LongRoPE: [short, long] frequency sets, selected per forward from
        # the true max position (padding lanes continue the arange past the
        # real last token, so read positions at last_token_index). The regime
        # is a scalar, so select the frequency SET before the trig — one
        # cos/sin build instead of two.
        if "last_token_index" in batch:
            real_last = jnp.take_along_axis(
                position_ids, batch["last_token_index"][:, None], axis=1
            )
            seq_len_now = jnp.max(real_last) + 1
        else:
            seq_len_now = jnp.max(position_ids) + 1
        is_long = seq_len_now > arch.longrope_original_max
        inv = jnp.where(is_long, jnp.asarray(inv_freq[1]), jnp.asarray(inv_freq[0]))
        cos, sin = rope_cos_sin(position_ids, inv, dtype=jnp.float32)
    elif inv_freq.ndim == 2:  # (2, D/2): [global, local] thetas (gemma3)
        cos_g, sin_g = rope_cos_sin(position_ids, inv_freq[0], dtype=jnp.float32)
        cos_l, sin_l = rope_cos_sin(position_ids, inv_freq[1], dtype=jnp.float32)
        cos = jnp.stack([cos_g, cos_l])
        sin = jnp.stack([sin_g, sin_l])
    else:
        cos, sin = rope_cos_sin(position_ids, inv_freq, dtype=jnp.float32)
    if arch.rope_mscale != 1.0:
        cos = cos * arch.rope_mscale
        sin = sin * arch.rope_mscale

    if isinstance(layout, BlockKVLayout):
        slots = cache["k"].shape[1]
        cache_spec = BlockKVCacheSpec(
            num_layers=arch.num_layers,
            num_blocks=slots // layout.block_size,
            block_size=layout.block_size,
            num_kv_heads=arch.num_kv_heads,
            head_dim=arch.head_dim,
            dtype=arch.dtype,
        )
    else:
        cache_spec = arch.kv_cache_spec(cache["k"].shape[1], cache["k"].shape[3])
    cache_inputs = collect_cache_inputs(batch)
    if (
        arch.bidirectional_image_attention
        and image_token_id is not None
        and input_ids.shape[1] > 1
        and not attend_to_cache
    ):
        # per-image span ids (consecutive placeholder runs; distinct images
        # never attend each other — HF image_group_ids semantics), derived
        # in-graph so no extra host input is needed. PREFILL-stage programs
        # only (attend_to_cache=False): a cache-attending S>1 window is a
        # speculation verify pass whose generated tokens carry no image spans
        # — computing spans there tripped attention_block's prefix-caching
        # rejection at trace time and kept fused/EAGLE speculation from
        # compiling on gemma3-vision configs (ADVICE r5). Prefix-cached /
        # chunked prefill (also cache-attending S>1) is rejected up front at
        # wrapper construction for these models (runtime/model_wrapper.py).
        is_img = input_ids == image_token_id
        starts = is_img & ~jnp.concatenate(
            [jnp.zeros_like(is_img[:, :1]), is_img[:, :-1]], axis=1
        )
        cache_inputs["bidir_spans"] = jnp.where(
            is_img, jnp.cumsum(starts.astype(jnp.int32), axis=1), 0
        )
    layer_injections = None
    if image_token_id is not None and "deepstack_embeds" in batch:
        # qwen3-vl deepstack: layer k's output gains the k-th vision feature
        # stream at image-placeholder positions (reference: qwen3_vl
        # _deepstack_process; HF Qwen3VLTextModel layer loop)
        ds = batch["deepstack_embeds"].astype(compute_dtype)  # (B, K, N, H)
        K = ds.shape[1]
        is_img = input_ids == image_token_id  # (B, S)
        idx = jnp.clip(jnp.cumsum(is_img, axis=1) - 1, 0, ds.shape[2] - 1)
        gathered = jnp.take_along_axis(
            ds, idx[:, None, :, None].astype(jnp.int32), axis=2
        )  # (B, K, S, H)
        inj = jnp.where(is_img[:, None, :, None], gathered, 0.0)
        inj = jnp.swapaxes(inj, 0, 1)  # (K, B, S, H)
        pad = arch.num_layers - K
        layer_injections = jnp.concatenate(
            [inj, jnp.zeros((pad,) + inj.shape[1:], inj.dtype)], axis=0
        )

    spec_window_inputs = None
    if "k_spec" in cache:
        # fused-speculation draft window scratch (speculation/fused.py): the
        # window's absolute rope positions and this step's scratch column
        spec_window_inputs = (
            batch["spec_win_pos"].astype(jnp.int32),
            batch["spec_win_slot"].astype(jnp.int32),
        )

    layer_replacements = None
    if tensor_replacement and "layers" in tensor_replacement:
        layer_replacements = (
            jnp.swapaxes(batch["tr_layer_values"], 0, 1),  # (L, B, S, H)
            batch["tr_layer_mask"][0],  # (L,) — every batch row carries the same mask
        )

    captured: Dict[str, jax.Array] = {}
    if tensor_capture and "embeds" in tensor_capture:
        captured["embeds"] = hidden
    layer_hiddens = None
    if tensor_capture and "layer_hiddens" in tensor_capture and not aux_hidden_indices:
        aux_hidden_indices = ()  # falsy: don't emit aux_hidden output
        hidden, new_cache, layer_hiddens = run_decoder_layers(
            arch, params["layers"], hidden, cos, sin, cache,
            position_ids, cache_spec, attend_to_cache, kv_window=kv_window,
            policy=policy, layout=layout, cache_inputs=cache_inputs,
            collect_hidden=True, adapter_ids=batch.get("adapter_ids"),
            layer_injections=layer_injections,
            layer_replacements=layer_replacements,
            spec_window_inputs=spec_window_inputs,
        )
        captured["layer_hiddens"] = layer_hiddens
    elif aux_hidden_indices:
        hidden, new_cache, layer_hiddens = run_decoder_layers(
            arch, params["layers"], hidden, cos, sin, cache,
            position_ids, cache_spec, attend_to_cache, kv_window=kv_window,
            policy=policy, layout=layout, cache_inputs=cache_inputs,
            collect_hidden=True, adapter_ids=batch.get("adapter_ids"),
            layer_injections=layer_injections,
            layer_replacements=layer_replacements,
            spec_window_inputs=spec_window_inputs,
        )
        if tensor_capture and "layer_hiddens" in tensor_capture:
            captured["layer_hiddens"] = layer_hiddens
    else:
        hidden, new_cache = run_decoder_layers(
            arch, params["layers"], hidden, cos, sin, cache,
            position_ids, cache_spec, attend_to_cache, kv_window=kv_window,
            policy=policy, layout=layout, cache_inputs=cache_inputs,
            adapter_ids=batch.get("adapter_ids"),
            layer_injections=layer_injections,
            layer_replacements=layer_replacements,
            spec_window_inputs=spec_window_inputs,
        )
    if tensor_replacement and "hidden" in tensor_replacement:
        hidden = jnp.where(
            batch["tr_hidden_mask"][0] > 0,
            batch["tr_hidden"].astype(compute_dtype), hidden,
        )
        hidden = constrain(hidden, policy.hidden)
    pre_norm_hidden = hidden
    if "norm" in params:  # EAGLE drafts have no final norm
        hidden = _norm(arch, hidden, params["norm"])

    lm_head = params.get("lm_head")
    if lm_head is None:  # tied embeddings
        lm_head = jnp.swapaxes(params["embed_tokens"], 0, 1)

    if mixed_rows:
        # packed mixed stream: gather each ROW's newest token off the single
        # packed batch row — everything below (lm_head, stats, sampling)
        # sees (R, 1, hidden) exactly like an R-row decode batch
        idx = batch["last_token_index"].astype(jnp.int32)  # (R,)
        hidden = jnp.take(hidden[0], idx, axis=0)[:, None, :]
    elif gather_last_token and not output_all_logits:
        idx = batch["last_token_index"][:, None, None]  # (B,1,1)
        hidden = jnp.take_along_axis(
            hidden, jnp.broadcast_to(idx, (hidden.shape[0], 1, hidden.shape[2])), axis=1
        )  # (B, 1, hidden)

    logits = (hidden @ lm_head.astype(hidden.dtype)).astype(jnp.float32)
    if "lm_head_bias" in params:  # phi lineage: biased lm_head
        logits = logits + params["lm_head_bias"].astype(jnp.float32)
    if arch.logits_scaling != 1.0:
        logits = logits / arch.logits_scaling
    if arch.final_logit_softcap is not None:
        cap = arch.final_logit_softcap
        logits = cap * jnp.tanh(logits / cap)
    logits = constrain(logits, policy.logits)
    logits = sampling_ops.mask_padded_logits(logits, arch.vocab_pad)

    outputs: Dict[str, jax.Array] = {}
    if tensor_capture:
        if "hidden" in tensor_capture:
            captured["hidden"] = pre_norm_hidden
        if "logits" in tensor_capture:
            captured["logits"] = logits
        outputs["captured"] = captured
    if output_hidden:
        # last-layer hidden BEFORE the final norm — the EAGLE feature stream
        outputs["hidden"] = pre_norm_hidden
    if aux_hidden_indices:
        # (B, S, len(indices)*H) concat of selected layers' outputs (EAGLE3)
        sel = [layer_hiddens[i] for i in aux_hidden_indices]
        outputs["aux_hidden"] = jnp.concatenate(sel, axis=-1)
    if output_all_logits and gather_last_token:
        # still provide the last-position logits for the sampler
        idx = batch["last_token_index"][:, None, None]
        last_logits = jnp.take_along_axis(
            logits, jnp.broadcast_to(idx, (logits.shape[0], 1, logits.shape[2])), axis=1
        )
    else:
        last_logits = logits

    if output_logit_stats:
        # numerics sentinel (TpuConfig(sentinel=...)): a (B, 5) health
        # readout over the sampled position's logit row block, computed
        # in-graph so only five floats per row cross the program boundary
        outputs["logit_stats"] = sampling_ops.logit_health_stats(last_logits)
    if output_argmax_all:
        # speculation verify: the greedy token at EVERY position, selected
        # in-graph — the full-vocab fp32 logits never cross the program
        # boundary, the accept/gather logic downstream runs on (B, S) tokens
        outputs["tokens"] = sampling_ops.greedy_sample(logits)
    if on_device_sampling:
        sample_in = last_logits[:, -1, :]
        if dp_sampling:
            # DataParallelSampler analog (reference: sampling.py:469-569):
            # batch rows shard over the tp world for the top-k stages; GSPMD
            # gathers the sampled tokens
            sample_in = constrain(sample_in, P(AXIS_MP, None))
        tokens = sampling_ops.sample(
            sample_in,
            batch["sampling_params"],
            rng=batch.get("rng"),
            do_sample=do_sample,
            global_topk=global_topk,
            deterministic=deterministic,
        )
        outputs["tokens"] = tokens[:, None]  # (B, 1)
    if output_logits or output_all_logits or (
        not on_device_sampling and not output_argmax_all
    ):
        outputs["logits"] = logits[..., : arch.vocab_size - arch.vocab_pad]

    if return_next_inputs and on_device_sampling:
        # Device-resident generation loop (the analog of the reference's async
        # execution + ranked I/O keeping tensors on device between steps,
        # async_execution.py:131, model_wrapper.py:623): emit the NEXT step's
        # token-generation inputs so the host never touches the hot path.
        nxt: Dict[str, jax.Array] = {
            "input_ids": outputs["tokens"].astype(jnp.int32),
            # next token goes one past each sequence's current last position
            "position_ids": (
                jnp.take_along_axis(
                    position_ids, batch["last_token_index"][:, None], axis=1
                )
                + 1
            ).astype(jnp.int32),
            "last_token_index": jnp.zeros_like(batch["last_token_index"]),
            "sampling_params": batch["sampling_params"],
        }
        if "rng" in batch:
            nxt["rng"] = sampling_ops.next_step_rng(batch["rng"])
        outputs["next_inputs"] = nxt
    return outputs, new_cache


# ---------------------------------------------------------------------------
# Multi-step decode: K token-generation steps in ONE compiled program
# ---------------------------------------------------------------------------

# step-batch keys chained from one in-scan decode step to the next (exactly
# the 1-step program's next_inputs contract)
_MULTISTEP_CHAIN_KEYS = (
    "input_ids", "position_ids", "last_token_index", "sampling_params",
)
# batch keys carried through the scan (and the window-to-window next_inputs)
# unchanged
_MULTISTEP_PASSTHROUGH_KEYS = ("seq_ids", "eos_token_ids", "pad_token_id")


def multi_step_token_gen(
    arch: DecoderArch,
    inv_freq: np.ndarray,
    params: Dict[str, Any],
    cache: Dict[str, jax.Array],
    batch: Dict[str, jax.Array],
    *,
    num_steps: int,
    kv_window: Optional[int] = None,
    policy: ShardingPolicy = DEFAULT_POLICY,
    layout=DEFAULT_KV_LAYOUT,
    do_sample: bool = False,
    global_topk: int = 256,
    deterministic: bool = False,
    dp_sampling: bool = False,
    return_next_inputs: bool = True,
) -> Tuple[Dict[str, jax.Array], Dict[str, jax.Array]]:
    """K decode steps fused into one dispatch (the ``tkg_multistep`` submodel).

    One ``lax.scan`` chains K single-token ``causal_lm_forward`` steps —
    sample -> embed -> layer stack -> deferred KV commit -> position advance —
    entirely on device, so the host dispatches (and XLA enters/exits a
    program) once per K tokens instead of once per token. The per-step
    plumbing is EXACTLY the 1-step program's ``next_inputs`` contract,
    including the :func:`sampling.next_step_rng` key schedule, which makes
    the K-step scan token-identical to K chained 1-step dispatches (greedy
    and sampled).

    ``batch`` extends the decode contract with three optional fixed-shape
    inputs for in-scan EOS/budget handling:
      - ``eos_token_ids`` (B, E) int32, -1 = unused slot: once a row samples
        any of its EOS ids, its later in-window tokens are emitted as
        ``pad_token_id`` and the pad is what feeds the next step — the same
        stream the host-side sync loop produces for finished rows.
      - ``pad_token_id`` (B,) int32.
      - ``budget_steps`` (B,) int32, <= 0 = unlimited: row i may emit at most
        ``budget_steps[i]`` tokens this window, then finishes like EOS. This
        is what lets the serving engine dispatch a window LARGER than the
        smallest per-row remaining budget — near-EOS rows ride along and
        halt per-row instead of degrading the whole batch to 1-step.

    Finished rows (EOS'd or out of budget) freeze: their position stops
    advancing and their KV writes are dropped (negative write positions →
    the layout scatter's drop mode), so a long window can never push a
    finished row's pad-chain garbage over its own last real KV line or out
    of the compiled window.

    Returns outputs with ``tokens`` (B, K) — all K emitted tokens, in order —
    and (optionally) ``next_inputs`` carrying the step-batch for the NEXT
    window plus the passthrough inputs, so windows chain device-resident.
    """
    B = batch["input_ids"].shape[0]
    eos_ids = batch.get("eos_token_ids")  # (B, E) int32; None = no masking
    pad_id = batch.get("pad_token_id")  # (B,) int32
    budget = batch.get("budget_steps")  # (B,) int32; None/<=0 = unlimited
    passthrough = {
        k: batch[k] for k in _MULTISTEP_PASSTHROUGH_KEYS if k in batch
    }

    step0 = {k: batch[k] for k in _MULTISTEP_CHAIN_KEYS}
    if "rng" in batch:
        step0["rng"] = batch["rng"]

    def step(carry, t):
        sbatch, done, kvc = carry
        fwd_batch = dict(passthrough)
        fwd_batch.update(sbatch)
        fwd_batch["write_positions"] = jnp.where(
            done[:, None], jnp.int32(-1), sbatch["position_ids"]
        )
        out, kvc = causal_lm_forward(
            arch,
            inv_freq,
            params,
            kvc,
            fwd_batch,
            attend_to_cache=True,
            kv_window=kv_window,
            policy=policy,
            layout=layout,
            gather_last_token=False,
            on_device_sampling=True,
            do_sample=do_sample,
            global_topk=global_topk,
            deterministic=deterministic,
            dp_sampling=dp_sampling,
            return_next_inputs=True,
        )
        nxt = out["next_inputs"]
        tok = out["tokens"][:, 0]  # (B,)
        if eos_ids is not None:
            # finished rows emit (and feed forward) the pad token; a row
            # finishes the step AFTER its EOS is emitted, so the EOS itself
            # always lands in the output — the sync host loop's semantics
            pad = (
                pad_id.astype(tok.dtype)
                if pad_id is not None
                else jnp.zeros_like(tok)
            )
            emitted = jnp.where(done, pad, tok)
            done = done | jnp.any(emitted[:, None] == eos_ids, axis=1)
        else:
            emitted = tok
        if budget is not None:
            # the budget-hit token itself is real (the host's "length"
            # finish emits it); only LATER steps are frozen out
            done = done | ((budget > 0) & (t + 1 >= budget))
        new_sbatch = {
            "input_ids": emitted[:, None].astype(jnp.int32),
            "position_ids": jnp.where(
                done[:, None], sbatch["position_ids"], nxt["position_ids"]
            ),
            "last_token_index": nxt["last_token_index"],
            "sampling_params": nxt["sampling_params"],
        }
        if "rng" in sbatch:
            new_sbatch["rng"] = nxt["rng"]
        return (new_sbatch, done, kvc), emitted

    done0 = jnp.zeros((B,), bool)
    (step_k, _, cache), toks = jax.lax.scan(
        step, (step0, done0, cache), jnp.arange(num_steps, dtype=jnp.int32)
    )
    outputs: Dict[str, jax.Array] = {"tokens": jnp.swapaxes(toks, 0, 1)}  # (B, K)
    if return_next_inputs:
        nxt = dict(step_k)
        nxt.update(passthrough)
        outputs["next_inputs"] = nxt
    return outputs, cache


# ---------------------------------------------------------------------------
# Device-resident decode loop: while-loop with per-row EOS/budget exit
# ---------------------------------------------------------------------------


def device_loop_token_gen(
    arch: DecoderArch,
    inv_freq: np.ndarray,
    params: Dict[str, Any],
    cache: Dict[str, jax.Array],
    batch: Dict[str, jax.Array],
    *,
    max_steps: int,
    kv_window: Optional[int] = None,
    policy: ShardingPolicy = DEFAULT_POLICY,
    layout=DEFAULT_KV_LAYOUT,
    do_sample: bool = False,
    global_topk: int = 256,
    deterministic: bool = False,
    dp_sampling: bool = False,
    outfeed: Optional[Any] = None,
) -> Tuple[Dict[str, jax.Array], Dict[str, jax.Array]]:
    """The ``tkg_device_loop`` submodel: a ``lax.while_loop`` whose body is
    one full sample -> embed -> layer stack -> KV-commit decode step, exiting
    as soon as EVERY row has sampled one of its EOS ids or exhausted its
    per-row token budget. Unlike the fixed-rung scan (``tkg_multistep``) the
    iteration count is data-dependent: a batch with heterogeneous remaining
    budgets runs ONE dispatch and each row halts exactly where the host loop
    would have stopped it — the host never re-enters the hot path to referee.

    Contract (all of ``multi_step_token_gen``'s, plus):
      - ``max_steps`` is the STATIC capacity of the token out-buffer
        (B, max_steps); the loop exits early once all rows are done, so the
        cap bounds — never schedules — the work.
      - ``budget_steps`` (B,) int32, <= 0 = unlimited: per-row emission
        budget; the budget-hit token itself is emitted (the host's "length"
        finish semantics).
      - sampling keys are COUNTER-BASED: iteration t draws with
        ``batch["rng"] + [0, t]`` — i.e. the host ``StepRngSchedule``'s own
        ``(seed, counter + t)`` sequence — so a fixed-seed sampled loop
        reproduces N chained 1-step engine dispatches token-for-token (the
        host advances its counter by the returned ``loop_iters - 1``).
      - ``outfeed``, when given, is a host callable ``(t, tokens, done)``
        invoked per iteration via an unordered ``io_callback`` — the
        device→host token out-feed ring. The (B, max_steps) result buffer is
        ALWAYS returned too, so CPU/interpret runs (and tier-1) stay exact
        without the ring.

    Finished rows freeze exactly like the scan: pad-token feed-forward,
    position pinned, KV writes dropped via negative write positions.

    Returns outputs with ``tokens`` (B, max_steps) — entries past a row's
    halt point are ``pad_token_id`` — and ``loop_iters`` (scalar int32), the
    number of body iterations the loop actually ran.
    """
    from jax.experimental import io_callback

    B = batch["input_ids"].shape[0]
    eos_ids = batch.get("eos_token_ids")
    pad_id = batch.get("pad_token_id")
    budget = batch.get("budget_steps")
    base_rng = batch.get("rng")
    passthrough = {
        k: batch[k] for k in _MULTISTEP_PASSTHROUGH_KEYS if k in batch
    }

    step0 = {k: batch[k] for k in _MULTISTEP_CHAIN_KEYS}
    pad0 = (
        pad_id.astype(jnp.int32)
        if pad_id is not None
        else jnp.zeros((B,), jnp.int32)
    )
    toks0 = jnp.broadcast_to(pad0[:, None], (B, max_steps)).astype(jnp.int32)

    def cond(carry):
        t, done, _sbatch, _toks, _kvc = carry
        return (t < max_steps) & ~jnp.all(done)

    def body(carry):
        t, done, sbatch, toks, kvc = carry
        fwd_batch = dict(passthrough)
        fwd_batch.update(sbatch)
        fwd_batch["write_positions"] = jnp.where(
            done[:, None], jnp.int32(-1), sbatch["position_ids"]
        )
        if base_rng is not None:
            # counter-based key schedule: one host counter per iteration
            fwd_batch["rng"] = base_rng + jnp.array(
                [0, 1], jnp.uint32
            ) * t.astype(jnp.uint32)
        out, kvc = causal_lm_forward(
            arch,
            inv_freq,
            params,
            kvc,
            fwd_batch,
            attend_to_cache=True,
            kv_window=kv_window,
            policy=policy,
            layout=layout,
            gather_last_token=False,
            on_device_sampling=True,
            do_sample=do_sample,
            global_topk=global_topk,
            deterministic=deterministic,
            dp_sampling=dp_sampling,
            return_next_inputs=True,
        )
        nxt = out["next_inputs"]
        tok = out["tokens"][:, 0]  # (B,)
        emitted = jnp.where(done, pad0.astype(tok.dtype), tok)
        if eos_ids is not None:
            done = done | jnp.any(emitted[:, None] == eos_ids, axis=1)
        if budget is not None:
            done = done | ((budget > 0) & (t + 1 >= budget))
        toks = jax.lax.dynamic_update_slice(
            toks, emitted[:, None].astype(jnp.int32), (0, t)
        )
        if outfeed is not None:
            # unordered: iteration index t rides along so the host ring can
            # reassemble order without serializing the loop on the callback
            io_callback(outfeed, None, t, emitted, done, ordered=False)
        new_sbatch = {
            "input_ids": emitted[:, None].astype(jnp.int32),
            "position_ids": jnp.where(
                done[:, None], sbatch["position_ids"], nxt["position_ids"]
            ),
            "last_token_index": nxt["last_token_index"],
            "sampling_params": nxt["sampling_params"],
        }
        return (t + 1, done, new_sbatch, toks, kvc)

    done0 = jnp.zeros((B,), bool)
    t_end, _done, _sbatch, toks, cache = jax.lax.while_loop(
        cond, body, (jnp.int32(0), done0, step0, toks0, cache)
    )
    return {"tokens": toks, "loop_iters": t_end}, cache
