"""Mistral family (7B v0.1/v0.2, Ministral...).

Llama-lineage dense decoder with optional sliding-window attention
(reference handles SWA via the sliding-window kernel + windowed KV,
modules/sliding_window/attention.py and attention_base.py:3080; here the
window is a mask family in ops/attention.py plus the same full-length cache).
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from nxdi_tpu.config import InferenceConfig
from nxdi_tpu.models import dense
from nxdi_tpu.models.base import DecoderArch

build_inv_freq = dense.build_inv_freq


class MistralInferenceConfig(dense.DenseInferenceConfig):
    pass


def build_arch(config: InferenceConfig, **overrides) -> DecoderArch:
    sw = getattr(config, "sliding_window", None)
    return dense.build_arch(config, **{"sliding_window": sw, **overrides})


def convert_hf_state_dict(
    state_dict: Dict[str, np.ndarray], config: InferenceConfig
) -> Dict[str, Any]:
    return dense.convert_hf_state_dict(state_dict, config, build_arch(config))


def param_specs(config: InferenceConfig):
    return dense.param_specs_for(build_arch(config))


