"""Mllama application — vision encoder + cross-attention CausalLM.

Reference: NeuronMllamaForCausalLM (models/mllama/modeling_mllama.py:1083)
and its model wrapper (model_wrapper_mllama.py): a vision submodel feeds
cross-attention states into CTE; decode reads the cross-KV cache written at
prefill. Here the cross-KV are two extra entries in the donated cache pytree
(the reference's MultimodalKVCache as explicit state)."""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import numpy as np

from nxdi_tpu.models.cross_attention_app import CrossAttentionVLApplication
from nxdi_tpu.models.mllama import modeling_mllama as mm
from nxdi_tpu.runtime.model_wrapper import TAG_CONTEXT_ENCODING


class MllamaApplication(CrossAttentionVLApplication):
    FAMILY_NAME = "mllama"

    def __init__(self, *args, **kwargs):
        kwargs.setdefault("model_family", mm)
        super().__init__(*args, **kwargs)
        self._reject_unsupported()
        self._encode_jit = None
        # last prompt cross-mask row per batch line (HF generation repeats it
        # for every generated token, modeling_mllama.py:1732)
        self._last_xmask: Optional[np.ndarray] = None
        # static across the app's life; avoid rebuilding per decode dispatch
        self._arch = mm.build_arch(self.config)

    def _cross_kv_shape(self):
        arch = self._arch
        t = arch.text
        B = self.tpu_config.kv_cache_batch_size + self.tpu_config.kv_cache_padding_size
        return (arch.n_cross, B, t.num_kv_heads, arch.t_vis, t.head_dim)

    # -- submodels --
    def enable_models(self) -> None:
        import jax.numpy as jnp

        super().enable_models()
        arch = mm.build_arch(self.config)
        H = self.config.hidden_size
        MT = arch.max_tiles_total
        for tag, w in self.models.items():
            w.forward_fn = mm.causal_lm_forward
            # the mllama forward does not implement these base-fn kwargs
            w.forward_kwargs.pop("output_all_logits", None)
            w.forward_kwargs.pop("tensor_capture", None)
            w.forward_kwargs.pop("return_next_inputs", None)
            if w.forward_kwargs.pop("dp_sampling", False):
                raise NotImplementedError(
                    "mllama does not support dp_sampling yet"
                )
            if tag == TAG_CONTEXT_ENCODING:
                w.extra_inputs["cross_states"] = ((arch.t_vis, H), jnp.float32)
                w.extra_inputs["cross_attention_mask"] = (
                    (self.tpu_config.max_context_length, MT), jnp.float32,
                )
            else:
                w.extra_inputs["cross_attention_mask"] = ((1, MT), jnp.float32)

    # -- vision program --
    def encode_images(self, pixel_values, aspect_ratio_ids, aspect_ratio_mask):
        if self._encode_jit is None:
            varch = mm.build_vision_arch(self.config)
            self._encode_jit = jax.jit(partial(mm.encode_images, varch))
        with jax.set_mesh(self.mesh):
            return self._encode_jit(
                {"vision": self.params["vision"], "projector": self.params["projector"]},
                np.asarray(pixel_values, np.float32),
                np.asarray(aspect_ratio_ids, np.int32),
                np.asarray(aspect_ratio_mask, np.float32),
            )

    # -- dispatch --
    def forward(
        self,
        input_ids,
        position_ids,
        pixel_values=None,
        aspect_ratio_ids=None,
        aspect_ratio_mask=None,
        cross_attention_mask=None,
        **kwargs,
    ):
        MT = self._arch.max_tiles_total
        B, S = np.asarray(input_ids).shape
        is_prefill = S > 1
        if is_prefill:
            if pixel_values is None:
                raise NotImplementedError(
                    "mllama prefill requires images (text-only prefill would "
                    "need a cross-layer-free compiled variant)"
                )
            kwargs["cross_states"] = np.asarray(
                self.encode_images(pixel_values, aspect_ratio_ids, aspect_ratio_mask)
            )
            if cross_attention_mask is None:
                raise ValueError("cross_attention_mask is required at prefill")
            xm = np.asarray(cross_attention_mask, np.float32)  # (B, S, M, T) or (B, S, MT)
            xm = xm.reshape(B, xm.shape[1], -1)[:, :, :MT]
            S_cap = self.tpu_config.max_context_length
            pad = np.zeros((B, S_cap, MT), np.float32)
            pad[:, : xm.shape[1]] = xm[:, :S_cap]
            kwargs["cross_attention_mask"] = pad
            lti = kwargs.get("last_token_index")
            last = (
                np.asarray(lti, np.int64)
                if lti is not None
                else np.full((B,), xm.shape[1] - 1, np.int64)
            )
            self._last_xmask = xm[np.arange(B), np.minimum(last, xm.shape[1] - 1)]
        else:
            if cross_attention_mask is not None:
                xm = np.asarray(cross_attention_mask, np.float32).reshape(B, 1, -1)[:, :, :MT]
            elif self._last_xmask is not None:
                xm = self._last_xmask[:B].reshape(B, 1, MT)
            else:
                raise ValueError("decode before prefill: no cross_attention_mask available")
            kwargs["cross_attention_mask"] = xm
        return super().forward(input_ids, position_ids, **kwargs)
