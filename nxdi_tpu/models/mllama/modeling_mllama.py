"""Mllama (Llama-3.2 Vision) — tiled ViT encoder + cross-attention llama.

Reference: models/mllama/modeling_mllama.py (cross-attention text stack,
fusion schedule every-Nth layer), modeling_mllama_vision.py (two-stage tiled
ViT), and the cross-attn KV manager modules/kvcache/multimodal_kv_cache_manager.py.
Semantics follow the HF ``MllamaForConditionalGeneration`` graph exactly so
tiny-model greedy tokens match.

TPU-native layout:
  - text self-attention layers are the shared dense decoder (models/base.py)
    scanned in contiguous SEGMENTS between cross-attention layers; cross
    layers are unrolled (there are few — 8 in the 11B) with their own
    stacked params.
  - cross-attention K/V are computed ONCE at prefill from the vision
    features and live in the donated cache pytree as ``cross_k``/``cross_v``
    shaped (n_cross, B, KV, T_vis, D) — the reference's MultimodalKVCache
    (multimodal_kv_cache_manager.py:18) as explicit state. Decode reads them;
    the self-attn KV cache behaves exactly as in the dense decoder.
  - the vision tower (local transformer + gated global transformer, gated
    tile/position embeddings) runs as its own jitted program; patchify is a
    reshape+matmul (stride == kernel), so everything rides the MXU.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from nxdi_tpu.config import InferenceConfig, promote_text_config, to_jax_dtype
from nxdi_tpu.models import dense
from nxdi_tpu.models.base import (
    DEFAULT_KV_LAYOUT,
    DecoderArch,
    constrain,
    rms_norm,
    run_decoder_layers,
)
from nxdi_tpu.ops import attention as attn_ops
from nxdi_tpu.ops import sampling as sampling_ops
from nxdi_tpu.ops.norms import layer_norm
from nxdi_tpu.ops.rope import rope_cos_sin
from nxdi_tpu.models.dense import gqa_plan
from nxdi_tpu.parallel import gqa
from nxdi_tpu.parallel.layers import COLUMN_PARALLEL, REPLICATED, ROW_PARALLEL
from nxdi_tpu.parallel.policy import DEFAULT_POLICY


class MllamaInferenceConfig(dense.DenseInferenceConfig):
    REQUIRED = ["text_config", "vision_config", "image_token_index"]

    def add_derived_config(self):
        promote_text_config(self)
        vc = self.vision_config
        if not isinstance(vc, dict):
            self.vision_config = vc.to_dict()
        super().add_derived_config()


# ---------------------------------------------------------------------------
# Arch
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class MllamaArch:
    """Static composite: dense text arch over the SELF layers only, plus the
    fusion schedule (reference: cross_attention_layers, modeling_mllama.py
    fusion schedule _init_fusion_schedule :747)."""

    text: DecoderArch
    # alternating walk: ("self", start, end) half-open self-layer ranges in
    # the stacked self params / self KV cache; ("cross", ordinal) unrolled
    schedule: Tuple[Tuple, ...]
    n_cross: int
    num_patches: int  # per tile, INCLUDING the cls token
    t_vis: int  # total vision tokens per text row = media*tiles*num_patches
    max_tiles_total: int  # media * tiles (cross-mask width)
    image_token_index: int

    def kv_cache_spec(self, batch_size, max_len, quant_dtype=None):
        return self.text.kv_cache_spec(batch_size, max_len, quant_dtype=quant_dtype)


def _cross_layer_indices(config: InferenceConfig) -> Tuple[int, ...]:
    return tuple(config.cross_attention_layers)


def build_arch(config: InferenceConfig) -> MllamaArch:
    cross = _cross_layer_indices(config)
    n_total = config.num_hidden_layers
    n_self = n_total - len(cross)
    text = dense.build_arch(config, num_layers=n_self)
    schedule = []
    s = 0
    for i in range(n_total):
        if i in cross:
            schedule.append(("cross", cross.index(i)))
        else:
            if schedule and schedule[-1][0] == "self":
                schedule[-1] = ("self", schedule[-1][1], schedule[-1][2] + 1)
            else:
                schedule.append(("self", s, s + 1))
            s += 1
    vc = config.vision_config
    num_patches = (vc["image_size"] // vc["patch_size"]) ** 2 + 1
    max_media = int(getattr(config.tpu_config, "max_num_images", 1) or 1)
    tiles = vc["max_num_tiles"]
    return MllamaArch(
        text=text,
        schedule=tuple(tuple(x) for x in schedule),
        n_cross=len(cross),
        num_patches=num_patches,
        t_vis=max_media * tiles * num_patches,
        max_tiles_total=max_media * tiles,
        image_token_index=config.image_token_index,
    )


def _self_count_before(cross, i):
    return i - sum(1 for c in cross if c < i)


def build_inv_freq(config: InferenceConfig) -> np.ndarray:
    return dense.build_inv_freq(config)


# ---------------------------------------------------------------------------
# Vision tower
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class MllamaVisionArch:
    hidden_size: int
    intermediate_size: int
    num_layers: int
    num_global_layers: int
    num_heads: int
    image_size: int
    patch_size: int
    num_channels: int
    max_num_tiles: int
    max_aspect_ratio_id: int
    intermediate_layers_indices: Tuple[int, ...]
    norm_eps: float
    vision_output_dim: int
    text_hidden: int

    @property
    def num_patches(self) -> int:  # per tile, incl cls
        return (self.image_size // self.patch_size) ** 2 + 1

    @property
    def padded_patches(self) -> int:  # HF pads the patch dim to %8
        return self.num_patches + (8 - self.num_patches % 8) % 8


def build_vision_arch(config: InferenceConfig) -> MllamaVisionArch:
    vc = config.vision_config
    sar = vc.get("supported_aspect_ratios") or [[1, 1]]
    return MllamaVisionArch(
        hidden_size=vc["hidden_size"],
        intermediate_size=vc["intermediate_size"],
        num_layers=vc["num_hidden_layers"],
        num_global_layers=vc["num_global_layers"],
        num_heads=vc["attention_heads"],
        image_size=vc["image_size"],
        patch_size=vc["patch_size"],
        num_channels=vc.get("num_channels", 3),
        max_num_tiles=vc["max_num_tiles"],
        max_aspect_ratio_id=vc.get("max_aspect_ratio_id", len(sar)),
        intermediate_layers_indices=tuple(vc["intermediate_layers_indices"]),
        norm_eps=vc.get("norm_eps", 1e-5),
        vision_output_dim=vc["vision_output_dim"],
        text_hidden=config.hidden_size,
    )


def _vit_layer(varch: MllamaVisionArch, lp, h, additive_mask, gated: bool):
    """One vision encoder layer (HF MllamaVisionEncoderLayer semantics:
    pre-LN attn + MLP, optional tanh gates on both residual branches)."""
    B, S, Hv = h.shape
    nh = varch.num_heads
    d = Hv // nh

    y = layer_norm(h, lp["ln1"]["w"], lp["ln1"]["b"], eps=varch.norm_eps)
    q = (y @ lp["q_proj"]["w"]).reshape(B, S, nh, d).transpose(0, 2, 1, 3)
    k = (y @ lp["k_proj"]["w"]).reshape(B, S, nh, d).transpose(0, 2, 1, 3)
    v = (y @ lp["v_proj"]["w"]).reshape(B, S, nh, d).transpose(0, 2, 1, 3)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32)
    s = s * (d ** -0.5) + additive_mask
    w = jax.nn.softmax(s, axis=-1).astype(v.dtype)
    attn = jnp.einsum("bhqk,bhkd->bhqd", w, v).transpose(0, 2, 1, 3).reshape(B, S, Hv)
    attn = attn @ lp["o_proj"]["w"]
    if gated:
        attn = jnp.tanh(lp["gate_attn"]) * attn
    h = h + attn

    y = layer_norm(h, lp["ln2"]["w"], lp["ln2"]["b"], eps=varch.norm_eps)
    ff = jax.nn.gelu(y @ lp["fc1"]["w"] + lp["fc1"]["b"], approximate=False)
    ff = ff @ lp["fc2"]["w"] + lp["fc2"]["b"]
    if gated:
        ff = jnp.tanh(lp["gate_ffn"]) * ff
    return h + ff


def encode_images(
    varch: MllamaVisionArch,
    params: Dict[str, Any],
    pixel_values,  # (B, M, T, C, Himg, Wimg)
    aspect_ratio_ids,  # (B, M) int32
    aspect_ratio_mask,  # (B, M, T)
):
    """HF MllamaVisionModel.forward + multi_modal_projector, returning
    cross-attention states (B, M*T*num_patches, text_hidden)."""
    v = params["vision"]
    B, M, T, C, HI, WI = pixel_values.shape
    P = varch.patch_size
    g = HI // P
    Hv = varch.hidden_size
    np_tile = varch.num_patches  # incl cls
    pad_p = varch.padded_patches

    # patchify: (BMT, C, g, P, g, P) -> (BMT, g*g, C*P*P) @ W
    x = pixel_values.reshape(B * M * T, C, g, P, g, P)
    x = x.transpose(0, 2, 4, 1, 3, 5).reshape(B * M * T, g * g, C * P * P)
    h = x @ v["patch_embedding"]  # (BMT, g*g, Hv)

    ar_ids = aspect_ratio_ids.reshape(B * M)
    # pre-tile positional embedding (gated)
    pre = jnp.take(v["pre_tile_pos"]["emb"], ar_ids, axis=0).reshape(B * M, varch.max_num_tiles, 1, Hv)
    h = h.reshape(B * M, T, g * g, Hv) + pre[:, :T] * jnp.tanh(v["pre_tile_pos"]["gate"])

    # cls token first
    h = h.reshape(B * M * T, g * g, Hv)
    cls = jnp.broadcast_to(v["class_embedding"][None, None, :], (B * M * T, 1, Hv))
    h = jnp.concatenate([cls, h], axis=1)  # (BMT, np_tile, Hv)

    # gated position embedding
    gate = jnp.tanh(v["pos_gate"])
    h = h + (1.0 - gate) * v["pos_embedding"][None]
    tile_pos = jnp.take(v["tile_pos_emb"], ar_ids, axis=0).reshape(
        B * M, varch.max_num_tiles, np_tile, Hv
    )
    h = h.reshape(B * M, T, np_tile, Hv) + gate * tile_pos[:, :T]

    h = layer_norm(h, v["ln_pre"]["w"], v["ln_pre"]["b"], eps=1e-5)

    # pad patch dim to %8 and build the HF aspect-ratio attention mask:
    # additive MIN where BOTH query and key slots are invalid (HF quirk —
    # _prepare_aspect_ratio_attention_mask modeling_mllama.py:76)
    h = jnp.pad(h, ((0, 0), (0, 0), (0, pad_p - np_tile), (0, 0)))
    valid = jnp.broadcast_to(
        aspect_ratio_mask.reshape(B * M, T, 1).astype(jnp.float32), (B * M, T, pad_p)
    )
    valid = valid * (jnp.arange(pad_p)[None, None, :] < np_tile)
    inv = (1.0 - valid).reshape(B * M, T * pad_p, 1)
    additive = (inv @ jnp.swapaxes(inv, 1, 2)) * jnp.float32(-3.4028235e38)
    additive = additive[:, None]  # (BM, 1, T*pad, T*pad)

    h = h.reshape(B * M, T * pad_p, Hv)

    def local_body(carry, lp):
        out = _vit_layer(varch, lp, carry, additive, gated=False)
        return out, out

    h, layer_outs = jax.lax.scan(local_body, h, v["layers"])
    intermediates = jnp.stack(
        [layer_outs[i] for i in varch.intermediate_layers_indices], axis=-1
    )  # (BM, T*pad, Hv, n_int)

    h = layer_norm(h, v["ln_post"]["w"], v["ln_post"]["b"], eps=1e-5)

    post = jnp.take(v["post_tile_pos"]["emb"], ar_ids, axis=0).reshape(
        B * M, varch.max_num_tiles, 1, Hv
    )
    h = h.reshape(B * M, T, pad_p, Hv) + post[:, :T] * jnp.tanh(v["post_tile_pos"]["gate"])
    h = h.reshape(B * M, T * pad_p, Hv)

    def global_body(carry, lp):
        return _vit_layer(varch, lp, carry, additive, gated=True), None

    h, _ = jax.lax.scan(global_body, h, v["global_layers"])

    # strip patch padding, concat intermediates -> vision_output_dim
    h = h.reshape(B * M, T, pad_p, Hv)[:, :, :np_tile]
    inter = intermediates.reshape(B * M, T, pad_p, -1)[:, :, :np_tile]
    feat = jnp.concatenate([h, inter], axis=-1)  # (BM, T, np_tile, vision_output_dim)

    proj = params["projector"]
    states = feat @ proj["w"] + proj["b"]  # (BM, T, np_tile, text_hidden)
    return states.reshape(B, M * T * np_tile, varch.text_hidden)


# ---------------------------------------------------------------------------
# Text forward
# ---------------------------------------------------------------------------


def _cross_attention_layer(
    t: DecoderArch,
    lp: Dict[str, Any],
    hidden,  # (B, S, H)
    xk,  # (B, KV, Tv, D)
    xv,
    attend,  # (B, S, Tv) bool
    full_row,  # (B, S, 1) float
    policy,
):
    """HF MllamaCrossAttentionDecoderLayer: q-normed cross attention with a
    tanh attn gate, MLP row-masked by full_text_row then tanh mlp gate."""
    B, S, _ = hidden.shape
    H, KV, D = t.num_attention_heads, t.num_kv_heads, t.head_dim

    y = rms_norm(hidden, lp["input_layernorm"], t.rms_norm_eps)
    q = (y @ lp["attn"]["q_proj"]["w"]).reshape(B, S, H, D)
    q = rms_norm(q, lp["attn"]["q_norm"], t.rms_norm_eps)
    q = jnp.swapaxes(q, 1, 2)  # (B, H, S, D)
    q = constrain(q, policy.q)

    ctx = attn_ops.grouped_attention(q, xk, xv, attend, softmax_dtype=jnp.float32)
    ctx = jnp.swapaxes(ctx, 1, 2).reshape(B, S, H * D)
    attn_out = ctx @ lp["attn"]["o_proj"]["w"]
    hidden = hidden + jnp.tanh(lp["gate_attn"]) * attn_out

    y = rms_norm(hidden, lp["post_attention_layernorm"], t.rms_norm_eps)
    from nxdi_tpu.models.base import mlp_block

    ff = mlp_block(t, lp["mlp"], y)
    ff = ff * full_row.astype(ff.dtype)
    hidden = hidden + jnp.tanh(lp["gate_mlp"]) * ff
    return constrain(hidden, policy.hidden)


def _compute_cross_kv(t: DecoderArch, lp, cross_states, policy):
    """k/v projections of the vision states with per-head k-norm (HF
    MllamaTextCrossAttention._compute / k_norm semantics)."""
    B, Tv, _ = cross_states.shape
    KV, D = t.num_kv_heads, t.head_dim
    k = (cross_states @ lp["attn"]["k_proj"]["w"]).reshape(B, Tv, KV, D)
    v = (cross_states @ lp["attn"]["v_proj"]["w"]).reshape(B, Tv, KV, D)
    k = rms_norm(k, lp["attn"]["k_norm"], t.rms_norm_eps)
    k = jnp.swapaxes(k, 1, 2)  # (B, KV, Tv, D)
    v = jnp.swapaxes(v, 1, 2)
    return constrain(k, policy.kv), constrain(v, policy.kv)


def causal_lm_forward(
    arch: MllamaArch,
    inv_freq: np.ndarray,
    params: Dict[str, Any],
    cache: Dict[str, jax.Array],
    batch: Dict[str, jax.Array],
    *,
    attend_to_cache: bool,
    kv_window: Optional[int] = None,
    policy=DEFAULT_POLICY,
    layout=DEFAULT_KV_LAYOUT,
    gather_last_token: bool = True,
    output_logits: bool = False,
    on_device_sampling: bool = True,
    do_sample: bool = False,
    global_topk: int = 256,
    deterministic: bool = False,
):
    """One submodel forward (reference: NeuronMllamaTextModel.forward,
    modeling_mllama.py:819): dense self-attn segments + unrolled gated
    cross-attn layers walking the fusion schedule."""
    t = arch.text
    compute_dtype = to_jax_dtype(t.dtype)
    input_ids = batch["input_ids"]
    position_ids = batch["position_ids"]
    B, S = input_ids.shape

    hidden = jnp.take(params["embed_tokens"], input_ids, axis=0).astype(compute_dtype)
    hidden = constrain(hidden, policy.hidden)
    cos, sin = rope_cos_sin(position_ids, np.asarray(inv_freq), dtype=jnp.float32)

    cache_spec = t.kv_cache_spec(cache["k"].shape[1], cache["k"].shape[3])

    # cross mask rows for the active tokens: (B, S_fixed, MT) -> (B, S, Tv)
    xmask = batch["cross_attention_mask"][:, :S].astype(jnp.float32)
    attend = jnp.repeat(xmask, arch.num_patches, axis=2) > 0  # (B, S, Tv)
    full_row = jnp.any(attend, axis=-1, keepdims=True).astype(jnp.float32)
    # HF cancels the mask for rows that attend nothing (full-row masking):
    # all-False rows already softmax uniformly over every vision token,
    # which is exactly the canceled-mask result — no special case needed.

    if attend_to_cache:
        xk_all, xv_all = cache["cross_k"], cache["cross_v"]
    else:
        xk_list, xv_list = [], []

    k_segs, v_segs = [], []
    for item in arch.schedule:
        if item[0] == "self":
            _, lo, hi = item
            seg = jax.tree_util.tree_map(lambda x: x[lo:hi], params["layers"])
            k_sl = jax.lax.slice_in_dim(cache["k"], lo, hi, axis=0)
            v_sl = jax.lax.slice_in_dim(cache["v"], lo, hi, axis=0)
            hidden, seg_cache = run_decoder_layers(
                t, seg, hidden, cos, sin, {"k": k_sl, "v": v_sl},
                position_ids, cache_spec, attend_to_cache, kv_window=kv_window,
                policy=policy, layout=layout,
            )
            k_segs.append(seg_cache["k"])
            v_segs.append(seg_cache["v"])
        else:
            _, ordinal = item
            lp = jax.tree_util.tree_map(lambda x: x[ordinal], params["cross"])
            if attend_to_cache:
                xk = xk_all[ordinal].astype(compute_dtype)
                xv = xv_all[ordinal].astype(compute_dtype)
            else:
                xk, xv = _compute_cross_kv(
                    t, lp, batch["cross_states"].astype(compute_dtype), policy
                )
                xk_list.append(xk)
                xv_list.append(xv)
            hidden = _cross_attention_layer(
                t, lp, hidden, xk, xv, attend, full_row, policy
            )

    new_cache = {
        "k": jnp.concatenate(k_segs, axis=0) if len(k_segs) > 1 else k_segs[0],
        "v": jnp.concatenate(v_segs, axis=0) if len(v_segs) > 1 else v_segs[0],
    }
    if attend_to_cache:
        new_cache["cross_k"], new_cache["cross_v"] = xk_all, xv_all
    else:
        store = cache["cross_k"].dtype
        new_cache["cross_k"] = jnp.stack(xk_list).astype(store)
        new_cache["cross_v"] = jnp.stack(xv_list).astype(store)

    hidden = rms_norm(hidden, params["norm"], t.rms_norm_eps)
    lm_head = params.get("lm_head")
    if lm_head is None:
        lm_head = jnp.swapaxes(params["embed_tokens"], 0, 1)
    if gather_last_token:
        idx = batch["last_token_index"][:, None, None]
        hidden = jnp.take_along_axis(
            hidden, jnp.broadcast_to(idx, (B, 1, hidden.shape[2])), axis=1
        )
    logits = (hidden @ lm_head.astype(hidden.dtype)).astype(jnp.float32)
    logits = constrain(logits, policy.logits)
    logits = sampling_ops.mask_padded_logits(logits, t.vocab_pad)

    outputs: Dict[str, jax.Array] = {}
    if on_device_sampling:
        outputs["tokens"] = sampling_ops.sample(
            logits[:, -1, :],
            batch["sampling_params"],
            rng=batch.get("rng"),
            do_sample=do_sample,
            global_topk=global_topk,
            deterministic=deterministic,
        )[:, None]
    if output_logits or not on_device_sampling:
        outputs["logits"] = logits
    return outputs, new_cache


# ---------------------------------------------------------------------------
# Checkpoint conversion
# ---------------------------------------------------------------------------


def _text_sd(state_dict: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    out = {}
    for k, v in state_dict.items():
        for prefix in ("model.language_model.", "language_model.model.", "language_model."):
            if k.startswith(prefix):
                out[k[len(prefix):]] = v
                break
        else:
            if k in ("lm_head.weight", "language_model.lm_head.weight"):
                out["lm_head.weight"] = v
    return out


def convert_hf_state_dict(
    state_dict: Dict[str, np.ndarray], config: InferenceConfig
) -> Dict[str, Any]:
    arch = build_arch(config)
    t = arch.text
    sd = _text_sd(state_dict)
    cross = _cross_layer_indices(config)

    # renumber SELF layers contiguously and convert them with the dense
    # converter (handles GQA padding/replication + vocab pad)
    sd_self = {}
    s = 0
    for i in range(config.num_hidden_layers):
        if i in cross:
            continue
        pre = f"layers.{i}."
        for k, v in sd.items():
            if k.startswith(pre):
                sd_self[f"layers.{s}." + k[len(pre):]] = v
        s += 1
    for k, v in sd.items():
        if not k.startswith("layers."):
            sd_self[k] = v
    params = dense.convert_hf_state_dict(sd_self, config, t)

    # cross layers: stacked over their ordinals
    dt = dense.np_dtype(t.dtype)
    plan = gqa_plan(config)
    D = t.head_dim
    cast = lambda x: np.asarray(x, dtype=dt)  # noqa: E731
    cross_layers = []
    for i in cross:
        pre = f"layers.{i}."

        def get(name):
            return sd[pre + name]

        cross_layers.append({
            "input_layernorm": cast(get("input_layernorm.weight")),
            "post_attention_layernorm": cast(get("post_attention_layernorm.weight")),
            "gate_attn": cast(get("cross_attn_attn_gate")),
            "gate_mlp": cast(get("cross_attn_mlp_gate")),
            "attn": {
                "q_proj": {"w": cast(gqa.convert_q(get("cross_attn.q_proj.weight"), D, plan).T)},
                "k_proj": {"w": cast(gqa.convert_kv(get("cross_attn.k_proj.weight"), D, plan).T)},
                "v_proj": {"w": cast(gqa.convert_kv(get("cross_attn.v_proj.weight"), D, plan).T)},
                "o_proj": {"w": cast(gqa.convert_o(get("cross_attn.o_proj.weight"), D, plan).T)},
                "q_norm": cast(get("cross_attn.q_norm.weight")),
                "k_norm": cast(get("cross_attn.k_norm.weight")),
            },
            "mlp": {
                "gate_proj": {"w": cast(get("mlp.gate_proj.weight").T)},
                "up_proj": {"w": cast(get("mlp.up_proj.weight").T)},
                "down_proj": {"w": cast(get("mlp.down_proj.weight").T)},
            },
        })
    params["cross"] = dense.tree_stack(cross_layers)
    return params


def convert_vision_params(
    state_dict: Dict[str, np.ndarray], config: InferenceConfig
) -> Dict[str, Any]:
    varch = build_vision_arch(config)

    def get(name):
        for k in (f"model.{name}", name):
            if k in state_dict:
                return state_dict[k]
        raise KeyError(f"missing vision weight {name}")

    def f32(x):
        return np.asarray(x, np.float32)

    Hv = varch.hidden_size

    def vit_layers(prefix, n, gated):
        layers = []
        for i in range(n):
            p = f"{prefix}.layers.{i}."
            lp = {
                "q_proj": {"w": f32(get(p + "self_attn.q_proj.weight").T)},
                "k_proj": {"w": f32(get(p + "self_attn.k_proj.weight").T)},
                "v_proj": {"w": f32(get(p + "self_attn.v_proj.weight").T)},
                "o_proj": {"w": f32(get(p + "self_attn.o_proj.weight").T)},
                "ln1": {"w": f32(get(p + "input_layernorm.weight")),
                        "b": f32(get(p + "input_layernorm.bias"))},
                "ln2": {"w": f32(get(p + "post_attention_layernorm.weight")),
                        "b": f32(get(p + "post_attention_layernorm.bias"))},
                "fc1": {"w": f32(get(p + "mlp.fc1.weight").T), "b": f32(get(p + "mlp.fc1.bias"))},
                "fc2": {"w": f32(get(p + "mlp.fc2.weight").T), "b": f32(get(p + "mlp.fc2.bias"))},
            }
            if gated:
                lp["gate_attn"] = f32(get(p + "gate_attn"))
                lp["gate_ffn"] = f32(get(p + "gate_ffn"))
            layers.append(lp)
        return dense.tree_stack(layers)

    conv = get("vision_model.patch_embedding.weight")  # (Hv, C, P, P)
    vision = {
        "patch_embedding": f32(conv.reshape(Hv, -1).T),  # (C*P*P, Hv)
        "class_embedding": f32(get("vision_model.class_embedding")),
        "pos_gate": f32(get("vision_model.gated_positional_embedding.gate")),
        "pos_embedding": f32(get("vision_model.gated_positional_embedding.embedding")),
        "tile_pos_emb": f32(get("vision_model.gated_positional_embedding.tile_embedding.weight")),
        "pre_tile_pos": {
            "emb": f32(get("vision_model.pre_tile_positional_embedding.embedding.weight")),
            "gate": f32(get("vision_model.pre_tile_positional_embedding.gate")),
        },
        "post_tile_pos": {
            "emb": f32(get("vision_model.post_tile_positional_embedding.embedding.weight")),
            "gate": f32(get("vision_model.post_tile_positional_embedding.gate")),
        },
        "ln_pre": {"w": f32(get("vision_model.layernorm_pre.weight")),
                   "b": f32(get("vision_model.layernorm_pre.bias"))},
        "ln_post": {"w": f32(get("vision_model.layernorm_post.weight")),
                    "b": f32(get("vision_model.layernorm_post.bias"))},
        "layers": vit_layers("vision_model.transformer", varch.num_layers, gated=False),
        "global_layers": vit_layers(
            "vision_model.global_transformer", varch.num_global_layers, gated=True
        ),
    }
    projector = {
        "w": f32(get("multi_modal_projector.weight").T),
        "b": f32(get("multi_modal_projector.bias")),
    }
    return {"vision": vision, "projector": projector}


# ---------------------------------------------------------------------------
# Shape structs + sharding specs
# ---------------------------------------------------------------------------


def param_shape_struct(config: InferenceConfig):
    arch = build_arch(config)
    t = arch.text
    struct = dense.param_shape_struct(config, t)
    dt = dense.np_dtype(t.dtype)
    H = t.hidden_size
    nC = arch.n_cross
    HD = t.num_attention_heads * t.head_dim
    KVD = t.num_kv_heads * t.head_dim
    I = t.intermediate_size

    def s(*shape):
        return jax.ShapeDtypeStruct(shape, dt)

    struct["cross"] = {
        "input_layernorm": s(nC, H),
        "post_attention_layernorm": s(nC, H),
        "gate_attn": s(nC, 1),
        "gate_mlp": s(nC, 1),
        "attn": {
            "q_proj": {"w": s(nC, H, HD)},
            "k_proj": {"w": s(nC, H, KVD)},
            "v_proj": {"w": s(nC, H, KVD)},
            "o_proj": {"w": s(nC, HD, H)},
            "q_norm": s(nC, t.head_dim),
            "k_norm": s(nC, t.head_dim),
        },
        "mlp": {
            "gate_proj": {"w": s(nC, H, I)},
            "up_proj": {"w": s(nC, H, I)},
            "down_proj": {"w": s(nC, I, H)},
        },
    }
    return struct


def param_specs(config: InferenceConfig):
    from jax.sharding import PartitionSpec as P

    arch = build_arch(config)
    specs = dense.param_specs_for(arch.text)

    def stack(tree):
        return jax.tree_util.tree_map(
            lambda sp: P(*((None,) + tuple(sp))), tree, is_leaf=lambda x: isinstance(x, P)
        )

    specs["cross"] = stack({
        "input_layernorm": REPLICATED,
        "post_attention_layernorm": REPLICATED,
        "gate_attn": REPLICATED,
        "gate_mlp": REPLICATED,
        "attn": {
            "q_proj": {"w": COLUMN_PARALLEL},
            "k_proj": {"w": COLUMN_PARALLEL},
            "v_proj": {"w": COLUMN_PARALLEL},
            "o_proj": {"w": ROW_PARALLEL},
            "q_norm": REPLICATED,
            "k_norm": REPLICATED,
        },
        "mlp": {
            "gate_proj": {"w": COLUMN_PARALLEL},
            "up_proj": {"w": COLUMN_PARALLEL},
            "down_proj": {"w": ROW_PARALLEL},
        },
    })
    return specs


def vision_shape_struct(config: InferenceConfig) -> Dict[str, Any]:
    varch = build_vision_arch(config)
    Hv, Iv = varch.hidden_size, varch.intermediate_size
    nP = varch.num_patches
    nAR = varch.max_aspect_ratio_id + 1
    TmaxP = varch.max_num_tiles

    def s(*shape):
        return jax.ShapeDtypeStruct(shape, np.float32)

    def vit(L, gated):
        lp = {
            "q_proj": {"w": s(L, Hv, Hv)},
            "k_proj": {"w": s(L, Hv, Hv)},
            "v_proj": {"w": s(L, Hv, Hv)},
            "o_proj": {"w": s(L, Hv, Hv)},
            "ln1": {"w": s(L, Hv), "b": s(L, Hv)},
            "ln2": {"w": s(L, Hv), "b": s(L, Hv)},
            "fc1": {"w": s(L, Hv, Iv), "b": s(L, Iv)},
            "fc2": {"w": s(L, Iv, Hv), "b": s(L, Hv)},
        }
        if gated:
            lp["gate_attn"] = s(L, 1)
            lp["gate_ffn"] = s(L, 1)
        return lp

    return {
        "vision": {
            "patch_embedding": s(varch.num_channels * varch.patch_size ** 2, Hv),
            "class_embedding": s(Hv),
            "pos_gate": s(1),
            "pos_embedding": s(nP, Hv),
            "tile_pos_emb": s(nAR, TmaxP * nP * Hv),
            "pre_tile_pos": {"emb": s(nAR, TmaxP * Hv), "gate": s(1)},
            "post_tile_pos": {"emb": s(nAR, TmaxP * Hv), "gate": s(1)},
            "ln_pre": {"w": s(Hv), "b": s(Hv)},
            "ln_post": {"w": s(Hv), "b": s(Hv)},
            "layers": vit(varch.num_layers, False),
            "global_layers": vit(varch.num_global_layers, True),
        },
        "projector": {
            "w": s(varch.vision_output_dim, varch.text_hidden),
            "b": s(varch.text_hidden),
        },
    }


# ---------------------------------------------------------------------------
# Application
# ---------------------------------------------------------------------------


class MllamaForConditionalGeneration:
    """Factory: builds the app class lazily to avoid a runtime import cycle."""

    def __new__(cls, *args, **kwargs):
        from nxdi_tpu.models.mllama.application import MllamaApplication

        return MllamaApplication(*args, **kwargs)
