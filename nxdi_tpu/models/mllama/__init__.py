from nxdi_tpu.models.mllama import modeling_mllama  # noqa: F401
