"""XGLM family — fairseq decoder with FIXED sinusoidal positions.

Reference: contrib/models/xglm-564M. HF XGLMForCausalLM (modeling_xglm.py):
``XGLMSinusoidalPositionalEmbedding`` (tensor2tensor [sin|cos] halves,
offset 2, padding_idx row zeroed) — regenerated deterministically at
conversion and baked into the learned-position table; sqrt(H) embed scale,
biased pre-LayerNorms, gelu fc MLP, model-level ``layer_norm``, tied head."""

from __future__ import annotations

from nxdi_tpu.config import InferenceConfig
from nxdi_tpu.models import dense, fairseq_dense
from nxdi_tpu.models.base import DecoderArch

build_inv_freq = fairseq_dense.build_inv_freq


class XGLMInferenceConfig(dense.DenseInferenceConfig):
    REQUIRED = ["d_model", "attention_heads", "num_layers", "vocab_size", "ffn_dim"]

    def add_derived_config(self):
        self.hidden_size = self.d_model
        self.num_attention_heads = self.attention_heads
        self.num_hidden_layers = self.num_layers
        self.num_key_value_heads = self.attention_heads
        self.intermediate_size = self.ffn_dim
        self.rms_norm_eps = 1e-5  # nn.LayerNorm default
        self.hidden_act = getattr(self, "activation_function", "gelu")
        self.tie_word_embeddings = bool(getattr(self, "tie_word_embeddings", True))
        super().add_derived_config()


def build_arch(config: InferenceConfig, **overrides) -> DecoderArch:
    kwargs = dict(
        hidden_act=getattr(config, "activation_function", "gelu"),
        tie_word_embeddings=bool(getattr(config, "tie_word_embeddings", True)),
        embed_scale=(
            float(config.d_model) ** 0.5
            if getattr(config, "scale_embedding", True) else None
        ),
    )
    kwargs.update(overrides)
    return fairseq_dense.build_arch(config, **kwargs)


def convert_hf_state_dict(state_dict, config: InferenceConfig):
    offset = 2
    table = fairseq_dense.sinusoid_table(
        config.max_position_embeddings + offset,
        config.d_model,
        padding_idx=getattr(config, "pad_token_id", 1),
    )
    return fairseq_dense.convert_hf_state_dict(
        state_dict, config, build_arch(config),
        prefix="model.",
        pos_table=lambda: table,
        pos_offset=offset,
        final_norm_key="layer_norm",
    )


def param_specs(config: InferenceConfig):
    return fairseq_dense.param_specs(build_arch(config))


def param_shape_struct(config: InferenceConfig):
    return fairseq_dense.param_shape_struct(
        config, build_arch(config), config.max_position_embeddings
    )
