"""GLM-4 family — sandwich norms + partial INTERLEAVED rope + fused gate_up.

Reference: contrib/models/glm-4-9b-chat-hf. HF Glm4ForCausalLM
(modeling_glm4.py:53-230):
  - four rms norms per layer: input, post_self_attn (on the attention
    output, pre-residual), post_attention (pre-MLP), post_mlp (on the MLP
    output, pre-residual) — exactly the gemma sandwich ordering, so the
    names remap onto the shared sandwich keys;
  - rope over ``head_dim * partial_rotary_factor`` channels with the
    GPT-J ADJACENT-pair layout (repeat_interleave'd cos/sin);
  - MLP stores one fused ``gate_up_proj`` ((2I, H)) — gate is the first I
    rows; q/k/v optionally biased, o_proj not."""

from __future__ import annotations

import numpy as np

from nxdi_tpu.config import InferenceConfig
from nxdi_tpu.models import dense
from nxdi_tpu.models.base import DecoderArch
from nxdi_tpu.ops.rope import default_inv_freq
from nxdi_tpu.parallel.layers import REPLICATED


class Glm4InferenceConfig(dense.DenseInferenceConfig):
    def add_derived_config(self):
        if not hasattr(self, "partial_rotary_factor"):
            self.partial_rotary_factor = 0.5
        if not hasattr(self, "attention_bias"):
            self.attention_bias = True
        super().add_derived_config()


def _rotary_dim(config) -> int:
    head_dim = getattr(config, "head_dim", None) or (
        config.hidden_size // config.num_attention_heads
    )
    return int(head_dim * config.partial_rotary_factor)


def build_arch(config: InferenceConfig, **overrides) -> DecoderArch:
    kwargs = dict(
        sandwich_norm=True,
        rope_interleaved=True,
        rotary_dim=_rotary_dim(config),
        attention_bias=bool(getattr(config, "attention_bias", True)),
    )
    kwargs.update(overrides)
    return dense.build_arch(config, **kwargs)


def build_inv_freq(config: InferenceConfig) -> np.ndarray:
    return default_inv_freq(
        _rotary_dim(config), getattr(config, "rope_theta", 10000.0)
    )


def convert_hf_state_dict(state_dict, config: InferenceConfig):
    arch = build_arch(config)
    dt = dense.np_dtype(arch.dtype)

    def src(name):
        for k in (name, f"model.{name}"):
            if k in state_dict:
                return np.asarray(state_dict[k])
        raise KeyError(name)

    def ff(get, has, cast, pre):
        gu = get(pre + "mlp.gate_up_proj.weight")  # (2I, H); gate first
        I = gu.shape[0] // 2
        return "mlp", {
            "gate_proj": {"w": cast(gu[:I].T)},
            "up_proj": {"w": cast(gu[I:].T)},
            "down_proj": {"w": cast(get(pre + "mlp.down_proj.weight").T)},
        }

    # remap glm4's norm names onto the shared sandwich keys BEFORE the dense
    # converter reads them: post_self_attn -> post_attention (attn-out norm)
    sd = dict(state_dict)
    L = arch.num_layers
    for i in range(L):
        for a, b in ((f"layers.{i}.post_self_attn_layernorm.weight",
                      f"layers.{i}.post_attention_layernorm.weight"),):
            for pre in ("", "model."):
                if pre + a in state_dict:
                    sd[pre + b] = state_dict[pre + a]
    params = dense.convert_hf_state_dict(sd, config, arch, ff_converter=ff)
    params["layers"]["pre_feedforward_layernorm"] = np.stack(
        [np.asarray(src(f"layers.{i}.post_attention_layernorm.weight"), dt)
         for i in range(L)]
    )
    params["layers"]["post_feedforward_layernorm"] = np.stack(
        [np.asarray(src(f"layers.{i}.post_mlp_layernorm.weight"), dt)
         for i in range(L)]
    )
    return params


def param_specs(config: InferenceConfig):
    specs = dense.param_specs_for(build_arch(config))
    specs["layers"]["pre_feedforward_layernorm"] = REPLICATED
    specs["layers"]["post_feedforward_layernorm"] = REPLICATED
    return specs


def param_shape_struct(config: InferenceConfig):
    import jax

    from nxdi_tpu.config import to_jax_dtype

    arch = build_arch(config)
    struct = dense.param_shape_struct(config, arch)
    dt = to_jax_dtype(arch.dtype)
    L, H = arch.num_layers, arch.hidden_size
    struct["layers"]["pre_feedforward_layernorm"] = jax.ShapeDtypeStruct((L, H), dt)
    struct["layers"]["post_feedforward_layernorm"] = jax.ShapeDtypeStruct((L, H), dt)
    return struct
