"""Qwen2.5-Omni (thinker) — audio-to-text on the multimodal base.

Reference: contrib/models/Qwen2.5-Omni-7B (the audio-omni slice of the contrib
hub). The audio tower is a whisper-style windowed mel encoder
(HF ``Qwen2_5OmniAudioEncoder``): mel features split into 2*n_window-frame
chunks -> conv1(k3) gelu -> conv2(k3, stride 2) gelu -> per-chunk sinusoidal
positions -> BLOCK-DIAGONAL bidirectional attention (each chunk attends only
itself; k_proj has no bias, q/v/out do) -> pair-average pooling over the
concatenated valid frames -> LayerNorm -> projection to the text width. The
projected frames replace the ``<|AUDIO|>`` placeholder tokens in the prefill
embedding stream — the image-to-text merge machinery verbatim
(models/image_to_text.py; reference: image_to_text_model_base.py).

Text side: the thinker text model is qwen2-style (qkv biases, o un-biased).
Its TMRoPE collapses for text+audio inputs — HF assigns audio frames
sequential positions IDENTICAL across the three rope streams
(modeling_qwen2_5_omni.py get_rope_index: ``arange(audio_len).expand(3, -1)``)
— so standard 1-D rope positions reproduce HF numerics exactly; the full
M-RoPE machinery engages only for vision inputs (models/qwen2_vl).

The whisper encoder machinery (models/whisper) is the sibling this reuses
conceptually; the chunked/block-diagonal structure here maps to a batch dim
(chunks) so no masking tricks are needed for full chunks.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from nxdi_tpu.config import InferenceConfig
from nxdi_tpu.models import dense
from nxdi_tpu.models.image_to_text import ImageToTextForCausalLM

build_inv_freq = dense.build_inv_freq


class Qwen2_5OmniInferenceConfig(dense.DenseInferenceConfig):
    """HF thinker config nests audio/vision/text configs; promote text."""

    REQUIRED = ["text_config", "audio_config"]

    def add_derived_config(self):
        from nxdi_tpu.config import promote_text_config

        promote_text_config(self)
        ac = self.audio_config
        if not isinstance(ac, dict):
            self.audio_config = ac.to_dict()
        if not hasattr(self, "audio_token_index"):
            self.audio_token_index = getattr(self, "audio_token_id", None)
        # the multimodal base reads image_token_index; audio IS the modality
        self.image_token_index = self.audio_token_index
        super().add_derived_config()


def build_arch(config: InferenceConfig, **overrides):
    # thinker text attention is qwen2-style: qkv biases, o un-biased
    return dense.build_arch(config, **{"attention_bias": True, **overrides})


def convert_hf_state_dict(state_dict, config: InferenceConfig):
    return dense.convert_hf_state_dict(state_dict, config, build_arch(config))


def param_specs(config: InferenceConfig):
    return dense.param_specs_for(build_arch(config))


def param_shape_struct(config: InferenceConfig):
    return dense.param_shape_struct(config, build_arch(config))


# ---------------------------------------------------------------------------
# Audio tower
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class AudioArch:
    d_model: int
    num_heads: int
    num_layers: int
    ffn_dim: int
    num_mel_bins: int
    n_window: int
    output_dim: int


def build_vision_arch(config: InferenceConfig) -> AudioArch:
    """(named for the multimodal base's hook contract; the 'vision' tower of
    this family is the AUDIO encoder)"""
    ac = config.audio_config
    return AudioArch(
        d_model=ac["d_model"],
        num_heads=ac["encoder_attention_heads"],
        num_layers=ac["encoder_layers"],
        ffn_dim=ac["encoder_ffn_dim"],
        num_mel_bins=ac["num_mel_bins"],
        n_window=ac.get("n_window", 100),
        output_dim=ac.get("output_dim", config.hidden_size),
    )


def num_image_tokens(config: InferenceConfig) -> int:
    """Audio-frame capacity per request: the CTE program's fixed feature
    width. T mel frames -> ceil(T/2) after the strided conv -> //2 after the
    pair pooler."""
    cap = int(getattr(config, "audio_frames_capacity", 4 * (config.audio_config.get("n_window", 100))))
    return ((cap - 1) // 2 + 1) // 2


def _layer_norm(x, w, b, eps=1e-5):
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, -1, keepdims=True)
    var = jnp.mean((xf - mu) ** 2, -1, keepdims=True)
    return ((xf - mu) * jax.lax.rsqrt(var + eps) * w + b).astype(x.dtype)


def _sinusoid_positions(length: int, channels: int) -> np.ndarray:
    log_inc = np.log(10000.0) / (channels // 2 - 1)
    inv = np.exp(-log_inc * np.arange(channels // 2, dtype=np.float64))
    t = np.arange(length, dtype=np.float64)[:, None] * inv[None, :]
    return np.concatenate([np.sin(t), np.cos(t)], axis=1).astype(np.float32)


def encode_audio(arch: AudioArch, params: Dict[str, Any], input_features, feature_len):
    """(mel, T) mel features -> (1, N, output_dim) audio frames.

    ``T`` must be a multiple of 2*n_window (the chunking grid; right-pad the
    mel features — ``feature_len`` marks the true length and everything past
    it is masked out of attention and pooling)."""
    p = params["audio"]
    mel, T = input_features.shape
    win2 = 2 * arch.n_window
    assert T % win2 == 0, "pad mel features to a multiple of 2*n_window"
    n_chunks = T // win2
    feat = input_features.astype(jnp.float32).reshape(mel, n_chunks, win2)
    feat = jnp.swapaxes(feat, 0, 1)  # (chunks, mel, win2)

    # per-chunk true lengths from the flat feature_len
    starts = jnp.arange(n_chunks, dtype=jnp.int32) * win2
    chunk_len = jnp.clip(feature_len - starts, 0, win2)  # (chunks,)
    frame_idx = jnp.arange(win2, dtype=jnp.int32)[None, :]
    in_mask = (frame_idx < chunk_len[:, None]).astype(jnp.float32)  # (chunks, win2)

    def conv1d(x, w, b, stride):
        # x (N, C, L), w (out, in, k) torch layout
        return jax.lax.conv_general_dilated(
            x, w, (stride,), [(1, 1)],
            dimension_numbers=("NCH", "OIH", "NCH"),
        ) + b[None, :, None]

    h = jax.nn.gelu(conv1d(feat, p["conv1_w"], p["conv1_b"], 1))
    h = h * in_mask[:, None, :]
    h = jax.nn.gelu(conv1d(h, p["conv2_w"], p["conv2_b"], 2))
    h = jnp.swapaxes(h, 1, 2)  # (chunks, win, d)
    win = h.shape[1]
    h = h + jnp.asarray(_sinusoid_positions(win, arch.d_model))[None]

    after_len = (chunk_len - 1) // 2 + 1  # ceil(len/2); 0 stays invalid below
    after_len = jnp.where(chunk_len > 0, after_len, 0)
    pos = jnp.arange(win, dtype=jnp.int32)[None, :]
    valid = pos < after_len[:, None]  # (chunks, win)

    Hh = arch.num_heads
    D = arch.d_model // Hh
    scale = D ** -0.5
    for layer in p["layers"]:
        x = _layer_norm(h, layer["ln1_w"], layer["ln1_b"])
        q = (x @ layer["q_w"] + layer["q_b"]).reshape(n_chunks, win, Hh, D)
        k = (x @ layer["k_w"]).reshape(n_chunks, win, Hh, D)
        v = (x @ layer["v_w"] + layer["v_b"]).reshape(n_chunks, win, Hh, D)
        s = jnp.einsum("cqhd,ckhd->chqk", q, k).astype(jnp.float32) * scale
        s = jnp.where(valid[:, None, None, :], s, -1e30)
        w = jax.nn.softmax(s, axis=-1).astype(v.dtype)
        ctx = jnp.einsum("chqk,ckhd->cqhd", w, v).reshape(n_chunks, win, arch.d_model)
        h = h + ctx @ layer["out_w"] + layer["out_b"]
        x = _layer_norm(h, layer["ln2_w"], layer["ln2_b"])
        x = jax.nn.gelu(x @ layer["fc1_w"] + layer["fc1_b"])
        h = h + x @ layer["fc2_w"] + layer["fc2_b"]

    # compact the valid frames of all chunks into one flat sequence
    flat = h.reshape(n_chunks * win, arch.d_model)
    flat_valid = valid.reshape(-1)
    slot = jnp.cumsum(flat_valid.astype(jnp.int32)) - 1
    cap = n_chunks * win
    slot = jnp.where(flat_valid, slot, cap)
    compact = jnp.zeros((cap + 1, arch.d_model), flat.dtype).at[slot].set(flat)[:cap]
    n_flat = jnp.sum(flat_valid.astype(jnp.int32))

    # pair-average pooling (AvgPool1d(2, 2): a trailing odd frame drops)
    pooled = (compact[0::2] + compact[1::2]) * 0.5  # (cap//2, d)
    n_pooled = n_flat // 2
    pooled = _layer_norm(pooled, p["ln_post_w"], p["ln_post_b"])
    out = pooled @ p["proj_w"] + p["proj_b"]
    keep = jnp.arange(out.shape[0], dtype=jnp.int32) < n_pooled
    out = jnp.where(keep[:, None], out, 0.0)
    return out[None]  # (1, N, output_dim)


def encode_images(varch, params, pixel_values):
    """Multimodal-base hook: 'images' are mel features here. ``pixel_values``
    (mel, T) or (1, mel, T); full-length features (no padding)."""
    feats = jnp.asarray(pixel_values)
    if feats.ndim == 3:
        feats = feats[0]
    return encode_audio(varch, params, feats, feats.shape[1])


def convert_vision_params(state_dict, config: InferenceConfig):
    arch = build_vision_arch(config)
    f32 = lambda a: np.asarray(a, np.float32)  # noqa: E731

    def get(name):
        for k in (name, f"thinker.{name}"):
            if k in state_dict:
                return np.asarray(state_dict[k])
        raise KeyError(name)

    layers = []
    for i in range(arch.num_layers):
        lp = f"audio_tower.layers.{i}."
        layers.append({
            "ln1_w": f32(get(lp + "self_attn_layer_norm.weight")),
            "ln1_b": f32(get(lp + "self_attn_layer_norm.bias")),
            "q_w": f32(get(lp + "self_attn.q_proj.weight").T),
            "q_b": f32(get(lp + "self_attn.q_proj.bias")),
            "k_w": f32(get(lp + "self_attn.k_proj.weight").T),
            "v_w": f32(get(lp + "self_attn.v_proj.weight").T),
            "v_b": f32(get(lp + "self_attn.v_proj.bias")),
            "out_w": f32(get(lp + "self_attn.out_proj.weight").T),
            "out_b": f32(get(lp + "self_attn.out_proj.bias")),
            "ln2_w": f32(get(lp + "final_layer_norm.weight")),
            "ln2_b": f32(get(lp + "final_layer_norm.bias")),
            "fc1_w": f32(get(lp + "fc1.weight").T),
            "fc1_b": f32(get(lp + "fc1.bias")),
            "fc2_w": f32(get(lp + "fc2.weight").T),
            "fc2_b": f32(get(lp + "fc2.bias")),
        })
    audio = {
        "conv1_w": f32(get("audio_tower.conv1.weight")),
        "conv1_b": f32(get("audio_tower.conv1.bias")),
        "conv2_w": f32(get("audio_tower.conv2.weight")),
        "conv2_b": f32(get("audio_tower.conv2.bias")),
        "layers": layers,
        "ln_post_w": f32(get("audio_tower.ln_post.weight")),
        "ln_post_b": f32(get("audio_tower.ln_post.bias")),
        "proj_w": f32(get("audio_tower.proj.weight").T),
        "proj_b": f32(get("audio_tower.proj.bias")),
    }
    return {"audio": audio}


def vision_shape_struct(config: InferenceConfig) -> Dict[str, Any]:
    arch = build_vision_arch(config)

    def s(*shape):
        return jax.ShapeDtypeStruct(shape, np.float32)

    d, f = arch.d_model, arch.ffn_dim
    layer = {
        "ln1_w": s(d), "ln1_b": s(d),
        "q_w": s(d, d), "q_b": s(d),
        "k_w": s(d, d),
        "v_w": s(d, d), "v_b": s(d),
        "out_w": s(d, d), "out_b": s(d),
        "ln2_w": s(d), "ln2_b": s(d),
        "fc1_w": s(d, f), "fc1_b": s(f),
        "fc2_w": s(f, d), "fc2_b": s(d),
    }
    return {
        "audio": {
            "conv1_w": s(d, arch.num_mel_bins, 3),
            "conv1_b": s(d),
            "conv2_w": s(d, d, 3),
            "conv2_b": s(d),
            "layers": [dict(layer) for _ in range(arch.num_layers)],
            "ln_post_w": s(d), "ln_post_b": s(d),
            "proj_w": s(d, arch.output_dim), "proj_b": s(arch.output_dim),
        }
    }


class Qwen2_5OmniForCausalLM(ImageToTextForCausalLM):
    """Audio-to-text thinker application. ``forward``/``generate`` accept the
    mel features as ``input_features`` (or through the adapter's
    ``pixel_values`` slot, which this family defines as mel features)."""

    def encode_images(self, pixel_values):
        from functools import partial

        if self._encode_jit is None:
            varch = self.family.build_vision_arch(self.config)
            self._encode_jit = jax.jit(partial(encode_images, varch))
        with jax.set_mesh(self.mesh):
            return self._encode_jit(
                {"audio": self.params["audio"]},
                np.asarray(pixel_values, dtype=np.float32),
            )

    def forward(self, input_ids, position_ids, input_features=None, **kwargs):
        if input_features is not None:
            kwargs.setdefault("pixel_values", input_features)
        return super().forward(input_ids, position_ids, **kwargs)


APPLICATION_CLS = Qwen2_5OmniForCausalLM
