from nxdi_tpu.models.qwen2_5_omni import modeling_qwen2_5_omni  # noqa: F401
