"""Seq-id routing for raw per-row state stacks.

The hybrid-state families (qwen3_next, lfm2, recurrentgemma, falcon_h1) keep
their recurrent state — conv tails, delta-rule/RG-LRU states, ring KV stacks —
as plain ``(n_layers, B_cache, ...)`` arrays outside the KV layout classes.
Continuous batching routes the ACTIVE batch row ``i`` to cache line
``seq_ids[i]`` (reference: the ``is_continuous_batching`` seq-id plumbing,
modules/kvcache/kv_cache_manager.py — batchline gather on read, scatter on
write). These helpers apply the same convention to raw stacks:

- :func:`take_rows` gathers a layer's state rows for the active batch before
  the layer runs;
- :func:`put_rows` scatters the updated rows back into the stacked state.

Padded batch lanes duplicate row 0's seq_id with identical values, so the
duplicate-index scatter is idempotent (the repeated-first-batchline
convention, see ModelWrapper._layout_inputs).

TPU perf note: the routed write is a real batch-dim scatter (the unrouted
path is a full-slice dynamic-update-slice XLA handles in place). XLA's TPU
scatter lowering can materialize cache copies on large operands (the decode
hot path routes KV through ops/kernels/kv_commit.py for exactly this
reason); the hybrid families' recurrent states are small, but their
attention KV stacks under continuous batching should move to the commit
kernel before any of them becomes a benchmarked serving path.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def take_rows(state: jax.Array, seq_ids: Optional[jax.Array]) -> jax.Array:
    """Gather active-batch rows from one layer's ``(B_cache, ...)`` state."""
    if seq_ids is None:
        return state
    return jnp.take(state, seq_ids.astype(jnp.int32), axis=0, mode="clip")


def put_rows(
    stack: jax.Array,
    layer_idx: int,
    rows: jax.Array,
    seq_ids: Optional[jax.Array],
) -> jax.Array:
    """Scatter updated active rows into layer ``layer_idx`` of a stacked
    ``(n_layers, B_cache, ...)`` state."""
    if seq_ids is None:
        return stack.at[layer_idx].set(rows)
    return stack.at[layer_idx, seq_ids.astype(jnp.int32)].set(rows, mode="drop")
