from nxdi_tpu.models.gemma3 import modeling_gemma3
