"""Gemma3 (text) family.

Reference: models/gemma3/modeling_gemma3.py (361 LoC) — gemma-style (1+w)
float32 RMSNorm (:44), per-layer interleaved sliding-window attention with a
full-attention layer every Nth (:68 ``get_updated_configs``), local/global
rope thetas chosen per layer (:151), sandwich pre/post feed-forward norms
(:224), sqrt(hidden) embedding scale (:238), and a ``query_pre_attn_scalar``
softmax scale.

TPU-native mapping: all per-layer heterogeneity (window on/off, local/global
rope) rides the layer scan as boolean flag arrays in the params pytree
(models/base.py decoder_layer), so the stack still compiles as ONE scanned
body; ``build_inv_freq`` returns the [global, local] inv-freq pair stacked.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from nxdi_tpu.config import InferenceConfig
from nxdi_tpu.models import dense
from nxdi_tpu.models.base import DecoderArch
from nxdi_tpu.ops.rope import default_inv_freq, inv_freq_from_hf_config
from nxdi_tpu.parallel.layers import REPLICATED


class Gemma3InferenceConfig(dense.DenseInferenceConfig):
    REQUIRED = dense.DenseInferenceConfig.REQUIRED + ["head_dim"]

    def add_derived_config(self):
        super().add_derived_config()
        if getattr(self, "hidden_act", None) in (None, "silu"):
            # HF stores gemma's activation under hidden_activation
            self.hidden_act = getattr(self, "hidden_activation", "gelu_pytorch_tanh")
        if not hasattr(self, "query_pre_attn_scalar"):
            self.query_pre_attn_scalar = self.head_dim
        if not hasattr(self, "rope_local_base_freq"):
            self.rope_local_base_freq = 10000.0
        if not hasattr(self, "sliding_window"):
            self.sliding_window = None
        if not hasattr(self, "tie_word_embeddings"):
            self.tie_word_embeddings = True


def _layer_is_sliding(config: InferenceConfig, i: int) -> bool:
    """Which layers use the sliding window: HF ``layer_types`` when present,
    else the every-Nth-global pattern (reference: modeling_gemma3.py:79)."""
    lt = getattr(config, "layer_types", None)
    if lt:
        return lt[i] == "sliding_attention"
    pattern = getattr(config, "sliding_window_pattern", None) or 6
    return (i + 1) % pattern != 0


def build_arch(config: InferenceConfig, **overrides) -> DecoderArch:
    sw = getattr(config, "sliding_window", None)
    kwargs = dict(
        qk_norm=True,
        gemma_norm=True,
        sandwich_norm=True,
        embed_scale=float(config.hidden_size) ** 0.5,
        sliding_window=sw,
        attention_scale=float(config.query_pre_attn_scalar) ** -0.5,
        tie_word_embeddings=getattr(config, "tie_word_embeddings", True),
        # interleaved ring stacks under window_sized_kv (5-of-6 local layers;
        # reference: per-layer window-sized shapes kv_cache_manager.py:195)
        kv_window_pattern=(
            tuple(_layer_is_sliding(config, i) for i in range(config.num_hidden_layers))
            if sw
            else None
        ),
    )
    kwargs.update(overrides)
    return dense.build_arch(config, **kwargs)


def build_inv_freq(config: InferenceConfig) -> np.ndarray:
    """Stacked [global, local] inverse frequencies — global layers use
    rope_theta (+ scaling), sliding layers the local base freq."""
    g = inv_freq_from_hf_config(
        config.head_dim,
        getattr(config, "rope_theta", 1000000.0),
        getattr(config, "rope_scaling", None),
    )
    loc = default_inv_freq(config.head_dim, config.rope_local_base_freq)
    return np.stack([g, loc])


# -- shared gemma-lineage helpers (gemma2 reuses these with dual_rope=False) --

def add_sandwich_params(params, state_dict, config, arch, layer_is_sliding, dual_rope):
    dt = dense.np_dtype(arch.dtype)

    def get(name):
        for k in (name, f"model.{name}"):
            if k in state_dict:
                return state_dict[k]
        raise KeyError(name)

    L = arch.num_layers
    params["layers"]["pre_feedforward_layernorm"] = np.stack(
        [np.asarray(get(f"layers.{i}.pre_feedforward_layernorm.weight"), dt) for i in range(L)]
    )
    params["layers"]["post_feedforward_layernorm"] = np.stack(
        [np.asarray(get(f"layers.{i}.post_feedforward_layernorm.weight"), dt) for i in range(L)]
    )
    sliding = np.array([layer_is_sliding(config, i) for i in range(L)], dtype=bool)
    params["layers"]["use_sliding_window"] = sliding
    if dual_rope:
        params["layers"]["use_local_rope"] = sliding  # local rope on SWA layers
    return params


def add_sandwich_specs(specs, dual_rope):
    specs["layers"]["pre_feedforward_layernorm"] = REPLICATED
    specs["layers"]["post_feedforward_layernorm"] = REPLICATED
    specs["layers"]["use_sliding_window"] = REPLICATED
    if dual_rope:
        specs["layers"]["use_local_rope"] = REPLICATED
    return specs


def add_sandwich_struct(struct, config, arch, dual_rope):
    import jax
    import jax.numpy as jnp

    from nxdi_tpu.config import to_jax_dtype

    dt = to_jax_dtype(arch.dtype)
    L, H = arch.num_layers, arch.hidden_size
    struct["layers"]["pre_feedforward_layernorm"] = jax.ShapeDtypeStruct((L, H), dt)
    struct["layers"]["post_feedforward_layernorm"] = jax.ShapeDtypeStruct((L, H), dt)
    struct["layers"]["use_sliding_window"] = jax.ShapeDtypeStruct((L,), jnp.bool_)
    if dual_rope:
        struct["layers"]["use_local_rope"] = jax.ShapeDtypeStruct((L,), jnp.bool_)
    return struct


def convert_hf_state_dict(
    state_dict: Dict[str, np.ndarray], config: InferenceConfig
) -> Dict[str, Any]:
    arch = build_arch(config)
    params = dense.convert_hf_state_dict(state_dict, config, arch)
    return add_sandwich_params(
        params, state_dict, config, arch, _layer_is_sliding, dual_rope=True
    )


def param_specs(config: InferenceConfig):
    specs = dense.param_specs_for(build_arch(config))
    return add_sandwich_specs(specs, dual_rope=True)


def param_shape_struct(config: InferenceConfig):
    arch = build_arch(config)
    struct = dense.param_shape_struct(config, arch)
    return add_sandwich_struct(struct, config, arch, dual_rope=True)
