"""Gemma3 multimodal — SigLIP vision tower + gemma3 text decoder.

Reference: contrib/models/gemma3-vision (the last uncovered contrib vision
family): ``Gemma3ForConditionalGeneration`` = SigLIP tower -> avg-pool to
``mm_tokens_per_image`` -> gemma (1+w) RMSNorm -> biasless projection matmul
into the text stream, with image-token spans attending BIDIRECTIONALLY
during prefill (HF token_type_ids_mask_function — carried here by the
``bidirectional_image_attention`` arch flag; masks are OR-ed in-graph from
input_ids, models/base.py).

This module also serves flat (text-only) ``gemma3`` configs so the registry
key stays backward-compatible: without ``vision_config`` everything delegates
to modeling_gemma3 and the plain causal-lm application is used.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from nxdi_tpu.config import InferenceConfig, promote_text_config
from nxdi_tpu.models.gemma3 import modeling_gemma3 as g3
from nxdi_tpu.ops import vision as vision_ops


def __getattr__(name):
    if name == "APPLICATION_CLS":
        return _app_factory
    raise AttributeError(name)


def _app_factory(model_path, config, model_family=None, **kwargs):
    """Image-to-text app when the config carries a vision tower, the plain
    causal-lm app for flat text configs (one registry key serves both)."""
    import sys

    from nxdi_tpu.models.image_to_text import ImageToTextForCausalLM
    from nxdi_tpu.runtime.application import TpuModelForCausalLM

    family = model_family or sys.modules[__name__]
    cls = ImageToTextForCausalLM if _has_vision(config) else TpuModelForCausalLM
    return cls(model_path, config, model_family=family, **kwargs)


def _has_vision(config: InferenceConfig) -> bool:
    return getattr(config, "vision_config", None) is not None


class Gemma3VisionInferenceConfig(g3.Gemma3InferenceConfig):
    def add_derived_config(self):
        if getattr(self, "text_config", None) is not None:
            promote_text_config(self)
            vc = getattr(self, "vision_config", None)
            if vc is not None and not isinstance(vc, dict):
                self.vision_config = vc.to_dict()
            if not hasattr(self, "mm_tokens_per_image"):
                self.mm_tokens_per_image = 256
        super().add_derived_config()


def build_arch(config: InferenceConfig, **overrides):
    if _has_vision(config):
        overrides.setdefault("bidirectional_image_attention", True)
    return g3.build_arch(config, **overrides)


def build_inv_freq(config: InferenceConfig) -> np.ndarray:
    return g3.build_inv_freq(config)


from nxdi_tpu.checkpoint import strip_language_model_prefix as _strip_text_prefix


def convert_hf_state_dict(state_dict, config: InferenceConfig):
    # g3's converter adds the per-layer window/local-rope flag arrays and
    # sandwich norms — required for the interleaved gemma3 layer scan
    if not _has_vision(config):
        return g3.convert_hf_state_dict(state_dict, config)
    return g3.convert_hf_state_dict(_strip_text_prefix(state_dict), config)


def param_specs(config: InferenceConfig):
    return g3.param_specs(config)


def param_shape_struct(config: InferenceConfig):
    return g3.param_shape_struct(config)


# -- vision protocol (ImageToTextForCausalLM) --


def build_vision_arch(config: InferenceConfig):
    vc = config.vision_config
    return vision_ops.SiglipVisionArch(
        hidden_size=vc["hidden_size"],
        intermediate_size=vc["intermediate_size"],
        num_layers=vc["num_hidden_layers"],
        num_heads=vc["num_attention_heads"],
        image_size=vc["image_size"],
        patch_size=vc["patch_size"],
        num_channels=vc.get("num_channels", 3),
        hidden_act=vc.get("hidden_act", "gelu_pytorch_tanh"),
        layer_norm_eps=vc.get("layer_norm_eps", 1e-6),
        proj_tokens_per_image=int(config.mm_tokens_per_image),
        proj_eps=float(vc.get("layer_norm_eps", 1e-6)),
    )


def num_image_tokens(config: InferenceConfig) -> int:
    return int(config.mm_tokens_per_image)


def convert_vision_params(state_dict, config: InferenceConfig):
    varch = build_vision_arch(config)

    def get(name):
        for k in ("multi_modal_projector." + name,
                  "model.multi_modal_projector." + name):
            if k in state_dict:
                return np.asarray(state_dict[k], dtype=np.float32)
        raise KeyError(name)

    return {
        "vision": vision_ops.convert_siglip_vision(state_dict, varch),
        "projector": {
            "mm_input_projection": get("mm_input_projection_weight"),
            "mm_soft_emb_norm": get("mm_soft_emb_norm.weight"),
        },
    }


def encode_images(varch, params: Dict[str, Any], pixel_values):
    """SigLIP features -> avg-pool grid to tokens_per_side^2 -> gemma RMSNorm
    -> projection (reference: Gemma3MultiModalProjector)."""
    feat = vision_ops.siglip_vision_forward(varch, params["vision"], pixel_values)
    p = params["projector"]
    B, N, d = feat.shape
    g = varch.grid
    side = int(round(varch.proj_tokens_per_image ** 0.5))
    k = g // side
    # (B, g, g, d) average-pooled with kernel/stride k
    grid = feat.reshape(B, g // k, k, g // k, k, d)
    pooled = grid.mean(axis=(2, 4)).reshape(B, side * side, d)
    # gemma-style (1+w) RMSNorm in fp32
    x = pooled.astype(jnp.float32)
    x = x * jax.lax.rsqrt(jnp.mean(x * x, -1, keepdims=True) + varch.proj_eps)
    x = x * (1.0 + p["mm_soft_emb_norm"].astype(jnp.float32))
    return (x @ p["mm_input_projection"].astype(jnp.float32)).astype(feat.dtype)


def vision_shape_struct(config: InferenceConfig) -> Dict[str, Any]:
    varch = build_vision_arch(config)
    Hv, Iv, L = varch.hidden_size, varch.intermediate_size, varch.num_layers
    P2 = varch.num_channels * varch.patch_size ** 2
    s = lambda *shape: jax.ShapeDtypeStruct(shape, np.float32)  # noqa: E731
    lin = lambda i, o: {"w": s(L, i, o), "b": s(L, o)}  # noqa: E731
    ln = lambda: {"w": s(L, Hv), "b": s(L, Hv)}  # noqa: E731
    return {
        "vision": {
            "patch_embedding": s(P2, Hv),
            "patch_bias": s(Hv),
            "position_embedding": s(varch.num_patches, Hv),
            "post_layernorm": {"w": s(Hv), "b": s(Hv)},
            "layers": {
                "attn": {n: lin(Hv, Hv)
                         for n in ("q_proj", "k_proj", "v_proj", "out_proj")},
                "ln1": ln(), "ln2": ln(),
                "fc1": lin(Hv, Iv), "fc2": lin(Iv, Hv),
            },
        },
        "projector": {
            "mm_input_projection": s(Hv, config.hidden_size),
            "mm_soft_emb_norm": s(Hv),
        },
    }
