"""Mixtral family (8x7B / 8x22B) — sparse-MoE llama lineage.

Reference: models/mixtral/modeling_mixtral.py (330 LoC) builds the MoE via
modules/moe_v2.py; here the MoE feed-forward is ops/moe.py with the expert dim
sharded over tp when it divides (expert parallelism).

HF weight layout: ``block_sparse_moe.gate`` router, experts ``w1`` (gate),
``w3`` (up), ``w2`` (down). Router semantics: full softmax -> top-k ->
renormalize (always).
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np


from nxdi_tpu.config import InferenceConfig
from nxdi_tpu.models import dense
from nxdi_tpu.models.base import DecoderArch
from nxdi_tpu.ops.moe import MoEArch, convert_hf_experts, moe_parallel_fields

build_inv_freq = dense.build_inv_freq

# HF Mixtral expert projections: w1=gate, w3=up, w2=down
_W_NAMES = {"gate": "w1", "up": "w3", "down": "w2"}


class MixtralInferenceConfig(dense.DenseInferenceConfig):
    REQUIRED = dense.DenseInferenceConfig.REQUIRED + [
        "num_local_experts",
        "num_experts_per_tok",
    ]


def _moe_arch(config: InferenceConfig) -> MoEArch:
    return MoEArch(
        num_experts=config.num_local_experts,
        top_k=config.num_experts_per_tok,
        intermediate_size=config.intermediate_size,
        hidden_act=getattr(config, "hidden_act", "silu"),
        norm_topk_prob=True,
        **moe_parallel_fields(config.tpu_config, config.num_local_experts),
    )


def build_arch(config: InferenceConfig, **overrides) -> DecoderArch:
    sw = getattr(config, "sliding_window", None)
    return dense.build_arch(
        config, **{"moe": _moe_arch(config), "sliding_window": sw, **overrides}
    )


def convert_hf_state_dict(
    state_dict: Dict[str, np.ndarray], config: InferenceConfig
) -> Dict[str, Any]:
    arch = build_arch(config)

    def ff(get, has, cast, pre):
        return "moe", convert_hf_experts(
            get,
            cast,
            arch.moe.num_experts,
            pre + "block_sparse_moe.gate.weight",
            lambda j, proj: f"{pre}block_sparse_moe.experts.{j}.{_W_NAMES[proj]}.weight",
        )

    return dense.convert_hf_state_dict(state_dict, config, arch, ff_converter=ff)


def param_specs(config: InferenceConfig):
    return dense.param_specs_for(build_arch(config))


def param_shape_struct(config: InferenceConfig):
    return dense.param_shape_struct(config, build_arch(config))
