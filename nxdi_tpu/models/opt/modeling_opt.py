"""OPT family — fairseq decoder: learned positions (+2 offset), ReLU fc MLP.

Reference: contrib/models/opt-1.3b. HF OPTForCausalLM
(modeling_opt.py): ``OPTLearnedPositionalEmbedding`` (offset 2, baked at
conversion), biased pre-LayerNorms, relu fc1/fc2, tied lm_head. The 350m
post-norm (``do_layer_norm_before=False``) and projected-embedding
(``word_embed_proj_dim != hidden_size``) variants are rejected loudly."""

from __future__ import annotations


from nxdi_tpu.config import InferenceConfig
from nxdi_tpu.models import dense, fairseq_dense
from nxdi_tpu.models.base import DecoderArch

build_inv_freq = fairseq_dense.build_inv_freq


class OPTInferenceConfig(dense.DenseInferenceConfig):
    REQUIRED = ["hidden_size", "num_attention_heads", "num_hidden_layers", "vocab_size"]

    def add_derived_config(self):
        self.num_key_value_heads = self.num_attention_heads
        self.intermediate_size = getattr(self, "ffn_dim", 4 * self.hidden_size)
        self.rms_norm_eps = 1e-5  # nn.LayerNorm default
        self.hidden_act = getattr(self, "activation_function", "relu")
        self.tie_word_embeddings = bool(getattr(self, "tie_word_embeddings", True))
        super().add_derived_config()
        if not getattr(self, "do_layer_norm_before", True):
            raise NotImplementedError(
                "OPT post-norm variant (do_layer_norm_before=False) is not supported"
            )
        wepd = getattr(self, "word_embed_proj_dim", None)
        if wepd is not None and wepd != self.hidden_size:
            raise NotImplementedError(
                "OPT word_embed_proj_dim != hidden_size (project_in/out) is not supported"
            )


def build_arch(config: InferenceConfig, **overrides) -> DecoderArch:
    kwargs = dict(
        hidden_act=getattr(config, "activation_function", "relu"),
        tie_word_embeddings=bool(getattr(config, "tie_word_embeddings", True)),
    )
    kwargs.update(overrides)
    return fairseq_dense.build_arch(config, **kwargs)


def convert_hf_state_dict(state_dict, config: InferenceConfig):
    return fairseq_dense.convert_hf_state_dict(
        state_dict, config, build_arch(config),
        prefix="model.decoder.",
        final_norm_key="final_layer_norm",
    )


def param_specs(config: InferenceConfig):
    return fairseq_dense.param_specs(build_arch(config))


def param_shape_struct(config: InferenceConfig):
    return fairseq_dense.param_shape_struct(
        config, build_arch(config), config.max_position_embeddings
    )
