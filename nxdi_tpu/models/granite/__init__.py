from nxdi_tpu.models.granite import modeling_granite  # noqa: F401
