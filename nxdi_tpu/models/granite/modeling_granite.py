"""Granite family — llama with scalar multipliers.

Reference: contrib/models/granite-3.1-8b-instruct. HF GraniteForCausalLM =
llama plus ``embedding_multiplier`` (scales token embeddings),
``attention_multiplier`` (replaces 1/sqrt(d) attention scaling),
``residual_multiplier`` (scales every block output before the residual add)
and ``logits_scaling`` (divides the final logits)."""

from __future__ import annotations


from nxdi_tpu.config import InferenceConfig
from nxdi_tpu.models import dense
from nxdi_tpu.models.base import DecoderArch

build_inv_freq = dense.build_inv_freq


class GraniteInferenceConfig(dense.DenseInferenceConfig):
    pass


def build_arch(config: InferenceConfig, **overrides) -> DecoderArch:
    # HF GraniteConfig defaults attention_multiplier to 1.0 — a config relying
    # on that default must get scale 1.0, not the 1/sqrt(d) fallback (None)
    attn_mult = getattr(config, "attention_multiplier", None)
    kwargs = dict(
        embed_scale=float(getattr(config, "embedding_multiplier", 1.0)),
        attention_scale=1.0 if attn_mult is None else float(attn_mult),
        residual_multiplier=float(getattr(config, "residual_multiplier", 1.0)),
        logits_scaling=float(getattr(config, "logits_scaling", 1.0)),
    )
    kwargs.update(overrides)
    return dense.build_arch(config, **kwargs)


def convert_hf_state_dict(state_dict, config: InferenceConfig):
    return dense.convert_hf_state_dict(state_dict, config, build_arch(config))


def param_specs(config: InferenceConfig):
    return dense.param_specs_for(build_arch(config))


def param_shape_struct(config: InferenceConfig):
    return dense.param_shape_struct(config, build_arch(config))
