"""Afmoe (Arcee Trinity) family — gated-attention MoE with expert-bias
sigmoid routing, dual (sandwich) layer norms, NoPE global layers, and a
dense head segment.

Reference: contrib/models/Trinity (src/modeling_trinity.py:24-40, 553-640,
1340-1480, mirroring the Arcee AfmoeForCausalLM remote code):
  - attention: per-head q/k RMSNorm; output gated by
    ``sigmoid(gate_proj(attention input))`` before o_proj (the shared
    ``attn_out_gate`` switch); rope ONLY on sliding layers (every
    ``global_attn_every_n_layers``-th layer is full attention AND NoPE);
  - norms: input/post-attention + pre/post-MLP — the gemma sandwich
    machinery with plain RMSNorms (pre_mlp/post_mlp renamed onto the
    pre/post_feedforward slots);
  - muP: embeddings scaled by sqrt(hidden) (``mup_enabled``);
  - MoE (layers >= num_dense_layers): sigmoid router, top-k selected over
    bias-ADDED scores but weighted by the raw scores (the deepseek-V3
    correction-bias machinery), optional renorm (``route_norm``) and
    ``route_scale``, ``num_shared_experts`` fused shared MLP; the first
    ``num_dense_layers`` layers are a plain dense segment (segmented layer
    stacks, like deepseek first_k_dense_replace)."""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict

import numpy as np

from nxdi_tpu.config import InferenceConfig
from nxdi_tpu.models import dense
from nxdi_tpu.models.base import DecoderArch, decoder_param_specs
from nxdi_tpu.ops.moe import MoEArch, moe_parallel_fields
from nxdi_tpu.parallel import gqa
from nxdi_tpu.parallel.layers import REPLICATED

build_inv_freq = dense.build_inv_freq


class AfmoeInferenceConfig(dense.DenseInferenceConfig):
    def add_derived_config(self):
        defaults = {
            "num_dense_layers": 2,
            "num_experts_per_tok": 8,
            "num_shared_experts": 1,
            "route_norm": True,
            "route_scale": 1.0,
            "score_func": "sigmoid",
            "global_attn_every_n_layers": 4,
            "sliding_window": 2048,
            "mup_enabled": True,
        }
        for k, v in defaults.items():
            if not hasattr(self, k):
                setattr(self, k, v)
        if not hasattr(self, "num_local_experts"):
            self.num_local_experts = getattr(self, "num_experts", 128)
        if not hasattr(self, "moe_intermediate_size"):
            self.moe_intermediate_size = self.intermediate_size
        super().add_derived_config()
        if self.score_func != "sigmoid":
            raise NotImplementedError(
                f"afmoe score_func {self.score_func!r} not supported (sigmoid only)"
            )
        if not hasattr(self, "layer_types") or self.layer_types is None:
            n = self.global_attn_every_n_layers
            self.layer_types = [
                "sliding_attention" if bool((i + 1) % n) else "full_attention"
                for i in range(self.num_hidden_layers)
            ]


def _moe_arch(config: InferenceConfig) -> MoEArch:
    E = config.num_local_experts
    n_shared = getattr(config, "num_shared_experts", 0) or 0
    return MoEArch(
        num_experts=E,
        top_k=config.num_experts_per_tok,
        intermediate_size=config.moe_intermediate_size,
        hidden_act=getattr(config, "hidden_act", "silu"),
        norm_topk_prob=bool(getattr(config, "route_norm", True)),
        sigmoid_routing=True,
        routed_scaling=float(getattr(config, "route_scale", 1.0)),
        correction_bias=True,  # expert_bias: selection-only (RouterTopKWithBias)
        shared_expert_intermediate_size=(
            n_shared * config.moe_intermediate_size if n_shared else None
        ),
        **moe_parallel_fields(config.tpu_config, E),
    )


def _n_dense(config: InferenceConfig) -> int:
    return int(getattr(config, "num_dense_layers", 0) or 0)


def _sliding_flags(config) -> np.ndarray:
    return np.array(
        [t == "sliding_attention" for t in config.layer_types], dtype=bool
    )


def build_arch(config: InferenceConfig, **overrides) -> DecoderArch:
    moe = _moe_arch(config)
    if _n_dense(config) >= config.num_hidden_layers:
        moe = None
    sw = getattr(config, "sliding_window", None)
    kwargs = dict(
        qk_norm=True,
        attn_out_gate=True,
        sandwich_norm=True,
        sliding_window=sw,
        embed_scale=(
            math.sqrt(config.hidden_size)
            if getattr(config, "mup_enabled", True) else None
        ),
        moe=moe,
        kv_window_pattern=tuple(_sliding_flags(config)) if sw else None,
    )
    kwargs.update(overrides)
    return dense.build_arch(config, **kwargs)


def _segment_archs(config: InferenceConfig, arch: DecoderArch):
    k = _n_dense(config)
    if arch.moe is None or not (0 < k < arch.num_layers):
        return None
    head = dataclasses.replace(arch, num_layers=k, moe=None)
    tail = dataclasses.replace(arch, num_layers=arch.num_layers - k)
    return head, tail


def convert_hf_state_dict(
    state_dict: Dict[str, np.ndarray], config: InferenceConfig
) -> Dict[str, Any]:
    arch = build_arch(config)
    dt = dense.np_dtype(arch.dtype)
    plan = dense.gqa_plan(config)
    D = arch.head_dim
    k_dense = _n_dense(config)

    def get(name):
        for k in (name, f"model.{name}"):
            if k in state_dict:
                return np.asarray(state_dict[k])
        raise KeyError(name)

    def cast(x):
        return np.asarray(x, dtype=dt)

    layers = []
    for i in range(arch.num_layers):
        pre = f"layers.{i}."
        attn = {
            "q_proj": {"w": cast(gqa.convert_q(get(pre + "self_attn.q_proj.weight"), D, plan).T)},
            "k_proj": {"w": cast(gqa.convert_kv(get(pre + "self_attn.k_proj.weight"), D, plan).T)},
            "v_proj": {"w": cast(gqa.convert_kv(get(pre + "self_attn.v_proj.weight"), D, plan).T)},
            "o_proj": {"w": cast(gqa.convert_o(get(pre + "self_attn.o_proj.weight"), D, plan).T)},
            # the attention output gate has q-shaped columns: same interleave
            "gate_proj": {"w": cast(gqa.convert_q(get(pre + "self_attn.gate_proj.weight"), D, plan).T)},
            "q_norm": cast(get(pre + "self_attn.q_norm.weight")),
            "k_norm": cast(get(pre + "self_attn.k_norm.weight")),
        }
        layer: Dict[str, Any] = {
            "input_layernorm": cast(get(pre + "input_layernorm.weight")),
            "post_attention_layernorm": cast(get(pre + "post_attention_layernorm.weight")),
            "pre_feedforward_layernorm": cast(get(pre + "pre_mlp_layernorm.weight")),
            "post_feedforward_layernorm": cast(get(pre + "post_mlp_layernorm.weight")),
            "attn": attn,
        }
        if arch.moe is not None and i >= k_dense:
            moe = arch.moe
            mo: Dict[str, Any] = {
                "router": {
                    "w": cast(get(pre + "mlp.router.gate.weight")).T,
                    # expert_bias: selection-only, kept f32 (near-tie flips)
                    "e_bias": np.asarray(get(pre + "mlp.expert_bias"), np.float32),
                },
                "experts": {
                    p: {"w": cast(np.stack([
                        np.asarray(get(f"{pre}mlp.experts.{j}.{p}.weight")).T
                        for j in range(moe.num_experts)
                    ]))}
                    for p in ("gate_proj", "up_proj", "down_proj")
                },
            }
            if moe.shared_expert_intermediate_size:
                mo["shared_expert"] = {
                    p: {"w": cast(get(f"{pre}mlp.shared_experts.{p}.weight")).T}
                    for p in ("gate_proj", "up_proj", "down_proj")
                }
            layer["moe"] = mo
        else:
            layer["mlp"] = {
                p: {"w": cast(get(f"{pre}mlp.{p}.weight")).T}
                for p in ("gate_proj", "up_proj", "down_proj")
            }
        layers.append(layer)

    sliding = _sliding_flags(config)
    if arch.moe is not None and 0 < k_dense < arch.num_layers:
        stacked = [dense.tree_stack(layers[:k_dense]), dense.tree_stack(layers[k_dense:])]
        for seg, sl in ((stacked[0], sliding[:k_dense]), (stacked[1], sliding[k_dense:])):
            seg["use_sliding_window"] = sl
            seg["use_rope"] = sl.copy()  # full-attention layers are NoPE
    else:
        stacked = dense.tree_stack(layers)
        stacked["use_sliding_window"] = sliding
        stacked["use_rope"] = sliding.copy()

    embed = get("embed_tokens.weight")
    if arch.vocab_pad:
        embed = np.concatenate(
            [embed, np.zeros((arch.vocab_pad, embed.shape[1]), embed.dtype)], axis=0
        )
    params: Dict[str, Any] = {
        "embed_tokens": cast(embed),
        "layers": stacked,
        "norm": cast(get("norm.weight")),
    }
    head = np.asarray(
        state_dict.get("lm_head.weight", embed[: config.vocab_size]), dtype=dt
    )
    if arch.vocab_pad:
        head = np.concatenate(
            [head, np.zeros((arch.vocab_pad, head.shape[1]), dtype=dt)], axis=0
        )
    params["lm_head"] = head.T
    return params


def _seg_layer_specs(seg_arch: DecoderArch):
    import jax.numpy as jnp  # noqa: F401

    spec = decoder_param_specs(seg_arch)["layers"]
    spec["pre_feedforward_layernorm"] = REPLICATED
    spec["post_feedforward_layernorm"] = REPLICATED
    spec["use_sliding_window"] = REPLICATED
    spec["use_rope"] = REPLICATED
    return spec


def param_specs(config: InferenceConfig):
    arch = build_arch(config)
    segs = _segment_archs(config, arch)
    specs = dense.param_specs_for(arch)
    if segs is None:
        specs["layers"] = _seg_layer_specs(arch)
    else:
        specs["layers"] = [_seg_layer_specs(s) for s in segs]
    return specs


def _seg_layer_struct(config, seg_arch: DecoderArch):
    import jax
    import jax.numpy as jnp

    from nxdi_tpu.config import to_jax_dtype

    dt = to_jax_dtype(seg_arch.dtype)
    L, hs, D = seg_arch.num_layers, seg_arch.hidden_size, seg_arch.head_dim
    H = seg_arch.num_attention_heads
    s = lambda *shape: jax.ShapeDtypeStruct(shape, dt)  # noqa: E731
    st = dense.param_shape_struct(config, seg_arch)["layers"]
    st["pre_feedforward_layernorm"] = s(L, hs)
    st["post_feedforward_layernorm"] = s(L, hs)
    st["attn"]["gate_proj"] = {"w": s(L, hs, H * D)}
    st["use_sliding_window"] = jax.ShapeDtypeStruct((L,), jnp.bool_)
    st["use_rope"] = jax.ShapeDtypeStruct((L,), jnp.bool_)
    return st


def param_shape_struct(config: InferenceConfig):
    arch = build_arch(config)
    struct = dense.param_shape_struct(config, arch)
    segs = _segment_archs(config, arch)
    if segs is None:
        struct["layers"] = _seg_layer_struct(config, arch)
    else:
        struct["layers"] = [_seg_layer_struct(config, s) for s in segs]
    return struct
