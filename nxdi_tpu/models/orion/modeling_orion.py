"""Orion family — llama geometry with biased LayerNorm block norms.

Reference: contrib/models/orion-14b-chat (src/modeling_orion.py:50-230,
mirroring the OrionStarAI remote-code OrionForCausalLM): pre-norm llama
whose ``input_layernorm``/``post_attention_layernorm``/final ``norm`` are
full nn.LayerNorm (weight + bias, eps = rms_norm_eps); no projection
biases."""

from __future__ import annotations

import numpy as np

from nxdi_tpu.config import InferenceConfig
from nxdi_tpu.models import dense
from nxdi_tpu.models.base import DecoderArch

build_inv_freq = dense.build_inv_freq


class OrionInferenceConfig(dense.DenseInferenceConfig):
    pass


def build_arch(config: InferenceConfig, **overrides) -> DecoderArch:
    kwargs = dict(layernorm=True)
    kwargs.update(overrides)
    return dense.build_arch(config, **kwargs)


def convert_hf_state_dict(state_dict, config: InferenceConfig):
    arch = build_arch(config)
    dt = dense.np_dtype(arch.dtype)
    L = arch.num_layers

    def src(name):
        for k in (name, f"model.{name}"):
            if k in state_dict:
                return np.asarray(state_dict[k])
        raise KeyError(name)

    params = dense.convert_hf_state_dict(state_dict, config, arch)
    return dense.attach_norm_biases(
        params,
        [src(f"layers.{i}.input_layernorm.bias") for i in range(L)],
        [src(f"layers.{i}.post_attention_layernorm.bias") for i in range(L)],
        src("norm.bias"), dt,
    )


def param_specs(config: InferenceConfig):
    return dense.biased_layernorm_specs(dense.param_specs_for(build_arch(config)))


def param_shape_struct(config: InferenceConfig):
    from nxdi_tpu.config import to_jax_dtype

    arch = build_arch(config)
    return dense.biased_layernorm_struct(
        dense.param_shape_struct(config, arch),
        arch.num_layers, arch.hidden_size, to_jax_dtype(arch.dtype),
    )
