"""EAGLE draft model family (llama-lineage).

The reference expresses EAGLE drafts as a llama variant with an ``fc`` layer
fusing (embedding, previous hidden state) (modeling_llama.py:1408-1416) and
wires them into fused speculation via ``FusedSpecNeuronConfig``
(config.py:1009). Here the draft is its own model family: the dense param
layout (models/dense.py) plus

  - ``fc``          — (2H, H) projection of concat(embed, feature),
  - ``fc_features`` — (kH, H) EAGLE3 aux-feature projection (k = number of
                      captured target layers),
  - ``d2t``         — optional EAGLE3 draft→target vocab id table,
  - ``input_norm_skip`` — per-layer flag: official EAGLE drafts feed the fc
                      output into attention without an input layernorm for
                      layer 0; the flag rides the layer scan (models/base.py).

EAGLE drafts have no final norm; conversion simply omits ``norm`` and the
forward skips it (models/base.py handles a missing ``norm``).
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from nxdi_tpu.models import dense
from nxdi_tpu.models.base import DecoderArch
from nxdi_tpu.parallel.layers import REPLICATED

build_inv_freq = dense.build_inv_freq


class LlamaEagleInferenceConfig(dense.DenseInferenceConfig):
    """Draft hyperparams; ``target_vocab_size`` is set (by the application)
    when the draft vocabulary is reduced (EAGLE3 d2t)."""

    def add_derived_config(self):
        super().add_derived_config()
        # drafts always own an explicit lm_head over (possibly reduced) vocab
        self.tie_word_embeddings = False


def build_arch(config, **overrides) -> DecoderArch:
    return dense.build_arch(config, **overrides)


def _layer_key(i: int, name: str) -> str:
    return f"layers.{i}.{name}"


def convert_hf_state_dict(
    state_dict: Dict[str, np.ndarray], config, arch: DecoderArch = None
) -> Dict[str, Any]:
    arch = arch or build_arch(config)
    dt = dense.np_dtype(arch.dtype)
    sd = dict(state_dict)

    # strip HF "model." prefixes once so the probes below are uniform
    sd = {k[len("model."):] if k.startswith("model.") else k: v for k, v in sd.items()}

    # layer-0 input layernorm is absent in official EAGLE drafts: synthesize a
    # placeholder weight (never read — the skip flag bypasses it) and record
    # which layers skip
    skip = np.zeros((arch.num_layers,), dtype=bool)
    for i in range(arch.num_layers):
        key = _layer_key(i, "input_layernorm.weight")
        if key not in sd:
            sd[key] = np.ones((arch.hidden_size,), dtype=dt)
            skip[i] = True

    had_norm = "norm.weight" in sd
    if not had_norm:
        sd["norm.weight"] = np.ones((arch.hidden_size,), dtype=dt)

    # EAGLE3 reduced draft vocab: the draft EMBEDS target-vocab ids (borrowed
    # target table) but its lm_head scores only the draft vocab; d2t maps the
    # argmax back to target ids. Stash the target-vocab embedding so the dense
    # converter (which assumes one vocab) pads only the lm_head side. Gated on
    # is_eagle3 — the same predicate param_specs/param_shape_struct use — so
    # the three pytrees always agree regardless of checkpoint contents.
    is_eagle3 = bool(config.tpu_config.is_eagle3)
    target_embed = None
    if is_eagle3:
        target_embed = np.asarray(sd["embed_tokens.weight"], dtype=dt)
        sd["embed_tokens.weight"] = np.zeros(
            (config.vocab_size, arch.hidden_size), dtype=dt
        )
    elif "d2t" in sd:
        del sd["d2t"]  # non-eagle3 drafts have no reduced vocab to translate

    params = dense.convert_hf_state_dict(sd, config, arch)
    if not had_norm:
        del params["norm"]
    params["layers"]["input_norm_skip"] = skip

    if target_embed is not None:
        tp = config.tpu_config.tp_degree
        tv = target_embed.shape[0]
        pad = (-tv) % tp
        if pad:
            target_embed = np.concatenate(
                [target_embed, np.zeros((pad, target_embed.shape[1]), dtype=dt)], axis=0
            )
        params["embed_tokens"] = target_embed

    # biases are always present in the pytree (zeros when the checkpoint has
    # none) so params/specs/struct agree regardless of checkpoint contents —
    # official EAGLE drafts ship fc WITH bias, many retrains without
    def _proj(prefix):
        w = np.asarray(sd[f"{prefix}.weight"], dtype=dt).T
        b = (
            np.asarray(sd[f"{prefix}.bias"], dtype=dt)
            if f"{prefix}.bias" in sd
            else np.zeros((w.shape[1],), dtype=dt)
        )
        return {"w": w, "b": b}

    params["fc"] = _proj("fc")
    if "fc_features.weight" in sd:
        params["fc_features"] = _proj("fc_features")
    elif is_eagle3:
        raise KeyError(
            "is_eagle3 requires an fc_features.weight in the draft checkpoint "
            "(projects the concatenated target aux hidden states)"
        )
    if is_eagle3:
        draft_vocab = arch.vocab_size - arch.vocab_pad
        params["d2t"] = (
            np.asarray(sd["d2t"], dtype=np.int32)
            if "d2t" in sd
            else np.arange(draft_vocab, dtype=np.int32)  # full-vocab draft head
        )
    return params


def param_specs(config) -> Dict[str, Any]:
    arch = build_arch(config)
    specs = dense.param_specs_for(arch)
    specs.pop("norm", None)
    specs["layers"]["input_norm_skip"] = REPLICATED
    specs["fc"] = {"w": REPLICATED, "b": REPLICATED}
    if config.tpu_config.is_eagle3:
        specs["fc_features"] = {"w": REPLICATED, "b": REPLICATED}
        specs["d2t"] = REPLICATED
    return specs


def param_shape_struct(config) -> Dict[str, Any]:
    import jax
    import jax.numpy as jnp

    from nxdi_tpu.config import to_jax_dtype

    arch = build_arch(config)
    struct = dense.param_shape_struct(config, arch)
    struct.pop("norm", None)
    dt = to_jax_dtype(arch.dtype)
    H = arch.hidden_size
    struct["layers"]["input_norm_skip"] = jax.ShapeDtypeStruct(
        (arch.num_layers,), jnp.bool_
    )
    struct["fc"] = {
        "w": jax.ShapeDtypeStruct((2 * H, H), dt),
        "b": jax.ShapeDtypeStruct((H,), dt),
    }
    if config.tpu_config.is_eagle3:
        k = len(eagle3_aux_indices_default(getattr(config, "target_num_layers", 3)))
        Ht = getattr(config, "target_hidden_size", H)
        struct["fc_features"] = {
            "w": jax.ShapeDtypeStruct((k * Ht, H), dt),
            "b": jax.ShapeDtypeStruct((H,), dt),
        }
        struct["d2t"] = jax.ShapeDtypeStruct((arch.vocab_size - arch.vocab_pad,), jnp.int32)
        tv = getattr(config, "target_vocab_size", None) or (arch.vocab_size - arch.vocab_pad)
        tp = config.tpu_config.tp_degree
        struct["embed_tokens"] = jax.ShapeDtypeStruct(
            (tv + (-tv) % tp, arch.hidden_size), dt
        )
    return struct


def eagle3_aux_indices_default(target_num_layers: int):
    """Which target layers feed the EAGLE3 feature concat: an early, middle,
    and late layer (clamped for tiny test models)."""
    L = target_num_layers
    idx = sorted({max(0, min(L - 1, i)) for i in (1, L // 2, L - 2)})
    return tuple(idx)
