from nxdi_tpu.models.olmo2 import modeling_olmo2  # noqa: F401
