"""OLMo-2 family — post-block-norm llama variant with flat qk rmsnorm.

Reference: contrib/models/OLMo-2-* (community hub). Architectural deltas vs
llama, all expressed as shared-decoder switches (models/base.py):
  - NO input layernorms; RMSNorm on the attention/MLP OUTPUT before the
    residual add (``post_block_norm``) — the conversion aliases the HF
    ``post_attention_layernorm`` -> layer key "input_layernorm" (used as the
    attn post-norm) and ``post_feedforward_layernorm`` ->
    "post_attention_layernorm" (the mlp post-norm);
  - RMSNorm over the FLAT q/k projections before head reshape
    (``qk_norm_flat``, same switch as minimax-m2).
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from nxdi_tpu.config import InferenceConfig
from nxdi_tpu.models import dense
from nxdi_tpu.models.base import DecoderArch
from nxdi_tpu.models.minimax_m2.modeling_minimax_m2 import _add_flat_norm_entries
from nxdi_tpu.parallel import gqa

build_inv_freq = dense.build_inv_freq


class Olmo2InferenceConfig(dense.DenseInferenceConfig):
    pass


def build_arch(config: InferenceConfig, **overrides) -> DecoderArch:
    kwargs = dict(
        post_block_norm=True,
        qk_norm_flat=True,
        qk_norm_flat_qdim=config.num_attention_heads * dense.head_dim_of(config),
    )
    kwargs.update(overrides)
    return dense.build_arch(config, **kwargs)


def convert_hf_state_dict(
    state_dict: Dict[str, np.ndarray], config: InferenceConfig
) -> Dict[str, Any]:
    arch = build_arch(config)
    # alias the post-norms onto the standard layer keys (see module docstring)
    sd = dict(state_dict)
    for i in range(config.num_hidden_layers):
        p = f"model.layers.{i}."
        sd[p + "input_layernorm.weight"] = sd[p + "post_attention_layernorm.weight"]
        sd[p + "post_attention_layernorm.weight"] = sd.pop(
            p + "post_feedforward_layernorm.weight"
        )
    params = dense.convert_hf_state_dict(sd, config, arch)

    plan = dense.gqa_plan(config)
    D = arch.head_dim
    dt = dense.np_dtype(arch.dtype)

    def grab(i, side, conv):
        w = state_dict[f"model.layers.{i}.self_attn.{side}.weight"]
        return np.asarray(conv(w[:, None], D, plan)[:, 0], dt)

    params["layers"]["attn"]["q_norm"] = np.stack(
        [grab(i, "q_norm", gqa.convert_q) for i in range(arch.num_layers)]
    )
    params["layers"]["attn"]["k_norm"] = np.stack(
        [grab(i, "k_norm", gqa.convert_kv) for i in range(arch.num_layers)]
    )
    return params


def param_specs(config: InferenceConfig):
    arch = build_arch(config)
    return _add_flat_norm_entries(arch, dense.param_specs_for(arch), "spec")


def param_shape_struct(config: InferenceConfig):
    arch = build_arch(config)
    return _add_flat_norm_entries(
        arch, dense.param_shape_struct(config, arch), "struct"
    )
