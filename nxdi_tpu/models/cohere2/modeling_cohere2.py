"""Cohere2 (Command R7B) family — PARALLEL attention+MLP block, interleaved
sliding windows with local-only rope, scaled logits.

Reference: contrib/models/c4ai-command-r7b-12-2024. HF Cohere2ForCausalLM
(modeling_cohere2.py:79-500):
  - ONE (mean-subtracted, weight-only) LayerNorm per layer; attention and
    MLP both read it and sum into a single residual (``parallel_block``;
    the shared norm is aliased onto both norm keys at conversion);
  - GPT-J interleaved-pair rope, applied ONLY on sliding-window layers
    (global layers are NoPE) — per-layer use_sliding_window/use_rope flags;
  - logits multiplied by ``logit_scale`` (mapped onto the dividing
    ``logits_scaling`` switch); embeddings tied."""

from __future__ import annotations

import numpy as np

from nxdi_tpu.config import InferenceConfig
from nxdi_tpu.models import dense
from nxdi_tpu.models.base import DecoderArch
from nxdi_tpu.parallel.layers import REPLICATED

build_inv_freq = dense.build_inv_freq


class Cohere2InferenceConfig(dense.DenseInferenceConfig):
    def add_derived_config(self):
        self.rms_norm_eps = getattr(self, "layer_norm_eps", 1e-5)
        if not hasattr(self, "tie_word_embeddings"):
            self.tie_word_embeddings = True
        super().add_derived_config()
        if getattr(self, "use_qk_norm", False):
            raise NotImplementedError("cohere2 use_qk_norm is not supported yet")
        if not hasattr(self, "layer_types") or self.layer_types is None:
            pat = getattr(self, "sliding_window_pattern", 4) or 4
            self.layer_types = [
                "full_attention" if (i + 1) % pat == 0 else "sliding_attention"
                for i in range(self.num_hidden_layers)
            ]


def build_arch(config: InferenceConfig, **overrides) -> DecoderArch:
    sw = getattr(config, "sliding_window", None)
    kwargs = dict(
        parallel_block=True,
        layernorm=True,
        rope_interleaved=True,
        sliding_window=sw,
        # window_sized_kv: full-attention layers stay off the ring
        kv_window_pattern=tuple(_flags(config)) if sw else None,
        logits_scaling=1.0 / float(getattr(config, "logit_scale", 1.0)),
        tie_word_embeddings=bool(getattr(config, "tie_word_embeddings", True)),
    )
    kwargs.update(overrides)
    return dense.build_arch(config, **kwargs)


def _flags(config):
    sliding = np.array(
        [t == "sliding_attention" for t in config.layer_types], dtype=bool
    )
    return sliding


def convert_hf_state_dict(state_dict, config: InferenceConfig):
    arch = build_arch(config)
    # ONE norm per layer: alias it onto post_attention_layernorm so the
    # parallel block's MLP branch reads the same weights
    sd = dict(state_dict)
    for i in range(config.num_hidden_layers):
        for pre in ("model.layers.", "layers."):
            key = f"{pre}{i}.input_layernorm.weight"
            if key in sd:
                sd[f"{pre}{i}.post_attention_layernorm.weight"] = sd[key]
    params = dense.convert_hf_state_dict(sd, config, arch)
    sliding = _flags(config)
    params["layers"]["use_sliding_window"] = sliding
    params["layers"]["use_rope"] = sliding.copy()  # global layers are NoPE
    return params


def param_specs(config: InferenceConfig):
    specs = dense.param_specs_for(build_arch(config))
    specs["layers"]["use_sliding_window"] = REPLICATED
    specs["layers"]["use_rope"] = REPLICATED
    return specs


def param_shape_struct(config: InferenceConfig):
    import jax
    import jax.numpy as jnp

    struct = dense.param_shape_struct(config, build_arch(config))
    L = config.num_hidden_layers
    struct["layers"]["use_sliding_window"] = jax.ShapeDtypeStruct((L,), jnp.bool_)
    struct["layers"]["use_rope"] = jax.ShapeDtypeStruct((L,), jnp.bool_)
    return struct
