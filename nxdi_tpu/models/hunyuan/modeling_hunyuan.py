"""HunYuan dense v1 family — llama geometry + per-head q/k RMSNorm.

Reference: contrib/models/hunyuan-7b-instruct. HF HunYuanDenseV1ForCausalLM
(modeling_hunyuan_v1_dense.py:155-210): per-head ``query_layernorm`` /
``key_layernorm`` RMSNorms applied after head reshape and before rope
(mapped onto the shared qk_norm switch with a key rename), explicit
``head_dim``, silu gated MLP."""

from __future__ import annotations

from nxdi_tpu.config import InferenceConfig
from nxdi_tpu.models import dense
from nxdi_tpu.models.base import DecoderArch

build_inv_freq = dense.build_inv_freq


class HunYuanInferenceConfig(dense.DenseInferenceConfig):
    pass


def build_arch(config: InferenceConfig, **overrides) -> DecoderArch:
    kwargs = dict(qk_norm=True)
    kwargs.update(overrides)
    return dense.build_arch(config, **kwargs)


def convert_hf_state_dict(state_dict, config: InferenceConfig):
    sd = dict(state_dict)
    for k in list(sd):
        if "self_attn.query_layernorm." in k:
            sd[k.replace("query_layernorm", "q_norm")] = sd.pop(k)
        elif "self_attn.key_layernorm." in k:
            sd[k.replace("key_layernorm", "k_norm")] = sd.pop(k)
    return dense.convert_hf_state_dict(sd, config, build_arch(config))


def param_specs(config: InferenceConfig):
    return dense.param_specs_for(build_arch(config))


def param_shape_struct(config: InferenceConfig):
    return dense.param_shape_struct(config, build_arch(config))
