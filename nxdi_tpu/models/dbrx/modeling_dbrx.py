"""DBRX family — 16-expert MoE with fused Wqkv, qkv clamp and LayerNorm.

Reference: models/dbrx/modeling_dbrx.py (308 LoC). Distinguishing traits vs
the llama lineage: weight-only LayerNorm (not RMSNorm), a fused ``Wqkv``
projection whose output is clamped to ±clip_qkv, packed expert weights
(``experts.mlp.w1/v1/w2`` holding all experts stacked on the row dim), and a
router whose top-k weights renormalize by their sum — the same semantics as
mixtral's router, so ops/moe.py is reused as-is.

HF config nests attention/ffn knobs under ``attn_config``/``ffn_config``;
the InferenceConfig flattens them to the shared field names.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from nxdi_tpu.config import InferenceConfig
from nxdi_tpu.models import dense
from nxdi_tpu.models.base import DecoderArch
from nxdi_tpu.ops.moe import MoEArch, moe_parallel_fields

build_inv_freq = dense.build_inv_freq


class DbrxInferenceConfig(dense.DenseInferenceConfig):
    REQUIRED = ["d_model", "n_heads", "n_layers", "vocab_size"]

    def add_derived_config(self):
        # flatten dbrx's nested config blocks into the shared names
        attn = getattr(self, "attn_config", None) or {}
        ffn = getattr(self, "ffn_config", None) or {}
        if not isinstance(attn, dict):
            attn = dict(attn)
        if not isinstance(ffn, dict):
            ffn = dict(ffn)
        self.hidden_size = self.d_model
        self.num_attention_heads = self.n_heads
        self.num_hidden_layers = self.n_layers
        self.num_key_value_heads = attn.get("kv_n_heads", self.n_heads)
        self.rope_theta = attn.get("rope_theta", 10000.0)
        self.clip_qkv = attn.get("clip_qkv")
        self.intermediate_size = ffn.get("ffn_hidden_size", 4 * self.d_model)
        self.num_local_experts = ffn.get("moe_num_experts", 16)
        self.num_experts_per_tok = ffn.get("moe_top_k", 4)
        act = ffn.get("ffn_act_fn") or {}
        self.hidden_act = act.get("name", "silu")
        self.rms_norm_eps = 1e-5  # LayerNorm eps (HF nn.LayerNorm default)
        self.rope_scaling = None
        self.tie_word_embeddings = False
        super().add_derived_config()


def _moe_arch(config: InferenceConfig) -> MoEArch:
    return MoEArch(
        num_experts=config.num_local_experts,
        top_k=config.num_experts_per_tok,
        intermediate_size=config.intermediate_size,
        hidden_act=config.hidden_act,
        norm_topk_prob=True,  # dbrx: / sum(top_weights) (p=1 norm of softmax)
        **moe_parallel_fields(config.tpu_config, config.num_local_experts),
    )


def build_arch(config: InferenceConfig, **overrides) -> DecoderArch:
    return dense.build_arch(
        config,
        **{
            "moe": _moe_arch(config),
            "layernorm": True,
            "clip_qkv": getattr(config, "clip_qkv", None),
            **overrides,
        },
    )


def convert_hf_state_dict(
    state_dict: Dict[str, np.ndarray], config: InferenceConfig
) -> Dict[str, Any]:
    """dbrx HF layout (transformer.blocks.{i}...) -> the shared dense layout,
    then the dense converter does GQA padding etc."""
    arch = build_arch(config)
    E = arch.moe.num_experts
    inter, hs = arch.moe.intermediate_size, config.hidden_size
    kv_dim = config.num_key_value_heads * (hs // config.num_attention_heads)

    sd: Dict[str, np.ndarray] = {}
    sd["embed_tokens.weight"] = state_dict["transformer.wte.weight"]
    sd["norm.weight"] = state_dict["transformer.norm_f.weight"]
    sd["lm_head.weight"] = state_dict["lm_head.weight"]
    for i in range(arch.num_layers):
        src = f"transformer.blocks.{i}."
        dst = f"layers.{i}."
        qkv = state_dict[src + "norm_attn_norm.attn.Wqkv.weight"]  # (hs+2kv, hs)
        sd[dst + "self_attn.q_proj.weight"] = qkv[:hs]
        sd[dst + "self_attn.k_proj.weight"] = qkv[hs : hs + kv_dim]
        sd[dst + "self_attn.v_proj.weight"] = qkv[hs + kv_dim :]
        sd[dst + "self_attn.o_proj.weight"] = state_dict[src + "norm_attn_norm.attn.out_proj.weight"]
        sd[dst + "input_layernorm.weight"] = state_dict[src + "norm_attn_norm.norm_1.weight"]
        sd[dst + "post_attention_layernorm.weight"] = state_dict[src + "norm_attn_norm.norm_2.weight"]

    def ff(get, has, cast, pre):
        i = int(pre.split(".")[1])
        src = f"transformer.blocks.{i}.ffn."
        # packed (E*inter, hs) rows -> (E, hs, inter) stacked layout;
        # w2 rows are (inter, hs) per expert already (x @ w2, no transpose)
        w1 = state_dict[src + "experts.mlp.w1"].reshape(E, inter, hs)
        v1 = state_dict[src + "experts.mlp.v1"].reshape(E, inter, hs)
        w2 = state_dict[src + "experts.mlp.w2"].reshape(E, inter, hs)
        return "moe", {
            "router": {"w": cast(state_dict[src + "router.layer.weight"].T)},
            "experts": {
                "gate_proj": {"w": cast(np.swapaxes(w1, 1, 2))},
                "up_proj": {"w": cast(np.swapaxes(v1, 1, 2))},
                "down_proj": {"w": cast(w2)},
            },
        }

    return dense.convert_hf_state_dict(sd, config, arch, ff_converter=ff)


def param_specs(config: InferenceConfig):
    return dense.param_specs_for(build_arch(config))


def param_shape_struct(config: InferenceConfig):
    return dense.param_shape_struct(config, build_arch(config))
