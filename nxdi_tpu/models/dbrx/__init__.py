from nxdi_tpu.models.dbrx import modeling_dbrx
