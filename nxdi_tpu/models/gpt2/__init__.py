from nxdi_tpu.models.gpt2 import modeling_gpt2
