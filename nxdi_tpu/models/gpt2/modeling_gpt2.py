"""GPT-2 family (reference scope: the contrib hub's gpt2 model).

The oldest layout the hub supports and the one that exercises the non-rope
path: learned position embeddings, biased pre-LayerNorms, a fused ``c_attn``
projection stored in Conv1D (in, out) orientation, a plain (non-gated)
gelu MLP, and tied lm_head.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from nxdi_tpu.config import InferenceConfig
from nxdi_tpu.models import dense
from nxdi_tpu.models.base import DecoderArch
from nxdi_tpu.parallel.layers import REPLICATED


class GPT2InferenceConfig(dense.DenseInferenceConfig):
    REQUIRED = ["n_embd", "n_head", "n_layer", "vocab_size", "n_positions"]

    def add_derived_config(self):
        self.hidden_size = self.n_embd
        self.num_attention_heads = self.n_head
        self.num_hidden_layers = self.n_layer
        self.num_key_value_heads = self.n_head
        self.intermediate_size = getattr(self, "n_inner", None) or 4 * self.n_embd
        self.rms_norm_eps = getattr(self, "layer_norm_epsilon", 1e-5)
        self.hidden_act = getattr(self, "activation_function", "gelu_new")
        self.tie_word_embeddings = True
        self.rope_theta = 10000.0  # unused (no_rope)
        self.rope_scaling = None
        super().add_derived_config()
        if self.tpu_config.seq_len > self.n_positions:
            raise ValueError(
                f"seq_len {self.tpu_config.seq_len} exceeds the checkpoint's "
                f"learned position table (n_positions={self.n_positions})"
            )


def build_arch(config: InferenceConfig, **overrides) -> DecoderArch:
    kwargs = dict(
        learned_pos_embeds=True,
        no_rope=True,
        gated_mlp=False,
        attention_bias=True,
        attention_o_bias=True,
        mlp_bias=True,
        tie_word_embeddings=True,
    )
    kwargs.update(overrides)
    return dense.build_arch(config, **kwargs)


def build_inv_freq(config: InferenceConfig) -> np.ndarray:
    # unused (no_rope) but the pipeline expects a frequency table
    from nxdi_tpu.ops.rope import default_inv_freq

    return default_inv_freq(config.hidden_size // config.num_attention_heads, 10000.0)


def convert_hf_state_dict(
    state_dict: Dict[str, np.ndarray], config: InferenceConfig
) -> Dict[str, Any]:
    """GPT2 HF layout -> dense layout. Conv1D weights are stored (in, out);
    the dense converter expects HF (out, in), so fused splits transpose."""
    arch = build_arch(config)
    H = config.hidden_size

    def src(name):
        for k in (name, f"transformer.{name}"):
            if k in state_dict:
                return np.asarray(state_dict[k])
        raise KeyError(name)

    sd: Dict[str, np.ndarray] = {
        "embed_tokens.weight": src("wte.weight"),
        "norm.weight": src("ln_f.weight"),
    }
    norm_biases: Dict[str, np.ndarray] = {"norm": src("ln_f.bias")}
    for i in range(arch.num_layers):
        pre = f"h.{i}."
        dst = f"layers.{i}."
        ca_w = src(pre + "attn.c_attn.weight")  # (H, 3H) in,out
        ca_b = src(pre + "attn.c_attn.bias")  # (3H,)
        sd[dst + "self_attn.q_proj.weight"] = ca_w[:, :H].T
        sd[dst + "self_attn.k_proj.weight"] = ca_w[:, H : 2 * H].T
        sd[dst + "self_attn.v_proj.weight"] = ca_w[:, 2 * H :].T
        sd[dst + "self_attn.q_proj.bias"] = ca_b[:H]
        sd[dst + "self_attn.k_proj.bias"] = ca_b[H : 2 * H]
        sd[dst + "self_attn.v_proj.bias"] = ca_b[2 * H :]
        sd[dst + "self_attn.o_proj.weight"] = src(pre + "attn.c_proj.weight").T
        sd[dst + "self_attn.o_proj.bias"] = src(pre + "attn.c_proj.bias")
        sd[dst + "mlp.up_proj.weight"] = src(pre + "mlp.c_fc.weight").T
        sd[dst + "mlp.up_proj.bias"] = src(pre + "mlp.c_fc.bias")
        sd[dst + "mlp.down_proj.weight"] = src(pre + "mlp.c_proj.weight").T
        sd[dst + "mlp.down_proj.bias"] = src(pre + "mlp.c_proj.bias")
        # gated_mlp=False has no gate_proj, but the dense converter still
        # probes one — synthesize nothing; handled below via custom mlp conv
        sd[dst + "input_layernorm.weight"] = src(pre + "ln_1.weight")
        sd[dst + "post_attention_layernorm.weight"] = src(pre + "ln_2.weight")
        norm_biases[f"layers.{i}.input"] = src(pre + "ln_1.bias")
        norm_biases[f"layers.{i}.post"] = src(pre + "ln_2.bias")

    def ff(get, has, cast, pre):
        return "mlp", {
            "up_proj": {"w": cast(get(pre + "mlp.up_proj.weight").T),
                        "b": cast(get(pre + "mlp.up_proj.bias"))},
            "down_proj": {"w": cast(get(pre + "mlp.down_proj.weight").T),
                          "b": cast(get(pre + "mlp.down_proj.bias"))},
        }

    params = dense.convert_hf_state_dict(sd, config, arch, ff_converter=ff)
    dt = dense.np_dtype(arch.dtype)
    L = arch.num_layers
    # biased LayerNorms: replace the weight-only arrays with {"w","b"} dicts
    params["layers"]["input_layernorm"] = {
        "w": params["layers"]["input_layernorm"],
        "b": np.stack([norm_biases[f"layers.{i}.input"] for i in range(L)]).astype(dt),
    }
    params["layers"]["post_attention_layernorm"] = {
        "w": params["layers"]["post_attention_layernorm"],
        "b": np.stack([norm_biases[f"layers.{i}.post"] for i in range(L)]).astype(dt),
    }
    params["norm"] = {"w": params["norm"], "b": norm_biases["norm"].astype(dt)}
    params["position_embeddings"] = np.asarray(src("wpe.weight"), dtype=dt)
    return params


def param_specs(config: InferenceConfig):
    from jax.sharding import PartitionSpec as P

    specs = dense.param_specs_for(build_arch(config))
    specs["layers"]["input_layernorm"] = {"w": REPLICATED, "b": REPLICATED}
    specs["layers"]["post_attention_layernorm"] = {"w": REPLICATED, "b": REPLICATED}
    specs["norm"] = {"w": P(), "b": P()}
    specs["position_embeddings"] = REPLICATED
    return specs


def param_shape_struct(config: InferenceConfig):
    import jax

    from nxdi_tpu.config import to_jax_dtype

    arch = build_arch(config)
    struct = dense.param_shape_struct(config, arch)
    dt = to_jax_dtype(arch.dtype)
    L, H = arch.num_layers, arch.hidden_size

    def s(*shape):
        return jax.ShapeDtypeStruct(shape, dt)

    struct["layers"]["input_layernorm"] = {"w": s(L, H), "b": s(L, H)}
    struct["layers"]["post_attention_layernorm"] = {"w": s(L, H), "b": s(L, H)}
    struct["norm"] = {"w": s(H), "b": s(H)}
    struct["position_embeddings"] = s(config.n_positions, H)
    return struct
