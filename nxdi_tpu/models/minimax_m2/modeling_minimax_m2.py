"""MiniMax-M2 — 256-expert sigmoid-routed MoE with flat qk-norm and partial
rotary (the reference's flagship published-benchmark model, BASELINE.md).

Reference: models/minimax_m2/modeling_minimax_m2.py (3878 LoC) — all of its
architectural deltas vs llama map onto existing framework switches:
  - MoE every layer: sigmoid affinities, e_score_correction_bias added ONLY
    for expert selection, weights renormalized from the uncorrected scores
    (RouterTopKWithBias :56) -> MoEArch(sigmoid_routing, correction_bias,
    norm_topk_prob).
  - "per_layer" qk-norm: RMSNorm over the FLAT q/k projection before head
    reshape (:260) -> DecoderArch.qk_norm_flat (GQA-padding-safe: fixed true
    denominator for zero-padded q, plain mean for replicated k).
  - partial rotary rotary_dim=64 of head_dim=128 (:730) ->
    DecoderArch.rotary_dim; inv_freq built at rotary_dim.
MTP (multi-token-prediction) weights in the checkpoint are serving-irrelevant
and dropped, matching the reference which serves the causal trunk only.

HF weight layout: llama-style attention (+ flat q_norm/k_norm vectors) and
``block_sparse_moe`` with ``gate``, ``experts.{i}.w1/w3/w2`` (gate/up/down),
``e_score_correction_bias``.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from nxdi_tpu.config import InferenceConfig
from nxdi_tpu.models import dense
from nxdi_tpu.models.base import DecoderArch
from nxdi_tpu.ops.moe import MoEArch, convert_hf_experts, moe_parallel_fields
from nxdi_tpu.ops.rope import inv_freq_from_hf_config
from nxdi_tpu.parallel import gqa

_W_NAMES = {"gate": "w1", "up": "w3", "down": "w2"}


class MiniMaxM2InferenceConfig(dense.DenseInferenceConfig):
    REQUIRED = dense.DenseInferenceConfig.REQUIRED + [
        "num_local_experts",
        "num_experts_per_tok",
        "rotary_dim",
        "use_qk_norm",
    ]


def _moe_arch(config: InferenceConfig) -> MoEArch:
    return MoEArch(
        num_experts=config.num_local_experts,
        top_k=config.num_experts_per_tok,
        intermediate_size=config.intermediate_size,
        hidden_act=getattr(config, "hidden_act", "silu"),
        norm_topk_prob=True,
        sigmoid_routing=True,
        correction_bias=True,
        **moe_parallel_fields(config.tpu_config, config.num_local_experts),
    )


def build_arch(config: InferenceConfig, **overrides) -> DecoderArch:
    rd = int(getattr(config, "rotary_dim", 0) or 0)
    kwargs: Dict[str, Any] = {"moe": _moe_arch(config)}
    if rd and rd < dense.head_dim_of(config):
        kwargs["rotary_dim"] = rd
    if getattr(config, "use_qk_norm", False):
        kwargs["qk_norm_flat"] = True
        kwargs["qk_norm_flat_qdim"] = (
            config.num_attention_heads * dense.head_dim_of(config)
        )
    kwargs.update(overrides)
    return dense.build_arch(config, **kwargs)


def build_inv_freq(config: InferenceConfig) -> np.ndarray:
    rd = int(getattr(config, "rotary_dim", 0) or 0) or dense.head_dim_of(config)
    return inv_freq_from_hf_config(
        rd,
        getattr(config, "rope_theta", 10000.0),
        None,
        max_position_embeddings=getattr(config, "max_position_embeddings", 4096),
    )


def convert_hf_state_dict(
    state_dict: Dict[str, np.ndarray], config: InferenceConfig
) -> Dict[str, Any]:
    arch = build_arch(config)
    # drop MTP module weights (serving uses the causal trunk only)
    state_dict = {k: v for k, v in state_dict.items() if ".mtp" not in k and "mtp_" not in k}

    def ff(get, has, cast, pre):
        moe_params = convert_hf_experts(
            get,
            cast,
            arch.moe.num_experts,
            pre + "block_sparse_moe.gate.weight",
            lambda j, proj: f"{pre}block_sparse_moe.experts.{j}.{_W_NAMES[proj]}.weight",
        )
        moe_params["router"]["e_bias"] = np.asarray(
            get(pre + "block_sparse_moe.e_score_correction_bias"), np.float32
        )
        return "moe", moe_params

    params = dense.convert_hf_state_dict(state_dict, config, arch, ff_converter=ff)

    if arch.qk_norm_flat:
        # flat norm weights follow the projections' GQA padding layout:
        # q interleaved zero-pad, k per-head replication (vector variant of
        # the bias conversion)
        plan = dense.gqa_plan(config)
        D = arch.head_dim
        dt = dense.np_dtype(arch.dtype)

        def grab(i, side, conv):
            w = state_dict[f"model.layers.{i}.self_attn.{side}.weight"]
            return np.asarray(conv(w[:, None], D, plan)[:, 0], dt)

        params["layers"]["attn"]["q_norm"] = np.stack(
            [grab(i, "q_norm", gqa.convert_q) for i in range(arch.num_layers)]
        )
        params["layers"]["attn"]["k_norm"] = np.stack(
            [grab(i, "k_norm", gqa.convert_kv) for i in range(arch.num_layers)]
        )
    return params


def _add_flat_norm_entries(arch: DecoderArch, specs_or_struct, kind: str):
    import jax
    from jax.sharding import PartitionSpec as P

    from nxdi_tpu.parallel.mesh import AXIS_MP

    attn = specs_or_struct["layers"]["attn"]
    if kind == "spec":
        # weights multiply the tp-sharded flat projections elementwise
        attn["q_norm"] = P(None, AXIS_MP)
        attn["k_norm"] = P(None, AXIS_MP)
    else:
        dt = dense.np_dtype(arch.dtype)
        L, D = arch.num_layers, arch.head_dim
        attn["q_norm"] = jax.ShapeDtypeStruct((L, arch.num_attention_heads * D), dt)
        attn["k_norm"] = jax.ShapeDtypeStruct((L, arch.num_kv_heads * D), dt)
    return specs_or_struct


def param_specs(config: InferenceConfig):
    arch = build_arch(config)
    specs = dense.param_specs_for(arch)
    if arch.qk_norm_flat:
        specs = _add_flat_norm_entries(arch, specs, "spec")
    return specs


def param_shape_struct(config: InferenceConfig):
    arch = build_arch(config)
    struct = dense.param_shape_struct(config, arch)
    if arch.qk_norm_flat:
        struct = _add_flat_norm_entries(arch, struct, "struct")
    return struct
