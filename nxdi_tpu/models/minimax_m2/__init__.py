from nxdi_tpu.models.minimax_m2 import modeling_minimax_m2  # noqa: F401
