"""Llama model family (Llama 2/3/3.1/3.2, TinyLlama, OpenLlama...).

The canonical dense model, mirroring the role of the reference's
models/llama/modeling_llama.py (1624 LoC there). A family module exposes:
  - an ``InferenceConfig`` subclass (hyperparameter surface),
  - ``build_arch`` — :class:`DecoderArch` with family flags set,
  - ``build_inv_freq`` — rope tables (llama3 scaling supported),
  - ``convert_hf_state_dict`` — HF checkpoint -> params pytree,
  - ``param_specs`` — PartitionSpec pytree.

The forward pass itself is the shared generic decoder (models/base.py) — Llama
needs no overrides, exactly like the reference where NeuronLlamaAttention is a
thin NeuronAttentionBase subclass (reference: modeling_llama.py:1186-1250).
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from nxdi_tpu.config import InferenceConfig
from nxdi_tpu.models import dense
from nxdi_tpu.models.base import DecoderArch

# re-exported helpers (public API used by tests/tools)
gqa_plan = dense.gqa_plan
planned_head_counts = dense.planned_head_counts
padded_vocab = dense.padded_vocab
build_inv_freq = dense.build_inv_freq
jax_tree_stack = dense.tree_stack


class LlamaInferenceConfig(dense.DenseInferenceConfig):
    pass


def build_arch(config: InferenceConfig, **overrides) -> DecoderArch:
    return dense.build_arch(config, **overrides)


def convert_hf_state_dict(
    state_dict: Dict[str, np.ndarray], config: InferenceConfig
) -> Dict[str, Any]:
    return dense.convert_hf_state_dict(state_dict, config, build_arch(config))


def param_specs(config: InferenceConfig):
    return dense.param_specs_for(build_arch(config))

