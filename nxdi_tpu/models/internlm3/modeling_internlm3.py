"""InternLM3 family — llama geometry with split bias knobs.

Reference: contrib/models/internlm3-8b-instruct
(src/modeling_internlm3.py:60-120, mirroring the InternLM remote-code
InternLM3ForCausalLM): ``qkv_bias`` gates the q/k/v biases and ``bias``
the o_proj/MLP biases independently; optional explicit ``head_dim``."""

from __future__ import annotations

from nxdi_tpu.config import InferenceConfig
from nxdi_tpu.models import dense
from nxdi_tpu.models.base import DecoderArch

build_inv_freq = dense.build_inv_freq


class InternLM3InferenceConfig(dense.DenseInferenceConfig):
    def add_derived_config(self):
        if not hasattr(self, "qkv_bias"):
            self.qkv_bias = False
        if not hasattr(self, "bias"):
            self.bias = False
        super().add_derived_config()


def build_arch(config: InferenceConfig, **overrides) -> DecoderArch:
    kwargs = dict(
        attention_bias=bool(getattr(config, "qkv_bias", False)),
        attention_o_bias=bool(getattr(config, "bias", False)),
        mlp_bias=bool(getattr(config, "bias", False)),
    )
    kwargs.update(overrides)
    return dense.build_arch(config, **kwargs)


def convert_hf_state_dict(state_dict, config: InferenceConfig):
    return dense.convert_hf_state_dict(state_dict, config, build_arch(config))


def param_specs(config: InferenceConfig):
    return dense.param_specs_for(build_arch(config))


def param_shape_struct(config: InferenceConfig):
    return dense.param_shape_struct(config, build_arch(config))
