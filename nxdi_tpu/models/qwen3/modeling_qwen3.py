"""Qwen3 family (reference: models/qwen3/modeling_qwen3.py, 241 LoC).

Dense llama-lineage decoder distinguished by per-head q/k RMSNorm
(``qk_norm``), an explicit ``head_dim`` decoupled from hidden_size/heads, and
no attention biases.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from nxdi_tpu.config import InferenceConfig
from nxdi_tpu.models import dense
from nxdi_tpu.models.base import DecoderArch

build_inv_freq = dense.build_inv_freq


class Qwen3InferenceConfig(dense.DenseInferenceConfig):
    pass


def build_arch(config: InferenceConfig, **overrides) -> DecoderArch:
    return dense.build_arch(config, **{"qk_norm": True, **overrides})


def convert_hf_state_dict(
    state_dict: Dict[str, np.ndarray], config: InferenceConfig
) -> Dict[str, Any]:
    return dense.convert_hf_state_dict(state_dict, config, build_arch(config))


def param_specs(config: InferenceConfig):
    return dense.param_specs_for(build_arch(config))


