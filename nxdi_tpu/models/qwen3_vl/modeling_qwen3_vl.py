"""Qwen3-VL — interleaved M-RoPE qwen3 decoder + deepstack ViT.

Reference: models/qwen3_vl/ (1852 LoC) — the deepstack vision tower emits
per-depth feature streams that are summed into the FIRST K text layers'
outputs at image positions (model_base.py:1421-1428 analog), on top of the
qwen2-vl style flat-grid ViT. HF ``Qwen3VLForConditionalGeneration``
semantics are matched exactly.

TPU-native: the text model is the shared dense decoder (qwen3 flavor:
qk-norm, no biases) with two arch flags — interleaved M-RoPE cos/sin
(ops/rope.py) and per-layer residual injections that ride the layer scan as
xs (models/base.py run_decoder_layers ``layer_injections``). The vision
tower is one jitted program per grid; position-embedding bilinear
interpolation is folded into a host-computed (4, N) gather + weight table so
the device sees a fixed-shape weighted embedding lookup."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from nxdi_tpu.config import InferenceConfig, promote_text_config
from nxdi_tpu.models import dense
from nxdi_tpu.ops.norms import layer_norm
from nxdi_tpu.ops.rope import inv_freq_from_hf_config


class Qwen3VLInferenceConfig(dense.DenseInferenceConfig):
    REQUIRED = ["text_config", "vision_config", "image_token_id"]

    def add_derived_config(self):
        promote_text_config(self)
        vc = self.vision_config
        if not isinstance(vc, dict):
            self.vision_config = vc.to_dict()
        if not hasattr(self, "image_token_index"):
            self.image_token_index = self.image_token_id
        super().add_derived_config()


def _mrope_section(config: InferenceConfig) -> Tuple[int, ...]:
    rs = getattr(config, "rope_scaling", None) or {}
    return tuple(rs.get("mrope_section", ()))


def build_arch(config: InferenceConfig, **overrides):
    kwargs = dict(
        qk_norm=True,
        mrope_section=_mrope_section(config) or None,
        mrope_interleaved=True,
    )
    kwargs.update(overrides)
    return dense.build_arch(config, **kwargs)


def build_inv_freq(config: InferenceConfig) -> np.ndarray:
    return inv_freq_from_hf_config(
        dense.head_dim_of(config),
        getattr(config, "rope_theta", 10000.0),
        None,
        max_position_embeddings=getattr(config, "max_position_embeddings", 4096),
    )


def convert_hf_state_dict(state_dict, config: InferenceConfig):
    sd = {}
    for k, v in state_dict.items():
        for prefix in ("model.language_model.", "language_model.model.", "language_model."):
            if k.startswith(prefix):
                sd[k[len(prefix):]] = v
                break
        else:
            if k in ("lm_head.weight", "language_model.lm_head.weight"):
                sd["lm_head.weight"] = v
    return dense.convert_hf_state_dict(sd, config, build_arch(config))


def param_specs(config: InferenceConfig):
    return dense.param_specs_for(build_arch(config))


def param_shape_struct(config: InferenceConfig):
    return dense.param_shape_struct(config, build_arch(config))


# ---------------------------------------------------------------------------
# Vision tower
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Qwen3VLVisionArch:
    hidden_size: int
    intermediate_size: int
    depth: int
    num_heads: int
    patch_size: int
    temporal_patch_size: int
    in_channels: int
    spatial_merge_size: int
    out_hidden: int
    num_position_embeddings: int
    deepstack_indexes: Tuple[int, ...]
    hidden_act: str = "gelu_pytorch_tanh"

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @property
    def num_grid_per_side(self) -> int:
        return int(self.num_position_embeddings ** 0.5)


def build_vision_arch(config: InferenceConfig) -> Qwen3VLVisionArch:
    vc = config.vision_config
    return Qwen3VLVisionArch(
        hidden_size=vc["hidden_size"],
        intermediate_size=vc["intermediate_size"],
        depth=vc["depth"],
        num_heads=vc["num_heads"],
        patch_size=vc["patch_size"],
        temporal_patch_size=vc.get("temporal_patch_size", 2),
        in_channels=vc.get("in_channels", 3),
        spatial_merge_size=vc.get("spatial_merge_size", 2),
        out_hidden=vc["out_hidden_size"],
        num_position_embeddings=vc["num_position_embeddings"],
        deepstack_indexes=tuple(vc["deepstack_visual_indexes"]),
        hidden_act=vc.get("hidden_act", "gelu_pytorch_tanh"),
    )


def vision_rot_table(varch: Qwen3VLVisionArch, grid_thw) -> np.ndarray:
    """(N, head_dim) rope phase table in merge-grouped order (HF
    Qwen3VLVisionModel.rot_pos_emb)."""
    m = varch.spatial_merge_size
    dim = varch.head_dim // 2
    inv = 1.0 / (10000.0 ** (np.arange(0, dim, 2, dtype=np.float64) / dim))
    pos_list = []
    for t, h, w in grid_thw:
        mh, mw = h // m, w // m
        rows = (
            np.arange(mh)[:, None, None, None] * m + np.arange(m)[None, None, :, None]
        )
        cols = (
            np.arange(mw)[None, :, None, None] * m + np.arange(m)[None, None, None, :]
        )
        rows = np.broadcast_to(rows, (mh, mw, m, m)).reshape(-1)
        cols = np.broadcast_to(cols, (mh, mw, m, m)).reshape(-1)
        coords = np.stack([rows, cols], axis=-1)
        pos_list.append(np.tile(coords, (int(t), 1)))
    pos = np.concatenate(pos_list, axis=0)
    freqs = pos[:, :, None].astype(np.float64) * inv[None, None, :]
    half = freqs.reshape(pos.shape[0], -1)
    return np.concatenate([half, half], axis=-1).astype(np.float32)


def pos_embed_gather(varch: Qwen3VLVisionArch, grid_thw):
    """Host: bilinear pos-embed interpolation folded into (4, N) indices +
    weights in merge-grouped patch order (HF fast_pos_embed_interpolate)."""
    side = varch.num_grid_per_side
    m = varch.spatial_merge_size
    idx_all, w_all = [], []
    for t, h, w in grid_thw:
        t, h, w = int(t), int(h), int(w)
        hi = np.linspace(0, side - 1, h)
        wi = np.linspace(0, side - 1, w)
        hf_, wf_ = hi.astype(np.int64), wi.astype(np.int64)
        hc = np.clip(hf_ + 1, None, side - 1)
        wc = np.clip(wf_ + 1, None, side - 1)
        dh, dw = hi - hf_, wi - wf_
        idx = np.stack([
            (hf_[:, None] * side + wf_[None, :]).reshape(-1),
            (hf_[:, None] * side + wc[None, :]).reshape(-1),
            (hc[:, None] * side + wf_[None, :]).reshape(-1),
            (hc[:, None] * side + wc[None, :]).reshape(-1),
        ])
        wt = np.stack([
            ((1 - dh)[:, None] * (1 - dw)[None, :]).reshape(-1),
            ((1 - dh)[:, None] * dw[None, :]).reshape(-1),
            (dh[:, None] * (1 - dw)[None, :]).reshape(-1),
            (dh[:, None] * dw[None, :]).reshape(-1),
        ])
        # permute (h, w) order -> merge-grouped order, tile over t
        perm = (
            np.arange(h * w)
            .reshape(h // m, m, w // m, m)
            .transpose(0, 2, 1, 3)
            .reshape(-1)
        )
        idx = np.tile(idx[:, perm], (1, t))
        wt = np.tile(wt[:, perm], (1, t))
        idx_all.append(idx)
        w_all.append(wt)
    return (
        np.concatenate(idx_all, axis=1).astype(np.int32),
        np.concatenate(w_all, axis=1).astype(np.float32),
    )


def _merger(p, x, m2_hidden, post_norm):
    if post_norm:
        x = x.reshape(-1, m2_hidden)
        x = layer_norm(x, p["norm"]["w"], p["norm"]["b"], eps=1e-6)
    else:
        x = layer_norm(x, p["norm"]["w"], p["norm"]["b"], eps=1e-6)
        x = x.reshape(-1, m2_hidden)
    x = jax.nn.gelu(x @ p["fc1"]["w"] + p["fc1"]["b"], approximate=False)
    return x @ p["fc2"]["w"] + p["fc2"]["b"]


def vision_forward(
    varch: Qwen3VLVisionArch,
    params: Dict[str, Any],
    patches,  # (N, C * Tp * P * P)
    phases,  # (N, head_dim)
    seg_ids,  # (N,)
    pe_idx,  # (4, N) pos-embed gather indices
    pe_w,  # (4, N) bilinear weights
):
    """Returns (merged_features (N/m2, out_hidden), deepstack (K, N/m2, out_hidden))."""
    from nxdi_tpu.ops.vision import ACTS

    v = params["vision"]
    nh, d = varch.num_heads, varch.head_dim
    E = varch.hidden_size
    h = patches @ v["patch_embedding"]["w"] + v["patch_embedding"]["b"]
    pe = jnp.einsum("gn,gnh->nh", pe_w, v["pos_embed"][pe_idx])
    h = h + pe
    N = h.shape[0]
    cos = jnp.cos(phases)[:, None, :]
    sin = jnp.sin(phases)[:, None, :]
    block_mask = seg_ids[:, None] == seg_ids[None, :]
    act = ACTS[varch.hidden_act]
    m2 = varch.spatial_merge_size ** 2

    def rot(x):
        half = x.shape[-1] // 2
        return jnp.concatenate([-x[..., half:], x[..., :half]], axis=-1)

    def layer(carry, lp):
        y = layer_norm(carry, lp["ln1"]["w"], lp["ln1"]["b"], eps=1e-6)
        qkv = y @ lp["qkv"]["w"] + lp["qkv"]["b"]
        q, k, val = jnp.split(qkv.reshape(N, 3, nh, d), 3, axis=1)
        q, k, val = q[:, 0], k[:, 0], val[:, 0]
        qf, kf = q.astype(jnp.float32), k.astype(jnp.float32)
        q = qf * cos + rot(qf) * sin
        k = kf * cos + rot(kf) * sin
        s = jnp.einsum("qhd,khd->hqk", q, k, preferred_element_type=jnp.float32)
        s = s * (d ** -0.5)
        s = jnp.where(block_mask[None], s, -3.4028235e38)
        w = jax.nn.softmax(s, axis=-1).astype(val.dtype)
        attn = jnp.einsum("hqk,khd->qhd", w, val).reshape(N, nh * d)
        carry = carry + attn @ lp["proj"]["w"] + lp["proj"]["b"]
        y = layer_norm(carry, lp["ln2"]["w"], lp["ln2"]["b"], eps=1e-6)
        ff = act(y @ lp["fc1"]["w"] + lp["fc1"]["b"]) @ lp["fc2"]["w"] + lp["fc2"]["b"]
        return carry + ff

    # unrolled blocks: deepstack taps specific depths (K is small)
    deepstack = []
    for i in range(varch.depth):
        lp = jax.tree_util.tree_map(lambda x: x[i], v["blocks"])
        h = layer(h, lp)
        if i in varch.deepstack_indexes:
            k_idx = varch.deepstack_indexes.index(i)
            mp = jax.tree_util.tree_map(lambda x: x[k_idx], params["deepstack_mergers"])
            deepstack.append(_merger(mp, h, m2 * E, post_norm=True))

    merged = _merger(params["merger"], h, m2 * E, post_norm=False)
    return merged, jnp.stack(deepstack)


def vision_segment_ids(grid_thw) -> np.ndarray:
    return np.concatenate(
        [np.full(int(t * h * w), i, np.int32) for i, (t, h, w) in enumerate(grid_thw)]
    )


# family-protocol alias (presence check; the app drives the grid-aware path)
encode_images = vision_forward


def convert_vision_params(state_dict, config: InferenceConfig) -> Dict[str, Any]:
    varch = build_vision_arch(config)

    def get(name):
        for k in (f"model.visual.{name}", f"visual.{name}"):
            if k in state_dict:
                return state_dict[k]
        raise KeyError(f"missing vision weight {name}")

    f32 = lambda x: np.asarray(x, np.float32)  # noqa: E731
    conv = get("patch_embed.proj.weight")
    blocks = []
    for i in range(varch.depth):
        p = f"blocks.{i}."
        blocks.append({
            "ln1": {"w": f32(get(p + "norm1.weight")), "b": f32(get(p + "norm1.bias"))},
            "ln2": {"w": f32(get(p + "norm2.weight")), "b": f32(get(p + "norm2.bias"))},
            "qkv": {"w": f32(get(p + "attn.qkv.weight").T), "b": f32(get(p + "attn.qkv.bias"))},
            "proj": {"w": f32(get(p + "attn.proj.weight").T), "b": f32(get(p + "attn.proj.bias"))},
            "fc1": {"w": f32(get(p + "mlp.linear_fc1.weight").T), "b": f32(get(p + "mlp.linear_fc1.bias"))},
            "fc2": {"w": f32(get(p + "mlp.linear_fc2.weight").T), "b": f32(get(p + "mlp.linear_fc2.bias"))},
        })

    def merger(prefix):
        return {
            "norm": {"w": f32(get(prefix + ".norm.weight")), "b": f32(get(prefix + ".norm.bias"))},
            "fc1": {"w": f32(get(prefix + ".linear_fc1.weight").T), "b": f32(get(prefix + ".linear_fc1.bias"))},
            "fc2": {"w": f32(get(prefix + ".linear_fc2.weight").T), "b": f32(get(prefix + ".linear_fc2.bias"))},
        }

    ds = [merger(f"deepstack_merger_list.{i}") for i in range(len(varch.deepstack_indexes))]
    return {
        "vision": {
            "patch_embedding": {
                "w": f32(conv.reshape(varch.hidden_size, -1).T),
                "b": f32(get("patch_embed.proj.bias")),
            },
            "pos_embed": f32(get("pos_embed.weight")),
            "blocks": dense.tree_stack(blocks),
        },
        "merger": merger("merger"),
        "deepstack_mergers": dense.tree_stack(ds),
    }


def vision_shape_struct(config: InferenceConfig) -> Dict[str, Any]:
    varch = build_vision_arch(config)
    E, I, L = varch.hidden_size, varch.intermediate_size, varch.depth
    P2 = varch.in_channels * varch.temporal_patch_size * varch.patch_size ** 2
    m2E = varch.spatial_merge_size ** 2 * E
    K = len(varch.deepstack_indexes)

    def s(*shape):
        return jax.ShapeDtypeStruct(shape, np.float32)

    def merger_struct(n=None):
        pre = (n,) if n is not None else ()
        norm_dim = m2E if n is not None else E  # deepstack uses postshuffle norm
        return {
            "norm": {"w": s(*pre, norm_dim), "b": s(*pre, norm_dim)},
            "fc1": {"w": s(*pre, m2E, m2E), "b": s(*pre, m2E)},
            "fc2": {"w": s(*pre, m2E, varch.out_hidden), "b": s(*pre, varch.out_hidden)},
        }

    return {
        "vision": {
            "patch_embedding": {"w": s(P2, E), "b": s(E)},
            "pos_embed": s(varch.num_position_embeddings, E),
            "blocks": {
                "ln1": {"w": s(L, E), "b": s(L, E)},
                "ln2": {"w": s(L, E), "b": s(L, E)},
                "qkv": {"w": s(L, E, 3 * E), "b": s(L, 3 * E)},
                "proj": {"w": s(L, E, E), "b": s(L, E)},
                "fc1": {"w": s(L, E, I), "b": s(L, I)},
                "fc2": {"w": s(L, I, E), "b": s(L, E)},
            },
        },
        "merger": merger_struct(),
        "deepstack_mergers": merger_struct(K),
    }


def num_image_tokens(config: InferenceConfig) -> int:
    return int(getattr(config, "max_image_tokens", 0) or 64)


class Qwen3VLForConditionalGeneration:
    def __new__(cls, *args, **kwargs):
        from nxdi_tpu.models.qwen3_vl.application import Qwen3VLApplication

        return Qwen3VLApplication(*args, **kwargs)
