from nxdi_tpu.models.qwen3_vl import modeling_qwen3_vl  # noqa: F401
