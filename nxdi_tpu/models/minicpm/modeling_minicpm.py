"""MiniCPM family — llama geometry with mu-P-style scalings.

Reference: contrib/models/MiniCPM4-8B (src/modeling_minicpm.py:196-350,
mirroring the OpenBMB remote-code MiniCPMForCausalLM): embeddings scaled by
``scale_emb``, every block output scaled by ``scale_depth / sqrt(L)`` before
the residual add (the shared residual_multiplier switch), and final logits
divided by ``hidden_size / dim_model_base`` (the logits_scaling divisor,
granite semantics)."""

from __future__ import annotations

import math

from nxdi_tpu.config import InferenceConfig
from nxdi_tpu.models import dense
from nxdi_tpu.models.base import DecoderArch

build_inv_freq = dense.build_inv_freq


class MiniCPMInferenceConfig(dense.DenseInferenceConfig):
    def add_derived_config(self):
        for name, default in (("scale_emb", 1.0), ("scale_depth", 1.0),
                              ("dim_model_base", None)):
            if not hasattr(self, name):
                setattr(self, name, default)
        super().add_derived_config()


def build_arch(config: InferenceConfig, **overrides) -> DecoderArch:
    dim_base = getattr(config, "dim_model_base", None) or config.hidden_size
    kwargs = dict(
        embed_scale=float(getattr(config, "scale_emb", 1.0)),
        residual_multiplier=(
            float(getattr(config, "scale_depth", 1.0))
            / math.sqrt(config.num_hidden_layers)
        ),
        logits_scaling=float(config.hidden_size) / float(dim_base),
        tie_word_embeddings=bool(getattr(config, "tie_word_embeddings", False)),
    )
    kwargs.update(overrides)
    return dense.build_arch(config, **kwargs)


def convert_hf_state_dict(state_dict, config: InferenceConfig):
    return dense.convert_hf_state_dict(state_dict, config, build_arch(config))


def param_specs(config: InferenceConfig):
    return dense.param_specs_for(build_arch(config))


def param_shape_struct(config: InferenceConfig):
    return dense.param_shape_struct(config, build_arch(config))
