"""Janus (DeepSeek) family — SigLIP-style CLS-less vision tower + aligner MLP
+ llama language model (text/understanding mode).

Reference: contrib/models/Janus-1.3B. HF JanusForConditionalGeneration
(modeling_janus.py:144-1200): conv patch embed + learned per-patch positions
(no class token, no pre-layernorm), pre-norm ViT blocks whose attention out
projection is ``projection_layer``, model-level ``post_layernorm``, then the
``aligner`` MLP (fc1 + (depth-1) hidden linears with gelu between) into the
LM hidden space; image features replace ``image_token_id`` placeholders.
The image-GENERATION path (VQVAE decoder, generation_* modules) is out of
scope — text generation only, like the reference contrib port."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from nxdi_tpu.config import InferenceConfig
from nxdi_tpu.models import dense
from nxdi_tpu.ops import vision as vision_ops
from nxdi_tpu.ops.norms import layer_norm


class JanusInferenceConfig(dense.DenseInferenceConfig):
    REQUIRED = ["text_config", "vision_config"]

    def add_derived_config(self):
        from nxdi_tpu.config import promote_text_config

        promote_text_config(self)
        vc = self.vision_config
        if not isinstance(vc, dict):
            self.vision_config = vc.to_dict()
        if not hasattr(self, "image_token_index"):
            self.image_token_index = getattr(self, "image_token_id", 100581)
        super().add_derived_config()
        if self.vision_config.get("use_qk_norm", False):
            raise NotImplementedError("janus vision use_qk_norm is not supported yet")


@dataclass(frozen=True)
class JanusVisionArch:
    hidden_size: int
    intermediate_size: int
    num_layers: int
    num_heads: int
    image_size: int
    patch_size: int
    num_channels: int = 3
    hidden_act: str = "gelu"
    layer_norm_eps: float = 1e-6
    attention_bias: bool = True
    aligner_depth: int = 2
    projection_dim: int = 2048

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2


def build_arch(config: InferenceConfig, **overrides):
    return dense.build_arch(config, **overrides)


def build_inv_freq(config: InferenceConfig) -> np.ndarray:
    return dense.build_inv_freq(config)


def build_vision_arch(config: InferenceConfig) -> JanusVisionArch:
    vc = config.vision_config
    return JanusVisionArch(
        hidden_size=vc["hidden_size"],
        intermediate_size=int(vc["hidden_size"] * vc.get("mlp_ratio", 4.0)),
        num_layers=vc["num_hidden_layers"],
        num_heads=vc["num_attention_heads"],
        image_size=vc["image_size"],
        patch_size=vc["patch_size"],
        num_channels=vc.get("num_channels", 3),
        hidden_act=vc.get("hidden_act", "gelu"),
        layer_norm_eps=vc.get("layer_norm_eps", 1e-6),
        attention_bias=vc.get("attention_bias", True),
        aligner_depth=vc.get("depth", 2),
        projection_dim=vc.get("projection_dim", 2048),
    )


def num_image_tokens(config: InferenceConfig) -> int:
    return build_vision_arch(config).num_patches


from nxdi_tpu.checkpoint import strip_language_model_prefix as _strip_text_prefix


def convert_hf_state_dict(
    state_dict: Dict[str, np.ndarray], config: InferenceConfig
) -> Dict[str, Any]:
    return dense.convert_hf_state_dict(
        _strip_text_prefix(state_dict), config, build_arch(config)
    )


def janus_vision_forward(
    arch: JanusVisionArch, params: Dict[str, Any], pixel_values: jax.Array
) -> jax.Array:
    """pixel_values (B, C, H, W) -> post-layernorm patch features (B, N, Hv)
    (HF JanusVisionModel.forward)."""
    B = pixel_values.shape[0]
    P, C = arch.patch_size, arch.num_channels
    g = arch.image_size // P
    # conv with stride=patch == unfold into patches + one matmul (MXU path)
    x = pixel_values.reshape(B, C, g, P, g, P)
    x = jnp.transpose(x, (0, 2, 4, 1, 3, 5)).reshape(B, g * g, C * P * P)
    h = x @ params["patch_embedding"] + params["patch_bias"]
    h = h + params["position_embedding"][None]

    def body(carry, lp):
        res = carry
        y = layer_norm(res, lp["ln1"]["w"], lp["ln1"]["b"], eps=arch.layer_norm_eps)
        res = res + vision_ops._vit_attention(lp["attn"], y, arch.num_heads)
        y = layer_norm(res, lp["ln2"]["w"], lp["ln2"]["b"], eps=arch.layer_norm_eps)
        y = vision_ops.ACTS[arch.hidden_act](y @ lp["fc1"]["w"] + lp["fc1"]["b"])
        res = res + (y @ lp["fc2"]["w"] + lp["fc2"]["b"])
        return res, None

    h, _ = jax.lax.scan(body, h, params["layers"])
    return layer_norm(
        h, params["post_layernorm"]["w"], params["post_layernorm"]["b"],
        eps=arch.layer_norm_eps,
    )


def encode_images(varch: JanusVisionArch, params: Dict[str, Any], pixel_values):
    feat = janus_vision_forward(varch, params["vision"], pixel_values)
    # aligner MLP: fc1, then (depth-1) x [gelu, linear] (JanusVisionAlignerMLP)
    p = params["projector"]
    h = feat @ p["fc1"]["w"] + p["fc1"]["b"]
    for hp in p["hidden"]:
        h = vision_ops.ACTS[varch.hidden_act](h)
        h = h @ hp["w"] + hp["b"]
    return h


def convert_vision_params(
    state_dict: Dict[str, np.ndarray], config: InferenceConfig, dtype=np.float32
) -> Dict[str, Any]:
    varch = build_vision_arch(config)

    def get(name):
        for k in ("model." + name, name):
            if k in state_dict:
                return np.asarray(state_dict[k], dtype=dtype)
        raise KeyError(name)

    conv = get("vision_model.embeddings.patch_embedding.weight")  # (Hv, C, P, P)
    vision: Dict[str, Any] = {
        "patch_embedding": conv.reshape(conv.shape[0], -1).T,
        "patch_bias": get("vision_model.embeddings.patch_embedding.bias"),
        "position_embedding": get("vision_model.embeddings.position_embedding.weight"),
        "post_layernorm": {
            "w": get("vision_model.post_layernorm.weight"),
            "b": get("vision_model.post_layernorm.bias"),
        },
    }
    layers = []
    for i in range(varch.num_layers):
        pre = f"vision_model.encoder.layers.{i}."
        attn = {
            name: {
                "w": get(pre + f"self_attn.{name}.weight").T,
                "b": get(pre + f"self_attn.{name}.bias"),
            }
            for name in ("q_proj", "k_proj", "v_proj")
        }
        attn["out_proj"] = {
            "w": get(pre + "self_attn.projection_layer.weight").T,
            "b": get(pre + "self_attn.projection_layer.bias"),
        }
        layers.append({
            "attn": attn,
            "ln1": {"w": get(pre + "layer_norm1.weight"), "b": get(pre + "layer_norm1.bias")},
            "ln2": {"w": get(pre + "layer_norm2.weight"), "b": get(pre + "layer_norm2.bias")},
            "fc1": {"w": get(pre + "mlp.fc1.weight").T, "b": get(pre + "mlp.fc1.bias")},
            "fc2": {"w": get(pre + "mlp.fc2.weight").T, "b": get(pre + "mlp.fc2.bias")},
        })
    import jax.tree_util as jtu

    vision["layers"] = jtu.tree_map(lambda *xs: np.stack(xs), *layers)

    projector: Dict[str, Any] = {
        "fc1": {"w": get("aligner.fc1.weight").T, "b": get("aligner.fc1.bias")},
        "hidden": [
            {
                "w": get(f"aligner.hidden_layers.{j}.weight").T,
                "b": get(f"aligner.hidden_layers.{j}.bias"),
            }
            for j in range(varch.aligner_depth - 1)
        ],
    }
    return {"vision": vision, "projector": projector}


def vision_shape_struct(config: InferenceConfig) -> Dict[str, Any]:
    varch = build_vision_arch(config)
    Hv, Iv, L = varch.hidden_size, varch.intermediate_size, varch.num_layers
    P2 = varch.num_channels * varch.patch_size ** 2
    s = lambda *shape: jax.ShapeDtypeStruct(shape, np.float32)  # noqa: E731
    lin = lambda i, o: {"w": s(L, i, o), "b": s(L, o)}  # noqa: E731
    return {
        "vision": {
            "patch_embedding": s(P2, Hv),
            "patch_bias": s(Hv),
            "position_embedding": s(varch.num_patches, Hv),
            "post_layernorm": {"w": s(Hv), "b": s(Hv)},
            "layers": {
                "attn": {
                    n: lin(Hv, Hv) for n in ("q_proj", "k_proj", "v_proj", "out_proj")
                },
                "ln1": {"w": s(L, Hv), "b": s(L, Hv)},
                "ln2": {"w": s(L, Hv), "b": s(L, Hv)},
                "fc1": lin(Hv, Iv),
                "fc2": lin(Iv, Hv),
            },
        },
        "projector": {
            "fc1": {"w": s(Hv, varch.projection_dim), "b": s(varch.projection_dim)},
            "hidden": [
                {"w": s(varch.projection_dim, varch.projection_dim), "b": s(varch.projection_dim)}
                for _ in range(varch.aligner_depth - 1)
            ],
        },
    }


def param_specs(config: InferenceConfig):
    return dense.param_specs_for(build_arch(config))


def param_shape_struct(config: InferenceConfig):
    return dense.param_shape_struct(config, build_arch(config))
