from nxdi_tpu.models.deepseek import modeling_deepseek
